"""Normalization functionals (reference: ``python/paddle/nn/functional/norm.py``
— SURVEY.md §2.2). batch_norm handles running-stat updates imperatively (the
caller passes the mutable buffer Tensors, as the reference kernels do)."""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ...framework.core import Tensor
from ...autograd.tape import apply, no_grad


def layer_norm(x, normalized_shape, weight=None, bias=None, epsilon=1e-5, name=None):
    ns = (normalized_shape,) if isinstance(normalized_shape, int) else tuple(normalized_shape)
    axes = tuple(range(-len(ns), 0))

    def fn(a, *wb):
        mean = jnp.mean(a.astype(jnp.float32), axis=axes, keepdims=True)
        var = jnp.var(a.astype(jnp.float32), axis=axes, keepdims=True)
        out = ((a.astype(jnp.float32) - mean) * jax.lax.rsqrt(var + epsilon)).astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i]
            i += 1
        if bias is not None:
            out = out + wb[i]
        return out

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return apply(fn, *args, op_name="layer_norm")


def rms_norm(x, weight=None, epsilon=1e-6, name=None):
    """RMSNorm (paddle.incubate.nn.functional.fused_rms_norm equivalent)."""
    def fn(a, *w):
        var = jnp.mean(jnp.square(a.astype(jnp.float32)), axis=-1, keepdims=True)
        out = (a.astype(jnp.float32) * jax.lax.rsqrt(var + epsilon)).astype(a.dtype)
        if w:
            out = out * w[0]
        return out

    args = (x,) + ((weight,) if weight is not None else ())
    return apply(fn, *args, op_name="rms_norm")


def batch_norm(x, running_mean, running_var, weight=None, bias=None,
               training=False, momentum=0.9, epsilon=1e-5, data_format="NCHW",
               use_global_stats=None, name=None):
    ch_axis = 1 if data_format.startswith("NC") else x.ndim - 1
    reduce_axes = tuple(i for i in range(x.ndim) if i != ch_axis)
    use_batch_stats = training and not use_global_stats

    if use_batch_stats:
        # compute batch stats (and update running buffers imperatively)
        def stats(a):
            af = a.astype(jnp.float32)
            m = jnp.mean(af, axis=reduce_axes)
            v = jnp.var(af, axis=reduce_axes)
            return m, v

        mean_t, var_t = apply(stats, x, op_name="bn_stats")
        with no_grad():
            if running_mean is not None:
                running_mean._data = (momentum * running_mean._data
                                      + (1 - momentum) * mean_t._data).astype(running_mean.dtype)
            if running_var is not None:
                n = 1
                for i in reduce_axes:
                    n *= x.shape[i]
                unbiased = var_t._data * (n / max(n - 1, 1))
                running_var._data = (momentum * running_var._data
                                     + (1 - momentum) * unbiased).astype(running_var.dtype)
        mean_arg, var_arg = mean_t, var_t
    else:
        mean_arg, var_arg = running_mean, running_var

    shape = [1] * x.ndim
    shape[ch_axis] = -1

    def fn(a, m, v, *wb):
        out = (a - m.reshape(shape).astype(a.dtype)) * \
            jax.lax.rsqrt(v.reshape(shape).astype(jnp.float32) + epsilon).astype(a.dtype)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = (x, mean_arg, var_arg) + tuple(t for t in (weight, bias) if t is not None)
    return apply(fn, *args, op_name="batch_norm")


def instance_norm(x, running_mean=None, running_var=None, weight=None, bias=None,
                  use_input_stats=True, momentum=0.9, eps=1e-5,
                  data_format="NCHW", name=None):
    axes = tuple(range(2, x.ndim))

    def fn(a, *wb):
        af = a.astype(jnp.float32)
        m = jnp.mean(af, axis=axes, keepdims=True)
        v = jnp.var(af, axis=axes, keepdims=True)
        out = ((af - m) * jax.lax.rsqrt(v + eps)).astype(a.dtype)
        shape = [1, -1] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return apply(fn, *args, op_name="instance_norm")


def group_norm(x, num_groups, epsilon=1e-5, weight=None, bias=None,
               data_format="NCHW", name=None):
    channels_last = data_format.endswith("C") and not data_format.startswith("NC")

    def fn(a, *wb):
        if channels_last:  # NHWC-style: channels to axis 1, norm, move back
            return jnp.moveaxis(_core(jnp.moveaxis(a, -1, 1), *wb), 1, -1)
        return _core(a, *wb)

    def _core(a, *wb):
        n, c = a.shape[0], a.shape[1]
        g = num_groups
        spatial = a.shape[2:]
        r = a.reshape(n, g, c // g, *spatial).astype(jnp.float32)
        axes = tuple(range(2, r.ndim))
        m = jnp.mean(r, axis=axes, keepdims=True)
        v = jnp.var(r, axis=axes, keepdims=True)
        out = ((r - m) * jax.lax.rsqrt(v + epsilon)).reshape(a.shape).astype(a.dtype)
        shape = [1, -1] + [1] * (a.ndim - 2)
        i = 0
        if weight is not None:
            out = out * wb[i].reshape(shape)
            i += 1
        if bias is not None:
            out = out + wb[i].reshape(shape)
        return out

    args = (x,) + tuple(t for t in (weight, bias) if t is not None)
    return apply(fn, *args, op_name="group_norm")


def local_response_norm(x, size, alpha=1e-4, beta=0.75, k=1.0,
                        data_format="NCHW", name=None):
    def fn(a):
        sq = jnp.square(a)
        c = a.shape[1]
        half = size // 2
        padded = jnp.pad(sq, [(0, 0), (half, size - 1 - half)] + [(0, 0)] * (a.ndim - 2))
        acc = jnp.zeros_like(a)
        for i in range(size):
            acc = acc + padded[:, i:i + c]
        return a / jnp.power(k + alpha * acc / size, beta)

    return apply(fn, x, op_name="local_response_norm")
