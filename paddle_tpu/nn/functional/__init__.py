"""paddle.nn.functional (reference: ``python/paddle/nn/functional/`` —
SURVEY.md §2.2)."""
from .activation import *  # noqa: F401,F403
from .common import *  # noqa: F401,F403
from .norm import *  # noqa: F401,F403
from .loss import *  # noqa: F401,F403
from .extras import *  # noqa: F401,F403
