"""Activation layers (reference: ``python/paddle/nn/layer/activation.py``)."""
from __future__ import annotations

from ..layer import Layer
from .. import functional as F
from ..initializer import Constant


def _act_layer(name, fn, **defaults):
    class _Act(Layer):
        def __init__(self, **kwargs):
            super().__init__()
            self._kwargs = {**defaults, **{k: v for k, v in kwargs.items()
                                           if k != "name"}}

        def forward(self, x):
            return fn(x, **self._kwargs)

    _Act.__name__ = _Act.__qualname__ = name
    return _Act


ReLU = _act_layer("ReLU", lambda x: F.relu(x))
ReLU6 = _act_layer("ReLU6", lambda x: F.relu6(x))
GELU = _act_layer("GELU", lambda x, approximate=False: F.gelu(x, approximate),
                  approximate=False)
Sigmoid = _act_layer("Sigmoid", lambda x: F.sigmoid(x))
Tanh = _act_layer("Tanh", lambda x: F.tanh(x))
Silu = _act_layer("Silu", lambda x: F.silu(x))
Swish = _act_layer("Swish", lambda x: F.silu(x))
Hardswish = _act_layer("Hardswish", lambda x: F.hardswish(x))
Hardsigmoid = _act_layer("Hardsigmoid", lambda x: F.hardsigmoid(x))
Hardtanh = _act_layer("Hardtanh", lambda x, min=-1.0, max=1.0: F.hardtanh(x, min, max),
                      min=-1.0, max=1.0)
LeakyReLU = _act_layer("LeakyReLU",
                       lambda x, negative_slope=0.01: F.leaky_relu(x, negative_slope),
                       negative_slope=0.01)
ELU = _act_layer("ELU", lambda x, alpha=1.0: F.elu(x, alpha), alpha=1.0)
CELU = _act_layer("CELU", lambda x, alpha=1.0: F.celu(x, alpha), alpha=1.0)
SELU = _act_layer("SELU", lambda x: F.selu(x))
Mish = _act_layer("Mish", lambda x: F.mish(x))
Softplus = _act_layer("Softplus",
                      lambda x, beta=1.0, threshold=20.0: F.softplus(x, beta, threshold),
                      beta=1.0, threshold=20.0)
Softshrink = _act_layer("Softshrink",
                        lambda x, threshold=0.5: F.softshrink(x, threshold),
                        threshold=0.5)
Hardshrink = _act_layer("Hardshrink",
                        lambda x, threshold=0.5: F.hardshrink(x, threshold),
                        threshold=0.5)
Softsign = _act_layer("Softsign", lambda x: F.softsign(x))
Tanhshrink = _act_layer("Tanhshrink", lambda x: F.tanhshrink(x))
LogSigmoid = _act_layer("LogSigmoid", lambda x: F.log_sigmoid(x))
Softmax = _act_layer("Softmax", lambda x, axis=-1: F.softmax(x, axis), axis=-1)
LogSoftmax = _act_layer("LogSoftmax", lambda x, axis=-1: F.log_softmax(x, axis),
                        axis=-1)
Maxout = _act_layer("Maxout", lambda x, groups=1, axis=1: F.maxout(x, groups, axis),
                    groups=1, axis=1)
GLU = _act_layer("GLU", lambda x, axis=-1: F.glu(x, axis), axis=-1)


class PReLU(Layer):
    def __init__(self, num_parameters=1, init=0.25, weight_attr=None,
                 data_format="NCHW", name=None):
        super().__init__()
        self._data_format = data_format
        self.weight = self.create_parameter([num_parameters], attr=weight_attr,
                                            default_initializer=Constant(init))

    def forward(self, x):
        return F.prelu(x, self.weight, self._data_format)


class RReLU(Layer):
    def __init__(self, lower=1.0 / 8, upper=1.0 / 3, name=None):
        super().__init__()
        self.lower, self.upper = lower, upper

    def forward(self, x):
        return F.rrelu(x, self.lower, self.upper, self.training)
