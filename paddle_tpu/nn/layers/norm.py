"""Norm layers (reference: ``python/paddle/nn/layer/norm.py`` — SURVEY.md §2.2).
BatchNorm keeps running stats as buffers updated imperatively in forward;
SyncBatchNorm syncs batch stats across the dp axis when a mesh is live."""
from __future__ import annotations

import jax.numpy as jnp

from ..layer import Layer
from .. import functional as F
from ..initializer import Constant
from ...framework.core import Tensor


class _BatchNormBase(Layer):
    def __init__(self, num_features, momentum=0.9, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", use_global_stats=None, name=None):
        super().__init__()
        self._num_features = num_features
        self._momentum = momentum
        self._epsilon = epsilon
        self._data_format = data_format
        self._use_global_stats = use_global_stats
        if weight_attr is False:
            self.weight = None
        else:
            self.weight = self.create_parameter(
                [num_features], attr=weight_attr, default_initializer=Constant(1.0))
        if bias_attr is False:
            self.bias = None
        else:
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)
        self.register_buffer("_mean", Tensor(jnp.zeros([num_features])))
        self.register_buffer("_variance", Tensor(jnp.ones([num_features])))

    def forward(self, x):
        return F.batch_norm(x, self._mean, self._variance, self.weight, self.bias,
                            training=self.training, momentum=self._momentum,
                            epsilon=self._epsilon, data_format=self._data_format,
                            use_global_stats=self._use_global_stats)

    def extra_repr(self):
        return f"num_features={self._num_features}, momentum={self._momentum}"


class BatchNorm(_BatchNormBase):
    """paddle.nn.BatchNorm (legacy act arg accepted)."""

    def __init__(self, num_channels, act=None, momentum=0.9, epsilon=1e-5,
                 param_attr=None, bias_attr=None, data_layout="NCHW",
                 use_global_stats=False, **kw):
        super().__init__(num_channels, momentum, epsilon, param_attr, bias_attr,
                         data_layout, use_global_stats)
        self._act = act

    def forward(self, x):
        out = super().forward(x)
        if self._act == "relu":
            out = F.relu(out)
        return out


class BatchNorm1D(_BatchNormBase):
    pass


class BatchNorm2D(_BatchNormBase):
    pass


class BatchNorm3D(_BatchNormBase):
    pass


class SyncBatchNorm(_BatchNormBase):
    """Cross-replica BN: on TPU the dp-mean/var sync happens automatically when
    the train step is jitted over the mesh (XLA emits the psum); in eager
    single-process mode it degrades to plain BN (documented deviation)."""

    @classmethod
    def convert_sync_batchnorm(cls, layer):
        if isinstance(layer, _BatchNormBase) and not isinstance(layer, SyncBatchNorm):
            new = SyncBatchNorm(layer._num_features, layer._momentum, layer._epsilon,
                                data_format=layer._data_format)
            if layer.weight is not None:
                new.weight.set_value(layer.weight)
                new.bias.set_value(layer.bias)
            new._mean.set_value(layer._mean)
            new._variance.set_value(layer._variance)
            return new
        for name, sub in list(layer._sub_layers.items()):
            layer._sub_layers[name] = cls.convert_sync_batchnorm(sub)
        return layer


class LayerNorm(Layer):
    def __init__(self, normalized_shape, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, name=None):
        super().__init__()
        ns = [normalized_shape] if isinstance(normalized_shape, int) \
            else list(normalized_shape)
        self._normalized_shape = ns
        self._epsilon = epsilon
        self.weight = None if weight_attr is False else self.create_parameter(
            ns, attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            ns, attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.layer_norm(x, self._normalized_shape, self.weight, self.bias,
                            self._epsilon)

    def extra_repr(self):
        return f"normalized_shape={self._normalized_shape}"


class RMSNorm(Layer):
    """paddle.incubate fused_rms_norm as a first-class layer (llama family)."""

    def __init__(self, hidden_size, epsilon=1e-6, weight_attr=None, name=None):
        super().__init__()
        self._epsilon = epsilon
        self.weight = self.create_parameter([hidden_size], attr=weight_attr,
                                            default_initializer=Constant(1.0))

    def forward(self, x):
        return F.rms_norm(x, self.weight, self._epsilon)


class GroupNorm(Layer):
    def __init__(self, num_groups, num_channels, epsilon=1e-5, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._num_groups = num_groups
        self._epsilon = epsilon
        self._data_format = data_format
        self.weight = None if weight_attr is False else self.create_parameter(
            [num_channels], attr=weight_attr, default_initializer=Constant(1.0))
        self.bias = None if bias_attr is False else self.create_parameter(
            [num_channels], attr=bias_attr, is_bias=True)

    def forward(self, x):
        return F.group_norm(x, self._num_groups, self._epsilon, self.weight,
                            self.bias, self._data_format)


class _InstanceNormBase(Layer):
    def __init__(self, num_features, epsilon=1e-5, momentum=0.9, weight_attr=None,
                 bias_attr=None, data_format="NCHW", name=None):
        super().__init__()
        self._epsilon = epsilon
        if weight_attr is False:
            self.scale = None
            self.bias = None
        else:
            self.scale = self.create_parameter([num_features], attr=weight_attr,
                                               default_initializer=Constant(1.0))
            self.bias = self.create_parameter([num_features], attr=bias_attr,
                                              is_bias=True)

    def forward(self, x):
        return F.instance_norm(x, weight=self.scale, bias=self.bias,
                               eps=self._epsilon)


class InstanceNorm1D(_InstanceNormBase):
    pass


class InstanceNorm2D(_InstanceNormBase):
    pass


class InstanceNorm3D(_InstanceNormBase):
    pass


class LocalResponseNorm(Layer):
    def __init__(self, size, alpha=1e-4, beta=0.75, k=1.0, data_format="NCHW",
                 name=None):
        super().__init__()
        self.args = (size, alpha, beta, k, data_format)

    def forward(self, x):
        return F.local_response_norm(x, *self.args)


class SpectralNorm(Layer):
    def __init__(self, weight_shape, dim=0, power_iters=1, eps=1e-12, name=None):
        super().__init__()
        raise NotImplementedError("SpectralNorm lands with the GAN round; "
                                  "use nn.utils.spectral_norm")
