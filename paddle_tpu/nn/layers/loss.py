"""Loss layers (reference: ``python/paddle/nn/layer/loss.py``)."""
from __future__ import annotations

from ..layer import Layer
from .. import functional as F


class CrossEntropyLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean",
                 soft_label=False, axis=-1, use_softmax=True, label_smoothing=0.0,
                 name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction
        self.soft_label = soft_label
        self.axis = axis
        self.use_softmax = use_softmax
        self.label_smoothing = label_smoothing

    def forward(self, input, label):
        return F.cross_entropy(input, label, self.weight, self.ignore_index,
                               self.reduction, self.soft_label, self.axis,
                               self.use_softmax, self.label_smoothing)


class MSELoss(Layer):
    def __init__(self, reduction="mean"):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.mse_loss(input, label, self.reduction)


class L1Loss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.l1_loss(input, label, self.reduction)


class SmoothL1Loss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.smooth_l1_loss(input, label, self.reduction, self.delta)


class HuberLoss(Layer):
    def __init__(self, reduction="mean", delta=1.0, name=None):
        super().__init__()
        self.reduction = reduction
        self.delta = delta

    def forward(self, input, label):
        return F.huber_loss(input, label, self.delta, self.reduction)


class GaussianNLLLoss(Layer):
    def __init__(self, full=False, epsilon=1e-6, reduction="mean", name=None):
        super().__init__()
        self.full = full
        self.epsilon = epsilon
        self.reduction = reduction

    def forward(self, input, label, variance):
        return F.gaussian_nll_loss(input, label, variance, self.full,
                                   self.epsilon, self.reduction)


class NLLLoss(Layer):
    def __init__(self, weight=None, ignore_index=-100, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.ignore_index = ignore_index
        self.reduction = reduction

    def forward(self, input, label):
        return F.nll_loss(input, label, self.weight, self.ignore_index, self.reduction)


class BCELoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.binary_cross_entropy(input, label, self.weight, self.reduction)


class BCEWithLogitsLoss(Layer):
    def __init__(self, weight=None, reduction="mean", pos_weight=None, name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction
        self.pos_weight = pos_weight

    def forward(self, logit, label):
        return F.binary_cross_entropy_with_logits(logit, label, self.weight,
                                                  self.reduction, self.pos_weight)


class KLDivLoss(Layer):
    def __init__(self, reduction="mean", log_target=False):
        super().__init__()
        self.reduction = reduction
        self.log_target = log_target

    def forward(self, input, label):
        return F.kl_div(input, label, self.reduction, self.log_target)


class MarginRankingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, other, label):
        return F.margin_ranking_loss(input, other, label, self.margin, self.reduction)


class CosineEmbeddingLoss(Layer):
    def __init__(self, margin=0.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input1, input2, label):
        return F.cosine_embedding_loss(input1, input2, label, self.margin,
                                       self.reduction)


class TripletMarginLoss(Layer):
    def __init__(self, margin=1.0, p=2.0, epsilon=1e-6, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (margin, p, epsilon, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_loss(input, positive, negative, *self.args)


class HingeEmbeddingLoss(Layer):
    def __init__(self, margin=1.0, reduction="mean", name=None):
        super().__init__()
        self.margin = margin
        self.reduction = reduction

    def forward(self, input, label):
        return F.hinge_embedding_loss(input, label, self.margin, self.reduction)


class AdaptiveLogSoftmaxWithLoss(Layer):
    """reference: ``paddle.nn.AdaptiveLogSoftmaxWithLoss`` — hierarchical
    softmax over frequency-sorted classes; forward returns
    ``(output, loss)``."""

    def __init__(self, in_features, n_classes, cutoffs, div_value=4.0,
                 head_bias=False, name=None):
        super().__init__()
        cutoffs = [int(c) for c in cutoffs]
        if (not cutoffs or cutoffs != sorted(set(cutoffs))
                or cutoffs[0] <= 0 or cutoffs[-1] > n_classes):
            raise ValueError(
                "cutoffs must be unique increasing ints in (0, n_classes]")
        if cutoffs[-1] != n_classes:
            cutoffs = cutoffs + [n_classes]
        self.in_features = in_features
        self.n_classes = n_classes
        self.cutoffs = cutoffs
        self.div_value = div_value
        n_clusters = len(cutoffs) - 1
        # create_parameter: the repo-wide seeded init path (XavierUniform
        # through the framework key tree; Constant(0) bias convention)
        self.head_weight = self.create_parameter(
            (in_features, cutoffs[0] + n_clusters))
        self.head_bias = self.create_parameter(
            (cutoffs[0] + n_clusters,), is_bias=True) if head_bias else None
        self.tail_weights = []
        for k in range(n_clusters):
            hsz = max(1, int(in_features // (div_value ** (k + 1))))
            csz = cutoffs[k + 1] - cutoffs[k]
            pair = [self.create_parameter((in_features, hsz)),
                    self.create_parameter((hsz, csz))]
            self.tail_weights.append(pair)
            self.add_parameter(f"tail_{k}_proj", pair[0])
            self.add_parameter(f"tail_{k}_out", pair[1])

    def forward(self, input, label):
        return F.adaptive_log_softmax_with_loss(
            input, label, self.head_weight, self.tail_weights,
            self.cutoffs, head_bias=self.head_bias)

    def log_prob(self, input):
        """Full [N, n_classes] log-distribution."""
        return F.adaptive_log_softmax_log_prob(
            input, self.head_weight, self.tail_weights, self.cutoffs,
            head_bias=self.head_bias)

    def predict(self, input):
        lp = self.log_prob(input)
        from ...ops.logic import argmax
        return argmax(lp, axis=-1)
