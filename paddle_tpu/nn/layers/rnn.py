"""Recurrent layers (reference: ``python/paddle/nn/layer/rnn.py`` —
SimpleRNN/LSTM/GRU + cells, multi-layer, bidirectional, time_major;
SURVEY.md §2.2 "nn").

TPU-native: the whole sequence loop is ONE ``lax.scan`` per (layer,
direction) inside a single traced op — no per-step Python dispatch, XLA
pipelines the gate matmuls on the MXU. Weight layout matches the reference:
``weight_ih`` [gates*hidden, input], ``weight_hh`` [gates*hidden, hidden],
gate order i,f,c,o for LSTM and r,z,c for GRU.
"""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..layer import Layer, LayerList
from ..initializer import Uniform
from ...autograd.tape import apply
from ...framework import random as prandom
from ...framework.core import Tensor

__all__ = ["SimpleRNNCell", "LSTMCell", "GRUCell", "RNNCellBase", "RNN",
           "SimpleRNN", "LSTM", "GRU"]


# ---------------------------------------------------------------------------
# cells
# ---------------------------------------------------------------------------

class RNNCellBase(Layer):
    GATES = 1

    def __init__(self, input_size, hidden_size, weight_ih_attr=None,
                 weight_hh_attr=None, bias_ih_attr=None, bias_hh_attr=None,
                 name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        g = self.GATES
        std = 1.0 / math.sqrt(hidden_size)
        init = Uniform(-std, std)
        self.weight_ih = self.create_parameter(
            [g * hidden_size, input_size], attr=weight_ih_attr,
            default_initializer=init)
        self.weight_hh = self.create_parameter(
            [g * hidden_size, hidden_size], attr=weight_hh_attr,
            default_initializer=init)
        self.bias_ih = self.create_parameter(
            [g * hidden_size], attr=bias_ih_attr, is_bias=True,
            default_initializer=init)
        self.bias_hh = self.create_parameter(
            [g * hidden_size], attr=bias_hh_attr, is_bias=True,
            default_initializer=init)

    def get_initial_states(self, batch, dtype=jnp.float32):
        z = jnp.zeros((batch, self.hidden_size), dtype)
        return z

    # pure-array single step (used by the scan and by eager cell calls)
    @staticmethod
    def step(params, x, state):
        raise NotImplementedError


class SimpleRNNCell(RNNCellBase):
    GATES = 1

    def __init__(self, input_size, hidden_size, activation="tanh", **kw):
        super().__init__(input_size, hidden_size, **kw)
        self.activation = activation

    @staticmethod
    def make_step(activation="tanh"):
        act = jnp.tanh if activation == "tanh" else \
            (lambda v: jnp.maximum(v, 0))

        def step(params, x, state):
            wih, whh, bih, bhh = params
            h = state
            h2 = act(x @ wih.T + bih + h @ whh.T + bhh)
            return h2, h2
        return step

    def forward(self, inputs, states=None):
        def fn(x, wih, whh, bih, bhh, *st):
            h = st[0] if st else jnp.zeros((x.shape[0], self.hidden_size),
                                           x.dtype)
            h2, _ = SimpleRNNCell.make_step(self.activation)(
                (wih, whh, bih, bhh), x, h)
            return h2, h2

        args = (inputs, self.weight_ih, self.weight_hh, self.bias_ih,
                self.bias_hh) + ((states,) if states is not None else ())
        out, h = apply(fn, *args, op_name="simple_rnn_cell")
        return out, h


class LSTMCell(RNNCellBase):
    GATES = 4

    @staticmethod
    def make_step():
        def step(params, x, state):
            wih, whh, bih, bhh = params
            h, c = state
            gates = x @ wih.T + bih + h @ whh.T + bhh
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            i = jax.nn.sigmoid(i)
            f = jax.nn.sigmoid(f)
            g = jnp.tanh(g)
            o = jax.nn.sigmoid(o)
            c2 = f * c + i * g
            h2 = o * jnp.tanh(c2)
            return h2, (h2, c2)
        return step

    def forward(self, inputs, states=None):
        def fn(x, wih, whh, bih, bhh, *st):
            if st:
                h, c = st
            else:
                z = jnp.zeros((x.shape[0], self.hidden_size), x.dtype)
                h = c = z
            h2, (h2b, c2) = LSTMCell.make_step()((wih, whh, bih, bhh), x,
                                                 (h, c))
            return h2, (h2b, c2)

        args = [inputs, self.weight_ih, self.weight_hh, self.bias_ih,
                self.bias_hh]
        if states is not None:
            args += list(states)
        out, hc = apply(fn, *args, op_name="lstm_cell")
        return out, hc


class GRUCell(RNNCellBase):
    GATES = 3

    @staticmethod
    def make_step():
        def step(params, x, state):
            wih, whh, bih, bhh = params
            h = state
            xg = x @ wih.T + bih
            hg = h @ whh.T + bhh
            xr, xz, xc = jnp.split(xg, 3, axis=-1)
            hr, hz, hc = jnp.split(hg, 3, axis=-1)
            r = jax.nn.sigmoid(xr + hr)
            z = jax.nn.sigmoid(xz + hz)
            cand = jnp.tanh(xc + r * hc)
            h2 = (1 - z) * cand + z * h
            return h2, h2
        return step

    def forward(self, inputs, states=None):
        def fn(x, wih, whh, bih, bhh, *st):
            h = st[0] if st else jnp.zeros((x.shape[0], self.hidden_size),
                                           x.dtype)
            return GRUCell.make_step()((wih, whh, bih, bhh), x, h)

        args = (inputs, self.weight_ih, self.weight_hh, self.bias_ih,
                self.bias_hh) + ((states,) if states is not None else ())
        out, h = apply(fn, *args, op_name="gru_cell")
        return out, h


# ---------------------------------------------------------------------------
# multi-layer wrappers
# ---------------------------------------------------------------------------

_CELLS = {"SimpleRNN": SimpleRNNCell, "LSTM": LSTMCell, "GRU": GRUCell}


class _RNNBase(Layer):
    MODE = "SimpleRNN"

    def __init__(self, input_size, hidden_size, num_layers=1,
                 direction="forward", time_major=False, dropout=0.0,
                 activation="tanh", weight_ih_attr=None, weight_hh_attr=None,
                 bias_ih_attr=None, bias_hh_attr=None, name=None):
        super().__init__()
        self.input_size = input_size
        self.hidden_size = hidden_size
        self.num_layers = num_layers
        self.time_major = time_major
        self.dropout = dropout
        self.activation = activation
        self.bidirect = direction in ("bidirect", "bidirectional")
        ndirs = 2 if self.bidirect else 1
        cell_cls = _CELLS[self.MODE]
        cells = []
        for layer in range(num_layers):
            in_sz = input_size if layer == 0 else hidden_size * ndirs
            for _ in range(ndirs):
                kw = dict(weight_ih_attr=weight_ih_attr,
                          weight_hh_attr=weight_hh_attr,
                          bias_ih_attr=bias_ih_attr, bias_hh_attr=bias_hh_attr)
                if self.MODE == "SimpleRNN":
                    kw["activation"] = activation
                cells.append(cell_cls(in_sz, hidden_size, **kw))
        self.cells = LayerList(cells)

    def _step_fn(self):
        if self.MODE == "SimpleRNN":
            return SimpleRNNCell.make_step(self.activation)
        if self.MODE == "LSTM":
            return LSTMCell.make_step()
        return GRUCell.make_step()

    def forward(self, inputs, initial_states=None, sequence_length=None):
        """Reference semantics (``python/paddle/nn/layer/rnn.py`` RNNBase):
        ``initial_states`` is ``[nl*ndirs, B, H]`` (tuple of two for LSTM),
        ``sequence_length`` ``[B]`` masks steps past each example's length
        (outputs zeroed, final states taken at the last valid step), and
        ``dropout`` applies between stacked layers while training."""
        ndirs = 2 if self.bidirect else 1
        step = self._step_fn()
        is_lstm = self.MODE == "LSTM"
        hidden = self.hidden_size
        time_major = self.time_major
        nl = self.num_layers
        ncells = nl * ndirs
        has_init = initial_states is not None
        has_len = sequence_length is not None
        dropout_p = float(self.dropout)
        use_drop = dropout_p > 0.0 and self.training and nl > 1
        drop_key = prandom.next_key() if use_drop else None

        def fn(x, *args):
            weights = args[:4 * ncells]
            rest = list(args[4 * ncells:])
            init_h = init_c = seq_len = None
            if has_init:
                init_h = rest.pop(0)
                if is_lstm:
                    init_c = rest.pop(0)
            if has_len:
                seq_len = rest.pop(0)

            # x -> [T, B, F] internally
            xs = x if time_major else jnp.swapaxes(x, 0, 1)
            T = xs.shape[0]
            if has_len:
                valid = (jnp.arange(T)[:, None]
                         < seq_len[None, :].astype(jnp.int32))   # [T, B]
            hs, cs = [], []
            for layer in range(nl):
                outs = []
                for d in range(ndirs):
                    ci = layer * ndirs + d
                    w = weights[4 * ci: 4 * ci + 4]
                    seq = xs if d == 0 else jnp.flip(xs, 0)
                    b = seq.shape[1]
                    if has_init:
                        h0 = init_h[ci].astype(seq.dtype)
                        init = (h0, init_c[ci].astype(seq.dtype)) \
                            if is_lstm else h0
                    else:
                        z = jnp.zeros((b, hidden), seq.dtype)
                        init = (z, z) if is_lstm else z

                    if has_len:
                        # Masked scan: past-length steps keep the carry and
                        # emit zeros. For the reverse direction the first
                        # *valid* step of the descending scan is t=len-1, so
                        # the same carry-freeze yields correct semantics.
                        vmask = valid if d == 0 else jnp.flip(valid, 0)

                        def scan_step(carry, inp, w=w):
                            xt, vt = inp
                            h2, carry2 = step(w, xt, carry)
                            keep = vt[:, None]
                            carry2 = jax.tree.map(
                                lambda new, old: jnp.where(keep, new, old),
                                carry2, carry)
                            return carry2, jnp.where(keep, h2, 0.0)

                        final, ys = jax.lax.scan(scan_step, init,
                                                 (seq, vmask))
                    else:
                        def scan_step(carry, xt, w=w):
                            h2, carry2 = step(w, xt, carry)
                            return carry2, h2

                        final, ys = jax.lax.scan(scan_step, init, seq)
                    if d == 1:
                        ys = jnp.flip(ys, 0)
                    outs.append(ys)
                    if is_lstm:
                        hs.append(final[0])
                        cs.append(final[1])
                    else:
                        hs.append(final)
                xs = outs[0] if ndirs == 1 else jnp.concatenate(outs, -1)
                if use_drop and layer < nl - 1:
                    key_l = jax.random.fold_in(drop_key, layer)
                    keep = jax.random.bernoulli(key_l, 1.0 - dropout_p,
                                                xs.shape)
                    xs = jnp.where(keep, xs / (1.0 - dropout_p),
                                   0.0).astype(xs.dtype)
            out = xs if time_major else jnp.swapaxes(xs, 0, 1)
            h = jnp.stack(hs, 0)                   # [nl*ndirs, B, H]
            if is_lstm:
                return out, (h, jnp.stack(cs, 0))
            return out, h

        wargs = []
        for cell in self.cells:
            wargs += [cell.weight_ih, cell.weight_hh, cell.bias_ih,
                      cell.bias_hh]
        if has_init:
            wargs += list(initial_states) if is_lstm else [initial_states]
        if has_len:
            wargs.append(sequence_length)
        return apply(fn, inputs, *wargs, op_name=f"{self.MODE.lower()}")


class SimpleRNN(_RNNBase):
    MODE = "SimpleRNN"


class LSTM(_RNNBase):
    MODE = "LSTM"


class GRU(_RNNBase):
    MODE = "GRU"


class RNN(Layer):
    """Generic scanner over a user cell (reference paddle.nn.RNN)."""

    def __init__(self, cell, is_reverse=False, time_major=False):
        super().__init__()
        self.cell = cell
        self.is_reverse = is_reverse
        self.time_major = time_major

    def forward(self, inputs, initial_states=None, sequence_length=None):
        # eager per-step loop through the cell (keeps arbitrary cells valid)
        xs = inputs if self.time_major else inputs.transpose(
            [1, 0] + list(range(2, inputs.ndim)))
        steps = xs.shape[0]
        order = range(steps - 1, -1, -1) if self.is_reverse else range(steps)
        state = initial_states
        outs = [None] * steps
        for t in order:
            out, state = self.cell(xs[t], state)
            outs[t] = out
        from ...ops import manipulation as manip
        stacked = manip.stack(outs, axis=0)
        if not self.time_major:
            stacked = stacked.transpose([1, 0] +
                                        list(range(2, stacked.ndim)))
        return stacked, state
