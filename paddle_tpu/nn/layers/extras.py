"""Layer breadth batch 2 (reference: ``python/paddle/nn/layer/`` —
pooling.py 3-D/unpool tiers, conv.py 1-D/3-D transpose, common.py
Unflatten/Fold/PairwiseDistance, vision.py PixelUnshuffle, loss.py tail,
activation.py SiLU/Softmax2D)."""
from __future__ import annotations

from ..layer import Layer
from .. import functional as F
from .conv import _ConvNd


# -------------------------------------------------------------- pooling

class MaxPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 return_mask=False, data_format="NCDHW", name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, return_mask,
                     data_format)

    def forward(self, x):
        return F.max_pool3d(x, *self.args)


class AvgPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0, ceil_mode=False,
                 exclusive=True, divisor_override=None, data_format="NCDHW",
                 name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, ceil_mode, exclusive,
                     divisor_override, data_format)

    def forward(self, x):
        return F.avg_pool3d(x, *self.args)


class AdaptiveAvgPool3D(Layer):
    def __init__(self, output_size, data_format="NCDHW", name=None):
        super().__init__()
        self.output_size = output_size
        self.data_format = data_format

    def forward(self, x):
        return F.adaptive_avg_pool3d(x, self.output_size, self.data_format)


class AdaptiveMaxPool1D(Layer):
    def __init__(self, output_size, return_mask=False, name=None):
        super().__init__()
        self.output_size = output_size
        self.return_mask = return_mask

    def forward(self, x):
        from ...autograd.tape import apply
        out = int(self.output_size)
        l = int(x.shape[-1])
        if l % out != 0:
            raise ValueError(
                f"AdaptiveMaxPool1D: length {l} not divisible by "
                f"output_size {out}")
        if self.return_mask:
            return F.max_pool1d_with_index(x, kernel_size=l // out)

        def fn(a):
            n, c, ll = a.shape
            return a.reshape(n, c, out, ll // out).max(axis=-1)

        return apply(fn, x, op_name="adaptive_max_pool1d")


class MaxUnPool1D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCL", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, output_size)

    def forward(self, x, indices):
        k, s, p, o = self.args
        return F.max_unpool1d(x, indices, k, s, p, o)


class MaxUnPool2D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCHW", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, output_size)

    def forward(self, x, indices):
        k, s, p, o = self.args
        return F.max_unpool2d(x, indices, k, s, p, o)


class MaxUnPool3D(Layer):
    def __init__(self, kernel_size, stride=None, padding=0,
                 data_format="NCDHW", output_size=None, name=None):
        super().__init__()
        self.args = (kernel_size, stride, padding, output_size)

    def forward(self, x, indices):
        k, s, p, o = self.args
        return F.max_unpool3d(x, indices, k, s, p, o)


# -------------------------------------------------------------- convs

class Conv1DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCL"):
        super().__init__(in_channels, out_channels, kernel_size, 1, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv1d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation,
                                  self._data_format, output_size)


class Conv3DTranspose(_ConvNd):
    def __init__(self, in_channels, out_channels, kernel_size, stride=1,
                 padding=0, output_padding=0, groups=1, dilation=1,
                 weight_attr=None, bias_attr=None, data_format="NCDHW"):
        super().__init__(in_channels, out_channels, kernel_size, 3, stride,
                         padding, dilation, groups, "zeros", weight_attr,
                         bias_attr, data_format, transpose=True,
                         output_padding=output_padding)

    def forward(self, x, output_size=None):
        return F.conv3d_transpose(x, self.weight, self.bias, self._stride,
                                  self._padding, self._output_padding,
                                  self._groups, self._dilation,
                                  self._data_format, output_size)


# -------------------------------------------------------------- common

class Unflatten(Layer):
    def __init__(self, axis, shape, name=None):
        super().__init__()
        self.axis = int(axis)
        self.shape = list(shape)

    def forward(self, x):
        full = list(x.shape)
        ax = self.axis % len(full)
        return x.reshape(full[:ax] + self.shape + full[ax + 1:])


class Fold(Layer):
    def __init__(self, output_sizes, kernel_sizes, strides=1, paddings=0,
                 dilations=1, name=None):
        super().__init__()
        self.args = (output_sizes, kernel_sizes, strides, paddings,
                     dilations)

    def forward(self, x):
        return F.fold(x, *self.args)


class PixelUnshuffle(Layer):
    def __init__(self, downscale_factor, data_format="NCHW", name=None):
        super().__init__()
        self.factor = downscale_factor

    def forward(self, x):
        return F.pixel_unshuffle(x, self.factor)


class PairwiseDistance(Layer):
    def __init__(self, p=2.0, epsilon=1e-6, keepdim=False, name=None):
        super().__init__()
        self.args = (p, epsilon, keepdim)

    def forward(self, x, y):
        return F.pairwise_distance(x, y, *self.args)


# -------------------------------------------------------------- activations

class SiLU(Layer):
    def forward(self, x):
        return F.silu(x)


class Softmax2D(Layer):
    """Softmax over the channel axis of NCHW input."""

    def forward(self, x):
        return F.softmax(x, axis=-3)


# -------------------------------------------------------------- losses

class CTCLoss(Layer):
    def __init__(self, blank=0, reduction="mean"):
        super().__init__()
        self.blank = blank
        self.reduction = reduction

    def forward(self, log_probs, labels, input_lengths, label_lengths,
                norm_by_times=False):
        return F.ctc_loss(log_probs, labels, input_lengths, label_lengths,
                          blank=self.blank, reduction=self.reduction,
                          norm_by_times=norm_by_times)


class SoftMarginLoss(Layer):
    def __init__(self, reduction="mean", name=None):
        super().__init__()
        self.reduction = reduction

    def forward(self, input, label):
        return F.soft_margin_loss(input, label, self.reduction)


class PoissonNLLLoss(Layer):
    def __init__(self, log_input=True, full=False, epsilon=1e-8,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (log_input, full, epsilon, reduction)

    def forward(self, input, label):
        return F.poisson_nll_loss(input, label, *self.args)


class MultiLabelSoftMarginLoss(Layer):
    def __init__(self, weight=None, reduction="mean", name=None):
        super().__init__()
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_label_soft_margin_loss(input, label, self.weight,
                                              self.reduction)


class MultiMarginLoss(Layer):
    def __init__(self, p=1, margin=1.0, weight=None, reduction="mean",
                 name=None):
        super().__init__()
        self.args = (p, margin)
        self.weight = weight
        self.reduction = reduction

    def forward(self, input, label):
        return F.multi_margin_loss(input, label, *self.args,
                                   weight=self.weight,
                                   reduction=self.reduction)


class TripletMarginWithDistanceLoss(Layer):
    def __init__(self, distance_function=None, margin=1.0, swap=False,
                 reduction="mean", name=None):
        super().__init__()
        self.args = (distance_function, margin, swap, reduction)

    def forward(self, input, positive, negative):
        return F.triplet_margin_with_distance_loss(input, positive,
                                                   negative, *self.args)


class HSigmoidLoss(Layer):
    """Hierarchical sigmoid classifier head (reference
    ``paddle.nn.HSigmoidLoss``: owns the internal-node weight table)."""

    def __init__(self, feature_size, num_classes, weight_attr=None,
                 bias_attr=None, is_custom=False, is_sparse=False,
                 name=None):
        super().__init__()
        self.num_classes = num_classes
        rows = num_classes - 1 if not is_custom else num_classes
        self.weight = self.create_parameter([rows, feature_size],
                                            attr=weight_attr)
        self.bias = (None if bias_attr is False else
                     self.create_parameter([rows, 1], attr=bias_attr,
                                           is_bias=True))

    def forward(self, input, label, path_table=None, path_code=None):
        return F.hsigmoid_loss(input, label, self.num_classes, self.weight,
                               bias=self.bias, path_table=path_table,
                               path_code=path_code)
