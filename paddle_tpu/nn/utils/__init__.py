"""nn.utils (reference: ``python/paddle/nn/utils/`` — weight_norm,
spectral_norm, vector/params helpers)."""
from __future__ import annotations

import jax.numpy as jnp

from ...framework.core import Tensor
from ..clip_grad import clip_grad_norm_, clip_grad_value_  # noqa: F401


def parameters_to_vector(parameters, name=None):
    from ...ops.manipulation import concat
    return concat([p.reshape([-1]) for p in parameters], axis=0)


def vector_to_parameters(vec, parameters, name=None):
    offset = 0
    for p in parameters:
        n = p.size
        p.set_value(vec._data[offset:offset + n].reshape(p._data.shape))
        offset += n


def weight_norm(layer, name="weight", dim=0):
    """Reparametrize layer.weight = g * v / ||v|| (recomputed each forward
    via a pre-hook — functional equivalent of the reference's WeightNorm)."""
    from ...framework.core import Parameter

    w = getattr(layer, name)
    axes = tuple(i for i in range(w.ndim) if i != (dim if dim is not None else 0))
    g_init = jnp.sqrt(jnp.sum(jnp.square(w._data), axis=axes, keepdims=True))
    g = Parameter(g_init)
    v = Parameter(w._data)
    layer.add_parameter(name + "_g", g)
    layer.add_parameter(name + "_v", v)
    del layer._parameters[name]

    def hook(lyr, inputs):
        vv = lyr._parameters[name + "_v"]
        gg = lyr._parameters[name + "_g"]
        norm = (vv * vv).sum(axis=list(axes), keepdim=True).sqrt()
        setattr_plain(lyr, name, gg * vv / norm)
        return None

    def setattr_plain(lyr, nm, tensor):
        object.__setattr__(lyr, nm, tensor)

    layer.register_forward_pre_hook(hook)
    hook(layer, None)
    return layer


def remove_weight_norm(layer, name="weight"):
    v = layer._parameters.pop(name + "_v")
    g = layer._parameters.pop(name + "_g")
    w = getattr(layer, name)
    from ...framework.core import Parameter
    layer.add_parameter(name, Parameter(w._data))
    return layer


def spectral_norm(layer, name="weight", n_power_iterations=1, eps=1e-12, dim=None):
    from ...framework.core import Parameter
    from ...framework import random as prandom
    import jax

    w = getattr(layer, name)
    if dim is None:
        dim = 0
    w_mat = jnp.moveaxis(w._data, dim, 0).reshape(w._data.shape[dim], -1)
    u = jax.random.normal(prandom.next_key(), (w_mat.shape[0],))
    state = {"u": u / jnp.linalg.norm(u)}
    orig = Parameter(w._data)
    layer.add_parameter(name + "_orig", orig)
    del layer._parameters[name]

    def hook(lyr, inputs):
        wv = lyr._parameters[name + "_orig"]
        mat = jnp.moveaxis(wv._data, dim, 0).reshape(wv._data.shape[dim], -1)
        u_ = state["u"]
        for _ in range(n_power_iterations):
            v_ = mat.T @ u_
            v_ = v_ / jnp.maximum(jnp.linalg.norm(v_), eps)
            u_ = mat @ v_
            u_ = u_ / jnp.maximum(jnp.linalg.norm(u_), eps)
        state["u"] = u_
        sigma = u_ @ mat @ v_
        object.__setattr__(lyr, name, Tensor(wv._data / sigma))
        return None

    layer.register_forward_pre_hook(hook)
    hook(layer, None)
    return layer
