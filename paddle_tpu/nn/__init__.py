"""paddle.nn (reference: ``python/paddle/nn/`` — SURVEY.md §2.2)."""
from .layer import Layer, Sequential, LayerList, LayerDict, ParameterList  # noqa: F401
from . import functional  # noqa: F401
from . import initializer  # noqa: F401
from .layers.common import (  # noqa: F401
    Linear, Embedding, Dropout, Dropout2D, Dropout3D, AlphaDropout, Flatten,
    Identity, Upsample, UpsamplingNearest2D, UpsamplingBilinear2D, PixelShuffle,
    Pad1D, Pad2D, Pad3D, ZeroPad2D, Bilinear, CosineSimilarity, Unfold,
    ChannelShuffle,
)
from .layers.conv import Conv1D, Conv2D, Conv3D, Conv2DTranspose  # noqa: F401
from .layers.norm import (  # noqa: F401
    BatchNorm, BatchNorm1D, BatchNorm2D, BatchNorm3D, SyncBatchNorm, LayerNorm,
    RMSNorm, GroupNorm, InstanceNorm1D, InstanceNorm2D, InstanceNorm3D,
    LocalResponseNorm, SpectralNorm,
)
from .layers.pooling import (  # noqa: F401
    MaxPool1D, MaxPool2D, AvgPool1D, AvgPool2D, AdaptiveAvgPool1D,
    AdaptiveAvgPool2D, AdaptiveMaxPool2D,
)
from .layers.activation import (  # noqa: F401
    ReLU, ReLU6, GELU, Sigmoid, Tanh, Silu, Swish, Hardswish, Hardsigmoid,
    Hardtanh, LeakyReLU, ELU, CELU, SELU, Mish, Softplus, Softshrink,
    Hardshrink, Softsign, Tanhshrink, LogSigmoid, Softmax, LogSoftmax, Maxout,
    GLU, PReLU, RReLU,
)
from .layers.loss import (  # noqa: F401
    CrossEntropyLoss, MSELoss, L1Loss, SmoothL1Loss, NLLLoss, BCELoss,
    BCEWithLogitsLoss, KLDivLoss, MarginRankingLoss, CosineEmbeddingLoss,
    TripletMarginLoss, HingeEmbeddingLoss, HuberLoss, GaussianNLLLoss,
    AdaptiveLogSoftmaxWithLoss,
)
from .layers.transformer import (  # noqa: F401
    MultiHeadAttention, TransformerEncoderLayer, TransformerEncoder,
    TransformerDecoderLayer, TransformerDecoder, Transformer,
)
from .layers.rnn import (  # noqa: F401
    SimpleRNN, LSTM, GRU, RNN, SimpleRNNCell, LSTMCell, GRUCell,
)
from . import utils  # noqa: F401
from .clip_grad import ClipGradByValue, ClipGradByNorm, ClipGradByGlobalNorm  # noqa: F401
from .layers.rnn import RNNCellBase  # noqa: F401
from .layers.extras import (  # noqa: F401
    MaxPool3D, AvgPool3D, AdaptiveAvgPool3D, AdaptiveMaxPool1D,
    MaxUnPool1D, MaxUnPool2D, MaxUnPool3D,
    Conv1DTranspose, Conv3DTranspose,
    Unflatten, Fold, PixelUnshuffle, PairwiseDistance,
    SiLU, Softmax2D,
    CTCLoss, SoftMarginLoss, PoissonNLLLoss, MultiLabelSoftMarginLoss,
    MultiMarginLoss, TripletMarginWithDistanceLoss, HSigmoidLoss,
)
