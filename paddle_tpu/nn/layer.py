"""nn.Layer — module base class (reference: ``python/paddle/nn/layer/layers.py``
— SURVEY.md §2.2: sublayers, parameters, buffers, hooks, state_dict, to, apply)."""
from __future__ import annotations

import collections
from typing import Callable, Iterator

import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor, Parameter, _auto_name
from ..framework import dtype as dtypes


class HookRemoveHelper:
    def __init__(self, hooks, hook_id):
        self._hooks = hooks
        self._hook_id = hook_id

    def remove(self):
        self._hooks.pop(self._hook_id, None)


class Layer:
    def __init__(self, name_scope=None, dtype="float32"):
        self.training = True
        self._dtype = dtype
        self._full_name = name_scope or _auto_name(type(self).__name__.lower())
        self._parameters: dict[str, Parameter] = collections.OrderedDict()
        self._sub_layers: dict[str, Layer] = collections.OrderedDict()
        self._buffers: dict[str, Tensor] = collections.OrderedDict()
        self._non_persistable_buffer_names = set()
        self._forward_pre_hooks = collections.OrderedDict()
        self._forward_post_hooks = collections.OrderedDict()
        self._hook_id = [0]

    # -- forward ------------------------------------------------------------
    def forward(self, *inputs, **kwargs):
        raise NotImplementedError

    def __call__(self, *inputs, **kwargs):
        for hook in list(self._forward_pre_hooks.values()):
            out = hook(self, inputs)
            if out is not None:
                inputs = out if isinstance(out, tuple) else (out,)
        outputs = self.forward(*inputs, **kwargs)
        for hook in list(self._forward_post_hooks.values()):
            o = hook(self, inputs, outputs)
            if o is not None:
                outputs = o
        return outputs

    # -- registration -------------------------------------------------------
    def __setattr__(self, name, value):
        params = self.__dict__.get("_parameters")
        layers = self.__dict__.get("_sub_layers")
        buffers = self.__dict__.get("_buffers")
        if isinstance(value, Parameter):
            if params is None:
                raise RuntimeError("call super().__init__() first")
            for d in (layers, buffers):
                d.pop(name, None) if d else None
            params[name] = value
            self.__dict__.pop(name, None)
        elif isinstance(value, Layer):
            if layers is None:
                raise RuntimeError("call super().__init__() first")
            for d in (params, buffers):
                d.pop(name, None) if d else None
            layers[name] = value
            self.__dict__.pop(name, None)
        else:
            if params is not None and name in params:
                if value is None:
                    params[name] = None
                    return
                del params[name]
            if layers is not None and name in layers:
                del layers[name]
            if buffers is not None and name in buffers:
                if value is None or isinstance(value, Tensor):
                    buffers[name] = value
                    return
                del buffers[name]
            object.__setattr__(self, name, value)

    def __getattr__(self, name):
        for d in ("_parameters", "_sub_layers", "_buffers"):
            dd = self.__dict__.get(d)
            if dd is not None and name in dd:
                return dd[name]
        raise AttributeError(f"'{type(self).__name__}' object has no attribute '{name}'")

    def __delattr__(self, name):
        for d in (self._parameters, self._sub_layers, self._buffers):
            if name in d:
                del d[name]
                return
        object.__delattr__(self, name)

    def add_parameter(self, name, parameter):
        if parameter is not None and not isinstance(parameter, Parameter):
            raise TypeError("add_parameter expects a Parameter")
        self._parameters[name] = parameter
        return parameter

    def add_sublayer(self, name, sublayer):
        self._sub_layers[str(name)] = sublayer
        return sublayer

    def register_buffer(self, name, tensor, persistable=True):
        self._buffers[name] = tensor
        if not persistable:
            self._non_persistable_buffer_names.add(name)
        return tensor

    def create_parameter(self, shape, attr=None, dtype=None, is_bias=False,
                         default_initializer=None):
        from .initializer import Constant, XavierUniform
        from ..framework.param_attr import ParamAttr
        dtype = dtype or self._dtype or "float32"
        attr = ParamAttr._to_attr(attr)
        init = None
        if attr is not None and attr.initializer is not None:
            init = attr.initializer
        elif default_initializer is not None:
            init = default_initializer
        else:
            init = Constant(0.0) if is_bias else XavierUniform()
        data = init(shape, dtype)
        p = Parameter(data, dtype=dtype,
                      name=(attr.name if attr and attr.name else None))
        p.initializer = init
        if attr is not None:
            p.optimize_attr["learning_rate"] = attr.learning_rate
            p.regularizer = attr.regularizer
            p.trainable = attr.trainable
            p.stop_gradient = not attr.trainable
            p.need_clip = attr.need_clip
        return p

    def create_tensor(self, name=None, persistable=None, dtype=None):
        import jax.numpy as jnp
        return Tensor(jnp.zeros([], dtypes.convert_dtype(dtype or "float32")), name=name)

    # -- traversal ----------------------------------------------------------
    def parameters(self, include_sublayers=True):
        return [p for _, p in self.named_parameters(
            include_sublayers=include_sublayers)]

    def named_parameters(self, prefix="", include_sublayers=True):
        memo = set()
        for name, sub, pfx in self._walk(prefix, include_sublayers):
            for pname, p in sub._parameters.items():
                if p is not None and id(p) not in memo:
                    memo.add(id(p))
                    yield (f"{pfx}{pname}", p)

    def _walk(self, prefix="", include_sublayers=True):
        yield ("", self, prefix)
        if include_sublayers:
            for name, sub in self._sub_layers.items():
                if sub is None:
                    continue
                for n2, s2, p2 in sub._walk(f"{prefix}{name}.", True):
                    yield (n2, s2, p2)

    def children(self) -> Iterator["Layer"]:
        return iter([l for l in self._sub_layers.values() if l is not None])

    def named_children(self):
        return iter([(n, l) for n, l in self._sub_layers.items() if l is not None])

    def sublayers(self, include_self=False):
        out = [self] if include_self else []
        for sub in self.children():
            out.extend(sub.sublayers(include_self=True))
        return out

    def named_sublayers(self, prefix="", include_self=False):
        if include_self:
            yield (prefix.rstrip("."), self)
        for name, sub in self.named_children():
            p = f"{prefix}{name}"
            yield (p, sub)
            yield from sub.named_sublayers(prefix=p + ".", include_self=False)

    def buffers(self, include_sublayers=True):
        return [b for _, b in self.named_buffers(include_sublayers=include_sublayers)]

    def named_buffers(self, prefix="", include_sublayers=True):
        memo = set()
        for name, sub, pfx in self._walk(prefix, include_sublayers):
            for bname, b in sub._buffers.items():
                if b is not None and id(b) not in memo:
                    memo.add(id(b))
                    yield (f"{pfx}{bname}", b)

    def apply(self, fn: Callable):
        for l in self.sublayers(include_self=True):
            fn(l)
        return self

    def full_name(self):
        return self._full_name

    # -- train / eval -------------------------------------------------------
    def train(self):
        for l in self.sublayers(include_self=True):
            l.training = True
        return self

    def eval(self):
        for l in self.sublayers(include_self=True):
            l.training = False
        return self

    # -- state dict ---------------------------------------------------------
    def state_dict(self, destination=None, include_sublayers=True,
                   structured_name_prefix="", use_hook=True):
        dest = destination if destination is not None else collections.OrderedDict()
        for name, p in self.named_parameters(prefix=structured_name_prefix,
                                             include_sublayers=include_sublayers):
            dest[name] = p
        for _, sub, pfx in self._walk(structured_name_prefix, include_sublayers):
            for bname, b in sub._buffers.items():
                if b is not None and bname not in sub._non_persistable_buffer_names:
                    dest[f"{pfx}{bname}"] = b
        return dest

    def set_state_dict(self, state_dict, use_structured_name=True):
        missing, unexpected = [], []
        own = self.state_dict()
        for k, v in state_dict.items():
            if k in own:
                val = v.numpy() if isinstance(v, Tensor) else np.asarray(v)
                own[k].set_value(val.astype(own[k].numpy().dtype)
                                 if val.dtype != own[k].numpy().dtype else val)
            else:
                unexpected.append(k)
        for k in own:
            if k not in state_dict:
                missing.append(k)
        return missing, unexpected

    set_dict = set_state_dict
    load_dict = set_state_dict

    # -- dtype / device movement -------------------------------------------
    def to(self, device=None, dtype=None, blocking=None):
        if dtype is not None:
            self._cast_params(dtype)
        return self

    def astype(self, dtype):
        self._cast_params(dtype)
        return self

    def _cast_params(self, dtype, only_floating=True):
        dt = dtypes.convert_dtype(dtype)
        for p in self.parameters():
            if not only_floating or jnp.issubdtype(p.dtype, jnp.floating) \
                    or p.dtype == jnp.bfloat16:
                p._data = p._data.astype(dt)
        for b in self.buffers():
            if jnp.issubdtype(b.dtype, jnp.floating) or b.dtype == jnp.bfloat16:
                b._data = b._data.astype(dt)
        self._dtype = dtypes.dtype_name(dt)

    def float(self):
        return self.astype("float32")

    def half(self):
        return self.astype("float16")

    def bfloat16(self):
        return self.astype("bfloat16")

    # -- hooks --------------------------------------------------------------
    def register_forward_pre_hook(self, hook):
        self._hook_id[0] += 1
        self._forward_pre_hooks[self._hook_id[0]] = hook
        return HookRemoveHelper(self._forward_pre_hooks, self._hook_id[0])

    def register_forward_post_hook(self, hook):
        self._hook_id[0] += 1
        self._forward_post_hooks[self._hook_id[0]] = hook
        return HookRemoveHelper(self._forward_post_hooks, self._hook_id[0])

    # -- misc ---------------------------------------------------------------
    def clear_gradients(self):
        for p in self.parameters():
            p.clear_grad()

    def __repr__(self):
        extra = self.extra_repr()
        lines = []
        for name, sub in self._sub_layers.items():
            sub_repr = repr(sub).split("\n")
            sub_repr = "\n  ".join(sub_repr)
            lines.append(f"({name}): {sub_repr}")
        body = ("\n  " + "\n  ".join(lines) + "\n") if lines else extra
        return f"{type(self).__name__}({body})"

    def extra_repr(self):
        return ""


class Sequential(Layer):
    def __init__(self, *layers):
        super().__init__()
        if len(layers) == 1 and isinstance(layers[0], (list, tuple)) and \
                layers[0] and isinstance(layers[0][0], tuple):
            for name, layer in layers[0]:
                self.add_sublayer(name, layer)
        else:
            for i, layer in enumerate(layers):
                self.add_sublayer(str(i), layer)

    def forward(self, x):
        for layer in self._sub_layers.values():
            x = layer(x)
        return x

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return Sequential(*list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class LayerList(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers is not None:
            for i, l in enumerate(sublayers):
                self.add_sublayer(str(i), l)

    def append(self, sublayer):
        self.add_sublayer(str(len(self._sub_layers)), sublayer)
        return self

    def insert(self, index, sublayer):
        layers = list(self._sub_layers.values())
        layers.insert(index, sublayer)
        self._sub_layers.clear()
        for i, l in enumerate(layers):
            self._sub_layers[str(i)] = l

    def extend(self, sublayers):
        for l in sublayers:
            self.append(l)
        return self

    def __getitem__(self, idx):
        if isinstance(idx, slice):
            return LayerList(list(self._sub_layers.values())[idx])
        return list(self._sub_layers.values())[idx]

    def __setitem__(self, idx, layer):
        self._sub_layers[str(idx)] = layer

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers.values())


class LayerDict(Layer):
    def __init__(self, sublayers=None):
        super().__init__()
        if sublayers:
            self.update(sublayers)

    def update(self, sublayers):
        items = sublayers.items() if isinstance(sublayers, dict) else sublayers
        for k, v in items:
            self.add_sublayer(k, v)

    def __getitem__(self, key):
        return self._sub_layers[key]

    def __setitem__(self, key, layer):
        self.add_sublayer(key, layer)

    def __delitem__(self, key):
        del self._sub_layers[key]

    def __len__(self):
        return len(self._sub_layers)

    def __iter__(self):
        return iter(self._sub_layers)

    def keys(self):
        return self._sub_layers.keys()

    def values(self):
        return self._sub_layers.values()

    def items(self):
        return self._sub_layers.items()


class ParameterList(Layer):
    def __init__(self, parameters=None):
        super().__init__()
        if parameters is not None:
            for i, p in enumerate(parameters):
                self.add_parameter(str(i), p)

    def append(self, parameter):
        self.add_parameter(str(len(self._parameters)), parameter)
        return self

    def __getitem__(self, idx):
        return self._parameters[str(idx)]

    def __len__(self):
        return len(self._parameters)

    def __iter__(self):
        return iter(self._parameters.values())
