"""Top-level compat surface (reference: assorted ``python/paddle/``
namespaces — ``regularizer.py``, ``version/__init__.py``,
``sysconfig.py``, ``base/`` (the old fluid glue), ``batch.py``, the
``iinfo/finfo`` dtype-info APIs and tensor predicates from
``python/paddle/framework/``/``tensor/attribute.py``)."""
from __future__ import annotations

import sys
import types

import numpy as np
import jax.numpy as jnp

from .framework.core import Tensor, Parameter
from .framework import dtype as dtypes


# ---------------------------------------------------------------- regularizer

regularizer = types.ModuleType("paddle_tpu.regularizer")


class L1Decay:
    _l1 = True        # optimizer applies coeff*sign(w), not L2 decay

    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)


class L2Decay:
    def __init__(self, coeff=0.0):
        self._coeff = float(coeff)


regularizer.L1Decay = L1Decay
regularizer.L2Decay = L2Decay
sys.modules["paddle_tpu.regularizer"] = regularizer


# ---------------------------------------------------------------- version

version = types.ModuleType("paddle_tpu.version")
version.full_version = "3.0.0+tpu"
version.major = "3"
version.minor = "0"
version.patch = "0"
version.rc = "0"
version.commit = "tpu-native"
version.istaged = False
version.cuda = lambda: "False"
version.cudnn = lambda: "False"
version.xpu = lambda: "False"
version.show = lambda: print(f"paddle_tpu {version.full_version} "
                             f"(TPU-native JAX/XLA build)")
sys.modules["paddle_tpu.version"] = version


# ---------------------------------------------------------------- sysconfig

sysconfig = types.ModuleType("paddle_tpu.sysconfig")


def _get_include():
    import os
    return os.path.join(os.path.dirname(__file__), "include")


def _get_lib():
    import os
    return os.path.join(os.path.dirname(__file__), "lib")


sysconfig.get_include = _get_include
sysconfig.get_lib = _get_lib
sys.modules["paddle_tpu.sysconfig"] = sysconfig


# ---------------------------------------------------------------- dtype info

class iinfo:
    """paddle.iinfo — integer dtype metadata."""

    def __init__(self, dtype):
        info = np.iinfo(np.dtype(dtypes.convert_dtype(dtype)))
        self.min = int(info.min)
        self.max = int(info.max)
        self.bits = int(info.bits)
        self.dtype = str(info.dtype)


class finfo:
    """paddle.finfo — floating dtype metadata (bfloat16 included)."""

    def __init__(self, dtype):
        dt = dtypes.convert_dtype(dtype)
        try:
            info = np.finfo(np.dtype(dt))
        except ValueError:
            # this numpy doesn't treat ml_dtypes.bfloat16 as inexact;
            # ml_dtypes ships its own exact finfo
            import ml_dtypes
            info = ml_dtypes.finfo(dt)
        self.min = float(info.min)
        self.max = float(info.max)
        self.eps = float(info.eps)
        self.tiny = self.smallest_normal = float(info.tiny)
        self.resolution = float(info.resolution)
        self.bits = int(info.bits)
        self.dtype = str(info.dtype)


# ---------------------------------------------------------------- predicates

def is_tensor(x):
    return isinstance(x, Tensor)


def is_complex(x):
    return jnp.issubdtype(_dt(x), jnp.complexfloating)


def is_floating_point(x):
    return jnp.issubdtype(_dt(x), jnp.floating)


def is_integer(x):
    return jnp.issubdtype(_dt(x), jnp.integer)


def _dt(x):
    return x.dtype if hasattr(x, "dtype") else jnp.asarray(x).dtype


# ---------------------------------------------------------------- misc

def create_parameter(shape, dtype="float32", name=None, attr=None,
                     is_bias=False, default_initializer=None):
    """paddle.create_parameter — a free-standing Parameter honoring the
    same ParamAttr precedence as Layer.create_parameter."""
    from .nn.initializer import Constant, XavierUniform
    from .framework.param_attr import ParamAttr
    attr = ParamAttr._to_attr(attr)
    init = None
    trainable = True
    lr = 1.0
    if attr is not None:
        if attr.initializer is not None:
            init = attr.initializer
        trainable = getattr(attr, "trainable", True)
        lr = getattr(attr, "learning_rate", 1.0)
        name = name or getattr(attr, "name", None)
    if init is None:
        init = default_initializer or (Constant(0.0) if is_bias
                                       else XavierUniform())
    shape = [int(s) for s in shape]
    data = init(shape, dtypes.convert_dtype(dtype))
    p = Parameter(data, trainable=trainable)
    p.optimize_attr["learning_rate"] = lr
    if name:
        p.name = name
    return p


def batch(reader, batch_size, drop_last=False):
    """paddle.batch — wrap a sample reader into a batch reader (legacy
    reader-decorator API)."""
    def batched():
        buf = []
        for sample in reader():
            buf.append(sample)
            if len(buf) == batch_size:
                yield buf
                buf = []
        if buf and not drop_last:
            yield buf
    return batched


class LazyGuard:
    """paddle.LazyGuard — in the reference, defers parameter
    materialization until ``layer.to()`` is called. JAX arrays are
    buffer-backed and cheap on host, and jit tracing never materializes
    donated inits, so eager init is already effectively lazy; the guard
    is a functional no-op kept for API compatibility."""

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False
