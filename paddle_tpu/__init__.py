"""paddle_tpu — a TPU-native deep-learning framework with PaddlePaddle's API.

Built new on JAX/XLA (eager ops over jax.Array + imperative autograd tape;
``to_static`` → jax.jit → HLO; Fleet hybrid parallelism → named-mesh sharding
with XLA collectives over ICI/DCN). Blueprint: SURVEY.md at the repo root.

Usage matches paddle::

    import paddle_tpu as paddle
    paddle.set_device('tpu')
    x = paddle.randn([4, 8])
"""
from __future__ import annotations

__version__ = "0.1.0"

from .framework import (  # noqa: F401
    Tensor, Parameter, to_tensor, CPUPlace, TPUPlace, CUDAPlace, XPUPlace,
    set_device, get_device, device_count,
    is_compiled_with_cuda, is_compiled_with_xpu,
    bfloat16, float16, float32, float64, int8, int16, int32, int64, uint8,
    bool_, complex64, complex128, set_default_dtype, get_default_dtype,
    seed, get_rng_state, set_rng_state, get_cuda_rng_state, set_cuda_rng_state,
)
from .framework import core as _core  # noqa: F401
from .ops import *  # noqa: F401,F403
from .ops import linalg  # noqa: F401
from .ops.linalg import norm, dist, inv as inverse  # noqa: F401
from .ops.linalg import (  # noqa: F401  (reference top-level aliases)
    matrix_power, cov, corrcoef,
)
from .ops import bitwise_not as bitwise_invert  # noqa: F401
from .autograd import no_grad, enable_grad, grad, set_grad_enabled, is_grad_enabled  # noqa: F401
from .autograd.pylayer import PyLayer  # noqa: F401
from . import framework  # noqa: F401
from .framework import tensor_patch as _tensor_patch  # noqa: F401  (side effect: methods)
from . import autograd  # noqa: F401

# subsystem namespaces (populated as the build proceeds)
from . import nn  # noqa: F401
from . import optimizer  # noqa: F401
from . import io  # noqa: F401
from . import vision  # noqa: F401
from . import metric  # noqa: F401
from . import amp  # noqa: F401
from . import jit  # noqa: F401
from . import static  # noqa: F401
from . import device  # noqa: F401
from . import distributed  # noqa: F401
from . import incubate  # noqa: F401
from . import profiler  # noqa: F401
from . import sparse  # noqa: F401
from . import distribution  # noqa: F401
from . import geometric  # noqa: F401
from . import quantization  # noqa: F401
from . import inference  # noqa: F401
from . import callbacks  # noqa: F401
from . import onnx  # noqa: F401
from . import utils  # noqa: F401
from . import fft  # noqa: F401
from . import signal  # noqa: F401
from . import text  # noqa: F401
from . import audio  # noqa: F401
from .framework.io import save, load  # noqa: F401
from .nn.layer import Layer  # noqa: F401
from .hapi import Model, summary, flops  # noqa: F401
from .compat_namespaces import (  # noqa: F401
    regularizer, version, sysconfig, iinfo, finfo, is_tensor, is_complex,
    is_floating_point, is_integer, create_parameter, batch, LazyGuard,
)
from . import ops as tensor  # noqa: F401  (paddle.tensor namespace alias)
import sys as _sys
_sys.modules[__name__ + ".tensor"] = tensor   # `import paddle_tpu.tensor`
from .flags import set_flags, get_flags  # noqa: F401
from .jit.api import disable_static, enable_static, in_dynamic_mode  # noqa: F401


def is_grad_enabled_():  # legacy alias
    return is_grad_enabled()


def check_shape_match(*a):  # placeholder for paddle.utils compat
    pass


def run_check():
    """paddle.utils.run_check equivalent: verify the device works."""
    import jax
    x = randn([128, 128])  # noqa: F405
    y = (x @ x).sum()
    y.numpy()
    n = len(jax.devices())
    print(f"paddle_tpu is installed successfully! {n} device(s) "
          f"({jax.default_backend()}) available.")


_printoptions_state = {}


def set_printoptions(precision=None, threshold=None, edgeitems=None,
                     sci_mode=None, linewidth=None):
    """reference: ``paddle.set_printoptions`` — numpy print formatting
    governs how Tensor reprs render in this build. Options persist
    across calls (paddle semantics): a later call that sets only e.g.
    ``linewidth`` keeps the earlier ``sci_mode``."""
    import numpy as _np
    st = _printoptions_state
    for k, v in (("precision", precision), ("threshold", threshold),
                 ("edgeitems", edgeitems), ("linewidth", linewidth),
                 ("sci_mode", sci_mode)):
        if v is not None:
            st[k] = v
    kw = {k: st[k] for k in ("precision", "threshold", "edgeitems",
                             "linewidth") if k in st}
    if st.get("sci_mode"):
        # numpy has no "force scientific" flag — use a formatter
        prec = st.get("precision", 8)
        kw["formatter"] = {"float_kind": lambda v: f"{v:.{prec}e}"}
    elif "sci_mode" in st:
        kw["suppress"] = True
        kw["formatter"] = None
    _np.set_printoptions(**kw)


def _module_inplace(name):
    def fn(x, *a, **kw):
        return getattr(x, name)(*a, **kw)
    fn.__name__ = name
    fn.__doc__ = f"paddle.{name} — module-level alias of Tensor.{name}"
    return fn


scatter_ = _module_inplace("scatter_")
tril_ = _module_inplace("tril_")
triu_ = _module_inplace("triu_")
normal_ = _module_inplace("normal_")
bernoulli_ = _module_inplace("bernoulli_")


def disable_signal_handler():
    """reference: ``paddle.disable_signal_handler`` — this build installs
    no signal handlers, so there is nothing to disable (no-op)."""


# reference namespace aliases: paddle.base (the post-2.5 name of the
# fluid glue layer) and dtype objects
from . import framework as base  # noqa: F401,E402
import sys as _sys_mod  # noqa: E402

_sys_mod.modules[__name__ + ".base"] = base
import numpy as _np_mod  # noqa: E402


class _DTypeMeta(type):
    # this build's dtype singletons are numpy scalar TYPES (np.float32)
    # while user code also passes np.dtype instances — isinstance must
    # accept both, as paddle.dtype does for its singletons
    def __instancecheck__(cls, obj):
        return (isinstance(obj, _np_mod.dtype)
                or (isinstance(obj, type)
                    and issubclass(obj, _np_mod.generic)))


class dtype(metaclass=_DTypeMeta):
    """paddle.dtype — constructor normalizes any dtype spelling."""

    def __new__(cls, v="float32"):
        from .framework.dtype import convert_dtype
        return convert_dtype(v)


from .framework.dtype import bool_ as bool  # noqa: F401,E402,A001

# star-import hygiene: everything public EXCEPT `bool` (rebinding the
# caller's builtin bool to np.bool_ would break isinstance(x, bool)) and
# `annotations` (the __future__ Feature object, not an API)
__all__ = [_n for _n in dict(globals()) if not _n.startswith("_")
           and _n not in ("bool", "annotations")]
