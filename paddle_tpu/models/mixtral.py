"""Mixtral model family — sparse-MoE decoder LM (reference behavior:
PaddleNLP ``mixtral/modeling.py`` — Llama-style attention/RMSNorm/RoPE
with the dense SwiGLU MLP replaced by a top-k routed mixture of SwiGLU
experts + router load-balancing aux loss).

TPU-first design: same philosophy as models/llama.py — plain eager
layers, parallelism via ``sharding_rules()`` name→PartitionSpec maps.
The sparse block reuses the GShard dispatch plan from
``incubate.distributed.models.moe`` (one-hot dispatch/combine einsums,
static capacity) with STACKED expert weights ``[E, h, m]`` so the
per-expert matmuls stay batched on the MXU, and the expert dim is
EP-shardable over the mesh (XLA lowers the expert resharding to the
all-to-all the reference implements with global_scatter/gather)."""
from __future__ import annotations

import math

import jax
import jax.numpy as jnp

from ..nn.layer import Layer, LayerList
from ..nn.layers.common import Linear, Embedding
from ..nn.layers.norm import RMSNorm
from ..nn.initializer import Normal, XavierUniform
from ..ops import math as pmath
from ..autograd.tape import apply
from .generation import GenerationMixin
from .llama import (LlamaAttention, LlamaConfig, LlamaPretrainingCriterion,
                    shard_activation)


class MixtralConfig(LlamaConfig):
    def __init__(self, num_local_experts=8, num_experts_per_tok=2,
                 router_aux_loss_coef=0.02, moe_capacity_factor=2.0,
                 **kwargs):
        super().__init__(**kwargs)
        self.num_local_experts = num_local_experts
        self.num_experts_per_tok = num_experts_per_tok
        self.router_aux_loss_coef = router_aux_loss_coef
        self.moe_capacity_factor = moe_capacity_factor


def mixtral_8x7b(**kw):
    """Mixtral-8x7B shape (46.7B total / 12.9B active params)."""
    kw.setdefault("vocab_size", 32000)
    kw.setdefault("hidden_size", 4096)
    kw.setdefault("intermediate_size", 14336)
    kw.setdefault("num_hidden_layers", 32)
    kw.setdefault("num_attention_heads", 32)
    kw.setdefault("num_key_value_heads", 8)
    kw.setdefault("max_position_embeddings", 32768)
    kw.setdefault("rope_theta", 1e6)
    return MixtralConfig(**kw)


def mixtral_tiny(**kw):
    """CI-sized config exercising routing + GQA + RoPE + SwiGLU experts."""
    kw.setdefault("vocab_size", 128)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("intermediate_size", 96)
    kw.setdefault("num_hidden_layers", 2)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("num_key_value_heads", 2)
    kw.setdefault("max_position_embeddings", 128)
    kw.setdefault("num_local_experts", 4)
    return MixtralConfig(**kw)


class MixtralSparseMoeBlock(Layer):
    """Top-k routed SwiGLU experts with stacked weights [E, h, m]/[E, m, h].

    Dispatch is the shared GShard data path (``moe.dispatch_combine``):
    static capacity ``C = ceil(S · cap_factor · k / E)``, overflow
    tokens keep their residual path only (combine weight 0) — the
    TPU-native static-shape form of the reference's per-token gather.
    ``forward`` RETURNS ``(out, aux)`` — the router load-balance aux
    loss (switch-style ``E · Σ mean(P_e)·frac_e`` scaled by
    ``router_aux_loss_coef``) must ride the return value so it crosses
    the ``jax.checkpoint`` boundary under ``use_recompute`` (a
    ``self.aux_loss`` side-channel would leak an inner-trace tracer);
    the attribute is still set for eager standalone inspection."""

    def __init__(self, config):
        super().__init__()
        h, m = config.hidden_size, config.intermediate_size
        e = config.num_local_experts
        self.num_experts = e
        self.top_k = config.num_experts_per_tok
        self.capacity_factor = config.moe_capacity_factor
        self.aux_coef = config.router_aux_loss_coef
        self.gate = Linear(h, e, weight_attr=Normal(
            0.0, config.initializer_range), bias_attr=False)
        self.w_gate = self.create_parameter(
            [e, h, m], default_initializer=XavierUniform())
        self.w_up = self.create_parameter(
            [e, h, m], default_initializer=XavierUniform())
        self.w_down = self.create_parameter(
            [e, m, h], default_initializer=XavierUniform())
        self.aux_loss = None

    def forward(self, x):
        from ..incubate.distributed.models.moe import (dispatch_combine,
                                                       ep_axis_for,
                                                       moe_capacity)

        orig_shape = x.shape
        d = orig_shape[-1]
        s = 1
        for n in orig_shape[:-1]:
            s *= n
        e, k = self.num_experts, self.top_k
        capacity = moe_capacity(s, e, k, self.capacity_factor)
        ep = ep_axis_for(e, "dp")

        def fn(xa, gw, wg, wu, wd):
            tok = xa.reshape(s, d)
            logits = tok.astype(jnp.float32) @ gw.astype(jnp.float32)

            def experts(ein):                      # [E, C, h] -> [E, C, h]
                hidd = jax.nn.silu(
                    jnp.einsum("ecd,edm->ecm", ein, wg)) \
                    * jnp.einsum("ecd,edm->ecm", ein, wu)
                return jnp.einsum("ecm,emd->ecd", hidd, wd)

            out, probs, frac = dispatch_combine(tok, logits, capacity, k,
                                                experts, ep_axis=ep,
                                                tracer_ref=xa)
            aux = self.aux_coef * e * jnp.sum(
                jnp.mean(probs, axis=0) * frac)
            return (out.reshape(orig_shape).astype(xa.dtype), aux)

        out, aux = apply(fn, x, self.gate.weight, self.w_gate, self.w_up,
                         self.w_down, op_name="mixtral_moe")
        self.aux_loss = aux
        return out, aux


class MixtralDecoderLayer(Layer):
    def __init__(self, config):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.block_sparse_moe = MixtralSparseMoeBlock(config)
        self.input_layernorm = RMSNorm(config.hidden_size,
                                       config.rms_norm_eps)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                config.rms_norm_eps)

    def forward(self, hidden, attn_mask=None, position_ids=None, cache=None):
        hidden = hidden + self.self_attn(self.input_layernorm(hidden),
                                         attn_mask, position_ids, cache)
        moe_out, aux = self.block_sparse_moe(
            self.post_attention_layernorm(hidden))
        return hidden + moe_out, aux


class MixtralModel(Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        self.embed_tokens = Embedding(
            config.vocab_size, config.hidden_size,
            weight_attr=Normal(0.0, config.initializer_range))
        self.layers = LayerList(
            [MixtralDecoderLayer(config)
             for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, config.rms_norm_eps)

    def forward(self, input_ids, attn_mask=None, position_ids=None,
                cache=None):
        hidden = self.embed_tokens(input_ids)
        hidden = shard_activation(hidden)
        recompute = (self.config.use_recompute and self.training
                     and cache is None)
        if recompute:
            from ..distributed.fleet.utils import recompute as remat
        auxes = []
        for layer in self.layers:
            if recompute:
                # the aux loss crosses the jax.checkpoint boundary as a
                # RETURN value — outer-trace legal, differentiable
                hidden, aux = remat(layer, hidden, attn_mask, position_ids)
            else:
                hidden, aux = layer(hidden, attn_mask, position_ids, cache)
            auxes.append(aux)
            hidden = shard_activation(hidden)
        self._aux_losses = auxes
        hidden = self.norm(hidden)
        if cache is not None:
            cache.advance(input_ids.shape[1])
        return hidden

    def aux_losses(self):
        """Per-layer router aux losses of the LAST forward (values
        returned through any recompute boundary, not attribute
        side-channels)."""
        return list(getattr(self, "_aux_losses", []))


class MixtralForCausalLM(GenerationMixin, Layer):
    supports_cache = True

    def __init__(self, config):
        super().__init__()
        self.config = config
        self.mixtral = MixtralModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(
                config.hidden_size, config.vocab_size,
                weight_attr=Normal(0.0, config.initializer_range),
                bias_attr=False)
        self.criterion = LlamaPretrainingCriterion()

    def forward(self, input_ids, labels=None, attn_mask=None,
                position_ids=None, cache=None):
        hidden = self.mixtral(input_ids, attn_mask, position_ids, cache)
        if self.lm_head is not None:
            logits = self.lm_head(hidden)
        else:
            logits = pmath.matmul(hidden, self.mixtral.embed_tokens.weight,
                                  transpose_y=True)
        if labels is None:
            return logits
        loss = self.criterion(logits, labels)
        for aux in self.mixtral.aux_losses():
            loss = loss + aux
        return loss, logits

    @staticmethod
    def sharding_rules():
        """Llama rules + the stacked expert weights sharded over the ep
        axis ('dp' — the reference's default ep group) on dim 0; router
        gates replicated."""
        mp = "mp"
        return [
            (r"embed_tokens\.weight$", (mp, None)),
            (r"(q_proj|k_proj|v_proj)\.weight$", (None, mp)),
            (r"o_proj\.weight$", (mp, None)),
            (r"lm_head\.weight$", (None, mp)),
            (r"(w_gate|w_up|w_down)$", ("dp", None, None)),
            (r".*", ()),   # norms, routers etc. replicated
        ]
