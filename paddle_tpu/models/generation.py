"""Autoregressive generation (reference behavior: PaddleNLP
``GenerationMixin.generate`` — greedy/sampling decode with KV cache; core
Paddle contributes the fused attention + cache kernels, SURVEY.md §2.4 note
on PaddleNLP being a separate repo → in-repo equivalent).

TPU notes: the eager cache is concat-grown (simple, correct); the compiled
serving path would preallocate [b, max_len, h, d] rings and use the Pallas
decode kernel — follow-up on the inference milestone.
"""
from __future__ import annotations

import os

import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor
from ..autograd.tape import no_grad
from ..framework import random as prandom

__all__ = ["KVCache", "PagedKVCache", "SlotPagedKVCache", "GenerationMixin",
           "block_hash_chain", "quantize_kv_rows", "dequantize_kv_rows",
           "kv_page_nbytes"]

#: kv_dtype values SlotPagedKVCache understands (PADDLE_KV_DTYPE)
KV_DTYPES = ("auto", "int8", "native")


def quantize_kv_rows(x):
    """Symmetric int8 row codec for KV pages: abs-max over the head_dim
    axis, one fp32 scale per ``[..., d]`` row — the ``quant_matmul``
    per-output-channel discipline applied at (kv_head, page, slot)
    granularity. ``x [..., d]`` -> ``(int8 [..., d], f32 scales [...])``;
    round half-to-even matches the comm-layer wire codec."""
    xf = jnp.asarray(x).astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.rint(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_kv_rows(q, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv_rows` (error bound per element:
    ``scale / 2 = max|row| / 254``)."""
    return (jnp.asarray(q).astype(jnp.float32)
            * jnp.asarray(scale)[..., None]).astype(dtype)


def kv_page_nbytes(kv_heads, head_dim, page_size=16, kv_dtype="native",
                   native_dtype="float32", num_layers=1):
    """HBM bytes ONE page pins across K+V (plus int8 row scales) for
    ``num_layers`` attention layers — the int8-KV capacity math:
    ``sessions_per_pool = pool_bytes // (pages_per_seq * this)``. int8
    vs fp32 is ``4d/(d+4)`` (~3.8x at d=64), vs bf16 ``2d/(d+4)``
    (~1.94x at d=128)."""
    elems = int(kv_heads) * int(page_size) * int(head_dim)
    if str(kv_dtype) == "int8":
        per = elems + int(kv_heads) * int(page_size) * 4   # + f32 scales
    else:
        per = elems * np.dtype(native_dtype).itemsize
    return 2 * per * int(num_layers)                       # K and V


def block_hash_chain(tokens, page_size, parent=b""):
    """vLLM-style chained block hashes for prefix caching: block ``i``'s
    key is ``sha1(key_{i-1} || tokens_of_block_i)``, so a key identifies
    not just a block's tokens but its entire left context — two prompts
    share a cache entry iff they share the whole prefix up to and
    including that block. Returns one digest per FULL block (the trailing
    partial block has no key: it is never shared)."""
    import hashlib
    arr = np.ascontiguousarray(np.asarray(tokens, np.int64).reshape(-1))
    out = []
    for i in range(len(arr) // int(page_size)):
        h = hashlib.sha1()
        h.update(parent)
        h.update(arr[i * page_size:(i + 1) * page_size].tobytes())
        parent = h.digest()
        out.append(parent)
    return out


class KVCache:
    """Per-attention-layer concat cache. ``update`` returns the full K/V so
    far (including the new tokens); ``pos`` is the filled length, advanced
    once per model forward."""

    def __init__(self):
        self.pos = 0
        self._store = {}

    def update(self, layer, k_new, v_new):
        from ..ops import manipulation as manip
        key = id(layer)
        if key in self._store:
            k_old, v_old = self._store[key]
            k = manip.concat([k_old, k_new], axis=1)
            v = manip.concat([v_old, v_new], axis=1)
        else:
            k, v = k_new, v_new
        self._store[key] = (k.detach(), v.detach())
        return k, v

    def advance(self, s):
        self.pos += int(s)

    def reorder(self, idx):
        """Gather the cache along the batch axis (beam-search hop:
        beam b's continuation may extend a DIFFERENT parent beam)."""
        for key, (k, v) in self._store.items():
            self._store[key] = (Tensor(k._data[idx]), Tensor(v._data[idx]))

    def reset(self):
        self.pos = 0
        self._store.clear()

    def attend(self, layer, q, k, v, training=False, dropout_p=0.0):
        """Cache-aware attention: update the store with this step's K/V and
        return the attention output [b, s, heads, d]. The attention layer
        delegates here so cache layouts (concat vs paged) are swappable."""
        from ..nn import functional as F
        k, v = self.update(layer, k, v)
        return F.scaled_dot_product_attention(q, k, v, attn_mask=None,
                                              dropout_p=dropout_p,
                                              is_causal=True,
                                              training=training)


class PagedKVCache(KVCache):
    """Paged (block-table) KV cache for batched decode — the serving tier's
    cache (reference: ``block_multihead_attention``'s vLLM-style paged KV;
    VERDICT.md round-1 item 10).

    K/V live in fixed-size pages ``[kv_heads, num_pages, page_size, d]``
    (kv-head-major: each (head, page) block is one contiguous aligned
    slab, the layout the TPU decode kernel DMAs) per attention layer; a
    shared per-sequence block table maps positions to pages. Prefill
    scatters the prompt's K/V into pages and attends densely; each decode
    step writes one slot and runs the ``paged_attention`` kernel
    (ops/pallas/paged_attention.py)."""

    def __init__(self, page_size=16, max_len=2048):
        super().__init__()
        self.page_size = int(page_size)
        self.max_len = int(max_len)
        self.pages_per_seq = -(-self.max_len // self.page_size)
        self._pools = {}          # id(layer) -> (k_pages, v_pages)
        self._tables = None       # [batch, pages_per_seq] int32
        self._batch = None

    def reset(self):
        super().reset()
        self._pools.clear()
        self._tables = None
        self._batch = None

    def _ensure_tables(self, batch):
        if self._tables is None:
            self._batch = batch
            # contiguous static allocation: sequence b owns pages
            # [b*pps, (b+1)*pps) — correctness-first; a free-list
            # allocator can swap in without touching the kernel
            self._tables = (np.arange(batch)[:, None] * self.pages_per_seq
                            + np.arange(self.pages_per_seq)[None, :]
                            ).astype(np.int32)
        return jnp.asarray(self._tables)

    def _pool(self, layer, kv_heads, d, dtype, batch):
        key = id(layer)
        if key not in self._pools:
            n = batch * self.pages_per_seq
            shape = (kv_heads, n, self.page_size, d)
            self._pools[key] = (jnp.zeros(shape, dtype),
                                jnp.zeros(shape, dtype))
        return self._pools[key]

    def _step_indices(self, start, s, b):
        """Scatter/kernel indices for this step — identical for every
        layer, so compute once per (pos, s, batch)."""
        key = (start, s, b)
        if getattr(self, "_idx_key", None) != key:
            pos = np.arange(start, start + s)
            self._idx_cache = (
                jnp.asarray(self._tables[:, pos // self.page_size]),   # [b,s]
                jnp.asarray((pos % self.page_size)[None, :]
                            .repeat(b, axis=0)),
                jnp.asarray(self._tables),
                jnp.full((b,), start + s, jnp.int32),
            )
            self._idx_key = key
        return self._idx_cache

    def attend(self, layer, q, k, v, training=False, dropout_p=0.0):
        from ..autograd.tape import apply
        from ..nn import functional as F

        if dropout_p and training:
            raise ValueError("PagedKVCache is a serving cache: attention "
                             "dropout is not supported")
        b, s, kv_heads, d = (k.shape if not isinstance(k, Tensor)
                             else tuple(k.shape))
        if self._batch is not None and self._batch != b:
            raise ValueError(f"PagedKVCache was allocated for batch "
                             f"{self._batch}, got {b}; call reset() first")
        self._ensure_tables(b)
        k_pages, v_pages = self._pool(layer, kv_heads, d,
                                      k._data.dtype if isinstance(k, Tensor)
                                      else k.dtype, b)
        start = self.pos
        if start + s > self.max_len:
            raise ValueError(f"PagedKVCache overflow: {start}+{s} > "
                             f"{self.max_len}")
        page_ids, slot_ids, tables, ctx = self._step_indices(start, s, b)

        def scatter(kp, vp, ka, va):
            # pools are [kv, page, slot, d]; ka/va arrive [b, s, kv, d]
            kt = jnp.moveaxis(ka, 2, 0)            # [kv, b, s, d]
            vt = jnp.moveaxis(va, 2, 0)
            kp = kp.at[:, page_ids, slot_ids].set(kt)
            vp = vp.at[:, page_ids, slot_ids].set(vt)
            return kp, vp

        new_kp, new_vp = scatter(k_pages, v_pages,
                                 k._data if isinstance(k, Tensor) else k,
                                 v._data if isinstance(v, Tensor) else v)
        self._pools[id(layer)] = (new_kp, new_vp)

        if s > 1:
            # prefill: dense attention; with prior context (a reused cache,
            # chunked prefill) read the full prefix back from the pages —
            # sdpa's bottom-right causal alignment handles sq != sk
            if start > 0:
                n_pages = -(-(start + s) // self.page_size)
                tb = jnp.asarray(self._tables[:, :n_pages])
                # [kv, b, pages, slot, d] -> [b, seq, kv, d]
                kf = Tensor(jnp.moveaxis(new_kp[:, tb], 0, 3)
                            .reshape(b, n_pages * self.page_size, kv_heads,
                                     d)[:, :start + s])
                vf = Tensor(jnp.moveaxis(new_vp[:, tb], 0, 3)
                            .reshape(b, n_pages * self.page_size, kv_heads,
                                     d)[:, :start + s])
            else:
                kf, vf = k, v
            return F.scaled_dot_product_attention(q, kf, vf, attn_mask=None,
                                                  is_causal=True,
                                                  training=training)
        # decode: one token per sequence through the paged kernel
        from ..ops.pallas.paged_attention import paged_attention
        import jax as _jax
        interpret = _jax.default_backend() != "tpu"

        def fn(qa):
            out = paged_attention(qa[:, 0], new_kp, new_vp, tables, ctx,
                                  interpret=interpret)
            return out[:, None]          # [b, 1, heads, d]

        return apply(fn, q, op_name="paged_attention")


class SlotPagedKVCache:
    """Per-slot paged KV cache over a SHARED refcounted page pool — the
    continuous-batching serving cache (reference: the vLLM-style block
    cache behind ``block_multihead_attention``; VERDICT.md round-2 item 8,
    prefix caching per Ragged Paged Attention, arxiv 2604.15464).

    Unlike :class:`PagedKVCache` (one uniform batch filled in lockstep),
    every slot here has its own context length and lifecycle: a slot is
    **assigned** a prompt on admission (leading full blocks that hit the
    hash-chained prefix index map straight onto already-filled pages —
    refcount++, zero prefill work), **prefilled** in chunks for the
    uncached suffix, participates in fixed-shape [max_batch, 1]
    **decode** steps with its own position, and is **freed** on
    completion (refcount--, pages return to the free list at zero). The
    decode step's shape never changes, so the whole serve loop stays on
    one compiled program while requests come and go.

    Pages are allocated from one free list shared by all slots; page 0
    is a scratch page — the fixed-shape decode write of a free or
    mid-prefill slot is steered there so it can never corrupt a page
    another request owns. Writes into a shared page (refcount > 1 or
    registered in the prefix index) trigger copy-on-write.
    """

    def __init__(self, max_batch, page_size=16, max_len=2048,
                 num_pages=None, enable_prefix_cache=True, kv_dtype=None):
        self.max_batch = int(max_batch)
        self.page_size = int(page_size)
        self.max_len = int(max_len)
        self.pages_per_seq = -(-self.max_len // self.page_size)
        self.enable_prefix_cache = bool(enable_prefix_cache)
        # int8 KV pages (PADDLE_KV_DTYPE=auto|int8|native): pages store
        # int8 values + one fp32 scale per (kv_head, page, slot) row,
        # halving page bytes vs bf16 (quartering vs fp32) so the same
        # HBM holds ~2x the concurrent sessions; "auto" resolves to
        # native today (int8 is an explicit capacity opt-in)
        if kv_dtype is None:
            kv_dtype = os.environ.get("PADDLE_KV_DTYPE", "auto")
        kv_dtype = str(kv_dtype).lower()
        if kv_dtype not in KV_DTYPES:
            raise ValueError(f"kv_dtype {kv_dtype!r} not in {KV_DTYPES}")
        self.kv_dtype = "native" if kv_dtype == "auto" else kv_dtype
        self.kv_quant = self.kv_dtype == "int8"
        self._scales = {}       # id(layer) -> (k_scales, v_scales) if int8
        # +1: page 0 is the never-allocated scratch page, so capacity for
        # max_batch full-length sequences survives even with zero sharing
        self.num_pages = (int(num_pages) if num_pages is not None
                          else self.max_batch * self.pages_per_seq + 1)
        if self.num_pages < self.pages_per_seq + 1:
            raise ValueError("num_pages must cover one full sequence")
        from collections import deque, OrderedDict
        self._free = deque(range(1, self.num_pages))
        self._ref = np.zeros(self.num_pages, np.int32)
        self._index = OrderedDict()       # block digest -> page (LRU order)
        self._page_digest = {}            # page -> digest (registered)
        self._chain = [None] * self.max_batch   # per-slot block digests
        self._pools = {}            # id(layer) -> (k_pages, v_pages)
        self._tables = np.zeros((self.max_batch, self.pages_per_seq),
                                np.int32)
        self._n_blocks = np.zeros(self.max_batch, np.int32)
        self.lens = np.zeros(self.max_batch, np.int32)   # filled ctx/slot
        self._mode = None            # ("prefill", slot) | ("decode", mask)
        self._idx = None             # per-forward index memo
        self._prefill_valid = None   # real tokens in the current chunk
        # prefix-cache statistics (mirrored into the telemetry registry
        # by the serving engine)
        self.prefix_hits = 0          # full blocks served from the index
        self.prefix_misses = 0        # full blocks that had to prefill
        self.cached_tokens_total = 0
        self.cow_copies = 0
        # disagg handoff: pages imported before this pool ran its first
        # forward have no per-layer arrays to land in yet — their K/V is
        # staged here and applied as each layer's pool materializes (pool
        # creation order == layer forward order == export order)
        self._import_backlog: list = []     # (page, kv/layer, scales/layer)
        self.pages_imported = 0
        self.pages_exported = 0
        # speculative-decode rejection accounting (rollback())
        self.rollbacks = 0
        self.tokens_rolled_back = 0

    # -- page allocator ------------------------------------------------------
    def _alloc_page(self):
        if not self._free:
            self._evict_lru()
        if not self._free:
            raise RuntimeError(
                f"KV page pool exhausted ({self.num_pages - 1} pages, all "
                f"backing live sequences)")
        page = self._free.popleft()
        self._ref[page] = 1
        return int(page)

    def _evict_lru(self):
        """Reclaim the least-recently-used prefix-index entry whose page
        has no live slot mapping (refcount 1 == the index's own ref)."""
        for digest in list(self._index):
            page = self._index[digest]
            if self._ref[page] == 1:
                del self._index[digest]
                del self._page_digest[page]
                self._ref[page] = 0
                self._free.append(page)
                return True
        return False

    def _decref(self, page):
        page = int(page)
        if page == 0:
            return
        if self._ref[page] <= 0:
            raise RuntimeError(f"page {page} refcount underflow")
        self._ref[page] -= 1
        if self._ref[page] == 0:
            # registered pages always carry the index's ref, so zero
            # means the page is unreachable — back to the free list
            self._free.append(page)

    def _ensure_blocks(self, slot, tokens):
        """Allocate fresh pages so ``slot`` can hold ``tokens`` context."""
        need = -(-int(tokens) // self.page_size)
        for i in range(int(self._n_blocks[slot]), need):
            self._tables[slot, i] = self._alloc_page()
        if need > self._n_blocks[slot]:
            self._n_blocks[slot] = need

    def _make_writable(self, slot, blk):
        """Copy-on-write: writing into a block whose page is shared
        (mapped by another slot, or registered in the prefix index) must
        first copy the page so the sharer's content survives."""
        page = int(self._tables[slot, blk])
        if page == 0:
            return
        if self._ref[page] <= 1 and page not in self._page_digest:
            return
        new = self._alloc_page()
        for key, (kp, vp) in self._pools.items():
            self._pools[key] = (kp.at[:, new].set(kp[:, page]),
                                vp.at[:, new].set(vp[:, page]))
        for key, (ks, vs) in self._scales.items():
            self._scales[key] = (ks.at[:, new].set(ks[:, page]),
                                 vs.at[:, new].set(vs[:, page]))
        self._decref(page)
        self._tables[slot, blk] = new
        self.cow_copies += 1

    @property
    def free_page_count(self):
        return len(self._free)

    @property
    def used_page_count(self):
        return self.num_pages - 1 - len(self._free)

    @property
    def page_nbytes(self):
        """dtype-aware HBM bytes one page pins across every layer's K+V
        pools (and int8 scale arrays) — 0 until the first forward
        materializes the pools."""
        total = 0
        for kp, vp in self._pools.values():
            total += kp.nbytes + vp.nbytes
        for ks, vs in self._scales.values():
            total += ks.nbytes + vs.nbytes
        return total // self.num_pages if total else 0

    def rollback(self, slot, n):
        """Truncate the last ``n`` context tokens of ``slot`` — the
        speculative-decode rejection path: a verify span wrote K/V for
        ``k`` drafted tokens, the target model accepted only ``m``, and
        positions past the accepted prefix must leave the context.
        Pages wholly past the truncation point are unmapped from the
        slot's table (refcount--): a page another slot still shares, or
        one the prefix index registered, keeps its other references and
        survives untouched; a private page returns to the free list.
        The kept partial block may hold stale K/V past the new length —
        masked by every reader's context bound and overwritten by the
        next write (which re-runs copy-on-write protection)."""
        slot = int(slot)
        n = int(n)
        if n <= 0:
            return 0
        if n > int(self.lens[slot]):
            raise ValueError(f"rollback {n} > slot context "
                             f"{int(self.lens[slot])}")
        new_len = int(self.lens[slot]) - n
        keep = -(-new_len // self.page_size)
        for blk in range(keep, int(self._n_blocks[slot])):
            self._decref(int(self._tables[slot, blk]))
            self._tables[slot, blk] = 0
        self._n_blocks[slot] = keep
        self.lens[slot] = new_len
        self.rollbacks += 1
        self.tokens_rolled_back += n
        return n

    # -- engine-facing lifecycle -------------------------------------------
    def assign(self, slot, prompt):
        """Admission: map the prompt's leading full blocks that hit the
        prefix index onto already-filled pages. Returns ``(cached_tokens,
        hit_blocks, missed_blocks)``; the caller only prefills
        ``prompt[cached_tokens:]``. Always leaves at least one token to
        prefill (the model must produce logits for the last prompt
        token)."""
        slot = int(slot)
        self.free(slot)                       # defensive: slot starts clean
        prompt = np.asarray(prompt).reshape(-1)
        chain = (block_hash_chain(prompt, self.page_size)
                 if self.enable_prefix_cache else [])
        self._chain[slot] = chain
        matchable = min(len(chain), (len(prompt) - 1) // self.page_size)
        matched = 0
        for i in range(matchable):
            page = self._index.get(chain[i])
            if page is None:
                break
            self._index.move_to_end(chain[i])          # LRU touch
            self._ref[page] += 1
            self._tables[slot, i] = page
            matched += 1
        self._n_blocks[slot] = matched
        cached = matched * self.page_size
        self.lens[slot] = cached
        # misses are real index lookups that came back empty — with the
        # cache disabled there are no lookups, so the hit rate stays
        # meaningful across mixed on/off runs
        missed = (max(len(prompt) // self.page_size - matched, 0)
                  if self.enable_prefix_cache else 0)
        self.prefix_hits += matched
        self.prefix_misses += missed
        self.cached_tokens_total += cached
        return cached, matched, missed

    def commit_prefix(self, slot):
        """Register the slot's now-filled full prompt blocks in the
        prefix index (digest chain computed at :meth:`assign`) so later
        prompts sharing the prefix reuse the pages. A digest another slot
        registered first wins — this slot's duplicate pages stay private
        and free normally. Returns the number of new registrations."""
        if not self.enable_prefix_cache:
            return 0
        slot = int(slot)
        chain = self._chain[slot] or []
        registered = 0
        for i, digest in enumerate(chain):
            if i >= int(self._n_blocks[slot]):
                break
            page = int(self._tables[slot, i])
            if digest in self._index or page == 0 \
                    or page in self._page_digest:
                continue
            self._index[digest] = page
            self._page_digest[page] = digest
            self._ref[page] += 1          # the index's own reference
            registered += 1
        return registered

    def begin_prefill(self, slot, n_valid=None):
        """Arm the next forward as a prefill chunk for ``slot`` writing at
        position ``lens[slot]``. ``n_valid`` is the number of REAL tokens
        in the chunk when the engine pads it to a fixed bucket shape —
        pad positions scatter to the scratch page and don't advance the
        context."""
        self._mode = ("prefill", int(slot))
        self._idx = None             # per-forward index memo (see attend)
        self._prefill_valid = None if n_valid is None else int(n_valid)

    def begin_decode(self, active_mask):
        mask = np.asarray(active_mask, bool)
        self._mode = ("decode", mask)
        self._idx = None
        for i in np.nonzero(mask)[0]:
            self._ensure_blocks(int(i), int(self.lens[i]) + 1)
            self._make_writable(int(i),
                                int(self.lens[i]) // self.page_size)

    def begin_ragged(self, spans):
        """Arm the next forward as ONE ragged mixed prefill+decode step
        (Ragged Paged Attention, arxiv 2604.15464). ``spans`` is a list
        of ``(slot, q_start, n_new)``: slot's next ``n_new`` context
        tokens sit at ``q_start`` of the flat ``[1, tokens]`` batch
        (``n_new == 1`` is a decode token). ``q_start`` must be
        non-decreasing across spans; tokens outside every span are
        bucket padding — their K/V scatters to the scratch page and
        their output is discarded. Pages are allocated and
        copy-on-write-resolved here, once per step, for every span."""
        spans = [(int(s), int(qs), int(n)) for s, qs, n in spans]
        for slot, _, n_new in spans:
            start = int(self.lens[slot])
            if start + n_new > self.max_len:
                raise ValueError(f"slot overflow: {start}+{n_new} > "
                                 f"{self.max_len}")
            self._ensure_blocks(slot, start + n_new)
            for blk in range(start // self.page_size,
                             -(-(start + n_new) // self.page_size)):
                self._make_writable(slot, blk)
        self._mode = ("ragged", spans)
        self._idx = None

    def free(self, slot):
        slot = int(slot)
        for i in range(int(self._n_blocks[slot])):
            self._decref(self._tables[slot, i])
        self._tables[slot, :] = 0
        self._n_blocks[slot] = 0
        self.lens[slot] = 0
        self._chain[slot] = None

    # -- prefill/decode disaggregation handoff -------------------------------
    def export_pages(self, digests):
        """Serialize the prefix-index pages backing the LEADING run of
        ``digests`` (a ``block_hash_chain``) — the prefill→decode
        disaggregation payload. Returns ``None`` when the first digest
        is not registered, else a dict with the digests actually
        exported and one host-side ``[kv, blocks, page_size, d]`` K/V
        array pair per attention layer (layer order == pool creation
        order == forward order, the cross-replica identity). On device
        tiers the ``np.asarray`` copies ARE the wire transfer."""
        pages, out_digests = [], []
        for d in digests:
            page = self._index.get(d)
            if page is None:
                break
            self._index.move_to_end(d)              # LRU touch
            pages.append(int(page))
            out_digests.append(bytes(d))
        if not out_digests or not self._pools:
            return None
        idx = jnp.asarray(pages)
        layers = [(np.asarray(kp[:, idx]), np.asarray(vp[:, idx]))
                  for kp, vp in self._pools.values()]
        # int8 pools ship their quantized ints AS-IS plus the per-row
        # scales — the handoff blob shrinks with the pages and the
        # receiver re-registers bit-exactly (no requantization step)
        scales = [(np.asarray(ks[:, idx]), np.asarray(vs[:, idx]))
                  for ks, vs in self._scales.values()] if self.kv_quant \
            else None
        self.pages_exported += len(pages)
        blob = {"page_size": self.page_size, "digests": out_digests,
                "layers": layers, "kv_dtype": self.kv_dtype,
                "native_dtype": str(layers[0][0].dtype), "scales": scales}
        from ..profiler import ledger as _ledger
        if _ledger.is_enabled():
            # determinism ledger: seal the handoff payload so the
            # importer can verify it arrived bit-exact
            blob["ledger_digest"] = _ledger.seal_handoff(blob)
        return blob

    def import_pages(self, blob):
        """Receiver side of the disagg handoff: allocate pages for the
        exported blocks, write their K/V into this pool, and register
        the digests in the prefix index (holding the index's own ref,
        exactly like :meth:`commit_prefix`) so the next ``assign`` of a
        prompt sharing the chain maps straight onto them. Digests
        already registered are skipped — first writer wins. Returns the
        number of pages imported."""
        if not blob or not self.enable_prefix_cache:
            return 0
        if int(blob["page_size"]) != self.page_size:
            raise ValueError(
                f"page_size mismatch: exporter {blob['page_size']} vs "
                f"importer {self.page_size}")
        blob_kv = blob.get("kv_dtype", "native")
        if blob_kv != self.kv_dtype:
            # an int8 blob landed in a native pool (or vice versa) would
            # silently de/re-quantize — reject instead; the disagg
            # handoff is best-effort and falls back to full prefill
            raise ValueError(f"kv_dtype mismatch: exporter {blob_kv} vs "
                             f"importer {self.kv_dtype}")
        if self._pools:
            pool_dtype = str(next(iter(self._pools.values()))[0].dtype)
            blob_native = blob.get("native_dtype", pool_dtype)
            if blob_native != pool_dtype:
                raise ValueError(
                    f"pool dtype mismatch: exporter {blob_native} vs "
                    f"importer {pool_dtype}")
        from ..profiler import ledger as _ledger
        if _ledger.is_enabled():
            # verify a sealed blob BEFORE any page registers — a
            # corrupted handoff must never serve tokens (raise mode) or
            # at least be on the record (warn mode)
            _ledger.check_handoff(blob)
        blob_scales = blob.get("scales")
        imported = 0
        for j, digest in enumerate(blob["digests"]):
            if digest in self._index:
                continue
            page = self._alloc_page()        # ref=1: the index's own ref
            per_layer = [(k[:, j], v[:, j]) for k, v in blob["layers"]]
            per_scales = ([(ks[:, j], vs[:, j]) for ks, vs in blob_scales]
                          if blob_scales is not None else None)
            if self._pools:
                if len(per_layer) != len(self._pools):
                    raise ValueError(
                        f"layer count mismatch: exporter "
                        f"{len(per_layer)} vs importer {len(self._pools)}")
                for li, key in enumerate(list(self._pools)):
                    kp, vp = self._pools[key]
                    kb, vb = per_layer[li]
                    self._pools[key] = (kp.at[:, page].set(kb),
                                        vp.at[:, page].set(vb))
                    if per_scales is not None:
                        ks, vs = self._scales[key]
                        ksb, vsb = per_scales[li]
                        self._scales[key] = (ks.at[:, page].set(ksb),
                                             vs.at[:, page].set(vsb))
            else:
                self._import_backlog.append((page, per_layer, per_scales))
            self._index[digest] = page
            self._page_digest[page] = digest
            imported += 1
        self.pages_imported += imported
        return imported

    @property
    def pos(self):
        # models read cache.pos for default position ids; the engine
        # always passes explicit per-slot positions instead
        m = self._mode
        return int(self.lens[m[1]]) if m and m[0] == "prefill" else 0

    def advance(self, s):
        mode, arg = self._mode
        if mode == "prefill":
            n = self._prefill_valid
            self.lens[arg] += int(s) if n is None else min(int(s), n)
        elif mode == "ragged":
            for slot, _, n_new in arg:
                self.lens[slot] += n_new
        else:
            self.lens[arg] += 1

    def _pool(self, layer, kv_heads, d, dtype):
        key = id(layer)
        if key not in self._pools:
            li = len(self._pools)       # this layer's forward-order index
            shape = (kv_heads, self.num_pages, self.page_size, d)
            pool_dtype = jnp.int8 if self.kv_quant else dtype
            kp = jnp.zeros(shape, pool_dtype)
            vp = jnp.zeros(shape, pool_dtype)
            if self.kv_quant:
                # scale 1.0 everywhere: the scratch page (and any
                # never-written slot) dequantizes to finite garbage that
                # context bounds mask, never NaN/inf
                sshape = (kv_heads, self.num_pages, self.page_size)
                ks = jnp.ones(sshape, jnp.float32)
                vs = jnp.ones(sshape, jnp.float32)
            # land any pre-forward disagg imports (import_pages before the
            # first request) for this layer; entries whose page has since
            # been evicted from the index are dead — skip them
            for page, per_layer, per_scales in self._import_backlog:
                if li < len(per_layer) and page in self._page_digest:
                    kb, vb = per_layer[li]
                    kp = kp.at[:, page].set(kb)
                    vp = vp.at[:, page].set(vb)
                    if self.kv_quant and per_scales is not None:
                        ksb, vsb = per_scales[li]
                        ks = ks.at[:, page].set(ksb)
                        vs = vs.at[:, page].set(vsb)
            self._pools[key] = (kp, vp)
            if self.kv_quant:
                self._scales[key] = (ks, vs)
        return self._pools[key]

    def _scatter(self, layer, k_pages, v_pages, kt, vt, page_ids, slot_ids):
        """Write this forward's K/V rows into the pages — quantizing on
        scatter when the pool is int8 (each ``[..., d]`` row gets its
        own fp32 scale, stored beside the pool) — and return the updated
        pools. The leading shape of ``kt``/``vt`` past the kv axis must
        match ``page_ids``/``slot_ids``."""
        key = id(layer)
        if self.kv_quant:
            kq, ks_new = quantize_kv_rows(kt)
            vq, vs_new = quantize_kv_rows(vt)
            ks, vs = self._scales[key]
            self._scales[key] = (
                ks.at[:, page_ids, slot_ids].set(ks_new),
                vs.at[:, page_ids, slot_ids].set(vs_new))
            kt, vt = kq, vq
        new_kp = k_pages.at[:, page_ids, slot_ids].set(kt)
        new_vp = v_pages.at[:, page_ids, slot_ids].set(vt)
        self._pools[key] = (new_kp, new_vp)
        return new_kp, new_vp

    def _layer_scales(self, layer):
        """(k_scales, v_scales) for the paged kernels' dequant-gather
        tiers, or (None, None) on native pools."""
        if not self.kv_quant:
            return None, None
        return self._scales[id(layer)]

    # -- attention ----------------------------------------------------------
    def attend(self, layer, q, k, v, training=False, dropout_p=0.0):
        from ..autograd.tape import apply
        from ..nn import functional as F

        mode, arg = self._mode
        ka = k._data if isinstance(k, Tensor) else k
        va = v._data if isinstance(v, Tensor) else v
        b, s, kv_heads, d = ka.shape
        k_pages, v_pages = self._pool(layer, kv_heads, d, ka.dtype)

        if mode == "prefill":
            assert b == 1, "prefill admits one request at a time"
            slot = arg
            start = int(self.lens[slot])
            n_valid = s if self._prefill_valid is None \
                else min(self._prefill_valid, s)
            if start + n_valid > self.max_len:
                raise ValueError(f"slot overflow: {start}+{n_valid} > "
                                 f"{self.max_len}")
            # NB: start + s (PADDED chunk) may exceed the slot's page
            # table near max_len — pad positions scatter to the scratch
            # page regardless, so the engine can keep every chunk shape
            # inside its fixed bucket set instead of compiling a
            # per-request tail shape
            if self._idx is None:    # indices shared by every layer
                self._ensure_blocks(slot, start + n_valid)
                for blk in range(start // self.page_size,
                                 -(-(start + n_valid) // self.page_size)):
                    self._make_writable(slot, blk)
                pos = np.arange(start, start + s)
                valid = pos < start + n_valid
                # pad positions scatter into the scratch page: their K/V
                # is garbage and must never land in an allocatable page
                blk_ids = np.minimum(pos // self.page_size,
                                     self.pages_per_seq - 1)
                self._idx = (
                    jnp.asarray(np.where(valid,
                                         self._tables[slot, blk_ids], 0)),
                    jnp.asarray(np.where(valid, pos % self.page_size, 0)))
            page_ids, slot_ids = self._idx
            kt = jnp.moveaxis(ka[0], 1, 0)          # [kv, s, d]
            vt = jnp.moveaxis(va[0], 1, 0)
            new_kp, new_vp = self._scatter(layer, k_pages, v_pages, kt, vt,
                                           page_ids, slot_ids)
            if start > 0 or self.kv_quant:
                # chunked / prefix-cached prefill: read the whole prefix
                # back from the pages; sdpa's bottom-right causal
                # alignment handles sq != sk. Table entries past the
                # allocated blocks are the scratch page — those keys sit
                # at pad positions and are never attended by valid
                # queries. int8 pools ALWAYS read back (dequantized) so
                # every attention consistently sees the quantized KV the
                # later decode steps will see.
                n_pages = min(-(-(start + s) // self.page_size),
                              self.pages_per_seq)
                tb = jnp.asarray(self._tables[slot, :n_pages])
                kp_g, vp_g = new_kp[:, tb], new_vp[:, tb]
                if self.kv_quant:
                    ks, vs = self._scales[id(layer)]
                    kp_g = dequantize_kv_rows(kp_g, ks[:, tb], ka.dtype)
                    vp_g = dequantize_kv_rows(vp_g, vs[:, tb], va.dtype)
                kf_flat = jnp.moveaxis(kp_g, 0, 2).reshape(
                    n_pages * self.page_size, kv_heads, d)
                vf_flat = jnp.moveaxis(vp_g, 0, 2).reshape(
                    n_pages * self.page_size, kv_heads, d)
                if n_pages * self.page_size < start + s:
                    # bucket-padded chunk ran past the table: keep sdpa's
                    # bottom-right causal alignment by zero-padding the
                    # key axis — the extra rows sit past every valid
                    # query's window, only pad queries (output discarded)
                    # ever attend them
                    pad = start + s - n_pages * self.page_size
                    kf_flat = jnp.pad(kf_flat, ((0, pad), (0, 0), (0, 0)))
                    vf_flat = jnp.pad(vf_flat, ((0, pad), (0, 0), (0, 0)))
                kf = Tensor(kf_flat[None, :start + s])
                vf = Tensor(vf_flat[None, :start + s])
            else:
                kf, vf = k, v
            return F.scaled_dot_product_attention(
                q, kf, vf, attn_mask=None, is_causal=True,
                training=training)

        if mode == "ragged":
            # ONE program for the whole tick: decode tokens and prefill
            # spans of several sequences packed into a flat [1, tokens]
            # batch (token-budget scheduler). K/V scatter first, then
            # the ragged kernel reads every span's full context back
            # from the pages — causal masking inside each span comes
            # from the kernel's per-token context bound.
            assert b == 1, "ragged step packs one flat token batch"
            spans = arg
            if self._idx is None:       # indices shared by every layer
                page_ids = np.zeros(s, np.int64)     # default: scratch
                slot_ids = np.zeros(s, np.int64)
                for slot, qs, n_new in spans:
                    pos = np.arange(self.lens[slot],
                                    self.lens[slot] + n_new)
                    page_ids[qs:qs + n_new] = \
                        self._tables[slot, pos // self.page_size]
                    slot_ids[qs:qs + n_new] = pos % self.page_size
                self._idx = (
                    jnp.asarray(page_ids), jnp.asarray(slot_ids),
                    jnp.asarray(self._tables),
                    jnp.asarray([sl for sl, _, _ in spans], jnp.int32),
                    jnp.asarray([qs for _, qs, _ in spans], jnp.int32),
                    jnp.asarray([n for _, _, n in spans], jnp.int32),
                    jnp.asarray([int(self.lens[sl]) + n
                                 for sl, _, n in spans], jnp.int32))
            (page_ids, slot_ids, tables, seq_slots, q_starts, q_lens,
             ctx_lens) = self._idx
            kt = jnp.moveaxis(ka[0], 1, 0)          # [kv, s, d]
            vt = jnp.moveaxis(va[0], 1, 0)
            new_kp, new_vp = self._scatter(layer, k_pages, v_pages, kt, vt,
                                           page_ids, slot_ids)
            ksc, vsc = self._layer_scales(layer)

            from ..ops.pallas.ragged_paged_attention import (
                ragged_paged_attention)
            import jax as _jax
            interpret = _jax.default_backend() != "tpu"

            def fn(qa):
                out = ragged_paged_attention(
                    qa[0], new_kp, new_vp, tables, seq_slots, q_starts,
                    q_lens, ctx_lens, k_scales=ksc, v_scales=vsc,
                    interpret=interpret)
                return out[None]         # [1, tokens, heads, d]
            return apply(fn, q, op_name="ragged_paged_attention")

        # decode: one token for EVERY slot (fixed shape), per-slot ctx
        assert b == self.max_batch and s == 1
        if self._idx is None:        # indices shared by every layer
            lens = self.lens.copy()
            # inactive / mid-prefill slots still flow through the kernel
            # (fixed shape) but their write is steered to the scratch
            # page and their ctx=1 read covers only page 0 slot 0 —
            # finite, discarded, and never a page someone else owns
            wr_blk = np.minimum(lens // self.page_size,
                                self.pages_per_seq - 1)
            self._idx = (
                jnp.asarray(np.where(
                    arg, self._tables[np.arange(b), wr_blk], 0))[:, None],
                jnp.asarray(np.where(arg, lens % self.page_size,
                                     0))[:, None],
                jnp.asarray(self._tables),
                jnp.asarray(np.where(arg, lens + 1, 1).astype(np.int32)))
        page_ids, slot_ids, tables, ctx = self._idx
        kt = jnp.moveaxis(ka, 2, 0)                 # [kv, b, 1, d]
        vt = jnp.moveaxis(va, 2, 0)
        new_kp, new_vp = self._scatter(layer, k_pages, v_pages, kt, vt,
                                       page_ids, slot_ids)
        ksc, vsc = self._layer_scales(layer)

        from ..ops.pallas.paged_attention import paged_attention
        import jax as _jax
        interpret = _jax.default_backend() != "tpu"

        def fn(qa):
            out = paged_attention(qa[:, 0], new_kp, new_vp, tables, ctx,
                                  k_scales=ksc, v_scales=vsc,
                                  interpret=interpret)
            return out[:, None]
        return apply(fn, q, op_name="paged_attention")


def _sample_logits(logits, do_sample, top_k, top_p, temperature, key=None):
    """logits [b, V] (jnp) -> token ids [b] (jnp).

    ``key`` is an explicit jax PRNG key for the categorical draw; with
    it the sample is a pure function of (logits, key) — the serving
    engine derives one key per (request seed, row, token index) so
    sampled decode is reproducible and speculative verification of
    sampled tokens is deterministic. ``None`` falls back to the global
    stateful generator (legacy call-order-dependent behavior)."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1)
    logits = logits / max(temperature, 1e-6)
    if top_k:
        kth = jnp.sort(logits, axis=-1)[:, -int(top_k)][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p and top_p < 1.0:
        sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jnp.cumsum(
            jnp.exp(sorted_l - jnp.max(sorted_l, -1, keepdims=True)) /
            jnp.sum(jnp.exp(sorted_l - jnp.max(sorted_l, -1, keepdims=True)),
                    -1, keepdims=True), axis=-1)
        cutoff_idx = jnp.sum(probs < top_p, axis=-1)
        kth = jnp.take_along_axis(sorted_l, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    import jax
    if key is None:
        key = prandom.next_key()
    return jax.random.categorical(key, logits, axis=-1)


class GenerationMixin:
    """Adds ``generate`` to causal-LM models whose forward accepts
    ``cache=`` (``supports_cache=True``) or recomputes otherwise."""

    supports_cache = False

    @no_grad()
    def generate(self, input_ids, max_new_tokens=32, max_length=None,
                 do_sample=False, top_k=0, top_p=1.0, temperature=1.0,
                 eos_token_id=None, num_beams=1, length_penalty=1.0,
                 seed=None, **kw):
        """Returns generated ids [b, prompt + new] (prompt included,
        reference decode contract). ``num_beams > 1`` runs beam search
        (reference ``decode_strategy='beam_search'``) — greedy expansion
        over the top-``num_beams`` hypotheses with KV-cache reordering;
        requires ``do_sample=False``. ``seed`` makes sampled decode
        reproducible: step ``i`` draws with ``fold_in(key(seed), i)``
        instead of the global stateful generator."""
        input_ids = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(np.asarray(input_ids, np.int64))
        if max_length is not None:
            max_new_tokens = max(max_length - input_ids.shape[1], 0)
            max_length = None
        if num_beams > 1:
            if do_sample:
                raise ValueError("beam search requires do_sample=False "
                                 "(reference beam_search is deterministic)")
            return self._beam_search(input_ids, max_new_tokens, num_beams,
                                     eos_token_id, length_penalty)
        was_training = self.training
        self.eval()
        try:
            ids = input_ids                   # prologue already normalized
            cache = kw.pop("cache", None)
            if cache is None and self.supports_cache:
                if kw.pop("use_paged_cache", False):
                    cache = PagedKVCache(
                        page_size=kw.pop("page_size", 16),
                        max_len=ids.shape[1] + max_new_tokens)
                else:
                    cache = KVCache()
            cur = ids
            all_ids = ids._data
            finished = jnp.zeros((ids.shape[0],), bool)
            base_key = None
            if seed is not None:
                import jax
                base_key = jax.random.key(int(seed))
            for step in range(max_new_tokens):
                logits = self.forward(cur, cache=cache) \
                    if cache is not None else self.forward(
                        Tensor(all_ids))
                lg = logits._data[:, -1].astype(jnp.float32)
                step_key = None
                if base_key is not None:
                    import jax
                    step_key = jax.random.fold_in(base_key, step)
                nxt = _sample_logits(lg, do_sample, top_k, top_p,
                                     temperature,
                                     key=step_key).astype(all_ids.dtype)
                if eos_token_id is not None:
                    nxt = jnp.where(finished,
                                    jnp.asarray(eos_token_id, nxt.dtype),
                                    nxt)
                    finished = jnp.logical_or(finished, nxt == eos_token_id)
                all_ids = jnp.concatenate([all_ids, nxt[:, None]], axis=1)
                cur = Tensor(nxt[:, None])
                if eos_token_id is not None and bool(finished.all()):
                    break
            return Tensor(all_ids)
        finally:
            if was_training:
                self.train()

    @no_grad()
    def _beam_search(self, input_ids, max_new_tokens, num_beams,
                     eos_token_id, length_penalty):
        """Batched beam search over the dense KV cache (paged pools are
        per-sequence-owned, so a beam hop would alias pages — the serving
        engines cover paged decode; beams use the concat cache)."""
        import jax

        was_training = self.training
        self.eval()
        try:
            ids = input_ids                   # generate() already normalized
            b, prompt = ids.shape
            n = int(num_beams)
            # expand rows to beams: [b*n, s]
            all_ids = jnp.repeat(ids._data, n, axis=0)
            cache = KVCache() if self.supports_cache else None
            # beam 0 carries the prompt; others start dead so step 1
            # doesn't pick n copies of the same continuation
            scores = jnp.tile(jnp.asarray([0.0] + [-jnp.inf] * (n - 1),
                                          jnp.float32), (b,))      # [b*n]
            finished = jnp.zeros((b * n,), bool)
            lengths = jnp.zeros((b * n,), jnp.float32)   # generated tokens
            cur = Tensor(all_ids)
            for step in range(max_new_tokens):
                logits = self.forward(cur, cache=cache) \
                    if cache is not None else self.forward(Tensor(all_ids))
                lp = jax.nn.log_softmax(
                    logits._data[:, -1].astype(jnp.float32), axis=-1)
                vocab = lp.shape[-1]
                if eos_token_id is not None:
                    # a finished beam only continues with EOS at no cost
                    frozen = jnp.full((vocab,), -jnp.inf
                                      ).at[int(eos_token_id)].set(0.0)
                    lp = jnp.where(finished[:, None], frozen[None, :], lp)
                total = scores[:, None] + lp                       # [b*n, V]
                flat = total.reshape(b, n * vocab)
                top_s, top_i = jax.lax.top_k(flat, n)              # [b, n]
                parent = (top_i // vocab + jnp.arange(b)[:, None] * n
                          ).reshape(-1)                            # [b*n]
                token = (top_i % vocab).reshape(-1)
                scores = top_s.reshape(-1)
                all_ids = jnp.concatenate(
                    [all_ids[parent], token[:, None].astype(all_ids.dtype)],
                    axis=1)
                # per-hypothesis true length: frozen at the step EOS fired
                lengths = jnp.where(finished[parent], lengths[parent],
                                    float(step + 1))
                finished = finished[parent]
                if eos_token_id is not None:
                    finished = jnp.logical_or(finished,
                                              token == eos_token_id)
                if cache is not None:
                    cache.reorder(parent)
                cur = Tensor(token[:, None].astype(all_ids.dtype))
                if eos_token_id is not None and bool(finished.all()):
                    break
            # each row's best hypothesis under the PER-HYPOTHESIS length
            # penalty (reference normalizes by the length at EOS)
            norm = scores / jnp.maximum(lengths, 1.0) ** float(
                length_penalty)
            best = jnp.argmax(norm.reshape(b, n), axis=-1) \
                + jnp.arange(b) * n
            return Tensor(all_ids[best])
        finally:
            if was_training:
                self.train()
