"""Autoregressive generation (reference behavior: PaddleNLP
``GenerationMixin.generate`` — greedy/sampling decode with KV cache; core
Paddle contributes the fused attention + cache kernels, SURVEY.md §2.4 note
on PaddleNLP being a separate repo → in-repo equivalent).

TPU notes: the eager cache is concat-grown (simple, correct); the compiled
serving path would preallocate [b, max_len, h, d] rings and use the Pallas
decode kernel — follow-up on the inference milestone.
"""
from __future__ import annotations

import os

import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor
from ..autograd.tape import no_grad
from ..framework import random as prandom

__all__ = ["KVCache", "PagedKVCache", "SlotPagedKVCache", "HostKVPool",
           "GenerationMixin", "block_hash_chain", "quantize_kv_rows",
           "dequantize_kv_rows", "kv_page_nbytes"]

#: kv_dtype values SlotPagedKVCache understands (PADDLE_KV_DTYPE)
KV_DTYPES = ("auto", "int8", "native")


def quantize_kv_rows(x):
    """Symmetric int8 row codec for KV pages: abs-max over the head_dim
    axis, one fp32 scale per ``[..., d]`` row — the ``quant_matmul``
    per-output-channel discipline applied at (kv_head, page, slot)
    granularity. ``x [..., d]`` -> ``(int8 [..., d], f32 scales [...])``;
    round half-to-even matches the comm-layer wire codec."""
    xf = jnp.asarray(x).astype(jnp.float32)
    amax = jnp.max(jnp.abs(xf), axis=-1)
    scale = jnp.maximum(amax, 1e-8) / 127.0
    q = jnp.clip(jnp.rint(xf / scale[..., None]), -127, 127).astype(jnp.int8)
    return q, scale.astype(jnp.float32)


def dequantize_kv_rows(q, scale, dtype=jnp.float32):
    """Inverse of :func:`quantize_kv_rows` (error bound per element:
    ``scale / 2 = max|row| / 254``)."""
    return (jnp.asarray(q).astype(jnp.float32)
            * jnp.asarray(scale)[..., None]).astype(dtype)


def kv_page_nbytes(kv_heads, head_dim, page_size=16, kv_dtype="native",
                   native_dtype="float32", num_layers=1):
    """HBM bytes ONE page pins across K+V (plus int8 row scales) for
    ``num_layers`` attention layers — the int8-KV capacity math:
    ``sessions_per_pool = pool_bytes // (pages_per_seq * this)``. int8
    vs fp32 is ``4d/(d+4)`` (~3.8x at d=64), vs bf16 ``2d/(d+4)``
    (~1.94x at d=128)."""
    elems = int(kv_heads) * int(page_size) * int(head_dim)
    if str(kv_dtype) == "int8":
        per = elems + int(kv_heads) * int(page_size) * 4   # + f32 scales
    else:
        per = elems * np.dtype(native_dtype).itemsize
    return 2 * per * int(num_layers)                       # K and V


def block_hash_chain(tokens, page_size, parent=b""):
    """vLLM-style chained block hashes for prefix caching: block ``i``'s
    key is ``sha1(key_{i-1} || tokens_of_block_i)``, so a key identifies
    not just a block's tokens but its entire left context — two prompts
    share a cache entry iff they share the whole prefix up to and
    including that block. Returns one digest per FULL block (the trailing
    partial block has no key: it is never shared)."""
    import hashlib
    arr = np.ascontiguousarray(np.asarray(tokens, np.int64).reshape(-1))
    out = []
    for i in range(len(arr) // int(page_size)):
        h = hashlib.sha1()
        h.update(parent)
        h.update(arr[i * page_size:(i + 1) * page_size].tobytes())
        parent = h.digest()
        out.append(parent)
    return out


class HostKVPool:
    """Host-RAM second tier under the device prefix index (ROADMAP item 4;
    arxiv 2604.15464's HBM-capacity argument taken to its conclusion). At
    fleet scale the shared-prefix working set dwarfs device HBM: today an
    LRU-evicted prefix page is simply gone and the next tenant re-prefills
    it from scratch. This pool catches those evictions — a demoted page is
    one single-page blob in the :meth:`SlotPagedKVCache.export_pages`
    codec (int8 pools demote their quantized ints + fp32 row scales as-is,
    ~4x less copy traffic than fp32) — and promotion on an admission hit
    writes the bytes back verbatim, so the roundtrip is bit-exact.

    Capacity is bounded by ``PADDLE_KV_HOST_POOL_MB`` (0 = tier disabled,
    exact legacy eviction behavior) with its own second-level LRU: when a
    demotion would exceed the bound, the least-recently-touched host
    entries fall off the end of the world. The pool is deliberately
    cache-agnostic — the serving engine owns ONE pool across cache
    rebuilds (crash recovery keeps the warm tier) and hands it to every
    :class:`SlotPagedKVCache` it constructs."""

    def __init__(self, max_mb=None):
        if max_mb is None:
            max_mb = float(os.environ.get("PADDLE_KV_HOST_POOL_MB", "0")
                           or 0)
        self.max_bytes = int(float(max_mb) * 2 ** 20)
        from collections import OrderedDict
        self._entries = OrderedDict()     # digest -> page blob (LRU order)
        self.used_bytes = 0
        self.demotions = 0        # accepted puts
        self.promotions = 0       # takes that moved a page back to device
        self.hits = 0             # lookups that found an entry
        self.misses = 0           # lookups that came back empty
        self.evictions = 0        # second-level LRU drops

    @property
    def enabled(self):
        return self.max_bytes > 0

    def __len__(self):
        return len(self._entries)

    def __contains__(self, digest):
        return bytes(digest) in self._entries

    @staticmethod
    def entry_nbytes(entry):
        total = sum(k.nbytes + v.nbytes for k, v in entry["layers"])
        if entry.get("scales"):
            total += sum(ks.nbytes + vs.nbytes
                         for ks, vs in entry["scales"])
        return total

    def put(self, digest, entry):
        """Admit a demoted page under ``digest``, evicting LRU entries
        until the byte bound holds again (an entry bigger than the whole
        pool is admitted then immediately evicted — same contract).
        Returns True when the entry is resident after the call."""
        if not self.enabled:
            return False
        digest = bytes(digest)
        old = self._entries.pop(digest, None)
        if old is not None:
            self.used_bytes -= self.entry_nbytes(old)
        self._entries[digest] = entry
        self.used_bytes += self.entry_nbytes(entry)
        self.demotions += 1
        while self.used_bytes > self.max_bytes and self._entries:
            _, dropped = self._entries.popitem(last=False)
            self.used_bytes -= self.entry_nbytes(dropped)
            self.evictions += 1
        return digest in self._entries

    def get(self, digest):
        """Peek (LRU touch, entry stays resident) — used by read-only
        consumers like the disagg exporter."""
        entry = self._entries.get(bytes(digest))
        if entry is None:
            self.misses += 1
            return None
        self._entries.move_to_end(bytes(digest))
        self.hits += 1
        return entry

    def take(self, digest):
        """Remove and return the entry (promotion path: once the page is
        device-resident and index-registered, the device index is
        authoritative — keeping the host copy would double-count bytes;
        a later eviction demotes it again)."""
        entry = self._entries.pop(bytes(digest), None)
        if entry is not None:
            self.used_bytes -= self.entry_nbytes(entry)
        return entry

    def clear(self):
        self._entries.clear()
        self.used_bytes = 0


class KVCache:
    """Per-attention-layer concat cache. ``update`` returns the full K/V so
    far (including the new tokens); ``pos`` is the filled length, advanced
    once per model forward."""

    def __init__(self):
        self.pos = 0
        self._store = {}

    def update(self, layer, k_new, v_new):
        from ..ops import manipulation as manip
        key = id(layer)
        if key in self._store:
            k_old, v_old = self._store[key]
            k = manip.concat([k_old, k_new], axis=1)
            v = manip.concat([v_old, v_new], axis=1)
        else:
            k, v = k_new, v_new
        self._store[key] = (k.detach(), v.detach())
        return k, v

    def advance(self, s):
        self.pos += int(s)

    def reorder(self, idx):
        """Gather the cache along the batch axis (beam-search hop:
        beam b's continuation may extend a DIFFERENT parent beam)."""
        for key, (k, v) in self._store.items():
            self._store[key] = (Tensor(k._data[idx]), Tensor(v._data[idx]))

    def reset(self):
        self.pos = 0
        self._store.clear()

    def attend(self, layer, q, k, v, training=False, dropout_p=0.0):
        """Cache-aware attention: update the store with this step's K/V and
        return the attention output [b, s, heads, d]. The attention layer
        delegates here so cache layouts (concat vs paged) are swappable."""
        from ..nn import functional as F
        k, v = self.update(layer, k, v)
        return F.scaled_dot_product_attention(q, k, v, attn_mask=None,
                                              dropout_p=dropout_p,
                                              is_causal=True,
                                              training=training)


class PagedKVCache(KVCache):
    """Paged (block-table) KV cache for batched decode — the serving tier's
    cache (reference: ``block_multihead_attention``'s vLLM-style paged KV;
    VERDICT.md round-1 item 10).

    K/V live in fixed-size pages ``[kv_heads, num_pages, page_size, d]``
    (kv-head-major: each (head, page) block is one contiguous aligned
    slab, the layout the TPU decode kernel DMAs) per attention layer; a
    shared per-sequence block table maps positions to pages. Prefill
    scatters the prompt's K/V into pages and attends densely; each decode
    step writes one slot and runs the ``paged_attention`` kernel
    (ops/pallas/paged_attention.py)."""

    def __init__(self, page_size=16, max_len=2048):
        super().__init__()
        self.page_size = int(page_size)
        self.max_len = int(max_len)
        self.pages_per_seq = -(-self.max_len // self.page_size)
        self._pools = {}          # id(layer) -> (k_pages, v_pages)
        self._tables = None       # [batch, pages_per_seq] int32
        self._batch = None

    def reset(self):
        super().reset()
        self._pools.clear()
        self._tables = None
        self._batch = None

    def _ensure_tables(self, batch):
        if self._tables is None:
            self._batch = batch
            # contiguous static allocation: sequence b owns pages
            # [b*pps, (b+1)*pps) — correctness-first; a free-list
            # allocator can swap in without touching the kernel
            self._tables = (np.arange(batch)[:, None] * self.pages_per_seq
                            + np.arange(self.pages_per_seq)[None, :]
                            ).astype(np.int32)
        return jnp.asarray(self._tables)

    def _pool(self, layer, kv_heads, d, dtype, batch):
        key = id(layer)
        if key not in self._pools:
            n = batch * self.pages_per_seq
            shape = (kv_heads, n, self.page_size, d)
            self._pools[key] = (jnp.zeros(shape, dtype),
                                jnp.zeros(shape, dtype))
        return self._pools[key]

    def _step_indices(self, start, s, b):
        """Scatter/kernel indices for this step — identical for every
        layer, so compute once per (pos, s, batch)."""
        key = (start, s, b)
        if getattr(self, "_idx_key", None) != key:
            pos = np.arange(start, start + s)
            self._idx_cache = (
                jnp.asarray(self._tables[:, pos // self.page_size]),   # [b,s]
                jnp.asarray((pos % self.page_size)[None, :]
                            .repeat(b, axis=0)),
                jnp.asarray(self._tables),
                jnp.full((b,), start + s, jnp.int32),
            )
            self._idx_key = key
        return self._idx_cache

    def attend(self, layer, q, k, v, training=False, dropout_p=0.0):
        from ..autograd.tape import apply
        from ..nn import functional as F

        if dropout_p and training:
            raise ValueError("PagedKVCache is a serving cache: attention "
                             "dropout is not supported")
        b, s, kv_heads, d = (k.shape if not isinstance(k, Tensor)
                             else tuple(k.shape))
        if self._batch is not None and self._batch != b:
            raise ValueError(f"PagedKVCache was allocated for batch "
                             f"{self._batch}, got {b}; call reset() first")
        self._ensure_tables(b)
        k_pages, v_pages = self._pool(layer, kv_heads, d,
                                      k._data.dtype if isinstance(k, Tensor)
                                      else k.dtype, b)
        start = self.pos
        if start + s > self.max_len:
            raise ValueError(f"PagedKVCache overflow: {start}+{s} > "
                             f"{self.max_len}")
        page_ids, slot_ids, tables, ctx = self._step_indices(start, s, b)

        def scatter(kp, vp, ka, va):
            # pools are [kv, page, slot, d]; ka/va arrive [b, s, kv, d]
            kt = jnp.moveaxis(ka, 2, 0)            # [kv, b, s, d]
            vt = jnp.moveaxis(va, 2, 0)
            kp = kp.at[:, page_ids, slot_ids].set(kt)
            vp = vp.at[:, page_ids, slot_ids].set(vt)
            return kp, vp

        new_kp, new_vp = scatter(k_pages, v_pages,
                                 k._data if isinstance(k, Tensor) else k,
                                 v._data if isinstance(v, Tensor) else v)
        self._pools[id(layer)] = (new_kp, new_vp)

        if s > 1:
            # prefill: dense attention; with prior context (a reused cache,
            # chunked prefill) read the full prefix back from the pages —
            # sdpa's bottom-right causal alignment handles sq != sk
            if start > 0:
                n_pages = -(-(start + s) // self.page_size)
                tb = jnp.asarray(self._tables[:, :n_pages])
                # [kv, b, pages, slot, d] -> [b, seq, kv, d]
                kf = Tensor(jnp.moveaxis(new_kp[:, tb], 0, 3)
                            .reshape(b, n_pages * self.page_size, kv_heads,
                                     d)[:, :start + s])
                vf = Tensor(jnp.moveaxis(new_vp[:, tb], 0, 3)
                            .reshape(b, n_pages * self.page_size, kv_heads,
                                     d)[:, :start + s])
            else:
                kf, vf = k, v
            return F.scaled_dot_product_attention(q, kf, vf, attn_mask=None,
                                                  is_causal=True,
                                                  training=training)
        # decode: one token per sequence through the paged kernel
        from ..ops.pallas.paged_attention import paged_attention
        import jax as _jax
        interpret = _jax.default_backend() != "tpu"

        def fn(qa):
            out = paged_attention(qa[:, 0], new_kp, new_vp, tables, ctx,
                                  interpret=interpret)
            return out[:, None]          # [b, 1, heads, d]

        return apply(fn, q, op_name="paged_attention")


class SlotPagedKVCache:
    """Per-slot paged KV cache over a SHARED refcounted page pool — the
    continuous-batching serving cache (reference: the vLLM-style block
    cache behind ``block_multihead_attention``; VERDICT.md round-2 item 8,
    prefix caching per Ragged Paged Attention, arxiv 2604.15464).

    Unlike :class:`PagedKVCache` (one uniform batch filled in lockstep),
    every slot here has its own context length and lifecycle: a slot is
    **assigned** a prompt on admission (leading full blocks that hit the
    hash-chained prefix index map straight onto already-filled pages —
    refcount++, zero prefill work), **prefilled** in chunks for the
    uncached suffix, participates in fixed-shape [max_batch, 1]
    **decode** steps with its own position, and is **freed** on
    completion (refcount--, pages return to the free list at zero). The
    decode step's shape never changes, so the whole serve loop stays on
    one compiled program while requests come and go.

    Pages are allocated from one free list shared by all slots; page 0
    is a scratch page — the fixed-shape decode write of a free or
    mid-prefill slot is steered there so it can never corrupt a page
    another request owns. Writes into a shared page (refcount > 1 or
    registered in the prefix index) trigger copy-on-write.
    """

    def __init__(self, max_batch, page_size=16, max_len=2048,
                 num_pages=None, enable_prefix_cache=True, kv_dtype=None,
                 host_pool=None, allow_page_overcommit=False):
        self.max_batch = int(max_batch)
        self.page_size = int(page_size)
        self.max_len = int(max_len)
        self.pages_per_seq = -(-self.max_len // self.page_size)
        self.enable_prefix_cache = bool(enable_prefix_cache)
        # int8 KV pages (PADDLE_KV_DTYPE=auto|int8|native): pages store
        # int8 values + one fp32 scale per (kv_head, page, slot) row,
        # halving page bytes vs bf16 (quartering vs fp32) so the same
        # HBM holds ~2x the concurrent sessions; "auto" resolves to
        # native today (int8 is an explicit capacity opt-in)
        if kv_dtype is None:
            kv_dtype = os.environ.get("PADDLE_KV_DTYPE", "auto")
        kv_dtype = str(kv_dtype).lower()
        if kv_dtype not in KV_DTYPES:
            raise ValueError(f"kv_dtype {kv_dtype!r} not in {KV_DTYPES}")
        self.kv_dtype = "native" if kv_dtype == "auto" else kv_dtype
        self.kv_quant = self.kv_dtype == "int8"
        self._scales = {}       # id(layer) -> (k_scales, v_scales) if int8
        # +1: page 0 is the never-allocated scratch page, so capacity for
        # max_batch full-length sequences survives even with zero sharing
        self.num_pages = (int(num_pages) if num_pages is not None
                          else self.max_batch * self.pages_per_seq + 1)
        if allow_page_overcommit:
            # sep-parallel long-context serving deliberately overcommits:
            # the bulk of a 100k+ prompt's KV lives in host-side stripes,
            # only the decode tail needs device pages
            if self.num_pages < 2:
                raise ValueError("num_pages must be >= 2")
        elif self.num_pages < self.pages_per_seq + 1:
            raise ValueError("num_pages must cover one full sequence")
        from collections import deque, OrderedDict
        self._free = deque(range(1, self.num_pages))
        self._ref = np.zeros(self.num_pages, np.int32)
        self._index = OrderedDict()       # block digest -> page (LRU order)
        self._page_digest = {}            # page -> digest (registered)
        self._chain = [None] * self.max_batch   # per-slot block digests
        self._pools = {}            # id(layer) -> (k_pages, v_pages)
        self._tables = np.zeros((self.max_batch, self.pages_per_seq),
                                np.int32)
        self._n_blocks = np.zeros(self.max_batch, np.int32)
        self.lens = np.zeros(self.max_batch, np.int32)   # filled ctx/slot
        self._mode = None            # ("prefill", slot) | ("decode", mask)
        self._idx = None             # per-forward index memo
        self._prefill_valid = None   # real tokens in the current chunk
        # prefix-cache statistics (mirrored into the telemetry registry
        # by the serving engine)
        self.prefix_hits = 0          # full blocks served from the index
        self.prefix_misses = 0        # full blocks that had to prefill
        self.cached_tokens_total = 0
        self.cow_copies = 0
        # disagg handoff: pages imported before this pool ran its first
        # forward have no per-layer arrays to land in yet — their K/V is
        # staged here and applied as each layer's pool materializes (pool
        # creation order == layer forward order == export order)
        self._import_backlog: list = []     # (page, kv/layer, scales/layer)
        self.pages_imported = 0
        self.pages_exported = 0
        # speculative-decode rejection accounting (rollback())
        self.rollbacks = 0
        self.tokens_rolled_back = 0
        # tiered KV: host-RAM second level under the prefix index.
        # ``host_pool=None`` builds a private pool from the env knob
        # (PADDLE_KV_HOST_POOL_MB=0 keeps the tier off); the serving
        # engine passes its own long-lived pool so the warm tier
        # survives cache rebuilds.
        self.host_pool = host_pool if host_pool is not None else HostKVPool()
        self.prefix_evictions_device = 0   # device-index LRU evictions
        self.host_demotions = 0            # evictions caught by the tier
        self.host_promotions = 0           # host hits moved back to device
        self.host_promote_rejects = 0      # dtype/geometry mismatch drops
        # sep-parallel long-context prefill: per-slot stripe state — the
        # prompt span is chunked into fixed ``stripe`` token blocks whose
        # K/V lives as host-side stripes (the single-host stand-in for
        # pages striped across the sep ring's replicas), only the decode
        # tail occupies device pages
        self._sep = [None] * self.max_batch
        self._sep_pending = None     # per-layer K/V of the in-flight chunk
        self._sep_layer_i = 0        # forward-order layer cursor
        self.sep_stripes_stored = 0
        self.sep_chunks = 0
        self.sep_decode_steps = 0

    # -- page allocator ------------------------------------------------------
    def _alloc_page(self):
        if not self._free:
            self._evict_lru()
        if not self._free:
            raise RuntimeError(
                f"KV page pool exhausted ({self.num_pages - 1} pages, all "
                f"backing live sequences)")
        page = self._free.popleft()
        self._ref[page] = 1
        return int(page)

    def _evict_lru(self):
        """Reclaim the least-recently-used prefix-index entry whose page
        has no live slot mapping (refcount 1 == the index's own ref).
        With the host tier enabled the page's bytes are demoted there
        before the device page frees — the prefix survives device churn
        and a later :meth:`assign` promotes it back."""
        for digest in list(self._index):
            page = self._index[digest]
            if self._ref[page] == 1:
                self._demote(digest, page)
                del self._index[digest]
                del self._page_digest[page]
                self._ref[page] = 0
                self._free.append(page)
                self.prefix_evictions_device += 1
                return True
        return False

    def _page_entry(self, page):
        """Single-page host blob in the export_pages codec layout: one
        ``[kv, page_size, d]`` K/V pair per layer (pool/forward order)
        plus the int8 row scales — np copies, device-independent."""
        layers = [(np.asarray(kp[:, page]), np.asarray(vp[:, page]))
                  for kp, vp in self._pools.values()]
        scales = ([(np.asarray(ks[:, page]), np.asarray(vs[:, page]))
                   for ks, vs in self._scales.values()]
                  if self.kv_quant else None)
        return {"page_size": self.page_size, "kv_dtype": self.kv_dtype,
                "native_dtype": str(layers[0][0].dtype),
                "layers": layers, "scales": scales}

    def _demote(self, digest, page):
        """Eviction hook: copy the page into the host tier (no-op when
        the tier is off, or before the first forward materializes the
        pools — there is nothing to copy yet)."""
        hp = self.host_pool
        if hp is None or not hp.enabled or not self._pools:
            return False
        if hp.put(bytes(digest), self._page_entry(int(page))):
            self.host_demotions += 1
            return True
        return False

    def _promote(self, digest):
        """Admission hook: move a host-tier entry back onto a device page
        and register it in the prefix index (the index's own ref, like
        :meth:`commit_prefix`). Returns the page, or None on miss /
        mismatch / device pool exhaustion (entry stays host-resident in
        the last case so a later admission can retry)."""
        hp = self.host_pool
        if hp is None or not hp.enabled:
            return None
        entry = hp.get(bytes(digest))
        if entry is None:
            return None
        ok = (int(entry["page_size"]) == self.page_size
              and entry["kv_dtype"] == self.kv_dtype)
        if ok and self._pools:
            pool_dtype = str(next(iter(self._pools.values()))[0].dtype)
            ok = (entry["native_dtype"] == pool_dtype
                  and len(entry["layers"]) == len(self._pools))
        if not ok:
            # a stale entry from a differently-configured cache can never
            # land bit-exactly — drop it rather than poison the pool
            hp.take(bytes(digest))
            self.host_promote_rejects += 1
            return None
        entry = hp.take(bytes(digest))
        try:
            # may recursively _evict_lru -> _demote colder digests; our
            # entry is already off the host LRU so it cannot be a victim
            page = self._alloc_page()
        except RuntimeError:
            hp.put(bytes(digest), entry)
            return None
        if self._pools:
            scales = entry["scales"]
            for li, key in enumerate(list(self._pools)):
                kp, vp = self._pools[key]
                kb, vb = entry["layers"][li]
                self._pools[key] = (kp.at[:, page].set(kb),
                                    vp.at[:, page].set(vb))
                if self.kv_quant and scales is not None:
                    ks, vs = self._scales[key]
                    ksb, vsb = scales[li]
                    self._scales[key] = (ks.at[:, page].set(ksb),
                                         vs.at[:, page].set(vsb))
        else:
            self._import_backlog.append(
                (page, entry["layers"], entry["scales"]))
        self._index[bytes(digest)] = page     # MRU end, ref=1 = index's
        self._page_digest[page] = bytes(digest)
        self.host_promotions += 1
        hp.promotions += 1
        return page

    def _decref(self, page):
        page = int(page)
        if page == 0:
            return
        if self._ref[page] <= 0:
            raise RuntimeError(f"page {page} refcount underflow")
        self._ref[page] -= 1
        if self._ref[page] == 0:
            # registered pages always carry the index's ref, so zero
            # means the page is unreachable — back to the free list
            self._free.append(page)

    def _ensure_blocks(self, slot, tokens):
        """Allocate fresh pages so ``slot`` can hold ``tokens`` context."""
        need = -(-int(tokens) // self.page_size)
        for i in range(int(self._n_blocks[slot]), need):
            self._tables[slot, i] = self._alloc_page()
        if need > self._n_blocks[slot]:
            self._n_blocks[slot] = need

    def _make_writable(self, slot, blk):
        """Copy-on-write: writing into a block whose page is shared
        (mapped by another slot, or registered in the prefix index) must
        first copy the page so the sharer's content survives."""
        page = int(self._tables[slot, blk])
        if page == 0:
            return
        if self._ref[page] <= 1 and page not in self._page_digest:
            return
        new = self._alloc_page()
        for key, (kp, vp) in self._pools.items():
            self._pools[key] = (kp.at[:, new].set(kp[:, page]),
                                vp.at[:, new].set(vp[:, page]))
        for key, (ks, vs) in self._scales.items():
            self._scales[key] = (ks.at[:, new].set(ks[:, page]),
                                 vs.at[:, new].set(vs[:, page]))
        self._decref(page)
        self._tables[slot, blk] = new
        self.cow_copies += 1

    @property
    def free_page_count(self):
        return len(self._free)

    @property
    def used_page_count(self):
        return self.num_pages - 1 - len(self._free)

    @property
    def page_nbytes(self):
        """dtype-aware HBM bytes one page pins across every layer's K+V
        pools (and int8 scale arrays) — 0 until the first forward
        materializes the pools."""
        total = 0
        for kp, vp in self._pools.values():
            total += kp.nbytes + vp.nbytes
        for ks, vs in self._scales.values():
            total += ks.nbytes + vs.nbytes
        return total // self.num_pages if total else 0

    def rollback(self, slot, n):
        """Truncate the last ``n`` context tokens of ``slot`` — the
        speculative-decode rejection path: a verify span wrote K/V for
        ``k`` drafted tokens, the target model accepted only ``m``, and
        positions past the accepted prefix must leave the context.
        Pages wholly past the truncation point are unmapped from the
        slot's table (refcount--): a page another slot still shares, or
        one the prefix index registered, keeps its other references and
        survives untouched; a private page returns to the free list.
        The kept partial block may hold stale K/V past the new length —
        masked by every reader's context bound and overwritten by the
        next write (which re-runs copy-on-write protection)."""
        slot = int(slot)
        n = int(n)
        if n <= 0:
            return 0
        if n > int(self.lens[slot]):
            raise ValueError(f"rollback {n} > slot context "
                             f"{int(self.lens[slot])}")
        new_len = int(self.lens[slot]) - n
        keep = -(-new_len // self.page_size)
        for blk in range(keep, int(self._n_blocks[slot])):
            self._decref(int(self._tables[slot, blk]))
            self._tables[slot, blk] = 0
        self._n_blocks[slot] = keep
        self.lens[slot] = new_len
        self.rollbacks += 1
        self.tokens_rolled_back += n
        return n

    # -- engine-facing lifecycle -------------------------------------------
    def assign(self, slot, prompt):
        """Admission: map the prompt's leading full blocks that hit the
        prefix index onto already-filled pages. Returns ``(cached_tokens,
        hit_blocks, missed_blocks)``; the caller only prefills
        ``prompt[cached_tokens:]``. Always leaves at least one token to
        prefill (the model must produce logits for the last prompt
        token)."""
        slot = int(slot)
        self.free(slot)                       # defensive: slot starts clean
        prompt = np.asarray(prompt).reshape(-1)
        chain = (block_hash_chain(prompt, self.page_size)
                 if self.enable_prefix_cache else [])
        self._chain[slot] = chain
        matchable = min(len(chain), (len(prompt) - 1) // self.page_size)
        matched = 0
        for i in range(matchable):
            page = self._index.get(chain[i])
            if page is not None:
                self._index.move_to_end(chain[i])      # LRU touch
            else:
                # device miss: the block may have been demoted to the
                # host tier — promote it back and keep matching
                page = self._promote(chain[i])
            if page is None:
                break
            self._ref[page] += 1
            self._tables[slot, i] = page
            matched += 1
        self._n_blocks[slot] = matched
        cached = matched * self.page_size
        self.lens[slot] = cached
        # misses are real index lookups that came back empty — with the
        # cache disabled there are no lookups, so the hit rate stays
        # meaningful across mixed on/off runs
        missed = (max(len(prompt) // self.page_size - matched, 0)
                  if self.enable_prefix_cache else 0)
        self.prefix_hits += matched
        self.prefix_misses += missed
        self.cached_tokens_total += cached
        return cached, matched, missed

    def commit_prefix(self, slot):
        """Register the slot's now-filled full prompt blocks in the
        prefix index (digest chain computed at :meth:`assign`) so later
        prompts sharing the prefix reuse the pages. A digest another slot
        registered first wins — this slot's duplicate pages stay private
        and free normally. Returns the number of new registrations."""
        if not self.enable_prefix_cache:
            return 0
        slot = int(slot)
        chain = self._chain[slot] or []
        registered = 0
        for i, digest in enumerate(chain):
            if i >= int(self._n_blocks[slot]):
                break
            page = int(self._tables[slot, i])
            if digest in self._index or page == 0 \
                    or page in self._page_digest:
                continue
            self._index[digest] = page
            self._page_digest[page] = digest
            self._ref[page] += 1          # the index's own reference
            registered += 1
        return registered

    def begin_prefill(self, slot, n_valid=None):
        """Arm the next forward as a prefill chunk for ``slot`` writing at
        position ``lens[slot]``. ``n_valid`` is the number of REAL tokens
        in the chunk when the engine pads it to a fixed bucket shape —
        pad positions scatter to the scratch page and don't advance the
        context."""
        self._mode = ("prefill", int(slot))
        self._idx = None             # per-forward index memo (see attend)
        self._prefill_valid = None if n_valid is None else int(n_valid)

    def begin_decode(self, active_mask):
        mask = np.asarray(active_mask, bool)
        self._mode = ("decode", mask)
        self._idx = None
        for i in np.nonzero(mask)[0]:
            self._ensure_blocks(int(i), int(self.lens[i]) + 1)
            self._make_writable(int(i),
                                int(self.lens[i]) // self.page_size)

    def begin_ragged(self, spans):
        """Arm the next forward as ONE ragged mixed prefill+decode step
        (Ragged Paged Attention, arxiv 2604.15464). ``spans`` is a list
        of ``(slot, q_start, n_new)``: slot's next ``n_new`` context
        tokens sit at ``q_start`` of the flat ``[1, tokens]`` batch
        (``n_new == 1`` is a decode token). ``q_start`` must be
        non-decreasing across spans; tokens outside every span are
        bucket padding — their K/V scatters to the scratch page and
        their output is discarded. Pages are allocated and
        copy-on-write-resolved here, once per step, for every span."""
        spans = [(int(s), int(qs), int(n)) for s, qs, n in spans]
        for slot, _, n_new in spans:
            start = int(self.lens[slot])
            if start + n_new > self.max_len:
                raise ValueError(f"slot overflow: {start}+{n_new} > "
                                 f"{self.max_len}")
            self._ensure_blocks(slot, start + n_new)
            for blk in range(start // self.page_size,
                             -(-(start + n_new) // self.page_size)):
                self._make_writable(slot, blk)
        self._mode = ("ragged", spans)
        self._idx = None

    def free(self, slot):
        slot = int(slot)
        # sep slots own no device pages below their tail block — those
        # table entries stay 0 and _decref(0) is a no-op, so one loop
        # covers both lifecycles
        self._sep[slot] = None
        for i in range(int(self._n_blocks[slot])):
            self._decref(self._tables[slot, i])
        self._tables[slot, :] = 0
        self._n_blocks[slot] = 0
        self.lens[slot] = 0
        self._chain[slot] = None

    # -- prefill/decode disaggregation handoff -------------------------------
    def export_pages(self, digests):
        """Serialize the prefix-index pages backing the LEADING run of
        ``digests`` (a ``block_hash_chain``) — the prefill→decode
        disaggregation payload. Returns ``None`` when the first digest
        is not registered, else a dict with the digests actually
        exported and one host-side ``[kv, blocks, page_size, d]`` K/V
        array pair per attention layer (layer order == pool creation
        order == forward order, the cross-replica identity). On device
        tiers the ``np.asarray`` copies ARE the wire transfer.

        Tiered KV: a digest missing from the device index is looked up
        in the host tier — a demoted block still hands off (read-only,
        no promotion), so the disagg path survives device churn. The
        blob reports how many blocks came from host as ``host_pages``."""
        entries, out_digests, host_pages = [], [], 0
        hp = self.host_pool
        for d in digests:
            page = self._index.get(d)
            if page is not None:
                if not self._pools:
                    break                 # device KV not materialized yet
                self._index.move_to_end(d)          # LRU touch
                entries.append(self._page_entry(int(page)))
            else:
                he = (hp.get(bytes(d))
                      if hp is not None and hp.enabled else None)
                if (he is None or int(he["page_size"]) != self.page_size
                        or he["kv_dtype"] != self.kv_dtype
                        or (entries and len(he["layers"]) !=
                            len(entries[0]["layers"]))):
                    break
                entries.append(he)
                host_pages += 1
            out_digests.append(bytes(d))
        if not entries:
            return None
        n_layers = len(entries[0]["layers"])
        if any(len(e["layers"]) != n_layers for e in entries):
            return None
        # stack per-page blobs into the [kv, blocks, page_size, d] wire
        # layout; int8 pools ship their quantized ints AS-IS plus the
        # per-row scales — the handoff blob shrinks with the pages and
        # the receiver re-registers bit-exactly (no requantization step)
        layers = [(np.stack([e["layers"][li][0] for e in entries], axis=1),
                   np.stack([e["layers"][li][1] for e in entries], axis=1))
                  for li in range(n_layers)]
        scales = ([(np.stack([e["scales"][li][0] for e in entries], axis=1),
                    np.stack([e["scales"][li][1] for e in entries], axis=1))
                   for li in range(n_layers)] if self.kv_quant else None)
        self.pages_exported += len(entries)
        blob = {"page_size": self.page_size, "digests": out_digests,
                "layers": layers, "kv_dtype": self.kv_dtype,
                "native_dtype": str(layers[0][0].dtype), "scales": scales,
                "host_pages": host_pages}
        from ..profiler import ledger as _ledger
        if _ledger.is_enabled():
            # determinism ledger: seal the handoff payload so the
            # importer can verify it arrived bit-exact
            blob["ledger_digest"] = _ledger.seal_handoff(blob)
        return blob

    def import_pages(self, blob):
        """Receiver side of the disagg handoff: allocate pages for the
        exported blocks, write their K/V into this pool, and register
        the digests in the prefix index (holding the index's own ref,
        exactly like :meth:`commit_prefix`) so the next ``assign`` of a
        prompt sharing the chain maps straight onto them. Digests
        already registered are skipped — first writer wins. Returns the
        number of pages imported."""
        if not blob or not self.enable_prefix_cache:
            return 0
        if int(blob["page_size"]) != self.page_size:
            raise ValueError(
                f"page_size mismatch: exporter {blob['page_size']} vs "
                f"importer {self.page_size}")
        blob_kv = blob.get("kv_dtype", "native")
        if blob_kv != self.kv_dtype:
            # an int8 blob landed in a native pool (or vice versa) would
            # silently de/re-quantize — reject instead; the disagg
            # handoff is best-effort and falls back to full prefill
            raise ValueError(f"kv_dtype mismatch: exporter {blob_kv} vs "
                             f"importer {self.kv_dtype}")
        if self._pools:
            pool_dtype = str(next(iter(self._pools.values()))[0].dtype)
            blob_native = blob.get("native_dtype", pool_dtype)
            if blob_native != pool_dtype:
                raise ValueError(
                    f"pool dtype mismatch: exporter {blob_native} vs "
                    f"importer {pool_dtype}")
        from ..profiler import ledger as _ledger
        if _ledger.is_enabled():
            # verify a sealed blob BEFORE any page registers — a
            # corrupted handoff must never serve tokens (raise mode) or
            # at least be on the record (warn mode)
            _ledger.check_handoff(blob)
        blob_scales = blob.get("scales")
        imported = 0
        for j, digest in enumerate(blob["digests"]):
            if digest in self._index:
                continue
            page = self._alloc_page()        # ref=1: the index's own ref
            per_layer = [(k[:, j], v[:, j]) for k, v in blob["layers"]]
            per_scales = ([(ks[:, j], vs[:, j]) for ks, vs in blob_scales]
                          if blob_scales is not None else None)
            if self._pools:
                if len(per_layer) != len(self._pools):
                    raise ValueError(
                        f"layer count mismatch: exporter "
                        f"{len(per_layer)} vs importer {len(self._pools)}")
                for li, key in enumerate(list(self._pools)):
                    kp, vp = self._pools[key]
                    kb, vb = per_layer[li]
                    self._pools[key] = (kp.at[:, page].set(kb),
                                        vp.at[:, page].set(vb))
                    if per_scales is not None:
                        ks, vs = self._scales[key]
                        ksb, vsb = per_scales[li]
                        self._scales[key] = (ks.at[:, page].set(ksb),
                                             vs.at[:, page].set(vsb))
            else:
                self._import_backlog.append((page, per_layer, per_scales))
            self._index[digest] = page
            self._page_digest[page] = digest
            imported += 1
        self.pages_imported += imported
        return imported

    # -- sep-parallel long-context prefill -----------------------------------
    def assign_sep(self, slot, prompt_tokens, stripe_tokens):
        """Arm ``slot`` for sep-parallel long-context serving: the prompt
        is prefilled in fixed ``stripe_tokens`` chunks whose K/V is kept
        as host-side stripes (ring order — stripe ``i``'s home replica is
        ``i % sep_ways``; see :meth:`export_stripes`) instead of device
        pages, so a prompt far larger than the page pool still serves.
        Only the trailing partial chunk and the decode tail land in
        device pages. No prefix-index interaction: a striped span is not
        page-granular shareable."""
        slot = int(slot)
        self.free(slot)
        n = int(prompt_tokens)
        stripe = int(stripe_tokens)
        if stripe <= 0 or stripe % self.page_size:
            raise ValueError(f"stripe_tokens {stripe} must be a positive "
                             f"multiple of page_size {self.page_size}")
        if self.kv_quant:
            raise ValueError("sep prefill requires native KV pages "
                             "(PADDLE_KV_DTYPE=int8 is unsupported)")
        if n > self.max_len:
            raise ValueError(f"prompt {n} > max_len {self.max_len}")
        self._sep[slot] = {"stripe": stripe, "base": 0, "len": n,
                           "stripes": []}
        return -(-n // stripe)          # chunks the engine will drive

    def begin_sep_prefill(self, slot, n_valid=None):
        """Arm the next forward as one fixed-shape sep prefill chunk for
        ``slot`` (chunk length == stripe length; ``n_valid`` marks the
        real tokens of the trailing partial chunk)."""
        slot = int(slot)
        if self._sep[slot] is None:
            raise RuntimeError(f"slot {slot} is not sep-assigned")
        self._mode = ("sep_prefill", slot)
        self._idx = None
        self._prefill_valid = None if n_valid is None else int(n_valid)
        self._sep_pending = []
        self._sep_layer_i = 0
        self.sep_chunks += 1

    def begin_sep_decode(self, slot):
        """Arm the next forward as a [1, 1] decode step of a sep slot:
        the token's K/V lands in a device tail page; attention reads the
        stripes plus the tail through the same block table."""
        slot = int(slot)
        sep = self._sep[slot]
        if sep is None:
            raise RuntimeError(f"slot {slot} is not sep-assigned")
        self._mode = ("sep_decode", slot)
        self._idx = None
        self._sep_layer_i = 0
        blk0 = sep["base"] // self.page_size
        if int(self._n_blocks[slot]) < blk0:
            # blocks below the tail stay unallocated (stripes cover those
            # positions); start the allocator at the tail's first block
            self._n_blocks[slot] = blk0
        self._ensure_blocks(slot, int(self.lens[slot]) + 1)
        self._make_writable(slot, int(self.lens[slot]) // self.page_size)
        self.sep_decode_steps += 1

    def export_stripes(self, slot, sep_ways=None):
        """Striped-page disagg payload for a live sep slot: each stripe
        is tagged with its home replica on the sep ring (``i % ways``,
        ``PADDLE_SEP_WAYS``) — the layout a multi-process fleet shards
        by, and the single-host blob a migration ships whole."""
        slot = int(slot)
        sep = self._sep[slot]
        if sep is None:
            return None
        ways = int(sep_ways if sep_ways is not None
                   else os.environ.get("PADDLE_SEP_WAYS", "1") or 1)
        stripes = [{"home": j % max(ways, 1),
                    "layers": [(np.asarray(k), np.asarray(v))
                               for k, v in st]}
                   for j, st in enumerate(sep["stripes"])]
        native = (str(stripes[0]["layers"][0][0].dtype) if stripes
                  else None)
        # the decode tail [base, pos) lives in device pages — ship it as
        # raw [kv, n_tail, d] rows so the importer can resume mid-span
        base, pos = int(sep["base"]), int(self.lens[slot])
        tail = None
        if pos > base and self._pools:
            blk0 = base // self.page_size
            n_pages = -(-(pos - base) // self.page_size)
            tb = jnp.asarray(self._tables[slot, blk0:blk0 + n_pages])
            tail = [(np.asarray(kp[:, tb].reshape(
                         kp.shape[0], -1, kp.shape[-1])[:, :pos - base]),
                     np.asarray(vp[:, tb].reshape(
                         vp.shape[0], -1, vp.shape[-1])[:, :pos - base]))
                    for kp, vp in self._pools.values()]
        return {"page_size": self.page_size, "stripe": sep["stripe"],
                "base": base, "len": int(sep["len"]), "pos": pos,
                "native_dtype": native, "sep_ways": max(ways, 1),
                "stripes": stripes, "tail": tail}

    def import_stripes(self, slot, blob):
        """Receiver side of a striped handoff: arm ``slot`` with the
        exported stripes and resume at the exporter's position — the
        importer continues prefilling from ``pos`` (or decoding, if the
        span completed). Returns the number of stripes imported."""
        slot = int(slot)
        if not blob:
            return 0
        if int(blob["page_size"]) != self.page_size:
            raise ValueError(
                f"page_size mismatch: exporter {blob['page_size']} vs "
                f"importer {self.page_size}")
        stripe = int(blob["stripe"])
        if self.kv_quant:
            raise ValueError("sep stripes require a native KV pool")
        if self._pools and blob.get("native_dtype"):
            pool_dtype = str(next(iter(self._pools.values()))[0].dtype)
            if blob["native_dtype"] != pool_dtype:
                raise ValueError(
                    f"pool dtype mismatch: exporter "
                    f"{blob['native_dtype']} vs importer {pool_dtype}")
        base, pos = int(blob["base"]), int(blob["pos"])
        tail = blob.get("tail")
        if pos > base and tail is None:
            raise ValueError("striped blob resumes mid-span but carries "
                             "no tail rows")
        if tail is not None and not self._pools:
            # landing tail rows needs per-layer pools; stripes alone
            # (pos == base) import anywhere. Engines materialize pools
            # at warmup, so this only bites bare caches.
            raise ValueError("import_stripes needs materialized pools "
                             "to land a mid-span tail")
        if tail is not None and len(tail) != len(self._pools):
            raise ValueError(f"layer count mismatch: exporter "
                             f"{len(tail)} vs importer {len(self._pools)}")
        self.free(slot)
        self._sep[slot] = {
            "stripe": stripe, "base": base, "len": int(blob["len"]),
            "stripes": [[(np.asarray(k), np.asarray(v))
                         for k, v in st["layers"]]
                        for st in blob["stripes"]]}
        self.lens[slot] = pos
        if tail is not None:
            blk0 = base // self.page_size
            self._n_blocks[slot] = blk0
            self._ensure_blocks(slot, pos)
            n_pages = -(-(pos - base) // self.page_size)
            tb = jnp.asarray(self._tables[slot, blk0:blk0 + n_pages])
            pad = n_pages * self.page_size - (pos - base)
            for li, key in enumerate(list(self._pools)):
                kp, vp = self._pools[key]
                kb = jnp.pad(jnp.asarray(tail[li][0]),
                             ((0, 0), (0, pad), (0, 0)))
                vb = jnp.pad(jnp.asarray(tail[li][1]),
                             ((0, 0), (0, pad), (0, 0)))
                shape = (kp.shape[0], n_pages, self.page_size,
                         kp.shape[-1])
                self._pools[key] = (
                    kp.at[:, tb].set(kb.reshape(shape)),
                    vp.at[:, tb].set(vb.reshape(shape)))
        self.sep_stripes_stored += len(blob["stripes"])
        return len(blob["stripes"])

    def sep_view(self, slot):
        """Shape-relevant sep state for the engine's observatory
        signatures: the stripe count and the pow2 tail-page window the
        NEXT decode step would compile with."""
        sep = self._sep[int(slot)]
        if sep is None:
            return None
        n_tail = int(self.lens[slot]) + 1 - sep["base"]
        n_tp = -(-max(n_tail, 1) // self.page_size)
        return {"stripes": len(sep["stripes"]),
                "tail_pages": 1 << max(n_tp - 1, 0).bit_length(),
                "base": int(sep["base"]), "len": int(sep["len"])}

    @property
    def pos(self):
        # models read cache.pos for default position ids; the engine
        # always passes explicit per-slot positions instead
        m = self._mode
        if m and m[0] in ("prefill", "sep_prefill"):
            return int(self.lens[m[1]])
        return 0

    def advance(self, s):
        mode, arg = self._mode
        if mode == "prefill":
            n = self._prefill_valid
            self.lens[arg] += int(s) if n is None else min(int(s), n)
        elif mode == "sep_prefill":
            sep = self._sep[arg]
            n = self._prefill_valid
            n = int(s) if n is None else min(int(s), n)
            if self._sep_pending:
                # a full chunk becomes the next stripe on the ring
                sep["stripes"].append(list(self._sep_pending))
                sep["base"] += sep["stripe"]
                self.sep_stripes_stored += 1
            self._sep_pending = None
            self.lens[arg] += n
        elif mode == "ragged":
            for slot, _, n_new in arg:
                self.lens[slot] += n_new
        else:                   # "decode" mask or "sep_decode" slot
            self.lens[arg] += 1

    def _pool(self, layer, kv_heads, d, dtype):
        key = id(layer)
        if key not in self._pools:
            li = len(self._pools)       # this layer's forward-order index
            shape = (kv_heads, self.num_pages, self.page_size, d)
            pool_dtype = jnp.int8 if self.kv_quant else dtype
            kp = jnp.zeros(shape, pool_dtype)
            vp = jnp.zeros(shape, pool_dtype)
            if self.kv_quant:
                # scale 1.0 everywhere: the scratch page (and any
                # never-written slot) dequantizes to finite garbage that
                # context bounds mask, never NaN/inf
                sshape = (kv_heads, self.num_pages, self.page_size)
                ks = jnp.ones(sshape, jnp.float32)
                vs = jnp.ones(sshape, jnp.float32)
            # land any pre-forward disagg imports (import_pages before the
            # first request) for this layer; entries whose page has since
            # been evicted from the index are dead — skip them
            for page, per_layer, per_scales in self._import_backlog:
                if li < len(per_layer) and page in self._page_digest:
                    kb, vb = per_layer[li]
                    kp = kp.at[:, page].set(kb)
                    vp = vp.at[:, page].set(vb)
                    if self.kv_quant and per_scales is not None:
                        ksb, vsb = per_scales[li]
                        ks = ks.at[:, page].set(ksb)
                        vs = vs.at[:, page].set(vsb)
            self._pools[key] = (kp, vp)
            if self.kv_quant:
                self._scales[key] = (ks, vs)
        return self._pools[key]

    def _scatter(self, layer, k_pages, v_pages, kt, vt, page_ids, slot_ids):
        """Write this forward's K/V rows into the pages — quantizing on
        scatter when the pool is int8 (each ``[..., d]`` row gets its
        own fp32 scale, stored beside the pool) — and return the updated
        pools. The leading shape of ``kt``/``vt`` past the kv axis must
        match ``page_ids``/``slot_ids``."""
        key = id(layer)
        if self.kv_quant:
            kq, ks_new = quantize_kv_rows(kt)
            vq, vs_new = quantize_kv_rows(vt)
            ks, vs = self._scales[key]
            self._scales[key] = (
                ks.at[:, page_ids, slot_ids].set(ks_new),
                vs.at[:, page_ids, slot_ids].set(vs_new))
            kt, vt = kq, vq
        new_kp = k_pages.at[:, page_ids, slot_ids].set(kt)
        new_vp = v_pages.at[:, page_ids, slot_ids].set(vt)
        self._pools[key] = (new_kp, new_vp)
        return new_kp, new_vp

    def _layer_scales(self, layer):
        """(k_scales, v_scales) for the paged kernels' dequant-gather
        tiers, or (None, None) on native pools."""
        if not self.kv_quant:
            return None, None
        return self._scales[id(layer)]

    # -- attention ----------------------------------------------------------
    def attend(self, layer, q, k, v, training=False, dropout_p=0.0):
        from ..autograd.tape import apply
        from ..nn import functional as F

        mode, arg = self._mode
        ka = k._data if isinstance(k, Tensor) else k
        va = v._data if isinstance(v, Tensor) else v
        b, s, kv_heads, d = ka.shape
        k_pages, v_pages = self._pool(layer, kv_heads, d, ka.dtype)

        if mode == "prefill":
            assert b == 1, "prefill admits one request at a time"
            slot = arg
            start = int(self.lens[slot])
            n_valid = s if self._prefill_valid is None \
                else min(self._prefill_valid, s)
            if start + n_valid > self.max_len:
                raise ValueError(f"slot overflow: {start}+{n_valid} > "
                                 f"{self.max_len}")
            # NB: start + s (PADDED chunk) may exceed the slot's page
            # table near max_len — pad positions scatter to the scratch
            # page regardless, so the engine can keep every chunk shape
            # inside its fixed bucket set instead of compiling a
            # per-request tail shape
            if self._idx is None:    # indices shared by every layer
                self._ensure_blocks(slot, start + n_valid)
                for blk in range(start // self.page_size,
                                 -(-(start + n_valid) // self.page_size)):
                    self._make_writable(slot, blk)
                pos = np.arange(start, start + s)
                valid = pos < start + n_valid
                # pad positions scatter into the scratch page: their K/V
                # is garbage and must never land in an allocatable page
                blk_ids = np.minimum(pos // self.page_size,
                                     self.pages_per_seq - 1)
                self._idx = (
                    jnp.asarray(np.where(valid,
                                         self._tables[slot, blk_ids], 0)),
                    jnp.asarray(np.where(valid, pos % self.page_size, 0)))
            page_ids, slot_ids = self._idx
            kt = jnp.moveaxis(ka[0], 1, 0)          # [kv, s, d]
            vt = jnp.moveaxis(va[0], 1, 0)
            new_kp, new_vp = self._scatter(layer, k_pages, v_pages, kt, vt,
                                           page_ids, slot_ids)
            if start > 0 or self.kv_quant:
                # chunked / prefix-cached prefill: read the whole prefix
                # back from the pages; sdpa's bottom-right causal
                # alignment handles sq != sk. Table entries past the
                # allocated blocks are the scratch page — those keys sit
                # at pad positions and are never attended by valid
                # queries. int8 pools ALWAYS read back (dequantized) so
                # every attention consistently sees the quantized KV the
                # later decode steps will see.
                n_pages = min(-(-(start + s) // self.page_size),
                              self.pages_per_seq)
                tb = jnp.asarray(self._tables[slot, :n_pages])
                kp_g, vp_g = new_kp[:, tb], new_vp[:, tb]
                if self.kv_quant:
                    ks, vs = self._scales[id(layer)]
                    kp_g = dequantize_kv_rows(kp_g, ks[:, tb], ka.dtype)
                    vp_g = dequantize_kv_rows(vp_g, vs[:, tb], va.dtype)
                kf_flat = jnp.moveaxis(kp_g, 0, 2).reshape(
                    n_pages * self.page_size, kv_heads, d)
                vf_flat = jnp.moveaxis(vp_g, 0, 2).reshape(
                    n_pages * self.page_size, kv_heads, d)
                if n_pages * self.page_size < start + s:
                    # bucket-padded chunk ran past the table: keep sdpa's
                    # bottom-right causal alignment by zero-padding the
                    # key axis — the extra rows sit past every valid
                    # query's window, only pad queries (output discarded)
                    # ever attend them
                    pad = start + s - n_pages * self.page_size
                    kf_flat = jnp.pad(kf_flat, ((0, pad), (0, 0), (0, 0)))
                    vf_flat = jnp.pad(vf_flat, ((0, pad), (0, 0), (0, 0)))
                kf = Tensor(kf_flat[None, :start + s])
                vf = Tensor(vf_flat[None, :start + s])
            else:
                kf, vf = k, v
            return F.scaled_dot_product_attention(
                q, kf, vf, attn_mask=None, is_causal=True,
                training=training)

        if mode in ("sep_prefill", "sep_decode"):
            # long-context serving: attention over the slot's host-side
            # stripes (the ring-attention schedule run block-by-block —
            # each stripe is one ring step; see ops/pallas/ring_attention
            # .blockwise_causal_attention for the tiering) plus the
            # device-resident tail, online-softmax merged.
            assert b == 1, "sep serving admits one request at a time"
            slot = arg
            sep = self._sep[slot]
            stripe = sep["stripe"]
            li = self._sep_layer_i        # forward-order stripe index
            self._sep_layer_i += 1
            blocks = [(jnp.asarray(st[li][0])[None],
                       jnp.asarray(st[li][1])[None], j * stripe)
                      for j, st in enumerate(sep["stripes"])]
            kt = jnp.moveaxis(ka[0], 1, 0)          # [kv, s, d]
            vt = jnp.moveaxis(va[0], 1, 0)
            if mode == "sep_prefill":
                if s != stripe:
                    raise ValueError(f"sep chunk must be padded to the "
                                     f"stripe length: got {s}, expected "
                                     f"{stripe}")
                start = int(self.lens[slot])        # == sep["base"]
                n_valid = s if self._prefill_valid is None \
                    else min(self._prefill_valid, s)
                if start + n_valid > self.max_len:
                    raise ValueError(f"slot overflow: {start}+{n_valid} "
                                     f"> {self.max_len}")
                # the chunk itself: pad keys sit past every valid query's
                # causal window, so attending the raw [kv, s, d] is safe
                blocks.append((jnp.swapaxes(ka, 1, 2),
                               jnp.swapaxes(va, 1, 2), start))
                if n_valid == s:
                    # full chunk -> staged as the next ring stripe
                    # (host-side np copy) at advance()
                    self._sep_pending.append((np.asarray(kt),
                                              np.asarray(vt)))
                else:
                    # trailing partial chunk -> device tail pages, read
                    # by decode through the block table
                    if self._idx is None:
                        blk0 = start // self.page_size
                        if int(self._n_blocks[slot]) < blk0:
                            self._n_blocks[slot] = blk0
                        self._ensure_blocks(slot, start + n_valid)
                        pos = np.arange(start, start + s)
                        valid = pos < start + n_valid
                        blk_ids = np.minimum(pos // self.page_size,
                                             self.pages_per_seq - 1)
                        self._idx = (
                            jnp.asarray(np.where(
                                valid, self._tables[slot, blk_ids], 0)),
                            jnp.asarray(np.where(
                                valid, pos % self.page_size, 0)))
                    page_ids, slot_ids = self._idx
                    self._scatter(layer, k_pages, v_pages, kt, vt,
                                  page_ids, slot_ids)
                q_offset = start
            else:                          # sep_decode
                assert s == 1
                pos_tok = int(self.lens[slot])
                if self._idx is None:
                    self._idx = (
                        jnp.asarray(
                            [self._tables[slot,
                                          pos_tok // self.page_size]]),
                        jnp.asarray([pos_tok % self.page_size]))
                page_ids, slot_ids = self._idx
                new_kp, new_vp = self._scatter(layer, k_pages, v_pages,
                                               kt, vt, page_ids, slot_ids)
                base = sep["base"]
                blk0 = base // self.page_size
                n_tail = pos_tok + 1 - base
                n_tp = -(-n_tail // self.page_size)
                # pow2-bucketed tail window keeps the compiled-shape set
                # bounded (and declarable: always the pure power of two,
                # zero-padded past the table's end); entries past the
                # allocated tail are the scratch page, causally masked
                # (their positions exceed the query's)
                npp = 1 << max(n_tp - 1, 0).bit_length()
                tbl = self._tables[slot, blk0:blk0 + npp]
                if tbl.shape[0] < npp:
                    tbl = np.pad(tbl, (0, npp - tbl.shape[0]))
                tb = jnp.asarray(tbl)
                kf = new_kp[:, tb].reshape(kv_heads, -1, d)[None]
                vf = new_vp[:, tb].reshape(kv_heads, -1, d)[None]
                blocks.append((kf, vf, base))
                q_offset = pos_tok

            from ..ops.pallas.ring_attention import (
                blockwise_causal_attention)

            def fn(qa):
                out = blockwise_causal_attention(
                    jnp.swapaxes(qa, 1, 2), q_offset, blocks)
                return jnp.swapaxes(out, 1, 2)
            return apply(fn, q, op_name="sep_ring_attention")

        if mode == "ragged":
            # ONE program for the whole tick: decode tokens and prefill
            # spans of several sequences packed into a flat [1, tokens]
            # batch (token-budget scheduler). K/V scatter first, then
            # the ragged kernel reads every span's full context back
            # from the pages — causal masking inside each span comes
            # from the kernel's per-token context bound.
            assert b == 1, "ragged step packs one flat token batch"
            spans = arg
            if self._idx is None:       # indices shared by every layer
                page_ids = np.zeros(s, np.int64)     # default: scratch
                slot_ids = np.zeros(s, np.int64)
                for slot, qs, n_new in spans:
                    pos = np.arange(self.lens[slot],
                                    self.lens[slot] + n_new)
                    page_ids[qs:qs + n_new] = \
                        self._tables[slot, pos // self.page_size]
                    slot_ids[qs:qs + n_new] = pos % self.page_size
                self._idx = (
                    jnp.asarray(page_ids), jnp.asarray(slot_ids),
                    jnp.asarray(self._tables),
                    jnp.asarray([sl for sl, _, _ in spans], jnp.int32),
                    jnp.asarray([qs for _, qs, _ in spans], jnp.int32),
                    jnp.asarray([n for _, _, n in spans], jnp.int32),
                    jnp.asarray([int(self.lens[sl]) + n
                                 for sl, _, n in spans], jnp.int32))
            (page_ids, slot_ids, tables, seq_slots, q_starts, q_lens,
             ctx_lens) = self._idx
            kt = jnp.moveaxis(ka[0], 1, 0)          # [kv, s, d]
            vt = jnp.moveaxis(va[0], 1, 0)
            new_kp, new_vp = self._scatter(layer, k_pages, v_pages, kt, vt,
                                           page_ids, slot_ids)
            ksc, vsc = self._layer_scales(layer)

            from ..ops.pallas.ragged_paged_attention import (
                ragged_paged_attention)
            import jax as _jax
            interpret = _jax.default_backend() != "tpu"

            def fn(qa):
                out = ragged_paged_attention(
                    qa[0], new_kp, new_vp, tables, seq_slots, q_starts,
                    q_lens, ctx_lens, k_scales=ksc, v_scales=vsc,
                    interpret=interpret)
                return out[None]         # [1, tokens, heads, d]
            return apply(fn, q, op_name="ragged_paged_attention")

        # decode: one token for EVERY slot (fixed shape), per-slot ctx
        assert b == self.max_batch and s == 1
        if self._idx is None:        # indices shared by every layer
            lens = self.lens.copy()
            # inactive / mid-prefill slots still flow through the kernel
            # (fixed shape) but their write is steered to the scratch
            # page and their ctx=1 read covers only page 0 slot 0 —
            # finite, discarded, and never a page someone else owns
            wr_blk = np.minimum(lens // self.page_size,
                                self.pages_per_seq - 1)
            self._idx = (
                jnp.asarray(np.where(
                    arg, self._tables[np.arange(b), wr_blk], 0))[:, None],
                jnp.asarray(np.where(arg, lens % self.page_size,
                                     0))[:, None],
                jnp.asarray(self._tables),
                jnp.asarray(np.where(arg, lens + 1, 1).astype(np.int32)))
        page_ids, slot_ids, tables, ctx = self._idx
        kt = jnp.moveaxis(ka, 2, 0)                 # [kv, b, 1, d]
        vt = jnp.moveaxis(va, 2, 0)
        new_kp, new_vp = self._scatter(layer, k_pages, v_pages, kt, vt,
                                       page_ids, slot_ids)
        ksc, vsc = self._layer_scales(layer)

        from ..ops.pallas.paged_attention import paged_attention
        import jax as _jax
        interpret = _jax.default_backend() != "tpu"

        def fn(qa):
            out = paged_attention(qa[:, 0], new_kp, new_vp, tables, ctx,
                                  k_scales=ksc, v_scales=vsc,
                                  interpret=interpret)
            return out[:, None]
        return apply(fn, q, op_name="paged_attention")


def _sample_logits(logits, do_sample, top_k, top_p, temperature, key=None):
    """logits [b, V] (jnp) -> token ids [b] (jnp).

    ``key`` is an explicit jax PRNG key for the categorical draw; with
    it the sample is a pure function of (logits, key) — the serving
    engine derives one key per (request seed, row, token index) so
    sampled decode is reproducible and speculative verification of
    sampled tokens is deterministic. ``None`` falls back to the global
    stateful generator (legacy call-order-dependent behavior)."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1)
    logits = logits / max(temperature, 1e-6)
    if top_k:
        kth = jnp.sort(logits, axis=-1)[:, -int(top_k)][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p and top_p < 1.0:
        sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jnp.cumsum(
            jnp.exp(sorted_l - jnp.max(sorted_l, -1, keepdims=True)) /
            jnp.sum(jnp.exp(sorted_l - jnp.max(sorted_l, -1, keepdims=True)),
                    -1, keepdims=True), axis=-1)
        cutoff_idx = jnp.sum(probs < top_p, axis=-1)
        kth = jnp.take_along_axis(sorted_l, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    import jax
    if key is None:
        key = prandom.next_key()
    return jax.random.categorical(key, logits, axis=-1)


class GenerationMixin:
    """Adds ``generate`` to causal-LM models whose forward accepts
    ``cache=`` (``supports_cache=True``) or recomputes otherwise."""

    supports_cache = False

    @no_grad()
    def generate(self, input_ids, max_new_tokens=32, max_length=None,
                 do_sample=False, top_k=0, top_p=1.0, temperature=1.0,
                 eos_token_id=None, num_beams=1, length_penalty=1.0,
                 seed=None, **kw):
        """Returns generated ids [b, prompt + new] (prompt included,
        reference decode contract). ``num_beams > 1`` runs beam search
        (reference ``decode_strategy='beam_search'``) — greedy expansion
        over the top-``num_beams`` hypotheses with KV-cache reordering;
        requires ``do_sample=False``. ``seed`` makes sampled decode
        reproducible: step ``i`` draws with ``fold_in(key(seed), i)``
        instead of the global stateful generator."""
        input_ids = input_ids if isinstance(input_ids, Tensor) \
            else Tensor(np.asarray(input_ids, np.int64))
        if max_length is not None:
            max_new_tokens = max(max_length - input_ids.shape[1], 0)
            max_length = None
        if num_beams > 1:
            if do_sample:
                raise ValueError("beam search requires do_sample=False "
                                 "(reference beam_search is deterministic)")
            return self._beam_search(input_ids, max_new_tokens, num_beams,
                                     eos_token_id, length_penalty)
        was_training = self.training
        self.eval()
        try:
            ids = input_ids                   # prologue already normalized
            cache = kw.pop("cache", None)
            if cache is None and self.supports_cache:
                if kw.pop("use_paged_cache", False):
                    cache = PagedKVCache(
                        page_size=kw.pop("page_size", 16),
                        max_len=ids.shape[1] + max_new_tokens)
                else:
                    cache = KVCache()
            cur = ids
            all_ids = ids._data
            finished = jnp.zeros((ids.shape[0],), bool)
            base_key = None
            if seed is not None:
                import jax
                base_key = jax.random.key(int(seed))
            for step in range(max_new_tokens):
                logits = self.forward(cur, cache=cache) \
                    if cache is not None else self.forward(
                        Tensor(all_ids))
                lg = logits._data[:, -1].astype(jnp.float32)
                step_key = None
                if base_key is not None:
                    import jax
                    step_key = jax.random.fold_in(base_key, step)
                nxt = _sample_logits(lg, do_sample, top_k, top_p,
                                     temperature,
                                     key=step_key).astype(all_ids.dtype)
                if eos_token_id is not None:
                    nxt = jnp.where(finished,
                                    jnp.asarray(eos_token_id, nxt.dtype),
                                    nxt)
                    finished = jnp.logical_or(finished, nxt == eos_token_id)
                all_ids = jnp.concatenate([all_ids, nxt[:, None]], axis=1)
                cur = Tensor(nxt[:, None])
                if eos_token_id is not None and bool(finished.all()):
                    break
            return Tensor(all_ids)
        finally:
            if was_training:
                self.train()

    @no_grad()
    def _beam_search(self, input_ids, max_new_tokens, num_beams,
                     eos_token_id, length_penalty):
        """Batched beam search over the dense KV cache (paged pools are
        per-sequence-owned, so a beam hop would alias pages — the serving
        engines cover paged decode; beams use the concat cache)."""
        import jax

        was_training = self.training
        self.eval()
        try:
            ids = input_ids                   # generate() already normalized
            b, prompt = ids.shape
            n = int(num_beams)
            # expand rows to beams: [b*n, s]
            all_ids = jnp.repeat(ids._data, n, axis=0)
            cache = KVCache() if self.supports_cache else None
            # beam 0 carries the prompt; others start dead so step 1
            # doesn't pick n copies of the same continuation
            scores = jnp.tile(jnp.asarray([0.0] + [-jnp.inf] * (n - 1),
                                          jnp.float32), (b,))      # [b*n]
            finished = jnp.zeros((b * n,), bool)
            lengths = jnp.zeros((b * n,), jnp.float32)   # generated tokens
            cur = Tensor(all_ids)
            for step in range(max_new_tokens):
                logits = self.forward(cur, cache=cache) \
                    if cache is not None else self.forward(Tensor(all_ids))
                lp = jax.nn.log_softmax(
                    logits._data[:, -1].astype(jnp.float32), axis=-1)
                vocab = lp.shape[-1]
                if eos_token_id is not None:
                    # a finished beam only continues with EOS at no cost
                    frozen = jnp.full((vocab,), -jnp.inf
                                      ).at[int(eos_token_id)].set(0.0)
                    lp = jnp.where(finished[:, None], frozen[None, :], lp)
                total = scores[:, None] + lp                       # [b*n, V]
                flat = total.reshape(b, n * vocab)
                top_s, top_i = jax.lax.top_k(flat, n)              # [b, n]
                parent = (top_i // vocab + jnp.arange(b)[:, None] * n
                          ).reshape(-1)                            # [b*n]
                token = (top_i % vocab).reshape(-1)
                scores = top_s.reshape(-1)
                all_ids = jnp.concatenate(
                    [all_ids[parent], token[:, None].astype(all_ids.dtype)],
                    axis=1)
                # per-hypothesis true length: frozen at the step EOS fired
                lengths = jnp.where(finished[parent], lengths[parent],
                                    float(step + 1))
                finished = finished[parent]
                if eos_token_id is not None:
                    finished = jnp.logical_or(finished,
                                              token == eos_token_id)
                if cache is not None:
                    cache.reorder(parent)
                cur = Tensor(token[:, None].astype(all_ids.dtype))
                if eos_token_id is not None and bool(finished.all()):
                    break
            # each row's best hypothesis under the PER-HYPOTHESIS length
            # penalty (reference normalizes by the length at EOS)
            norm = scores / jnp.maximum(lengths, 1.0) ** float(
                length_penalty)
            best = jnp.argmax(norm.reshape(b, n), axis=-1) \
                + jnp.arange(b) * n
            return Tensor(all_ids[best])
        finally:
            if was_training:
                self.train()
