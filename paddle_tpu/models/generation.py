"""Autoregressive generation (reference behavior: PaddleNLP
``GenerationMixin.generate`` — greedy/sampling decode with KV cache; core
Paddle contributes the fused attention + cache kernels, SURVEY.md §2.4 note
on PaddleNLP being a separate repo → in-repo equivalent).

TPU notes: the eager cache is concat-grown (simple, correct); the compiled
serving path would preallocate [b, max_len, h, d] rings and use the Pallas
decode kernel — follow-up on the inference milestone.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..framework.core import Tensor
from ..autograd.tape import no_grad
from ..framework import random as prandom

__all__ = ["KVCache", "GenerationMixin"]


class KVCache:
    """Per-attention-layer concat cache. ``update`` returns the full K/V so
    far (including the new tokens); ``pos`` is the filled length, advanced
    once per model forward."""

    def __init__(self):
        self.pos = 0
        self._store = {}

    def update(self, layer, k_new, v_new):
        from ..ops import manipulation as manip
        key = id(layer)
        if key in self._store:
            k_old, v_old = self._store[key]
            k = manip.concat([k_old, k_new], axis=1)
            v = manip.concat([v_old, v_new], axis=1)
        else:
            k, v = k_new, v_new
        self._store[key] = (k.detach(), v.detach())
        return k, v

    def advance(self, s):
        self.pos += int(s)

    def reset(self):
        self.pos = 0
        self._store.clear()


def _sample_logits(logits, do_sample, top_k, top_p, temperature):
    """logits [b, V] (jnp) -> token ids [b] (jnp)."""
    if not do_sample:
        return jnp.argmax(logits, axis=-1)
    logits = logits / max(temperature, 1e-6)
    if top_k:
        kth = jnp.sort(logits, axis=-1)[:, -int(top_k)][:, None]
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    if top_p and top_p < 1.0:
        sorted_l = jnp.sort(logits, axis=-1)[:, ::-1]
        probs = jnp.cumsum(
            jnp.exp(sorted_l - jnp.max(sorted_l, -1, keepdims=True)) /
            jnp.sum(jnp.exp(sorted_l - jnp.max(sorted_l, -1, keepdims=True)),
                    -1, keepdims=True), axis=-1)
        cutoff_idx = jnp.sum(probs < top_p, axis=-1)
        kth = jnp.take_along_axis(sorted_l, cutoff_idx[:, None], axis=-1)
        logits = jnp.where(logits < kth, -jnp.inf, logits)
    import jax
    key = prandom.next_key()
    return jax.random.categorical(key, logits, axis=-1)


class GenerationMixin:
    """Adds ``generate`` to causal-LM models whose forward accepts
    ``cache=`` (``supports_cache=True``) or recomputes otherwise."""

    supports_cache = False

    @no_grad()
    def generate(self, input_ids, max_new_tokens=32, max_length=None,
                 do_sample=False, top_k=0, top_p=1.0, temperature=1.0,
                 eos_token_id=None, **kw):
        """Returns generated ids [b, prompt + new] (prompt included,
        reference decode contract)."""
        was_training = self.training
        self.eval()
        try:
            ids = input_ids if isinstance(input_ids, Tensor) \
                else Tensor(np.asarray(input_ids, np.int64))
            if max_length is not None:
                max_new_tokens = max(max_length - ids.shape[1], 0)
            cache = KVCache() if self.supports_cache else None
            cur = ids
            all_ids = ids._data
            finished = jnp.zeros((ids.shape[0],), bool)
            for step in range(max_new_tokens):
                logits = self.forward(cur, cache=cache) \
                    if cache is not None else self.forward(
                        Tensor(all_ids))
                lg = logits._data[:, -1].astype(jnp.float32)
                nxt = _sample_logits(lg, do_sample, top_k, top_p,
                                     temperature).astype(all_ids.dtype)
                if eos_token_id is not None:
                    nxt = jnp.where(finished,
                                    jnp.asarray(eos_token_id, nxt.dtype),
                                    nxt)
                    finished = jnp.logical_or(finished, nxt == eos_token_id)
                all_ids = jnp.concatenate([all_ids, nxt[:, None]], axis=1)
                cur = Tensor(nxt[:, None])
                if eos_token_id is not None and bool(finished.all()):
                    break
            return Tensor(all_ids)
        finally:
            if was_training:
                self.train()
