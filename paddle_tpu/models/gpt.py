"""GPT model family (reference behavior: PaddleNLP GPT ``modeling.py`` /
``modeling_pp.py`` — learned positions, pre-LN blocks, GeLU MLP, tied
embeddings; the Fleet hybrid benchmark config is GPT-3-1.3B dp+mp+pp with
sharding stage-2, BASELINE.json configs[3]).

Same TPU-first shape as ``llama.py``: plain layers + ``sharding_rules()``.
``GPTForCausalLM.to_pipeline_layer()`` re-expresses the model as a
``PipelineLayer`` LayerDesc list for the PP engine (reference:
``GPTForCausalLMPipe`` built on ``pp_layers.PipelineLayer``).
"""
from __future__ import annotations

import jax.numpy as jnp

from ..nn.layer import Layer, LayerList
from ..nn.layers.common import Linear, Embedding, Dropout
from ..nn.layers.norm import LayerNorm
from ..nn import functional as F
from ..nn.initializer import Normal
from ..ops import math as pmath
from .llama import LlamaPretrainingCriterion
from .generation import GenerationMixin


class GPTConfig:
    def __init__(self, vocab_size=50304, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=None, max_position_embeddings=1024,
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 layer_norm_epsilon=1e-5, initializer_range=0.02,
                 use_recompute=False, **kwargs):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size or 4 * hidden_size
        self.max_position_embeddings = max_position_embeddings
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.layer_norm_epsilon = layer_norm_epsilon
        self.initializer_range = initializer_range
        self.use_recompute = use_recompute
        for k, v in kwargs.items():
            setattr(self, k, v)


def gpt3_1p3b(**kw):
    """GPT-3 1.3B (BASELINE.json configs[3] hybrid benchmark)."""
    return GPTConfig(vocab_size=50304, hidden_size=2048,
                     num_hidden_layers=24, num_attention_heads=16,
                     max_position_embeddings=2048, **kw)


def gpt_tiny(**kw):
    kw.setdefault("vocab_size", 128)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_hidden_layers", 2)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("max_position_embeddings", 128)
    return GPTConfig(**kw)


class GPTAttention(Layer):
    def __init__(self, config):
        super().__init__()
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.head_dim = h // self.num_heads
        init = Normal(0.0, config.initializer_range)
        self.qkv_proj = Linear(h, 3 * h, weight_attr=init)
        self.out_proj = Linear(h, h, weight_attr=init)
        self.dropout_p = config.attention_probs_dropout_prob

    def forward(self, hidden, cache=None):
        b, s, h = hidden.shape
        qkv = self.qkv_proj(hidden).reshape(
            [b, s, 3, self.num_heads, self.head_dim])
        q, k, v = (qkv[:, :, i] for i in range(3))
        if cache is not None:
            out = cache.attend(self, q, k, v, training=self.training,
                               dropout_p=self.dropout_p)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, is_causal=True, dropout_p=self.dropout_p,
                training=self.training)
        return self.out_proj(out.reshape([b, s, h]))


class GPTDecoderLayer(Layer):
    def __init__(self, config):
        super().__init__()
        h = config.hidden_size
        init = Normal(0.0, config.initializer_range)
        self.norm1 = LayerNorm(h, config.layer_norm_epsilon)
        self.self_attn = GPTAttention(config)
        self.norm2 = LayerNorm(h, config.layer_norm_epsilon)
        self.linear1 = Linear(h, config.intermediate_size, weight_attr=init)
        self.linear2 = Linear(config.intermediate_size, h, weight_attr=init)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, hidden, cache=None):
        hidden = hidden + self.dropout(
            self.self_attn(self.norm1(hidden), cache))
        ff = self.linear2(F.gelu(self.linear1(self.norm2(hidden)),
                                 approximate=True))
        return hidden + self.dropout(ff)


class GPTEmbeddings(Layer):
    def __init__(self, config):
        super().__init__()
        init = Normal(0.0, config.initializer_range)
        self.word_embeddings = Embedding(config.vocab_size,
                                         config.hidden_size, weight_attr=init)
        self.position_embeddings = Embedding(config.max_position_embeddings,
                                             config.hidden_size,
                                             weight_attr=init)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, position_ids=None):
        from ..ops import creation as C
        if position_ids is None:
            position_ids = C.arange(0, input_ids.shape[1], dtype="int64")
        return self.dropout(self.word_embeddings(input_ids) +
                            self.position_embeddings(position_ids))


class GPTModel(Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        self.embeddings = GPTEmbeddings(config)
        self.decoder = LayerList(
            [GPTDecoderLayer(config) for _ in range(config.num_hidden_layers)])
        self.final_norm = LayerNorm(config.hidden_size,
                                    config.layer_norm_epsilon)

    def forward(self, input_ids, position_ids=None, cache=None):
        if cache is not None and position_ids is None:
            from ..ops import creation as C
            position_ids = C.arange(cache.pos,
                                    cache.pos + input_ids.shape[1],
                                    dtype="int64")
        hidden = self.embeddings(input_ids, position_ids)
        for layer in self.decoder:
            hidden = layer(hidden, cache)
        hidden = self.final_norm(hidden)
        if cache is not None:
            cache.advance(input_ids.shape[1])
        return hidden


class GPTForCausalLM(GenerationMixin, Layer):
    supports_cache = True

    """Tied lm_head (logits = hidden @ word_embeddings.T) — the reference's
    ``SharedLayerDesc`` tied-embedding case in pipeline mode."""

    def __init__(self, config):
        super().__init__()
        self.config = config
        self.gpt = GPTModel(config)
        self.criterion = LlamaPretrainingCriterion()

    def forward(self, input_ids, labels=None, position_ids=None,
                cache=None):
        hidden = self.gpt(input_ids, position_ids, cache)
        logits = pmath.matmul(
            hidden, self.gpt.embeddings.word_embeddings.weight,
            transpose_y=True)
        if labels is None:
            return logits
        return self.criterion(logits, labels), logits

    @staticmethod
    def sharding_rules():
        mp = "mp"
        return [
            (r"word_embeddings\.weight$", (mp, None)),
            (r"qkv_proj\.weight$", (None, mp)),
            (r"qkv_proj\.bias$", (mp,)),
            (r"out_proj\.weight$", (mp, None)),
            (r"linear1\.weight$", (None, mp)),
            (r"linear1\.bias$", (mp,)),
            (r"linear2\.weight$", (mp, None)),
            (r".*", ()),
        ]


# ---------------------------------------------------------------------------
# pipeline-parallel model description (reference: PaddleNLP
# ``GPTForCausalLMPipe`` — modeling_pp.py LayerDesc list with tied
# embeddings via SharedLayerDesc; the Fleet hybrid benchmark model,
# BASELINE.json configs[3]). Unlike Llama, GPT blocks are stochastic
# (attention + residual dropout) — the PP engine threads per-
# (microbatch, chunk) PRNG keys through the schedule for them.
# ---------------------------------------------------------------------------

class GPTWordEmbeddingPipe(Layer):
    """Tied pair's minimal stage: ONLY the word embedding lives here, so
    the head-side SharedLayerDesc instance carries no dead
    position/dropout parameters (same shape as LlamaEmbeddingPipe)."""

    def __init__(self, config):
        super().__init__()
        self.word_embeddings = Embedding(
            config.vocab_size, config.hidden_size,
            weight_attr=Normal(0.0, config.initializer_range))

    def forward(self, input_ids):
        return self.word_embeddings(input_ids)


class GPTPosDropPipe(Layer):
    """Second embedding stage: learned positions + embedding dropout."""

    def __init__(self, config):
        super().__init__()
        self.position_embeddings = Embedding(
            config.max_position_embeddings, config.hidden_size,
            weight_attr=Normal(0.0, config.initializer_range))
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, hidden):
        from ..ops import creation as C
        pos = C.arange(0, hidden.shape[1], dtype="int64")
        return self.dropout(hidden + self.position_embeddings(pos))


def _gpt_tied_head_forward(layer, hidden):
    """logits = hidden @ E^T (same Parameter as the embedding stage)."""
    return pmath.matmul(hidden, layer.word_embeddings.weight,
                        transpose_y=True)


def build_gpt_pipe(config, **pp_kwargs):
    """``GPTForCausalLMPipe``: [word-embed (tied), pos+dropout, L pre-LN
    blocks, final LayerNorm, tied head] as a PipelineLayer description
    for the jitted SPMD engine."""
    from ..distributed.fleet.meta_parallel.pp_layers import (
        PipelineLayer, LayerDesc, SharedLayerDesc)

    descs = [SharedLayerDesc("gpt_embed", GPTWordEmbeddingPipe, config,
                             shared_weight_attr="word_embeddings"),
             LayerDesc(GPTPosDropPipe, config)]
    descs += [LayerDesc(GPTDecoderLayer, config)
              for _ in range(config.num_hidden_layers)]
    descs.append(LayerDesc(LayerNorm, config.hidden_size,
                           config.layer_norm_epsilon))
    descs.append(SharedLayerDesc("gpt_embed", GPTWordEmbeddingPipe, config,
                                 forward_func=_gpt_tied_head_forward,
                                 shared_weight_attr="word_embeddings"))
    pp_kwargs.setdefault("loss_fn", LlamaPretrainingCriterion())
    pipe = PipelineLayer(descs, **pp_kwargs)
    pipe.config = config
    return pipe


GPTForCausalLMPipe = build_gpt_pipe
