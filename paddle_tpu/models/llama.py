"""Llama model family (reference behavior: PaddleNLP ``modeling.py`` for
Llama — RMSNorm pre-norm, RoPE, GQA, SwiGLU MLP, untied lm_head; the north
star config is Llama-3-8B pretrain, BASELINE.json configs[4]).

TPU-first design: the model is plain eager layers; parallelism is NOT baked
into the module graph (no Column/RowParallelLinear forks). Instead
``sharding_rules()`` maps parameter names to PartitionSpecs over the hybrid
mesh axes, and the train-step engine / ``dryrun_multichip`` place the params
— XLA SPMD then derives exactly the Megatron collectives the reference
implements by hand in ``fleet/layers/mpu/mp_layers.py`` (SURVEY.md §2.3).
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from ..nn.layer import Layer, LayerList
from ..nn.layers.common import Linear, Embedding
from ..nn.layers.norm import RMSNorm
from ..nn import functional as F
from ..nn.initializer import Normal
from ..ops import fused as fused_ops
from ..ops import math as pmath
from ..autograd.tape import apply
from .generation import GenerationMixin


def shard_activation(x):
    """Pin a [B, T, H] activation to the canonical data layout (batch over
    dp+sharding, seq over sep) when tracing under a multi-device mesh.
    Without this, GSPMD can propagate a weight's ZeRO 'sharding'-axis split
    into the residual stream and fall back to replicate-repartition
    ("Involuntary full rematerialization") — the maxtext-style activation
    annotation recipe. No-op in eager / single-device."""
    import jax
    from ..distributed import mesh as mesh_mod

    spec = mesh_mod.batch_spec(3)
    if spec is None:
        return x

    sh = mesh_mod.sharding(*spec)

    def fn(a):
        if isinstance(a, jax.core.Tracer):
            return jax.lax.with_sharding_constraint(a, sh)
        return a

    return apply(fn, x, op_name="shard_activation")


class LlamaConfig:
    def __init__(self, vocab_size=32000, hidden_size=4096,
                 intermediate_size=11008, num_hidden_layers=32,
                 num_attention_heads=32, num_key_value_heads=None,
                 max_position_embeddings=4096, rms_norm_eps=1e-5,
                 rope_theta=10000.0, initializer_range=0.02,
                 tie_word_embeddings=False, use_recompute=False,
                 recompute_granularity="full", sequence_parallel=False,
                 context_parallel=False, cp_mode="ring", dtype="float32",
                 **kwargs):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.intermediate_size = intermediate_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.num_key_value_heads = num_key_value_heads or num_attention_heads
        self.max_position_embeddings = max_position_embeddings
        self.rms_norm_eps = rms_norm_eps
        self.rope_theta = rope_theta
        self.initializer_range = initializer_range
        self.tie_word_embeddings = tie_word_embeddings
        self.use_recompute = use_recompute
        self.recompute_granularity = recompute_granularity
        self.sequence_parallel = sequence_parallel
        self.context_parallel = context_parallel
        self.cp_mode = cp_mode            # "ring" | "ulysses" (SURVEY §5.7)
        self.dtype = dtype
        for k, v in kwargs.items():
            setattr(self, k, v)

    @property
    def head_dim(self):
        return self.hidden_size // self.num_attention_heads


def llama3_8b(**kw):
    """Llama-3-8B (north star, BASELINE.json configs[4])."""
    return LlamaConfig(vocab_size=128256, hidden_size=4096,
                       intermediate_size=14336, num_hidden_layers=32,
                       num_attention_heads=32, num_key_value_heads=8,
                       max_position_embeddings=8192, rms_norm_eps=1e-5,
                       rope_theta=500000.0, **kw)


def llama_tiny(**kw):
    """CI-sized config exercising GQA + RoPE + SwiGLU."""
    kw.setdefault("vocab_size", 128)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("intermediate_size", 176)
    kw.setdefault("num_hidden_layers", 2)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("num_key_value_heads", 2)
    kw.setdefault("max_position_embeddings", 128)
    return LlamaConfig(**kw)


class LlamaMLP(Layer):
    def __init__(self, config):
        super().__init__()
        h, m = config.hidden_size, config.intermediate_size
        init = Normal(0.0, config.initializer_range)
        self.gate_proj = Linear(h, m, weight_attr=init, bias_attr=False)
        self.up_proj = Linear(h, m, weight_attr=init, bias_attr=False)
        self.down_proj = Linear(m, h, weight_attr=init, bias_attr=False)

    def forward(self, x):
        return self.down_proj(
            fused_ops.fused_swiglu(self.gate_proj(x), self.up_proj(x)))


class LlamaAttention(Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        h = config.hidden_size
        self.num_heads = config.num_attention_heads
        self.num_kv_heads = config.num_key_value_heads
        self.head_dim = config.head_dim
        init = Normal(0.0, config.initializer_range)
        self.q_proj = Linear(h, self.num_heads * self.head_dim,
                             weight_attr=init, bias_attr=False)
        self.k_proj = Linear(h, self.num_kv_heads * self.head_dim,
                             weight_attr=init, bias_attr=False)
        self.v_proj = Linear(h, self.num_kv_heads * self.head_dim,
                             weight_attr=init, bias_attr=False)
        self.o_proj = Linear(self.num_heads * self.head_dim, h,
                             weight_attr=init, bias_attr=False)
        self._cos, self._sin = fused_ops.rope_freqs(
            self.head_dim, config.max_position_embeddings, config.rope_theta)

    def _use_ring_attention(self):
        if not getattr(self.config, "context_parallel", False):
            return False
        from ..distributed import mesh as mesh_mod
        return mesh_mod.has_mesh() and mesh_mod.axis_size("sep") > 1

    def forward(self, hidden, attn_mask=None, position_ids=None, cache=None):
        from ..ops import manipulation as manip
        b, s, _ = hidden.shape
        q = self.q_proj(hidden).reshape([b, s, self.num_heads, self.head_dim])
        k = self.k_proj(hidden).reshape([b, s, self.num_kv_heads, self.head_dim])
        v = self.v_proj(hidden).reshape([b, s, self.num_kv_heads, self.head_dim])
        if cache is not None and position_ids is None:
            # raw jnp: consumed as a closure constant by the rope op
            position_ids = jnp.arange(cache.pos, cache.pos + s,
                                      dtype=jnp.int32)
        q, k, _ = fused_ops.fused_rotary_position_embedding(
            q, k, sin=self._sin, cos=self._cos, position_ids=position_ids)
        if cache is not None:
            # decode: the cache owns its layout (concat or paged) and the
            # cache-aware attention over the filled prefix
            out = cache.attend(self, q, k, v, training=self.training)
        elif self._use_ring_attention():
            # context parallelism: seq dim sharded over 'sep'. cp_mode
            # picks the mechanism (SURVEY.md §5.7): "ring" rotates KV
            # blocks with ppermute (3); "ulysses" swaps seq<->head with
            # one all-to-all each way (2)
            if getattr(self.config, "cp_mode", "ring") == "ulysses":
                from ..distributed.fleet.utils import ulysses_attention
                out = ulysses_attention(q, k, v, causal=True)
            else:
                from ..distributed.fleet.utils import ring_attention
                out = ring_attention(q, k, v, causal=True)
        else:
            out = F.scaled_dot_product_attention(
                q, k, v, attn_mask=attn_mask, is_causal=attn_mask is None,
                training=self.training)
        return self.o_proj(out.reshape([b, s, self.num_heads * self.head_dim]))


class LlamaDecoderLayer(Layer):
    def __init__(self, config):
        super().__init__()
        self.self_attn = LlamaAttention(config)
        self.mlp = LlamaMLP(config)
        self.input_layernorm = RMSNorm(config.hidden_size, config.rms_norm_eps)
        self.post_attention_layernorm = RMSNorm(config.hidden_size,
                                                config.rms_norm_eps)

    def forward(self, hidden, attn_mask=None, position_ids=None, cache=None):
        hidden = hidden + self.self_attn(self.input_layernorm(hidden),
                                         attn_mask, position_ids, cache)
        return hidden + self.mlp(self.post_attention_layernorm(hidden))


class LlamaModel(Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        self.embed_tokens = Embedding(
            config.vocab_size, config.hidden_size,
            weight_attr=Normal(0.0, config.initializer_range))
        self.layers = LayerList(
            [LlamaDecoderLayer(config) for _ in range(config.num_hidden_layers)])
        self.norm = RMSNorm(config.hidden_size, config.rms_norm_eps)

    def forward(self, input_ids, attn_mask=None, position_ids=None,
                cache=None):
        hidden = self.embed_tokens(input_ids)
        hidden = shard_activation(hidden)
        recompute = (self.config.use_recompute and self.training
                     and cache is None)
        if recompute:
            # per-layer remat (reference recompute_granularity='full'):
            # under jit this wraps each decoder layer in jax.checkpoint
            from ..distributed.fleet.utils import recompute as remat
        for layer in self.layers:
            if recompute:
                hidden = remat(layer, hidden, attn_mask, position_ids)
            else:
                hidden = layer(hidden, attn_mask, position_ids, cache)
            hidden = shard_activation(hidden)
        hidden = self.norm(hidden)
        if cache is not None:
            cache.advance(input_ids.shape[1])
        return hidden


class LlamaPretrainingCriterion(Layer):
    """Causal-LM loss; mean over non-ignored tokens (ignore_index=-100).
    Computed in fp32 regardless of model dtype (reference: vocab-parallel
    softmax-CE kernel accumulates in fp32)."""

    def __init__(self, ignore_index=-100):
        super().__init__()
        self.ignore_index = ignore_index

    def forward(self, logits, labels):
        ign = self.ignore_index

        def fn(lg, lb):
            import jax
            lg = lg.astype(jnp.float32)
            logp = lg - jax.nn.logsumexp(lg, axis=-1, keepdims=True)
            valid = lb != ign
            lb_safe = jnp.where(valid, lb, 0)
            tok = jnp.take_along_axis(logp, lb_safe[..., None], axis=-1)[..., 0]
            tok = jnp.where(valid, tok, 0.0)
            return -tok.sum() / jnp.maximum(valid.sum(), 1)

        return apply(fn, logits, labels, op_name="causal_lm_loss")


class LlamaForCausalLM(GenerationMixin, Layer):
    supports_cache = True

    @classmethod
    def from_pretrained(cls, model_dir, dtype="float32", **overrides):
        """Build from a LOCAL HF-format Llama checkpoint directory
        (config.json + safetensors/bin; PaddleNLP-``from_pretrained``
        surface, zero-egress — see models/pretrained.py)."""
        from .pretrained import llama_config_from_hf, load_llama_from_hf
        cfg = llama_config_from_hf(model_dir, dtype=dtype, **overrides)
        model = cls(cfg)
        return load_llama_from_hf(model, model_dir, dtype=dtype)

    def __init__(self, config):
        super().__init__()
        self.config = config
        self.llama = LlamaModel(config)
        if config.tie_word_embeddings:
            self.lm_head = None
        else:
            self.lm_head = Linear(config.hidden_size, config.vocab_size,
                                  weight_attr=Normal(0.0, config.initializer_range),
                                  bias_attr=False)
        self.criterion = LlamaPretrainingCriterion()

    def forward(self, input_ids, labels=None, attn_mask=None,
                position_ids=None, cache=None):
        hidden = self.llama(input_ids, attn_mask, position_ids, cache)
        if self.lm_head is not None:
            logits = self.lm_head(hidden)
        else:
            logits = pmath.matmul(hidden, self.llama.embed_tokens.weight,
                                  transpose_y=True)
        if labels is None:
            return logits
        return self.criterion(logits, labels), logits

    @staticmethod
    def sharding_rules():
        """(param-name regex, PartitionSpec tuple) over hybrid mesh axes.
        Megatron TP: column-parallel q/k/v/gate/up + lm_head, row-parallel
        o/down, vocab-parallel embedding. The 'sharding' (ZeRO/FSDP) axis is
        composed on top by the engine (stage>=3 shards dim 0 residually)."""
        mp = "mp"
        return [
            (r"embed_tokens\.weight$", (mp, None)),
            (r"(q_proj|k_proj|v_proj|gate_proj|up_proj)\.weight$", (None, mp)),
            (r"(o_proj|down_proj)\.weight$", (mp, None)),
            (r"lm_head\.weight$", (None, mp)),
            (r".*", ()),   # norms etc. replicated
        ]


# ---------------------------------------------------------------------------
# pipeline-parallel model description (reference: PaddleNLP
# ``LlamaForCausalLMPipe`` built on ``PipelineLayer`` with EmbeddingPipe /
# decoder LayerDescs / RMSNormPipe / LMHeadPipe, tied embeddings via
# ``SharedLayerDesc`` — fleet pp_layers.py)
# ---------------------------------------------------------------------------

class LlamaEmbeddingPipe(Layer):
    """Embedding stage: ids -> hidden. Doubles as the tied lm head via
    ``SharedLayerDesc(forward_func=_tied_head_forward)``."""

    def __init__(self, config):
        super().__init__()
        self.word_embeddings = Embedding(
            config.vocab_size, config.hidden_size,
            weight_attr=Normal(0.0, config.initializer_range))

    def forward(self, input_ids):
        return shard_activation(self.word_embeddings(input_ids))


def _tied_head_forward(layer, hidden):
    """Head forward for the tied-embedding SharedLayerDesc instance:
    logits = hidden @ E^T (same Parameter object as the embedding stage —
    no shared-weight allreduce needed; grads sum through jax.grad)."""
    return pmath.matmul(hidden, layer.word_embeddings.weight,
                        transpose_y=True)


class LlamaLMHeadPipe(Layer):
    def __init__(self, config):
        super().__init__()
        self.lm_head = Linear(config.hidden_size, config.vocab_size,
                              weight_attr=Normal(0.0, config.initializer_range),
                              bias_attr=False)

    def forward(self, hidden):
        return self.lm_head(hidden)


def build_llama_pipe(config, **pp_kwargs):
    """``LlamaForCausalLMPipe``: the PipelineLayer description of Llama.
    Layer list = [embedding, L decoder blocks, final RMSNorm, head]; the
    jitted SPMD engine (``distributed/engine.py::PipelinedModule``) maps
    the decoder run onto the pp mesh axis and runs embedding/norm/head as
    whole-mesh sharded compute."""
    from ..distributed.fleet.meta_parallel.pp_layers import (
        PipelineLayer, LayerDesc, SharedLayerDesc)

    descs = []
    if config.tie_word_embeddings:
        descs.append(SharedLayerDesc(
            "llama_embed", LlamaEmbeddingPipe, config,
            shared_weight_attr="word_embeddings"))
    else:
        descs.append(LayerDesc(LlamaEmbeddingPipe, config))
    descs += [LayerDesc(LlamaDecoderLayer, config)
              for _ in range(config.num_hidden_layers)]
    descs.append(LayerDesc(RMSNorm, config.hidden_size, config.rms_norm_eps))
    if config.tie_word_embeddings:
        descs.append(SharedLayerDesc(
            "llama_embed", LlamaEmbeddingPipe, config,
            forward_func=_tied_head_forward,
            shared_weight_attr="word_embeddings"))
    else:
        descs.append(LayerDesc(LlamaLMHeadPipe, config))
    pp_kwargs.setdefault("loss_fn", LlamaPretrainingCriterion())
    pipe = PipelineLayer(descs, **pp_kwargs)
    pipe.config = config
    return pipe


LlamaForCausalLMPipe = build_llama_pipe
