"""HF-format pretrained checkpoint loading for the LM zoo (reference:
PaddleNLP's ``from_pretrained`` tier over the model zoo — SURVEY.md §2.4
notes the zoos are separate repos, so the in-repo equivalent loads the
interoperable Hugging Face layout: ``config.json`` +
``model.safetensors`` / ``pytorch_model.bin`` from a LOCAL directory
(zero-egress build: no hub download; point at a path)).

Weight convention: HF/torch linears are ``[out, in]``; this framework
follows the reference's ``[in, out]`` — 2-D projection weights are
transposed on load. Embedding tables ``[vocab, hidden]`` pass through.
"""
from __future__ import annotations

import json
import os

import numpy as np


def _read_hf_weights(model_dir):
    """Load all tensors from safetensors shards or pytorch_model.bin."""
    tensors = {}
    st_files = sorted(f for f in os.listdir(model_dir)
                      if f.endswith(".safetensors"))
    if st_files:
        from safetensors import safe_open
        for fname in st_files:
            with safe_open(os.path.join(model_dir, fname), framework="np") \
                    as f:
                for k in f.keys():
                    tensors[k] = np.asarray(f.get_tensor(k))
        return tensors
    bin_files = sorted(f for f in os.listdir(model_dir)
                       if f.startswith("pytorch_model") and
                       f.endswith(".bin"))
    if bin_files:
        import torch
        for fname in bin_files:
            sd = torch.load(os.path.join(model_dir, fname),
                            map_location="cpu", weights_only=True)
            for k, v in sd.items():
                tensors[k] = v.to(torch.float32).numpy()
        return tensors
    raise IOError(f"no model.safetensors / pytorch_model*.bin under "
                  f"{model_dir}")


def load_hf_config(model_dir):
    with open(os.path.join(model_dir, "config.json")) as f:
        return json.load(f)


def _strip_prefix(name, prefixes):
    for p in prefixes:
        if name.startswith(p):
            return name[len(p):]
    return name


def _check_fully_mapped(own, mapped, arch, optional=()):
    """Every model parameter must come from the checkpoint — an unmapped
    key would silently stay randomly initialized after set_state_dict.
    ``optional`` prefixes (e.g. BERT's pooler, absent from MLM-only
    exports) only warn, matching HF's own load behavior."""
    missing = [k for k in own if k not in mapped]
    soft = [k for k in missing if any(k.startswith(p) for p in optional)]
    hard = [k for k in missing if k not in soft]
    if hard:
        raise ValueError(
            f"{arch} checkpoint left parameters unmapped (random init "
            f"would be silent garbage): {hard[:8]}")
    if soft:
        import warnings
        warnings.warn(f"{arch} checkpoint omits optional parameters "
                      f"(randomly initialized): {soft[:8]}", RuntimeWarning,
                      stacklevel=3)


def load_llama_from_hf(model, model_dir, dtype="float32"):
    """Fill a ``LlamaForCausalLM`` from an HF Llama checkpoint dir."""
    raw = _read_hf_weights(model_dir)
    own = model.state_dict()
    mapped = {}
    for name, arr in raw.items():
        n = _strip_prefix(name, ("model.",))
        if n.startswith("layers.") or n in ("embed_tokens.weight",
                                            "norm.weight"):
            tgt = "llama." + n
        elif name == "lm_head.weight":
            tgt = "lm_head.weight"
        else:
            continue          # rotary inv_freq buffers etc.
        if tgt not in own:
            continue
        # HF torch Linears are [out, in]; ours are [in, out] — transpose
        # every 2-D projection (shape comparison can't catch square ones).
        # The embedding table [vocab, hidden] is the one 2-D passthrough.
        if arr.ndim == 2 and tgt != "llama.embed_tokens.weight":
            arr = arr.T
        want = tuple(own[tgt].shape)
        if tuple(arr.shape) != want:
            raise ValueError(f"shape mismatch for {tgt}: checkpoint "
                             f"{arr.shape} vs model {want}")
        mapped[tgt] = arr.astype(dtype)
    if getattr(model.config, "tie_word_embeddings", False) \
            and "lm_head.weight" not in mapped:
        mapped["lm_head.weight"] = mapped["llama.embed_tokens.weight"] \
            .T.astype(dtype)
    _check_fully_mapped(own, mapped, "Llama")
    model.set_state_dict(mapped)
    return model


def llama_config_from_hf(model_dir, **overrides):
    from .llama import LlamaConfig
    cfg = load_hf_config(model_dir)
    fields = dict(
        vocab_size=cfg.get("vocab_size", 32000),
        hidden_size=cfg.get("hidden_size", 4096),
        intermediate_size=cfg.get("intermediate_size", 11008),
        num_hidden_layers=cfg.get("num_hidden_layers", 32),
        num_attention_heads=cfg.get("num_attention_heads", 32),
        num_key_value_heads=cfg.get("num_key_value_heads"),
        max_position_embeddings=cfg.get("max_position_embeddings", 4096),
        rms_norm_eps=cfg.get("rms_norm_eps", 1e-5),
        rope_theta=cfg.get("rope_theta", 10000.0),
        tie_word_embeddings=cfg.get("tie_word_embeddings", False),
    )
    fields.update(overrides)
    return LlamaConfig(**fields)


def load_gpt_from_hf(model, model_dir, dtype="float32"):
    """Fill a ``GPTForCausalLM`` from an HF GPT-2 checkpoint dir.

    GPT-2 quirk: HF stores ``Conv1D`` weights already ``[in, out]`` —
    attn/mlp projections pass through untransposed; only true Linears
    (none in GPT-2 blocks) would transpose.
    """
    raw = _read_hf_weights(model_dir)
    own = model.state_dict()
    mapped = {}
    for name, arr in raw.items():
        n = _strip_prefix(name, ("transformer.",))
        tgt = None
        if n == "wte.weight":
            tgt = "gpt.embeddings.word_embeddings.weight"
        elif n == "wpe.weight":
            tgt = "gpt.embeddings.position_embeddings.weight"
        elif n.startswith("ln_f."):
            tgt = "gpt.final_norm." + n[len("ln_f."):]
        elif n.startswith("h."):
            tgt = "gpt.decoder." + n[2:]
            for hf, ours in ((".attn.c_attn.", ".self_attn.qkv_proj."),
                             (".attn.c_proj.", ".self_attn.out_proj."),
                             (".mlp.c_fc.", ".linear1."),
                             (".mlp.c_proj.", ".linear2."),
                             (".ln_1.", ".norm1."), (".ln_2.", ".norm2.")):
                tgt = tgt.replace(hf, ours)
        elif name == "lm_head.weight":
            tgt = "lm_head.weight"
        if tgt is None or tgt not in own:
            continue
        # GPT-2 Conv1D weights are already [in, out] — pass through; the
        # only true torch Linear is lm_head ([out, in] -> transpose)
        if tgt == "lm_head.weight" and arr.ndim == 2:
            arr = arr.T
        want = tuple(own[tgt].shape)
        if tuple(arr.shape) != want:
            raise ValueError(f"shape mismatch for {tgt}: checkpoint "
                             f"{arr.shape} vs model {want}")
        mapped[tgt] = arr.astype(dtype)
    _check_fully_mapped(own, mapped, "GPT")
    model.set_state_dict(mapped)
    return model


def bert_config_from_hf(model_dir, **overrides):
    from .bert import BertConfig
    cfg = load_hf_config(model_dir)
    fields = dict(
        vocab_size=cfg.get("vocab_size", 30522),
        hidden_size=cfg.get("hidden_size", 768),
        num_hidden_layers=cfg.get("num_hidden_layers", 12),
        num_attention_heads=cfg.get("num_attention_heads", 12),
        intermediate_size=cfg.get("intermediate_size", 3072),
        hidden_act=cfg.get("hidden_act", "gelu"),
        hidden_dropout_prob=cfg.get("hidden_dropout_prob", 0.1),
        attention_probs_dropout_prob=cfg.get(
            "attention_probs_dropout_prob", 0.1),
        max_position_embeddings=cfg.get("max_position_embeddings", 512),
        type_vocab_size=cfg.get("type_vocab_size", 2),
        layer_norm_eps=cfg.get("layer_norm_eps", 1e-12),
    )
    fields.update(overrides)
    return BertConfig(**fields)


def load_bert_from_hf(model, model_dir, dtype="float32"):
    """Fill a ``BertModel`` from an HF BERT checkpoint dir (post-LN
    naming: attention.output.LayerNorm -> norm1, output.LayerNorm ->
    norm2; all torch Linears transpose to [in, out])."""
    raw = _read_hf_weights(model_dir)
    own = model.state_dict()
    mapped = {}
    for name, arr in raw.items():
        n = _strip_prefix(name, ("bert.",))
        # old TF-converted checkpoints: LayerNorm.gamma/beta
        n = n.replace(".LayerNorm.gamma", ".LayerNorm.weight") \
             .replace(".LayerNorm.beta", ".LayerNorm.bias")
        tgt = None
        if n.startswith("embeddings."):
            tgt = n.replace(".LayerNorm.", ".layer_norm.")
        elif n.startswith("encoder.layer."):
            tgt = "encoder.layers." + n[len("encoder.layer."):]
            for hf, ours in (
                    (".attention.self.query.", ".self_attn.q_proj."),
                    (".attention.self.key.", ".self_attn.k_proj."),
                    (".attention.self.value.", ".self_attn.v_proj."),
                    (".attention.output.dense.", ".self_attn.out_proj."),
                    (".attention.output.LayerNorm.", ".norm1."),
                    (".intermediate.dense.", ".linear1."),
                    (".output.dense.", ".linear2."),
                    (".output.LayerNorm.", ".norm2.")):
                tgt = tgt.replace(hf, ours)
        elif n.startswith("pooler.dense."):
            tgt = n
        if tgt is None or tgt not in own:
            continue
        if arr.ndim == 2 and "word_embeddings" not in tgt \
                and "position_embeddings" not in tgt \
                and "token_type_embeddings" not in tgt:
            arr = arr.T           # torch Linear [out, in] -> [in, out]
        want = tuple(own[tgt].shape)
        if tuple(arr.shape) != want:
            raise ValueError(f"shape mismatch for {tgt}: checkpoint "
                             f"{arr.shape} vs model {want}")
        mapped[tgt] = arr.astype(dtype)
    _check_fully_mapped(own, mapped, "BERT", optional=("pooler.",))
    model.set_state_dict(mapped)
    return model


def t5_config_from_hf(model_dir, **overrides):
    from .t5 import T5Config
    cfg = load_hf_config(model_dir)
    fields = dict(
        vocab_size=cfg.get("vocab_size", 32128),
        d_model=cfg.get("d_model", 512),
        d_kv=cfg.get("d_kv", 64),
        d_ff=cfg.get("d_ff", 2048),
        num_layers=cfg.get("num_layers", 6),
        num_decoder_layers=cfg.get("num_decoder_layers"),
        num_heads=cfg.get("num_heads", 8),
        relative_attention_num_buckets=cfg.get(
            "relative_attention_num_buckets", 32),
        relative_attention_max_distance=cfg.get(
            "relative_attention_max_distance", 128),
        dropout_rate=cfg.get("dropout_rate", 0.1),
        layer_norm_epsilon=cfg.get("layer_norm_epsilon", 1e-6),
        feed_forward_proj=cfg.get("feed_forward_proj", "relu"),
        pad_token_id=cfg.get("pad_token_id", 0),
        decoder_start_token_id=cfg.get("decoder_start_token_id", 0),
        eos_token_id=cfg.get("eos_token_id", 1),
        tie_word_embeddings=cfg.get("tie_word_embeddings", True),
    )
    fields.update(overrides)
    return T5Config(**fields)


def load_t5_from_hf(model, model_dir, dtype="float32"):
    """Fill a ``T5ForConditionalGeneration`` from an HF T5 checkpoint
    dir. HF layout: encoder/decoder ``block.N.layer.K`` where K=0 is
    self-attention, the decoder's K=1 is cross-attention (EncDecAttention)
    and the last K is DenseReluDense; all Linears are [out, in] →
    transposed to this framework's [in, out]."""
    raw = _read_hf_weights(model_dir)
    own = model.state_dict()
    mapped = {}
    for name, arr in raw.items():
        n = name
        if n in ("shared.weight", "encoder.embed_tokens.weight",
                 "decoder.embed_tokens.weight", "lm_head.weight"):
            if n == "lm_head.weight" and "lm_head.weight" in own:
                # untied checkpoint (T5 v1.1 / Flan): independent head,
                # torch Linear [out, in] -> transpose
                mapped["lm_head.weight"] = arr.T.astype(dtype)
                continue
            if n != "shared.weight":
                continue              # tied copies of the same table
            mapped["shared.weight"] = arr.astype(dtype)
            continue
        tgt = n
        for stack, dec in (("encoder.", False), ("decoder.", True)):
            if not n.startswith(stack + "block."):
                continue
            parts = n.split(".")       # stack, block, N, layer, K, ...
            bi, k = parts[2], int(parts[4])
            rest = ".".join(parts[5:])
            ff_k = 2 if dec else 1
            if k == 0:                 # self-attention sub-layer
                rest = rest.replace("SelfAttention.", "self_attn.") \
                           .replace("layer_norm.", "norm1.")
            elif dec and k == 1:       # cross-attention sub-layer
                rest = rest.replace("EncDecAttention.", "cross_attn.") \
                           .replace("layer_norm.", "norm_cross.")
            elif k == ff_k:            # feed-forward sub-layer
                rest = rest.replace("DenseReluDense.wi_0.", "ff.wi.") \
                           .replace("DenseReluDense.wi_1.", "ff.wi_1.") \
                           .replace("DenseReluDense.wi.", "ff.wi.") \
                           .replace("DenseReluDense.wo.", "ff.wo.") \
                           .replace("layer_norm.", "norm2.")
            tgt = f"{stack}blocks.{bi}.{rest}"
        tgt = tgt.replace("encoder.final_layer_norm.",
                          "encoder.final_norm.") \
                 .replace("decoder.final_layer_norm.",
                          "decoder.final_norm.")
        if tgt not in own:
            continue
        # torch Linear [out, in] -> [in, out]; embeddings pass through
        if arr.ndim == 2 and "relative_attention_bias" not in tgt \
                and tgt != "shared.weight":
            arr = arr.T
        want = tuple(own[tgt].shape)
        if tuple(arr.shape) != want:
            raise ValueError(f"shape mismatch for {tgt}: checkpoint "
                             f"{arr.shape} vs model {want}")
        mapped[tgt] = arr.astype(dtype)
    _check_fully_mapped(own, mapped, "T5")
    model.set_state_dict(mapped)
    return model
