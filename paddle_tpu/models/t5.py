"""T5-style encoder-decoder family (reference behavior: PaddleNLP
``transformers/t5/modeling.py`` — relative-position-bias attention,
pre-RMSNorm blocks, gated/ReLU FFN, tied embedding, encoder-decoder
``generate``; the zoos are separate repos per SURVEY.md §2.4, so this is
the in-repo equivalent, same TPU-first shape as ``llama.py``).

TPU-first notes: the relative-position bias is a static [heads, S, S]
tensor computed from bucketized distances (one gather, added to logits
before softmax — XLA folds it into the attention fusion); decode reuses
the shared :class:`KVCache` for decoder self-attention while the
encoder states are computed once and closed over.
"""
from __future__ import annotations

import numpy as np
import jax.numpy as jnp

from ..nn.layer import Layer, LayerList
from ..nn.layers.common import Linear, Embedding, Dropout
from ..nn.layers.norm import RMSNorm
from ..nn import functional as F
from ..nn.initializer import Normal
from ..ops import math as pmath
from ..autograd.tape import apply, no_grad
from ..framework.core import Tensor
from .llama import LlamaPretrainingCriterion
from .generation import KVCache


class T5Config:
    def __init__(self, vocab_size=32128, d_model=512, d_kv=64, d_ff=2048,
                 num_layers=6, num_decoder_layers=None, num_heads=8,
                 relative_attention_num_buckets=32,
                 relative_attention_max_distance=128, dropout_rate=0.1,
                 layer_norm_epsilon=1e-6, feed_forward_proj="relu",
                 initializer_factor=1.0, pad_token_id=0,
                 decoder_start_token_id=0, eos_token_id=1,
                 tie_word_embeddings=True, **kw):
        self.vocab_size = vocab_size
        self.d_model = d_model
        self.d_kv = d_kv
        self.d_ff = d_ff
        self.num_layers = num_layers
        self.num_decoder_layers = num_decoder_layers or num_layers
        self.num_heads = num_heads
        self.relative_attention_num_buckets = relative_attention_num_buckets
        self.relative_attention_max_distance = relative_attention_max_distance
        self.dropout_rate = dropout_rate
        self.layer_norm_epsilon = layer_norm_epsilon
        self.feed_forward_proj = feed_forward_proj
        self.initializer_factor = initializer_factor
        self.pad_token_id = pad_token_id
        self.decoder_start_token_id = decoder_start_token_id
        self.eos_token_id = eos_token_id
        self.tie_word_embeddings = tie_word_embeddings
        for k, v in kw.items():
            setattr(self, k, v)


def t5_tiny(**kw):
    kw.setdefault("vocab_size", 128)
    kw.setdefault("d_model", 64)
    kw.setdefault("d_kv", 16)
    kw.setdefault("d_ff", 128)
    kw.setdefault("num_layers", 2)
    kw.setdefault("num_heads", 4)
    return T5Config(**kw)


def _relative_bucket(rel, bidirectional, num_buckets, max_dist):
    """numpy bucketization (static shapes → computed once per length)."""
    rel = np.asarray(rel)
    if bidirectional:
        num_buckets //= 2
        base = (rel > 0).astype(np.int64) * num_buckets
        rel = np.abs(rel)
    else:
        base = np.zeros_like(rel)
        rel = -np.minimum(rel, 0)
    max_exact = num_buckets // 2
    is_small = rel < max_exact
    large = max_exact + (
        np.log(np.maximum(rel, 1) / max_exact)
        / np.log(max_dist / max_exact) * (num_buckets - max_exact)
    ).astype(np.int64)
    large = np.minimum(large, num_buckets - 1)
    return base + np.where(is_small, rel, large)


class T5Attention(Layer):
    def __init__(self, config, is_decoder, has_relative_bias=False,
                 is_cross=False):
        super().__init__()
        cfg = config
        self.cfg = cfg
        self.is_decoder = is_decoder
        self.is_cross = is_cross
        inner = cfg.num_heads * cfg.d_kv
        init = Normal(0.0, cfg.initializer_factor * (cfg.d_model ** -0.5))
        self.q = Linear(cfg.d_model, inner, weight_attr=init, bias_attr=False)
        self.k = Linear(cfg.d_model, inner, weight_attr=init, bias_attr=False)
        self.v = Linear(cfg.d_model, inner, weight_attr=init, bias_attr=False)
        self.o = Linear(inner, cfg.d_model, weight_attr=init,
                        bias_attr=False)
        self.has_relative_bias = has_relative_bias
        if has_relative_bias:
            self.relative_attention_bias = Embedding(
                cfg.relative_attention_num_buckets, cfg.num_heads,
                weight_attr=init)

    def _bias(self, q_len, k_len, q_offset=0):
        """[1, heads, q_len, k_len] relative position bias."""
        ctx = np.arange(q_len)[:, None] + q_offset
        mem = np.arange(k_len)[None, :]
        buckets = _relative_bucket(
            mem - ctx, bidirectional=not self.is_decoder,
            num_buckets=self.cfg.relative_attention_num_buckets,
            max_dist=self.cfg.relative_attention_max_distance)
        emb = self.relative_attention_bias(
            Tensor(jnp.asarray(buckets)))            # [q, k, heads]
        return emb.transpose([2, 0, 1]).unsqueeze(0)

    def forward(self, hidden, kv_source=None, bias=None, cache=None):
        cfg = self.cfg
        b, s, _ = hidden.shape
        src = hidden if kv_source is None else kv_source
        q = self.q(hidden).reshape([b, s, cfg.num_heads, cfg.d_kv])
        if self.is_cross and cache is not None:
            # encoder states are fixed across decode: project K/V once
            store = getattr(cache, "_cross", None)
            if store is None:
                store = cache._cross = {}
            if id(self) not in store:
                store[id(self)] = (
                    self.k(src).reshape([b, src.shape[1], cfg.num_heads,
                                         cfg.d_kv]).detach(),
                    self.v(src).reshape([b, src.shape[1], cfg.num_heads,
                                         cfg.d_kv]).detach())
            k, v = store[id(self)]
        else:
            k = self.k(src).reshape([b, src.shape[1], cfg.num_heads,
                                     cfg.d_kv])
            v = self.v(src).reshape([b, src.shape[1], cfg.num_heads,
                                     cfg.d_kv])
        if cache is not None and not self.is_cross:
            k, v = cache.update(self, k, v)          # decoder self-attn
        # T5 applies NO 1/sqrt(d) scaling (folded into init); logits get
        # the additive relative bias before softmax
        def fn(qa, ka, va, *rest):
            lg = jnp.einsum("bqhd,bkhd->bhqk", qa, ka)
            if rest:
                lg = lg + rest[0]
            if self.is_decoder and not self.is_cross:
                ql, kl = qa.shape[1], ka.shape[1]
                qi = jnp.arange(ql)[:, None] + (kl - ql)
                ki = jnp.arange(kl)[None, :]
                lg = jnp.where(qi[None, None] >= ki[None, None], lg, -1e30)
            w = jnp.exp(lg - jnp.max(lg, -1, keepdims=True))
            w = w / jnp.maximum(w.sum(-1, keepdims=True), 1e-30)
            return jnp.einsum("bhqk,bkhd->bqhd", w, va)
        args = (q, k, v) + ((bias,) if bias is not None else ())
        out = apply(fn, *args, op_name="t5_attention")
        return self.o(out.reshape([b, s, cfg.num_heads * cfg.d_kv]))


class T5FF(Layer):
    def __init__(self, config):
        super().__init__()
        cfg = config
        init = Normal(0.0, cfg.initializer_factor * (cfg.d_model ** -0.5))
        self.gated = cfg.feed_forward_proj.startswith("gated")
        self.wi = Linear(cfg.d_model, cfg.d_ff, weight_attr=init,
                         bias_attr=False)
        if self.gated:
            self.wi_1 = Linear(cfg.d_model, cfg.d_ff, weight_attr=init,
                               bias_attr=False)
        self.wo = Linear(cfg.d_ff, cfg.d_model, weight_attr=init,
                         bias_attr=False)
        self.dropout = Dropout(cfg.dropout_rate)

    def forward(self, x):
        h = self.wi(x)
        # gated variant uses gelu_new (tanh approximation), the HF
        # 'gated-gelu' activation — exact gelu drifts ~1e-3
        h = F.gelu(h, approximate=True) * self.wi_1(x) if self.gated \
            else F.relu(h)
        return self.wo(self.dropout(h))


class T5Block(Layer):
    def __init__(self, config, is_decoder, has_relative_bias):
        super().__init__()
        cfg = config
        self.is_decoder = is_decoder
        self.norm1 = RMSNorm(cfg.d_model, cfg.layer_norm_epsilon)
        self.self_attn = T5Attention(cfg, is_decoder, has_relative_bias)
        if is_decoder:
            self.norm_cross = RMSNorm(cfg.d_model, cfg.layer_norm_epsilon)
            self.cross_attn = T5Attention(cfg, is_decoder, is_cross=True)
        self.norm2 = RMSNorm(cfg.d_model, cfg.layer_norm_epsilon)
        self.ff = T5FF(cfg)
        self.dropout = Dropout(cfg.dropout_rate)

    def forward(self, x, enc=None, bias=None, cache=None):
        x = x + self.dropout(self.self_attn(self.norm1(x), bias=bias,
                                            cache=cache))
        if self.is_decoder and enc is not None:
            x = x + self.dropout(self.cross_attn(self.norm_cross(x),
                                                 kv_source=enc, cache=cache))
        return x + self.dropout(self.ff(self.norm2(x)))


class T5Stack(Layer):
    def __init__(self, config, is_decoder):
        super().__init__()
        cfg = config
        self.cfg = cfg
        self.is_decoder = is_decoder
        n = cfg.num_decoder_layers if is_decoder else cfg.num_layers
        # T5 shares ONE relative bias table per stack (layer 0 owns it)
        self.blocks = LayerList([
            T5Block(cfg, is_decoder, has_relative_bias=(i == 0))
            for i in range(n)])
        self.final_norm = RMSNorm(cfg.d_model, cfg.layer_norm_epsilon)
        self.dropout = Dropout(cfg.dropout_rate)

    def forward(self, hidden, enc=None, cache=None):
        s = hidden.shape[1]
        q_off = cache.pos if (cache is not None and self.is_decoder) else 0
        k_len = s + q_off
        bias = self.blocks[0].self_attn._bias(s, k_len, q_offset=q_off)
        hidden = self.dropout(hidden)
        for blk in self.blocks:
            hidden = blk(hidden, enc=enc, bias=bias, cache=cache)
        if cache is not None and self.is_decoder:
            cache.advance(s)
        return self.final_norm(hidden)


class T5ForConditionalGeneration(Layer):
    """Encoder-decoder LM with tied embedding (logits scaled by
    d_model^-0.5, the T5 tie convention)."""

    def __init__(self, config):
        super().__init__()
        self.config = config
        cfg = config
        self.shared = Embedding(cfg.vocab_size, cfg.d_model,
                                weight_attr=Normal(0.0,
                                                   cfg.initializer_factor))
        self.encoder = T5Stack(cfg, is_decoder=False)
        self.decoder = T5Stack(cfg, is_decoder=True)
        # T5 v1.1 / Flan style: an independent (untied, unscaled) head
        self.lm_head = None if cfg.tie_word_embeddings else Linear(
            cfg.d_model, cfg.vocab_size,
            weight_attr=Normal(0.0, cfg.initializer_factor),
            bias_attr=False)
        self.criterion = LlamaPretrainingCriterion()

    @classmethod
    def from_pretrained(cls, model_dir, dtype="float32", **overrides):
        """Build from a LOCAL HF-format T5 checkpoint directory
        (zero-egress; see models/pretrained.py)."""
        from .pretrained import t5_config_from_hf, load_t5_from_hf
        cfg = t5_config_from_hf(model_dir, **overrides)
        model = cls(cfg)
        return load_t5_from_hf(model, model_dir, dtype=dtype)

    def _shift_right(self, labels):
        arr = labels._data if isinstance(labels, Tensor) else labels
        start = jnp.full((arr.shape[0], 1), self.config.decoder_start_token_id,
                         arr.dtype)
        shifted = jnp.concatenate([start, arr[:, :-1]], axis=1)
        # ignore_index positions (-100, the criterion's convention) must
        # become valid decoder inputs (HF masks them to pad_token_id)
        shifted = jnp.where(shifted == -100,
                            jnp.asarray(self.config.pad_token_id,
                                        shifted.dtype), shifted)
        return Tensor(shifted)

    def encode(self, input_ids):
        return self.encoder(self.shared(input_ids))

    def forward(self, input_ids, decoder_input_ids=None, labels=None,
                encoder_outputs=None, cache=None):
        if encoder_outputs is None:
            encoder_outputs = self.encode(input_ids)
        if decoder_input_ids is None:
            if labels is None:
                raise ValueError("need decoder_input_ids or labels")
            decoder_input_ids = self._shift_right(labels)
        dec = self.decoder(self.shared(decoder_input_ids),
                           enc=encoder_outputs, cache=cache)
        if self.lm_head is not None:       # untied head: no tie scaling
            logits = self.lm_head(dec)
        else:
            logits = pmath.matmul(dec * (self.config.d_model ** -0.5),
                                  self.shared.weight, transpose_y=True)
        if labels is None:
            return logits
        return self.criterion(logits, labels), logits

    @no_grad()
    def generate(self, input_ids, max_new_tokens=32, eos_token_id=None):
        """Greedy encoder-decoder decode with a decoder-side KV cache
        (the encoder runs ONCE)."""
        was_training = self.training
        self.eval()
        try:
            ids = input_ids if isinstance(input_ids, Tensor) \
                else Tensor(jnp.asarray(np.asarray(input_ids)))
            eos = self.config.eos_token_id if eos_token_id is None \
                else eos_token_id
            enc = self.encode(ids)
            b = ids.shape[0]
            cache = KVCache()
            cur = Tensor(jnp.full((b, 1), self.config.decoder_start_token_id,
                                  jnp.int32))
            out = cur._data
            finished = jnp.zeros((b,), bool)
            for _ in range(max_new_tokens):
                logits = self.forward(None, decoder_input_ids=cur,
                                      encoder_outputs=enc, cache=cache)
                nxt = jnp.argmax(logits._data[:, -1].astype(jnp.float32),
                                 axis=-1).astype(out.dtype)
                if eos is not None:
                    nxt = jnp.where(finished, jnp.asarray(eos, out.dtype),
                                    nxt)
                    finished = jnp.logical_or(finished, nxt == eos)
                out = jnp.concatenate([out, nxt[:, None]], axis=1)
                cur = Tensor(nxt[:, None])
                if eos is not None and bool(finished.all()):
                    break
            return Tensor(out)
        finally:
            if was_training:
                self.train()
