"""BERT / ERNIE encoder family (reference behavior: PaddleNLP
``transformers/bert/modeling.py`` and ``transformers/ernie/modeling.py`` —
the `@to_static` fine-tune benchmark is ERNIE-3.0 / BERT-base,
BASELINE.json configs[1]).

ERNIE shares BERT's architecture (token/position/segment embeddings +
post-LN transformer encoder + pooler); upstream differences are pretraining
data/objectives, so here ``Ernie*`` subclasses ``Bert*`` with ERNIE default
sizes.
"""
from __future__ import annotations

from ..nn.layer import Layer
from ..nn.layers.common import Linear, Embedding, Dropout
from ..nn.layers.norm import LayerNorm
from ..nn.layers.transformer import TransformerEncoder, TransformerEncoderLayer
from ..nn import functional as F
from ..nn.initializer import Normal
from ..ops import math as pmath
from ..ops import creation as C


class BertConfig:
    def __init__(self, vocab_size=30522, hidden_size=768,
                 num_hidden_layers=12, num_attention_heads=12,
                 intermediate_size=3072, hidden_act="gelu",
                 hidden_dropout_prob=0.1, attention_probs_dropout_prob=0.1,
                 max_position_embeddings=512, type_vocab_size=2,
                 initializer_range=0.02, layer_norm_eps=1e-12,
                 num_labels=2, **kwargs):
        self.vocab_size = vocab_size
        self.hidden_size = hidden_size
        self.num_hidden_layers = num_hidden_layers
        self.num_attention_heads = num_attention_heads
        self.intermediate_size = intermediate_size
        self.hidden_act = hidden_act
        self.hidden_dropout_prob = hidden_dropout_prob
        self.attention_probs_dropout_prob = attention_probs_dropout_prob
        self.max_position_embeddings = max_position_embeddings
        self.type_vocab_size = type_vocab_size
        self.initializer_range = initializer_range
        self.layer_norm_eps = layer_norm_eps
        self.num_labels = num_labels
        for k, v in kwargs.items():
            setattr(self, k, v)


def bert_base(**kw):
    return BertConfig(**kw)


def bert_tiny(**kw):
    kw.setdefault("vocab_size", 128)
    kw.setdefault("hidden_size", 64)
    kw.setdefault("num_hidden_layers", 2)
    kw.setdefault("num_attention_heads", 4)
    kw.setdefault("intermediate_size", 128)
    kw.setdefault("max_position_embeddings", 128)
    return BertConfig(**kw)


class BertEmbeddings(Layer):
    def __init__(self, config):
        super().__init__()
        init = Normal(0.0, config.initializer_range)
        self.word_embeddings = Embedding(config.vocab_size,
                                         config.hidden_size, weight_attr=init)
        self.position_embeddings = Embedding(config.max_position_embeddings,
                                             config.hidden_size,
                                             weight_attr=init)
        self.token_type_embeddings = Embedding(config.type_vocab_size,
                                               config.hidden_size,
                                               weight_attr=init)
        self.layer_norm = LayerNorm(config.hidden_size, config.layer_norm_eps)
        self.dropout = Dropout(config.hidden_dropout_prob)

    def forward(self, input_ids, token_type_ids=None, position_ids=None):
        if position_ids is None:
            position_ids = C.arange(0, input_ids.shape[1], dtype="int64")
        emb = (self.word_embeddings(input_ids) +
               self.position_embeddings(position_ids))
        if token_type_ids is None:
            # reference semantics: absent segment ids mean segment 0 —
            # the type-0 embedding is still added
            token_type_ids = C.zeros(list(input_ids.shape), dtype="int64")
        emb = emb + self.token_type_embeddings(token_type_ids)
        return self.dropout(self.layer_norm(emb))


class BertPooler(Layer):
    def __init__(self, config):
        super().__init__()
        self.dense = Linear(config.hidden_size, config.hidden_size,
                            weight_attr=Normal(0.0, config.initializer_range))

    def forward(self, hidden):
        return F.tanh(self.dense(hidden[:, 0]))


class BertModel(Layer):
    def __init__(self, config):
        super().__init__()
        self.config = config
        self.embeddings = BertEmbeddings(config)
        enc_layer = TransformerEncoderLayer(
            config.hidden_size, config.num_attention_heads,
            config.intermediate_size, dropout=config.hidden_dropout_prob,
            activation=config.hidden_act,
            attn_dropout=config.attention_probs_dropout_prob,
            act_dropout=0.0, normalize_before=False)
        self.encoder = TransformerEncoder(enc_layer, config.num_hidden_layers)
        self.pooler = BertPooler(config)

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None):
        if attention_mask is not None and len(attention_mask.shape) == 2:
            # [b, s] pad mask -> additive [b, 1, 1, s]
            am = attention_mask
            attention_mask = (
                (1.0 - am.astype("float32")) * -1e4).unsqueeze(1).unsqueeze(1)
        hidden = self.embeddings(input_ids, token_type_ids, position_ids)
        hidden = self.encoder(hidden, attention_mask)
        return hidden, self.pooler(hidden)


class BertForSequenceClassification(Layer):
    def __init__(self, config):
        super().__init__()
        self.bert = BertModel(config)
        self.dropout = Dropout(config.hidden_dropout_prob)
        self.classifier = Linear(config.hidden_size, config.num_labels,
                                 weight_attr=Normal(0.0,
                                                    config.initializer_range))

    def forward(self, input_ids, token_type_ids=None, position_ids=None,
                attention_mask=None, labels=None):
        _, pooled = self.bert(input_ids, token_type_ids, position_ids,
                              attention_mask)
        logits = self.classifier(self.dropout(pooled))
        if labels is None:
            return logits
        return F.cross_entropy(logits, labels), logits


class BertForPretraining(Layer):
    """MLM head (weight-tied decoder) + NSP head."""

    def __init__(self, config):
        super().__init__()
        self.bert = BertModel(config)
        init = Normal(0.0, config.initializer_range)
        self.transform = Linear(config.hidden_size, config.hidden_size,
                                weight_attr=init)
        self.transform_norm = LayerNorm(config.hidden_size,
                                        config.layer_norm_eps)
        self.mlm_bias = self.create_parameter([config.vocab_size],
                                              is_bias=True)
        self.nsp = Linear(config.hidden_size, 2, weight_attr=init)

    def forward(self, input_ids, token_type_ids=None, masked_lm_labels=None,
                next_sentence_labels=None):
        seq, pooled = self.bert(input_ids, token_type_ids)
        h = self.transform_norm(F.gelu(self.transform(seq)))
        mlm_logits = pmath.matmul(
            h, self.bert.embeddings.word_embeddings.weight,
            transpose_y=True) + self.mlm_bias
        nsp_logits = self.nsp(pooled)
        if masked_lm_labels is None:
            return mlm_logits, nsp_logits
        loss = F.cross_entropy(
            mlm_logits.reshape([-1, mlm_logits.shape[-1]]),
            masked_lm_labels.reshape([-1]), ignore_index=-100)
        if next_sentence_labels is not None:
            loss = loss + F.cross_entropy(nsp_logits,
                                          next_sentence_labels.reshape([-1]))
        return loss, mlm_logits, nsp_logits


class ErnieConfig(BertConfig):
    def __init__(self, **kwargs):
        kwargs.setdefault("vocab_size", 40000)
        kwargs.setdefault("type_vocab_size", 4)
        super().__init__(**kwargs)


class ErnieModel(BertModel):
    pass


class ErnieForSequenceClassification(BertForSequenceClassification):
    def __init__(self, config):
        super().__init__(config)
        self.ernie = self.bert
