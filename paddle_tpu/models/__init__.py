"""In-repo transformer model zoo (SURVEY.md §2.4: PaddleNLP/PaddleClas are
separate repos upstream — the build needs in-repo equivalents: a
transformer-LM family (BERT/ERNIE/GPT/Llama) plus the ResNet family that
lives in ``paddle_tpu.vision.models``).

Each model family exposes ``sharding_rules()`` — an ordered list of
``(param-name-regex, PartitionSpec-tuple)`` pairs mapping parameters onto the
named hybrid mesh axes (``paddle_tpu.distributed.mesh.HYBRID_AXES``). That is
the TPU-native form of the reference's mp/sharding wrappers: annotate, and
XLA's SPMD partitioner inserts the collectives (SURVEY.md §7.0).
"""
from .llama import (LlamaConfig, LlamaModel, LlamaForCausalLM,
                    LlamaPretrainingCriterion, LlamaForCausalLMPipe,
                    build_llama_pipe, llama3_8b, llama_tiny)
from .t5 import (T5Config, T5ForConditionalGeneration,  # noqa: F401
                 t5_tiny)
from .gpt import (GPTConfig, GPTModel, GPTForCausalLM, GPTForCausalLMPipe,
                  gpt3_1p3b, gpt_tiny)
from .bert import (BertConfig, BertModel, BertForSequenceClassification,
                   BertForPretraining, ErnieConfig, ErnieModel,
                   ErnieForSequenceClassification, bert_base, bert_tiny)
from .ppyoloe import (PPYOLOE, DetectionLoss, ppyoloe_lite, CSPBackbone,
                      FPNNeck, ETHead)
from .mixtral import (MixtralConfig, MixtralModel, MixtralForCausalLM,
                      MixtralSparseMoeBlock, mixtral_8x7b, mixtral_tiny)

__all__ = [
    "LlamaConfig", "LlamaModel", "LlamaForCausalLM",
    "LlamaPretrainingCriterion", "LlamaForCausalLMPipe",
    "build_llama_pipe", "llama3_8b", "llama_tiny",
    "MixtralConfig", "MixtralModel", "MixtralForCausalLM",
    "MixtralSparseMoeBlock", "mixtral_8x7b", "mixtral_tiny",
    "T5Config", "T5ForConditionalGeneration", "t5_tiny",
    "GPTConfig", "GPTModel", "GPTForCausalLM", "GPTForCausalLMPipe",
    "gpt3_1p3b", "gpt_tiny",
    "BertConfig", "BertModel", "BertForSequenceClassification",
    "BertForPretraining", "ErnieConfig", "ErnieModel",
    "ErnieForSequenceClassification", "bert_base", "bert_tiny",
    "PPYOLOE", "DetectionLoss", "ppyoloe_lite", "CSPBackbone", "FPNNeck",
    "ETHead",
]
