"""PP-YOLOE-style anchor-free detector (reference behavior: PaddleDetection's
``ppyoloe`` — CSPResNet backbone, CustomCSPPAN neck, ET-head with
distance-to-bbox regression; the in-repo target is BASELINE.json config 3:
detection model + heavy DataLoader pipeline; SURVEY.md §2.4).

Scope note: this is the *framework-side* detection family — backbone, FPN
neck, anchor-free head, decode (distance2bbox) and NMS post-processing, all
TPU-shaped (static shapes, NCHW convs, silu fusion). The full task-aligned
label assigner (TAL) of PaddleDetection lives model-side there and is
follow-up work; ``DetectionLoss`` here trains against dense per-point
targets (sufficient for pipeline/perf work and e2e tests).
"""
from __future__ import annotations

import math

import jax.numpy as jnp

from ..nn.layer import Layer, LayerList, Sequential
from ..nn.layers.conv import Conv2D
from ..nn.layers.norm import BatchNorm2D
from ..nn import functional as F
from ..autograd.tape import apply
from ..vision import ops as vops


class ConvBNLayer(Layer):
    def __init__(self, ch_in, ch_out, kernel=3, stride=1, padding=None):
        super().__init__()
        self.conv = Conv2D(ch_in, ch_out, kernel, stride=stride,
                           padding=padding if padding is not None
                           else kernel // 2, bias_attr=False)
        self.bn = BatchNorm2D(ch_out)

    def forward(self, x):
        return F.silu(self.bn(self.conv(x)))


class CSPBlock(Layer):
    """Cross-stage-partial block: split → conv path + identity → concat."""

    def __init__(self, ch, n=1):
        super().__init__()
        mid = ch // 2
        self.conv1 = ConvBNLayer(ch, mid, 1)
        self.conv2 = ConvBNLayer(ch, mid, 1)
        self.blocks = Sequential(*[ConvBNLayer(mid, mid, 3) for _ in range(n)])
        self.conv3 = ConvBNLayer(mid * 2, ch, 1)

    def forward(self, x):
        a = self.blocks(self.conv1(x))
        b = self.conv2(x)
        from ..ops import manipulation as manip
        return self.conv3(manip.concat([a, b], axis=1))


class CSPBackbone(Layer):
    """3-level feature extractor (strides 8/16/32)."""

    def __init__(self, width=32, depth=1):
        super().__init__()
        w = width
        self.stem = ConvBNLayer(3, w, 3, stride=2)
        self.stage1 = Sequential(ConvBNLayer(w, w * 2, 3, stride=2),
                                 CSPBlock(w * 2, depth))
        self.stage2 = Sequential(ConvBNLayer(w * 2, w * 4, 3, stride=2),
                                 CSPBlock(w * 4, depth))       # /8
        self.stage3 = Sequential(ConvBNLayer(w * 4, w * 8, 3, stride=2),
                                 CSPBlock(w * 8, depth))       # /16
        self.stage4 = Sequential(ConvBNLayer(w * 8, w * 16, 3, stride=2),
                                 CSPBlock(w * 16, depth))      # /32
        self.out_channels = [w * 4, w * 8, w * 16]

    def forward(self, x):
        x = self.stage2(self.stage1(self.stem(x)))
        c3 = x
        c4 = self.stage3(c3)
        c5 = self.stage4(c4)
        return [c3, c4, c5]


class FPNNeck(Layer):
    """Top-down feature fusion (CustomCSPPAN-lite)."""

    def __init__(self, in_channels, out_ch=96):
        super().__init__()
        self.lateral = LayerList([ConvBNLayer(c, out_ch, 1)
                                  for c in in_channels])
        self.fuse = LayerList([ConvBNLayer(out_ch, out_ch, 3)
                               for _ in in_channels])
        self.out_channels = [out_ch] * len(in_channels)

    def forward(self, feats):
        lat = [l(f) for l, f in zip(self.lateral, feats)]
        outs = [lat[-1]]
        for i in range(len(lat) - 2, -1, -1):
            up = F.interpolate(outs[0], scale_factor=2, mode="nearest")
            outs.insert(0, lat[i] + up)
        return [f(o) for f, o in zip(self.fuse, outs)]


class ETHead(Layer):
    """Anchor-free head: per level cls [N,C,H,W] + reg ltrb [N,4,H,W]."""

    def __init__(self, in_channels, num_classes=80):
        super().__init__()
        self.num_classes = num_classes
        self.cls_convs = LayerList([ConvBNLayer(c, c, 3) for c in in_channels])
        self.reg_convs = LayerList([ConvBNLayer(c, c, 3) for c in in_channels])
        self.cls_pred = LayerList([Conv2D(c, num_classes, 1)
                                   for c in in_channels])
        self.reg_pred = LayerList([Conv2D(c, 4, 1) for c in in_channels])

    def forward(self, feats):
        cls_outs, reg_outs = [], []
        for f, cc, rc, cp, rp in zip(feats, self.cls_convs, self.reg_convs,
                                     self.cls_pred, self.reg_pred):
            cls_outs.append(cp(cc(f)))
            reg_outs.append(F.relu(rp(rc(f))))   # distances are >= 0
        return cls_outs, reg_outs


class PPYOLOE(Layer):
    """End-to-end detector. ``forward`` returns per-level (cls, reg) in
    training mode; ``predict`` decodes + NMS."""

    STRIDES = (8, 16, 32)

    def __init__(self, num_classes=80, width=32, depth=1, neck_ch=96):
        super().__init__()
        self.backbone = CSPBackbone(width, depth)
        self.neck = FPNNeck(self.backbone.out_channels, neck_ch)
        self.head = ETHead(self.neck.out_channels, num_classes)
        self.num_classes = num_classes

    def forward(self, x):
        return self.head(self.neck(self.backbone(x)))

    def decode(self, cls_outs, reg_outs):
        """Flatten all levels → (scores [N,P,C], boxes [N,P,4] in pixels)."""
        def fn(*flat):
            half = len(flat) // 2
            clss, regs = flat[:half], flat[half:]
            all_scores, all_boxes = [], []
            for cl, rg, stride in zip(clss, regs, self.STRIDES):
                n, c, h, w = cl.shape
                pts_x = (jnp.arange(w) + 0.5) * stride
                pts_y = (jnp.arange(h) + 0.5) * stride
                px, py = jnp.meshgrid(pts_x, pts_y)
                pts = jnp.stack([px.reshape(-1), py.reshape(-1)], -1)
                scores = jnp.transpose(cl, (0, 2, 3, 1)).reshape(n, -1, c)
                dists = jnp.transpose(rg, (0, 2, 3, 1)).reshape(n, -1, 4) \
                    * stride
                x1 = pts[None, :, 0] - dists[..., 0]
                y1 = pts[None, :, 1] - dists[..., 1]
                x2 = pts[None, :, 0] + dists[..., 2]
                y2 = pts[None, :, 1] + dists[..., 3]
                all_scores.append(jnp.asarray(
                    1 / (1 + jnp.exp(-scores)), jnp.float32))
                all_boxes.append(jnp.stack([x1, y1, x2, y2], -1))
            return (jnp.concatenate(all_scores, 1),
                    jnp.concatenate(all_boxes, 1))

        return apply(fn, *cls_outs, *reg_outs, op_name="ppyoloe_decode")

    def predict(self, x, score_thresh=0.4, iou_thresh=0.5, top_k=100):
        """Returns a list (per image) of dicts {boxes, scores, labels}
        (numpy) after NMS."""
        import numpy as np
        self.eval()
        from ..autograd.tape import no_grad
        with no_grad():
            cls_outs, reg_outs = self.forward(x)
            scores, boxes = self.decode(cls_outs, reg_outs)
        out = []
        for i in range(scores.shape[0]):
            s = np.asarray(scores[i].numpy())
            b = np.asarray(boxes[i].numpy())
            conf = s.max(-1)
            lab = s.argmax(-1)
            m = conf >= score_thresh
            if not m.any():
                out.append({"boxes": np.zeros((0, 4), np.float32),
                            "scores": np.zeros((0,), np.float32),
                            "labels": np.zeros((0,), np.int64)})
                continue
            bi, ci, li = b[m], conf[m], lab[m]
            keep = vops.nms(bi, iou_threshold=iou_thresh, scores=ci,
                            category_idxs=li, top_k=top_k).numpy()
            out.append({"boxes": bi[keep], "scores": ci[keep],
                        "labels": li[keep].astype(np.int64)})
        return out


class DetectionLoss(Layer):
    """Dense per-point loss: BCE on class logits + masked L1 on distances
    (full TAL assignment is PaddleDetection model-side; see module note)."""

    def forward(self, cls_outs, reg_outs, cls_targets, reg_targets,
                pos_masks):
        def fn(*flat):
            k = len(flat) // 5
            clss = flat[:k]
            regs = flat[k:2 * k]
            tcls = flat[2 * k:3 * k]
            treg = flat[3 * k:4 * k]
            mask = flat[4 * k:]
            total = 0.0
            for cl, rg, tc, tr, m in zip(clss, regs, tcls, treg, mask):
                p = jnp.clip(1 / (1 + jnp.exp(-cl.astype(jnp.float32))),
                             1e-7, 1 - 1e-7)
                bce = -(tc * jnp.log(p) + (1 - tc) * jnp.log(1 - p)).mean()
                l1 = (jnp.abs(rg - tr) * m).sum() / jnp.maximum(m.sum(), 1)
                total = total + bce + l1
            return total

        return apply(fn, *cls_outs, *reg_outs, *cls_targets, *reg_targets,
                     *pos_masks, op_name="detection_loss")


def ppyoloe_lite(num_classes=80, **kw):
    return PPYOLOE(num_classes=num_classes, width=16, depth=1, neck_ch=48)
