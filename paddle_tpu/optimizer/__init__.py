"""paddle.optimizer (reference: ``python/paddle/optimizer/`` — SURVEY.md §2.2:
Optimizer base with param groups, grad clip, regularizer; SGD/Momentum/Adam/
AdamW/... with multi_precision master weights).

Each optimizer exposes a *functional core* — ``_init_slots(p)`` and
``_apply(p, g, slots, lr, t)`` on raw jnp arrays — used both by the eager
``step()`` (mutating Tensors in place, Paddle semantics) and by the jitted
whole-tree train step in ``paddle_tpu/parallel/engine.py`` (the perf path).
"""
from __future__ import annotations

import os

import jax
import jax.numpy as jnp

from ..framework.core import Tensor, Parameter
from ..autograd.tape import no_grad
from . import lr as lr_mod
from .lr import LRScheduler
from ..nn.clip_grad import ClipGradBase


class Optimizer:
    def __init__(self, learning_rate=0.001, parameters=None, weight_decay=None,
                 grad_clip=None, name=None, multi_precision=False):
        self._learning_rate = learning_rate
        self._parameter_list = list(parameters) if parameters is not None else None
        self._param_groups = None
        if self._parameter_list and isinstance(self._parameter_list[0], dict):
            self._param_groups = self._parameter_list
            flat = []
            for g in self._param_groups:
                flat.extend(g["params"])
            self._parameter_list = flat
        self.regularization = weight_decay
        self._grad_clip = grad_clip
        self._multi_precision = multi_precision
        self._slots: dict[int, dict] = {}
        self._step_t: dict[int, int] = {}
        self._name = name
        # fused donated step (optimizer/fused.py): None = auto (env
        # PADDLE_FUSED_STEP / min-params heuristic), True/False = forced
        self.fuse_step = None
        self._fused_engine = None

    # -- lr -----------------------------------------------------------------
    def get_lr(self):
        if isinstance(self._learning_rate, LRScheduler):
            return self._learning_rate()
        return float(self._learning_rate)

    def set_lr(self, value):
        self._learning_rate = float(value)

    def set_lr_scheduler(self, scheduler):
        self._learning_rate = scheduler

    # -- state --------------------------------------------------------------
    def _wd_coeff(self, param):
        wd = self.regularization
        if wd is None:
            return 0.0
        if hasattr(wd, "_coeff"):  # L2Decay object
            return float(wd._coeff)
        return float(wd)

    def _get_slots(self, p: Parameter):
        key = id(p)
        if key not in self._slots:
            slots = self._init_slots(p._data)
            if self._multi_precision and p.dtype in (jnp.float16, jnp.bfloat16):
                slots["master"] = p._data.astype(jnp.float32)
            self._slots[key] = slots
            self._step_t[key] = 0
        return self._slots[key]

    # -- functional core (override per optimizer) ---------------------------
    def _init_slots(self, p):
        return {}

    def _apply(self, p, g, slots, lr, t, wd):
        raise NotImplementedError

    def _masterized_apply(self, p, g, slots, lr, t, wd):
        """Run _apply with the fp32 master-weight round trip when the
        slot exists (low-precision params under multi_precision)."""
        g_arr = g._data
        if "master" in slots:
            p_arr = slots["master"]
            g_arr = g_arr.astype(jnp.float32)
        else:
            p_arr = p._data
        new_p, new_slots = self._apply(p_arr, g_arr, slots, lr, t, wd)
        if "master" in slots:
            new_slots["master"] = new_p
            p._data = new_p.astype(p.dtype)
        else:
            p._data = new_p
        self._slots[id(p)] = new_slots

    # -- the eager step ------------------------------------------------------
    def _use_fused(self, n_params: int) -> bool:
        if self.fuse_step is not None:
            return bool(self.fuse_step)
        env = os.environ.get("PADDLE_FUSED_STEP", "auto").lower()
        if env in ("0", "false", "off"):
            return False
        if env in ("1", "true", "on"):
            return True
        # auto: below the threshold the one-off trace+compile costs more
        # than the per-param dispatches it saves
        return n_params >= int(
            os.environ.get("PADDLE_FUSED_STEP_MIN_PARAMS", "16"))

    @no_grad()
    def step(self):
        # step-phase span ("optimizer" slice of the training-step
        # breakdown); clock() is None when the layer is off
        from ..profiler import step_phase as _step_phase
        from ..profiler import ledger as _ledger
        _t0 = _step_phase.clock()
        try:
            r = self._step_impl()
            # determinism ledger: digest this step's (post-sync) grads
            # + updated params, commit the step row, compare vs peers
            if _ledger.is_enabled():
                _ledger.record_optimizer_step(self)
            return r
        finally:
            if _t0 is not None:
                import time as _time
                _step_phase.record_phase("optimizer",
                                         _time.perf_counter() - _t0)

    def _step_impl(self):
        # accept plain Tensors with stop_gradient=False, like the
        # reference (Parameter.trainable; Tensor -> not stop_gradient)
        params_grads = [(p, p.grad) for p in self._parameter_list
                        if p.grad is not None
                        and getattr(p, "trainable", not p.stop_gradient)]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        if params_grads and self._use_fused(len(params_grads)):
            from .fused import FusedStepEngine
            if self._fused_engine is None:
                self._fused_engine = FusedStepEngine(self)
            # fused path consumes what it can; exotic groups (L1, master
            # weights, duplicate params) come back for the eager loop
            params_grads = self._fused_engine.step(params_grads, lr)
        if params_grads:
            from .fused import opt_telemetry
            opt_telemetry()["dispatches"].inc(len(params_grads),
                                              mode="eager")
        for p, g in params_grads:
            group_lr = lr * getattr(p, "optimize_attr",
                                    {}).get("learning_rate", 1.0)
            slots = self._get_slots(p)
            self._step_t[id(p)] += 1
            t = self._step_t[id(p)]
            reg = getattr(p, "regularizer", None) or self.regularization
            if getattr(reg, "_l1", False):
                # L1: add coeff*sign(w) to the gradient; no L2 term
                coeff = float(getattr(reg, "_coeff", 0.0))
                g = Tensor(g._data + coeff * jnp.sign(p._data))
                wd = 0.0
            else:
                wd = self._wd_coeff(p) \
                    if getattr(p, "regularizer", None) is None \
                    else float(getattr(p.regularizer, "_coeff", 0.0))
            self._masterized_apply(p, g, slots, group_lr, t, wd)
        return None

    minimize = None  # set below

    def _minimize(self, loss, startup_program=None, parameters=None, no_grad_set=None):
        loss.backward()
        self.step()
        self.clear_grad()
        return None, None

    @no_grad()
    def clear_grad(self, set_to_zero=False):
        for p in self._parameter_list:
            p.grad = None

    clear_gradients = clear_grad

    # -- checkpointing -------------------------------------------------------
    def state_dict(self):
        out = {}
        for p in self._parameter_list:
            key = id(p)
            if key in self._slots:
                for sname, arr in self._slots[key].items():
                    out[f"{p.name}_{sname}"] = Tensor(arr)
                out[f"{p.name}_step"] = self._step_t[key]
        if isinstance(self._learning_rate, LRScheduler):
            out["LR_Scheduler"] = self._learning_rate.state_dict()
        return out

    def set_state_dict(self, state):
        if "LR_Scheduler" in state and isinstance(self._learning_rate, LRScheduler):
            self._learning_rate.set_state_dict(state["LR_Scheduler"])
        for p in self._parameter_list:
            slots = self._get_slots(p)
            for sname in list(slots):
                k = f"{p.name}_{sname}"
                if k in state:
                    v = state[k]
                    slots[sname] = v._data if isinstance(v, Tensor) else jnp.asarray(v)
            k = f"{p.name}_step"
            if k in state:
                self._step_t[id(p)] = int(state[k])

    set_dict = set_state_dict


Optimizer.minimize = Optimizer._minimize


class SGD(Optimizer):
    def _apply(self, p, g, slots, lr, t, wd):
        if wd:
            g = g + wd * p
        return p - lr * g, slots

    def _fused_delta(self, p, g, slots, lr, t, wd, decay=None):
        # staged fused step (optimizer/fused.py): ``decay`` is ``wd*p``
        # precomputed by a SEPARATE compiled program — inside one program
        # the CPU backend contracts add(mul(wd,p), g) into an fma even
        # across an HLO optimization_barrier (the barrier lowers to a
        # no-op before LLVM's contraction pass), which rounds differently
        # from the eager loop's two ops. ``lr*(g+decay)`` is a mul fed BY
        # an add (not an fma pattern), and the final ``p - delta``
        # compiles separately too, so plain SGD stays bit-identical to
        # the eager per-param loop.
        if decay is not None:
            g = g + decay
        return lr * g, slots


class Momentum(Optimizer):
    def __init__(self, learning_rate=0.001, momentum=0.9, parameters=None,
                 use_nesterov=False, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._momentum = momentum
        self._nesterov = use_nesterov

    def _init_slots(self, p):
        return {"velocity": jnp.zeros_like(p, dtype=jnp.float32)
                if p.dtype in (jnp.float16, jnp.bfloat16) else jnp.zeros_like(p)}

    def _apply(self, p, g, slots, lr, t, wd):
        if wd:
            g = g + wd * p
        v = self._momentum * slots["velocity"] + g
        if self._nesterov:
            p = p - lr * (g + self._momentum * v)
        else:
            p = p - lr * v
        return p, {**slots, "velocity": v}


class Adam(Optimizer):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon

    def _init_slots(self, p):
        f32 = p.dtype in (jnp.float16, jnp.bfloat16)
        z = jnp.zeros_like(p, dtype=jnp.float32) if f32 else jnp.zeros_like(p)
        return {"moment1": z, "moment2": z}

    def _decoupled(self):
        return False

    def _apply(self, p, g, slots, lr, t, wd):
        if wd and not self._decoupled():
            g = g + wd * p
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * g * g
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        if wd and self._decoupled():
            p = p * (1 - lr * wd)
        p = p - lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        return p, {**slots, "moment1": m, "moment2": v}


class AdamW(Adam):
    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999, epsilon=1e-8,
                 parameters=None, weight_decay=0.01, lr_ratio=None,
                 apply_decay_param_fun=None, grad_clip=None, lazy_mode=False,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, beta1, beta2, epsilon, parameters,
                         weight_decay, grad_clip, lazy_mode, multi_precision, name)
        self._apply_decay_param_fun = apply_decay_param_fun

    def _decoupled(self):
        return True

    @no_grad()
    def step(self):
        if self._apply_decay_param_fun is not None:
            # temporarily zero decay for excluded params via regularizer override
            saved = {}
            for p in self._parameter_list:
                if not self._apply_decay_param_fun(p.name):
                    saved[id(p)] = p.regularizer
                    p.regularizer = _ZeroDecay()
            try:
                super().step()
            finally:
                for p in self._parameter_list:
                    if id(p) in saved:
                        p.regularizer = saved[id(p)]
        else:
            super().step()


class _ZeroDecay:
    _coeff = 0.0


class Adamax(Adam):
    def _init_slots(self, p):
        return {"moment": jnp.zeros_like(p), "inf_norm": jnp.zeros_like(p)}

    def _apply(self, p, g, slots, lr, t, wd):
        if wd:
            g = g + wd * p
        m = self._beta1 * slots["moment"] + (1 - self._beta1) * g
        u = jnp.maximum(self._beta2 * slots["inf_norm"], jnp.abs(g))
        p = p - lr / (1 - self._beta1 ** t) * m / (u + self._epsilon)
        return p, {**slots, "moment": m, "inf_norm": u}


class Adagrad(Optimizer):
    def __init__(self, learning_rate, epsilon=1e-6, parameters=None,
                 weight_decay=None, grad_clip=None, initial_accumulator_value=0.0,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._epsilon = epsilon
        self._init_acc = initial_accumulator_value

    def _init_slots(self, p):
        return {"moment": jnp.full_like(p, self._init_acc)}

    def _apply(self, p, g, slots, lr, t, wd):
        if wd:
            g = g + wd * p
        acc = slots["moment"] + g * g
        p = p - lr * g / (jnp.sqrt(acc) + self._epsilon)
        return p, {**slots, "moment": acc}


class RMSProp(Optimizer):
    def __init__(self, learning_rate, rho=0.95, epsilon=1e-6, momentum=0.0,
                 centered=False, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._rho = rho
        self._epsilon = epsilon
        self._momentum = momentum
        self._centered = centered

    def _init_slots(self, p):
        return {"mean_square": jnp.zeros_like(p), "mean_grad": jnp.zeros_like(p),
                "momentum": jnp.zeros_like(p)}

    def _apply(self, p, g, slots, lr, t, wd):
        if wd:
            g = g + wd * p
        ms = self._rho * slots["mean_square"] + (1 - self._rho) * g * g
        if self._centered:
            mg = self._rho * slots["mean_grad"] + (1 - self._rho) * g
            denom = jnp.sqrt(ms - mg * mg + self._epsilon)
        else:
            mg = slots["mean_grad"]
            denom = jnp.sqrt(ms + self._epsilon)
        mom = self._momentum * slots["momentum"] + lr * g / denom
        return p - mom, {**slots, "mean_square": ms, "mean_grad": mg, "momentum": mom}


class Adadelta(Optimizer):
    def __init__(self, learning_rate=0.001, epsilon=1e-6, rho=0.95,
                 parameters=None, weight_decay=None, grad_clip=None,
                 multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._epsilon = epsilon
        self._rho = rho

    def _init_slots(self, p):
        return {"avg_squared_grad": jnp.zeros_like(p),
                "avg_squared_update": jnp.zeros_like(p)}

    def _apply(self, p, g, slots, lr, t, wd):
        if wd:
            g = g + wd * p
        asg = self._rho * slots["avg_squared_grad"] + (1 - self._rho) * g * g
        update = g * jnp.sqrt(slots["avg_squared_update"] + self._epsilon) / \
            jnp.sqrt(asg + self._epsilon)
        asu = self._rho * slots["avg_squared_update"] + (1 - self._rho) * update * update
        return p - lr * update, {**slots, "avg_squared_grad": asg,
                                 "avg_squared_update": asu}


class Lamb(Optimizer):
    def __init__(self, learning_rate=0.001, lamb_weight_decay=0.01, beta1=0.9,
                 beta2=0.999, epsilon=1e-6, parameters=None, grad_clip=None,
                 exclude_from_weight_decay_fn=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._beta1 = beta1
        self._beta2 = beta2
        self._epsilon = epsilon
        self._lamb_wd = lamb_weight_decay
        self._exclude_fn = exclude_from_weight_decay_fn
        self._excluded_now = set()
        self._current_param = None

    def _init_slots(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p)}

    @no_grad()
    def step(self):
        from ..profiler import step_phase as _step_phase
        from ..profiler import ledger as _ledger
        _t0 = _step_phase.clock()
        try:
            self._lamb_step_impl()
            if _ledger.is_enabled():
                _ledger.record_optimizer_step(self)
        finally:
            if _t0 is not None:
                import time as _time
                _step_phase.record_phase("optimizer",
                                         _time.perf_counter() - _t0)

    def _lamb_step_impl(self):
        # resolve exclude_from_weight_decay_fn per parameter before updates
        if self._exclude_fn is not None:
            self._excluded_now = {id(p) for p in self._parameter_list
                                  if self._exclude_fn(p)}
        else:
            self._excluded_now = set()
        self._current_param = None
        # accept plain Tensors with stop_gradient=False, like the
        # reference (Parameter.trainable; Tensor -> not stop_gradient)
        params_grads = [(p, p.grad) for p in self._parameter_list
                        if p.grad is not None
                        and getattr(p, "trainable", not p.stop_gradient)]
        if self._grad_clip is not None:
            params_grads = self._grad_clip(params_grads)
        lr = self.get_lr()
        for p, g in params_grads:
            self._current_param = p
            slots = self._get_slots(p)
            self._step_t[id(p)] += 1
            self._masterized_apply(p, g, slots, lr,
                                   self._step_t[id(p)], 0.0)

    def _apply(self, p, g, slots, lr, t, wd):
        m = self._beta1 * slots["moment1"] + (1 - self._beta1) * g
        v = self._beta2 * slots["moment2"] + (1 - self._beta2) * g * g
        mhat = m / (1 - self._beta1 ** t)
        vhat = v / (1 - self._beta2 ** t)
        wd_coeff = 0.0 if (self._current_param is not None
                           and id(self._current_param) in self._excluded_now) \
            else self._lamb_wd
        r = mhat / (jnp.sqrt(vhat) + self._epsilon) + wd_coeff * p
        w_norm = jnp.linalg.norm(p)
        r_norm = jnp.linalg.norm(r)
        trust = jnp.where((w_norm > 0) & (r_norm > 0), w_norm / r_norm, 1.0)
        return p - lr * trust * r, {**slots, "moment1": m, "moment2": v}


# regularizers (paddle.regularizer)
class L2Decay:
    def __init__(self, coeff=0.0):
        self._coeff = coeff


class L1Decay:
    _l1 = True       # step() applies coeff*sign(w) — same contract as
                     # paddle_tpu.regularizer.L1Decay

    def __init__(self, coeff=0.0):
        self._coeff = coeff

from .extras import Rprop, ASGD, NAdam, RAdam, LBFGS  # noqa: E402,F401
