"""Fused, buffer-donated optimizer step.

The eager ``Optimizer.step`` loop issues O(params) host dispatches per
step (each ``_apply`` is a handful of jnp calls per tensor) — on a
100+-parameter model that Python-side dispatch tail is a measurable slice
of step time (the Gemma-on-TPU study's "fused weight update" gap). This
module collapses it to O(1) compiled calls: parameters are grouped by
update signature (dtype, per-group lr multiplier, weight-decay
coefficient), and each group runs ONE jitted program that unrolls the
optimizer's functional ``_apply`` over the whole group, with the old
parameter and slot buffers donated to XLA (``utils.donation.donated_jit``)
so the update is in-place in HBM.

Exotic param groups fall back to the eager per-parameter loop: L1
regularization (gradient rewrite outside the functional core),
``multi_precision`` master weights, and duplicate parameter occurrences
(donating one buffer twice is undefined).

Numerics: inside one compiled program XLA contracts mul+add chains into
FMAs (on CPU this happens in the LLVM backend, so even an HLO
``optimization_barrier`` between the mul and the add does not stop it)
and evaluates scalar schedule math (e.g. Adam's bias-correction powers)
in f32 where the eager loop's python floats carry f64, so a generic
fused ``_apply`` can differ from the eager per-op loop at f32 rounding
level (~1e-5 relative worst case observed). Optimizers that define
``_fused_delta`` (SGD) instead split the update so no compiled program
ever contains a contractible mul+add pair: an optional decay program
(``wd*p`` alone), a delta program (``lr*(g+decay)`` — a mul fed by an
add, not an fma pattern), and a bare ``p - delta`` combine. SGD thus
stays BIT-IDENTICAL to the eager loop (the overlap/fused parity
contract the dp-sim tests pin down), at 2-3 dispatches per group —
still O(1).

Engagement policy (``Optimizer._use_fused``): ``PADDLE_FUSED_STEP`` —
``auto`` (default: fuse when the step covers at least
``PADDLE_FUSED_STEP_MIN_PARAMS``, default 16, parameters — below that the
one-off trace costs more than the dispatches it saves), ``1`` force on,
``0`` off. Per-instance override: ``opt.fuse_step = True/False``.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

_OPT_TELEMETRY = None


def opt_telemetry():
    """Lazily bound dispatch counters: ``mode="eager"`` counts per-param
    updates, ``mode="fused"`` counts compiled group calls — the ratio is
    the host-dispatch collapse ``BENCH_MODEL=comm`` reports."""
    global _OPT_TELEMETRY
    if _OPT_TELEMETRY is None:
        from ..profiler.telemetry import get_registry
        r = get_registry()
        _OPT_TELEMETRY = {
            "dispatches": r.counter(
                "paddle_opt_step_dispatches_total",
                "optimizer update dispatches (eager: one per parameter; "
                "fused: one per compiled group call)", labels=("mode",)),
        }
    return _OPT_TELEMETRY


class FusedStepEngine:
    """Per-optimizer cache of jitted, donated group-update programs."""

    def __init__(self, optimizer):
        self._opt = optimizer
        self._jitted = {}     # (lr_mult, wd) -> donated-jit callable

    def step(self, params_grads, lr):
        """Run the fusable subset of ``params_grads`` through compiled
        group updates; return the (possibly empty) eager leftover list."""
        opt = self._opt
        groups: dict = {}
        leftover = []
        seen = set()
        for p, g in params_grads:
            slots = opt._get_slots(p)
            reg = getattr(p, "regularizer", None) or opt.regularization
            if (getattr(reg, "_l1", False) or "master" in slots
                    or id(p) in seen):
                leftover.append((p, g))
                continue
            seen.add(id(p))
            lr_mult = float(getattr(p, "optimize_attr",
                                    {}).get("learning_rate", 1.0))
            if getattr(p, "regularizer", None) is None:
                wd = opt._wd_coeff(p)
            else:
                wd = float(getattr(p.regularizer, "_coeff", 0.0))
            groups.setdefault((lr_mult, wd), []).append((p, g))
        for key, pg in groups.items():
            self._run_group(key, pg, lr)
        return leftover

    def _run_group(self, key, pg, lr):
        opt = self._opt
        lr_mult, wd = key
        ps = [p for p, _ in pg]
        g_arrs = [g._data for _, g in pg]
        p_arrs = [p._data for p in ps]
        slot_list = _dedupe_donated([opt._slots[id(p)] for p in ps],
                                    p_arrs, g_arrs)
        ts = []
        for p in ps:
            opt._step_t[id(p)] += 1
            ts.append(opt._step_t[id(p)])
        fns = self._jitted.get(key)
        if fns is None:
            fns = self._jitted[key] = self._build(wd)
        # lr and t travel as traced arrays so LR schedules / step advance
        # never retrace; shape changes (param-set growth) retrace via
        # jit's own cache
        lr_arr = jnp.asarray(lr * lr_mult, jnp.float32)
        t_arr = jnp.asarray(ts, jnp.float32)
        tele = opt_telemetry()["dispatches"]
        if len(fns) == 3:     # staged delta path: decay?, deltas, combine
            decay_fn, delta_fn, combine_fn = fns
            decay_arrs = decay_fn(p_arrs) if decay_fn is not None else None
            deltas, new_slots = delta_fn(p_arrs, g_arrs, decay_arrs,
                                         slot_list, lr_arr, t_arr)
            new_ps = combine_fn(p_arrs, deltas)
            tele.inc(2 if decay_fn is None else 3, mode="fused")
        else:
            (fn,) = fns
            new_ps, new_slots = fn(p_arrs, g_arrs, slot_list, lr_arr, t_arr)
            tele.inc(mode="fused")
        for p, new_p, ns in zip(ps, new_ps, new_slots):
            p._data = new_p
            opt._slots[id(p)] = ns

    def _build(self, wd):
        from ..utils.donation import donated_jit
        delta_fn = getattr(self._opt, "_fused_delta", None)
        if delta_fn is not None:
            # the weight-decay product compiles ALONE: sharing a program
            # with the ``g + decay`` add would let the backend contract
            # the pair into an fma and break eager bit-parity (see
            # module docstring)
            decay_jit = None
            if wd:
                def decay_terms(p_arrs):
                    return [wd * p for p in p_arrs]
                decay_jit = jax.jit(decay_terms)

            def deltas(p_arrs, g_arrs, decay_arrs, slot_list, lr, t_arr):
                out_d, out_s = [], []
                for k in range(len(p_arrs)):
                    d, ns = delta_fn(
                        p_arrs[k], g_arrs[k], slot_list[k], lr, t_arr[k],
                        wd, decay=None if decay_arrs is None
                        else decay_arrs[k])
                    out_d.append(d)
                    out_s.append(ns)
                return out_d, out_s

            def combine(p_arrs, d_arrs):
                return [p - d for p, d in zip(p_arrs, d_arrs)]

            # p survives the decay/delta programs (the combine needs it),
            # so only slots (and the dead decay terms) are donated there;
            # the combine donates p (deltas die by refcount — donating
            # both would leave half unusable)
            return (decay_jit,
                    donated_jit(deltas, donate_argnums=(2, 3)),
                    donated_jit(combine, donate_argnums=(0,)))

        apply_fn = self._opt._apply

        def fused(p_arrs, g_arrs, slot_list, lr, t_arr):
            new_ps, new_slots = [], []
            for k in range(len(p_arrs)):
                new_p, ns = apply_fn(p_arrs[k], g_arrs[k], slot_list[k],
                                     lr, t_arr[k], wd)
                new_ps.append(new_p)
                new_slots.append(ns)
            return new_ps, new_slots

        return (donated_jit(fused, donate_argnums=(0, 2)),)


def _dedupe_donated(slot_list, p_arrs, g_arrs):
    """Donated buffers must be unique: fresh slot inits can alias (e.g. a
    shared zeros constant for moment1/moment2) — replace repeat
    occurrences with a private copy before donation."""
    seen = {id(a) for a in p_arrs} | {id(a) for a in g_arrs}
    out = []
    for slots in slot_list:
        fixed = {}
        for name, arr in slots.items():
            if id(arr) in seen:
                arr = jnp.array(arr, copy=True)
            seen.add(id(arr))
            fixed[name] = arr
        out.append(fixed)
    return out
