"""Optimizer breadth batch (reference: ``python/paddle/optimizer/`` —
``rprop.py``, ``asgd.py``, ``nadam.py``, ``radam.py``, ``lbfgs.py``)."""
from __future__ import annotations

import math

import jax.numpy as jnp
import numpy as np

from . import Optimizer


class Rprop(Optimizer):
    """Resilient backprop: per-element step sizes grown/shrunk by the
    gradient sign agreement (reference ``paddle.optimizer.Rprop``)."""

    def __init__(self, learning_rate=0.001,
                 learning_rate_range=(1e-5, 50.0), parameters=None,
                 etas=(0.5, 1.2), grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, None, grad_clip, name,
                         multi_precision)
        self._lr_range = learning_rate_range
        self._etas = etas

    def _init_slots(self, p):
        try:
            lr0 = float(self.get_lr())
        except TypeError:
            lr0 = 0.001
        return {"prev_grad": jnp.zeros_like(p),
                "step_size": jnp.full_like(p, lr0)}

    def _apply(self, p, g, slots, lr, t, wd):
        eta_neg, eta_pos = self._etas
        lo, hi = self._lr_range
        sign = jnp.sign(g * slots["prev_grad"])
        factor = jnp.where(sign > 0, eta_pos,
                           jnp.where(sign < 0, eta_neg, 1.0))
        step = jnp.clip(slots["step_size"] * factor, lo, hi)
        # on sign change: zero the gradient for this step (classic Rprop-)
        g_eff = jnp.where(sign < 0, 0.0, g)
        p = p - jnp.sign(g_eff) * step
        return p, {"prev_grad": g_eff, "step_size": step}


class ASGD(Optimizer):
    """SGD over the average of the last ``batch_num`` gradients
    (reference ``paddle.optimizer.ASGD``: a circular gradient buffer of
    ``batch_num`` entries, update with the running mean)."""

    def __init__(self, learning_rate=0.001, batch_num=1, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._n = max(int(batch_num), 1)

    def _init_slots(self, p):
        return {"grad_sum": jnp.zeros_like(p),
                "buffer": jnp.zeros((self._n,) + p.shape, p.dtype)}

    def _apply(self, p, g, slots, lr, t, wd):
        if wd:
            g = g + wd * p
        idx = (t - 1) % self._n
        old = slots["buffer"][idx]
        gsum = slots["grad_sum"] - old + g
        buf = slots["buffer"].at[idx].set(g)
        denom = min(t, self._n)
        p = p - lr * gsum / denom
        return p, {"grad_sum": gsum, "buffer": buf}


class NAdam(Optimizer):
    """Adam with Nesterov momentum (reference ``paddle.optimizer.NAdam``,
    Dozat 2016 momentum-decay schedule)."""

    def __init__(self, learning_rate=0.002, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, momentum_decay=0.004, parameters=None,
                 weight_decay=None, grad_clip=None, multi_precision=False,
                 name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2 = beta1, beta2
        self._epsilon = epsilon
        self._psi = momentum_decay

    def _init_slots(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p),
                "mu_prod": jnp.ones((), jnp.float32)}

    def _apply(self, p, g, slots, lr, t, wd):
        if wd:
            g = g + wd * p
        b1, b2 = self._beta1, self._beta2
        mu_t = b1 * (1 - 0.5 * 0.96 ** (t * self._psi))
        mu_next = b1 * (1 - 0.5 * 0.96 ** ((t + 1) * self._psi))
        mu_prod = slots["mu_prod"] * mu_t
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * g * g
        mhat = (mu_next * m / (1 - mu_prod * mu_next)
                + (1 - mu_t) * g / (1 - mu_prod))
        vhat = v / (1 - b2 ** t)
        p = p - lr * mhat / (jnp.sqrt(vhat) + self._epsilon)
        return p, {"moment1": m, "moment2": v, "mu_prod": mu_prod}


class RAdam(Optimizer):
    """Rectified Adam (reference ``paddle.optimizer.RAdam``, Liu 2020:
    variance-rectification term, SGD-with-momentum fallback early on)."""

    def __init__(self, learning_rate=0.001, beta1=0.9, beta2=0.999,
                 epsilon=1e-8, parameters=None, weight_decay=None,
                 grad_clip=None, multi_precision=False, name=None):
        super().__init__(learning_rate, parameters, weight_decay, grad_clip,
                         name, multi_precision)
        self._beta1, self._beta2 = beta1, beta2
        self._epsilon = epsilon

    def _init_slots(self, p):
        return {"moment1": jnp.zeros_like(p), "moment2": jnp.zeros_like(p)}

    def _apply(self, p, g, slots, lr, t, wd):
        if wd:
            g = g + wd * p
        b1, b2 = self._beta1, self._beta2
        m = b1 * slots["moment1"] + (1 - b1) * g
        v = b2 * slots["moment2"] + (1 - b2) * g * g
        mhat = m / (1 - b1 ** t)
        rho_inf = 2.0 / (1 - b2) - 1.0
        rho_t = rho_inf - 2.0 * t * (b2 ** t) / (1 - b2 ** t)
        if rho_t > 5.0:
            vhat = jnp.sqrt(v / (1 - b2 ** t))
            r = math.sqrt(((rho_t - 4) * (rho_t - 2) * rho_inf)
                          / ((rho_inf - 4) * (rho_inf - 2) * rho_t))
            p = p - lr * r * mhat / (vhat + self._epsilon)
        else:
            p = p - lr * mhat
        return p, {"moment1": m, "moment2": v}


class LBFGS(Optimizer):
    """Limited-memory BFGS with closure-based ``step`` (reference
    ``paddle.optimizer.LBFGS``: two-loop recursion over a bounded
    (s, y) history; optional strong-Wolfe line search)."""

    def __init__(self, learning_rate=1.0, max_iter=20, max_eval=None,
                 tolerance_grad=1e-7, tolerance_change=1e-9,
                 history_size=100, line_search_fn=None, parameters=None,
                 weight_decay=None, grad_clip=None, name=None):
        if grad_clip is not None:
            raise ValueError(
                "LBFGS: grad_clip is incompatible with the closure-based "
                "line search (clipping would break the Wolfe conditions)")
        super().__init__(learning_rate, parameters, weight_decay, None,
                         name, False)
        self._wd = self._wd_coeff(None)   # number or L2Decay-style object
        self.max_iter = max_iter
        self.max_eval = max_eval or max_iter * 5 // 4
        self.tol_grad = tolerance_grad
        self.tol_change = tolerance_change
        self.history_size = history_size
        self.line_search_fn = line_search_fn
        self._s, self._y = [], []
        self._prev_flat_grad = None

    # -- flat helpers --------------------------------------------------------
    def _params(self):
        return [p for p in self._parameter_list
                if getattr(p, "trainable", not p.stop_gradient)]

    def _gather_flat_grad(self):
        # a parameter outside the closure's loss has grad None -> zeros
        flat = jnp.concatenate([
            jnp.ravel(p.grad._data) if p.grad is not None
            else jnp.zeros(int(np.prod(p.shape)) if p.shape else 1,
                           jnp.float32)
            for p in self._params()])
        if self._wd:
            flat = flat + self._wd * self._flat_params()
        return flat

    def _flat_params(self):
        return jnp.concatenate([jnp.ravel(p._data) for p in self._params()])

    def _set_flat_params(self, flat):
        off = 0
        for p in self._params():
            n = int(np.prod(p.shape)) if p.shape else 1
            p._data = flat[off:off + n].reshape(p.shape).astype(p.dtype)
            off += n

    def _direction(self, flat_grad):
        """Two-loop recursion: H·g over the stored (s, y) pairs."""
        q = flat_grad
        alphas = []
        for s, y in reversed(list(zip(self._s, self._y))):
            rho = 1.0 / jnp.maximum(jnp.vdot(y, s), 1e-10)
            a = rho * jnp.vdot(s, q)
            alphas.append((a, rho))
            q = q - a * y
        if self._s:
            s, y = self._s[-1], self._y[-1]
            gamma = jnp.vdot(s, y) / jnp.maximum(jnp.vdot(y, y), 1e-10)
            q = q * gamma
        for (a, rho), (s, y) in zip(reversed(alphas),
                                    zip(self._s, self._y)):
            b = rho * jnp.vdot(y, q)
            q = q + s * (a - b)
        return -q

    def _eval(self, closure, flat):
        """Set params to ``flat`` and re-evaluate. The closure follows the
        reference contract: clear grads, run forward, call backward, and
        return the loss tensor."""
        self._set_flat_params(flat)
        loss = closure()
        return float(loss.numpy()), self._gather_flat_grad()

    def step(self, closure):
        """Run up to ``max_iter`` L-BFGS iterations; returns final loss."""
        loss, flat_grad = self._eval(closure, self._flat_params())
        evals = 1
        for _ in range(self.max_iter):
            if float(jnp.max(jnp.abs(flat_grad))) <= self.tol_grad:
                break
            d = self._direction(flat_grad)
            x0 = self._flat_params()
            g0_dot_d = float(jnp.vdot(flat_grad, d))
            if g0_dot_d > -1e-15:      # not a descent direction: reset
                self._s, self._y = [], []
                d = -flat_grad
                g0_dot_d = float(jnp.vdot(flat_grad, d))
            lr = float(self.get_lr())
            if self.line_search_fn == "strong_wolfe":
                c1, c2 = 1e-4, 0.9
                t = lr
                t_eval = None            # step the params CURRENTLY sit at
                for _ls in range(20):
                    new_loss, new_grad = self._eval(closure, x0 + t * d)
                    t_eval = t
                    evals += 1
                    if new_loss > loss + c1 * t * g0_dot_d:
                        t *= 0.5          # Armijo failed: shrink
                    elif abs(float(jnp.vdot(new_grad, d))) \
                            > c2 * abs(g0_dot_d):
                        # curvature failed: widen/shrink and retry
                        t *= 2.0 if float(jnp.vdot(new_grad, d)) \
                            < 0 else 0.5
                    else:
                        break             # both Wolfe conditions hold
                    if evals >= self.max_eval:
                        break
                if t != t_eval:
                    # loop exited right after proposing a new t: evaluate
                    # it so params/loss/grad and the (s, y) pair agree
                    new_loss, new_grad = self._eval(closure, x0 + t * d)
                    t_eval = t
                    evals += 1
                t = t_eval
            else:
                t = lr
                new_loss, new_grad = self._eval(closure, x0 + t * d)
                evals += 1
            s = t * d
            y = new_grad - flat_grad
            if float(jnp.vdot(s, y)) > 1e-10:
                self._s.append(s)
                self._y.append(y)
                if len(self._s) > self.history_size:
                    self._s.pop(0)
                    self._y.pop(0)
            if abs(new_loss - loss) < self.tol_change:
                loss, flat_grad = new_loss, new_grad
                break
            loss, flat_grad = new_loss, new_grad
            if evals >= self.max_eval:
                break
        from ..framework.core import Tensor
        return Tensor(jnp.asarray(loss, jnp.float32))
