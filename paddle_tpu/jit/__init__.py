"""paddle.jit (reference: ``python/paddle/jit/`` — SURVEY.md §2.2/§3.2).

``to_static`` traces through jax.jit (see api.py). ``jit.save``/``jit.load``
replace the ``.pdmodel`` ProgramDesc format with serialized StableHLO via
``jax.export`` + a params file — the TPU-native inference-export path
(SURVEY.md §7.1 M1); ``.pdmodel`` reading is explicitly out of scope.
"""
from __future__ import annotations

import os
import pickle

import numpy as np
import jax
import jax.numpy as jnp

from .api import (  # noqa: F401
    to_static, not_to_static, ignore_module, StaticFunction, InputSpec,
    enable_static, disable_static, in_dynamic_mode, in_to_static_mode,
    enable_to_static,
)
from ..framework.core import Tensor
from ..framework import io as fio
from ..nn.layer import Layer

SUFFIX_PARAMS = ".pdiparams"
SUFFIX_MODEL = ".pdmodel.stablehlo"
SUFFIX_META = ".pdmeta"


def save(layer, path, input_spec=None, **configs):
    """paddle.jit.save — export a Layer (or plain function / StaticFunction)
    for inference.

    Writes: ``{path}.pdiparams`` (state dict), ``{path}.pdmodel.stablehlo``
    (serialized jax.export artifact of the eval-mode forward, parameters as
    runtime inputs), ``{path}.pdmeta`` (specs)."""
    from jax import export as jexport

    if not isinstance(layer, Layer):
        fn = layer._orig_fn if isinstance(layer, StaticFunction) else layer
        if not callable(fn):
            raise TypeError("jit.save expects a Layer, function, or "
                            "StaticFunction")
        return _save_function(fn, path, input_spec)
    was_training = layer.training
    layer.eval()
    try:
        fwd = layer.forward
        sf = fwd if isinstance(fwd, StaticFunction) else StaticFunction(layer)
        if input_spec is None:
            raise ValueError("jit.save requires input_spec")
        specs = [s if isinstance(s, InputSpec) else InputSpec.from_tensor(s)
                 for s in input_spec]
        example = [jnp.zeros([1 if d is None else d for d in s.shape],
                             s.dtype or jnp.float32) for s in specs]
        params = [p for p in layer.parameters() if p is not None]
        bufs = [b for b in layer.buffers() if b is not None]

        def infer_fn(p_arrs, b_arrs, *inputs):
            saved = [t._data for t in params + bufs]
            try:
                for t, a in zip(params, p_arrs):
                    t._data = a
                for t, a in zip(bufs, b_arrs):
                    t._data = a
                from ..autograd.tape import no_grad
                with no_grad():
                    out = layer._dygraph_forward(*[Tensor(i) for i in inputs]) \
                        if hasattr(layer, "_dygraph_forward") \
                        else layer.forward(*[Tensor(i) for i in inputs])
                return jax.tree.map(lambda t: t._data if isinstance(t, Tensor) else t,
                                    out, is_leaf=lambda x: isinstance(x, Tensor))
            finally:
                for t, a in zip(params + bufs, saved):
                    t._data = a

        jitted = jax.jit(infer_fn)
        # canonicalize state to host-backed single-device arrays: params
        # trained under a multi-device mesh carry shardings, and tracing
        # with them bakes an N-device requirement into the export (the
        # loaded artifact must run on a single chip)
        p_ex = [jnp.asarray(jax.device_get(p._data)) for p in params]
        b_ex = [jnp.asarray(jax.device_get(b._data)) for b in bufs]
        exported = jexport.export(jitted)(p_ex, b_ex, *example)
        blob = exported.serialize()
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        with open(path + SUFFIX_MODEL, "wb") as f:
            f.write(blob)
        fio.save(layer.state_dict(), path + SUFFIX_PARAMS)
        meta = {
            "param_names": [p.name for p in params],
            "param_keys": [k for k, _ in layer.state_dict().items()],
            "n_params": len(params),
            "n_bufs": len(bufs),
            "input_specs": [(s.shape, np.dtype(s.dtype or np.float32).name,
                             getattr(s, "name", None))
                            for s in specs],
        }
        with open(path + SUFFIX_META, "wb") as f:
            pickle.dump(meta, f)
    finally:
        if was_training:
            layer.train()


def _save_function(fn, path, input_spec):
    """Export a parameterless Tensor-function as StableHLO."""
    from jax import export as jexport
    from ..autograd.tape import no_grad

    if input_spec is None:
        raise ValueError("jit.save requires input_spec")
    specs = [s if isinstance(s, InputSpec) else InputSpec.from_tensor(s)
             for s in input_spec]
    example = [jnp.zeros([1 if d is None else d for d in s.shape],
                         s.dtype or jnp.float32) for s in specs]

    def infer_fn(*inputs):
        with no_grad():
            out = fn(*[Tensor(i) for i in inputs])
        return jax.tree.map(lambda t: t._data if isinstance(t, Tensor) else t,
                            out, is_leaf=lambda x: isinstance(x, Tensor))

    exported = jexport.export(jax.jit(infer_fn))(*example)
    d = os.path.dirname(path)
    if d:
        os.makedirs(d, exist_ok=True)
    with open(path + SUFFIX_MODEL, "wb") as f:
        f.write(exported.serialize())
    fio.save({}, path + SUFFIX_PARAMS)
    meta = {"param_names": [], "param_keys": [], "n_params": 0, "n_bufs": 0,
            "is_function": True,
            "input_specs": [(s.shape, np.dtype(s.dtype or np.float32).name,
                             getattr(s, "name", None))
                            for s in specs]}
    with open(path + SUFFIX_META, "wb") as f:
        pickle.dump(meta, f)


class TranslatedLayer(Layer):
    """Result of jit.load: a Layer whose forward runs the exported StableHLO."""

    def __init__(self, exported, params, bufs, meta):
        super().__init__()
        self._exported = exported
        self._params_list = params
        self._bufs_list = bufs
        self._meta = meta
        for i, p in enumerate(params):
            self.add_parameter(f"p{i}", p)

    def forward(self, *inputs):
        arrs = [t._data if isinstance(t, Tensor) else jnp.asarray(t) for t in inputs]
        if self._meta.get("is_function"):
            out = self._exported.call(*arrs)
        else:
            out = self._exported.call([p._data for p in self._params_list],
                                      [b._data for b in self._bufs_list],
                                      *arrs)
        return jax.tree.map(Tensor, out)


def load(path, **configs):
    from jax import export as jexport
    from ..framework.core import Parameter

    with open(path + SUFFIX_MODEL, "rb") as f:
        exported = jexport.deserialize(f.read())
    with open(path + SUFFIX_META, "rb") as f:
        meta = pickle.load(f)
    state = fio.load(path + SUFFIX_PARAMS)
    n_p = meta["n_params"]
    keys = meta["param_keys"]
    params = [Parameter(state[k]._data if isinstance(state[k], Tensor)
                        else state[k]) for k in keys[:n_p]]
    bufs = [Tensor(state[k]._data if isinstance(state[k], Tensor) else state[k])
            for k in keys[n_p:n_p + meta["n_bufs"]]]
    return TranslatedLayer(exported, params, bufs, meta)
