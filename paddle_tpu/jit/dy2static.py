"""Dynamic-to-static control-flow conversion (reference:
``python/paddle/jit/dy2static/transformers/`` — ``IfElseTransformer`` /
``LoopTransformer`` rewriting tensor-dependent ``if``/``while`` into
``cond`` / ``while_loop`` ops; SURVEY.md §2.2 "jit/dy2static", §3.2).

TPU-native design: the reference rewrites Python AST into Program-IR
control-flow ops. Here the jit tracer (``jit/api.py``) already handles
straight-line code; this module supplies the missing piece — when tracing
hits a *data-dependent branch* (``TracerBoolConversionError``), the
function is AST-rewritten so that

* ``if <tensor>:`` runs both arms through ``jax.lax.cond``, threading
  every name either arm assigns as explicit operands/results, and
* ``while <tensor>:`` runs through ``jax.lax.while_loop`` with the
  body-assigned names as the carry (Python scalars entering the carry are
  promoted to traced arrays, matching the reference's
  ``to_static_variable`` promotion),

while Python-valued conditions keep exact Python semantics (single-arm
execution, native loop). The rewritten function replaces the eager
fallback, so a model with a data-dependent branch stays ONE compiled
program instead of silently de-optimizing (VERDICT round-3 item 4).

Same caveats as the reference's converter: under a tensor condition both
arms are traced (side effects on Python state leak from the untaken
branch); arm results must match in shape/dtype; ``return``/``break``/
``continue`` inside a converted region are not converted (that construct
is left as plain Python — a tensor condition there still graph-breaks).
"""
from __future__ import annotations

import ast
import inspect
import textwrap

import jax
import jax.numpy as jnp

from ..framework.core import Tensor


class ConversionUnsupported(Exception):
    """Raised when a function has no convertible control flow (or cannot
    be source-rewritten at all) — callers fall back to eager."""


class _Undef:
    """Placeholder for a name with no binding yet when a converted region
    threads it. Any actual *use* must fail the way the unconverted code
    would (NameError), not silently act as a truthy object."""
    __slots__ = ()

    def __repr__(self):
        return "<undefined>"

    def __bool__(self):
        raise NameError("variable is unbound on this path (it is only "
                        "assigned inside an unexecuted branch)")

    def __getattr__(self, name):
        raise NameError("variable is unbound on this path (it is only "
                        "assigned inside an unexecuted branch)")


_UNDEF = _Undef()


def _is_tensor(x):
    return isinstance(x, Tensor)


def _is_traced(x):
    a = x._data if isinstance(x, Tensor) else x
    return isinstance(a, jax.core.Tracer)


def _scalar_pred(pred, ctx):
    a = pred._data if isinstance(pred, Tensor) else pred
    if getattr(a, "size", 1) != 1:
        raise ValueError(
            f"The truth value of a multi-element tensor {ctx} is ambiguous "
            f"(shape {a.shape})")
    return a.reshape(()) if getattr(a, "shape", ()) != () else a


# ---------------------------------------------------------------------------
# runtime: if / while dispatchers (injected into rewritten code)
# ---------------------------------------------------------------------------

def _flatten_vals(vals):
    flat, treedef = jax.tree.flatten(tuple(vals), is_leaf=_is_tensor)
    t_idx = [i for i, l in enumerate(flat) if isinstance(l, Tensor)]
    arrs = tuple(flat[i]._data for i in t_idx)
    sgs = [flat[i].stop_gradient for i in t_idx]
    return flat, treedef, t_idx, arrs, sgs


def _rebuild_vals(flat, treedef, t_idx, sgs, arrs):
    nf = list(flat)
    for i, a, sg in zip(t_idx, arrs, sgs):
        t = Tensor(a)
        t.stop_gradient = sg
        nf[i] = t
    return jax.tree.unflatten(treedef, nf)


def _jst_peek(get):
    """Resolve a read-only name exactly the way the original scope would
    (``get`` is ``lambda: name`` — local/closure/global/builtin lookup is
    the compiler's own), yielding ``_UNDEF`` when unbound (any later *use*
    then raises NameError via :class:`_Undef`)."""
    try:
        return get()
    except NameError:
        return _UNDEF


def _jst_if(pred, true_fn, false_fn, vals, names):
    """``if`` dispatcher: Python condition → run ONE arm natively; traced
    condition → ``lax.cond`` over both arms (reference ``convert_ifelse``).

    ``vals``/``names``: the assigned names (threaded in AND out — the
    arms return exactly these) followed by names the arms only read,
    passed as operands so the tape's cond node has edges to every
    differentiable input (an in-trace ``paddle.grad`` needs them)."""
    if not (_is_traced(pred) if isinstance(pred, Tensor)
            else isinstance(pred, jax.core.Tracer)):
        return tuple(true_fn(*vals)) if bool(pred) else tuple(false_fn(*vals))

    p = _scalar_pred(pred, "used as an `if` condition")
    flat, treedef, t_idx, arrs, sgs = _flatten_vals(vals)
    statics = [None, None]

    def arm(which, fn):
        def g(arrs_in):
            out = fn(*_rebuild_vals(flat, treedef, t_idx, sgs, arrs_in))
            o_flat, o_def = jax.tree.flatten(tuple(out), is_leaf=_is_tensor)
            o_arrs = tuple(l._data for l in o_flat if isinstance(l, Tensor))
            statics[which] = (o_def, tuple(
                None if isinstance(l, Tensor) else l for l in o_flat),
                tuple(l.stop_gradient for l in o_flat
                      if isinstance(l, Tensor)))
            return o_arrs
        return g

    def cond_arrays(*arrs_in):
        return jax.lax.cond(p != 0, arm(0, true_fn), arm(1, false_fn),
                            tuple(arrs_in))

    # route through the tape so an in-trace ``paddle.grad`` sees ONE
    # differentiable node for the whole cond (jax.vjp through lax.cond)
    from ..autograd.tape import apply as tape_apply
    try:
        out_ts = tape_apply(cond_arrays, *(flat[i] for i in t_idx),
                            op_name="dy2static_cond")
    except TypeError as e:
        raise TypeError(
            f"tensor-dependent `if`: the two branches must produce "
            f"matching shapes/dtypes for {names}: {e}") from None
    (o_def, o_static, o_sg), (f_def, f_static, _) = statics
    if o_def != f_def or o_static != f_static:
        raise ValueError(
            f"tensor-dependent `if`: every variable in {list(names)} must "
            f"be assigned a matching tensor in BOTH branches (one branch "
            f"leaves it undefined or Python-valued)")
    o_leaves = list(o_static)
    it = iter(jax.tree.leaves(out_ts, is_leaf=_is_tensor))
    o_leaves = [next(it) if l is None else l for l in o_leaves]
    for l, sg in zip((x for x in o_leaves if isinstance(x, Tensor)), o_sg):
        l.stop_gradient = sg
    return jax.tree.unflatten(o_def, o_leaves)


def _jst_while(cond_fn, body_fn, vals, names, n_carry):
    """``while`` dispatcher: Python condition → native loop; traced
    condition → ``lax.while_loop``. The first ``n_carry`` of ``vals`` are
    the body-assigned names (the carry); the rest are read-only loop
    invariants (operands for tape-edge completeness). Python int/float/
    bool carry entries are promoted to traced arrays (reference
    ``to_static_variable``) so counters work."""
    c0 = cond_fn(*vals)
    if not (_is_traced(c0) if isinstance(c0, Tensor)
            else isinstance(c0, jax.core.Tracer)):
        vals = list(vals)
        while bool(c0):
            vals[:n_carry] = tuple(body_fn(*vals))
            c0 = cond_fn(*vals)
        return tuple(vals[:n_carry])

    def promote(vs):
        return tuple(Tensor(jnp.asarray(v))
                     if isinstance(v, (bool, int, float)) else v for v in vs)

    carry = promote(vals[:n_carry])
    rest = tuple(vals[n_carry:])
    c_flat, c_def, c_idx, c_arrs, c_sgs = _flatten_vals(carry)
    r_flat, r_def, r_idx, r_arrs, r_sgs = _flatten_vals(rest)
    statics_in = tuple(None if isinstance(l, Tensor) else l for l in c_flat)

    def while_arrays(*arrs_in):
        ac, ar = arrs_in[:len(c_idx)], arrs_in[len(c_idx):]
        rest_v = _rebuild_vals(r_flat, r_def, r_idx, r_sgs, ar)

        def cond_w(carry_arrs):
            cv = _rebuild_vals(c_flat, c_def, c_idx, c_sgs, carry_arrs)
            c = cond_fn(*cv, *rest_v)
            return _scalar_pred(c, "used as a `while` condition") != 0

        def body_w(carry_arrs):
            cv = _rebuild_vals(c_flat, c_def, c_idx, c_sgs, carry_arrs)
            out = promote(body_fn(*cv, *rest_v))
            o_flat, o_def = jax.tree.flatten(tuple(out), is_leaf=_is_tensor)
            o_static = tuple(None if isinstance(l, Tensor) else l
                             for l in o_flat)
            if o_def != c_def or o_static != statics_in:
                bad = [n for n, v in zip(names, out)
                       if not isinstance(v, Tensor)] or list(names[:n_carry])
                raise ValueError(
                    f"tensor-dependent `while`: loop-carried variable(s) "
                    f"{bad} changed structure or Python value across an "
                    f"iteration — carry values must stay tensors of one "
                    f"shape/dtype")
            return tuple(l._data for l in o_flat if isinstance(l, Tensor))

        return jax.lax.while_loop(cond_w, body_w, tuple(ac))

    from ..autograd.tape import apply as tape_apply
    try:
        out_ts = tape_apply(while_arrays,
                            *(c_flat[i] for i in c_idx),
                            *(r_flat[i] for i in r_idx),
                            op_name="dy2static_while")
    except TypeError as e:
        raise TypeError(
            f"tensor-dependent `while`: the carry {names[:n_carry]} must "
            f"keep one shape/dtype across iterations: {e}") from None
    out_ts = jax.tree.leaves(out_ts, is_leaf=_is_tensor)
    nf = list(c_flat)
    for i, t, sg in zip(c_idx, out_ts, c_sgs):
        t.stop_gradient = sg
        nf[i] = t
    return jax.tree.unflatten(c_def, nf)


# ---------------------------------------------------------------------------
# AST analysis
# ---------------------------------------------------------------------------

def _assigned_names(stmts):
    """Names bound by Store at this function scope inside ``stmts`` —
    skipping nested function/class/lambda/comprehension scopes."""
    names = set()

    def walk(node):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Store):
            names.add(node.id)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef)):
            names.add(node.name)
            return
        if isinstance(node, (ast.Lambda, ast.ListComp, ast.SetComp,
                             ast.DictComp, ast.GeneratorExp)):
            return
        for child in ast.iter_child_nodes(node):
            walk(child)

    for s in stmts:
        walk(s)
    return names


def _read_names(nodes):
    """Names loaded at this scope inside ``nodes`` (statements or exprs) —
    skipping nested function/class/lambda/comprehension scopes. Used to
    pass read-only values into converted regions as explicit operands, so
    the tape records edges to every differentiable input."""
    names = set()

    def walk(node):
        if isinstance(node, ast.Name) and isinstance(node.ctx, ast.Load):
            names.add(node.id)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.ClassDef, ast.Lambda, ast.ListComp,
                             ast.SetComp, ast.DictComp, ast.GeneratorExp)):
            return
        for child in ast.iter_child_nodes(node):
            walk(child)

    for n in nodes:
        walk(n)
    return names


def _has_escape(stmts):
    """True if ``stmts`` contain return/yield/raise/assert/global/
    nonlocal/del at this scope (not inside nested defs), or break/continue
    that would escape this region — constructs the converter leaves as
    plain Python (tracing both arms would run them unconditionally)."""

    def walk(node, loop_depth):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda, ast.ClassDef)):
            return False
        if isinstance(node, (ast.Return, ast.Global, ast.Nonlocal,
                             ast.Delete, ast.Yield, ast.YieldFrom,
                             ast.Raise, ast.Assert)):
            return True
        if isinstance(node, (ast.Break, ast.Continue)) and loop_depth == 0:
            return True
        inner = loop_depth + (1 if isinstance(node, (ast.For, ast.While,
                                                     ast.AsyncFor)) else 0)
        return any(walk(c, inner) for c in ast.iter_child_nodes(node))

    return any(walk(s, 0) for s in stmts)


# ---------------------------------------------------------------------------
# the transformer
# ---------------------------------------------------------------------------

def _name(id_, ctx=None):
    return ast.Name(id=id_, ctx=ctx or ast.Load())


def _tuple(elts, ctx=None):
    return ast.Tuple(elts=elts, ctx=ctx or ast.Load())


def _guards(names):
    """``try: n\nexcept NameError: n = _jst_UNDEF`` per name (UnboundLocal
    is a NameError subclass, so function locals are covered)."""
    out = []
    for n in names:
        out.append(ast.Try(
            body=[ast.Expr(value=_name(n))],
            handlers=[ast.ExceptHandler(
                type=_name("NameError"), name=None,
                body=[ast.Assign(targets=[_name(n, ast.Store())],
                                 value=_name("_jst_UNDEF"))])],
            orelse=[], finalbody=[]))
    return out


def _fn_def(fname, argnames, body, names):
    ret = ast.Return(value=_tuple([_name(n) for n in names]))
    return ast.FunctionDef(
        name=fname,
        args=ast.arguments(posonlyargs=[],
                           args=[ast.arg(arg=a) for a in argnames],
                           vararg=None, kwonlyargs=[], kw_defaults=[],
                           kwarg=None, defaults=[]),
        body=(body or [ast.Pass()]) + [ret],
        decorator_list=[], returns=None, type_params=[])


def _call_stmt(names, helper, call_args):
    call = ast.Call(func=_name(helper), args=call_args, keywords=[])
    if not names:
        return ast.Expr(value=call)
    return ast.Assign(
        targets=[_tuple([_name(n, ast.Store()) for n in names],
                        ast.Store())],
        value=call)


def _peek_expr(n):
    """``_jst_peek(lambda: n)`` — resolves a read-only name through the
    compiler's own local/closure/global/builtin lookup without creating a
    local binding (a try/except-assign guard would make the name
    function-local and shadow module globals/closures)."""
    return ast.Call(func=_name("_jst_peek"),
                    args=[ast.Lambda(
                        args=ast.arguments(posonlyargs=[], args=[],
                                           vararg=None, kwonlyargs=[],
                                           kw_defaults=[], kwarg=None,
                                           defaults=[]),
                        body=_name(n))],
                    keywords=[])


class _Transformer(ast.NodeTransformer):
    def __init__(self):
        self.counter = 0
        self.converted = 0

    # keep nested function/class bodies untouched — they are their own
    # tracing scope and converting them here would capture wrong names
    def visit_FunctionDef(self, node):
        return node

    visit_AsyncFunctionDef = visit_FunctionDef
    visit_ClassDef = visit_FunctionDef
    visit_Lambda = visit_FunctionDef

    def visit_If(self, node):
        self.generic_visit(node)
        if _has_escape(node.body) or _has_escape(node.orelse):
            return node
        # nested converted regions bind _jst_* helpers inside the arm —
        # they are arm-local, never thread them through the outer cond
        names = sorted(n for n in (_assigned_names(node.body)
                                   | _assigned_names(node.orelse))
                       if not n.startswith("_jst_"))
        reads = sorted(n for n in (_read_names(node.body)
                                   | _read_names(node.orelse))
                       if n not in names and not n.startswith("_jst_"))
        i = self.counter
        self.counter += 1
        self.converted += 1
        cvar = f"_jst_c{i}"
        params = names + reads
        stmts = [ast.Assign(targets=[_name(cvar, ast.Store())],
                            value=node.test),
                 _fn_def(f"_jst_t{i}", params, node.body, names),
                 _fn_def(f"_jst_f{i}", params, node.orelse, names)]
        stmts += _guards(names)
        stmts.append(_call_stmt(names, "_jst_if", [
            _name(cvar), _name(f"_jst_t{i}"), _name(f"_jst_f{i}"),
            _tuple([_name(n) for n in names] + [_peek_expr(n)
                                                for n in reads]),
            _tuple([ast.Constant(value=n) for n in params])]))
        return stmts

    def visit_While(self, node):
        self.generic_visit(node)
        if node.orelse or _has_escape(node.body):
            return node
        names = sorted(n for n in _assigned_names(node.body)
                       if not n.startswith("_jst_"))
        if not names:
            return node      # no carry — nothing a traced loop could do
        reads = sorted(n for n in (_read_names(node.body)
                                   | _read_names([node.test]))
                       if n not in names and not n.startswith("_jst_"))
        i = self.counter
        self.counter += 1
        self.converted += 1
        params = names + reads
        cond_fn = ast.FunctionDef(
            name=f"_jst_wc{i}",
            args=ast.arguments(posonlyargs=[],
                               args=[ast.arg(arg=a) for a in params],
                               vararg=None, kwonlyargs=[], kw_defaults=[],
                               kwarg=None, defaults=[]),
            body=[ast.Return(value=node.test)],
            decorator_list=[], returns=None, type_params=[])
        stmts = [cond_fn,
                 _fn_def(f"_jst_wb{i}", params, node.body, names)]
        stmts += _guards(names)
        stmts.append(_call_stmt(names, "_jst_while", [
            _name(f"_jst_wc{i}"), _name(f"_jst_wb{i}"),
            _tuple([_name(n) for n in names] + [_peek_expr(n)
                                                for n in reads]),
            _tuple([ast.Constant(value=n) for n in params]),
            ast.Constant(value=len(names))]))
        return stmts


# ---------------------------------------------------------------------------
# entry point
# ---------------------------------------------------------------------------

_CACHE: dict = {}


def convert_function(fn):
    """AST-rewrite ``fn`` so tensor-dependent if/while run as lax.cond /
    lax.while_loop. Returns the rewritten function (cached per code
    object). Raises :class:`ConversionUnsupported` when nothing was
    convertible (no control flow, unavailable source, ...)."""
    code = getattr(fn, "__code__", None)
    if code is None:
        raise ConversionUnsupported(f"not a plain function: {fn!r}")
    if getattr(fn, "__wrapped__", None) is not None:
        # inspect.getsource unwraps to the INNER def — converting it would
        # silently drop the wrapper's behavior
        raise ConversionUnsupported(
            "function carries a functools.wraps decorator (__wrapped__); "
            "conversion would bypass the wrapper")
    # the rewrite bakes closure cell VALUES in — two closures sharing one
    # code object (factory-made functions) must not share a conversion
    cacheable = not code.co_freevars
    if cacheable:
        hit = _CACHE.get(code)
        if hit is not None:
            return hit
    try:
        src = textwrap.dedent(inspect.getsource(fn))
        tree = ast.parse(src)
    except (OSError, TypeError, SyntaxError) as e:
        raise ConversionUnsupported(f"source unavailable: {e}") from None
    fdef = tree.body[0]
    if not isinstance(fdef, (ast.FunctionDef, ast.AsyncFunctionDef)):
        raise ConversionUnsupported("not a function definition")
    fdef.decorator_list = []
    tr = _Transformer()
    tr.generic_visit(fdef)   # transform the body; visit_FunctionDef only
    #                          guards defs NESTED inside it
    if not tr.converted:
        raise ConversionUnsupported(
            "no convertible if/while (return/break/continue inside the "
            "region, or no control flow at all)")

    freevars = code.co_freevars
    if freevars:
        outer = ast.FunctionDef(
            name="_jst_outer",
            args=ast.arguments(posonlyargs=[],
                               args=[ast.arg(arg=a) for a in freevars],
                               vararg=None, kwonlyargs=[], kw_defaults=[],
                               kwarg=None, defaults=[]),
            body=[fdef, ast.Return(value=_name(fdef.name))],
            decorator_list=[], returns=None, type_params=[])
        module = ast.Module(body=[outer], type_ignores=[])
    else:
        module = ast.Module(body=[fdef], type_ignores=[])
    ast.fix_missing_locations(module)

    # a live CHAIN to fn's module globals (not a snapshot): rebinding a
    # module global after conversion must stay visible to the compiled
    # path. dict-subclass __missing__ is honored by LOAD_GLOBAL.
    class _Namespace(dict):
        def __init__(self, base):
            super().__init__()
            self._base = base

        def __missing__(self, key):
            return self._base[key]

    ns = _Namespace(getattr(fn, "__globals__", {}))
    ns.update(_jst_if=_jst_if, _jst_while=_jst_while, _jst_UNDEF=_UNDEF,
              _jst_peek=_jst_peek)
    filename = f"<dy2static {getattr(fn, '__qualname__', fn)}>"
    exec(compile(module, filename, "exec"), ns)       # noqa: S102
    if freevars:
        cells = [c.cell_contents for c in (fn.__closure__ or ())]
        new_fn = ns["_jst_outer"](*cells)
    else:
        new_fn = ns[fdef.name]
    new_fn.__defaults__ = fn.__defaults__
    new_fn.__kwdefaults__ = fn.__kwdefaults__
    new_fn.__name__ = getattr(fn, "__name__", fdef.name)
    new_fn.__qualname__ = getattr(fn, "__qualname__", fdef.name)
    new_fn._jst_source = ast.unparse(module)
    if cacheable:
        _CACHE[code] = new_fn
    return new_fn


def converted_code(fn):
    """The rewritten source (debugging aid — the reference exposes its
    transformed code via ``StaticFunction.code``)."""
    try:
        return convert_function(fn)._jst_source
    except ConversionUnsupported:
        return None
