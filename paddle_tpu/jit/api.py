"""@paddle.jit.to_static — the dynamic-to-static tracer (reference: the SOT/AST
dual path in ``python/paddle/jit/`` lowering Program IR through CINN; SURVEY.md
§3.2). TPU-native design (SURVEY.md §7.0): **jax.jit IS the tracer** — we trace
the eager op layer with jax tracers by swapping each Parameter/buffer's backing
array, cache the compiled program per input-spec (shape/dtype/stop_gradient +
training flag), and splice ONE GradNode for the whole compiled region into the
imperative tape (via ``tape.apply``) so ``loss.backward()`` keeps working.
Buffer mutation (BN running stats) threads through the trace as extra outputs.
Python branching on tensor values raises under tracing → graph break → eager
fallback, matching SOT's fallback semantics.
"""
from __future__ import annotations

import functools
import os
import time
import warnings

import numpy as np
import jax

from ..framework.core import Tensor
from ..framework import dtype as dtypes
from ..framework import random as prandom
from ..autograd.tape import apply, no_grad
from ..nn.layer import Layer
from ..profiler import compile_observatory as _co

_static_mode = [False]  # paddle.enable_static (legacy static-graph mode flag)
_TRACING = [False]
_STATIC_ACTIVE = [False]   # inside StaticFunction.__call__'s trace (the only
                           # context with an InTraceAutogradNeeded handler)

_JIT_METRICS = None        # lazily bound registry families


def _jit_metrics():
    global _JIT_METRICS
    if _JIT_METRICS is None:
        from ..profiler.telemetry import get_registry
        r = get_registry()
        _JIT_METRICS = {
            "cache": r.counter(
                "paddle_jit_cache_total",
                "to_static program-cache lookups", labels=("event",)),
            "compile": r.histogram(
                "paddle_jit_compile_seconds",
                "trace+compile+first-run seconds per to_static cache miss"),
            "breaks": r.counter(
                "paddle_jit_graph_breaks_total",
                "tracer graph breaks (data-dependent Python control flow)"),
            "fallback": r.counter(
                "paddle_jit_eager_fallback_total",
                "to_static calls served eager by a latched dy2static "
                "fallback"),
            "converted": r.counter(
                "paddle_jit_dy2static_conversions_total",
                "specs rebuilt through dy2static control-flow conversion"),
        }
    return _JIT_METRICS

_GRAPH_BREAK_ERRORS = (
    jax.errors.TracerBoolConversionError,
    jax.errors.ConcretizationTypeError,
    jax.errors.TracerArrayConversionError,
    jax.errors.TracerIntegerConversionError,
)

# persistent (disk) compilation cache state: None = not yet attempted,
# False = unavailable/disabled, str = active cache dir
_PERSISTENT_CACHE = [None]
_DISK_HIT_LISTENER = [False]


def _install_disk_hit_listener():
    """Count disk-cache restores into the existing jit cache metric
    (``paddle_jit_cache_total{event="disk_hit"}``): jax records a
    monitoring event on every compilation-cache read hit."""
    if _DISK_HIT_LISTENER[0]:
        return
    try:
        from jax import monitoring as _monitoring

        def _on_event(event, *a, **k):
            if event == "/jax/compilation_cache/cache_hits":
                _jit_metrics()["cache"].inc(event="disk_hit")

        _monitoring.register_event_listener(_on_event)
        _DISK_HIT_LISTENER[0] = True
    except Exception:
        pass


def enable_persistent_cache(path=None):
    """Wire jax's persistent compilation cache so repeated runs skip XLA
    recompiles entirely (the training/serving cold-start lever): compiled
    executables are keyed on HLO+flags and restored from ``path`` across
    processes. ``path`` defaults to ``PADDLE_JIT_CACHE_DIR``; returns True
    when active. Restores are counted as
    ``paddle_jit_cache_total{event="disk_hit"}``."""
    if path is None:
        path = os.environ.get("PADDLE_JIT_CACHE_DIR")
    if not path:
        _PERSISTENT_CACHE[0] = False
        return False
    path = str(path)
    if _PERSISTENT_CACHE[0] == path:
        return True
    try:
        jax.config.update("jax_compilation_cache_dir", path)
        # default thresholds skip tiny/fast programs — a framework whose
        # eager tier jits small regions wants everything cached
        for knob, val in (("jax_persistent_cache_min_entry_size_bytes", -1),
                          ("jax_persistent_cache_min_compile_time_secs", 0.0)):
            try:
                jax.config.update(knob, val)
            except Exception:
                pass
        os.makedirs(path, exist_ok=True)
        # the cache latches DISABLED at the first compile of the process
        # (lazy _initialize_cache); a reset re-reads the (now set) dir so
        # late wiring — after paddle's import-time jits — still engages
        try:
            from jax.experimental.compilation_cache import (
                compilation_cache as _jax_cc)
            _jax_cc.reset_cache()
        except Exception:
            pass
    except Exception:
        _PERSISTENT_CACHE[0] = False
        return False
    _install_disk_hit_listener()
    _PERSISTENT_CACHE[0] = path
    return True


def enable_static():
    _static_mode[0] = True


def disable_static():
    _static_mode[0] = False


def in_dynamic_mode():
    return not _static_mode[0]


def in_to_static_mode():
    return _TRACING[0]


class InputSpec:
    def __init__(self, shape=None, dtype="float32", name=None, stop_gradient=True):
        self.shape = list(shape) if shape is not None else None
        self.dtype = dtypes.convert_dtype(dtype) if dtype is not None else None
        self.name = name
        self.stop_gradient = stop_gradient

    def __repr__(self):
        return f"InputSpec(shape={self.shape}, dtype={self.dtype}, name={self.name})"

    @classmethod
    def from_tensor(cls, tensor, name=None):
        return cls(tensor.shape, tensor.dtype, name or tensor.name)


def _is_tensor(x):
    return isinstance(x, Tensor)


def _spec_key(args, kwargs, training):
    """Cache key + list of objects to pin. Unhashable objects key on id()
    — the caller must keep the returned ``pinned`` refs alive with the
    cache entry, else a freed object's recycled id() could wrongly hit."""
    parts = [bool(training)]
    pinned = []
    for a in jax.tree.leaves((args, kwargs), is_leaf=_is_tensor):
        if isinstance(a, Tensor):
            parts.append(("T", tuple(a._data.shape), str(a.dtype), a.stop_gradient))
        elif isinstance(a, (int, float, str, bool, bytes, type(None))):
            parts.append(a)
        elif isinstance(a, np.ndarray):
            parts.append(("A", a.shape, str(a.dtype), a.tobytes()))
        else:
            try:
                hash(a)
            except TypeError:
                parts.append(("O", id(a)))
                pinned.append(a)
            else:
                # key on (type, object): the key tuple holds a strong ref
                # (no id recycling), dict equality uses the object's own
                # __eq__, and the type tag keeps value-equal cross-type
                # args (2 vs 2.0 vs True) from aliasing one trace
                parts.append(("H", type(a).__qualname__, a))
    return tuple(parts), pinned


class StaticFunction:
    """Callable produced by @to_static. One compiled program per input spec."""

    def __init__(self, function, input_spec=None, instance=None, **unused):
        self._orig_fn = function
        self._input_spec = input_spec
        self._instance = instance  # set when decorating an unbound method
        self._cache = {}
        self._bound = {}
        self._converted = "unset"  # dy2static-converted fn, lazily built
        if not isinstance(function, Layer):
            functools.update_wrapper(self, function)

    def __get__(self, instance, owner):
        if instance is None:
            return self
        key = id(instance)
        if key not in self._bound:
            self._bound[key] = StaticFunction(self._orig_fn, self._input_spec,
                                              instance=instance)
        return self._bound[key]

    # -- helpers ------------------------------------------------------------
    def _layer(self):
        if isinstance(self._instance, Layer):
            return self._instance
        if isinstance(self._orig_fn, Layer):
            return self._orig_fn
        own = getattr(self._orig_fn, "__self__", None)
        return own if isinstance(own, Layer) else None

    def _call_eager(self, *args, **kwargs):
        if isinstance(self._orig_fn, Layer):
            return self._orig_fn.forward(*args, **kwargs)
        if self._instance is not None:
            return self._orig_fn(self._instance, *args, **kwargs)
        return self._orig_fn(*args, **kwargs)

    def _state(self):
        layer = self._layer()
        if layer is None:
            return [], []
        return ([p for p in layer.parameters() if p is not None],
                [b for b in layer.buffers() if b is not None])

    # -- trace + compile ----------------------------------------------------
    def _make_core(self, treedef, leaves, kwargs_static, params, bufs, sg_flags,
                   tape_in_trace=False, call_fn=None):
        """Returns jitted core(p_arrs, b_arrs, key, t_arrs) -> (out, new_bufs).

        ``leaves`` gives the static (non-Tensor) leaves; Tensor slots are None
        and filled from t_arrs at call time. ``tape_in_trace`` keeps the tape
        recording during the trace (needed when the function calls
        paddle.grad — see autograd.tape.InTraceAutogradNeeded).
        ``call_fn`` overrides the traced callable — used to swap in the
        dy2static control-flow-converted function after a graph break.
        """
        static_leaves = [None if isinstance(l, Tensor) else l for l in leaves]
        tensor_slots = [i for i, l in enumerate(leaves) if isinstance(l, Tensor)]

        def core(p_arrs, b_arrs, key, t_arrs):
            from ..framework.functional import swap_state
            with swap_state(params, bufs, p_arrs, b_arrs, key,
                            enable_grad=tape_in_trace):
                new_leaves = list(static_leaves)
                for slot, arr, sg in zip(tensor_slots, t_arrs, sg_flags):
                    tt = Tensor(arr)
                    tt.stop_gradient = sg
                    new_leaves[slot] = tt
                new_args, new_kwargs = jax.tree.unflatten(treedef, new_leaves)
                if call_fn is not None:
                    out = call_fn(*new_args, **new_kwargs)
                else:
                    out = self._call_eager(*new_args, **new_kwargs)
                out_arrays = jax.tree.map(
                    lambda t: t._data if isinstance(t, Tensor) else t, out,
                    is_leaf=_is_tensor)
                new_bufs = [t._data for t in bufs]
                return out_arrays, new_bufs

        return jax.jit(core)

    # -- dy2static control-flow conversion ----------------------------------
    def _conversion_target(self):
        """(plain function, bound instance or None) for the AST converter."""
        fn, inst = self._orig_fn, self._instance
        if isinstance(fn, Layer):
            fn = type(fn).forward
            inst = self._orig_fn
        if hasattr(fn, "__func__"):          # bound method
            inst = fn.__self__
            fn = fn.__func__
        return fn, inst

    def _get_converted(self):
        """Control-flow-converted callable (reference ``convert_ifelse`` /
        ``convert_while`` — SURVEY.md §3.2), or None when the function has
        no convertible construct. Built lazily on the first graph break."""
        if self._converted == "unset":
            from . import dy2static
            fn, inst = self._conversion_target()
            try:
                cfn = dy2static.convert_function(fn)
            except dy2static.ConversionUnsupported:
                self._converted = None
            else:
                if inst is not None:
                    self._converted = functools.partial(cfn, inst)
                else:
                    self._converted = cfn
        return self._converted

    def __call__(self, *args, **kwargs):
        if _PERSISTENT_CACHE[0] is None:     # PADDLE_JIT_CACHE_DIR, once
            enable_persistent_cache()
        params, bufs = self._state()
        layer = self._layer()
        training = layer.training if layer is not None else True
        leaves, treedef = jax.tree.flatten((args, kwargs), is_leaf=_is_tensor)
        tensor_leaves = [l for l in leaves if isinstance(l, Tensor)]
        key, pinned = _spec_key(args, kwargs, training)
        tm = _jit_metrics()
        entry = self._cache.get(key)
        tm["cache"].inc(event="hit" if entry is not None else "miss")
        t_miss = None if entry is not None else time.perf_counter()
        # compile observatory: to_static IS a training-step jit boundary;
        # record the full input spec as a program signature so a retrace
        # gets a cause string ("arg `arg0` dim0 13→16", "static arg
        # `training` True→False") instead of a silent cache miss
        co_sig = None
        if _co.is_enabled():
            fam = f"jit.{getattr(self._orig_fn, '__name__', 'fn')}"
            if t_miss is not None:
                _co.declare_family(
                    fam, warmup=lambda: "warmed by first traced call")
            co_sig = {"training": _co.static_arg(training)}
            for i, l in enumerate(leaves):
                if isinstance(l, Tensor):
                    co_sig[f"arg{i}"] = _co.tensor_arg(
                        l._data.shape, l.dtype)
                elif isinstance(l, np.ndarray):
                    co_sig[f"arg{i}"] = _co.tensor_arg(l.shape, l.dtype)
                elif isinstance(l, (int, float, str, bool, bytes,
                                    type(None))):
                    co_sig[f"arg{i}"] = _co.static_arg(l)
        if entry is None:
            sg_flags = [t.stop_gradient for t in tensor_leaves]
            # a spec that already needed control-flow conversion tells us
            # the next spec will too — skip the doomed plain trace
            conv = self._converted if callable(self._converted) else None
            core = self._make_core(treedef, leaves, kwargs, params, bufs,
                                   sg_flags, call_fn=conv)
            entry = {"core": core, "fallback": False, "breaks": 0,
                     "pinned": pinned, "converted": conv is not None,
                     "call_fn": conv}
            self._cache[key] = entry
        if entry["fallback"]:
            tm["fallback"].inc()
            return self._call_eager(*args, **kwargs)

        rng_key = prandom.next_key()
        np_, nb_ = len(params), len(bufs)

        def runner(*xs):
            p_arrs = list(xs[:np_])
            b_arrs = list(xs[np_:np_ + nb_])
            t_arrs = list(xs[np_ + nb_:])
            return entry["core"](p_arrs, b_arrs, rng_key, t_arrs)

        from ..autograd.tape import InTraceAutogradNeeded

        def attempt(call_fn):
            try:
                return apply(runner, *params, *bufs, *tensor_leaves,
                             op_name="to_static")
            except InTraceAutogradNeeded:
                # the traced fn calls paddle.grad: re-trace with the tape
                # recording over tracers (unused vjps are DCE'd by XLA)
                sg_flags = [t.stop_gradient for t in tensor_leaves]
                entry["core"] = self._make_core(treedef, leaves, kwargs,
                                                params, bufs, sg_flags,
                                                tape_in_trace=True,
                                                call_fn=call_fn)
                return apply(runner, *params, *bufs, *tensor_leaves,
                             op_name="to_static")

        prev_static = _STATIC_ACTIVE[0]
        _STATIC_ACTIVE[0] = True
        try:
            try:
                out_vals, new_bufs = attempt(entry.get("call_fn"))
            except _GRAPH_BREAK_ERRORS as e:
                # a data-dependent branch: convert Python if/while on
                # tensor values into lax.cond/while_loop (reference
                # convert_ifelse/convert_while) and stay compiled
                tm["breaks"].inc()
                conv = (self._get_converted()
                        if not entry.get("converted") else None)
                if conv is None:
                    raise
                tm["converted"].inc()
                sg_flags = [t.stop_gradient for t in tensor_leaves]
                entry["core"] = self._make_core(treedef, leaves, kwargs,
                                                params, bufs, sg_flags,
                                                call_fn=conv)
                entry["converted"] = True
                entry["call_fn"] = conv
                out_vals, new_bufs = attempt(conv)
        except _GRAPH_BREAK_ERRORS as e:
            # latch the eager fallback only after a SECOND break, so one
            # transient tracer error doesn't permanently degrade the spec;
            # genuinely dynamic code (use static.nn.cond/while_loop to stay
            # compiled) latches on the next call
            tm["breaks"].inc()
            tm["fallback"].inc()
            entry["breaks"] += 1
            entry["fallback"] = entry["breaks"] >= 2
            warnings.warn(
                f"to_static: graph break ({type(e).__name__}) — falling back "
                f"to eager for "
                f"{getattr(self._orig_fn, '__name__', self._orig_fn)}"
                + (" (latched)" if entry["fallback"] else "; will retry once"))
            return self._call_eager(*args, **kwargs)
        finally:
            _STATIC_ACTIVE[0] = prev_static

        entry["breaks"] = 0     # a clean traced call re-arms the retry
        if t_miss is not None:
            # a miss pays trace + XLA compile + first run; later hits on
            # this spec are pure cache dispatch — the spread between this
            # histogram and steady-state step time IS the compile cost
            tm["compile"].observe(time.perf_counter() - t_miss)
        if co_sig is not None:
            _co.observe(f"jit.{getattr(self._orig_fn, '__name__', 'fn')}",
                        co_sig,
                        seconds=(time.perf_counter() - t_miss
                                 if t_miss is not None else None))
        with no_grad():
            for b, nb in zip(bufs, new_bufs):
                b._data = nb._data if isinstance(nb, Tensor) else nb
        return out_vals

    # -- introspection / export --------------------------------------------
    @property
    def code(self):
        import inspect
        try:
            return inspect.getsource(self._orig_fn)
        except (OSError, TypeError):
            return "<source unavailable>"

    def get_concrete_program(self, *args, **kwargs):
        """Lower to StableHLO for the given example inputs (Program analogue)."""
        from ..autograd.tape import InTraceAutogradNeeded
        params, bufs = self._state()
        leaves, treedef = jax.tree.flatten((args, kwargs), is_leaf=_is_tensor)
        tensor_leaves = [l for l in leaves if isinstance(l, Tensor)]
        sg = [t.stop_gradient for t in tensor_leaves]
        prev_static = _STATIC_ACTIVE[0]
        _STATIC_ACTIVE[0] = True
        last_break = None
        try:
            conv = self._converted if callable(self._converted) else None
            for call_fn in ((conv,) if conv is not None else (None, "conv")):
                if call_fn == "conv":
                    call_fn = self._get_converted()
                    if call_fn is None:
                        break
                for tape_in_trace in (False, True):
                    core = self._make_core(treedef, leaves, kwargs, params,
                                           bufs, sg,
                                           tape_in_trace=tape_in_trace,
                                           call_fn=call_fn)
                    try:
                        return core.lower([p._data for p in params],
                                          [b._data for b in bufs],
                                          prandom.next_key(),
                                          [t._data for t in tensor_leaves])
                    except InTraceAutogradNeeded:
                        continue   # retry with the tape recording in-trace
                    except _GRAPH_BREAK_ERRORS as e:
                        if call_fn is not None:
                            raise
                        last_break = e
                        break      # retry with control-flow conversion
        finally:
            _STATIC_ACTIVE[0] = prev_static
        raise (last_break if last_break is not None else RuntimeError(
            "get_concrete_program: could not lower (in-trace autograd "
            "retries exhausted)"))

    def rollback(self):
        if isinstance(self._orig_fn, Layer):
            return self._orig_fn
        return self._orig_fn


def to_static(function=None, input_spec=None, build_strategy=None,
              backend=None, full_graph=None, **kwargs):
    """@paddle.jit.to_static — decorator or functional form; accepts a Layer,
    a function, or a bound method."""

    def decorate(fn):
        if isinstance(fn, Layer):
            orig_forward = fn.forward
            sf = StaticFunction(orig_forward, input_spec)
            fn._static_forward = sf
            fn._dygraph_forward = orig_forward
            fn.forward = sf
            return fn
        return StaticFunction(fn, input_spec)

    if function is not None:
        return decorate(function)
    return decorate


def not_to_static(fn):
    fn._not_to_static = True
    return fn


def ignore_module(modules):
    pass


def enable_to_static(flag=True):
    pass
