"""Serving walkthrough: load an HF-format Llama checkpoint from a local
directory, stand up the batched ServingEngine (paged KV cache), and serve
concurrent generate() calls.

    python examples/serve_llama_hf.py --model-dir /path/to/hf_llama
    python examples/serve_llama_hf.py            # tiny random demo model
    FORCE_CPU=0 python examples/serve_llama_hf.py   # use the accelerator

Defaults to the CPU backend (FORCE_CPU=1) so the demo runs anywhere; with
FORCE_CPU=0 on a TPU host the decode path runs jax's production
paged-attention Pallas kernel — same API either way.
"""
import argparse
import os
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("FORCE_CPU", "1") == "1":
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np                                        # noqa: E402

import paddle_tpu as paddle                               # noqa: E402
from paddle_tpu.models import LlamaForCausalLM, llama_tiny  # noqa: E402
from paddle_tpu.inference.serving import ServingEngine    # noqa: E402


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--model-dir", default=None,
                    help="local HF checkpoint dir (config.json + weights)")
    ap.add_argument("--clients", type=int, default=3)
    ap.add_argument("--new-tokens", type=int, default=12)
    args = ap.parse_args()

    paddle.seed(0)
    if args.model_dir:
        model = LlamaForCausalLM.from_pretrained(args.model_dir)
        print(f"loaded HF checkpoint from {args.model_dir}")
    else:
        model = LlamaForCausalLM(llama_tiny(num_hidden_layers=2))
        print("no --model-dir: using a tiny random demo model")
    model.eval()
    vocab = model.config.vocab_size

    engine = ServingEngine(model, max_batch_size=8,
                           batch_window_s=0.02).start()
    rng = np.random.RandomState(0)
    prompts = [paddle.to_tensor(
        rng.randint(0, vocab, (1, 4 + i)).astype(np.int64))
        for i in range(args.clients)]

    outs = {}

    def client(i):
        outs[i] = engine.generate(prompts[i],
                                  max_new_tokens=args.new_tokens,
                                  timeout=600)

    threads = [threading.Thread(target=client, args=(i,))
               for i in range(args.clients)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    engine.stop()

    for i in range(args.clients):
        print(f"client {i}: prompt {tuple(prompts[i].shape)} -> "
              f"output {tuple(outs[i].shape)}; "
              f"batches_run={engine.batches_run}")
    assert all(tuple(outs[i].shape)[1]
               == tuple(prompts[i].shape)[1] + args.new_tokens
               for i in range(args.clients))
    print("serving demo OK")


if __name__ == "__main__":
    main()
