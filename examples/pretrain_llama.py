"""Llama pretraining with the full hybrid stack (BASELINE.json configs[4/5]).

Run (8 virtual CPU devices):
    XLA_FLAGS=--xla_force_host_platform_device_count=8 \
    python examples/pretrain_llama.py --dp 2 --mp 2 --sharding 2 --steps 10

On a TPU pod slice the same script runs per host (paddle.distributed.launch)
with the real device count; mesh axes and shardings are identical.
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

# CPU fallback when no TPU is attached (the axon tunnel is single-process)
if os.environ.get("LLAMA_FORCE_CPU", "1") == "1":
    os.environ.setdefault(
        "XLA_FLAGS", "--xla_force_host_platform_device_count=8")
    import jax
    jax.config.update("jax_platforms", "cpu")
else:
    import jax

import numpy as np
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import fleet
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.fleet import DistributedStrategy
from paddle_tpu.distributed.fleet.elastic import TrainingSupervisor
from paddle_tpu.framework.functional import FunctionalModule
from paddle_tpu.models import LlamaForCausalLM, llama_tiny


def parse():
    p = argparse.ArgumentParser()
    p.add_argument("--dp", type=int, default=2)
    p.add_argument("--mp", type=int, default=2)
    p.add_argument("--sharding", type=int, default=2)
    p.add_argument("--sep", type=int, default=1)
    p.add_argument("--steps", type=int, default=10)
    p.add_argument("--batch", type=int, default=8)
    p.add_argument("--seq", type=int, default=64)
    p.add_argument("--lr", type=float, default=1e-3)
    p.add_argument("--amp", action=argparse.BooleanOptionalAction,
                   default=True)
    p.add_argument("--ckpt_dir", default="/tmp/llama_pretrain_ckpt")
    return p.parse_args()


def main():
    args = parse()
    strategy = DistributedStrategy()
    strategy.hybrid_configs = {
        "dp_degree": args.dp, "mp_degree": args.mp,
        "sharding_degree": args.sharding, "sep_degree": args.sep,
        "pp_degree": 1,
    }
    fleet.init(is_collective=True, strategy=strategy)
    mesh = mesh_mod.get_mesh()
    print("mesh:", dict(mesh.shape))

    paddle.seed(0)
    cfg = llama_tiny(use_recompute=True,
                     context_parallel=args.sep > 1)
    model = LlamaForCausalLM(cfg)
    fm = FunctionalModule(model, training=True)
    specs = fm.param_specs(LlamaForCausalLM.sharding_rules(),
                           fsdp_axis="sharding", fsdp_size=args.sharding)
    p_sh = [NamedSharding(mesh, s) for s in specs]
    data_sh = NamedSharding(mesh, P(("dp", "sharding"), "sep"))

    p = [jax.device_put(a, s) for a, s in zip(fm.param_arrays(), p_sh)]
    m = [jax.device_put(jnp.zeros_like(a), s) for a, s in zip(p, p_sh)]
    v = [jax.device_put(jnp.zeros_like(a), s) for a, s in zip(p, p_sh)]
    lr, b1, b2, eps, wd = args.lr, 0.9, 0.999, 1e-8, 0.01
    amp = args.amp

    def train_step(p, m, v, key, ids, labels):
        def loss_fn(ps):
            cps = [a.astype(jnp.bfloat16) if amp and a.dtype == jnp.float32
                   else a for a in ps]       # AMP-O2: bf16 compute,
            (loss, _), _ = fm(cps, [], key, ids, labels=labels)
            return loss                      # fp32 master weights

        loss, grads = jax.value_and_grad(loss_fn)(p)
        new_p, new_m, new_v = [], [], []
        for pa, g, mm, vv in zip(p, grads, m, v):
            g = g.astype(pa.dtype)
            mm = b1 * mm + (1 - b1) * g
            vv = b2 * vv + (1 - b2) * g * g
            new_p.append(pa - lr * (mm / (jnp.sqrt(vv) + eps) + wd * pa))
            new_m.append(mm)
            new_v.append(vv)
        return loss, new_p, new_m, new_v

    step = jax.jit(train_step, donate_argnums=(0, 1, 2))

    def _save(ckpt, i, p, m, v):
        def host(arrs):
            return [paddle.to_tensor(np.asarray(jax.device_get(a)))
                    for a in arrs]
        ckpt.save(i, {"p": host(p), "m": host(m), "v": host(v)})

    def train(start_step, state, ckpt):
        nonlocal p, m, v
        if state is not None:
            # restore the FULL optimizer state — params AND Adam moments —
            # so restart resumes the exact trajectory (and never touches
            # arrays donated to a failed step call)
            p = [jax.device_put(jnp.asarray(t.numpy()), s)
                 for t, s in zip(state["p"], p_sh)]
            m = [jax.device_put(jnp.asarray(t.numpy()), s)
                 for t, s in zip(state["m"], p_sh)]
            v = [jax.device_put(jnp.asarray(t.numpy()), s)
                 for t, s in zip(state["v"], p_sh)]
        rng = np.random.default_rng(123 + start_step)  # deterministic skip
        t0 = time.time()
        loss = None
        for i in range(start_step, args.steps):
            ids_np = rng.integers(0, cfg.vocab_size,
                                  (args.batch, args.seq + 1))
            # causal-LM pretraining: labels are next-token-shifted ids
            ids = jax.device_put(jnp.asarray(ids_np[:, :-1], jnp.int32),
                                 data_sh)
            labels = jax.device_put(jnp.asarray(ids_np[:, 1:], jnp.int32),
                                    data_sh)
            key = fm.next_key()
            loss, p, m, v = step(p, m, v, key, ids, labels)
            if i % 5 == 0 or i == args.steps - 1:
                dt = (time.time() - t0) / max(i - start_step + 1, 1)
                tok = args.batch * args.seq / dt
                print(f"step {i} loss {float(loss):.4f} "
                      f"({tok:,.0f} tokens/s)")
            if (i + 1) % 10 == 0:
                _save(ckpt, i + 1, p, m, v)
        if loss is None:     # resumed at/after the final step: nothing to do
            _, state2 = ckpt.load()
            return None
        return float(loss)

    sup = TrainingSupervisor(args.ckpt_dir, max_restarts=2)
    final_loss = sup.run(train)
    print("done, final loss", final_loss)


if __name__ == "__main__":
    main()
