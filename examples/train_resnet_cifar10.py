"""ResNet-50 / CIFAR-10 single-device eager training (BASELINE.json
configs[1]) — the reference's dygraph flow: DataLoader → forward/backward →
optimizer, with checkpoint save/load.

    python examples/train_resnet_cifar10.py --steps 20
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("FORCE_CPU", "1") == "1":
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader
from paddle_tpu.vision.datasets import FakeData
from paddle_tpu.vision.models import resnet18


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=20)
    ap.add_argument("--batch", type=int, default=16)
    ap.add_argument("--lr", type=float, default=0.05)
    args = ap.parse_args()

    paddle.seed(0)
    paddle.set_device("cpu" if os.environ.get("FORCE_CPU", "1") == "1"
                      else "tpu")
    model = resnet18(num_classes=10)
    model.train()
    sched = paddle.optimizer.lr.CosineAnnealingDecay(
        learning_rate=args.lr, T_max=args.steps)
    opt = paddle.optimizer.Momentum(learning_rate=sched, momentum=0.9,
                                    parameters=model.parameters(),
                                    weight_decay=5e-4)
    loss_fn = paddle.nn.CrossEntropyLoss()
    ds = FakeData(size=args.batch * 4, image_shape=(3, 32, 32),
                  num_classes=10)
    loader = DataLoader(ds, batch_size=args.batch, shuffle=True,
                        num_workers=0)

    it = 0
    losses = []
    while it < args.steps:
        for x, y in loader:
            logits = model(x)
            loss = loss_fn(logits, y)
            loss.backward()
            opt.step()
            opt.clear_grad()
            sched.step()
            losses.append(float(loss))
            if it % 5 == 0:
                print(f"step {it} loss {losses[-1]:.4f} lr {sched.last_lr:.4f}")
            it += 1
            if it >= args.steps:
                break

    paddle.save(model.state_dict(), "/tmp/resnet_cifar10.pdparams")
    model.set_state_dict(paddle.load("/tmp/resnet_cifar10.pdparams"))
    first = float(np.mean(losses[: len(losses) // 2]))
    last = float(np.mean(losses[len(losses) // 2:]))
    print(f"done: first-half mean {first:.4f} -> last-half mean {last:.4f}")
    if args.steps >= 16:           # batches are random; compare averages
        assert last < first


if __name__ == "__main__":
    main()
