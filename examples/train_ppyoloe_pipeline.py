"""PP-YOLOE data-pipeline config (BASELINE.json configs[3]): detection model
fed by a heavy multiprocess DataLoader (augmentation in workers, shared-memory
transport, device prefetch) — the flow the reference runs with
``paddle.io.DataLoader`` + ``buffered_reader`` H2D double-buffering.

    python examples/train_ppyoloe_pipeline.py --steps 6
"""
import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("FORCE_CPU", "1") == "1":
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.io import DataLoader, Dataset
from paddle_tpu.models import ppyoloe_lite, DetectionLoss


class SyntheticDetection(Dataset):
    """Worker-side augmentation heavy enough to need the pipeline: random
    crop-ish jitter + flip + normalize on 64x64 images, dense targets."""

    def __init__(self, size=64, img=64, classes=4):
        self.size = size
        self.img = img
        self.classes = classes

    def __len__(self):
        return self.size

    def __getitem__(self, i):
        rng = np.random.default_rng(i)
        img = rng.integers(0, 256, (3, self.img, self.img)).astype(np.float32)
        if rng.random() < 0.5:
            img = img[:, :, ::-1]
        img = (img / 127.5) - 1.0
        jitter = rng.normal(0, 0.01, img.shape).astype(np.float32)
        img = img + jitter
        # dense per-level targets (cls one-hot-ish, ltrb distances, pos mask)
        tcls, treg, mask = [], [], []
        for stride in (8, 16, 32):
            g = self.img // stride
            tcls.append(rng.random((self.classes, g, g)).astype(np.float32)
                        < 0.02)
            treg.append(rng.random((4, g, g)).astype(np.float32) * 4)
            mask.append((rng.random((4, g, g)) < 0.1).astype(np.float32))
        return (img.astype(np.float32),
                [t.astype(np.float32) for t in tcls], treg, mask)


def collate(batch):
    imgs = np.stack([b[0] for b in batch])
    tcls = [np.stack([b[1][l] for b in batch]) for l in range(3)]
    treg = [np.stack([b[2][l] for b in batch]) for l in range(3)]
    mask = [np.stack([b[3][l] for b in batch]) for l in range(3)]
    return imgs, tcls, treg, mask


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=6)
    ap.add_argument("--batch", type=int, default=8)
    ap.add_argument("--workers", type=int, default=2)
    args = ap.parse_args()

    paddle.seed(0)
    model = ppyoloe_lite(num_classes=4)
    loss_fn = DetectionLoss()
    opt = paddle.optimizer.AdamW(learning_rate=1e-3,
                                 parameters=model.parameters())
    ds = SyntheticDetection(size=args.batch * args.steps)
    loader = DataLoader(ds, batch_size=args.batch, num_workers=args.workers,
                        collate_fn=collate, use_shared_memory=True,
                        prefetch_factor=2)

    t0 = time.time()
    losses = []
    for step, (imgs, tcls, treg, mask) in enumerate(loader):
        cls_outs, reg_outs = model(imgs)
        loss = loss_fn(cls_outs, reg_outs, tcls, treg, mask)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
        print(f"step {step} loss {losses[-1]:.4f} "
              f"({(time.time() - t0) / (step + 1):.2f}s/step)")
        if step + 1 >= args.steps:
            break

    # post-processing end-to-end
    dets = model.predict(imgs[:1], score_thresh=0.3, top_k=10)
    print(f"done: loss {losses[0]:.4f} -> {losses[-1]:.4f}; "
          f"{len(dets[0]['boxes'])} detections on sample 0")
    assert losses[-1] < losses[0]


if __name__ == "__main__":
    main()
