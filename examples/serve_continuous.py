"""Continuous-batching serving: mixed-length traffic through ONE
fixed-shape decode loop (reference: the vLLM-style serving tier around
fused_multi_transformer).

Run:  python examples/serve_continuous.py
"""
import os
import sys
import threading

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import numpy as np

if os.environ.get("FORCE_CPU", "1") == "1":
    import jax

    jax.config.update("jax_platforms", "cpu")

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousServingEngine
from paddle_tpu.models import LlamaForCausalLM, llama_tiny


def main():
    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny(num_hidden_layers=2))
    engine = ContinuousServingEngine(model, max_batch_size=4, max_len=128)
    rng = np.random.RandomState(0)

    results = {}

    def client(name, prompt_len, budget):
        prompt = rng.randint(0, 128, (1, prompt_len)).astype(np.int64)
        out = engine.generate(prompt, max_new_tokens=budget, timeout=600)
        results[name] = tuple(out.shape)

    with engine:
        # six clients with different prompt lengths and budgets share
        # every decode step; slots are reused as requests finish
        threads = [threading.Thread(target=client,
                                    args=(f"req{i}", 4 + 3 * i, 4 + i))
                   for i in range(6)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()

    for name in sorted(results):
        print(f"{name}: output shape {results[name]}")
    print(f"prefills={engine.prefills} decode_steps={engine.decode_steps} "
          f"(sum of per-request budgets would be "
          f"{sum(4 + i for i in range(6))} steps unbatched)")


if __name__ == "__main__":
    main()
