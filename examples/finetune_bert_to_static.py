"""BERT/ERNIE fine-tune under @to_static (BASELINE.json configs[2]) — the
dy2static flow: eager model wrapped by paddle.jit.to_static compiles the
step through jax.jit → HLO; AMP GradScaler included.

    python examples/finetune_bert_to_static.py --steps 8
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

if os.environ.get("FORCE_CPU", "1") == "1":
    import jax
    jax.config.update("jax_platforms", "cpu")

import numpy as np

import paddle_tpu as paddle
from paddle_tpu.models import BertForSequenceClassification, bert_tiny


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=8)
    ap.add_argument("--batch", type=int, default=4)
    ap.add_argument("--seq", type=int, default=32)
    args = ap.parse_args()

    paddle.seed(0)
    cfg = bert_tiny()
    cfg.num_labels = 2
    model = BertForSequenceClassification(cfg)
    model = paddle.jit.to_static(model)          # compile the forward
    opt = paddle.optimizer.AdamW(learning_rate=3e-4,
                                 parameters=model.parameters())
    scaler = paddle.amp.GradScaler(init_loss_scaling=2.0 ** 10)
    loss_fn = paddle.nn.CrossEntropyLoss()

    rng = np.random.default_rng(0)
    ids = paddle.to_tensor(rng.integers(0, cfg.vocab_size,
                                        (args.batch, args.seq)), "int64")
    labels = paddle.to_tensor(rng.integers(0, 2, (args.batch,)), "int64")

    losses = []
    for step in range(args.steps):
        with paddle.amp.auto_cast(level="O1"):
            logits = model(ids)
            loss = loss_fn(logits, labels)
        scaled = scaler.scale(loss)
        scaled.backward()
        scaler.step(opt)
        scaler.update()
        opt.clear_grad()
        losses.append(float(loss))
        print(f"step {step} loss {losses[-1]:.4f} "
              f"(loss_scale {float(scaler.get_scale_ratio()):.0f})")
    assert losses[-1] < losses[0]
    print("done")


if __name__ == "__main__":
    main()
