"""Metric time-series + alert rules (ISSUE 11): deterministic tick
sampling, counter-reset-aware rates, ring eviction, burn-rate window
edges, the PADDLE_ALERT_RULES grammar, alert telemetry/dump wiring, and
the two telemetry satellites (HELP/TYPE exposition defaults, JSONL
rotation)."""
import json
import os
import threading

import pytest

from paddle_tpu.profiler import alerts, timeseries
from paddle_tpu.profiler.telemetry import MetricRegistry
from paddle_tpu.profiler.timeseries import MetricsHistory


def _slo_registry():
    """A private registry with SLO-shaped counters the tests drive by
    hand (the global registry stays untouched)."""
    reg = MetricRegistry()
    bad = reg.counter("paddle_slo_violations_total", labels=("slo",))
    good = reg.counter("paddle_slo_goodput_total", labels=("slo",))
    return reg, good, bad


# ---------------------------------------------------------------------------
# history sampling + queries
# ---------------------------------------------------------------------------

def test_tick_window_and_latest():
    reg = MetricRegistry()
    g = reg.gauge("load")
    h = MetricsHistory(capacity=64, registry=reg)
    for t, v in enumerate([1.0, 3.0, 9.0, 5.0, 7.0]):
        g.set(v)
        h.tick(now=float(t))
    assert h.ticks == 5
    assert h.latest("load") == (4.0, 7.0)
    w = h.window("load", window_s=2.0, now=4.0)   # t in {2,3,4}
    assert w["count"] == 3
    assert w["min"] == 5.0 and w["max"] == 9.0
    assert w["mean"] == pytest.approx(7.0)
    full = h.window("load")
    assert full["count"] == 5 and full["p95"] == 9.0
    # never-sampled series answer empty, not raise
    assert h.points("nope") == []
    assert h.window("nope")["count"] == 0
    assert h.rate("nope") == 0.0


def test_counter_rate_and_reset_detection():
    """A process restart mid-history (counter drops) must yield the
    post-restart increase, never a huge negative rate."""
    reg = MetricRegistry()
    c = reg.counter("reqs")
    h = MetricsHistory(capacity=64, registry=reg)
    for t, total in enumerate([2, 5, 9, 12]):
        c._default_child().value = float(total)
        h.tick(now=float(t))
    assert h.rate("reqs") == pytest.approx(10.0 / 3.0)
    # restart: counter falls back to 1 then climbs again
    for t, total in enumerate([1, 4], start=4):
        c._default_child().value = float(total)
        h.tick(now=float(t))
    # increase = 10 (pre) + 1 (reset restart credit) + 3 = 14 over 5s
    r = h.rate("reqs")
    assert r == pytest.approx(14.0 / 5.0)
    assert r > 0
    assert h.increase("reqs") == pytest.approx(14.0)


def test_ring_eviction_under_capacity():
    reg = MetricRegistry()
    g = reg.gauge("x")
    h = MetricsHistory(capacity=8, registry=reg)
    for t in range(20):
        g.set(float(t))
        h.tick(now=float(t))
    pts = h.points("x")
    assert len(pts) == 8                       # bounded
    assert pts[0] == (12.0, 12.0)              # oldest evicted first
    assert pts[-1] == (19.0, 19.0)
    # eviction is observable: the per-series drop count and the
    # registry counter both moved
    s = h._find("x")
    assert s.dropped == 12
    assert reg.counter("paddle_history_points_evicted_total") \
        ._default_child().value >= 12
    assert reg.counter("paddle_history_samples_total") \
        ._default_child().value == 20
    assert reg.gauge("paddle_history_series")._default_child().value >= 1


def test_histogram_expands_to_derived_series():
    reg = MetricRegistry()
    hist = reg.histogram("lat_seconds")
    h = MetricsHistory(capacity=16, registry=reg)
    for v in (0.01, 0.02, 0.04):
        hist.observe(v)
    h.tick(now=1.0)
    assert h.latest("lat_seconds:count")[1] == 3
    assert h.latest("lat_seconds:sum")[1] == pytest.approx(0.07)
    assert h.latest("lat_seconds:p95")[1] > 0
    names = h.series_names()
    assert "lat_seconds:count" in names and "lat_seconds:p95" in names


def test_history_env_knobs(monkeypatch):
    monkeypatch.setenv("PADDLE_HISTORY_CAPACITY", "33")
    monkeypatch.setenv("PADDLE_HISTORY_INTERVAL_S", "0.125")
    h = MetricsHistory(registry=MetricRegistry())
    assert h.capacity == 33
    assert h.interval_s == 0.125


def test_history_disabled_is_inert():
    """PADDLE_HISTORY off (the default): the wired call site is a bool
    check — no tick, and the global instance is not even built."""
    was_enabled = timeseries._ENABLED
    was_hist = timeseries._HISTORY
    try:
        timeseries._ENABLED = False
        timeseries._HISTORY = None
        assert timeseries.history_tick() is None
        assert timeseries._HISTORY is None        # untouched when off
        timeseries._ENABLED = True
        assert timeseries.history_tick(now=1.0) is not None
        assert timeseries._HISTORY is not None
    finally:
        timeseries._HISTORY = was_hist
        timeseries._ENABLED = was_enabled


def test_disabled_history_adds_no_step_cost():
    """Overhead guard (the disabled half of the ISSUE 11 acceptance):
    a step loop with the history machinery present-but-disabled must
    show no measurable added per-step cost — same disabled-path guard
    pattern (and bench machinery) as the flight recorder's."""
    import numpy as np

    import bench

    was_enabled = timeseries._ENABLED
    was_hist = timeseries._HISTORY
    timeseries._ENABLED = False
    timeseries._HISTORY = None
    try:
        x = np.random.default_rng(0).normal(size=200_000).astype(
            np.float32)

        def step():
            return float(np.tanh(x).sum())

        def gated_step():
            timeseries.history_tick()      # the wired disabled-path call
            return step()

        pct = min(
            bench._telemetry_overhead_pct(step, lambda r: None, steps=30,
                                          instrumented_step=gated_step)
            for _ in range(3))
        assert pct < 10.0, f"disabled history costs {pct}% per step"
        assert timeseries._HISTORY is None   # truly sampled nothing
    finally:
        timeseries._HISTORY = was_hist
        timeseries._ENABLED = was_enabled


def test_background_sampler_start_stop():
    reg = MetricRegistry()
    reg.gauge("g").set(1.0)
    h = MetricsHistory(capacity=32, interval_s=0.01, registry=reg)
    h.start()
    try:
        evt = threading.Event()
        h.add_tick_observer(lambda hh, now: evt.set())
        assert evt.wait(5.0)
    finally:
        h.stop()
    assert h.ticks >= 1
    assert len(h.points("g")) >= 1


def test_export_jsonl_and_chrome_counter_tracks(tmp_path):
    reg = MetricRegistry()
    c = reg.counter("paddle_foo_total")
    h = MetricsHistory(capacity=16, registry=reg)
    for t in range(3):
        c.inc()
        h.tick(now=float(t))
    path = tmp_path / "hist.jsonl"
    n = h.export_jsonl(str(path))
    lines = [json.loads(ln) for ln in path.read_text().splitlines()]
    assert lines[0]["schema"] == timeseries.HISTORY_SCHEMA
    assert lines[0]["ticks"] == 3
    recs = {r["name"]: r for r in lines[1:]}
    assert len(recs) == n
    assert recs["paddle_foo_total"]["kind"] == "counter"
    assert [p[1] for p in recs["paddle_foo_total"]["points"]] == [1, 2, 3]
    # chrome counter tracks merge into the per-rank trace flow
    trace = h.to_chrome(pid="history")
    assert all(e["ph"] == "C" for e in trace["traceEvents"])
    from paddle_tpu.profiler.flight_recorder import merge_chrome_traces
    merged = merge_chrome_traces({0: {"traceEvents": []},
                                  "history": trace})
    counters = [e for e in merged["traceEvents"] if e.get("ph") == "C"]
    assert len(counters) >= 3
    assert all(e["pid"] == "history" for e in counters)
    # filtered export
    assert h.to_chrome(match="no_such")["traceEvents"] == []


# ---------------------------------------------------------------------------
# alert rules
# ---------------------------------------------------------------------------

def test_threshold_rule_above_below_and_hold():
    reg = MetricRegistry()
    g = reg.gauge("paddle_fleet_replicas_alive")
    h = MetricsHistory(capacity=32, registry=reg)
    rule = alerts.ThresholdRule(metric="paddle_fleet_replicas_alive",
                                below=2, severity="page")
    eng = alerts.AlertEngine(history=h, rules=[rule])
    g.set(3)
    h.tick(now=0.0)
    assert eng.evaluate(now=0.0) == []
    g.set(1)
    h.tick(now=1.0)
    tr = eng.evaluate(now=1.0)
    assert tr and tr[0]["action"] == "fired"
    assert rule.name in eng.active
    g.set(2)
    h.tick(now=2.0)
    assert eng.evaluate(now=2.0)[0]["action"] == "cleared"
    assert not eng.active
    # for_s hold: a single breaching blip must NOT page
    hold = alerts.ThresholdRule(name="held", metric="q", above=5.0,
                                for_s=2.0)
    q = reg.gauge("q")
    eng2 = alerts.AlertEngine(history=h, rules=[hold])
    for t, v in enumerate([1.0, 9.0, 1.0, 9.0, 9.0, 9.0, 9.0]):
        q.set(v)
        h.tick(now=10.0 + t)
        eng2.evaluate(now=10.0 + t)
    # breaches only from t=13 on; hold window (2 s) satisfied at t=15
    fires = [t for t in eng2.transitions if t["action"] == "fired"]
    assert len(fires) == 1 and fires[0]["t"] == 15.0


def test_burn_rate_fast_slow_window_edges():
    """The multi-window contract at its edges: a violation burst must
    breach BOTH windows to fire, and the fast window alone clearing
    un-fires it while the slow window still burns."""
    reg, good, bad = _slo_registry()
    h = MetricsHistory(capacity=256, registry=reg)
    rule = alerts.BurnRateRule(budget=0.25, fast_window_s=3.0,
                               slow_window_s=9.0, factor=1.0)
    eng = alerts.AlertEngine(history=h, rules=[rule])
    # 0..9: pure goodput — burn 0 everywhere
    for t in range(10):
        good.inc(slo="request")
        h.tick(now=float(t))
        eng.evaluate(now=float(t))
    assert not eng.active
    # t=10,11: violations start — fast window breaches immediately but
    # the slow window is still diluted by 8 good requests -> no fire
    for t in (10, 11):
        bad.inc(slo="request")
        h.tick(now=float(t))
        eng.evaluate(now=float(t))
    assert rule.burn(h, 3.0, 11.0) >= 1.0
    assert rule.burn(h, 9.0, 11.0) < 1.0
    assert not eng.active, "fast-only breach must not page"
    # keep violating: slow window crosses too -> fires
    t_fired = None
    for t in range(12, 20):
        bad.inc(slo="request")
        h.tick(now=float(t))
        if eng.evaluate(now=float(t)) and t_fired is None:
            t_fired = t
    assert rule.name in eng.active and t_fired is not None
    # recovery: goodput resumes; the FAST window clears the alert even
    # while the slow window still remembers the burst
    t_cleared = None
    for t in range(20, 30):
        good.inc(slo="request")
        h.tick(now=float(t))
        trs = eng.evaluate(now=float(t))
        if trs and trs[0]["action"] == "cleared":
            t_cleared = t
            break
    assert t_cleared is not None
    assert rule.burn(h, 9.0, float(t_cleared)) >= 1.0, \
        "cleared on the fast window while the slow window still burned"
    # no-traffic windows burn 0 (division guard)
    assert rule.burn(h, 3.0, 1000.0) == 0.0


def test_parse_rules_grammar_and_env(monkeypatch):
    spec = ("threshold:metric=paddle_fleet_replicas_alive,below=2,"
            "severity=page;"
            "burn_rate:slo=request,budget=0.1,fast=30,slow=120,"
            "factor=2,name=slo_burn")
    rules = alerts.parse_rules(spec)
    assert isinstance(rules[0], alerts.ThresholdRule)
    assert rules[0].below == 2.0 and rules[0].severity == "page"
    br = rules[1]
    assert isinstance(br, alerts.BurnRateRule)
    assert (br.name, br.budget, br.fast_window_s, br.slow_window_s,
            br.factor) == ("slo_burn", 0.1, 30.0, 120.0, 2.0)
    with pytest.raises(ValueError):
        alerts.parse_rules("bogus:metric=x")
    with pytest.raises(ValueError):
        alerts.parse_rules("threshold:metric=x,wat=1")
    with pytest.raises(ValueError):
        alerts.ThresholdRule(metric="x")            # no bound
    with pytest.raises(ValueError):
        alerts.BurnRateRule(budget=0.0)             # empty budget
    with pytest.raises(ValueError):
        alerts.BurnRateRule(fast_window_s=60, slow_window_s=30)
    # the PADDLE_ALERT_RULES env grammar seeds the global engine
    monkeypatch.setenv("PADDLE_ALERT_RULES",
                       "threshold:metric=qq,above=1")
    alerts.reset_alert_engine()
    try:
        eng = alerts.get_alert_engine()
        assert "threshold_qq" in eng.rules
        assert alerts.active_alerts() == {}
    finally:
        alerts.reset_alert_engine()


def test_alert_transitions_telemetry_events_and_dump(tmp_path):
    """Firing lands in all three places: the paddle_alerts_total /
    paddle_alert_active telemetry pair, a flight-recorder event, and
    the alerts state provider inside a watchdog dump."""
    from paddle_tpu.profiler import flight_recorder as fr
    from paddle_tpu.profiler.telemetry import get_registry

    reg, good, bad = _slo_registry()
    h = MetricsHistory(capacity=64, registry=reg)
    eng = alerts.AlertEngine(history=h)
    rule = eng.add_rule(alerts.BurnRateRule(
        name="slo_burn", budget=0.5, fast_window_s=2.0, slow_window_s=4.0,
        factor=1.0, severity="page"))
    fr.register_state_provider("alerts", eng.state)
    was_enabled = fr.is_enabled()
    fr.enable()
    try:
        eng.attach(h)                 # evaluates on each tick
        for t in range(4):
            bad.inc(slo="request")
            h.tick(now=float(t))
        assert "slo_burn" in eng.active
        g = get_registry()
        assert g.counter("paddle_alerts_total").value(
            rule="slo_burn", severity="page") >= 1
        assert g.gauge("paddle_alert_active").value(rule="slo_burn") == 1
        evs = [e for e in fr.get_flight_recorder().events(kind="alert")
               if e["rule"] == "slo_burn"]
        assert evs and evs[-1]["action"] == "fired"
        # watchdog dump carries the active alert
        dump = fr.get_flight_recorder().dump(reason="test",
                                             directory=str(tmp_path))
        payload = json.load(open(next(iter(dump["ranks"].values()))))
        assert "slo_burn" in payload["state"]["alerts"]["active"]
        # clear
        for t in range(4, 10):
            good.inc(slo="request")
            h.tick(now=float(t))
        assert not eng.active
        assert g.gauge("paddle_alert_active").value(rule="slo_burn") == 0
        acts = [t["action"] for t in eng.transitions]
        assert acts[-2:] == ["fired", "cleared"]
    finally:
        eng.detach()
        fr.unregister_state_provider("alerts")
        if not was_enabled:
            fr.disable()


# ---------------------------------------------------------------------------
# telemetry satellites
# ---------------------------------------------------------------------------

def test_exposition_help_type_defaults():
    """metrics_text() carries # HELP / # TYPE for every family, and an
    un-helped family self-documents with its own name (real Prometheus
    scrapers warn on empty HELP)."""
    reg = MetricRegistry()
    reg.counter("bare_total").inc()
    reg.gauge("described", help="a described gauge").set(2)
    reg.histogram("lat_seconds").observe(0.01)
    text = reg.to_text()
    assert "# HELP bare_total bare_total\n" in text
    assert "# TYPE bare_total counter\n" in text
    assert "# HELP described a described gauge\n" in text
    assert "# TYPE described gauge\n" in text
    assert "# TYPE lat_seconds histogram\n" in text
    for line in text.splitlines():
        if line.startswith("# HELP"):
            assert len(line.split(" ", 3)) == 4 and line.split(" ", 3)[3]


def test_export_jsonl_rotation(tmp_path, monkeypatch):
    """bench_telemetry.jsonl must not grow forever: past
    PADDLE_TELEMETRY_JSONL_MAX_MB the file rotates to <path>.1 and the
    append stays a single O_APPEND write (whole lines only)."""
    reg = MetricRegistry()
    for i in range(40):
        reg.counter(f"pad_{i:02d}_total", labels=("k",)).inc(k="v" * 40)
    path = tmp_path / "t.jsonl"
    monkeypatch.setenv("PADDLE_TELEMETRY_JSONL_MAX_MB", "0.002")  # ~2 KiB
    for _ in range(6):
        reg.export_jsonl(str(path))
    rotated = tmp_path / "t.jsonl.1"
    assert rotated.exists(), "cap exceeded without rotation"
    assert path.stat().st_size <= 0.002 * (1 << 20) + 8192
    # every line in both files parses whole
    for p in (path, rotated):
        for ln in p.read_text().splitlines():
            assert json.loads(ln)["metrics"]
    # rotation disabled: the file just grows
    monkeypatch.setenv("PADDLE_TELEMETRY_JSONL_MAX_MB", "0")
    before = path.stat().st_size
    reg.export_jsonl(str(path))
    assert path.stat().st_size > before
    assert os.path.getsize(rotated) > 0
