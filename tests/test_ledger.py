"""Determinism observatory (ISSUE 13): digest ledger unit tier, the
``bitflip:`` fault directive, dp-4 cross-rank divergence acceptance,
warn-mode bit-parity, KV publish/gather/compare, requeue + disagg
token-stream attestation, handoff blob digests, golden-ledger
roundtrip and the stdlib-only ``tools/ledger_diff.py`` CLI.

Acceptance here: dp-4 sim with ``PADDLE_FAULT_PLAN="bitflip:rank=2,
step=5"`` — the ledger's cross-rank comparator raises a structured
``DivergenceError`` at step 5 naming rank 2 and the exact parameter,
the built-in ``numerics_divergence`` alert fires, and the watchdog
dump's ``ledger`` state provider carries the latched divergence; the
identical run without the fault plan exports a golden ledger that is
byte-identical across two same-seed runs; a hard-killed replica's
requeued request passes token-stream attestation with ledger-on
outputs bit-identical to ledger-off."""
import hashlib
import json
import os
import subprocess
import sys
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn
from paddle_tpu.autograd import tape
from paddle_tpu.distributed import fault, simulator
from paddle_tpu.distributed.fleet.elastic.tcp_kv import MemKVStore
from paddle_tpu.inference import ContinuousServingEngine, ServingRouter
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.profiler import (alerts, flight_recorder as flight,
                                 ledger, request_trace as rt, timeseries)
from paddle_tpu.profiler.ledger import DivergenceError
from paddle_tpu.profiler.telemetry import get_registry

REPO = os.path.join(os.path.dirname(__file__), "..")
ENGINE_KW = dict(max_batch_size=4, max_len=160, page_size=16,
                 prefill_chunk_tokens=32)


@pytest.fixture(autouse=True)
def _clean_ledger():
    rt.enable()
    rt.get_trace_store().clear()
    yield
    ledger.disable()
    ledger.reset()
    fault.clear()
    alerts.reset_alert_engine()
    timeseries.reset()
    flight.disable()
    flight.reset()


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return LlamaForCausalLM(llama_tiny(num_hidden_layers=1,
                                       max_position_embeddings=256))


def _mlp(seed=0, din=16, dh=16, dout=4):
    """Deterministic per-rank init: explicit numpy values, NOT the
    process-global paddle generator (whose draw counter interleaves
    across simulated rank threads)."""
    net = nn.Sequential(nn.Linear(din, dh), nn.Tanh(), nn.Linear(dh, dout))
    wr = np.random.default_rng(seed)
    for p in net.parameters():
        p.set_value(paddle.to_tensor(
            (wr.normal(size=p.shape) * 0.1).astype(np.float32)))
    return net


def _oracle(model, p, n):
    return np.asarray(model.generate(paddle.to_tensor(p),
                                     max_new_tokens=n)._data)


def _shared_prompts(n_req=4, sys_len=32, tail=8, seed=0):
    rng = np.random.RandomState(seed)
    sys_prompt = rng.randint(0, 128, sys_len)
    return [np.concatenate([sys_prompt, rng.randint(0, 128, tail)])
            .astype(np.int64)[None] for _ in range(n_req)]


# ---------------------------------------------------------------------------
# unit tier: digests + comparator
# ---------------------------------------------------------------------------


class TestDigestOracle:
    def test_digest_stable_and_bit_sensitive(self):
        a = np.arange(6, dtype=np.float32).reshape(2, 3)
        assert ledger.tensor_digest(a) == ledger.tensor_digest(a.copy())
        # dtype- and shape-tagged
        assert ledger.tensor_digest(a) != \
            ledger.tensor_digest(a.astype(np.float64))
        assert ledger.tensor_digest(a) != \
            ledger.tensor_digest(a.reshape(3, 2))
        # raw BIT patterns, not values: -0.0 != 0.0, NaN payloads count
        z, z2 = np.zeros(3, np.float32), np.zeros(3, np.float32)
        z2[0] = -0.0
        assert ledger.tensor_digest(z) != ledger.tensor_digest(z2)
        # one flipped mantissa bit changes the digest
        b = a.copy()
        b.view(np.uint32)[0] ^= 1
        assert ledger.tensor_digest(a) != ledger.tensor_digest(b)

    def test_insertion_order_independent(self, tmp_path):
        """Same tensors => same exported ledger, regardless of the
        order entries were recorded in (ISSUE 13 stability oracle)."""
        rows = {"grad:p0000": "aa", "param:p0000": "bb",
                "grad:p0001": "cc", "param:p0001": "dd"}
        led1 = ledger.StepLedger(mode="warn")
        led1._commit(0, 0, dict(rows))
        led2 = ledger.StepLedger(mode="warn")
        led2._commit(0, 0, dict(reversed(list(rows.items()))))
        p1 = led1.export_golden(str(tmp_path / "a.jsonl"))
        p2 = led2.export_golden(str(tmp_path / "b.jsonl"))
        with open(p1, "rb") as f1, open(p2, "rb") as f2:
            assert f1.read() == f2.read()

    def test_first_divergence_majority_and_order(self):
        base = {"grad:p0000": "g0", "grad:p0001": "g1",
                "param:p0000": "w0", "param:p0001": "w1"}
        # rank 2 outvoted 3:1 on BOTH a grad and a param entry: the
        # grad is named (canonical order: cause before effect)
        bad = dict(base, **{"grad:p0001": "XX", "param:p0001": "YY"})
        div = ledger.first_divergence(
            {0: base, 1: base, 2: bad, 3: base})
        assert div["rank"] == 2 and div["tensor"] == "grad:p0001"
        # grad.local entries are never compared cross-rank
        div = ledger.first_divergence(
            {0: dict(base, **{"grad.local:w": "a"}),
             1: dict(base, **{"grad.local:w": "b"})})
        assert div is None
        # a rank missing a tensor the others have IS divergence
        short = {k: v for k, v in base.items() if k != "param:p0001"}
        div = ledger.first_divergence({0: base, 1: base, 2: short})
        assert div["rank"] == 2 and div["tensor"] == "param:p0001"
        # two-rank tie sides with the lowest rank
        div = ledger.first_divergence(
            {0: base, 1: dict(base, **{"param:p0000": "zz"})})
        assert div["rank"] == 1 and div["tensor"] == "param:p0000"

    def test_env_knobs(self, monkeypatch):
        monkeypatch.setenv("PADDLE_LEDGER_MODE", "warn")
        monkeypatch.setenv("PADDLE_LEDGER_INTERVAL", "4")
        monkeypatch.setenv("PADDLE_LEDGER_CAPACITY", "32")
        monkeypatch.setenv("PADDLE_LEDGER_STREAMS", "16")
        led = ledger.StepLedger()
        assert (led.mode, led.interval, led.capacity,
                led.stream_capacity) == ("warn", 4, 32, 16)
        monkeypatch.setenv("PADDLE_LEDGER_MODE", "explode")
        with pytest.raises(ValueError):
            ledger.StepLedger()

    def test_disabled_layer_is_inert(self):
        assert not ledger.is_enabled()
        ledger.note_stream_token("t", 0, 1)      # all no-ops
        assert ledger.stream_digest("t") is None
        assert ledger.attest_delivery("t") is None
        assert ledger.seal_handoff({}) is None
        net = _mlp()
        opt = paddle.optimizer.SGD(learning_rate=0.01,
                                   parameters=net.parameters())
        loss = (net(paddle.to_tensor(
            np.ones((2, 16), np.float32))) ** 2).mean()
        loss.backward()
        opt.step()
        assert ledger.get_ledger().rows() == []

    def test_import_time_enable_knob(self):
        code = ("import jax; jax.config.update('jax_platforms', 'cpu')\n"
                "from paddle_tpu.profiler import ledger\n"
                "assert ledger.is_enabled()\n"
                "assert ledger.get_ledger().mode == 'warn'\n")
        env = dict(os.environ, PADDLE_LEDGER="1",
                   PADDLE_LEDGER_MODE="warn", JAX_PLATFORMS="cpu")
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True, timeout=120,
                              cwd=REPO)
        assert proc.returncode == 0, proc.stderr


# ---------------------------------------------------------------------------
# bitflip fault directive
# ---------------------------------------------------------------------------


class TestBitflipFault:
    def test_parse_bitflip_directive(self):
        plan = fault.FaultPlan.parse("bitflip:rank=2,step=5")
        f = plan.faults[0]
        assert (f.kind, f.rank, f.step) == ("bitflip", 2, 5)
        with pytest.raises(ValueError):
            fault.FaultPlan.parse("bitflip:rank=0")     # needs a trigger
        with pytest.raises(ValueError):
            fault.FaultPlan.parse("gamma:rank=0,step=1")

    def test_flip_is_single_bit_once_only(self):
        net = _mlp(3)
        x = paddle.to_tensor(np.random.default_rng(1)
                             .normal(size=(4, 16)).astype(np.float32))

        def grads():
            for p in net.parameters():
                p.grad = None
            loss = (net(x) ** 2).mean()
            loss.backward()
            return {p.name: np.asarray(p.grad.numpy()).copy()
                    for p in net.parameters()}

        clean = grads()
        tape.flip_bit_next_leaf_grad()
        flipped = grads()
        diffs = [k for k in clean
                 if not np.array_equal(clean[k], flipped[k])]
        assert len(diffs) == 1, diffs
        xor = clean[diffs[0]].view(np.uint32) ^ \
            flipped[diffs[0]].view(np.uint32)
        assert sum(bin(v).count("1") for v in xor.ravel()) == 1
        # once-only: the next backward is clean again
        again = grads()
        for k in clean:
            np.testing.assert_array_equal(clean[k], again[k])

    def test_fault_fire_arms_flip_and_counts(self):
        fault.install("bitflip:rank=0,step=1")
        fault.check_step(0)                      # not due
        fault.check_step(1)                      # arms the tape poison
        c = get_registry().get("paddle_elastic_events_total")
        assert c.value(kind="bitflip") >= 1
        net = _mlp(4)
        x = paddle.to_tensor(np.ones((2, 16), np.float32))
        loss = (net(x) ** 2).mean()
        loss.backward()                          # consumes the poison
        fault.check_step(1)                      # once-only: no re-fire
        assert fault.active_plan().faults[0].fired


# ---------------------------------------------------------------------------
# optimizer-step digests (single rank)
# ---------------------------------------------------------------------------


class TestOptimizerCommits:
    def test_step_rows_and_local_grad_entries(self):
        ledger.enable(mode="warn", grad_ready=True)
        net = _mlp(0)
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=net.parameters())
        x = paddle.to_tensor(np.random.default_rng(2)
                             .normal(size=(4, 16)).astype(np.float32))
        for _ in range(2):
            loss = (net(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        rows = ledger.get_ledger().rows(rank=0)
        assert [r["step"] for r in rows] == [0, 1]
        names = set(rows[0]["entries"])
        n_params = len(list(net.parameters()))
        assert sum(1 for n in names if n.startswith("grad:")) == n_params
        assert sum(1 for n in names if n.startswith("param:")) == n_params
        # tape-attached local digests ride in the same row
        assert sum(1 for n in names
                   if n.startswith("grad.local:")) == n_params
        # the human name map covers every positional key
        assert set(rows[0]["names"]) == \
            {n.split(":")[1] for n in names if n.startswith("grad:")}
        c = get_registry().get("paddle_ledger_digests_total")
        assert c.value(kind="grad") >= n_params
        assert c.value(kind="param") >= n_params
        assert c.value(kind="grad_local") >= n_params

    def test_interval_skips_steps(self):
        ledger.enable(mode="warn", interval=2)
        net = _mlp(1)
        opt = paddle.optimizer.SGD(learning_rate=0.05,
                                   parameters=net.parameters())
        x = paddle.to_tensor(np.ones((2, 16), np.float32))
        for _ in range(4):
            loss = (net(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        rows = ledger.get_ledger().rows(rank=0)
        assert [r["step"] for r in rows] == [0, 1, 2, 3]
        assert [bool(r["entries"]) for r in rows] == [
            True, False, True, False]


# ---------------------------------------------------------------------------
# dp-4 acceptance + parity
# ---------------------------------------------------------------------------


def _dp4_worker(steps=7):
    r = dist.get_rank()
    net = _mlp(seed=0)
    strat = dist.fleet.DistributedStrategy()
    strat.hybrid_configs = {"dp_degree": 4}
    dp = dist.parallel.DataParallel(net, strategy=strat)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=net.parameters())
    ledger.attach()                      # per-rank: tape hooks are TLS
    rngX = np.random.default_rng(7)
    X = rngX.normal(size=(4 * 4 * steps, 16)).astype(np.float32)
    names = [p.name for p in net.parameters()]
    s = -1
    try:
        losses = []
        for s in range(steps):
            fault.check_step(s)
            lo = (s * 4 + r) * 4
            loss = (dp(paddle.to_tensor(X[lo:lo + 4])) ** 2).mean()
            loss.backward()
            losses.append(np.asarray(loss.numpy()).copy())
            opt.step()
            opt.clear_grad()
        return ("done", losses,
                [np.asarray(p.numpy()).copy() for p in net.parameters()],
                names)
    except DivergenceError as e:
        w = simulator.active_world()
        if w is not None:
            w.mark_dead(r)               # unblock the survivors
        return ("divergence", e, None, names)
    except simulator.RankFailure as e:
        return ("peer_failure", s, e.rank, names)
    finally:
        dp.shutdown()
        ledger.detach()


class TestAcceptanceDp4:
    def test_bitflip_raises_naming_rank_and_param(self, monkeypatch,
                                                  tmp_path):
        """ISSUE 13 acceptance: dp-4 sim with
        PADDLE_FAULT_PLAN="bitflip:rank=2,step=5" — the comparator
        raises DivergenceError at step 5 naming rank 2 and the exact
        parameter, survivors surface structured RankFailures, the
        built-in numerics_divergence alert fires, and the watchdog
        dump's ledger state provider carries the latched divergence."""
        monkeypatch.setenv("PADDLE_FAULT_PLAN", "bitflip:rank=2,step=5")
        monkeypatch.setenv("PADDLE_COMM_OVERLAP_TIMEOUT_S", "60")
        fault.clear()                    # re-arm lazy env parsing
        flight.enable()
        ledger.enable(mode="raise")
        results = dist.spawn(_dp4_worker, nprocs=4).results
        by_kind = {}
        for i, res in enumerate(results):
            by_kind.setdefault(res[0], []).append((i, res))
        divs = by_kind.get("divergence", [])
        assert divs, results
        detector, (_, err, _, _) = divs[0]
        assert err.kind == "cross_rank"
        assert err.step == 5, "detection must land at step 5"
        assert err.rank == 2, "majority vote must name rank 2"
        # the error names the exact parameter — the DIVERGENT rank's
        # human name substituted back into the positional entry key
        # (every rank's worker returns its own name list at index 3)
        rank2_names = results[2][3]
        assert err.tensor.split(":", 1)[1] in rank2_names, \
            (err.tensor, rank2_names)
        assert err.tensor.startswith(("grad:", "param:"))
        # rank 2's digest is the odd one out in the error payload
        assert err.digests[2] != err.digests[(set(err.digests) - {2}).pop()]
        for _i, res in by_kind.get("peer_failure", []):
            assert res[2] == detector    # failures name the dead rank
        # telemetry + latch + flight event
        c = get_registry().get("paddle_ledger_divergence_total")
        assert c.value(kind="cross_rank") >= 1
        g = get_registry().get("paddle_ledger_divergent_steps")
        assert g.value() >= 1            # the alert rule's signal
        latched = ledger.get_ledger().divergences()
        assert any(d["step"] == 5 and d["rank"] == 2 for d in latched)
        fr = flight.get_flight_recorder()
        assert any(e.get("divergence") == "cross_rank" and e.get("step") == 5
                   for e in fr.events(kind="ledger"))
        # alert: one history tick evaluates the built-in threshold rule
        eng = alerts.get_alert_engine()
        assert "numerics_divergence" in eng.rules
        timeseries.get_history().tick()
        active = alerts.active_alerts()
        assert "numerics_divergence" in active
        assert active["numerics_divergence"]["severity"] == "page"
        # watchdog dump carries the ledger provider with the latch
        out = fr.dump(reason="test", directory=str(tmp_path))
        with open(next(iter(out["ranks"].values()))) as f:
            dumped = json.load(f)
        led_state = dumped["state"]["ledger"]
        assert any(d["step"] == 5 and d["rank"] == 2
                   for d in led_state["divergences"])
        assert led_state["mode"] == "raise"

    def test_warn_mode_records_and_continues(self, monkeypatch):
        """Same bitflip, PADDLE_LEDGER_MODE=warn: every rank completes,
        the divergence is latched (step 5, rank 2) instead of raised."""
        monkeypatch.setenv("PADDLE_FAULT_PLAN", "bitflip:rank=2,step=5")
        monkeypatch.setenv("PADDLE_COMM_OVERLAP_TIMEOUT_S", "60")
        fault.clear()
        ledger.enable(mode="warn")
        results = dist.spawn(_dp4_worker, nprocs=4).results
        assert all(res[0] == "done" for res in results), \
            [res[0] for res in results]
        latched = ledger.get_ledger().divergences()
        assert any(d["kind"] == "cross_rank" and d["step"] == 5
                   and d["rank"] == 2 for d in latched)

    def test_warn_mode_is_bit_identical_to_disabled(self):
        """With the ledger in warn mode and no fault, the dp-4 loss
        trajectory AND final params are bit-identical to ledger-off
        (the sensing layer is read-only), and no divergence latches."""

        def run(sense):
            if sense:
                ledger.enable(mode="warn")
            else:
                ledger.disable()
                ledger.reset()
            results = dist.spawn(_dp4_worker, nprocs=4).results
            assert all(res[0] == "done" for res in results)
            return results

        sensed = run(True)
        assert ledger.get_ledger().divergences() == []
        plain = run(False)
        for (_, l_a, p_a, _), (_, l_b, p_b, _) in zip(sensed, plain):
            for a, b in zip(l_a, l_b):
                np.testing.assert_array_equal(a, b)
            for a, b in zip(p_a, p_b):
                np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# cross-process tier: publish / gather / compare over the KV path
# ---------------------------------------------------------------------------


def test_publish_gather_compare_store():
    ledger.enable(mode="warn")
    led = ledger.get_ledger()
    base = {"grad:p0000": "gg", "param:p0000": "w0"}
    led._commit(0, 0, dict(base), {"p0000": "w"})
    led._commit(1, 0, dict(base, **{"param:p0000": "w1"}), {"p0000": "w"})
    store = MemKVStore()
    assert ledger.publish_ledger(store, rank=0) == 1
    assert ledger.publish_ledger(store, rank=1) == 1
    got = ledger.gather_ledgers(store)
    assert set(got) == {0, 1} and set(got[0]) == {0}
    div = ledger.compare_store(store)
    assert div is not None
    assert (div["step"], div["tensor"]) == (0, "param:p0000")
    assert div["rank"] == 1              # two-way tie sides with rank 0
    # identical ledgers compare clean
    store2 = MemKVStore()
    led2 = ledger.StepLedger(mode="warn")
    led2._commit(0, 0, dict(base))
    led2._commit(1, 0, dict(base))
    for row in led2.rows():
        flight.publish_component_state(
            store2, f"{ledger.KV_LEDGER_PREFIX}{row['rank']}/{row['step']}",
            row)
    assert ledger.compare_store(store2) is None


def test_store_attached_commit_publishes():
    store = MemKVStore()
    ledger.enable(mode="warn", store=store)
    net = _mlp(5)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=net.parameters())
    loss = (net(paddle.to_tensor(np.ones((2, 16), np.float32))) ** 2).mean()
    loss.backward()
    opt.step()
    got = ledger.gather_ledgers(store)
    assert 0 in got and 0 in got[0]
    assert any(k.startswith("grad:") for k in got[0][0])


# ---------------------------------------------------------------------------
# serving: token streams, attestation, handoff digests
# ---------------------------------------------------------------------------


class TestAttestationUnit:
    def test_chain_and_matching_streams_pass(self):
        led = ledger.enable(mode="raise")
        toks = [5, 6, 7]
        for t in toks:
            led.note_stream_token("tr", 1, t)
        for t in toks + [8]:
            led.note_stream_token("tr", 2, t)
        # the chain digest is the documented recurrence
        want = ledger.STREAM_SEED
        for t in toks:
            want = ledger.chain_update(want, t)
        assert led.streams("tr")[1]["digest"] == want
        dg = led.attest_delivery("tr", attempt=2)
        assert dg == led.streams("tr")[2]["digest"]
        c = get_registry().get("paddle_ledger_attestations_total")
        assert c.value(result="pass") >= 1

    def test_tampered_stream_fails_attestation(self):
        led = ledger.enable(mode="raise")
        for t in [5, 6, 7]:
            led.note_stream_token("trx", 1, t)
        for t in [5, 9, 7, 8]:                 # diverges at position 1
            led.note_stream_token("trx", 2, t)
        with pytest.raises(DivergenceError) as ei:
            led.attest_delivery("trx", attempt=2)
        assert ei.value.kind == "attestation"
        assert ei.value.tensor == "tokens:trx"
        assert ei.value.rank == 1              # the non-delivering attempt
        c = get_registry().get("paddle_ledger_attestations_total")
        assert c.value(result="fail") >= 1
        # warn mode records and returns the digest
        led2 = ledger.enable(mode="warn")
        for t in [1, 2]:
            led2.note_stream_token("trw", 1, t)
        for t in [1, 3]:
            led2.note_stream_token("trw", 2, t)
        assert led2.attest_delivery("trw", attempt=2) is not None
        assert any(d["kind"] == "attestation"
                   for d in led2.divergences())

    def test_handoff_blob_seal_and_tamper(self):
        led = ledger.enable(mode="raise")
        blob = {"page_size": 16, "kv_dtype": "native",
                "native_dtype": "float32",
                "digests": [b"\x01" * 20, b"\x02" * 20],
                "layers": [(np.ones((2, 2, 16, 4), np.float32),
                            np.zeros((2, 2, 16, 4), np.float32))],
                "scales": None}
        blob["ledger_digest"] = led.seal_handoff(blob)
        # sealing is idempotent: the digest ignores itself
        assert ledger.blob_digest(blob) == blob["ledger_digest"]
        led.check_handoff(blob)                # bit-exact: passes
        blob["layers"][0][0][0, 0, 0, 0] = 2.0
        with pytest.raises(DivergenceError) as ei:
            led.check_handoff(blob)
        assert ei.value.kind == "handoff"
        c = get_registry().get("paddle_ledger_digests_total")
        assert c.value(kind="handoff") >= 3


class TestServingAttestation:
    def test_engine_outputs_bit_identical_and_trace_digest(self, model):
        """Ledger-on serving outputs are bit-identical to ledger-off,
        and the trace's terminal span carries the stream digest that
        matches a hand-computed chain over the generated tokens."""
        p = _shared_prompts(n_req=1, seed=3)[0]

        def run():
            eng = ContinuousServingEngine(model, **ENGINE_KW)
            with eng:
                return np.asarray(eng.generate(
                    p, max_new_tokens=6, timeout=600).numpy())

        off = run()
        ledger.enable(mode="raise")
        on = run()
        np.testing.assert_array_equal(on, off)
        # trace terminal span carries token_digest
        store = rt.get_trace_store()
        tid = store.trace_ids()[-1]
        rec = store.timeline(tid)
        done = [s for s in rec["spans"] if s["name"] == "done"][0]
        dg = (done.get("tags") or {}).get("token_digest")
        assert dg, rec["spans"]
        want = ledger.STREAM_SEED
        for t in on[0, p.shape[1]:p.shape[1] + 6]:
            want = ledger.chain_update(want, int(t))
        assert dg == want

    def test_requeue_attestation_parity(self, model):
        """ISSUE 13 acceptance (serving): hard-kill a replica
        mid-decode; the requeued request's regenerated stream passes
        attestation against the dead attempt's partial stream (digest
        equal over the common prefix), the delivered event carries the
        token digest, and outputs stay bit-identical to the oracle."""
        ledger.enable(mode="raise")      # attestation failure would raise
        prompts = _shared_prompts(n_req=4, sys_len=32, seed=2)
        want = [_oracle(model, p, 12) for p in prompts]
        router = ServingRouter(model, num_replicas=2, policy="balance",
                               engine_kwargs=ENGINE_KW, store=MemKVStore(),
                               heartbeat_ttl=60.0)
        results, errors = [None] * 4, [None] * 4

        def call(i):
            try:
                results[i] = np.asarray(router.generate(
                    prompts[i], max_new_tokens=12, tenant=f"t{i}",
                    timeout=600).numpy())
            except Exception as e:      # noqa: BLE001 — asserted below
                errors[i] = e

        led = ledger.get_ledger()
        store_rt = rt.get_trace_store()
        with router:
            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(4)]
            for t in threads:
                t.start()
            # kill only once some first attempt has DELIVERED tokens —
            # attestation needs a non-empty attempt-1 stream to check
            # the regenerated attempt-2 stream against
            deadline = time.monotonic() + 10
            victim = None
            while victim is None and time.monotonic() < deadline:
                for tid in store_rt.trace_ids():
                    st = led.streams(tid)
                    if st and max(st) == 1 and st[1]["count"] >= 2:
                        rec = store_rt.timeline(tid)
                        reps = [s.get("replica") for s in rec["spans"]
                                if s.get("replica")]
                        if not reps:
                            continue
                        r = router._replica(reps[-1])
                        if r.alive and r.inflight:
                            victim = r
                            break
                time.sleep(0.01)
            assert victim is not None, "no mid-decode work to kill under"
            router.kill_replica(victim.id)
            for t in threads:
                t.join()
            stats = router.stats()
        assert not [e for e in errors if e], errors
        for g, w in zip(results, want):
            np.testing.assert_array_equal(g, w)
        assert stats["requeues_total"] >= 1, stats
        # find the requeued trace: it has streams from >= 2 attempts,
        # all digest-consistent, and a delivered token_digest tag
        led = ledger.get_ledger()
        store = rt.get_trace_store()
        requeued = [tid for tid in store.trace_ids()
                    if len(led.streams(tid)) >= 2]
        assert requeued, "no request recorded streams from two attempts"
        for tid in requeued:
            streams = led.streams(tid)
            final = streams[max(streams)]
            rec = store.timeline(tid)
            delivered = [s for s in rec["spans"]
                         if s["name"] == "delivered"][0]
            assert (delivered.get("tags") or {}).get("token_digest") \
                == final["digest"]
        c = get_registry().get("paddle_ledger_attestations_total")
        assert c.value(result="pass") >= 4
        assert ledger.get_ledger().divergences() == []

    def test_disagg_attestation_and_handoff_digests(self, model):
        """Disagg fleet with the ledger on: the prefill replica's
        1-token stream attests against the decode replica's full
        stream, the export blob is sealed and verified bit-exact at
        import, outputs bit-identical to the colocated oracle."""
        ledger.enable(mode="raise")
        prompts = _shared_prompts(n_req=3, sys_len=48, seed=4)
        want = [_oracle(model, p, 4) for p in prompts]
        router = ServingRouter(model, num_replicas=2, disagg=True,
                               engine_kwargs=ENGINE_KW, store=MemKVStore(),
                               heartbeat_ttl=60.0)
        with router:
            results = [np.asarray(router.generate(
                p, max_new_tokens=4, timeout=600).numpy())
                for p in prompts]
            dec = router.replicas[1]
            assert dec.engine._cache.pages_imported > 0
        for g, w in zip(results, want):
            np.testing.assert_array_equal(g, w)
        led = ledger.get_ledger()
        # at least one request produced tokens on BOTH replicas
        # (prefill attempt = 1 token, decode attempt = the full stream)
        multi = [tid for tid in rt.get_trace_store().trace_ids()
                 if len(led.streams(tid)) >= 2]
        assert multi, "no trace recorded prefill AND decode streams"
        for tid in multi:
            counts = sorted(s["count"]
                            for s in led.streams(tid).values())
            assert counts[0] == 1        # the prefill replica's token
        # the export was sealed, the import verified, nothing diverged
        st = led.state()
        dirs = [h["direction"] for h in st["handoffs"]]
        assert "export" in dirs and "import" in dirs
        assert led.divergences() == []
        c = get_registry().get("paddle_ledger_digests_total")
        assert c.value(kind="handoff") >= 2


# ---------------------------------------------------------------------------
# golden ledger + ledger_diff CLI
# ---------------------------------------------------------------------------


def _seeded_train(tmp_path, tag, flip_step=None, steps=4):
    """One seeded single-rank training run with a fresh ledger; exports
    and returns the golden path."""
    ledger.reset()
    fault.clear()
    if flip_step is not None:
        fault.install(f"bitflip:rank=0,step={flip_step}")
    ledger.enable(mode="warn")
    net = _mlp(0)
    # deterministic parameter names: the auto-assigned ones come from a
    # process-global counter, which would differ between two in-process
    # runs (two real processes get identical names for free)
    for i, p in enumerate(net.parameters()):
        p.name = f"w{i}"
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=net.parameters())
    rngX = np.random.default_rng(7)
    X = rngX.normal(size=(4 * steps, 16)).astype(np.float32)
    for s in range(steps):
        fault.check_step(s)
        loss = (net(paddle.to_tensor(X[s * 4:(s + 1) * 4])) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
    path = ledger.export_golden(str(tmp_path / f"{tag}.jsonl"))
    ledger.disable()
    fault.clear()
    return path


def _run_ledger_diff(argv):
    """Run tools/ledger_diff.py in a jax/numpy-poisoned subprocess
    (laptop-vs-fleet-ledgers discipline)."""
    tool = os.path.join(REPO, "tools", "ledger_diff.py")
    code = (
        "import sys\n"
        "sys.modules['jax'] = None\n"
        "sys.modules['numpy'] = None\n"
        f"sys.argv = {argv!r}\n"
        "import runpy\n"
        "try:\n"
        f"    runpy.run_path({tool!r}, run_name='__main__')\n"
        "except SystemExit as e:\n"
        "    raise SystemExit(e.code or 0)\n")
    return subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=60)


class TestGoldenLedger:
    def test_same_seed_runs_are_byte_identical(self, tmp_path):
        """ISSUE 13 acceptance: two same-seed runs export byte-identical
        golden ledgers, and ledger_diff reports them identical (exit 0)
        with jax AND numpy poisoned out of the interpreter."""
        a = _seeded_train(tmp_path, "a")
        b = _seeded_train(tmp_path, "b")
        with open(a, "rb") as fa, open(b, "rb") as fb:
            assert fa.read() == fb.read()
        proc = _run_ledger_diff(["ledger_diff.py", a, b])
        assert proc.returncode == 0, proc.stderr
        assert "identical" in proc.stdout

    def test_diff_names_first_divergent_step_and_tensor(self, tmp_path):
        """A bitflipped run diverges from the golden; the CLI names the
        first divergent step (the flip step) and the tensor, exit 1."""
        golden = _seeded_train(tmp_path, "golden")
        bad = _seeded_train(tmp_path, "bad", flip_step=2)
        proc = _run_ledger_diff(["ledger_diff.py", golden, bad])
        assert proc.returncode == 1, proc.stdout + proc.stderr
        assert "FIRST DIVERGENCE: step 2 rank 0" in proc.stdout
        assert "grad:" in proc.stdout
        # steps before the flip agree — step 2 is the FIRST divergence
        assert "step 0" not in proc.stdout and "step 1" not in proc.stdout
        # --json mode round-trips
        proc = _run_ledger_diff(["ledger_diff.py", "--json", golden, bad])
        out = json.loads(proc.stdout)
        assert not out["identical"]
        assert out["divergences"][0]["step"] == 2

    def test_diff_reports_stream_divergence(self, tmp_path):
        led = ledger.enable(mode="warn")
        for t in [1, 2, 3]:
            led.note_stream_token("req-a", 1, t)
        a = ledger.export_golden(str(tmp_path / "sa.jsonl"))
        ledger.reset()
        led = ledger.enable(mode="warn")
        for t in [1, 9, 3]:
            led.note_stream_token("req-a", 1, t)
        b = ledger.export_golden(str(tmp_path / "sb.jsonl"))
        proc = _run_ledger_diff(["ledger_diff.py", a, b])
        assert proc.returncode == 1
        assert "FIRST DIVERGENCE: request req-a" in proc.stdout

    def test_cli_bad_input_exit_2(self, tmp_path):
        good = _seeded_train(tmp_path, "g")
        missing = str(tmp_path / "nope.jsonl")
        assert _run_ledger_diff(
            ["ledger_diff.py", good, missing]).returncode == 2
        notjson = tmp_path / "bad.jsonl"
        notjson.write_text("this is not a ledger\n")
        assert _run_ledger_diff(
            ["ledger_diff.py", good, str(notjson)]).returncode == 2

    def test_golden_env_default_path(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_LEDGER_GOLDEN",
                           str(tmp_path / "env_golden.jsonl"))
        ledger.enable(mode="warn")
        ledger.get_ledger()._commit(0, 0, {"grad:p0000": "x"})
        path = ledger.export_golden()
        assert path == str(tmp_path / "env_golden.jsonl")
        assert os.path.exists(path)
