"""Workload replay harness + fleet console (ISSUE 11).

Unit tier: seeded trace generation is bit-reproducible across all
presets, JSONL round-trips, time_to_recover is a pure function with the
"sustained to end of observation" semantics, env knob defaults.

Acceptance: a seeded 10x bursty replay against a 2-replica fleet fires
the SLO burn-rate alert during the overload episode and clears it
after; ReplayReport.time_to_recover_s agrees exactly with the first
post-burst compliant window recomputed from ``profiler.history()``; a
second trace from the same seed is bit-identical and the report is a
pure recompute. The console renders the exported history without jax.
"""
import importlib.util
import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.elastic.tcp_kv import MemKVStore
from paddle_tpu.inference import ServingRouter
from paddle_tpu.inference.fleet import replay
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.profiler import alerts, request_trace as rt
from paddle_tpu.profiler.telemetry import MetricRegistry
from paddle_tpu.profiler.timeseries import MetricsHistory

REPO = os.path.join(os.path.dirname(__file__), "..")


# ---------------------------------------------------------------------------
# trace generation
# ---------------------------------------------------------------------------

def test_trace_presets_deterministic(tmp_path):
    assert replay.REPLAY_PRESETS == ("poisson", "bursty", "diurnal",
                                     "adversarial")
    for preset in replay.REPLAY_PRESETS:
        a = replay.make_trace(preset=preset, seed=42, duration_s=5.0,
                              rate_rps=1.5)
        b = replay.make_trace(preset=preset, seed=42, duration_s=5.0,
                              rate_rps=1.5)
        assert a.digest() == b.digest(), preset
        assert a.to_jsonl() == b.to_jsonl(), preset
        c = replay.make_trace(preset=preset, seed=43, duration_s=5.0,
                              rate_rps=1.5)
        assert a.digest() != c.digest(), preset
        assert len(a) > 0
        assert all(0 <= r.t < 5.0 for r in a)
        # arrival order is sorted; every request carries its own seed
        ts = [r.t for r in a]
        assert ts == sorted(ts)
        # JSONL round-trip is identity on the canonical form
        path = tmp_path / f"{preset}.jsonl"
        a.to_jsonl(str(path))
        back = replay.load_trace(str(path))
        assert back.digest() == a.digest()
        assert back.preset == preset and back.seed == 42
    bursty = replay.make_trace(preset="bursty", seed=1, duration_s=10.0,
                               rate_rps=1.0, burst_factor=10.0,
                               burst_start_frac=0.4, burst_dur_frac=0.2)
    b0, b1 = bursty.burst_window()
    assert (b0, b1) == (pytest.approx(4.0), pytest.approx(6.0))
    in_burst = sum(1 for r in bursty if b0 <= r.t < b1)
    out_burst = len(bursty) - in_burst
    assert in_burst > out_burst, "10x window must dominate arrivals"
    adv = replay.make_trace(preset="adversarial", seed=1, duration_s=10.0,
                            rate_rps=1.0, tenants=("hog", "fair"))
    a0, a1 = adv.burst_window()
    flood = [r for r in adv if r.t <= a1]
    assert all(r.tenant == "hog" for r in flood)
    assert all(r.prompt_len == 48 for r in flood)   # max length flood
    assert replay.make_trace(preset="poisson", seed=0,
                             duration_s=4.0).burst_window() is None
    with pytest.raises(ValueError):
        replay.make_trace(preset="wat", seed=0)
    with pytest.raises(ValueError):
        replay.load_trace('{"schema": "nope"}')


def test_replay_env_knob_defaults(monkeypatch):
    monkeypatch.setenv("PADDLE_REPLAY_PRESET", "bursty")
    monkeypatch.setenv("PADDLE_REPLAY_SEED", "7")
    tr = replay.make_trace(duration_s=4.0, rate_rps=1.0)
    assert tr.preset == "bursty" and tr.seed == 7
    assert tr.digest() == replay.make_trace(
        preset="bursty", seed=7, duration_s=4.0, rate_rps=1.0).digest()
    monkeypatch.setenv("PADDLE_REPLAY_TIME_SCALE", "0.5")
    h = replay.ReplayHarness(router=None, trace=tr,
                             history=MetricsHistory(
                                 registry=MetricRegistry()))
    assert h.time_scale == 0.5


# ---------------------------------------------------------------------------
# time_to_recover (pure over a hand-built history)
# ---------------------------------------------------------------------------

def _slo_history():
    reg = MetricRegistry()
    bad = reg.counter("paddle_slo_violations_total", labels=("slo",))
    good = reg.counter("paddle_slo_goodput_total", labels=("slo",))
    return MetricsHistory(capacity=256, registry=reg), good, bad


def test_time_to_recover_semantics():
    h, good, bad = _slo_history()
    # violations t=5..8, a quiet gap 9..10, violations again 11..12,
    # then clean goodput: the quiet gap must NOT count as recovery
    for t in range(20):
        if 5 <= t <= 8 or 11 <= t <= 12:
            bad.inc(slo="request")
        elif t >= 13 or t < 5:
            good.inc(slo="request")
        h.tick(now=float(t))
    ttr = replay.time_to_recover(h, burst_end=6.0, window_s=2.0,
                                 budget=0.25, factor=1.0)
    assert ttr is not None
    # with a 2 s trailing window the last violation (t=12) stops
    # polluting at t=15 — recovery must be after the second wave
    assert 6.0 + ttr >= 13.0
    recompute = replay.time_to_recover(h, burst_end=6.0, window_s=2.0,
                                       budget=0.25, factor=1.0)
    assert recompute == ttr                   # pure function
    # still burning at the end of observation: no recovery claimed
    h2, good2, bad2 = _slo_history()
    for t in range(10):
        bad2.inc(slo="request")
        h2.tick(now=float(t))
    assert replay.time_to_recover(h2, burst_end=2.0, window_s=2.0,
                                  budget=0.25, factor=1.0) is None
    # empty history: None, not a crash
    h3, _, _ = _slo_history()
    assert replay.time_to_recover(h3, burst_end=0.0) is None


# ---------------------------------------------------------------------------
# acceptance: 2-replica fleet, seeded burst, alert + recovery
# ---------------------------------------------------------------------------

def test_replay_acceptance_burst_alert_recovery(monkeypatch):
    import paddle_tpu.profiler as profiler
    from paddle_tpu.profiler import timeseries as ts

    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny(num_hidden_layers=1,
                                        max_position_embeddings=256))
    trace = replay.make_trace(
        preset="bursty", seed=11, duration_s=6.0, rate_rps=0.7,
        burst_factor=10.0, burst_start_frac=0.35, burst_dur_frac=0.2,
        prompt_len=(8, 24), new_tokens=(2, 4))
    # bit-reproducible schedule: a second generation from the same seed
    # is byte-identical
    again = replay.make_trace(
        preset="bursty", seed=11, duration_s=6.0, rate_rps=0.7,
        burst_factor=10.0, burst_start_frac=0.35, burst_dur_frac=0.2,
        prompt_len=(8, 24), new_tokens=(2, 4))
    assert again.to_jsonl() == trace.to_jsonl()
    assert again.digest() == trace.digest()

    router = ServingRouter(
        model, num_replicas=2, store=MemKVStore(), heartbeat_ttl=600.0,
        engine_kwargs=dict(max_batch_size=2, max_len=96, page_size=16,
                           prefill_chunk_tokens=32))
    ts.reset()                      # fresh GLOBAL history for this run
    hist = profiler.history()
    engine = alerts.AlertEngine(history=hist)
    rule = engine.add_rule(alerts.BurnRateRule(
        name="slo_burn", budget=0.2, fast_window_s=1.5,
        slow_window_s=4.5, factor=1.0, severity="page"))
    engine.attach(hist)
    try:
        with router:
            # warm the compiled programs, then pick an adaptive TTFT
            # target: 2x a warm sequential request — the burst's
            # queueing (not host speed) decides the violation story
            warm = np.arange(16, dtype=np.int64)[None]
            router.generate(warm, max_new_tokens=2, timeout=600)
            t0 = time.perf_counter()
            router.generate(warm + 16, max_new_tokens=2, timeout=600)
            warm_s = time.perf_counter() - t0
            monkeypatch.setenv("PADDLE_SLO_TTFT_MS",
                               str(round(max(2.0 * warm_s, 0.2) * 1e3, 1)))
            rt.reset_slo_monitor()
            harness = replay.ReplayHarness(
                router, trace, vocab_size=128, history=hist,
                alert_engine=engine, tick_interval_s=0.25,
                recover_window_s=1.5, budget=0.2, factor=1.0)
            report = harness.run()
    finally:
        engine.detach()
        rt.reset_slo_monitor()
    d = report.as_dict()
    assert d["requests"] == len(trace)
    assert d["statuses"].get("ok", 0) == len(trace), d["statuses"]
    b0, b1 = d["burst_t"]

    # the burn-rate alert fired during the overload episode...
    fired = [t for t in d["alerts"]["transitions"]
             if t["action"] == "fired"]
    cleared = [t for t in d["alerts"]["transitions"]
               if t["action"] == "cleared"]
    assert fired, "burst never fired the SLO burn-rate alert"
    assert d["time_to_recover_s"] is not None, "fleet never recovered"
    episode_end = b1 + d["time_to_recover_s"]
    assert b0 - harness.tick_interval_s <= fired[0]["t"] <= episode_end
    # ...and cleared after it: nothing active at the end, last
    # transition is a clear, at/after the measured recovery point
    assert d["alerts"]["active"] == []
    assert cleared and cleared[-1]["t"] >= fired[-1]["t"]

    # time_to_recover agrees EXACTLY with the first post-burst
    # compliant window recomputed from profiler.history()
    recomputed = replay.time_to_recover(
        profiler.history(), b1, window_s=1.5, budget=0.2, factor=1.0)
    assert recomputed == d["time_to_recover_s"]

    # burst measurements exist and the report is a pure recompute
    assert d["burst_requests"] >= 5
    assert d["goodput_under_burst"] is not None
    assert d["p99_ttft_under_burst_s"] > 0
    # per-replica state rides in the report (fleet console food)
    assert set(d["replicas"]) == {"r0", "r1"}
    # the report is a pure recompute over (results, history) — replica
    # liveness is the one live snapshot field, so compare without it
    # (the router is stopped by now)
    d2 = harness.report().as_dict()
    d2.pop("replicas"), d.pop("replicas")
    assert d2 == d
    # the history observed the load moving: the serving gauge series
    # has points and a nonzero peak
    w = profiler.history().window("paddle_serving_active_requests",
                                  "continuous")
    assert w["count"] > 0 and w["max"] >= 2


# ---------------------------------------------------------------------------
# fleet console
# ---------------------------------------------------------------------------

def _load_console():
    path = os.path.join(REPO, "tools", "fleet_console.py")
    spec = importlib.util.spec_from_file_location("fleet_console_test",
                                                  path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _console_fixtures(tmp_path):
    """A history export, a flight dump with alerts + replicas, and a
    replay report file."""
    reg = MetricRegistry()
    c = reg.counter("paddle_slo_violations_total", labels=("slo",))
    g = reg.gauge("paddle_serving_active_requests", labels=("engine",))
    h = MetricsHistory(capacity=64, registry=reg)
    for t in range(12):
        c.inc(slo="request")
        g.set(t % 5, engine="continuous")
        h.tick(now=float(t))
    hist_path = tmp_path / "hist.jsonl"
    h.export_jsonl(str(hist_path))
    dump = {
        "schema": "paddle_flight_recorder/1", "rank": 0, "events": [],
        "state": {
            "alerts": {
                "active": {"slo_burn": {"severity": "page",
                                        "value": 5.0, "since": 3.0}},
                "recent_transitions": [
                    {"rule": "slo_burn", "action": "fired", "t": 3.0,
                     "severity": "page", "value": 5.0}],
            },
            "serving_fleet_x": {
                "replicas": {
                    "r0": {"alive": True, "draining": False,
                           "role": "mixed", "inflight": 2,
                           "load_tokens": 64, "queue_depth": 1},
                    "r1": {"alive": False, "draining": False,
                           "role": "mixed", "inflight": 0,
                           "load_tokens": 0, "queue_depth": 0},
                }},
            "fleet_controller": {
                "running": True,
                "cooldowns": {"restart": 0.0, "shed": 2.5},
                "recent_actions": [
                    {"t": 4.5, "action": "shed", "reason": "slo_burn",
                     "target": "hog", "value": 5.0, "cooldown_s": 0.5},
                    {"t": 6.0, "action": "restart",
                     "reason": "replica_dead", "target": "r1",
                     "value": 1.0, "cooldown_s": 0.5},
                ],
                "quarantined": ["r2"],
                "degraded": True,
                "shed_tenants": ["hog"],
                "max_new_cap": 4,
                "warm_pool": 1,
            },
        },
    }
    dump_path = tmp_path / "flight_rank0.json"
    dump_path.write_text(json.dumps(dump))
    report_path = tmp_path / "report.json"
    report_path.write_text(json.dumps({
        "schema": "paddle_replay_report/1", "preset": "bursty",
        "seed": 11, "requests": 14, "ok": 14,
        "goodput_under_burst": 0.2, "time_to_recover_s": 1.5,
        "schedule_digest": "abc"}))
    return hist_path, dump_path, report_path


def test_fleet_console_text_and_html(tmp_path, capsys):
    hist_path, dump_path, report_path = _console_fixtures(tmp_path)
    con = _load_console()
    rc = con.main([str(hist_path), str(dump_path), str(report_path)])
    assert rc == 0
    out = capsys.readouterr().out
    assert "paddle_slo_violations_total{request}" in out
    assert "rate " in out                       # counter renders a rate
    assert "ACTIVE  slo_burn" in out
    assert "r0" in out and "role=mixed" in out
    assert "time_to_recover_s: 1.5" in out
    # controller action timeline (action, reason, trigger value,
    # cooldown state) renders next to the alert table
    assert "== controller actions ==" in out
    assert "shed" in out and "reason=slo_burn" in out
    assert "restart" in out and "reason=replica_dead" in out
    assert "cooldown" in out and "shed=2.5" in out
    assert "QUARANTINED: r2" in out
    assert "DEGRADED: shed tenants [hog] max_new_cap=4" in out
    assert "warm pool: 1 engine(s)" in out
    # sparkline characters actually present
    assert any(ch in out for ch in con.BLOCKS)
    # --match filters series
    rc = con.main(["--match", "active_requests", str(hist_path)])
    out = capsys.readouterr().out
    assert "paddle_serving_active_requests" in out
    assert "paddle_slo_violations_total" not in out
    # --html writes a self-contained page
    html_path = tmp_path / "console.html"
    rc = con.main(["--html", str(html_path), str(hist_path),
                   str(dump_path), str(report_path)])
    assert rc == 0
    html = html_path.read_text()
    assert html.startswith("<!doctype html>")
    assert "slo_burn" in html and "replicas" in html
    assert "controller actions" in html
    assert "QUARANTINED: r2" in html
    # nothing usable -> exit 2
    junk = tmp_path / "junk.json"
    junk.write_text('{"hello": 1}')
    assert con.main([str(junk)]) == 2
    capsys.readouterr()


def test_fleet_console_no_jax_import(tmp_path):
    """Same discipline as trace_merge.py: the console must run with jax
    (and numpy) poisoned out of the interpreter — it renders files
    scp'd off the fleet, on machines with no accelerator stack."""
    hist_path, dump_path, _ = _console_fixtures(tmp_path)
    code = (
        "import sys\n"
        "sys.modules['jax'] = None\n"
        "sys.modules['numpy'] = None\n"
        "sys.argv = ['fleet_console.py', %r, %r]\n"
        "import runpy\n"
        "try:\n"
        "    runpy.run_path(%r, run_name='__main__')\n"
        "except SystemExit as e:\n"
        "    raise SystemExit(e.code or 0)\n"
        % (str(hist_path), str(dump_path),
           os.path.join(REPO, "tools", "fleet_console.py")))
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert "paddle_slo_violations_total" in proc.stdout
    assert "ACTIVE  slo_burn" in proc.stdout
    assert "== controller actions ==" in proc.stdout
    assert "QUARANTINED: r2" in proc.stdout
