"""Real-process elastic recovery (VERDICT.md round-2 weak #9): a worker
launched through the launch CLI is SIGKILLed mid-training; the
supervisor restarts it, it re-rendezvouses through the C++ TCPStore and
resumes from its checkpoint to completion (reference semantics: the
launch controllers + elastic manager, SURVEY.md §5.3)."""
import os
import signal
import subprocess
import sys
import textwrap
import time

import pytest

from paddle_tpu.distributed import native

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import os, sys, time
    from paddle_tpu.distributed.native import TCPStore

    store = TCPStore("127.0.0.1", int(os.environ["TEST_STORE_PORT"]),
                     is_master=False, world_size=1)
    attempt = store.add("attempts", 1)
    ckpt = os.environ["TEST_CKPT"]
    start = int(open(ckpt).read()) if os.path.exists(ckpt) else 0
    print(f"RESUMED_AT {start} attempt {attempt}", flush=True)
    for step in range(start, 10):
        with open(ckpt, "w") as f:       # checkpoint every step
            f.write(str(step + 1))
        if attempt == 1 and step == 4:
            # advertise ourselves and wait for the external SIGKILL —
            # a hard process death, not a clean python exception
            store.set("pid", str(os.getpid()))
            time.sleep(120)
    print("TRAINING_DONE", open(ckpt).read(), flush=True)
""")


@pytest.mark.skipif(not native.available(), reason="native TCPStore needed")
def test_sigkill_worker_recovers_through_supervisor(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)
    ckpt = tmp_path / "step.ckpt"
    logdir = tmp_path / "logs"

    # the test owns the rendezvous store (survives the worker's death,
    # like a real multi-host master)
    store = native.TCPStore("127.0.0.1", 0, is_master=True, world_size=1)

    env = dict(os.environ)
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon_site" not in p]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + parts)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env["TEST_STORE_PORT"] = str(store.port)
    env["TEST_CKPT"] = str(ckpt)

    sup = subprocess.Popen(
        [sys.executable, "-m", "paddle_tpu.distributed.launch",
         "--nnodes", "1", "--rank", "0", "--run_mode", "elastic",
         "--max_restarts", "2", "--log_dir", str(logdir), str(worker)],
        env=env, cwd=str(tmp_path), stderr=subprocess.PIPE, text=True)

    # wait for the first attempt to advertise its pid, then SIGKILL it
    deadline = time.monotonic() + 120
    pid = None
    while time.monotonic() < deadline:
        try:
            pid = int(store.get("pid", wait=False))
            break
        except KeyError:
            time.sleep(0.2)
        except RuntimeError:
            time.sleep(0.2)
    assert pid is not None, "worker never reached the kill point"
    os.kill(pid, signal.SIGKILL)

    rc = sup.wait(timeout=180)
    err = sup.stderr.read()
    assert rc == 0, err[-2000:]
    assert "[elastic] worker failed" in err          # supervisor observed it
    log = (logdir / "workerlog.0").read_text()
    assert "RESUMED_AT 0 attempt 1" in log           # first life
    assert "RESUMED_AT 5 attempt 2" in log           # resumed mid-training
    assert "TRAINING_DONE 10" in log                 # completed after restart
    # add() counters are stored as little-endian int64 bytes
    assert int.from_bytes(store.get("attempts", wait=False),
                          "little") == 2