"""Child script: the config-5-shaped FIVE-axis composition — dp=2, pp=2,
sharding=2, sep=2, mp=2 ALL >1 in one jitted program on 32 virtual CPU
devices (SURVEY.md §2.4 config 5 / §3.4; VERDICT round-4 weak #7: sep
was never >1 together with the rest). Delegates to the shared
multi-axis parity harness in ``__graft_entry__._config4_impl`` (same
oracle, parity, and structural sharding assertions — sep shards the
microbatch sequence dim)."""
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from __graft_entry__ import _config4_impl

if __name__ == "__main__":
    _config4_impl(degrees={"dp": 2, "pp": 2, "sharding": 2, "sep": 2,
                           "mp": 2},
                  seq=32, seed=5, label="config5")
