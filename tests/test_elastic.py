"""Elastic membership + checkpoint-restart supervision tests
(reference: fleet.elastic ElasticManager semantics; SURVEY.md §5.3)."""
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.elastic import (
    ElasticManager, FileKVStore, TrainingSupervisor, CheckpointManager,
)


def _mgr(tmp_path, host, np_spec="1:4", ttl=0.5):
    return ElasticManager(server=f"file://{tmp_path}/kv", job_id="j1",
                          np=np_spec, host=host, ttl=ttl,
                          heartbeat_interval=0.1)


def test_membership_register_and_scale_detect(tmp_path):
    a = _mgr(tmp_path, "10.0.0.1:8000")
    b = _mgr(tmp_path, "10.0.0.2:8000")
    a.register()
    assert a.hosts() == ["10.0.0.1:8000"]
    changed, cur = a.world_changed()
    assert not changed

    b.register()                       # scale-out event
    changed, cur = a.world_changed()
    assert changed and len(cur) == 2
    scale, healthy = a.should_scale()
    assert scale and healthy

    env = a.accept_world()
    assert env["PADDLE_TRAINERS_NUM"] == "2"
    assert "10.0.0.2:8000" in env["PADDLE_TRAINER_ENDPOINTS"]
    changed, _ = a.world_changed()
    assert not changed                 # baseline accepted


def test_heartbeat_ttl_expiry(tmp_path):
    a = _mgr(tmp_path, "h1:1", ttl=0.3)
    b = _mgr(tmp_path, "h2:1", ttl=0.3)
    a.start()                          # heartbeating
    b.register()                       # one-shot: will expire
    a.accept_world()
    time.sleep(0.6)
    hosts = a.hosts()
    assert hosts == ["h1:1"]           # b expired, a kept alive by heartbeat
    changed, _ = a.world_changed()
    assert changed                     # scale-in detected
    a.stop()
    assert a.hosts() == []             # deregistered


def test_np_range_health(tmp_path):
    a = _mgr(tmp_path, "h1:1", np_spec="2:3")
    a.register()
    _, healthy = a.should_scale()
    assert not healthy                 # 1 < min_np=2


def test_checkpoint_manager_retention_and_atomicity(tmp_path):
    cm = CheckpointManager(str(tmp_path / "ck"), keep=2)
    for s in (10, 20, 30):
        cm.save(s, {"w": paddle.to_tensor(np.full(3, s, np.float32))})
    assert cm.steps() == [20, 30]      # retention pruned step 10
    step, state = cm.load()
    assert step == 30
    np.testing.assert_allclose(state["w"].numpy(), 30.0)


def test_supervisor_restarts_from_checkpoint(tmp_path):
    sup = TrainingSupervisor(str(tmp_path / "ck"), max_restarts=3)
    attempts = []

    def train(start_step, state, ckpt):
        w = state["w"].numpy() if state else np.zeros(2, np.float32)
        attempts.append(start_step)
        for step in range(start_step + 1, 6):
            w = w + 1
            ckpt.save(step, {"w": paddle.to_tensor(w)})
            if step == 3 and len(attempts) == 1:
                raise RuntimeError("simulated TPU halt")
        return w

    out = sup.run(train)
    # first attempt died at step 3; second resumed from 3 and finished
    assert attempts == [0, 3]
    np.testing.assert_allclose(out, 5.0)
    assert sup.restarts == 1


def test_supervisor_gives_up(tmp_path):
    sup = TrainingSupervisor(str(tmp_path / "ck"), max_restarts=1)

    def always_fail(start_step, state, ckpt):
        raise RuntimeError("permafail")

    with pytest.raises(RuntimeError, match="permafail"):
        sup.run(always_fail)
    assert sup.restarts == 2


def test_amp_debugging_checker():
    from paddle_tpu.amp import debugging as dbg
    t = paddle.to_tensor(np.array([1.0, np.nan], np.float32))
    with pytest.raises(FloatingPointError, match="NaN"):
        dbg.check_numerics(t, op_type="test_op", var_name="t")
    ok = paddle.to_tensor(np.ones(3, np.float32))
    assert dbg.check_numerics(ok) is ok

    # FLAGS_check_nan_inf per-op scan via flags
    paddle.set_flags({"FLAGS_check_nan_inf": True})
    try:
        x = paddle.to_tensor(np.array([0.0], np.float32))
        with pytest.raises(FloatingPointError, match="log"):
            paddle.log(x - 1.0)        # log(-1) -> nan
    finally:
        paddle.set_flags({"FLAGS_check_nan_inf": False})
