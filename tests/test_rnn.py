"""RNN family tests (reference: paddle.nn SimpleRNN/LSTM/GRU — SURVEY.md
§2.2 'nn'): layer-vs-cell consistency, bidirectional, multi-layer, grads."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nn import LSTM, GRU, SimpleRNN, RNN, LSTMCell, GRUCell


def _x(b=2, t=5, f=4, seed=0):
    return paddle.to_tensor(np.random.default_rng(seed).normal(
        size=(b, t, f)).astype(np.float32))


def test_lstm_shapes_and_final_state():
    paddle.seed(0)
    lstm = LSTM(4, 8, num_layers=2)
    out, (h, c) = lstm(_x())
    assert out.shape == [2, 5, 8]
    assert h.shape == [2, 2, 8] and c.shape == [2, 2, 8]
    # final hidden of the last layer equals the last output step
    np.testing.assert_allclose(h.numpy()[-1], out.numpy()[:, -1], atol=1e-6)


def test_lstm_matches_cell_loop():
    paddle.seed(1)
    lstm = LSTM(4, 8)
    x = _x(seed=2)
    out, (h, c) = lstm(x)

    cell = LSTMCell(4, 8)
    cell.weight_ih.set_value(lstm.cells[0].weight_ih.numpy())
    cell.weight_hh.set_value(lstm.cells[0].weight_hh.numpy())
    cell.bias_ih.set_value(lstm.cells[0].bias_ih.numpy())
    cell.bias_hh.set_value(lstm.cells[0].bias_hh.numpy())
    state = None
    for t in range(5):
        o, state = cell(x[:, t], state)
    np.testing.assert_allclose(out.numpy()[:, -1], o.numpy(), atol=1e-5)
    np.testing.assert_allclose(c.numpy()[0], state[1].numpy(), atol=1e-5)


def test_bidirectional_lstm():
    paddle.seed(2)
    lstm = LSTM(4, 8, direction="bidirect")
    out, (h, c) = lstm(_x())
    assert out.shape == [2, 5, 16]
    assert h.shape == [2, 2, 8]


def test_gru_and_simple_rnn():
    paddle.seed(3)
    x = _x()
    gru = GRU(4, 8)
    out, h = gru(x)
    assert out.shape == [2, 5, 8] and h.shape == [1, 2, 8]
    rnn = SimpleRNN(4, 8, activation="relu")
    out2, h2 = rnn(x)
    assert out2.shape == [2, 5, 8]
    assert (out2.numpy() >= 0).all()       # relu activation


def test_time_major():
    paddle.seed(4)
    lstm = LSTM(4, 8, time_major=True)
    x = paddle.randn([5, 2, 4])            # [T, B, F]
    out, _ = lstm(x)
    assert out.shape == [5, 2, 8]


def test_lstm_trains():
    paddle.seed(5)
    lstm = LSTM(4, 8)
    head = paddle.nn.Linear(8, 1)
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-2,
        parameters=lstm.parameters() + head.parameters())
    x = _x(seed=6)
    y = paddle.randn([2, 1])
    losses = []
    for _ in range(5):
        out, (h, c) = lstm(x)
        loss = ((head(out[:, -1]) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert lstm.cells[0].weight_ih.grad is None   # cleared


def test_generic_rnn_wrapper():
    paddle.seed(6)
    cell = GRUCell(4, 8)
    rnn = RNN(cell)
    out, state = rnn(_x())
    assert out.shape == [2, 5, 8]
    # reverse direction
    rnn_r = RNN(cell, is_reverse=True)
    out_r, _ = rnn_r(_x())
    assert out_r.shape == [2, 5, 8]


def test_initial_states_honored():
    """Round-2 ADVICE fix: initial_states must seed the scan (was silently
    zero-initialized)."""
    paddle.seed(7)
    lstm = LSTM(4, 8)
    x = _x(seed=8)
    h0 = paddle.randn([1, 2, 8])
    c0 = paddle.randn([1, 2, 8])
    out0, _ = lstm(x)
    out1, (h, c) = lstm(x, (h0, c0))
    assert not np.allclose(out0.numpy(), out1.numpy())

    # oracle: drive the cell loop from the same initial state
    cell = LSTMCell(4, 8)
    for n in ("weight_ih", "weight_hh", "bias_ih", "bias_hh"):
        getattr(cell, n).set_value(getattr(lstm.cells[0], n).numpy())
    state = (h0[0], c0[0])
    for t in range(5):
        o, state = cell(x[:, t], state)
    np.testing.assert_allclose(out1.numpy()[:, -1], o.numpy(), atol=1e-5)
    np.testing.assert_allclose(h.numpy()[0], state[0].numpy(), atol=1e-5)

    # GRU path: [nl*ndirs, B, H] tensor form
    gru = GRU(4, 8)
    g0 = paddle.randn([1, 2, 8])
    ga, _ = gru(x)
    gb, _ = gru(x, g0)
    assert not np.allclose(ga.numpy(), gb.numpy())


def test_sequence_length_masks_outputs_and_states():
    """sequence_length semantics: outputs past each length are zero and the
    final state is the state at step len-1 (forward direction)."""
    paddle.seed(8)
    gru = GRU(4, 8)
    x = _x(b=2, t=5, seed=9)
    lens = paddle.to_tensor(np.array([3, 5], np.int64))
    out, h = gru(x, sequence_length=lens)
    o = out.numpy()
    # example 0: steps 3,4 masked to zero; example 1 untouched
    assert np.all(o[0, 3:] == 0)
    assert not np.all(o[1, 3:] == 0)
    # final state of example 0 == output at its last valid step
    np.testing.assert_allclose(h.numpy()[0, 0], o[0, 2], atol=1e-6)
    # full-length example matches the unmasked run
    full, hf = gru(x)
    np.testing.assert_allclose(o[1], full.numpy()[1], atol=1e-6)
    np.testing.assert_allclose(h.numpy()[0, 1], hf.numpy()[0, 1], atol=1e-6)


def test_sequence_length_bidirectional():
    """Reverse direction must start from each example's last valid step."""
    paddle.seed(9)
    lstm = LSTM(4, 8, direction="bidirect")
    x = _x(b=2, t=5, seed=10)
    lens = paddle.to_tensor(np.array([3, 5], np.int64))
    out, _ = lstm(x, sequence_length=lens)
    o = out.numpy()
    assert np.all(o[0, 3:] == 0)
    # oracle: run the truncated example alone at its true length
    x_trunc = paddle.to_tensor(x.numpy()[:1, :3])
    out_t, _ = lstm(x_trunc)
    np.testing.assert_allclose(o[0, :3], out_t.numpy()[0], atol=1e-5)


def test_interlayer_dropout_applied():
    paddle.seed(10)
    rnn = GRU(4, 8, num_layers=2, dropout=0.5)
    x = _x(seed=11)
    rnn.train()
    a = rnn(x)[0].numpy()
    b = rnn(x)[0].numpy()
    assert not np.allclose(a, b)          # stochastic between calls
    rnn.eval()
    c = rnn(x)[0].numpy()
    d = rnn(x)[0].numpy()
    np.testing.assert_allclose(c, d)      # deterministic in eval
