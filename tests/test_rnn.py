"""RNN family tests (reference: paddle.nn SimpleRNN/LSTM/GRU — SURVEY.md
§2.2 'nn'): layer-vs-cell consistency, bidirectional, multi-layer, grads."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.nn import LSTM, GRU, SimpleRNN, RNN, LSTMCell, GRUCell


def _x(b=2, t=5, f=4, seed=0):
    return paddle.to_tensor(np.random.default_rng(seed).normal(
        size=(b, t, f)).astype(np.float32))


def test_lstm_shapes_and_final_state():
    paddle.seed(0)
    lstm = LSTM(4, 8, num_layers=2)
    out, (h, c) = lstm(_x())
    assert out.shape == [2, 5, 8]
    assert h.shape == [2, 2, 8] and c.shape == [2, 2, 8]
    # final hidden of the last layer equals the last output step
    np.testing.assert_allclose(h.numpy()[-1], out.numpy()[:, -1], atol=1e-6)


def test_lstm_matches_cell_loop():
    paddle.seed(1)
    lstm = LSTM(4, 8)
    x = _x(seed=2)
    out, (h, c) = lstm(x)

    cell = LSTMCell(4, 8)
    cell.weight_ih.set_value(lstm.cells[0].weight_ih.numpy())
    cell.weight_hh.set_value(lstm.cells[0].weight_hh.numpy())
    cell.bias_ih.set_value(lstm.cells[0].bias_ih.numpy())
    cell.bias_hh.set_value(lstm.cells[0].bias_hh.numpy())
    state = None
    for t in range(5):
        o, state = cell(x[:, t], state)
    np.testing.assert_allclose(out.numpy()[:, -1], o.numpy(), atol=1e-5)
    np.testing.assert_allclose(c.numpy()[0], state[1].numpy(), atol=1e-5)


def test_bidirectional_lstm():
    paddle.seed(2)
    lstm = LSTM(4, 8, direction="bidirect")
    out, (h, c) = lstm(_x())
    assert out.shape == [2, 5, 16]
    assert h.shape == [2, 2, 8]


def test_gru_and_simple_rnn():
    paddle.seed(3)
    x = _x()
    gru = GRU(4, 8)
    out, h = gru(x)
    assert out.shape == [2, 5, 8] and h.shape == [1, 2, 8]
    rnn = SimpleRNN(4, 8, activation="relu")
    out2, h2 = rnn(x)
    assert out2.shape == [2, 5, 8]
    assert (out2.numpy() >= 0).all()       # relu activation


def test_time_major():
    paddle.seed(4)
    lstm = LSTM(4, 8, time_major=True)
    x = paddle.randn([5, 2, 4])            # [T, B, F]
    out, _ = lstm(x)
    assert out.shape == [5, 2, 8]


def test_lstm_trains():
    paddle.seed(5)
    lstm = LSTM(4, 8)
    head = paddle.nn.Linear(8, 1)
    opt = paddle.optimizer.AdamW(
        learning_rate=1e-2,
        parameters=lstm.parameters() + head.parameters())
    x = _x(seed=6)
    y = paddle.randn([2, 1])
    losses = []
    for _ in range(5):
        out, (h, c) = lstm(x)
        loss = ((head(out[:, -1]) - y) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    assert lstm.cells[0].weight_ih.grad is None   # cleared


def test_generic_rnn_wrapper():
    paddle.seed(6)
    cell = GRUCell(4, 8)
    rnn = RNN(cell)
    out, state = rnn(_x())
    assert out.shape == [2, 5, 8]
    # reverse direction
    rnn_r = RNN(cell, is_reverse=True)
    out_r, _ = rnn_r(_x())
    assert out_r.shape == [2, 5, 8]
