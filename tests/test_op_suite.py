"""The systematic op matrix over the OpTest harness (reference: the
per-op ``test_*_op.py`` files of ``test/legacy_test/`` driven by
``op_test.py`` — every public op in ``paddle_tpu/ops/`` must have an OpCase
here or an explicit exemption with a reason; ``test_coverage`` enforces it)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from op_test import OpCase, randn, randpos, randu, randint, _RNG


def _mk(**kw):
    return lambda: {k: (v() if callable(v) else v) for k, v in kw.items()}


def _np_gather_axis0(x, index):
    return x[index]


UNARY_SMOOTH = [
    ("exp", np.exp), ("expm1", np.expm1), ("square", np.square),
    ("sin", np.sin), ("cos", np.cos), ("tanh", np.tanh),
    ("sinh", np.sinh), ("cosh", np.cosh), ("asinh", np.arcsinh),
    ("atan", np.arctan), ("erf", lambda x: np.vectorize(_erf)(x)),
    ("sigmoid", lambda x: 1 / (1 + np.exp(-x))),
    ("neg", np.negative), ("deg2rad", np.deg2rad), ("rad2deg", np.rad2deg),
]
UNARY_POS = [  # need positive inputs
    ("log", np.log), ("log2", np.log2), ("log10", np.log10),
    ("log1p", np.log1p), ("sqrt", np.sqrt),
    ("rsqrt", lambda x: 1 / np.sqrt(x)),
    ("reciprocal", np.reciprocal),
    ("digamma", None), ("lgamma", None), ("i0", None),
]
UNARY_NONSMOOTH = [  # no grad check at kinks / not differentiable
    ("abs", np.abs), ("sign", np.sign), ("floor", np.floor),
    ("ceil", np.ceil), ("round", np.round), ("trunc", np.trunc),
    ("frac", lambda x: x - np.trunc(x)),
]


def _erf(v):
    import math
    return math.erf(v)


CASES = []

for name, ref in UNARY_SMOOTH:
    CASES.append(OpCase(name, _mk(x=lambda: randu(3, 4)),
                        ref=ref, grad=True, rtol=1e-4, atol=1e-5))
for name, ref in UNARY_POS:
    CASES.append(OpCase(
        name, _mk(x=lambda: randpos(3, 4, lo=0.5, hi=2.0)),
        ref=(None if ref is None else ref), grad=True, rtol=1e-4, atol=1e-5))
for name, ref in UNARY_NONSMOOTH:
    CASES.append(OpCase(name, _mk(x=lambda: randn(3, 4) * 3), ref=ref))

CASES += [
    OpCase("acosh", _mk(x=lambda: randpos(3, 4, lo=1.2, hi=3.0)),
           ref=np.arccosh, grad=True, rtol=1e-4, atol=1e-5),
    OpCase("tan", _mk(x=lambda: randu(3, 4, lo=-1.2, hi=1.2)), ref=np.tan,
           grad=True, rtol=1e-4, atol=1e-5),
    OpCase("asin", _mk(x=lambda: randu(3, 4, lo=-0.8, hi=0.8)),
           ref=np.arcsin, grad=True, rtol=1e-4, atol=1e-5),
    OpCase("acos", _mk(x=lambda: randu(3, 4, lo=-0.8, hi=0.8)),
           ref=np.arccos, grad=True, rtol=1e-4, atol=1e-5),
    OpCase("atanh", _mk(x=lambda: randu(3, 4, lo=-0.8, hi=0.8)),
           ref=np.arctanh, grad=True, rtol=1e-4, atol=1e-5),
    OpCase("erfinv", _mk(x=lambda: randu(3, 4, lo=-0.7, hi=0.7)), grad=True),
    OpCase("logit", _mk(x=lambda: randu(3, 4, lo=0.15, hi=0.85)),
           ref=lambda x: np.log(x / (1 - x)), grad=True, rtol=1e-4),
    OpCase("stanh", _mk(x=lambda: randu(3, 4)),
           ref=lambda x: 1.7159 * np.tanh(0.67 * x), grad=True, rtol=1e-4),
    OpCase("clip", _mk(x=lambda: randn(3, 4)), kwargs={"min": -0.5, "max": 0.5},
           ref=lambda x: np.clip(x, -0.5, 0.5)),
    OpCase("scale", _mk(x=lambda: randn(3, 4)),
           kwargs={"scale": 2.0, "bias": 1.0},
           ref=lambda x: 2 * x + 1, grad=True, rtol=1e-4),
    OpCase("nan_to_num",
           _mk(x=lambda: np.array([[np.nan, 1.0, np.inf, -np.inf]], np.float32)),
           ref=lambda x: np.nan_to_num(x, nan=0.0,
                                       posinf=np.finfo(np.float32).max,
                                       neginf=np.finfo(np.float32).min)),
    OpCase("increment", _mk(x=lambda: randn(4)), ref=lambda x: x + 1),
]

# binary elementwise ---------------------------------------------------------
BINARY = [
    ("add", np.add, True), ("subtract", np.subtract, True),
    ("multiply", np.multiply, True), ("maximum", np.maximum, False),
    ("minimum", np.minimum, False), ("fmax", np.fmax, False),
    ("fmin", np.fmin, False), ("atan2", np.arctan2, True),
    ("hypot", np.hypot, True), ("logaddexp", np.logaddexp, True),
    ("copysign", np.copysign, False), ("nextafter", np.nextafter, False),
    ("heaviside", np.heaviside, False),
]
for name, ref, grad in BINARY:
    CASES.append(OpCase(name, _mk(x=lambda: randn(3, 4),
                                  y=lambda: randn(3, 4) + 0.1),
                        ref=ref, grad=grad, rtol=1e-4, atol=1e-5))
CASES += [
    OpCase("divide", _mk(x=lambda: randn(3, 4),
                         y=lambda: randpos(3, 4, lo=0.5)),
           ref=np.divide, grad=True, rtol=1e-4, atol=1e-5),
    OpCase("divide_no_nan", _mk(x=lambda: randn(3, 4),
                                y=lambda: np.where(np.arange(12).reshape(3, 4) % 3,
                                                   randpos(3, 4), 0).astype(np.float32)),
           ref=lambda x, y: np.where(y == 0, 0.0, x / np.where(y == 0, 1, y))),
    OpCase("floor_divide", _mk(x=lambda: randint(3, 4, lo=1, hi=20),
                               y=lambda: randint(3, 4, lo=1, hi=5)),
           ref=np.floor_divide),
    OpCase("mod", _mk(x=lambda: randint(3, 4, lo=0, hi=20),
                      y=lambda: randint(3, 4, lo=1, hi=5)), ref=np.mod),
    OpCase("pow", _mk(x=lambda: randpos(3, 4), y=lambda: randu(3, 4, lo=1, hi=3)),
           ref=np.power, grad=True, rtol=1e-4, atol=1e-5),
    OpCase("lerp", _mk(x=lambda: randn(3, 4), y=lambda: randn(3, 4),
                       weight=lambda: randu(3, 4, lo=0, hi=1)),
           ref=lambda x, y, weight: x + weight * (y - x), grad=True, rtol=1e-4),
    OpCase("gcd", _mk(x=lambda: randint(4, lo=1, hi=40),
                      y=lambda: randint(4, lo=1, hi=40)), ref=np.gcd),
    OpCase("lcm", _mk(x=lambda: randint(4, lo=1, hi=12),
                      y=lambda: randint(4, lo=1, hi=12)), ref=np.lcm),
    OpCase("multiplex", _mk(inputs=lambda: [randn(4, 3), randn(4, 3)],
                            index=lambda: np.array([[0], [1], [1], [0]])),
           ref=lambda inputs, index: np.stack(
               [inputs[i[0]][r] for r, i in enumerate(index)])),
]

# reductions ------------------------------------------------------------------
CASES += [
    OpCase("sum", _mk(x=lambda: randn(3, 4, 5)), kwargs={"axis": 1},
           ref=lambda x: x.sum(1), grad=True, rtol=1e-4),
    OpCase("mean", _mk(x=lambda: randn(3, 4, 5)), kwargs={"axis": [0, 2]},
           ref=lambda x: x.mean((0, 2)), grad=True, rtol=1e-4),
    OpCase("prod", _mk(x=lambda: randpos(2, 3)), kwargs={"axis": 1},
           ref=lambda x: x.prod(1), grad=True, rtol=1e-4),
    OpCase("max", _mk(x=lambda: randn(3, 4)), kwargs={"axis": 1},
           ref=lambda x: x.max(1)),
    OpCase("min", _mk(x=lambda: randn(3, 4)), kwargs={"axis": -1},
           ref=lambda x: x.min(-1)),
    OpCase("amax", _mk(x=lambda: randn(3, 4)), kwargs={"axis": 0},
           ref=lambda x: x.max(0)),
    OpCase("amin", _mk(x=lambda: randn(3, 4)), kwargs={"axis": 0},
           ref=lambda x: x.min(0)),
    OpCase("logsumexp", _mk(x=lambda: randn(3, 4)), kwargs={"axis": 1},
           ref=lambda x: np.log(np.exp(x).sum(1)), grad=True, rtol=1e-4),
    OpCase("std", _mk(x=lambda: randn(3, 4)),
           ref=lambda x: x.std(ddof=1), rtol=1e-4),
    OpCase("var", _mk(x=lambda: randn(3, 4)),
           ref=lambda x: x.var(ddof=1), rtol=1e-4),
    OpCase("median", _mk(x=lambda: randn(3, 5)), kwargs={"axis": 1},
           ref=lambda x: np.median(x, 1)),
    OpCase("nanmedian", _mk(x=lambda: randn(3, 5)), kwargs={"axis": 1},
           ref=lambda x: np.nanmedian(x, 1)),
    OpCase("quantile", _mk(x=lambda: randn(3, 8)),
           kwargs={"q": 0.5, "axis": 1},
           ref=lambda x: np.quantile(x, 0.5, axis=1), rtol=1e-4, atol=1e-5),
    OpCase("nansum",
           _mk(x=lambda: np.where(randn(3, 4) > 1, np.nan, randn(3, 4)).astype(np.float32)),
           ref=np.nansum, rtol=1e-4, atol=1e-5),
    OpCase("nanmean",
           _mk(x=lambda: np.where(randn(3, 4) > 1, np.nan, randn(3, 4)).astype(np.float32)),
           ref=np.nanmean, rtol=1e-4, atol=1e-5),
    OpCase("count_nonzero",
           _mk(x=lambda: (randn(3, 4) > 0).astype(np.float32)),
           ref=lambda x: np.count_nonzero(x)),
    OpCase("cumsum", _mk(x=lambda: randn(3, 4)), kwargs={"axis": 1},
           ref=lambda x: np.cumsum(x, 1), grad=True, rtol=1e-4),
    OpCase("cumprod", _mk(x=lambda: randpos(3, 4)), kwargs={"dim": 1},
           ref=lambda x: np.cumprod(x, 1), grad=True, rtol=1e-4),
    OpCase("cummax", _mk(x=lambda: randn(3, 4)), kwargs={"axis": 1},
           ref=lambda x: (np.maximum.accumulate(x, 1),
                          np.array([np.argmax(x[:, :j + 1], 1) * 0 +
                                    np.array([row[:j + 1].argmax() for row in x])
                                    for j in range(x.shape[1])]).T)),
    OpCase("cummin", _mk(x=lambda: randn(3, 4)), kwargs={"axis": 1},
           ref=lambda x: (np.minimum.accumulate(x, 1),
                          np.array([[row[:j + 1].argmin() for j in range(x.shape[1])]
                                    for row in x]))),
    OpCase("logcumsumexp", _mk(x=lambda: randn(3, 4)), kwargs={"axis": 1},
           ref=lambda x: np.log(np.cumsum(np.exp(x), 1)), rtol=1e-4),
    OpCase("trapezoid", _mk(y=lambda: randn(3, 8)),
           ref=lambda y: np.trapezoid(y, axis=-1) if hasattr(np, "trapezoid")
           else np.trapz(y, axis=-1), rtol=1e-4),
    OpCase("all", _mk(x=lambda: randn(3, 4) > 0), kwargs={"axis": 1},
           ref=lambda x: x.all(1)),
    OpCase("any", _mk(x=lambda: randn(3, 4) > 0), kwargs={"axis": 1},
           ref=lambda x: x.any(1)),
]

# matmul family ---------------------------------------------------------------
CASES += [
    OpCase("matmul", _mk(x=lambda: randn(2, 3, 4), y=lambda: randn(2, 4, 5)),
           ref=np.matmul, grad=True, rtol=1e-4, atol=1e-5),
    OpCase("bmm", _mk(x=lambda: randn(2, 3, 4), y=lambda: randn(2, 4, 5)),
           ref=np.matmul, grad=True, rtol=1e-4, atol=1e-5),
    OpCase("dot", _mk(x=lambda: randn(5), y=lambda: randn(5)),
           ref=np.dot, grad=True, rtol=1e-4),
    OpCase("inner", _mk(x=lambda: randn(3, 4), y=lambda: randn(2, 4)),
           ref=np.inner, grad=True, rtol=1e-4),
    OpCase("outer", _mk(x=lambda: randn(3), y=lambda: randn(4)),
           ref=np.outer, grad=True, rtol=1e-4),
    OpCase("addmm", _mk(input=lambda: randn(3, 5), x=lambda: randn(3, 4),
                        y=lambda: randn(4, 5)),
           kwargs={"beta": 0.5, "alpha": 2.0},
           ref=lambda input, x, y: 0.5 * input + 2.0 * (x @ y),
           grad=True, rtol=1e-4, atol=1e-5),
    OpCase("kron", _mk(x=lambda: randn(2, 3), y=lambda: randn(3, 2)),
           ref=np.kron, rtol=1e-4, atol=1e-5),
    OpCase("cross", _mk(x=lambda: randn(4, 3), y=lambda: randn(4, 3)),
           ref=lambda x, y: np.cross(x, y), rtol=1e-4, atol=1e-5),
    OpCase("trace", _mk(x=lambda: randn(4, 4)), ref=np.trace,
           grad=True, rtol=1e-4),
    OpCase("t", _mk(x=lambda: randn(3, 4)), ref=np.transpose),
    OpCase("mv", _mk(x=lambda: randn(3, 4), vec=lambda: randn(4)),
           ref=lambda x, vec: x @ vec, grad=True, rtol=1e-4),
    OpCase(lambda x, y: paddle.einsum("ij,jk->ik", x, y),
           _mk(x=lambda: randn(3, 4), y=lambda: randn(4, 5)),
           ref=np.matmul, grad=True, rtol=1e-4, name="einsum"),
    OpCase("tensordot", _mk(x=lambda: randn(3, 4), y=lambda: randn(4, 5)),
           kwargs={"axes": 1}, ref=lambda x, y: np.tensordot(x, y, 1),
           rtol=1e-4, atol=1e-5),
]

# float predicates / comparisons ----------------------------------------------
CASES += [
    OpCase("isnan", _mk(x=lambda: np.array([1.0, np.nan], np.float32)),
           ref=np.isnan),
    OpCase("isinf", _mk(x=lambda: np.array([1.0, np.inf], np.float32)),
           ref=np.isinf),
    OpCase("isfinite", _mk(x=lambda: np.array([1.0, np.inf, np.nan], np.float32)),
           ref=np.isfinite),
    OpCase("isclose", _mk(x=lambda: randn(3), y=lambda: randn(3)),
           ref=lambda x, y: np.isclose(x, y)),
    OpCase("allclose", _mk(x=lambda: randn(3), y=lambda: randn(3)),
           ref=lambda x, y: np.allclose(x, y), static=False),
    OpCase("equal_all", _mk(x=lambda: randn(3), y=lambda: randn(3)),
           ref=lambda x, y: np.array_equal(x, y), static=False),
    OpCase("histogram", _mk(x=lambda: randu(64, lo=0, hi=1)),
           kwargs={"bins": 8, "min": 0, "max": 1},
           ref=lambda x: np.histogram(x, 8, (0, 1))[0]),
    OpCase("bincount", _mk(x=lambda: randint(20, lo=0, hi=6)),
           ref=lambda x: np.bincount(x)),
    OpCase("diff", _mk(x=lambda: randn(3, 6)),
           ref=lambda x: np.diff(x, axis=-1)),
    OpCase("take", _mk(x=lambda: randn(3, 4),
                       index=lambda: randint(5, lo=0, hi=12)),
           ref=lambda x, index: x.reshape(-1)[index]),
]
for name, ref in [("equal", np.equal), ("not_equal", np.not_equal),
                  ("greater_than", np.greater), ("greater_equal", np.greater_equal),
                  ("less_than", np.less), ("less_equal", np.less_equal)]:
    CASES.append(OpCase(name, _mk(x=lambda: randint(3, 4, lo=0, hi=3).astype(np.float32),
                                  y=lambda: randint(3, 4, lo=0, hi=3).astype(np.float32)),
                        ref=ref))
for name, ref in [("logical_and", np.logical_and), ("logical_or", np.logical_or),
                  ("logical_xor", np.logical_xor)]:
    CASES.append(OpCase(name, _mk(x=lambda: randn(3, 4) > 0,
                                  y=lambda: randn(3, 4) > 0), ref=ref))
for name, ref in [("bitwise_and", np.bitwise_and), ("bitwise_or", np.bitwise_or),
                  ("bitwise_xor", np.bitwise_xor)]:
    CASES.append(OpCase(name, _mk(x=lambda: randint(3, 4, lo=0, hi=16).astype(np.int32),
                                  y=lambda: randint(3, 4, lo=0, hi=16).astype(np.int32)),
                        ref=ref))
CASES += [
    OpCase("logical_not", _mk(x=lambda: randn(3, 4) > 0), ref=np.logical_not),
    OpCase("bitwise_not", _mk(x=lambda: randint(3, 4, lo=0, hi=16).astype(np.int32)),
           ref=np.bitwise_not),
    OpCase("is_empty", _mk(x=lambda: randn(2, 2)),
           ref=lambda x: np.array(False), static=False),
]

# search / sort ---------------------------------------------------------------
CASES += [
    OpCase("argmax", _mk(x=lambda: randn(4, 5)), kwargs={"axis": 1},
           ref=lambda x: np.argmax(x, 1)),
    OpCase("argmin", _mk(x=lambda: randn(4, 5)), kwargs={"axis": 1},
           ref=lambda x: np.argmin(x, 1)),
    OpCase("argsort", _mk(x=lambda: randn(4, 5)), kwargs={"axis": 1},
           ref=lambda x: np.argsort(x, 1, kind="stable")),
    OpCase("sort", _mk(x=lambda: randn(4, 5)), kwargs={"axis": 1},
           ref=lambda x: np.sort(x, 1)),
    OpCase("topk", _mk(x=lambda: randn(4, 6)), kwargs={"k": 3},
           ref=lambda x: (np.sort(x, -1)[:, ::-1][:, :3],
                          np.argsort(-x, -1, kind="stable")[:, :3])),
    OpCase("kthvalue", _mk(x=lambda: randn(4, 6)), kwargs={"k": 2},
           ref=lambda x: (np.sort(x, -1)[:, 1],
                          np.argsort(x, -1, kind="stable")[:, 1])),
    OpCase("mode", _mk(x=lambda: randint(4, 9, lo=0, hi=3).astype(np.float32))),
    OpCase("searchsorted",
           _mk(sorted_sequence=lambda: np.sort(randn(8)).astype(np.float32),
               values=lambda: randn(5)),
           ref=lambda sorted_sequence, values: np.searchsorted(
               sorted_sequence, values)),
    OpCase("bucketize",
           _mk(x=lambda: randn(5),
               sorted_sequence=lambda: np.sort(randn(8)).astype(np.float32)),
           ref=lambda x, sorted_sequence: np.searchsorted(sorted_sequence, x)),
    OpCase("nonzero", _mk(x=lambda: (randn(3, 4) > 0).astype(np.float32)),
           static=False),
    OpCase("masked_select", _mk(x=lambda: randn(3, 4),
                                mask=lambda: randn(3, 4) > 0), static=False),
    OpCase("unique", _mk(x=lambda: randint(12, lo=0, hi=5).astype(np.float32)),
           ref=lambda x: np.unique(x), static=False),
    OpCase("unique_consecutive",
           _mk(x=lambda: np.array([1, 1, 2, 2, 3, 1, 1], np.float32)),
           ref=lambda x: np.array([1, 2, 3, 1], np.float32), static=False),
]

# manipulation ----------------------------------------------------------------
CASES += [
    OpCase("reshape", _mk(x=lambda: randn(2, 3, 4)), kwargs={"shape": [6, 4]},
           ref=lambda x: x.reshape(6, 4), grad=True, rtol=1e-4),
    OpCase("view", _mk(x=lambda: randn(2, 6)), kwargs={"shape_or_dtype": [3, 4]},
           ref=lambda x: x.reshape(3, 4)),
    OpCase("flatten", _mk(x=lambda: randn(2, 3, 4)),
           kwargs={"start_axis": 1},
           ref=lambda x: x.reshape(2, 12)),
    OpCase("squeeze", _mk(x=lambda: randn(1, 3, 1)),
           ref=lambda x: x.reshape(3)),
    OpCase("unsqueeze", _mk(x=lambda: randn(3, 4)), kwargs={"axis": [0, -1]},
           ref=lambda x: x.reshape(1, 3, 4, 1)),
    OpCase("transpose", _mk(x=lambda: randn(2, 3, 4)),
           kwargs={"perm": [2, 0, 1]},
           ref=lambda x: x.transpose(2, 0, 1), grad=True, rtol=1e-4),
    OpCase(lambda x: paddle.permute(x, 2, 0, 1),
           _mk(x=lambda: randn(2, 3, 4)),
           ref=lambda x: x.transpose(2, 0, 1), name="permute"),
    OpCase("moveaxis", _mk(x=lambda: randn(2, 3, 4)),
           kwargs={"source": 0, "destination": 2},
           ref=lambda x: np.moveaxis(x, 0, 2)),
    OpCase("swapaxes", _mk(x=lambda: randn(2, 3, 4)),
           kwargs={"axis0": 0, "axis1": 2},
           ref=lambda x: np.swapaxes(x, 0, 2)),
    OpCase("concat", lambda: {"x": [randn(2, 3), randn(2, 3)]},
           kwargs={"axis": 0},
           ref=lambda x: np.concatenate(x, 0), name="concat"),
    OpCase("stack", lambda: {"x": [randn(2, 3), randn(2, 3)]},
           kwargs={"axis": 1}, ref=lambda x: np.stack(x, 1), name="stack"),
    OpCase("hstack", lambda: {"x": [randn(2, 3), randn(2, 3)]},
           ref=lambda x: np.hstack(x), name="hstack"),
    OpCase("vstack", lambda: {"x": [randn(2, 3), randn(2, 3)]},
           ref=lambda x: np.vstack(x), name="vstack"),
    OpCase("split", _mk(x=lambda: randn(6, 4)),
           kwargs={"num_or_sections": 3},
           ref=lambda x: tuple(np.split(x, 3))),
    OpCase("chunk", _mk(x=lambda: randn(6, 4)), kwargs={"chunks": 2},
           ref=lambda x: tuple(np.split(x, 2))),
    OpCase("unbind", _mk(x=lambda: randn(3, 4)),
           ref=lambda x: tuple(x[i] for i in range(3))),
    OpCase("unstack", _mk(x=lambda: randn(3, 4)),
           ref=lambda x: tuple(x[i] for i in range(3))),
    OpCase("tile", _mk(x=lambda: randn(2, 3)), kwargs={"repeat_times": [2, 2]},
           ref=lambda x: np.tile(x, (2, 2))),
    OpCase("expand", _mk(x=lambda: randn(1, 3)), kwargs={"shape": [4, 3]},
           ref=lambda x: np.broadcast_to(x, (4, 3))),
    OpCase("expand_as", _mk(x=lambda: randn(1, 3), y=lambda: randn(4, 3)),
           ref=lambda x, y: np.broadcast_to(x, (4, 3))),
    OpCase("broadcast_to", _mk(x=lambda: randn(1, 3)), kwargs={"shape": [4, 3]},
           ref=lambda x: np.broadcast_to(x, (4, 3))),
    OpCase("broadcast_tensors",
           lambda: {"inputs": [randn(1, 3), randn(4, 1)]},
           ref=lambda inputs: tuple(np.broadcast_arrays(*inputs)),
           name="broadcast_tensors"),
    OpCase("flip", _mk(x=lambda: randn(3, 4)), kwargs={"axis": [1]},
           ref=lambda x: x[:, ::-1]),
    OpCase("rot90", _mk(x=lambda: randn(3, 4)),
           ref=lambda x: np.rot90(x)),
    OpCase("roll", _mk(x=lambda: randn(3, 4)),
           kwargs={"shifts": 1, "axis": 0}, ref=lambda x: np.roll(x, 1, 0)),
    OpCase("repeat_interleave", _mk(x=lambda: randn(3, 2)),
           kwargs={"repeats": 2, "axis": 0},
           ref=lambda x: np.repeat(x, 2, 0)),
    OpCase("pad", _mk(x=lambda: randn(2, 2)), kwargs={"pad": [1, 1, 1, 1]},
           ref=lambda x: np.pad(x, 1)),
    OpCase("cast", _mk(x=lambda: randn(3, 4)), kwargs={"dtype": "int32"},
           ref=lambda x: x.astype(np.int32)),
    OpCase("numel", _mk(x=lambda: randn(3, 4)),
           ref=lambda x: np.array(12), static=False),
    OpCase("take_along_axis", _mk(arr=lambda: randn(3, 4),
                                  indices=lambda: randint(3, 2, lo=0, hi=4),
                                  axis=1),
           ref=lambda arr, indices, axis: np.take_along_axis(arr, indices, 1)),
    OpCase("put_along_axis", _mk(arr=lambda: randn(3, 4),
                                 indices=lambda: randint(3, 1, lo=0, hi=4),
                                 values=lambda: randn(3, 1), axis=1),
           ref=lambda arr, indices, values, axis: _np_put_along(
               arr, indices, values),
           static=False),
    OpCase("index_select", _mk(x=lambda: randn(5, 4),
                               index=lambda: np.array([0, 3, 2])),
           ref=lambda x, index: x[index]),
    OpCase("index_sample", _mk(x=lambda: randn(3, 6),
                               index=lambda: randint(3, 2, lo=0, hi=6)),
           ref=lambda x, index: np.take_along_axis(x, index, 1)),
    OpCase("gather", _mk(x=lambda: randn(5, 4),
                         index=lambda: np.array([1, 4])),
           ref=_np_gather_axis0, grad=True, grad_vars=["x"], rtol=1e-4),
    OpCase("gather_nd", _mk(x=lambda: randn(3, 4),
                            index=lambda: np.array([[0, 1], [2, 3]])),
           ref=lambda x, index: x[index[:, 0], index[:, 1]]),
    OpCase("scatter", _mk(x=lambda: np.zeros((5, 2), np.float32),
                          index=lambda: np.array([1, 3]),
                          updates=lambda: randn(2, 2)),
           ref=lambda x, index, updates: _np_scatter(x, index, updates)),
    OpCase("scatter_nd_add", _mk(x=lambda: np.ones((4, 2), np.float32),
                                 index=lambda: np.array([[1], [3]]),
                                 updates=lambda: randn(2, 2)),
           ref=lambda x, index, updates: _np_scatter_add(x, index, updates)),
    OpCase("scatter_nd", _mk(index=lambda: np.array([[1], [3]]),
                             updates=lambda: randn(2, 2), shape=[5, 2]),
           ref=lambda index, updates, shape: _np_scatter_add(
               np.zeros((5, 2), np.float32), index, updates)),
    OpCase("index_add", _mk(x=lambda: np.ones((5, 2), np.float32),
                            index=lambda: np.array([0, 2]), axis=0,
                            value=lambda: randn(2, 2)),
           ref=lambda x, index, axis, value: _np_index_add(x, index, value)),
    OpCase("index_put", _mk(x=lambda: np.zeros((4, 3), np.float32),
                            indices=lambda: (np.array([0, 2]),),
                            value=lambda: randn(2, 3)),
           ref=lambda x, indices, value: _np_index_put(x, indices, value),
           static=False),
    OpCase("masked_fill", _mk(x=lambda: randn(3, 4),
                              mask=lambda: randn(3, 4) > 0, value=9.0),
           ref=lambda x, mask, value: np.where(mask, 9.0, x)),
    OpCase("masked_scatter", _mk(x=lambda: randn(3, 4),
                                 mask=lambda: randn(3, 4) > 0,
                                 value=lambda: randn(12)), static=False),
    OpCase("where", _mk(condition=lambda: randn(3, 4) > 0,
                        x=lambda: randn(3, 4), y=lambda: randn(3, 4)),
           ref=lambda condition, x, y: np.where(condition, x, y),
           grad=True, rtol=1e-4),
    OpCase("slice", _mk(input=lambda: randn(4, 5)),
           kwargs={"axes": [0, 1], "starts": [1, 0], "ends": [3, 4]},
           ref=lambda input: input[1:3, 0:4]),
    OpCase("strided_slice", _mk(x=lambda: randn(6, 6)),
           kwargs={"axes": [0], "starts": [0], "ends": [6], "strides": [2]},
           ref=lambda x: x[::2]),
    OpCase("shard_index", _mk(input=lambda: randint(6, 1, lo=0, hi=20)),
           kwargs={"index_num": 20, "nshards": 2, "shard_id": 0},
           static=False),
    OpCase("one_hot", _mk(x=lambda: np.array([0, 2, 1])),
           kwargs={"num_classes": 3},
           ref=lambda x: np.eye(3, dtype=np.float32)[x]),
    OpCase("as_real", _mk(x=lambda: randn(3, 2).view(np.complex64)),
           static=False),
    OpCase(lambda x: paddle.as_complex(paddle.as_real(x)),
           _mk(x=lambda: randn(3, 2).view(np.complex64)),
           static=False, name="as_complex"),
]


def _np_scatter(x, index, updates):
    out = x.copy()
    out[index] = updates
    return out


def _np_scatter_add(x, index, updates):
    out = x.copy()
    for i, row in zip(index[:, 0], updates):
        out[i] += row
    return out


def _np_index_add(x, index, value):
    out = x.copy()
    for i, row in zip(index, value):
        out[i] += row
    return out


def _np_put_along(arr, indices, values):
    out = arr.copy()
    np.put_along_axis(out, indices, values, 1)
    return out


def _np_index_put(x, indices, value):
    out = x.copy()
    out[indices] = value
    return out


# creation --------------------------------------------------------------------
CASES += [
    OpCase(lambda: paddle.zeros([2, 3]), lambda: {},
           ref=lambda: np.zeros((2, 3), np.float32), name="zeros",
           static=False),
    OpCase(lambda: paddle.ones([2, 3]), lambda: {},
           ref=lambda: np.ones((2, 3), np.float32), name="ones", static=False),
    OpCase(lambda: paddle.full([2, 2], 7.0), lambda: {},
           ref=lambda: np.full((2, 2), 7.0, np.float32), name="full",
           static=False),
    OpCase("zeros_like", _mk(x=lambda: randn(2, 3)), ref=np.zeros_like),
    OpCase("ones_like", _mk(x=lambda: randn(2, 3)), ref=np.ones_like),
    OpCase("full_like", _mk(x=lambda: randn(2, 3)), kwargs={"fill_value": 3.0},
           ref=lambda x: np.full_like(x, 3.0)),
    OpCase(lambda: paddle.arange(0, 10, 2), lambda: {},
           ref=lambda: np.arange(0, 10, 2), name="arange", static=False),
    OpCase(lambda: paddle.linspace(0, 1, 5), lambda: {},
           ref=lambda: np.linspace(0, 1, 5, dtype=np.float32),
           name="linspace", static=False),
    OpCase(lambda: paddle.logspace(0, 2, 3), lambda: {},
           ref=lambda: np.logspace(0, 2, 3, dtype=np.float32),
           name="logspace", static=False, rtol=1e-4),
    OpCase(lambda: paddle.eye(3, 4), lambda: {},
           ref=lambda: np.eye(3, 4, dtype=np.float32), name="eye",
           static=False),
    OpCase("tril", _mk(x=lambda: randn(4, 4)), ref=np.tril),
    OpCase("triu", _mk(x=lambda: randn(4, 4)), ref=np.triu),
    OpCase("diag", _mk(x=lambda: randn(4)), ref=np.diag),
    OpCase("diagflat", _mk(x=lambda: randn(2, 2)), ref=np.diagflat),
    OpCase("diagonal", _mk(x=lambda: randn(3, 3)),
           ref=lambda x: np.diagonal(x)),
    OpCase("diag_embed", _mk(x=lambda: randn(2, 3)),
           ref=lambda x: np.stack([np.diag(r) for r in x])),
    OpCase("assign", _mk(x=lambda: randn(3, 4)), ref=lambda x: x),
    OpCase("clone", _mk(x=lambda: randn(3, 4)), ref=lambda x: x),
    OpCase("tolist", _mk(x=lambda: randn(3)), static=False,
           ref=None),
]
# meshgrid takes *args — wrap
CASES = [c for c in CASES if c.name != "meshgrid"]
CASES.append(OpCase(lambda args: paddle.meshgrid(*args),
                    lambda: {"args": [randn(3), randn(4)]},
                    ref=lambda args: tuple(np.meshgrid(*args, indexing="ij")),
                    name="meshgrid", static=False))

# linalg ----------------------------------------------------------------------
def _spd(n):
    a = randn(n, n)
    return (a @ a.T + n * np.eye(n)).astype(np.float32)


CASES += [
    OpCase("linalg.norm", _mk(x=lambda: randn(3, 4)),
           ref=lambda x: np.linalg.norm(x), rtol=1e-4, name="norm"),
    OpCase("linalg.matrix_norm", _mk(x=lambda: randn(3, 4)),
           ref=lambda x: np.linalg.norm(x, "fro"), rtol=1e-4,
           name="matrix_norm"),
    OpCase("linalg.dist", _mk(x=lambda: randn(3, 4), y=lambda: randn(3, 4)),
           ref=lambda x, y: np.linalg.norm(x - y), rtol=1e-4, name="dist"),
    OpCase("linalg.inv", _mk(x=lambda: _spd(4)),
           ref=np.linalg.inv, rtol=1e-3, atol=1e-4, name="inv"),
    OpCase("linalg.pinv", _mk(x=lambda: randn(4, 3)),
           ref=np.linalg.pinv, rtol=1e-3, atol=1e-4, name="pinv"),
    OpCase("linalg.det", _mk(x=lambda: _spd(3)),
           ref=np.linalg.det, rtol=1e-3, name="det"),
    OpCase("linalg.slogdet", _mk(x=lambda: _spd(3)),
           ref=lambda x: np.stack(np.linalg.slogdet(x)).astype(np.float32),
           rtol=1e-3, name="slogdet"),
    OpCase("linalg.cholesky", _mk(x=lambda: _spd(4)),
           ref=np.linalg.cholesky, rtol=1e-3, atol=1e-4, name="cholesky"),
    OpCase("linalg.solve", _mk(x=lambda: _spd(4), y=lambda: randn(4, 2)),
           ref=np.linalg.solve, rtol=1e-3, atol=1e-4, name="solve"),
    OpCase("linalg.triangular_solve",
           _mk(x=lambda: np.tril(_spd(4)).astype(np.float32),
               y=lambda: randn(4, 2)),
           kwargs={"upper": False},
           ref=lambda x, y: np.linalg.solve(x, y), rtol=1e-3, atol=1e-4,
           name="triangular_solve"),
    OpCase("linalg.cholesky_solve",
           _mk(x=lambda: randn(4, 2),
               y=lambda: np.linalg.cholesky(_spd(4)).astype(np.float32)),
           kwargs={"upper": False}, name="cholesky_solve", static=False),
    OpCase("linalg.matrix_power", _mk(x=lambda: _spd(3)), kwargs={"n": 3},
           ref=lambda x: np.linalg.matrix_power(x, 3), rtol=1e-3,
           name="matrix_power"),
    OpCase("linalg.matrix_rank", _mk(x=lambda: _spd(4)),
           ref=lambda x: np.array(np.linalg.matrix_rank(x)),
           static=False, name="matrix_rank"),
    OpCase("linalg.qr", _mk(x=lambda: randn(4, 3)), static=False, name="qr"),
    OpCase("linalg.svd", _mk(x=lambda: randn(4, 3)), static=False,
           name="svd"),
    OpCase("linalg.eigh", _mk(x=lambda: _spd(4)), static=False, name="eigh"),
    OpCase("linalg.eigvalsh", _mk(x=lambda: _spd(4)),
           ref=lambda x: np.linalg.eigvalsh(x), rtol=1e-3, atol=1e-4,
           name="eigvalsh"),
    OpCase("linalg.lstsq", _mk(x=lambda: randn(5, 3), y=lambda: randn(5, 2)),
           static=False, name="lstsq"),
    OpCase("linalg.lu", _mk(x=lambda: _spd(4)), static=False, name="lu"),
    OpCase("linalg.cond", _mk(x=lambda: _spd(4)),
           ref=lambda x: np.array(np.linalg.cond(x), np.float32), rtol=1e-2,
           name="cond"),
    OpCase("linalg.cov", _mk(x=lambda: randn(3, 8)),
           ref=lambda x: np.cov(x), rtol=1e-3, atol=1e-4, name="cov"),
    OpCase("linalg.corrcoef", _mk(x=lambda: randn(3, 8)),
           ref=lambda x: np.corrcoef(x), rtol=1e-3, atol=1e-4,
           name="corrcoef"),
    OpCase("linalg.householder_product",
           _mk(x=lambda: randn(4, 3), tau=lambda: randu(3, lo=0.1, hi=1.0)),
           static=False, name="householder_product"),
    OpCase("linalg.multi_dot",
           lambda: {"tensors": [randn(3, 4), randn(4, 5), randn(5, 2)]},
           ref=lambda tensors: tensors[0] @ tensors[1] @ tensors[2],
           rtol=1e-4, atol=1e-5, name="multi_dot"),
]

# round-3 op tranche (VERDICT item 7)
def _np_pdist(x):
    n = x.shape[0]
    out = []
    for i in range(n):
        for j in range(i + 1, n):
            out.append(np.sqrt(((x[i] - x[j]) ** 2).sum()))
    return np.asarray(out, x.dtype)


def _np_fill_diag_tensor(x, y):
    out = x.copy()
    np.fill_diagonal(out, y)
    return out


CASES += [
    OpCase("gammaln", _mk(x=lambda: randpos(3, 4, lo=0.5, hi=3.0)),
           grad=True, rtol=1e-4, atol=1e-4),
    OpCase("histogram_bin_edges", _mk(x=lambda: randn(20)),
           kwargs={"bins": 8, "min": -2.0, "max": 2.0},
           ref=lambda x: np.histogram_bin_edges(x, bins=8, range=(-2, 2))
           .astype(np.float32)),
    OpCase("pdist", _mk(x=lambda: randn(5, 3)), ref=_np_pdist,
           grad=False, rtol=1e-4, atol=1e-5),
    OpCase("reduce_as", _mk(x=lambda: randn(3, 4),
                            target=lambda: randn(1, 4)),
           ref=lambda x, target: x.sum(0, keepdims=True),
           rtol=1e-4, atol=1e-5),
    OpCase("linalg.vecdot", _mk(x=lambda: randn(3, 4),
                                y=lambda: randn(3, 4)),
           ref=lambda x, y: (x * y).sum(-1), grad=True,
           rtol=1e-4, atol=1e-5, name="vecdot"),
    OpCase("as_strided", _mk(x=lambda: randn(12)),
           kwargs={"shape": [3, 4], "stride": [4, 1]},
           ref=lambda x: x.reshape(3, 4)),
    OpCase("fill_diagonal_tensor",
           _mk(x=lambda: randn(4, 4), y=lambda: randn(4)),
           ref=_np_fill_diag_tensor),
]

# random / stateful creation: value checks are meaningless; check shape+range
RANDOM_OPS = {
    "rand": lambda: paddle.rand([3, 4]),
    "uniform": lambda: paddle.uniform([3, 4], min=-1.0, max=1.0),
    "randn": lambda: paddle.randn([3, 4]),
    "standard_normal": lambda: paddle.standard_normal([3, 4]),
    "normal": lambda: paddle.normal(0.0, 1.0, [3, 4]),
    "randint": lambda: paddle.randint(0, 10, [3, 4]),
    "randint_like": lambda: paddle.randint_like(paddle.zeros([3, 4]), low=0, high=10),
    "randperm": lambda: paddle.randperm(8),
    "bernoulli": lambda: paddle.bernoulli(paddle.full([3, 4], 0.5)),
    "multinomial": lambda: paddle.multinomial(
        paddle.to_tensor(np.ones(5, np.float32) / 5), 3),
    "poisson": lambda: paddle.poisson(paddle.full([3, 4], 2.0)),
    "exponential_": lambda: paddle.exponential_(paddle.ones([3, 4])),
    "empty": lambda: paddle.empty([2, 2]),
    "empty_like": lambda: paddle.empty_like(paddle.ones([2, 2])),
    "binomial": lambda: paddle.binomial(paddle.full([3, 4], 10.0),
                                        paddle.full([3, 4], 0.5)),
    "standard_gamma": lambda: paddle.standard_gamma(paddle.full([3, 4], 2.0)),
    "log_normal": lambda: paddle.log_normal(0.0, 1.0, [3, 4]),
    "top_p_sampling": lambda: paddle.tensor.top_p_sampling(
        paddle.to_tensor(np.full((2, 8), 0.125, np.float32)),
        paddle.to_tensor(np.full((2,), 0.9, np.float32)))[1],
}

CASES += [
    OpCase("mm", _mk(x=lambda: randn(3, 4), y=lambda: randn(4, 5)),
           ref=np.matmul, rtol=1e-4, atol=1e-5),
    OpCase("remainder", _mk(x=lambda: randint(3, 4, lo=0, hi=20),
                            y=lambda: randint(3, 4, lo=1, hi=5)), ref=np.mod),
    OpCase("floor_mod", _mk(x=lambda: randint(3, 4, lo=0, hi=20),
                            y=lambda: randint(3, 4, lo=1, hi=5)), ref=np.mod),
    OpCase("negative", _mk(x=lambda: randn(3, 4)), ref=np.negative),
    OpCase("conj", _mk(x=lambda: randn(3, 2).view(np.complex64)),
           static=False),
    OpCase("real", _mk(x=lambda: randn(3, 2).view(np.complex64)),
           ref=np.real, static=False),
    OpCase("imag", _mk(x=lambda: randn(3, 2).view(np.complex64)),
           ref=np.imag, static=False),
    OpCase("angle", _mk(x=lambda: randn(3, 2).view(np.complex64)),
           ref=np.angle, static=False),
    OpCase("linalg.vector_norm", _mk(x=lambda: randn(3, 4)),
           ref=lambda x: np.linalg.norm(x.ravel()), rtol=1e-4,
           name="vector_norm"),
]


# round-2 breadth batch ------------------------------------------------------
CASES += [
    OpCase("add_n", lambda: {"inputs": [randn(3, 4), randn(3, 4), randn(3, 4)]},
           ref=lambda inputs: inputs[0] + inputs[1] + inputs[2],
           rtol=1e-5, name="add_n"),
    OpCase("clip_by_norm", _mk(x=lambda: randn(4, 4) * 10),
           kwargs={"max_norm": 1.0},
           ref=lambda x: x * min(1.0, 1.0 / np.linalg.norm(x)), rtol=1e-4),
    OpCase("ldexp", _mk(x=lambda: randn(3, 4),
                        y=lambda: randint(3, 4, lo=-3, hi=4).astype(np.float32)),
           ref=lambda x, y: np.ldexp(x, y.astype(np.int32)), rtol=1e-5),
    OpCase("frexp", _mk(x=lambda: randpos(3, 4)),
           ref=lambda x: tuple(np.frexp(x))),
    OpCase("sinc", _mk(x=lambda: randn(3, 4)), ref=np.sinc, rtol=1e-4,
           atol=1e-5),
    OpCase("signbit", _mk(x=lambda: randn(3, 4)), ref=np.signbit),
    OpCase("isneginf", _mk(x=lambda: np.array([1.0, -np.inf, np.inf], np.float32)),
           ref=np.isneginf),
    OpCase("isposinf", _mk(x=lambda: np.array([1.0, -np.inf, np.inf], np.float32)),
           ref=np.isposinf),
    OpCase("isreal", _mk(x=lambda: randn(4)), ref=np.isreal, static=False),
    OpCase("i0e", _mk(x=lambda: randpos(3, 4))),
    OpCase("i1", _mk(x=lambda: randpos(3, 4))),
    OpCase("i1e", _mk(x=lambda: randpos(3, 4))),
    OpCase("polygamma", _mk(x=lambda: randpos(3, 4, lo=0.5, hi=3.0)),
           kwargs={"n": 1}),
    OpCase("gammainc", _mk(x=lambda: randpos(3, 4, lo=0.5, hi=3.0),
                           y=lambda: randpos(3, 4, lo=0.5, hi=3.0))),
    OpCase("gammaincc", _mk(x=lambda: randpos(3, 4, lo=0.5, hi=3.0),
                            y=lambda: randpos(3, 4, lo=0.5, hi=3.0))),
    OpCase("multigammaln", _mk(x=lambda: randpos(3, 4, lo=3.0, hi=6.0)),
           kwargs={"p": 2}),
    OpCase("nanquantile",
           _mk(x=lambda: np.where(randn(3, 8) > 1.5, np.nan,
                                  randn(3, 8)).astype(np.float32)),
           kwargs={"q": 0.5, "axis": 1},
           ref=lambda x: np.nanquantile(x, 0.5, axis=1), rtol=1e-4,
           atol=1e-5),
    OpCase("renorm", _mk(x=lambda: randn(3, 4, 5)),
           kwargs={"p": 2.0, "axis": 1, "max_norm": 1.0}),
    OpCase("bitwise_left_shift",
           _mk(x=lambda: randint(3, 4, lo=0, hi=8).astype(np.int32),
               y=lambda: randint(3, 4, lo=0, hi=4).astype(np.int32)),
           ref=np.left_shift),
    OpCase("bitwise_right_shift",
           _mk(x=lambda: randint(3, 4, lo=0, hi=64).astype(np.int32),
               y=lambda: randint(3, 4, lo=0, hi=4).astype(np.int32)),
           ref=np.right_shift),
    OpCase("cartesian_prod", lambda: {"x": [randn(3), randn(2)]},
           ref=lambda x: np.stack([g.reshape(-1) for g in
                                   np.meshgrid(*x, indexing="ij")], -1),
           name="cartesian_prod"),
    OpCase("combinations", _mk(x=lambda: randn(4)),
           ref=lambda x: np.array([[x[0], x[1]], [x[0], x[2]], [x[0], x[3]],
                                   [x[1], x[2]], [x[1], x[3]],
                                   [x[2], x[3]]])),
    OpCase(lambda x: paddle.atleast_1d(x), _mk(x=lambda: np.asarray(3.0, np.float32)),
           ref=lambda x: np.atleast_1d(x), static=False, name="atleast_1d"),
    OpCase(lambda x: paddle.atleast_2d(x), _mk(x=lambda: randn(3)),
           ref=lambda x: np.atleast_2d(x), static=False, name="atleast_2d"),
    OpCase(lambda x: paddle.atleast_3d(x), _mk(x=lambda: randn(3, 2)),
           ref=lambda x: np.atleast_3d(x), static=False, name="atleast_3d"),
    OpCase("column_stack", lambda: {"x": [randn(3), randn(3, 2)]},
           ref=lambda x: np.column_stack(x), name="column_stack"),
    OpCase("row_stack", lambda: {"x": [randn(2, 3), randn(1, 3)]},
           ref=lambda x: np.vstack(x), name="row_stack"),
    OpCase("dstack", lambda: {"x": [randn(2, 3), randn(2, 3)]},
           ref=lambda x: np.dstack(x), name="dstack"),
    OpCase("hsplit", _mk(x=lambda: randn(4, 6)),
           kwargs={"num_or_indices": 3},
           ref=lambda x: tuple(np.hsplit(x, 3))),
    OpCase("vsplit", _mk(x=lambda: randn(6, 4)),
           kwargs={"num_or_indices": 2},
           ref=lambda x: tuple(np.vsplit(x, 2))),
    OpCase("dsplit", _mk(x=lambda: randn(2, 3, 4)),
           kwargs={"num_or_indices": 2},
           ref=lambda x: tuple(np.dsplit(x, 2))),
    OpCase("tensor_split", _mk(x=lambda: randn(7, 3)),
           kwargs={"num_or_indices": 3},
           ref=lambda x: tuple(np.array_split(x, 3))),
    OpCase("unflatten", _mk(x=lambda: randn(2, 12)),
           kwargs={"axis": 1, "shape": [3, 4]},
           ref=lambda x: x.reshape(2, 3, 4)),
    OpCase("block_diag", lambda: {"inputs": [randn(2, 2), randn(3, 1)]},
           ref=lambda inputs: _np_block_diag(inputs), name="block_diag"),
    OpCase("diagonal_scatter", _mk(x=lambda: randn(4, 4),
                                   y=lambda: randn(4)),
           ref=lambda x, y: _np_diag_scatter(x, y)),
    OpCase("select_scatter", _mk(x=lambda: randn(3, 4),
                                 values=lambda: randn(4)),
           kwargs={"axis": 0, "index": 1},
           ref=lambda x, values: _np_select_scatter(x, values)),
    OpCase("slice_scatter", _mk(x=lambda: np.zeros((4, 4), np.float32),
                                value=lambda: randn(2, 4)),
           kwargs={"axes": [0], "starts": [1], "ends": [3]},
           ref=lambda x, value: _np_slice_scatter(x, value)),
    OpCase("index_fill", _mk(x=lambda: randn(4, 3),
                             index=lambda: np.array([0, 2])),
           kwargs={"axis": 0, "value": 7.0},
           ref=lambda x, index: _np_index_fill(x, index, 7.0)),
    OpCase("vander", _mk(x=lambda: randn(4)), kwargs={"n": 3},
           ref=lambda x: np.vander(x, 3), rtol=1e-4, atol=1e-5),
    OpCase("linalg.matrix_exp", _mk(x=lambda: randn(3, 3) * 0.3),
           rtol=1e-3, atol=1e-4, name="matrix_exp"),
    OpCase("linalg.ormqr", _mk(x=lambda: randn(4, 3),
                               tau=lambda: randu(3, lo=0.1, hi=1.0),
                               y=lambda: randn(4, 2)),
           static=False, name="ormqr"),
]


def _np_block_diag(inputs):
    import scipy.linalg as sl
    return sl.block_diag(*inputs).astype(np.float32)


def _np_diag_scatter(x, y):
    out = x.copy()
    np.fill_diagonal(out, y)
    return out


def _np_select_scatter(x, values):
    out = x.copy()
    out[1] = values
    return out


def _np_slice_scatter(x, value):
    out = x.copy()
    out[1:3] = value
    return out


def _np_index_fill(x, index, v):
    out = x.copy()
    out[index] = v
    return out


def _np_cdist(x, y):
    return np.sqrt(((x[:, None, :] - y[None, :, :]) ** 2).sum(-1))


def _np_cumtrap(y):
    from scipy.integrate import cumulative_trapezoid
    return cumulative_trapezoid(y, dx=1.0, axis=-1)


def _np_unfold(x):
    n = (x.shape[1] - 3) // 2 + 1
    return np.stack([x[:, i * 2:i * 2 + 3] for i in range(n)], axis=1)


CASES += [
    OpCase("sgn", _mk(x=lambda: randn(3, 4)), ref=np.sign),
    OpCase("float_power", _mk(x=lambda: randpos(3, 4), y=lambda: randu(3, 4, lo=1, hi=2)),
           ref=np.float_power),
    OpCase("vdot", _mk(x=lambda: randn(6), y=lambda: randn(6)),
           ref=np.vdot, grad=True, rtol=1e-4),
    OpCase("nanargmax", _mk(x=lambda: randn(3, 4)), kwargs={"axis": 1},
           ref=lambda x: np.nanargmax(x, 1)),
    OpCase("nanargmin", _mk(x=lambda: randn(3, 4)), kwargs={"axis": 1},
           ref=lambda x: np.nanargmin(x, 1)),
    OpCase("positive", _mk(x=lambda: randn(3, 4)), ref=lambda x: +x,
           grad=True, rtol=1e-5),
    OpCase("fliplr", _mk(x=lambda: randn(3, 4)), ref=np.fliplr, grad=True,
           rtol=1e-5),
    OpCase("flipud", _mk(x=lambda: randn(3, 4)), ref=np.flipud, grad=True,
           rtol=1e-5),
    OpCase("isin", _mk(x=lambda: randint(3, 4, lo=0, hi=5),
                       test_x=lambda: np.array([1, 3], np.int64)),
           ref=lambda x, test_x: np.isin(x, test_x)),
    OpCase("cdist", _mk(x=lambda: randu(5, 3), y=lambda: randu(4, 3)),
           ref=_np_cdist, grad=True, rtol=1e-4, atol=1e-5),
    OpCase("cumulative_trapezoid", _mk(y=lambda: randn(3, 6)),
           ref=_np_cumtrap, grad=True, rtol=1e-4, atol=1e-5),
    OpCase("unfold", _mk(x=lambda: randn(4, 9)),
           kwargs={"axis": 1, "size": 3, "step": 2}, ref=_np_unfold,
           grad=True, rtol=1e-4),
]


def test_linalg_extras():
    a = randn(4, 4)
    spd = a @ a.T + 4 * np.eye(4, dtype=np.float32)
    c = np.linalg.cholesky(spd).astype(np.float32)
    inv = paddle.linalg.cholesky_inverse(paddle.to_tensor(c))
    np.testing.assert_allclose(np.asarray(inv.numpy()), np.linalg.inv(spd),
                               rtol=2e-3, atol=1e-4)
    lu_d, piv = paddle.linalg.lu(paddle.to_tensor(spd))
    b = randn(4, 2)
    x = paddle.linalg.lu_solve(paddle.to_tensor(b), lu_d, piv)
    np.testing.assert_allclose(spd @ np.asarray(x.numpy()), b,
                               rtol=1e-3, atol=1e-3)
    mt = paddle.linalg.matrix_transpose(paddle.to_tensor(a))
    np.testing.assert_array_equal(np.asarray(mt.numpy()), a.T)


def test_lu_unpack_reconstructs():
    a = randn(5, 5)
    lu_d, piv = paddle.linalg.lu(paddle.to_tensor(a))
    P, L, U = paddle.linalg.lu_unpack(lu_d, piv)
    rec = P.numpy() @ L.numpy() @ U.numpy()
    np.testing.assert_allclose(rec, a, atol=1e-4)


def test_complex_roundtrip():
    r, i = randn(3, 4), randn(3, 4)
    c = paddle.complex(paddle.to_tensor(r), paddle.to_tensor(i))
    np.testing.assert_allclose(np.asarray(c.numpy()), r + 1j * i, rtol=1e-6)


def test_rank_shape_meta():
    x = paddle.to_tensor(randn(3, 4))
    assert int(paddle.rank(x).numpy()) == 2
    np.testing.assert_array_equal(paddle.shape(x).numpy(), [3, 4])


# intentionally not OpCase-covered (reason required)
EXEMPT = {
    "complex": "complex output; device_get unimplemented on TPU backend — "
               "covered by test_complex_roundtrip on CPU",
    "lu_unpack": "multi-output; covered by test_lu_unpack_reconstructs",
    "rank": "host-side shape metadata; covered by test_rank_shape_meta",
    "crop": "static slicing; covered by test_compat_namespaces",
    "matrix_transpose": "covered by test_linalg_extras",
    "cholesky_inverse": "covered by test_linalg_extras",
    "lu_solve": "covered by test_linalg_extras",
    "histogramdd": "multi-output histogram; smoke-covered in inventory",
    "index_copy": "same kernel family as index_fill (OpCase-covered)",
    "view": "reshape/bitcast alias; covered by test_compat_namespaces",
    "view_as": "alias of view",
    "tril_indices": "static index generator; covered below",
    "triu_indices": "static index generator; covered below",
    "shape": "host-side shape metadata; covered by test_rank_shape_meta",
    # module plumbing, not ops
    "apply": "tape dispatcher import", "defop": "tape decorator import",
    "Tensor": "class import", "builtins_sum": "python builtin passthrough",
    "builtins_slice": "python builtin passthrough",
    "in_dynamic_mode": "mode predicate, trivial",
    # shape/meta helpers with no kernel
    "broadcast_shape": "pure shape computation, no tensors",
    "tolist": "covered in CASES but host-side only",
    # covered through other suites
    "einsum": "covered via lambda case",
    "eig": "complex output; smoke-tested in test_fft_signal_vision_ops",
    "eigvals": "complex output; smoke-tested elsewhere",
    "pca_lowrank": "randomized algorithm; smoke-tested in test_models",
    "norm": "covered as linalg.norm case", "dist": "alias of linalg.dist",
    "inverse": "alias of linalg.inv",
    # in-place aliases: same kernel as the out-of-place op (covered above)
    "reshape_": "in-place alias of reshape",
    "squeeze_": "in-place alias of squeeze",
    "unsqueeze_": "in-place alias of unsqueeze",
    "igamma": "alias of gammainc", "igammac": "alias of gammaincc",
    "polar": "complex output; covered by test_polar_complex (CPU)",
    "svd_lowrank": "randomized algorithm; smoke-tested in "
                   "test_op_surface_r3.py",
    "fill_diagonal_": "in-place; same kernel as fill_diagonal_tensor",
    "fill_diagonal_tensor_": "in-place alias of fill_diagonal_tensor",
    "jax_silu": "internal helper of fused_swiglu (which is tested)",
}


@pytest.mark.parametrize("case", CASES, ids=lambda c: c.name)
def test_op_case(case):
    case.run()


@pytest.mark.parametrize("name", sorted(RANDOM_OPS), ids=str)
def test_random_op(name):
    paddle.seed(7)
    out = RANDOM_OPS[name]()
    arr = np.asarray(out.numpy())
    assert arr.size > 0
    if np.issubdtype(arr.dtype, np.floating):
        assert np.all(np.isfinite(arr))
    paddle.seed(7)
    again = np.asarray(RANDOM_OPS[name]().numpy())
    np.testing.assert_array_equal(arr, again, err_msg=f"{name}: not seeded")


# Modules whose ops are exercised by their own dedicated suites: an op
# there is covered iff its NAME literally appears in one of the listed
# test files (a real, greppable gate — renaming or adding an op without
# touching its suite fails test_coverage).
SUITE_COVERED = {
    "functional": ["test_nn.py", "test_nn_extras.py", "test_models.py",
                   "test_io_vision.py", "test_text_audio_autograd.py",
                   "test_fft_signal_vision_ops.py", "test_vision_zoo2.py",
                   "test_review_fixes.py", "test_ops_numeric.py",
                   "test_functional_ops.py"],
    "fft": ["test_fft_signal_vision_ops.py", "test_op_surface_r3.py"],
    "signal": ["test_fft_signal_vision_ops.py"],
    "sparse": ["test_sparse_quant.py", "test_op_surface_r3.py"],
    "geometric": ["test_geometric.py"],
    "fused": ["test_fused_multi_transformer.py", "test_nn_extras.py",
              "test_ops_numeric.py", "test_models.py",
              "test_op_surface_r3.py"],
}


def _suite_text(files):
    import os
    here = os.path.dirname(os.path.abspath(__file__))
    return "\n".join(open(os.path.join(here, f)).read() for f in files)


def test_coverage():
    """Every op in the schema registry has an OpCase, a random-op check,
    an explicit exemption, or (for suite-covered modules) appears by name
    in its dedicated test suite (the reference's every-op-has-an-OpTest
    policy, extended across the whole registry)."""
    import re
    from paddle_tpu.ops.schema import build_registry

    covered = {c.name for c in CASES} | set(RANDOM_OPS) | set(EXEMPT)
    suite_cache = {k: _suite_text(v) for k, v in SUITE_COVERED.items()}
    missing = []
    for name, spec in build_registry().items():
        mods = (spec.module,) + spec.aliases
        ok = name in covered
        for m in mods:
            if ok:
                break
            if m in suite_cache:
                ok = re.search(rf"\b{re.escape(name)}\b",
                               suite_cache[m]) is not None
        if not ok:
            missing.append(f"{spec.module}.{name}")
    assert not missing, (
        f"{len(missing)} ops lack OpTest coverage (add an OpCase, an "
        f"EXEMPT reason, or exercise it in its module suite): "
        f"{sorted(missing)}")
