"""Tiered KV cache (ISSUE 19): host-RAM prefix spill under the
prefix-index LRU — demote-on-evict through the ``export_pages`` codec,
promote-on-admission back to device pages, second-level LRU bound, COW
interplay, and exact legacy behavior with the tier off."""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousServingEngine
from paddle_tpu.inference.serving import _engine_state
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.models.generation import (HostKVPool, SlotPagedKVCache,
                                          block_hash_chain)
from paddle_tpu.profiler.telemetry import metrics


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return LlamaForCausalLM(llama_tiny(num_hidden_layers=2))


def _oracle(model, p, n):
    return np.asarray(model.generate(paddle.to_tensor(p),
                                     max_new_tokens=n)._data)


def _mk_cache(pool_mb, **kw):
    kw.setdefault("page_size", 4)
    kw.setdefault("max_len", 32)
    kw.setdefault("num_pages", 9)
    return SlotPagedKVCache(1, host_pool=HostKVPool(pool_mb), **kw)


def _prefill(cache, slot, toks, kv, rng, layer=None):
    """Admit + prefill the uncached suffix with caller-supplied K/V
    content; returns the cached (reused) token count. The layer object
    keys the cache's per-layer pool, so callers reuse one per cache
    (``cache._test_layer`` by default)."""
    if layer is None:
        layer = cache.__dict__.setdefault("_test_layer", object())
    h, d = 4, 8
    cache.assign(slot, toks)
    start = int(cache.lens[slot])
    n = len(toks) - start
    q = rng.standard_normal((1, n, h, d)).astype(np.float32)
    cache.begin_prefill(slot, n_valid=n)
    cache.attend(layer, jnp.asarray(q),
                 jnp.asarray(kv[0][:, start:start + n]),
                 jnp.asarray(kv[1][:, start:start + n]))
    cache.advance(n)
    cache.commit_prefix(slot)
    return start


def _page_kv(n, rng):
    return (rng.standard_normal((1, n, 2, 8)).astype(np.float32),
            rng.standard_normal((1, n, 2, 8)).astype(np.float32))


# ---------------------------------------------------------------------------
# demote -> promote roundtrip
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("kv_dtype", ["native", "int8"])
def test_demote_promote_roundtrip_bit_exact(kv_dtype):
    """Evicting every ref==1 index page spills it to the host pool; a
    later admission promotes the pages back bit-exactly (int8 pools
    roundtrip their quantized codes AND scales untouched)."""
    rng = np.random.default_rng(1)
    kw = {} if kv_dtype == "native" else {"kv_dtype": "int8"}
    cache = _mk_cache(64, **kw)
    toks = np.arange(16)
    kv = _page_kv(16, rng)
    _prefill(cache, 0, toks, kv, rng)
    snap = {dg: cache._page_entry(p) for dg, p in cache._index.items()}
    cache.free(0)
    while cache._evict_lru():
        pass
    assert len(cache._index) == 0
    assert cache.host_demotions == len(snap)
    assert cache.prefix_evictions_device == len(snap)
    assert cache.host_pool.used_bytes > 0

    cached = _prefill(cache, 0, toks, kv, rng)
    assert cached == 12                     # (16-1)//4 matchable blocks
    assert cache.host_promotions == 3
    for dg, entry_old in snap.items():
        if dg not in cache._index:          # unmatchable 4th block
            continue
        entry_new = cache._page_entry(int(cache._index[dg]))
        for (ko, vo), (kn, vn) in zip(entry_old["layers"],
                                      entry_new["layers"]):
            assert np.array_equal(ko, kn) and np.array_equal(vo, vn)
        if kv_dtype == "int8":
            assert entry_old["kv_dtype"] == "int8"
            for so, sn in zip(entry_old["scales"], entry_new["scales"]):
                assert np.array_equal(so[0], sn[0])
                assert np.array_equal(so[1], sn[1])


def test_promotion_removes_host_copy():
    """Promotion is a move, not a copy: the device index becomes the
    authoritative home again and the host entry is gone."""
    rng = np.random.default_rng(2)
    cache = _mk_cache(64)
    toks = np.arange(16)
    kv = _page_kv(16, rng)
    _prefill(cache, 0, toks, kv, rng)
    cache.free(0)
    while cache._evict_lru():
        pass
    n_host = len(cache.host_pool)
    assert n_host == 4
    _prefill(cache, 0, toks, kv, rng)
    assert len(cache.host_pool) == n_host - cache.host_promotions


# ---------------------------------------------------------------------------
# second-level LRU bound
# ---------------------------------------------------------------------------

def test_host_pool_lru_bound_enforced():
    entry = {"page_size": 4, "kv_dtype": "native",
             "native_dtype": "float32",
             "layers": [(np.zeros((2, 4, 64), np.float32),
                         np.zeros((2, 4, 64), np.float32))],
             "scales": None}
    per = HostKVPool.entry_nbytes(entry)
    pool = HostKVPool(per * 3 / (1024 * 1024))   # room for exactly 3
    for i in range(8):
        assert pool.put(bytes([i]), dict(entry))
    assert len(pool) == 3
    assert pool.evictions == 5
    assert pool.used_bytes <= pool.max_bytes
    # LRU order: oldest survivors are 5, 6, 7; get() refreshes recency
    assert bytes([4]) not in pool and bytes([5]) in pool
    assert pool.get(bytes([5])) is not None
    pool.put(bytes([8]), dict(entry))
    assert bytes([5]) in pool and bytes([6]) not in pool


def test_oversized_entry_rejected():
    entry = {"page_size": 4, "kv_dtype": "native",
             "native_dtype": "float32",
             "layers": [(np.zeros((2, 4, 4096), np.float32),
                         np.zeros((2, 4, 4096), np.float32))],
             "scales": None}
    pool = HostKVPool(0.01)                  # smaller than one entry
    assert not pool.put(b"x", entry)
    assert len(pool) == 0 and pool.used_bytes == 0


# ---------------------------------------------------------------------------
# COW / refcount interplay with promoted pages
# ---------------------------------------------------------------------------

def test_promoted_page_shared_then_written_cow():
    """A promoted page re-registered under the index behaves exactly
    like a first-class prefix page: shared by two slots, a mid-block
    write triggers copy-on-write and the index copy keeps its bytes."""
    rng = np.random.default_rng(3)
    layer = object()
    cache = SlotPagedKVCache(2, page_size=4, max_len=32, num_pages=9,
                             host_pool=HostKVPool(64))
    toks = np.arange(12)
    chain = block_hash_chain(toks, 4)
    kv = _page_kv(12, rng)

    def fill(slot):
        cache.assign(slot, toks)
        start = int(cache.lens[slot])
        n = 12 - start
        t = np.asarray(toks[start:], np.float32)
        k = np.broadcast_to(t[None, :, None, None], (1, n, 1, 4)).copy()
        cache.begin_prefill(slot, n_valid=n)
        cache.attend(layer, jnp.asarray(np.zeros((1, n, 1, 4),
                                                 np.float32)),
                     jnp.asarray(k), jnp.asarray(k))
        cache.advance(n)
        cache.commit_prefix(slot)

    fill(0)
    cache.free(0)
    while cache._evict_lru():
        pass
    assert cache.host_demotions == 3
    fill(0)                                  # promotes 2 matchable blocks
    assert cache.host_promotions == 2
    fill(1)                                  # shares the promoted pages
    shared = int(cache._tables[1, 1])
    assert shared == int(cache._tables[0, 1])
    assert cache._ref[shared] == 3           # index + slot 0 + slot 1

    # mid-block write into slot 1's shared (promoted) block 1
    cache.lens[1] = 6
    t = np.asarray([100.0, 101.0], np.float32)
    k = np.broadcast_to(t[None, :, None, None], (1, 2, 1, 4)).copy()
    cache.begin_prefill(1, n_valid=2)
    cache.attend(layer, jnp.asarray(np.zeros((1, 2, 1, 4), np.float32)),
                 jnp.asarray(k), jnp.asarray(k))
    cache.advance(2)
    assert cache.cow_copies == 1
    assert int(cache._tables[1, 1]) != shared
    assert int(cache._index[chain[1]]) == shared
    kp, _ = cache._pools[id(layer)]
    assert float(kp[0, shared, 2, 0]) == 6.0            # index copy intact
    assert float(kp[0, int(cache._tables[1, 1]), 2, 0]) == 100.0


# ---------------------------------------------------------------------------
# mismatch rejection
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("corrupt", ["page_size", "kv_dtype"])
def test_geometry_mismatch_rejected(corrupt):
    """A host entry whose page geometry or dtype no longer matches the
    pool is dropped on promotion (never written into device pages), and
    the chain walk stops at the bad block."""
    rng = np.random.default_rng(4)
    pool = HostKVPool(64)
    cache = SlotPagedKVCache(1, page_size=4, max_len=32, num_pages=9,
                             host_pool=pool)
    toks = np.arange(16)
    _prefill(cache, 0, toks, _page_kv(16, rng), rng)
    chain = block_hash_chain(toks, 4)
    cache.free(0)
    while cache._evict_lru():
        pass
    dg = bytes(chain[0])
    pool._entries[dg][corrupt] = \
        8 if corrupt == "page_size" else "int8"
    cached = _prefill(cache, 0, toks, _page_kv(16, rng), rng)
    assert cache.host_promote_rejects == 1
    assert dg not in pool                    # dropped, not retried
    assert cached == 0                       # walk stopped at block 0
    assert cache.host_promotions == 0


# ---------------------------------------------------------------------------
# PADDLE_KV_HOST_POOL_MB=0: exact legacy eviction
# ---------------------------------------------------------------------------

def test_pool_mb_zero_restores_legacy(monkeypatch):
    monkeypatch.setenv("PADDLE_KV_HOST_POOL_MB", "0")
    rng = np.random.default_rng(5)
    cache = SlotPagedKVCache(1, page_size=4, max_len=32, num_pages=9)
    assert not cache.host_pool.enabled
    toks = np.arange(16)
    kv = _page_kv(16, rng)
    _prefill(cache, 0, toks, kv, rng)
    cache.free(0)
    while cache._evict_lru():
        pass
    assert cache.host_demotions == 0
    assert len(cache.host_pool) == 0
    assert cache.prefix_evictions_device == 4
    cached = _prefill(cache, 0, toks, kv, rng)
    assert cached == 0                       # evicted prefix is just gone
    assert cache.host_promotions == 0


def test_env_pool_mb_enables_engine_tier(model, monkeypatch):
    monkeypatch.setenv("PADDLE_KV_HOST_POOL_MB", "8")
    eng = ContinuousServingEngine(model)
    assert eng.host_pool_mb == 8.0
    assert eng._host_pool.enabled
    assert eng._host_pool.max_bytes == 8 * 1024 * 1024
    monkeypatch.setenv("PADDLE_KV_HOST_POOL_MB", "-1")
    with pytest.raises(ValueError):
        ContinuousServingEngine(model)


# ---------------------------------------------------------------------------
# engine-level: eviction churn with the tier on, bit-identical outputs
# ---------------------------------------------------------------------------

def test_engine_host_tier_parity_and_telemetry(model):
    """Three requests through a pool too small to keep both prefixes
    resident: with the host tier on, the third request's prefix promotes
    from host RAM (promotions > 0) and every output matches both the
    tier-off engine and the dense oracle; the kv-tier metric families
    are populated."""
    rng = np.random.RandomState(7)
    pA = rng.randint(0, 128, (1, 24)).astype(np.int64)
    pB = rng.randint(0, 128, (1, 24)).astype(np.int64)
    wants = [_oracle(model, p, 4) for p in (pA, pB, pA)]
    outs = {}
    for mb in (0, 64):
        eng = ContinuousServingEngine(model, max_batch_size=1,
                                      page_size=4, max_len=32,
                                      num_pages=10, host_pool_mb=mb)
        with eng:
            outs[mb] = [np.asarray(eng.generate(
                p, max_new_tokens=4, timeout=300).numpy())
                for p in (pA, pB, pA)]
            promos = eng._cache.host_promotions
            state = _engine_state(eng)
        if mb:
            assert promos > 0
            assert eng._host_pool.demotions > 0
            assert state["kv_host_tier"]["enabled"]
            assert state["kv_host_tier"]["promotions"] == \
                eng._host_pool.promotions
        else:
            assert promos == 0 and len(eng._host_pool) == 0
    for got, want in zip(outs[0], wants):
        np.testing.assert_array_equal(got, want)
    for got, want in zip(outs[64], wants):
        np.testing.assert_array_equal(got, want)
    snap = metrics()
    assert snap["paddle_kv_host_pool_bytes"]["series"]["capacity"] >= 0
    assert "used" in snap["paddle_kv_host_pool_bytes"]["series"]
    assert snap["paddle_kv_host_demotions_total"]["series"][""] > 0
    assert snap["paddle_kv_host_promotions_total"]["series"][""] > 0
    ev = snap["paddle_serving_prefix_evictions_total"]["series"]
    assert ev.get("device", 0) > 0


def test_export_pages_reads_through_host_tier():
    """Disagg handoff: a chain whose pages were demoted still exports —
    the blob reads through the host tier and reports how many pages it
    served from there (the router's handoff_host_pages accounting)."""
    rng = np.random.default_rng(8)
    cache = _mk_cache(64)
    toks = np.arange(16)
    kv = _page_kv(16, rng)
    _prefill(cache, 0, toks, kv, rng)
    chain = list(cache._index)
    cache.free(0)
    while cache._evict_lru():
        pass
    blob = cache.export_pages(chain)
    assert blob is not None and blob["host_pages"] == 4
    dst = _mk_cache(64)
    _prefill(dst, 0, np.arange(100, 104), _page_kv(4, rng), rng)
    dst.free(0)
    assert dst.import_pages(blob) == 4
    assert _prefill(dst, 0, toks, kv, rng) == 12
