"""Auto-parallel cost model + tuner (reference:
``auto_parallel/static/cost/`` + rule-based tuner — the analytic roofline
re-design; SURVEY.md §2.3 auto-parallel row)."""
import pytest

from paddle_tpu.distributed.auto_parallel import CostModel, Tuner, ModelSpec
from paddle_tpu.models import llama3_8b, llama_tiny


def _8b(batch=64, seq=4096):
    return ModelSpec.from_config(llama3_8b(), seq_len=seq, global_batch=batch)


def test_param_count_sane():
    m = _8b()
    # Llama-3-8B ~8e9 params (MHA approximation inflates q/k/v a little)
    assert 6e9 < m.n_params < 11e9


def test_small_model_prefers_data_parallel():
    m = ModelSpec.from_config(llama_tiny(), seq_len=128, global_batch=32)
    plans = Tuner(chip="v5p").tune(m, 8)
    best = plans[0].degrees
    assert best["mp"] == 1 and best["pp"] == 1, plans[0]
    assert best["dp"] * best["sharding"] == 8


def test_big_model_small_chip_needs_model_sharding():
    """8B training state (fp32 master + adam ≈ 128GB) on v5e (16GB):
    every valid plan must shard the model state, and 8 chips genuinely
    cannot hold it at all."""
    with pytest.raises(ValueError, match="no valid plan"):
        Tuner(chip="v5e").tune(_8b(batch=64, seq=2048), 8)
    plans = Tuner(chip="v5e").tune(_8b(batch=64, seq=2048), 16)
    best = plans[0].degrees
    assert best["sharding"] * best["mp"] * best["pp"] > 1, plans[0]
    hbm = CostModel(chip="v5e").hw["hbm"]
    assert plans[0].mem_per_chip < 0.9 * hbm


def test_memory_rejects_impossible():
    with pytest.raises(ValueError, match="no valid plan"):
        Tuner(chip="v5e").tune(_8b(batch=512, seq=8192), 1)


def test_more_chips_faster():
    t = Tuner(chip="v5p")
    t8 = t.tune(_8b(), 8)[0].step_time_s
    t32 = t.tune(_8b(), 32)[0].step_time_s
    assert t32 < t8


def test_divisibility_respected():
    m = ModelSpec(num_layers=6, hidden=512, intermediate=1408, vocab=1000,
                  seq_len=128, global_batch=16, num_heads=8)
    for p in Tuner(chip="v5p").tune(m, 16, top_k=10):
        d = p.degrees
        assert m.num_layers % d["pp"] == 0
        assert d["mp"] == 1 or m.hidden % d["mp"] == 0
        assert m.global_batch % (d["dp"] * d["sharding"]) == 0


def test_breakdown_fields():
    p = Tuner(chip="v5p").tune(_8b(), 16)[0]
    assert {"compute_s", "tp_s", "dp_s", "bubble"} <= set(p.breakdown)
    assert p.step_time_s >= p.breakdown["compute_s"] > 0


def test_fleet_auto_search_installs_tuned_degrees():
    """strategy.auto_search wires the cost-model Tuner into fleet.init
    (VERDICT.md round-2 §2.3 'tuner not wired to fleet defaults'): the
    chosen plan's degrees become the job's hybrid config/mesh."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.models import llama3_8b

    strat = dist.fleet.DistributedStrategy()
    strat.auto_search = True
    strat.auto_search_configs = {"model": llama3_8b(), "seq_len": 4096,
                                 "global_batch": 8, "chip": "v5p"}
    dist.fleet.init(is_collective=True, strategy=strat)
    try:
        d = strat.degrees()
        # an 8B model on 8 chips cannot be plain dp: the tuner must have
        # chosen real model sharding, and the mesh must match it
        assert any(d[k] > 1 for k in ("mp", "pp", "sharding", "sep")), d
        mesh = mesh_mod.get_mesh()
        for k, v in d.items():
            assert int(mesh.shape[k]) == v
    finally:
        mesh_mod.reset_mesh()


def test_fleet_auto_search_respects_explicit_degrees():
    """User-set degrees always win over the tuner."""
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.models import llama3_8b

    strat = dist.fleet.DistributedStrategy()
    strat.auto_search = True
    strat.auto_search_configs = {"model": llama3_8b(), "chip": "v5p"}
    strat.hybrid_configs = {"dp_degree": 4, "mp_degree": 2}
    dist.fleet.init(is_collective=True, strategy=strat)
    try:
        assert strat.degrees()["dp"] == 4 and strat.degrees()["mp"] == 2
    finally:
        mesh_mod.reset_mesh()
