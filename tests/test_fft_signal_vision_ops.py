"""paddle.fft / paddle.signal / paddle.vision.ops / PPYOLOE tests
(SURVEY.md §2.2 surface + §2.4 config 3)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as vops


def test_fft_roundtrip_and_grad():
    x = paddle.to_tensor(np.random.default_rng(0).normal(
        size=(4, 16)).astype(np.float32), stop_gradient=False)
    sp = paddle.fft.rfft(x)
    assert sp.shape == [4, 9]
    back = paddle.fft.irfft(sp, n=16)
    np.testing.assert_allclose(back.numpy(), x.numpy(), atol=1e-5)
    back.sum().backward()
    assert x.grad is not None
    np.testing.assert_allclose(x.grad.numpy(), np.ones((4, 16)), atol=1e-5)


def test_fft_2d_and_shift():
    x = paddle.randn([3, 8, 8])
    sp = paddle.fft.fft2(x)
    rec = paddle.fft.ifft2(sp)
    np.testing.assert_allclose(rec.numpy().real, x.numpy(), atol=1e-5)
    f = paddle.fft.fftfreq(8)
    sh = paddle.fft.fftshift(f)
    assert float(sh.numpy()[0]) == pytest.approx(-0.5)


def test_stft_istft_roundtrip():
    sig = paddle.to_tensor(np.random.default_rng(1).normal(
        size=(2, 256)).astype(np.float32))
    win = paddle.to_tensor(np.hanning(64).astype(np.float32))
    sp = paddle.signal.stft(sig, n_fft=64, hop_length=16, window=win)
    assert sp.shape[1] == 33            # onesided bins
    rec = paddle.signal.istft(sp, n_fft=64, hop_length=16, window=win,
                              length=256)
    np.testing.assert_allclose(rec.numpy(), sig.numpy(), atol=1e-4)


def test_frame_overlap_add():
    x = paddle.to_tensor(np.arange(10, dtype=np.float32))
    fr = paddle.signal.frame(x, frame_length=4, hop_length=2)
    assert fr.shape == [4, 4]           # 4 frames of length 4
    np.testing.assert_allclose(fr.numpy()[:, 0], [0, 1, 2, 3])
    back = paddle.signal.overlap_add(fr, hop_length=2)
    # positions covered by two frames are summed
    assert back.shape == [10]
    np.testing.assert_allclose(back.numpy()[0], 0.0)


def test_nms_and_box_iou():
    boxes = np.array([[0, 0, 10, 10], [1, 1, 11, 11], [20, 20, 30, 30]],
                     np.float32)
    scores = np.array([0.9, 0.8, 0.7], np.float32)
    keep = vops.nms(paddle.to_tensor(boxes), iou_threshold=0.5,
                    scores=paddle.to_tensor(scores))
    np.testing.assert_array_equal(np.sort(keep.numpy()), [0, 2])
    iou = vops.box_iou(paddle.to_tensor(boxes), paddle.to_tensor(boxes))
    np.testing.assert_allclose(np.diag(iou.numpy()), 1.0, atol=1e-6)
    # category-aware: same boxes, different classes -> both kept
    keep2 = vops.nms(paddle.to_tensor(boxes), iou_threshold=0.5,
                     scores=paddle.to_tensor(scores),
                     category_idxs=paddle.to_tensor(
                         np.array([0, 1, 0], np.int64)))
    assert len(keep2.numpy()) == 3


def test_roi_align_shape_and_values():
    # constant feature map -> every roi bin equals the constant
    x = paddle.to_tensor(np.full((1, 2, 8, 8), 3.0, np.float32))
    boxes = paddle.to_tensor(np.array([[0, 0, 4, 4], [2, 2, 6, 6]],
                                      np.float32))
    num = paddle.to_tensor(np.array([2], np.int32))
    out = vops.roi_align(x, boxes, num, output_size=2, spatial_scale=1.0)
    assert out.shape == [2, 2, 2, 2]
    np.testing.assert_allclose(out.numpy(), 3.0, atol=1e-5)


def test_distance2bbox():
    pts = paddle.to_tensor(np.array([[10.0, 10.0]], np.float32))
    dist = paddle.to_tensor(np.array([[2.0, 3.0, 4.0, 5.0]], np.float32))
    out = vops.distance2bbox(pts, dist)
    np.testing.assert_allclose(out.numpy(), [[8, 7, 14, 15]])


def test_ppyoloe_forward_train_predict():
    from paddle_tpu.models import ppyoloe_lite, DetectionLoss
    paddle.seed(0)
    model = ppyoloe_lite(num_classes=4)
    x = paddle.randn([2, 3, 64, 64])
    cls_outs, reg_outs = model(x)
    assert len(cls_outs) == 3
    assert cls_outs[0].shape == [2, 4, 8, 8]       # stride 8
    assert reg_outs[2].shape == [2, 4, 2, 2]       # stride 32

    # decode shapes
    scores, boxes = model.decode(cls_outs, reg_outs)
    p = 8 * 8 + 4 * 4 + 2 * 2
    assert scores.shape == [2, p, 4] and boxes.shape == [2, p, 4]

    # one training step decreases loss on dense targets
    loss_fn = DetectionLoss()
    tcls = [paddle.zeros(c.shape) for c in cls_outs]
    treg = [paddle.ones(r.shape) for r in reg_outs]
    mask = [paddle.ones(r.shape) for r in reg_outs]
    opt = paddle.optimizer.AdamW(learning_rate=5e-3,
                                 parameters=model.parameters())
    losses = []
    for _ in range(3):
        cls_outs, reg_outs = model(x)
        loss = loss_fn(cls_outs, reg_outs, tcls, treg, mask)
        loss.backward()
        opt.step()
        opt.clear_grad()
        losses.append(float(loss))
    assert losses[-1] < losses[0]

    # post-processing runs end-to-end
    dets = model.predict(x, score_thresh=0.0, top_k=5)
    assert len(dets) == 2
    assert dets[0]["boxes"].shape[1] == 4
    assert len(dets[0]["scores"]) <= 5
