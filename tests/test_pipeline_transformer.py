"""A REAL transformer through the jitted SPMD pipeline engine (VERDICT.md
round-1 item 3; reference parity contract: the ``hybrid_parallel_pp_layer`` /
``hybrid_parallel_pp_embedding`` tests of ``test/collective/fleet`` — a
pipelined GPT/Llama must match the non-pipelined oracle's loss and grads).

Runs on the 8-device CPU mesh (conftest). The pipelined model is
stage-heterogeneous: embedding pre-stage, N decoder blocks through the
ppermute schedule, final-norm + head post-stage, optionally tied embeddings
(SharedLayerDesc)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.engine import PipelinedModule
from paddle_tpu.models import LlamaForCausalLMPipe, llama_tiny
from paddle_tpu.models.llama import LlamaPretrainingCriterion


def _make_pipe(tie=False, n_layers=4, num_stages=2, vpp=None):
    paddle.seed(7)
    cfg = llama_tiny(num_hidden_layers=n_layers, tie_word_embeddings=tie)
    pipe = LlamaForCausalLMPipe(
        cfg, num_stages=num_stages,
        num_virtual_pipeline_stages=vpp)
    return cfg, pipe


def _data(cfg, batch=8, seq=16, seed=0):
    rng = np.random.default_rng(seed)
    ids = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)), jnp.int32)
    labels = jnp.asarray(rng.integers(0, cfg.vocab_size, (batch, seq)),
                         jnp.int32)
    return ids, labels


def _oracle_loss_and_grads(pipe, pm, ids, labels):
    """Non-pipelined oracle: run the SAME parameter arrays through the
    eager layer stack functionally (n_stages=1 path is NOT used — this is
    an independent sequential apply) and grad the identical loss."""
    crit = LlamaPretrainingCriterion()

    def loss_fn(edge, stacked):
        # sequential apply: pre, blocks in order, post
        from paddle_tpu.framework.functional import FunctionalModule
        key = jax.random.PRNGKey(0)
        h = pm._fm_pre(edge, [], key, ids)[0]
        flat = [a.reshape((-1,) + tuple(a.shape[2:])) for a in stacked]
        for i in range(len(pm.blocks)):
            arrs = [a[i] for a in flat]
            h, _ = pm._fm_blk(arrs, [], key, h)
        logits = pm._fm_post(edge, [], key, h)[0]
        fm_crit = FunctionalModule(crit)
        return fm_crit([], [], key, logits, labels)[0]

    edge, stacked = pm.edge_arrays(), pm.stacked_arrays()
    loss, grads = jax.value_and_grad(loss_fn, argnums=(0, 1))(edge, stacked)
    return loss, grads


def _pipelined_loss_and_grads(pm, ids, labels, n_micro):
    mb = ids.shape[0] // n_micro
    mx = ids.reshape((n_micro, mb) + tuple(ids.shape[1:]))
    crit = LlamaPretrainingCriterion()
    from paddle_tpu.framework.functional import FunctionalModule
    fm_crit = FunctionalModule(crit)
    key = jax.random.PRNGKey(0)

    @jax.jit
    def step(edge, stacked):
        def loss_fn(e, s):
            out = pm(e, s, mx)      # [M, mb, s, V]
            logits = out.reshape((-1,) + tuple(out.shape[2:]))
            return fm_crit([], [], key, logits, labels)[0]

        return jax.value_and_grad(loss_fn, argnums=(0, 1))(edge, stacked)

    return step(pm.edge_arrays(), pm.stacked_arrays())


@pytest.mark.parametrize("tie", [False, True])
def test_pipelined_llama_matches_oracle(tie):
    cfg, pipe = _make_pipe(tie=tie, n_layers=4, num_stages=2)
    mesh_mod.init_mesh({"dp": 4, "pp": 2})
    try:
        pm = PipelinedModule(pipe)
        assert pm.n_stages == 2 and pm.lpc == 2
        ids, labels = _data(cfg)
        o_loss, (o_ge, o_gs) = _oracle_loss_and_grads(pipe, pm, ids, labels)
        p_loss, (p_ge, p_gs) = _pipelined_loss_and_grads(pm, ids, labels,
                                                         n_micro=4)
        np.testing.assert_allclose(float(p_loss), float(o_loss),
                                   rtol=2e-5, atol=2e-5)
        for a, b in zip(p_ge, o_ge):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)
        for a, b in zip(p_gs, o_gs):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-5)
    finally:
        mesh_mod.reset_mesh()


def test_pipelined_llama_vpp():
    """Interleaved schedule: 8 blocks as 4 chunks on 2 stages (vpp=2)."""
    cfg, pipe = _make_pipe(n_layers=8, num_stages=2, vpp=2)
    mesh_mod.init_mesh({"dp": 4, "pp": 2})
    try:
        pm = PipelinedModule(pipe)
        assert pm.vpp == 2 and pm.n_chunks == 4 and pm.lpc == 2
        ids, labels = _data(cfg)
        o_loss, _ = _oracle_loss_and_grads(pipe, pm, ids, labels)
        p_loss, _ = _pipelined_loss_and_grads(pm, ids, labels, n_micro=4)
        np.testing.assert_allclose(float(p_loss), float(o_loss),
                                   rtol=2e-5, atol=2e-5)
    finally:
        mesh_mod.reset_mesh()


def test_tied_embedding_single_array_and_grad():
    """SharedLayerDesc ties embedding+head to ONE edge array; its grad is
    the SUM of embedding-lookup and head-matmul contributions (reference:
    the tied-weight allreduce of pipeline_parallel.py)."""
    cfg, pipe = _make_pipe(tie=True, n_layers=2, num_stages=2)
    mesh_mod.init_mesh({"dp": 4, "pp": 2})
    try:
        pm = PipelinedModule(pipe)
        embed_shaped = [tuple(p.shape) for p in pm.edge_params
                        if tuple(p.shape) == (cfg.vocab_size, cfg.hidden_size)]
        assert len(embed_shaped) == 1, \
            f"tied embedding must be deduped to one edge param: {embed_shaped}"
        ids, labels = _data(cfg)
        _, (p_ge, _) = _pipelined_loss_and_grads(pm, ids, labels, n_micro=2)
        idx = [i for i, p in enumerate(pm.edge_params)
               if tuple(p.shape) == (cfg.vocab_size, cfg.hidden_size)][0]
        g = np.asarray(p_ge[idx])
        # head contribution is dense over vocab; untouched-token rows would
        # be zero if only the embedding lookup contributed
        assert (np.abs(g).sum(axis=1) > 0).mean() > 0.9
    finally:
        mesh_mod.reset_mesh()


def test_train_batch_spmd_dispatch_and_loss_drop():
    """PipelineParallel.train_batch uses the jitted engine when a pp mesh
    axis exists, and training reduces the loss."""
    from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
        PipelineParallel)

    cfg, pipe = _make_pipe(n_layers=4, num_stages=2)
    mesh_mod.init_mesh({"dp": 4, "pp": 2})
    try:
        pp = PipelineParallel(pipe)
        pp.accumulate_steps = 4
        opt = paddle.optimizer.AdamW(learning_rate=3e-3,
                                     parameters=pipe.parameters())
        ids, labels = _data(cfg, batch=8, seq=16)
        from paddle_tpu.framework.core import Tensor
        losses = [float(pp.train_batch([Tensor(ids), Tensor(labels)], opt))
                  for _ in range(8)]
        assert pp._spmd, "expected SPMD engine dispatch under a pp mesh"
        assert losses[-1] < losses[0] - 0.1, losses
    finally:
        mesh_mod.reset_mesh()


def test_train_batch_eager_parity_vs_spmd():
    """Same model + data: eager accumulation shim and SPMD engine produce
    the same loss (the hybrid_parallel_pp parity contract)."""
    from paddle_tpu.distributed.fleet.meta_parallel.pipeline_parallel import (
        PipelineParallel)
    from paddle_tpu.framework.core import Tensor

    losses = {}
    for mode in ("eager", "spmd"):
        cfg, pipe = _make_pipe(n_layers=4, num_stages=2)
        ids, labels = _data(cfg)
        opt = paddle.optimizer.SGD(learning_rate=0.0,
                                   parameters=pipe.parameters())
        if mode == "spmd":
            mesh_mod.init_mesh({"dp": 4, "pp": 2})
        try:
            pp = PipelineParallel(pipe)
            pp.accumulate_steps = 4
            losses[mode] = float(
                pp.train_batch([Tensor(ids), Tensor(labels)], opt))
            if mode == "spmd":
                assert pp._spmd
        finally:
            mesh_mod.reset_mesh()
    np.testing.assert_allclose(losses["spmd"], losses["eager"],
                               rtol=2e-5, atol=2e-5)
