import numpy as np
import paddle_tpu as paddle
from paddle_tpu import nn, static


def test_save_load_inference_model_roundtrip(tmp_path):
    paddle.seed(3)
    model = nn.Sequential(nn.Linear(6, 12), nn.Tanh(), nn.Linear(12, 2))
    model.eval()
    x = np.random.RandomState(0).randn(3, 6).astype(np.float32)
    want = np.asarray(model(paddle.to_tensor(x)).numpy())

    exe = static.Executor()
    spec = static.InputSpec([3, 6], "float32", "x")  # static batch (jax.export)
    path = str(tmp_path / "inf_model")
    static.save_inference_model(path, [spec], [model], exe)

    prog, feed_names, fetch_names = static.load_inference_model(path, exe)
    assert feed_names == ["x"]           # spec names survive the export
    (got,) = exe.run(prog, feed={"x": x})
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-6)
    # misnamed feeds fail loudly instead of silently reordering
    import pytest
    with pytest.raises(KeyError, match="feed mismatch"):
        exe.run(prog, feed={"wrong": x})
