"""Pretrained-weight cache path (reference ``utils/download.py`` +
``model_urls``): weights placed in the local cache load through
``pretrained=True``; a cache miss raises with the actionable path."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision.models import resnet18
from paddle_tpu.vision.models._utils import model_urls
import paddle_tpu.utils as U


def test_cache_roundtrip(tmp_path, monkeypatch):
    monkeypatch.setattr(U, "_WEIGHTS_HOME", str(tmp_path))
    paddle.seed(11)
    donor = resnet18(num_classes=10)
    fname = os.path.basename(model_urls["resnet18"])
    paddle.save(donor.state_dict(), str(tmp_path / fname))

    paddle.seed(99)   # different init — must be overwritten by the load
    model = resnet18(pretrained=True, num_classes=10)
    for k, v in donor.state_dict().items():
        np.testing.assert_array_equal(np.asarray(v.numpy()),
                                      np.asarray(model.state_dict()[k]
                                                 .numpy()), err_msg=k)


def test_cache_miss_is_actionable(tmp_path, monkeypatch):
    monkeypatch.setattr(U, "_WEIGHTS_HOME", str(tmp_path / "nope"))
    with pytest.raises(IOError, match="place the weights file at"):
        resnet18(pretrained=True)


def test_mismatched_state_dict_rejected(tmp_path, monkeypatch):
    monkeypatch.setattr(U, "_WEIGHTS_HOME", str(tmp_path))
    donor = resnet18(num_classes=7)    # head shape differs from default
    fname = os.path.basename(model_urls["resnet18"])
    paddle.save(donor.state_dict(), str(tmp_path / fname))
    with pytest.raises(Exception):
        resnet18(pretrained=True, num_classes=10)
