"""Device memory runtime (SURVEY.md §2.1 'Memory/allocators' — the
user-touchable stats/accounting tier over PJRT; VERDICT.md round-2 L1
row 'facade-thin')."""
import numpy as np

import paddle_tpu as paddle
from paddle_tpu.device import memory as dmem


def test_stats_and_live_accounting():
    big = paddle.to_tensor(np.ones((256, 1024), np.float32))   # 1 MiB
    stats = dmem.memory_stats()
    assert isinstance(stats, dict)
    rep = dmem.live_tensor_report()
    assert rep, "live array accounting returned nothing"
    # our 1 MiB tensor appears in the aggregation
    hit = [r for r in rep if r["shape"] == [256, 1024]
           and r["dtype"] == "float32"]
    assert hit and hit[0]["total_bytes"] >= 256 * 1024 * 4
    assert rep == sorted(rep, key=lambda r: -r["total_bytes"])
    del big


def test_summary_and_peak_reset():
    s = dmem.memory_summary()
    assert "device memory summary" in s and "live buffer" in s
    dmem.reset_peak_memory_stats()
    x = paddle.to_tensor(np.ones((512, 512), np.float32))
    assert dmem.max_memory_allocated() >= 0
    # namespace surface: paddle.device.* and the cuda alias agree
    import paddle_tpu.device as device
    assert device.memory_allocated() == device.cuda.memory_allocated()
    device.cuda.empty_cache()           # must not raise
    del x


def test_memory_allocated_tracks_cpu_backend():
    # CPU PJRT may not implement memory_stats — the API must degrade to
    # zeros, never raise (the paddle facade contract)
    assert dmem.memory_allocated() >= 0
    assert dmem.memory_reserved() >= 0
