"""Telemetry plane (ISSUE 15): per-process HTTP exporters, fleet-wide
scrape aggregation, and the correlated structured event log — endpoint
bounds, strict exposition parsing, KV discovery, staleness/recovery,
remote debug dumps, eventlog rotation/atomicity, the log_query join, and
the plane-off bit-identity + zero-overhead contract."""
import json
import os
import subprocess
import sys
import threading
import time
import urllib.request
from urllib.error import HTTPError

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.elastic.tcp_kv import MemKVStore
from paddle_tpu.inference import ContinuousServingEngine, ServingRouter
from paddle_tpu.inference.fleet import replay as rp
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.profiler import eventlog, exporter, scrape, timeseries
from paddle_tpu.profiler import flight_recorder as fr
from paddle_tpu.profiler.exporter import TelemetryServer
from paddle_tpu.profiler.scrape import (FleetScraper, parse_metrics_text,
                                        render_metrics_text)
from paddle_tpu.profiler.telemetry import get_registry

REPO = os.path.join(os.path.dirname(__file__), "..")
sys.path.insert(0, os.path.join(REPO, "tools"))

ENGINE_KW = dict(max_batch_size=4, max_len=96, page_size=16,
                 prefill_chunk_tokens=16)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return LlamaForCausalLM(llama_tiny(num_hidden_layers=1,
                                       max_position_embeddings=160))


@pytest.fixture(autouse=True)
def _eventlog_clean():
    yield
    eventlog.reset()


def _get(addr, path, timeout=10):
    try:
        with urllib.request.urlopen(f"http://{addr}{path}",
                                    timeout=timeout) as resp:
            return resp.status, resp.read()
    except HTTPError as e:
        return e.code, e.read()


def _post(addr, path, data=b"", timeout=30):
    req = urllib.request.Request(f"http://{addr}{path}", data=data,
                                 method="POST")
    try:
        with urllib.request.urlopen(req, timeout=timeout) as resp:
            return resp.status, resp.read()
    except HTTPError as e:
        return e.code, e.read()


# ---------------------------------------------------------------------------
# exporter endpoints + bounds
# ---------------------------------------------------------------------------


class TestExporterEndpoints:
    def test_metrics_healthz_state_history(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TELEMETRY_HOST", "127.0.0.1")
        reg = get_registry()
        reg.counter("plane_probe_total", "probe", labels=("k",)).inc(7,
                                                                     k="a")
        with TelemetryServer(instance="ep0", port=0) as srv:
            assert srv.port > 0
            code, body = _get(srv.address, "/metrics")
            assert code == 200
            fams = parse_metrics_text(body.decode())
            assert fams["plane_probe_total"]["series"]["a"] == 7.0
            # /metrics agrees exactly with the in-process registry
            assert (reg.get("plane_probe_total").value(k="a")
                    == fams["plane_probe_total"]["series"]["a"])
            code, body = _get(srv.address, "/healthz")
            assert code == 200
            hz = json.loads(body)
            assert hz["ok"] is True and hz["instance"] == "ep0"
            code, body = _get(srv.address, "/state")
            assert code == 200 and "state" in json.loads(body)
            # /history: capped window, substring match
            h = timeseries.get_history()
            h.tick()
            code, body = _get(srv.address,
                              "/history?match=plane_probe&window_s=1e9")
            assert code == 200
            j = json.loads(body)
            assert j["window_s"] == exporter.MAX_HISTORY_WINDOW_S
            assert any(s["name"] == "plane_probe_total"
                       for s in j["series"])
            assert len(j["series"]) <= exporter.MAX_HISTORY_SERIES
            # the exporter meters itself
            assert (reg.get("paddle_telemetry_http_requests_total")
                    .value(route="/metrics") >= 1)

    def test_unknown_trace_404_and_method_bounds(self):
        with TelemetryServer(instance="ep1", port=0) as srv:
            code, _ = _get(srv.address, "/timeline/no-such-trace")
            assert code == 404
            code, _ = _get(srv.address, "/nope")
            assert code == 404
            code, _ = _get(srv.address, "/debug/dump")     # GET -> 405
            assert code == 405
            code, _ = _post(srv.address, "/metrics")       # POST -> 405
            assert code == 405
            # bounded bodies: oversized POST refused with 400
            big = b"x" * (exporter.MAX_POST_BYTES + 1)
            code, _ = _post(srv.address, "/debug/dump", data=big)
            assert code == 400

    def test_debug_dump_and_healthz_503(self, tmp_path, monkeypatch):
        monkeypatch.setenv("PADDLE_FLIGHT_DIR", str(tmp_path))
        rec = fr.get_flight_recorder()
        with TelemetryServer(instance="ep2", port=0) as srv:
            code, body = _post(srv.address, "/debug/dump")
            assert code == 200
            paths = json.loads(body)["ranks"]
            assert paths and all(os.path.exists(p)
                                 for p in paths.values())
            # a stale heartbeat flips /healthz to 503 (and names it)
            rec._heartbeats["zz"] = time.monotonic() - 10_000
            try:
                code, body = _get(srv.address, "/healthz")
                assert code == 503
                assert "zz" in json.loads(body)["stale_ranks"]
            finally:
                rec._heartbeats.pop("zz", None)

    def test_fixed_port_collision_falls_back_to_ephemeral(self):
        a = TelemetryServer(instance="a", port=0).start()
        try:
            b = TelemetryServer(instance="b", port=a.port).start()
            try:
                assert b.port != a.port and b.port > 0
            finally:
                b.stop()
        finally:
            a.stop()

    def test_instance_name_env_default(self, monkeypatch):
        monkeypatch.setenv("PADDLE_TELEMETRY_INSTANCE", "named-by-env")
        srv = TelemetryServer(port=0)
        assert srv.instance == "named-by-env"


# ---------------------------------------------------------------------------
# gate tiers (fresh interpreters: unset/0 = off, auto = ephemeral)
# ---------------------------------------------------------------------------


class TestKnobTiers:
    def test_disabled_inert_subprocess(self):
        code = (
            "import os, jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "from paddle_tpu.profiler import exporter, eventlog\n"
            "assert not exporter.exporter_enabled()\n"
            "assert exporter.maybe_start_exporter('t') is None\n"
            "os.environ['PADDLE_TELEMETRY_PORT'] = '0'\n"
            "assert not exporter.exporter_enabled()\n"
            "os.environ['PADDLE_TELEMETRY_PORT'] = 'auto'\n"
            "srv = exporter.maybe_start_exporter('t')\n"
            "assert srv is not None and srv.port > 0\n"
            "srv.stop()\n"
            "assert not eventlog.is_enabled()\n"
            "assert eventlog.log_event('x') is None\n"
            "print('GATE_OK')\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu")
        env.pop("PADDLE_TELEMETRY_PORT", None)
        env.pop("PADDLE_EVENTLOG", None)
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True, timeout=240)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "GATE_OK" in proc.stdout

    def test_eventlog_env_enable_at_import(self, tmp_path):
        path = tmp_path / "boot.jsonl"
        code = (
            "import jax\n"
            "jax.config.update('jax_platforms', 'cpu')\n"
            "from paddle_tpu.profiler import eventlog\n"
            "assert eventlog.is_enabled()\n"
            "eventlog.log_event('boot', trace_id='t0')\n"
            "print('EVENTLOG_OK')\n")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PADDLE_EVENTLOG=str(path), PADDLE_EVENTLOG_MAX_MB="1")
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True, timeout=240)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "EVENTLOG_OK" in proc.stdout
        rec = json.loads(path.read_text().splitlines()[0])
        assert rec["kind"] == "boot" and rec["trace_id"] == "t0"

    def test_disabled_path_costs_nothing_measurable(self):
        assert not eventlog.is_enabled()
        t0 = time.perf_counter()
        for _ in range(100_000):
            eventlog.log_event("noop")
        dt = time.perf_counter() - t0
        # a plain bool check: generous ceiling so CI noise cannot flake
        assert dt < 1.0, f"disabled log_event too slow: {dt:.3f}s"


# ---------------------------------------------------------------------------
# strict exposition parser
# ---------------------------------------------------------------------------


class TestStrictParser:
    def test_round_trips_the_registry(self):
        reg = get_registry()
        reg.counter("rt_probe_total", "probe", labels=("k",)).inc(3, k="x")
        reg.histogram("rt_probe_seconds", "probe").observe(0.02)
        from paddle_tpu.profiler.telemetry import metrics_text
        fams = parse_metrics_text(metrics_text())
        assert fams["rt_probe_total"]["series"]["x"] == 3.0
        snap = fams["rt_probe_seconds"]["series"][""]
        assert snap["count"] == 1 and "+Inf" in snap["buckets"]
        again = parse_metrics_text(render_metrics_text(fams))
        assert again["rt_probe_total"]["series"] \
            == fams["rt_probe_total"]["series"]
        assert set(again) == set(fams)

    def test_strictness_raises_on_garbage(self):
        with pytest.raises(ValueError):
            parse_metrics_text("this is not an exposition\n")
        with pytest.raises(ValueError):
            parse_metrics_text("undeclared_metric 1\n")   # no # TYPE
        with pytest.raises(ValueError):
            parse_metrics_text("# TYPE foo counter\nfoo{oops} 1\n")
        with pytest.raises(ValueError):
            parse_metrics_text("# TYPE foo counter\nfoo notanumber\n")
        with pytest.raises(ValueError):
            # inconsistent label names inside one family
            parse_metrics_text('# TYPE foo counter\nfoo{a="1"} 1\n'
                               'foo{b="2"} 2\n')


# ---------------------------------------------------------------------------
# scraper over static endpoints: merge, history fold, staleness cycle
# ---------------------------------------------------------------------------


def test_scraper_static_endpoints_history_fold(monkeypatch):
    monkeypatch.setenv("PADDLE_TELEMETRY_SCRAPE_INTERVAL_S", "0.25")
    reg = get_registry()
    ctr = reg.counter("fold_probe_total", "probe")
    ctr.inc(4)
    srv = TelemetryServer(instance="s0", port=0).start()
    sc = FleetScraper(endpoints={"s0": srv.address}, stale_s=0.5,
                      timeout_s=5.0)
    assert sc.interval_s == 0.25      # env knob drives the loop default
    try:
        assert sc.scrape_once() == {"s0": "ok"}
        merged = sc.merged()
        assert merged["fold_probe_total"]["series"]["s0"] == 4.0
        # the fleet view folded into the scraper's OWN history (the
        # series alert rules over the fleet evaluate against)
        assert sc.history.latest("fold_probe_total", "s0")[1] == 4.0
        ctr.inc(2)
        sc.scrape_once()
        assert sc.history.latest("fold_probe_total", "s0")[1] == 6.0
        assert len(sc.history.points("fold_probe_total", "s0")) == 2
        # dead endpoint -> stale after stale_s, survivors unaffected;
        # answers again -> recovered
        srv.stop()
        time.sleep(0.6)
        out = sc.scrape_once()
        assert out == {"s0": "error"}
        assert sc.instances()["s0"]["stale"] is True
        assert "s0" not in sc.merged().get("fold_probe_total",
                                           {}).get("series", {})
    finally:
        sc.stop()
        srv.stop()


# ---------------------------------------------------------------------------
# watchdog metrics-text rewrite is atomic (satellite)
# ---------------------------------------------------------------------------


def test_watchdog_metrics_text_rewrite_atomic(tmp_path):
    """Concurrent rewriters + a reader: the published file is ALWAYS a
    complete exposition (write-unique-tmp-then-os.replace), never a
    truncated body — the contract a scraper or `tpu_watch.sh metrics`
    tailing PADDLE_METRICS_TEXT_PATH depends on."""
    reg = get_registry()
    reg.counter("atomic_probe_total", "probe").inc(5)
    reg.histogram("atomic_probe_seconds", "probe").observe(0.1)
    path = tmp_path / "metrics.prom"
    dogs = [fr.Watchdog(fr.FlightRecorder(), deadline_s=300.0,
                        poll_s=1000.0, metrics_text_path=str(path))
            for _ in range(3)]
    stop = threading.Event()

    def rewrite(wd):
        while not stop.is_set():
            wd.write_metrics_text()

    threads = [threading.Thread(target=rewrite, args=(wd,))
               for wd in dogs]
    for t in threads:
        t.start()
    try:
        deadline = time.monotonic() + 5
        while not path.exists() and time.monotonic() < deadline:
            time.sleep(0.005)
        assert path.exists()
        for _ in range(300):
            text = path.read_text()
            fams = parse_metrics_text(text)     # strict: torn body raises
            assert fams["atomic_probe_total"]["series"][""] == 5.0
    finally:
        stop.set()
        for t in threads:
            t.join(timeout=10)
    assert not list(tmp_path.glob("*.tmp.*")), \
        "leaked tmp files from the rewrite path"


# ---------------------------------------------------------------------------
# event log: rotation + single-line atomicity under concurrent writers
# ---------------------------------------------------------------------------


class TestEventLog:
    def test_rotation_and_concurrent_single_line_writes(self, tmp_path,
                                                        monkeypatch):
        monkeypatch.setenv("PADDLE_EVENTLOG_MAX_MB", "0.02")   # ~20 KiB
        path = tmp_path / "ev.jsonl"
        log = eventlog.EventLog(str(path))        # env knob wins
        assert log.max_bytes == int(0.02 * (1 << 20))
        reg = get_registry()
        rot_before = reg.counter("paddle_eventlog_rotations_total").value()
        rec_before = reg.counter("paddle_eventlog_records_total").value()
        pad = "x" * 120

        def writer(k):
            for i in range(150):
                log.append("spam", trace_id=f"t-{k}-{i}",
                           replica=f"r{k}", pad=pad)

        threads = [threading.Thread(target=writer, args=(k,))
                   for k in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert log.rotations >= 1
        assert (path.parent / "ev.jsonl.1").exists()
        # every surviving line is one whole JSON record — concurrent
        # writers may interleave LINES, never bytes
        seen = 0
        for p in (path, path.parent / "ev.jsonl.1"):
            for line in p.read_text().splitlines():
                rec = json.loads(line)
                assert rec["kind"] == "spam" and "trace_id" in rec
                seen += 1
        assert seen > 0
        assert (reg.counter("paddle_eventlog_rotations_total").value()
                - rot_before) >= 1
        assert (reg.counter("paddle_eventlog_records_total").value()
                - rec_before) == 8 * 150

    def test_flight_and_trace_tees(self, tmp_path):
        eventlog.enable(str(tmp_path / "tee.jsonl"))
        from paddle_tpu.profiler import request_trace as rt
        fr.record_event("controller", action="scale_up", reason="test")
        ctx = rt.start_request(tenant="acme", source="test")
        rt.add_event(ctx, "route", replica="r7", policy="affinity")
        rt.finish_request(ctx, status="ok")
        eventlog.disable()
        recs = [json.loads(l) for l in
                (tmp_path / "tee.jsonl").read_text().splitlines()]
        kinds = [r["kind"] for r in recs]
        assert "controller" in kinds          # flight-recorder tee
        assert "admission" in kinds and "route" in kinds \
            and "finish" in kinds             # request-trace tee
        route = next(r for r in recs if r["kind"] == "route")
        assert route["trace_id"] == ctx.trace_id
        assert route["replica"] == "r7"


# ---------------------------------------------------------------------------
# log_query CLI (incl. the poisoned-interpreter discipline)
# ---------------------------------------------------------------------------


def _story_fixtures(tmp_path):
    """Two per-replica logs telling one requeued request's story."""
    t0 = 1_754_300_000.0
    a = [
        {"ts": t0 + 0.0, "kind": "admission", "rank": 0,
         "trace_id": "req-abc", "tenant": "acme"},
        {"ts": t0 + 0.1, "kind": "route", "rank": 0, "replica": "r0",
         "trace_id": "req-abc", "policy": "affinity"},
        {"ts": t0 + 1.0, "kind": "fleet_replica_dead", "rank": 0,
         "replica": "r0", "reason": "killed"},
        {"ts": t0 + 1.1, "kind": "requeue", "rank": 0, "replica": "r0",
         "trace_id": "req-abc", "attempt": 1},
    ]
    b = [
        {"ts": t0 + 1.2, "kind": "route", "rank": 0, "replica": "r1",
         "trace_id": "req-abc", "policy": "balance"},
        {"ts": t0 + 2.0, "kind": "delivered", "rank": 0, "replica": "r1",
         "trace_id": "req-abc", "attempt": 2},
        {"ts": t0 + 2.1, "kind": "finish", "rank": 0, "replica": "r1",
         "trace_id": "req-abc", "status": "ok"},
        {"ts": t0 + 5.0, "kind": "admission", "rank": 0,
         "trace_id": "req-other"},
    ]
    pa, pb = tmp_path / "r0-events.jsonl", tmp_path / "r1-events.jsonl"
    pa.write_text("".join(json.dumps(r) + "\n" for r in a))
    pb.write_text("".join(json.dumps(r) + "\n" for r in b))
    return pa, pb, t0


def test_log_query_joins_and_filters(tmp_path, capsys):
    import log_query as lq
    pa, pb, t0 = _story_fixtures(tmp_path)
    rows = lq.query([str(pa), str(pb)], trace="req-abc")
    kinds = [r["kind"] for r in rows]
    assert kinds == ["admission", "route", "requeue", "route",
                     "delivered", "finish"]
    files = {r["_file"] for r in rows}
    assert files == {"r0-events.jsonl", "r1-events.jsonl"}
    # replica / kind / window filters
    assert all(r["replica"] == "r1"
               for r in lq.query([str(pa), str(pb)], replica="r1"))
    assert [r["kind"] for r in lq.query(
        [str(pa), str(pb)], kinds={"requeue", "delivered"})] \
        == ["requeue", "delivered"]
    assert len(lq.query([str(pa), str(pb)], since=t0 + 1.0,
                        until=t0 + 1.3)) == 3
    # CLI: text mode prints the ordered story, exit 0
    rc = lq.main(["--trace", "req-abc", str(pa), str(pb)])
    out = capsys.readouterr().out
    assert rc == 0
    assert out.index("admission") < out.index("requeue") \
        < out.index("delivered")
    assert lq.main(["--trace", "no-such", str(pa)]) == 1
    capsys.readouterr()


def test_log_query_no_jax_import(tmp_path):
    """tools/log_query.py must run with jax AND numpy poisoned out of
    the interpreter — it joins logs scp'd off the fleet on machines
    with no accelerator stack."""
    pa, pb, _ = _story_fixtures(tmp_path)
    code = (
        "import sys\n"
        "sys.modules['jax'] = None\n"
        "sys.modules['numpy'] = None\n"
        "sys.argv = ['log_query.py', '--until', '1754300004', %r, %r]\n"
        "import runpy\n"
        "try:\n"
        "    runpy.run_path(%r, run_name='__main__')\n"
        "except SystemExit as e:\n"
        "    raise SystemExit(e.code or 0)\n"
        % (str(pa), str(pb),
           os.path.join(REPO, "tools", "log_query.py")))
    proc = subprocess.run([sys.executable, "-c", code],
                          capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr
    assert proc.stdout.index("admission") \
        < proc.stdout.index("fleet_replica_dead") \
        < proc.stdout.index("requeue") < proc.stdout.index("delivered")


# ---------------------------------------------------------------------------
# fleet console --scrape (live mode, no-jax discipline)
# ---------------------------------------------------------------------------


def test_fleet_console_scrape_live_no_jax():
    reg = get_registry()
    reg.counter("console_probe_total", "probe").inc(9)
    srv = TelemetryServer(instance="c0", port=0).start()
    try:
        code = (
            "import sys\n"
            "sys.modules['jax'] = None\n"
            "sys.modules['numpy'] = None\n"
            "sys.argv = ['fleet_console.py', '--scrape', %r,\n"
            "            '--match', 'console_probe']\n"
            "import runpy\n"
            "try:\n"
            "    runpy.run_path(%r, run_name='__main__')\n"
            "except SystemExit as e:\n"
            "    raise SystemExit(e.code or 0)\n"
            % (f"c0={srv.address}",
               os.path.join(REPO, "tools", "fleet_console.py")))
        proc = subprocess.run([sys.executable, "-c", code],
                              capture_output=True, text=True, timeout=60)
        assert proc.returncode == 0, proc.stderr
        assert "live fleet" in proc.stdout
        assert "console_probe_total{c0}  9" in proc.stdout
        assert "healthy" in proc.stdout
    finally:
        srv.stop()


# ---------------------------------------------------------------------------
# the acceptance: 3-replica fleet, KV discovery, exact agreement,
# staleness + recovery, remote dump, cross-replica story
# ---------------------------------------------------------------------------


def _wait_for(cond, timeout=15.0, interval=0.05):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if cond():
            return True
        time.sleep(interval)
    return cond()


def test_fleet_telemetry_plane_acceptance(model, tmp_path, monkeypatch):
    monkeypatch.setenv("PADDLE_TELEMETRY_PORT", "auto")
    monkeypatch.setenv("PADDLE_TELEMETRY_STALE_S", "1.0")
    monkeypatch.setenv("PADDLE_TELEMETRY_SCRAPE_INTERVAL_S", "0.1")
    monkeypatch.setenv("PADDLE_FLIGHT_DIR", str(tmp_path / "flight"))
    eventlog.enable(str(tmp_path / "events.jsonl"))
    store = MemKVStore()
    router = ServingRouter(model, num_replicas=3, policy="balance",
                           engine_kwargs=ENGINE_KW, store=store,
                           heartbeat_ttl=600.0)
    reg = get_registry()
    sc = None
    try:
        with router:
            # -- discovery: each replica exports on its own ephemeral
            # port, announced under fleet/telemetry/<rid> in the store
            assert sorted(store.keys("fleet/telemetry/")) == [
                "fleet/telemetry/r0", "fleet/telemetry/r1",
                "fleet/telemetry/r2"]
            ports = {r.id: r.exporter.port for r in router.replicas}
            assert all(p > 0 for p in ports.values())
            assert len(set(ports.values())) == 3
            addrs = {r.id: r.exporter.address for r in router.replicas}

            # -- PR-11 bursty replay drives seeded load through the fleet
            trace = rp.make_trace(preset="bursty", seed=5,
                                  duration_s=1.2, rate_rps=3.0,
                                  burst_factor=4.0, burst_start_frac=0.3,
                                  burst_dur_frac=0.3, prompt_len=(4, 12),
                                  new_tokens=(2, 3))
            harness = rp.ReplayHarness(
                router, trace, vocab_size=128,
                history=timeseries.MetricsHistory(capacity=512),
                tick_interval_s=0.25, cooldown_s=0.25)
            rep = harness.run()
            assert rep.requests > 0

            # -- fleet_metrics() agrees EXACTLY with the in-process
            # registry on shared counters (thread-tier replicas share
            # one registry; each instance's scrape must reproduce it)
            sc = scrape.start_fleet_scraper(store=store, timeout_s=10.0)
            out = sc.scrape_once()
            assert out == {"r0": "ok", "r1": "ok", "r2": "ok"}, out
            merged = scrape.fleet_metrics()
            routed = reg.get("paddle_fleet_routed_total")
            fam = merged["paddle_fleet_routed_total"]
            assert fam["label_names"] == ["instance", "policy"]
            checked = 0
            for key, val in fam["series"].items():
                inst, _, policy = key.partition(",")
                assert val == routed.value(policy=policy), (key, val)
                checked += 1
            assert checked >= 3      # every instance reproduced it
            assert reg.counter("paddle_telemetry_scrapes_total",
                               labels=("outcome",)).value(outcome="ok") \
                >= 3
            # the merged text view round-trips the strict parser too
            again = parse_metrics_text(scrape.fleet_metrics_text())
            assert again["paddle_fleet_routed_total"]["series"] \
                == fam["series"]

            # -- every endpoint's /metrics body round-trips the strict
            # exposition parser
            for rid, addr in addrs.items():
                code, body = _get(addr, "/metrics")
                assert code == 200
                fams = parse_metrics_text(body.decode())
                rt2 = parse_metrics_text(render_metrics_text(fams))
                assert rt2["paddle_fleet_routed_total"]["series"] \
                    == fams["paddle_fleet_routed_total"]["series"]

            # -- POST /debug/dump on a live replica while a request is
            # in flight: the dump must NAME it
            prompts = [np.random.RandomState(11 + i)
                       .randint(0, 128, (1, 20)).astype(np.int64)
                       for i in range(6)]
            results = [None] * 6
            errors = [None] * 6

            def call(i):
                try:
                    results[i] = np.asarray(router.generate(
                        prompts[i], max_new_tokens=24,
                        timeout=600).numpy())
                except Exception as e:      # noqa: BLE001
                    errors[i] = e

            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(6)]
            for t in threads:
                t.start()
            assert _wait_for(lambda: any(r.inflight
                                         for r in router.replicas))
            busy = max((r for r in router.replicas if r.inflight),
                       key=lambda r: len(r.inflight))
            code, body = _post(addrs[busy.id], "/debug/dump")
            assert code == 200
            dump_paths = json.loads(body)["ranks"]
            dump = json.load(open(next(iter(dump_paths.values()))))
            in_flight_traces = [
                a.get("trace")
                for prov in dump["state"].values()
                if isinstance(prov, dict)
                for a in prov.get("request_ages", [])]
            assert any(in_flight_traces), \
                "dump did not name the in-flight requests"

            # -- hard-kill the busy replica mid-flight: its requests
            # requeue to survivors (the story the event log must tell)
            router.kill_replica(busy.id)
            victim = busy.id
            for t in threads:
                t.join()
            assert not [e for e in errors if e], errors
            assert router.stats()["requeues_total"] >= 1

            # -- the scrape loop marks the dead endpoint stale within
            # PADDLE_TELEMETRY_STALE_S, gauge ticks, survivors keep
            # being served
            sc_started_mono = time.monotonic()
            assert _wait_for(
                lambda: sc.instances().get(victim, {}).get("stale"))
            stale_after = time.monotonic() - sc_started_mono
            assert stale_after < 10.0
            assert reg.get("paddle_telemetry_stale_instances") \
                .value() >= 1
            merged = scrape.fleet_metrics()
            live_insts = {k.split(",", 1)[0] for k in
                          merged["paddle_fleet_routed_total"]["series"]}
            assert victim not in live_insts
            assert live_insts == {r.id for r in router.replicas
                                  if r.id != victim}
            survivors = {r.id for r in router.replicas
                         if r.id != victim}
            out = sc.scrape_once()
            assert all(out[s] == "ok" for s in survivors)
            assert out.get(victim) == "error"

            # -- rejoin: fresh endpoint (new ephemeral port), scraper
            # recovers, gauge returns to 0
            dead_engine = router._replica(victim).engine
            assert _wait_for(lambda: dead_engine._thread is None
                             or not dead_engine._thread.is_alive())
            router.rejoin(victim)
            assert router._replica(victim).exporter.port > 0
            assert _wait_for(
                lambda: not sc.instances().get(victim, {}).get("stale"))
            sc.scrape_once()
            assert reg.get("paddle_telemetry_stale_instances") \
                .value() == 0
            assert victim in {
                k.split(",", 1)[0] for k in
                scrape.fleet_metrics()["paddle_fleet_routed_total"]
                ["series"]}
    finally:
        if sc is not None:
            scrape.stop_fleet_scraper()
        eventlog.disable()

    # -- tools/log_query.py --trace reconstructs the requeued request's
    # admission -> kill -> requeue -> delivered story ACROSS two
    # replicas' event logs (split the process log by writing replica,
    # exactly what per-process logs would hold)
    import log_query as lq
    recs = [json.loads(l) for l in
            (tmp_path / "events.jsonl").read_text().splitlines()]
    requeued = [r for r in recs if r["kind"] == "requeue"
                and r.get("trace_id")]
    assert requeued, "no requeue event reached the event log"
    story_trace = requeued[0]["trace_id"]
    va, vb = tmp_path / "rA.jsonl", tmp_path / "rB.jsonl"
    with open(va, "w") as fa, open(vb, "w") as fb:
        for r in recs:
            tgt = fa if r.get("replica") == victim else fb
            tgt.write(json.dumps(r) + "\n")
    rows = lq.query([str(va), str(vb)], trace=story_trace)
    kinds = [r["kind"] for r in rows]
    assert kinds[0] == "admission"
    assert "requeue" in kinds and "delivered" in kinds
    assert kinds.index("requeue") < kinds.index("delivered")
    assert {r["_file"] for r in rows} == {"rA.jsonl", "rB.jsonl"}
    # the kill itself is in the joined window (replica-level event,
    # joined by time, not trace id)
    t_requeue = next(r["ts"] for r in rows if r["kind"] == "requeue")
    kills = lq.query([str(va), str(vb)], kinds={"fleet_replica_dead"},
                     until=t_requeue)
    assert any(k.get("replica") == victim for k in kills)


# ---------------------------------------------------------------------------
# plane off == bit-identical outputs, zero overhead
# ---------------------------------------------------------------------------


def test_plane_on_off_bit_identical(model, tmp_path, monkeypatch):
    """With PADDLE_TELEMETRY_PORT unset the plane is inert and outputs
    match a plane-on run bit-for-bit — exporter, scraper and event log
    observe, never steer."""
    p = np.random.RandomState(3).randint(0, 128, (1, 12)).astype(np.int64)
    monkeypatch.delenv("PADDLE_TELEMETRY_PORT", raising=False)
    eng = ContinuousServingEngine(model, **ENGINE_KW)
    with eng:
        assert getattr(eng, "_exporter", None) is None
        off = np.asarray(eng.generate(p, max_new_tokens=8,
                                      timeout=600).numpy())
    monkeypatch.setenv("PADDLE_TELEMETRY_PORT", "auto")
    eventlog.enable(str(tmp_path / "onoff.jsonl"))
    try:
        eng2 = ContinuousServingEngine(model, **ENGINE_KW)
        with eng2:
            assert eng2._exporter is not None and eng2._exporter.port > 0
            code, body = _get(eng2._exporter.address, "/metrics")
            assert code == 200 and b"paddle_serving" in body
            on = np.asarray(eng2.generate(p, max_new_tokens=8,
                                          timeout=600).numpy())
        assert eng2._exporter is None      # stopped with the engine
    finally:
        eventlog.disable()
    np.testing.assert_array_equal(on, off)


def test_controller_exporter_lifecycle(model, monkeypatch):
    """The FleetController exports too, on the fleet's discovery
    prefix, and tears its endpoint down with stop()."""
    from paddle_tpu.inference import FleetController
    monkeypatch.setenv("PADDLE_TELEMETRY_PORT", "auto")
    store = MemKVStore()
    router = ServingRouter(model, num_replicas=2, engine_kwargs=ENGINE_KW,
                           store=store, heartbeat_ttl=600.0)
    with router:
        ctl = FleetController(router, interval_s=0.1)
        ctl.start()
        try:
            assert ctl.exporter is not None
            assert "fleet/telemetry/controller" in \
                store.keys("fleet/telemetry/")
            code, body = _get(ctl.exporter.address, "/healthz")
            assert code in (200, 503) and json.loads(body)["instance"] \
                == "controller"
        finally:
            ctl.stop()
        assert ctl.exporter is None
        assert "fleet/telemetry/controller" not in \
            store.keys("fleet/telemetry/")
