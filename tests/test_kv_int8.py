"""int8 KV pages (ISSUE 10): row-codec bounds, quantized kernel-tier
parity vs the dequantized oracle, cache-level attend tolerance, engine
end-to-end (incl. composing with speculative decode), bit-exact disagg
export/import of quantized pages, dtype-mismatch rejection, the >=1.9x
capacity bar, COW scale copies, and the dtype-aware bytes telemetry."""
import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.models.generation import (SlotPagedKVCache, block_hash_chain,
                                          dequantize_kv_rows, kv_page_nbytes,
                                          quantize_kv_rows)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return LlamaForCausalLM(llama_tiny(num_hidden_layers=2,
                                       max_position_embeddings=256))


def _oracle(model, p, n):
    return np.asarray(model.generate(paddle.to_tensor(p),
                                     max_new_tokens=n)._data)


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------

def test_row_codec_error_bound():
    rng = np.random.RandomState(0)
    x = jnp.asarray(rng.randn(2, 7, 64) * 3.0, jnp.float32)
    q, s = quantize_kv_rows(x)
    assert q.dtype == jnp.int8 and s.shape == (2, 7)
    err = np.abs(np.asarray(dequantize_kv_rows(q, s)) - np.asarray(x))
    bound = np.asarray(s)[..., None] / 2 + 1e-7
    assert (err <= bound).all()
    # zero rows stay finite (scale floor, no division blow-up)
    qz, sz = quantize_kv_rows(jnp.zeros((1, 2, 8)))
    assert np.asarray(dequantize_kv_rows(qz, sz)).max() == 0.0


def test_kv_page_nbytes_capacity_ratio():
    """Acceptance bar: same-HBM page capacity >= 1.9x native."""
    f32 = kv_page_nbytes(8, 128, 16, "native", "float32", num_layers=32)
    bf16 = kv_page_nbytes(8, 128, 16, "native", "bfloat16", num_layers=32)
    i8 = kv_page_nbytes(8, 128, 16, "int8", num_layers=32)
    assert f32 / i8 >= 1.9                   # ~3.88 at d=128
    assert bf16 / i8 >= 1.9                  # ~1.94 at d=128
    # at this repo's f32-native tiny configs the win is larger still
    assert kv_page_nbytes(2, 16) / kv_page_nbytes(2, 16,
                                                  kv_dtype="int8") >= 1.9


# ---------------------------------------------------------------------------
# quantized kernel tiers vs the dequantized oracle
# ---------------------------------------------------------------------------

def _quant_pool(kv=2, npages=10, page=8, d=32, seed=0):
    rs = np.random.RandomState(seed)
    kq, ks = quantize_kv_rows(rs.randn(kv, npages, page, d))
    vq, vs = quantize_kv_rows(rs.randn(kv, npages, page, d))
    tbl = jnp.asarray(rs.randint(1, npages, (3, 4)), jnp.int32)
    return kq, ks, vq, vs, tbl


def test_paged_attention_int8_parity():
    from paddle_tpu.ops.pallas.paged_attention import (
        paged_attention, paged_attention_reference)
    kq, ks, vq, vs, tbl = _quant_pool()
    rs = np.random.RandomState(1)
    q = jnp.asarray(rs.randn(3, 4, 32), jnp.float32)
    lens = jnp.asarray([20, 7, 30], jnp.int32)
    out = paged_attention(q, kq, vq, tbl, lens, k_scales=ks, v_scales=vs,
                          interpret=True)
    ref = paged_attention_reference(q, dequantize_kv_rows(kq, ks),
                                    dequantize_kv_rows(vq, vs), tbl, lens)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ragged_attention_int8_parity_all_tiers():
    from paddle_tpu.ops.pallas.ragged_paged_attention import (
        _ragged_paged_attention_xla, _token_descriptors,
        ragged_paged_attention, ragged_paged_attention_reference)
    kq, ks, vq, vs, tbl = _quant_pool(seed=2)
    rs = np.random.RandomState(3)
    # decode span + speculative verify span (q_len=4) + prefill span
    layout = [(0, 0, 1, 20), (1, 1, 4, 12), (2, 5, 3, 3)]
    slots = np.asarray([x[0] for x in layout], np.int32)
    qs = np.asarray([x[1] for x in layout], np.int32)
    ql = np.asarray([x[2] for x in layout], np.int32)
    ctx = np.asarray([x[3] for x in layout], np.int32)
    q = jnp.asarray(rs.randn(8, 4, 32), jnp.float32)
    kd, vd = dequantize_kv_rows(kq, ks), dequantize_kv_rows(vq, vs)
    ref = ragged_paged_attention_reference(q, kd, vd, tbl, slots, qs, ql,
                                           ctx)
    out = ragged_paged_attention(q, kq, vq, tbl, slots, qs, ql, ctx,
                                 k_scales=ks, v_scales=vs, interpret=True)
    ts, tc = _token_descriptors(8, slots, qs, ql, ctx)
    xla = _ragged_paged_attention_xla(q, kq, vq, tbl, ts, tc,
                                      sm_scale=32 ** -0.5, k_scales=ks,
                                      v_scales=vs)
    for _, a, l, _ in layout:
        np.testing.assert_allclose(np.asarray(out)[a:a + l],
                                   np.asarray(ref)[a:a + l],
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(xla)[a:a + l],
                                   np.asarray(ref)[a:a + l],
                                   rtol=2e-5, atol=2e-5)


# ---------------------------------------------------------------------------
# cache-level: int8 attend within documented tolerance of native
# ---------------------------------------------------------------------------

def test_cache_attend_int8_close_to_native():
    """Decode attention through an int8 pool stays within the documented
    tolerance of the native-dtype oracle (round-trip error per element
    <= max|row|/254 => ~5e-2 absolute on randn-scale KV outputs)."""
    class _Layer:                            # cache keys by id(layer)
        pass

    from paddle_tpu.framework.core import Tensor

    layer = _Layer()
    rs = np.random.RandomState(4)
    outs = {}
    for dtype in ("native", "int8"):
        cache = SlotPagedKVCache(2, page_size=8, max_len=64,
                                 kv_dtype=dtype)
        # identical prefill chunk then one decode step
        k = Tensor(jnp.asarray(np.random.RandomState(5)
                               .randn(1, 12, 2, 32), jnp.float32))
        v = Tensor(jnp.asarray(np.random.RandomState(6)
                               .randn(1, 12, 2, 32), jnp.float32))
        q = Tensor(jnp.asarray(np.random.RandomState(7)
                               .randn(1, 12, 4, 32), jnp.float32))
        cache.assign(0, np.arange(12))
        cache.begin_prefill(0, 12)
        out = cache.attend(layer, q, k, v)
        cache.advance(12)
        qd = Tensor(jnp.asarray(np.random.RandomState(8)
                                .randn(2, 1, 4, 32), jnp.float32))
        kd = Tensor(jnp.asarray(np.random.RandomState(9)
                                .randn(2, 1, 2, 32), jnp.float32))
        vd = Tensor(jnp.asarray(np.random.RandomState(10)
                                .randn(2, 1, 2, 32), jnp.float32))
        cache.begin_decode(np.asarray([True, False]))
        dec = cache.attend(layer, qd, kd, vd)
        outs[dtype] = (np.asarray(out._data), np.asarray(dec._data))
    np.testing.assert_allclose(outs["int8"][0], outs["native"][0],
                               atol=8e-2)
    np.testing.assert_allclose(outs["int8"][1][0], outs["native"][1][0],
                               atol=8e-2)


# ---------------------------------------------------------------------------
# engine end-to-end + telemetry
# ---------------------------------------------------------------------------

def _engine(model, **kw):
    from paddle_tpu.inference import ContinuousServingEngine
    kw.setdefault("max_batch_size", 2)
    kw.setdefault("max_len", 96)
    kw.setdefault("page_size", 16)
    return ContinuousServingEngine(model, **kw)


def test_engine_int8_end_to_end_with_spec(model):
    """int8 pages serve real traffic, compose with speculative decode,
    and the engine state names the dtype and byte accounting."""
    from paddle_tpu.inference.serving import _engine_state
    from paddle_tpu.profiler import metrics

    rng = np.random.RandomState(11)
    p = rng.randint(0, 128, (1, 20)).astype(np.int64)
    eng = _engine(model, kv_dtype="int8", spec_decode=True, spec_k=3,
                  draft_model=model)
    with eng:
        out = np.asarray(eng.generate(p, max_new_tokens=6,
                                      timeout=300).numpy())
        state = _engine_state(eng)
    assert out.shape == (1, 26)
    assert eng._cache.kv_quant
    assert eng.spec_accepted_tokens > 0      # spec + int8 compose
    pc = state["prefix_cache"]
    assert pc["kv_dtype"] == "int8"
    assert pc["page_nbytes"] == kv_page_nbytes(
        2, 16, 16, "int8", num_layers=2)     # llama_tiny: 2 kv heads, d=16
    assert pc["pool_bytes_capacity"] == \
        (eng._cache.num_pages - 1) * pc["page_nbytes"]
    snap = metrics()["paddle_serving_page_pool_bytes"]["series"]
    assert snap.get("capacity", 0) == pc["pool_bytes_capacity"]
    assert snap.get("used", -1) >= 0


def test_engine_int8_vs_native_same_shape_and_tolerance(model):
    """The int8 engine's greedy stream stays plausible: same shape, and
    on this tiny config the tokens match native exactly (a tolerance
    check, not the repo's bit-parity contract — PERF.md documents the
    distinction)."""
    rng = np.random.RandomState(12)
    p = rng.randint(0, 128, (1, 24)).astype(np.int64)
    with _engine(model) as eng:
        native = np.asarray(eng.generate(p, max_new_tokens=4,
                                         timeout=300).numpy())
    with _engine(model, kv_dtype="int8") as eng8:
        quant = np.asarray(eng8.generate(p, max_new_tokens=4,
                                         timeout=300).numpy())
    assert quant.shape == native.shape
    np.testing.assert_array_equal(quant[:, :24], native[:, :24])


def test_kv_dtype_env_and_validation(model, monkeypatch):
    assert SlotPagedKVCache(2).kv_dtype == "native"       # auto -> native
    monkeypatch.setenv("PADDLE_KV_DTYPE", "int8")
    assert SlotPagedKVCache(2).kv_quant
    assert _engine(model)._new_cache().kv_quant           # engine env path
    monkeypatch.setenv("PADDLE_KV_DTYPE", "fp4")
    with pytest.raises(ValueError):
        SlotPagedKVCache(2)


# ---------------------------------------------------------------------------
# disagg export/import: quantized pages ride bit-exactly
# ---------------------------------------------------------------------------

def _filled_engine(model, prompt, **kw):
    eng = _engine(model, **kw)
    eng.start()
    eng.generate(prompt, max_new_tokens=1, timeout=600)
    return eng


def test_export_import_int8_bit_exact(model):
    prompt = np.random.RandomState(13).randint(0, 128, (1, 40)) \
        .astype(np.int64)
    chain = block_hash_chain(prompt[0], 16)
    src = _filled_engine(model, prompt, kv_dtype="int8")
    try:
        blob = src.run_on_loop(lambda e: e._cache.export_pages(chain))
        assert blob is not None
        assert blob["kv_dtype"] == "int8"
        assert blob["scales"] is not None
        assert blob["layers"][0][0].dtype == np.int8
        assert len(blob["scales"]) == len(blob["layers"]) == 2
    finally:
        src.stop()

    # cold import: ints + scales land through the pool-creation backlog
    dst = SlotPagedKVCache(2, page_size=16, max_len=96, kv_dtype="int8")
    assert dst.import_pages(blob) == 2
    cached, hits, _ = dst.assign(0, prompt[0])
    assert (cached, hits) == (32, 2)
    # drive one forward so the pools materialize, then compare bytes
    dst2 = _filled_engine(model, prompt, kv_dtype="int8")
    try:
        def grab(e):
            c = e._cache
            pages = [int(c._index[d]) for d in blob["digests"]]
            out = []
            for (kp, vp), (ks, vs) in zip(c._pools.values(),
                                          c._scales.values()):
                out.append((np.asarray(kp[:, pages]),
                            np.asarray(vp[:, pages]),
                            np.asarray(ks[:, pages]),
                            np.asarray(vs[:, pages])))
            return out
        got = dst2.run_on_loop(grab)
    finally:
        dst2.stop()
    for (kb, vb), (ksb, vsb), (kp, vp, ks, vs) in zip(
            blob["layers"], blob["scales"], got):
        np.testing.assert_array_equal(kp, kb)      # quantized ints...
        np.testing.assert_array_equal(vp, vb)
        np.testing.assert_array_equal(ks, ksb)     # ...and scales ride
        np.testing.assert_array_equal(vs, vsb)     # bit-exactly


def test_export_import_dtype_mismatch_rejected(model):
    prompt = np.random.RandomState(14).randint(0, 128, (1, 36)) \
        .astype(np.int64)
    chain = block_hash_chain(prompt[0], 16)
    src = _filled_engine(model, prompt, kv_dtype="int8")
    try:
        blob = src.run_on_loop(lambda e: e._cache.export_pages(chain))
    finally:
        src.stop()
    # int8 blob into a native pool: rejected, never wrong tokens
    with pytest.raises(ValueError):
        SlotPagedKVCache(2, page_size=16, max_len=96).import_pages(blob)
    # native blob into an int8 pool: same contract, other direction
    src2 = _filled_engine(model, prompt)
    try:
        blob_native = src2.run_on_loop(
            lambda e: e._cache.export_pages(chain))
    finally:
        src2.stop()
    with pytest.raises(ValueError):
        SlotPagedKVCache(2, page_size=16, max_len=96,
                         kv_dtype="int8").import_pages(blob_native)
    # geometry rejection still holds on quantized blobs
    with pytest.raises(ValueError):
        SlotPagedKVCache(2, page_size=8, max_len=96,
                         kv_dtype="int8").import_pages(blob)


def test_export_import_bf16_pool_dtype_guard():
    """A bf16-native pool exports its dtype in the blob; importing into
    a warm pool of a different native dtype is rejected (never silently
    re-cast), while the matching dtype round-trips bit-exactly."""
    class _Layer:
        pass

    from paddle_tpu.framework.core import Tensor

    def fill(dtype):
        cache = SlotPagedKVCache(2, page_size=4, max_len=32)
        layer = _Layer()
        rs = np.random.RandomState(15)
        k = Tensor(jnp.asarray(rs.randn(1, 8, 2, 16), dtype))
        v = Tensor(jnp.asarray(rs.randn(1, 8, 2, 16), dtype))
        q = Tensor(jnp.asarray(rs.randn(1, 8, 4, 16), dtype))
        cache.assign(0, np.arange(8))
        cache.begin_prefill(0, 8)
        cache.attend(layer, q, k, v)
        cache.advance(8)
        cache.commit_prefix(0)
        return cache, layer

    src, _ = fill(jnp.bfloat16)
    chain = block_hash_chain(np.arange(8), 4)
    blob = src.export_pages(chain)
    assert blob["native_dtype"] == "bfloat16"
    # warm f32 pool rejects the bf16 blob
    dst_f32, _ = fill(jnp.float32)
    with pytest.raises(ValueError):
        dst_f32.import_pages(blob)
    # warm bf16 pool accepts and stores byte-identical pages
    dst, layer = fill(jnp.bfloat16)
    for d in list(dst._index):               # clear so the import lands
        page = dst._index.pop(d)
        del dst._page_digest[page]
        dst._decref(page)
    assert dst.import_pages(blob) == 2
    page = dst._index[blob["digests"][0]]
    kp = next(iter(dst._pools.values()))[0]
    np.testing.assert_array_equal(
        np.asarray(kp[:, page]).astype(np.float32),
        blob["layers"][0][0][:, 0].astype(np.float32))


# ---------------------------------------------------------------------------
# COW copies scales; rollback on int8 pools
# ---------------------------------------------------------------------------

def test_cow_copies_scales(model):
    """Writing into a shared page of an int8 pool copies the scale rows
    with the values — a prefix-cache-shared run reads back EXACTLY the
    bytes a fresh unshared int8 run computes (quantization is
    deterministic, so any scale-aliasing bug breaks bit-equality).
    int8 vs NATIVE is a tolerance contract; int8 vs int8 is exact."""
    rng = np.random.RandomState(16)
    shared = rng.randint(0, 128, 32)
    a = np.concatenate([shared, rng.randint(0, 128, 4)]).astype(np.int64)
    b = np.concatenate([shared, rng.randint(0, 128, 4)]).astype(np.int64)
    with _engine(model, kv_dtype="int8",
                 enable_prefix_cache=False) as ref_eng:
        want_a = np.asarray(ref_eng.generate(a[None], max_new_tokens=4,
                                             timeout=300).numpy())
        want_b = np.asarray(ref_eng.generate(b[None], max_new_tokens=4,
                                             timeout=300).numpy())
    eng = _engine(model, kv_dtype="int8")
    with eng:
        got_a = np.asarray(eng.generate(a[None], max_new_tokens=4,
                                        timeout=300).numpy())
        got_b = np.asarray(eng.generate(b[None], max_new_tokens=4,
                                        timeout=300).numpy())
        cache = eng._cache
        assert cache.prefix_hits > 0         # b mapped the shared blocks
    np.testing.assert_array_equal(got_a, want_a)
    np.testing.assert_array_equal(got_b, want_b)


def test_int8_disagg_handoff_parity(model):
    """Quantized pages survive the fleet handoff: a disaggregated int8
    fleet (prefill replica exports ints+scales, decode replica imports
    them through the cold-pool backlog) produces output bit-identical
    to a colocated int8 engine."""
    from paddle_tpu.distributed.fleet.elastic.tcp_kv import MemKVStore
    from paddle_tpu.inference import ServingRouter

    rng = np.random.RandomState(17)
    prompts = [rng.randint(0, 128, (1, n)).astype(np.int64)
               for n in (36, 40)]
    want = []
    for p in prompts:
        with _engine(model, kv_dtype="int8",
                     enable_prefix_cache=False) as eng:
            want.append(np.asarray(eng.generate(
                p, max_new_tokens=4, timeout=600).numpy()))
    router = ServingRouter(
        model, num_replicas=2, disagg=True, store=MemKVStore(),
        heartbeat_ttl=600.0,
        engine_kwargs=dict(max_batch_size=2, max_len=96,
                           kv_dtype="int8"))
    with router:
        got = [np.asarray(router.generate(p, max_new_tokens=4,
                                          timeout=600).numpy())
               for p in prompts]
        pre, dec = router.replicas
        assert pre.engine._cache.pages_exported > 0
        assert dec.engine._cache.pages_imported > 0
        assert dec.engine._cache.kv_quant
    for g, w in zip(got, want):
        np.testing.assert_array_equal(g, w)
