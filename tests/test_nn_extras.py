"""Layer/functional breadth batch 2 — numeric parity against torch (CPU)
as the oracle where available (reference test pattern: per-op
``test_*_op.py`` with framework-reference comparison)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.nn as nn
import paddle_tpu.nn.functional as F

torch = pytest.importorskip("torch")
import torch.nn.functional as TF  # noqa: E402

RNG = np.random.RandomState(7)


def t(x):
    return paddle.to_tensor(np.asarray(x))


def _cmp(got, want, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(np.asarray(got.numpy()), want,
                               rtol=rtol, atol=atol)


def test_pool3d_parity():
    x = RNG.randn(2, 3, 8, 8, 8).astype(np.float32)
    _cmp(F.max_pool3d(t(x), 2),
         TF.max_pool3d(torch.tensor(x), 2).numpy())
    _cmp(F.avg_pool3d(t(x), 2, stride=2),
         TF.avg_pool3d(torch.tensor(x), 2, 2).numpy())
    _cmp(nn.MaxPool3D(2)(t(x)),
         TF.max_pool3d(torch.tensor(x), 2).numpy())


def test_max_unpool2d_roundtrip():
    x = RNG.randn(2, 3, 8, 8).astype(np.float32)
    tv, ti = torch.nn.functional.max_pool2d(torch.tensor(x), 2,
                                            return_indices=True)
    v, idx = F.max_pool2d_with_index(t(x), 2)
    np.testing.assert_allclose(np.asarray(v.numpy()), tv.numpy(),
                               rtol=1e-6)
    np.testing.assert_array_equal(np.asarray(idx.numpy()), ti.numpy())
    un_t = TF.max_unpool2d(tv, ti, 2).numpy()
    un = F.max_unpool2d(v, idx, 2)
    np.testing.assert_allclose(np.asarray(un.numpy()), un_t, rtol=1e-6)
    un_l = nn.MaxUnPool2D(2)(v, idx)
    np.testing.assert_allclose(np.asarray(un_l.numpy()), un_t, rtol=1e-6)


def test_conv_transpose_1d_3d_parity():
    x1 = RNG.randn(2, 4, 10).astype(np.float32)
    w1 = RNG.randn(4, 3, 3).astype(np.float32)   # [in, out, k]
    want = TF.conv_transpose1d(torch.tensor(x1), torch.tensor(w1),
                               stride=2, padding=1).numpy()
    _cmp(F.conv1d_transpose(t(x1), t(w1), stride=2, padding=1), want,
         rtol=1e-4)

    x3 = RNG.randn(1, 2, 5, 5, 5).astype(np.float32)
    w3 = RNG.randn(2, 3, 3, 3, 3).astype(np.float32)
    want3 = TF.conv_transpose3d(torch.tensor(x3), torch.tensor(w3),
                                stride=2).numpy()
    _cmp(F.conv3d_transpose(t(x3), t(w3), stride=2), want3, rtol=1e-4,
         atol=1e-4)


def test_pixel_unshuffle_fold_unflatten():
    x = RNG.randn(2, 3, 8, 8).astype(np.float32)
    _cmp(F.pixel_unshuffle(t(x), 2),
         TF.pixel_unshuffle(torch.tensor(x), 2).numpy())
    # fold(unfold(x)) == x * overlap_count
    cols = F.unfold(t(x), 3, strides=1, paddings=1)
    back = F.fold(cols, (8, 8), 3, strides=1, paddings=1)
    tcols = TF.unfold(torch.tensor(x), 3, padding=1)
    tback = TF.fold(tcols, (8, 8), 3, padding=1).numpy()
    _cmp(back, tback, rtol=1e-5)
    u = nn.Unflatten(1, [1, 3])(t(x))
    assert tuple(u.shape) == (2, 1, 3, 8, 8)


def test_affine_grid_grid_sample_parity():
    theta = RNG.randn(2, 2, 3).astype(np.float32) * 0.3
    theta[:, 0, 0] += 1
    theta[:, 1, 1] += 1
    x = RNG.randn(2, 3, 6, 6).astype(np.float32)
    for align in (True, False):
        grid_t = TF.affine_grid(torch.tensor(theta), (2, 3, 6, 6),
                                align_corners=align)
        grid = F.affine_grid(t(theta), (2, 3, 6, 6), align_corners=align)
        np.testing.assert_allclose(np.asarray(grid.numpy()),
                                   grid_t.numpy(), rtol=1e-4, atol=1e-5)
        want = TF.grid_sample(torch.tensor(x), grid_t,
                              align_corners=align).numpy()
        got = F.grid_sample(t(x), grid, align_corners=align)
        np.testing.assert_allclose(np.asarray(got.numpy()), want,
                                   rtol=1e-4, atol=1e-4)


def test_sequence_ops():
    lens = paddle.to_tensor(np.array([2, 4, 1], np.int64))
    m = F.sequence_mask(lens, maxlen=5)
    np.testing.assert_array_equal(
        np.asarray(m.numpy()),
        [[1, 1, 0, 0, 0], [1, 1, 1, 1, 0], [1, 0, 0, 0, 0]])

    x = RNG.randn(8, 8, 4, 4).astype(np.float32)   # nt=8, seg=4, c=8
    out = F.temporal_shift(t(x), 4, 0.25)           # fold = 2 channels
    assert tuple(out.shape) == (8, 8, 4, 4)
    v = x.reshape(2, 4, 8, 4, 4)
    np.testing.assert_allclose(np.asarray(out.numpy()).reshape(
        2, 4, 8, 4, 4)[:, :-1, 0], v[:, 1:, 0], rtol=1e-6)  # ch0 shifts left
    np.testing.assert_allclose(np.asarray(out.numpy()).reshape(
        2, 4, 8, 4, 4)[:, 1:, 2], v[:, :-1, 2], rtol=1e-6)  # ch2 shifts right
    np.testing.assert_allclose(np.asarray(out.numpy()).reshape(
        2, 4, 8, 4, 4)[:, :, 4:], v[:, :, 4:], rtol=1e-6)   # rest untouched

    ids = np.array([[[1, 2]], [[3, 4]], [[5, 6]]], np.int64)
    parents = np.array([[[0, 0]], [[1, 0]], [[0, 1]]], np.int64)
    out = F.gather_tree(paddle.to_tensor(ids), paddle.to_tensor(parents))
    assert tuple(out.shape) == (3, 1, 2)


def test_loss_tail_parity():
    x = RNG.randn(4, 5).astype(np.float32)
    y01 = (RNG.rand(4, 5) > 0.5).astype(np.float32)
    lab = RNG.randint(0, 5, (4,)).astype(np.int64)
    ypm = np.where(RNG.rand(4, 5) > 0.5, 1.0, -1.0).astype(np.float32)
    pos = np.abs(RNG.randn(4, 5)).astype(np.float32) + 0.5

    _cmp(F.soft_margin_loss(t(x), t(ypm)),
         TF.soft_margin_loss(torch.tensor(x), torch.tensor(ypm)).numpy())
    _cmp(F.multi_label_soft_margin_loss(t(x), t(y01)),
         TF.multilabel_soft_margin_loss(torch.tensor(x),
                                        torch.tensor(y01)).numpy())
    _cmp(F.multi_margin_loss(t(x), paddle.to_tensor(lab)),
         TF.multi_margin_loss(torch.tensor(x), torch.tensor(lab)).numpy())
    _cmp(F.poisson_nll_loss(t(x), t(pos)),
         TF.poisson_nll_loss(torch.tensor(x), torch.tensor(pos)).numpy())
    a, p, n = (RNG.randn(4, 8).astype(np.float32) for _ in range(3))
    _cmp(F.triplet_margin_with_distance_loss(t(a), t(p), t(n)),
         TF.triplet_margin_with_distance_loss(
             torch.tensor(a), torch.tensor(p), torch.tensor(n)).numpy(),
         rtol=1e-4)
    d = F.pairwise_distance(t(a), t(p))
    want = TF.pairwise_distance(torch.tensor(a), torch.tensor(p)).numpy()
    _cmp(d, want, rtol=1e-4)
    _cmp(nn.PairwiseDistance()(t(a), t(p)), want, rtol=1e-4)


def test_hsigmoid_loss():
    paddle.seed(3)
    feat, K = 6, 5
    layer = nn.HSigmoidLoss(feat, K)
    x = t(RNG.randn(4, feat).astype(np.float32))
    lab = paddle.to_tensor(RNG.randint(0, K, (4,)).astype(np.int64))
    out = layer(x, lab)
    assert tuple(out.shape) == (4, 1)
    arr = np.asarray(out.numpy())
    assert np.isfinite(arr).all() and (arr > 0).all()
    # differentiable down to the weight table
    out.sum().backward()
    g = layer.weight.grad
    assert g is not None and np.abs(np.asarray(g.numpy())).sum() > 0
    # custom path table: two classes, single root node decision
    w = t(np.array([[1.0, 0.0, 0, 0, 0, 0]], np.float32))
    pt = paddle.to_tensor(np.array([[0], [0]], np.int64))
    pc = paddle.to_tensor(np.array([[0], [1]], np.float32))
    xin = t(np.array([[2.0, 0, 0, 0, 0, 0], [2.0, 0, 0, 0, 0, 0]],
                     np.float32))
    labs = paddle.to_tensor(np.array([0, 1], np.int64))
    out = F.hsigmoid_loss(xin, labs, 2, w, path_table=pt, path_code=pc)
    # code 0 -> -log sigmoid(+2); code 1 -> -log sigmoid(-2)
    want = -np.log([1 / (1 + np.exp(-2.0)), 1 / (1 + np.exp(2.0))])
    np.testing.assert_allclose(np.asarray(out.numpy())[:, 0], want,
                               rtol=1e-5)


def test_activation_layers():
    x = RNG.randn(2, 3, 4, 4).astype(np.float32)
    _cmp(nn.SiLU()(t(x)), TF.silu(torch.tensor(x)).numpy())
    _cmp(nn.Softmax2D()(t(x)),
         TF.softmax(torch.tensor(x), dim=1).numpy())
    _cmp(F.logsigmoid(t(x)), TF.logsigmoid(torch.tensor(x)).numpy())


def test_adaptive_pools():
    x = RNG.randn(2, 3, 8, 8, 8).astype(np.float32)
    _cmp(nn.AdaptiveAvgPool3D(2)(t(x)),
         TF.adaptive_avg_pool3d(torch.tensor(x), 2).numpy())
    x1 = RNG.randn(2, 3, 12).astype(np.float32)
    _cmp(nn.AdaptiveMaxPool1D(4)(t(x1)),
         TF.adaptive_max_pool1d(torch.tensor(x1), 4).numpy())


def test_review_fixes_extras():
    x3 = RNG.randn(1, 2, 6, 6, 6).astype(np.float32)
    with pytest.raises(NotImplementedError):
        F.max_pool3d(t(x3), 2, ceil_mode=True, return_mask=True)
    with pytest.raises(NotImplementedError):
        F.max_pool3d(t(x3), 2, padding="SAME", return_mask=True)
    # divisor_override = window-sum semantics
    got = F.avg_pool3d(t(x3), 2, divisor_override=1)
    want = TF.avg_pool3d(torch.tensor(x3), 2, divisor_override=1).numpy()
    _cmp(got, want, rtol=1e-5)
    got2 = F.avg_pool2d(t(x3[:, :, 0]), 2, divisor_override=3)
    want2 = TF.avg_pool2d(torch.tensor(x3[:, :, 0]), 2,
                          divisor_override=3).numpy()
    _cmp(got2, want2, rtol=1e-5)
    # output_size resolves transposed-conv stride ambiguity
    x1 = RNG.randn(1, 2, 5).astype(np.float32)
    w1 = RNG.randn(2, 2, 3).astype(np.float32)
    for want_len in (9, 10):
        got = F.conv1d_transpose(t(x1), t(w1), stride=2, padding=1,
                                 output_size=[want_len])
        assert got.shape[-1] == want_len, (want_len, got.shape)
    with pytest.raises(ValueError):
        F.conv1d_transpose(t(x1), t(w1), stride=2, padding=1,
                           output_size=[20])
    # conv2d_transpose shares the core and honors output_size too
    x2 = RNG.randn(1, 2, 5, 5).astype(np.float32)
    w2 = RNG.randn(2, 2, 3, 3).astype(np.float32)
    got = F.conv2d_transpose(t(x2), t(w2), stride=2, padding=1,
                             output_size=[10, 9])
    assert tuple(got.shape)[-2:] == (10, 9)
    # grid_sample: unsupported modes raise instead of silently clamping
    g = np.zeros((1, 2, 2, 2), np.float32)
    with pytest.raises(NotImplementedError):
        F.grid_sample(t(x2), t(g), padding_mode="reflection")
    # adaptive max pool rejects non-divisible lengths
    with pytest.raises(ValueError):
        nn.AdaptiveMaxPool1D(4, return_mask=True)(
            t(RNG.randn(1, 2, 10).astype(np.float32)))


def test_ctc_loss_matches_torch():
    rng = np.random.RandomState(0)
    T_, B_, C_, L_ = 12, 3, 6, 4
    logits = rng.randn(T_, B_, C_).astype(np.float32)
    labels = rng.randint(1, C_, (B_, L_)).astype(np.int64)
    in_lens = np.array([12, 10, 8], np.int64)
    lab_lens = np.array([4, 3, 2], np.int64)
    want = torch.nn.functional.ctc_loss(
        torch.tensor(logits).log_softmax(-1), torch.tensor(labels),
        torch.tensor(in_lens), torch.tensor(lab_lens), blank=0,
        reduction="none").numpy()
    got = F.ctc_loss(t(logits), paddle.to_tensor(labels),
                     paddle.to_tensor(in_lens), paddle.to_tensor(lab_lens),
                     blank=0, reduction="none")
    _cmp(got, want, rtol=1e-4)
    # repeated labels exercise the skip-transition mask
    labels2 = np.array([[2, 2, 3, 3]] * B_, np.int64)
    want2 = torch.nn.functional.ctc_loss(
        torch.tensor(logits).log_softmax(-1), torch.tensor(labels2),
        torch.tensor(in_lens), torch.tensor(lab_lens), blank=0,
        reduction="none").numpy()
    got2 = F.ctc_loss(t(logits), paddle.to_tensor(labels2),
                      paddle.to_tensor(in_lens), paddle.to_tensor(lab_lens),
                      blank=0, reduction="none")
    _cmp(got2, want2, rtol=1e-4)
    # reduction='mean' divides each sample's loss by its label_length
    # before averaging (torch/paddle semantics)
    want_mean = torch.nn.functional.ctc_loss(
        torch.tensor(logits).log_softmax(-1), torch.tensor(labels),
        torch.tensor(in_lens), torch.tensor(lab_lens), blank=0,
        reduction="mean").numpy()
    got_mean = F.ctc_loss(t(logits), paddle.to_tensor(labels),
                          paddle.to_tensor(in_lens),
                          paddle.to_tensor(lab_lens),
                          blank=0, reduction="mean")
    _cmp(got_mean, want_mean, rtol=1e-4)
    # layer + norm_by_times + grad
    x = t(logits); x.stop_gradient = False
    loss = nn.CTCLoss()(x, paddle.to_tensor(labels),
                        paddle.to_tensor(in_lens),
                        paddle.to_tensor(lab_lens), norm_by_times=True)
    loss.backward()
    assert np.isfinite(np.asarray(x.grad.numpy())).all()


def test_second_review_fixes():
    # max_pool2d/1d return_mask now returns (values, indices)
    x = RNG.randn(2, 3, 8, 8).astype(np.float32)
    v, idx = F.max_pool2d(t(x), 2, return_mask=True)
    tv, ti = torch.nn.functional.max_pool2d(torch.tensor(x), 2,
                                            return_indices=True)
    np.testing.assert_array_equal(np.asarray(idx.numpy()), ti.numpy())
    x1 = RNG.randn(2, 3, 8).astype(np.float32)
    v1, i1 = F.max_pool1d(t(x1), 2, return_mask=True)
    assert tuple(v1.shape) == (2, 3, 4)
    # OOB unpool indices raise eagerly
    with pytest.raises(ValueError, match="out of range"):
        F.max_unpool2d(v, idx, 2, output_size=[4, 4])
    # non-channels-first layouts refuse instead of silently misreading
    with pytest.raises(NotImplementedError):
        F.pixel_unshuffle(t(x), 2, data_format="NHWC")
    with pytest.raises(NotImplementedError):
        F.temporal_shift(t(x), 2, data_format="NHWC")
    with pytest.raises(NotImplementedError):
        F.max_unpool2d(v, idx, 2, data_format="NHWC")
    # soft_margin_loss stable at confident wrong predictions
    big = F.soft_margin_loss(t(np.float32([[100.0]])),
                             t(np.float32([[-1.0]])))
    assert np.isfinite(big.numpy()).all() and abs(float(big.numpy()) - 100) < 1
    # adaptive_avg_pool3d non-divisible general path
    x5 = RNG.randn(1, 2, 5, 7, 5).astype(np.float32)
    got = F.adaptive_avg_pool3d(t(x5), 3)
    want = TF.adaptive_avg_pool3d(torch.tensor(x5), 3).numpy()
    _cmp(got, want, rtol=1e-5)
    got_l = nn.AdaptiveAvgPool3D(3)(t(x5))
    _cmp(got_l, want, rtol=1e-5)


def test_channel_shuffle_huber_gaussian_nll():
    """Round-4 API-parity additions: nn.ChannelShuffle / HuberLoss /
    GaussianNLLLoss (+ functionals)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn

    x = paddle.to_tensor(
        np.arange(1 * 4 * 2 * 2, dtype="float32").reshape(1, 4, 2, 2))
    y = nn.ChannelShuffle(2)(x)
    yf = F.channel_shuffle(x, 2)
    np.testing.assert_array_equal(y.numpy(), yf.numpy())
    # NCHW groups=2: channels [0,1,2,3] -> [0,2,1,3]
    np.testing.assert_allclose(np.asarray(y._data)[0, :, 0, 0],
                               np.asarray(x._data)[0, [0, 2, 1, 3], 0, 0])

    a = paddle.to_tensor(np.array([0.0, 3.0], dtype="float32"))
    b = paddle.to_tensor(np.array([0.5, 0.0], dtype="float32"))
    h = nn.HuberLoss(reduction="none", delta=1.0)(a, b)
    np.testing.assert_allclose(np.asarray(h._data), [0.125, 2.5], atol=1e-6)
    hf = F.huber_loss(a, b, delta=1.0, reduction="none")
    np.testing.assert_allclose(hf.numpy(), h.numpy())

    var = paddle.to_tensor(np.array([1.0, 4.0], dtype="float32"))
    g = nn.GaussianNLLLoss(reduction="none")(a, b, var)
    gf = F.gaussian_nll_loss(a, b, var, reduction="none")
    np.testing.assert_allclose(gf.numpy(), g.numpy())
    expect = 0.5 * (np.log([1.0, 4.0]) + np.array([0.25, 9.0]) / [1.0, 4.0])
    np.testing.assert_allclose(np.asarray(g._data), expect, atol=1e-6)

    # grads flow
    a.stop_gradient = False
    loss = nn.HuberLoss()(a, b)
    loss.backward()
    assert a.grad is not None


def test_round4_functional_additions():
    """npair/dice/margin-CE losses, zeropad2d, feature_alpha_dropout,
    class_center_sample, sparse_attention F-alias + new Tensor methods."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F

    paddle.seed(5)
    # margin_cross_entropy degenerates to scaled CE at zero margins
    cos = paddle.to_tensor((np.random.rand(4, 10) * 2 - 1).astype("float32"))
    lb = paddle.to_tensor(np.array([1, 2, 3, 4]))
    l0 = F.margin_cross_entropy(cos, lb, margin1=1.0, margin2=0.0,
                                margin3=0.0, scale=1.0)
    ref = F.cross_entropy(cos, lb)
    np.testing.assert_allclose(l0.numpy(), ref.numpy(), rtol=1e-5)
    # margins make the target harder -> loss goes up
    l1 = F.margin_cross_entropy(cos, lb, margin2=0.5, scale=1.0)
    assert float(l1.numpy()) > float(l0.numpy())

    probs = paddle.to_tensor(np.eye(4, 3, dtype="float32")[None])
    lab = paddle.to_tensor(np.array([[0, 1, 2, 0]])[..., None])
    d = F.dice_loss(probs, lab, epsilon=0.0)
    assert 0.0 < float(d.numpy()) < 1.0

    a = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    a.stop_gradient = False
    p = paddle.to_tensor(np.random.randn(4, 8).astype("float32"))
    y = paddle.to_tensor(np.array([0, 1, 0, 2]))
    loss = F.npair_loss(a, p, y)
    loss.backward()
    assert a.grad is not None

    x = paddle.to_tensor(np.ones((1, 2, 3, 3), "float32"))
    assert F.zeropad2d(x, [1, 2, 3, 4]).shape == [1, 2, 10, 6]

    rl, sc = F.class_center_sample(y, num_classes=10, num_samples=6)
    assert sc.shape[0] == 6
    assert sorted(set(rl.numpy().tolist())) == [0, 1, 2]

    # sparse_attention == dense softmax attention under an all-ones mask
    import paddle_tpu.sparse as sparse
    q = paddle.to_tensor(np.random.randn(1, 1, 4, 8).astype("float32"))
    mask = sparse.sparse_coo_tensor(
        np.array([[i for i in range(4) for _ in range(4)],
                  [j for _ in range(4) for j in range(4)]]),
        np.ones(16, "float32"), shape=[4, 4])
    out = F.sparse_attention(q, q, q, sparse_mask=mask)
    ref = F.scaled_dot_product_attention(
        paddle.to_tensor(np.swapaxes(q.numpy(), 1, 2)),
        paddle.to_tensor(np.swapaxes(q.numpy(), 1, 2)),
        paddle.to_tensor(np.swapaxes(q.numpy(), 1, 2)), is_causal=False)
    np.testing.assert_allclose(out.numpy(),
                               np.swapaxes(ref.numpy(), 1, 2), atol=2e-5)

    # multi-head CSR pattern (b=1, h=2): head 0 causal, head 1 full —
    # causal head must equal causal SDPA, full head the full SDPA
    qm = paddle.to_tensor(np.random.randn(1, 2, 4, 8).astype("float32"))
    offs = np.zeros((1, 2, 5), "int32")
    cols_list = [[], []]
    for row in range(4):
        causal_cols = list(range(row + 1))
        offs[0, 0, row + 1] = offs[0, 0, row] + len(causal_cols)
        cols_list[0] += causal_cols
        offs[0, 1, row + 1] = offs[0, 1, row] + 4
        cols_list[1] += list(range(4))
    pad = max(len(c) for c in cols_list)
    cols = np.zeros((1, 2, pad), "int32")
    for h_, c in enumerate(cols_list):
        cols[0, h_, :len(c)] = c
    outm = F.sparse_attention(qm, qm, qm,
                              sparse_csr_offset=paddle.to_tensor(offs),
                              sparse_csr_columns=paddle.to_tensor(cols))
    qs = paddle.to_tensor(np.swapaxes(qm.numpy(), 1, 2))
    ref_c = np.swapaxes(F.scaled_dot_product_attention(
        qs, qs, qs, is_causal=True).numpy(), 1, 2)
    ref_f = np.swapaxes(F.scaled_dot_product_attention(
        qs, qs, qs, is_causal=False).numpy(), 1, 2)
    np.testing.assert_allclose(outm.numpy()[:, 0], ref_c[:, 0], atol=2e-5)
    np.testing.assert_allclose(outm.numpy()[:, 1], ref_f[:, 1], atol=2e-5)

    # key_padding_mask: disallowing the last key == attending over :3
    kp = np.array([[1, 1, 1, 0]], "float32")
    outp = F.sparse_attention(qm, qm, qm,
                              sparse_csr_offset=paddle.to_tensor(offs),
                              sparse_csr_columns=paddle.to_tensor(cols),
                              key_padding_mask=paddle.to_tensor(kp))
    q3 = paddle.to_tensor(np.swapaxes(qm.numpy()[:, :, :3], 1, 2))
    ref3 = np.swapaxes(F.scaled_dot_product_attention(
        paddle.to_tensor(np.swapaxes(qm.numpy(), 1, 2)), q3, q3,
        is_causal=False).numpy(), 1, 2)
    np.testing.assert_allclose(outp.numpy()[:, 1], ref3[:, 1], atol=2e-5)

    # Tensor methods
    t = paddle.to_tensor(np.arange(6, dtype="float32").reshape(2, 3))
    assert t.element_size() == 4 and t.nbytes == 24
    assert t.is_sparse() is False and t.coalesce() is t
    assert isinstance(t.data_ptr(), int)
    t2 = t.clone().apply_(lambda v: v * 2)
    np.testing.assert_allclose(t2.numpy(), t.numpy() * 2)
    t3 = t.apply(lambda v: v + 1)
    np.testing.assert_allclose(t3.numpy(), t.numpy() + 1)
    np.testing.assert_allclose(t.numpy(),
                               np.arange(6, dtype="float32").reshape(2, 3))
    e = paddle.to_tensor(np.zeros(2000, "float32")).exponential_(lam=2.0)
    assert abs(float(e.numpy().mean()) - 0.5) < 0.1
    f = paddle.to_tensor(np.array([7.0, 9.0])).floor_divide_(2.0)
    np.testing.assert_allclose(f.numpy(), [3.0, 4.0])
    assert paddle.to_tensor(np.ones(2, "float32")).cuda().shape == [2]


def test_adaptive_log_softmax_with_loss():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn

    paddle.seed(7)
    np.random.seed(7)
    m = nn.AdaptiveLogSoftmaxWithLoss(in_features=16, n_classes=20,
                                      cutoffs=[4, 10], div_value=2.0,
                                      head_bias=True)
    x = paddle.to_tensor(np.random.randn(8, 16).astype("float32"))
    y = paddle.to_tensor(np.array([0, 3, 4, 9, 10, 19, 2, 12]))
    out, loss = m(x, y)
    from paddle_tpu.nn.functional import (adaptive_log_softmax_with_loss,
                                          adaptive_log_softmax_log_prob)
    out2, loss2 = adaptive_log_softmax_with_loss(
        x, y, m.head_weight, m.tail_weights, m.cutoffs,
        head_bias=m.head_bias)
    np.testing.assert_allclose(out.numpy(), out2.numpy(), atol=1e-6)
    lp_direct = adaptive_log_softmax_log_prob(
        x, m.head_weight, m.tail_weights, m.cutoffs, head_bias=m.head_bias)
    assert out.shape == [8]
    np.testing.assert_allclose(float(loss.numpy()),
                               -float(out.numpy().mean()), rtol=1e-6)

    # the full log-distribution must normalize and agree with forward
    lp = m.log_prob(x)
    assert lp.shape == [8, 20]
    np.testing.assert_allclose(np.exp(lp.numpy()).sum(-1), np.ones(8),
                               atol=1e-5)
    np.testing.assert_allclose(
        out.numpy(), np.take_along_axis(lp.numpy(),
                                        y.numpy()[:, None], 1)[:, 0],
        atol=1e-5)
    pred = m.predict(x)
    np.testing.assert_array_equal(pred.numpy(), lp.numpy().argmax(-1))

    # trains: grads reach head and tails
    x.stop_gradient = False
    _, loss2 = m(x, y)
    loss2.backward()
    assert m.head_weight.grad is not None
    assert m.tail_weights[0][0].grad is not None


def test_adaptive_log_softmax_validation_and_determinism():
    import numpy as np
    import pytest as pt
    import paddle_tpu as paddle
    from paddle_tpu import nn

    with pt.raises(ValueError, match="cutoffs"):
        nn.AdaptiveLogSoftmaxWithLoss(8, 10, cutoffs=[0, 5])
    with pt.raises(ValueError, match="cutoffs"):
        nn.AdaptiveLogSoftmaxWithLoss(8, 10, cutoffs=[-2, 5])

    # seeded init: same paddle.seed -> identical weights
    paddle.seed(12)
    m1 = nn.AdaptiveLogSoftmaxWithLoss(8, 10, cutoffs=[4])
    paddle.seed(12)
    m2 = nn.AdaptiveLogSoftmaxWithLoss(8, 10, cutoffs=[4])
    np.testing.assert_array_equal(m1.head_weight.numpy(),
                                  m2.head_weight.numpy())

    # out-of-range labels raise eagerly
    x = paddle.to_tensor(np.random.randn(2, 8).astype("float32"))
    with pt.raises(ValueError, match="label values"):
        m1(x, paddle.to_tensor(np.array([0, 10])))
