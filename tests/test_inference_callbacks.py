"""Inference predictor + hapi callbacks tests (SURVEY.md §2.1 inference,
§2.2 hapi)."""
import os

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.callbacks import (
    Callback, EarlyStopping, ModelCheckpoint, LRScheduler, LogWriterCallback,
)


def _export_model(tmp_path):
    paddle.seed(0)
    net = paddle.nn.Sequential(paddle.nn.Linear(4, 8), paddle.nn.ReLU(),
                               paddle.nn.Linear(8, 2))
    prefix = str(tmp_path / "m" / "model")
    paddle.jit.save(net, prefix,
                    input_spec=[paddle.jit.InputSpec([1, 4], "float32")])
    return net, prefix


def test_predictor_matches_eager(tmp_path):
    net, prefix = _export_model(tmp_path)
    x = np.random.default_rng(0).normal(size=(1, 4)).astype(np.float32)
    net.eval()
    ref = net(paddle.to_tensor(x)).numpy()

    config = Config(prefix + ".pdmodel")
    pred = create_predictor(config)
    names = pred.get_input_names()
    h = pred.get_input_handle(names[0])
    h.copy_from_cpu(x)
    pred.run()
    out = pred.get_output_handle(pred.get_output_names()[0]).copy_to_cpu()
    np.testing.assert_allclose(out, ref, rtol=1e-5, atol=1e-5)


def test_predictor_model_dir_and_list_form(tmp_path):
    net, prefix = _export_model(tmp_path)
    x = np.zeros((1, 4), np.float32)
    pred = create_predictor(Config(os.path.dirname(prefix)))
    outs = pred.run([x])
    assert outs[0].shape == (1, 2)


class _Probe(Callback):
    def __init__(self):
        super().__init__()
        self.events = []

    def on_train_begin(self, logs=None):
        self.events.append("train_begin")

    def on_epoch_begin(self, epoch, logs=None):
        self.events.append(f"epoch_begin_{epoch}")

    def on_train_batch_end(self, step, logs=None):
        self.events.append("batch")

    def on_epoch_end(self, epoch, logs=None):
        self.events.append(f"epoch_end_{epoch}")

    def on_train_end(self, logs=None):
        self.events.append("train_end")


def _fit(callbacks, tmp_path, epochs=3, with_eval=False):
    from paddle_tpu.io import TensorDataset
    paddle.seed(1)
    x = paddle.randn([16, 4])
    y = paddle.randn([16, 1])
    ds = TensorDataset([x, y])
    model = paddle.Model(paddle.nn.Linear(4, 1))
    model.prepare(optimizer=paddle.optimizer.SGD(
        learning_rate=0.01, parameters=model.parameters()),
        loss=paddle.nn.MSELoss())
    model.fit(ds, eval_data=ds if with_eval else None, batch_size=8,
              epochs=epochs, verbose=0, callbacks=callbacks,
              save_dir=str(tmp_path / "save") if with_eval else None)
    return model


def test_callback_hooks_fire(tmp_path):
    probe = _Probe()
    _fit([probe], tmp_path, epochs=2)
    assert probe.events[0] == "train_begin"
    assert probe.events[-1] == "train_end"
    assert "epoch_begin_0" in probe.events and "epoch_end_1" in probe.events
    assert probe.events.count("batch") == 4      # 2 epochs × 2 steps


def test_early_stopping_stops(tmp_path):
    # mode='max' on a decreasing loss: every eval is "worse" -> stops after
    # patience epochs
    es = EarlyStopping(monitor="loss", mode="max", patience=1,
                       save_best_model=False)
    probe = _Probe()
    _fit([es, probe], tmp_path, epochs=10, with_eval=True)
    n_epochs = len([e for e in probe.events if e.startswith("epoch_end")])
    assert n_epochs < 10                         # stopped early


def test_model_checkpoint_and_logwriter(tmp_path):
    mc = ModelCheckpoint(save_freq=1, save_dir=str(tmp_path / "ck"))
    lw = LogWriterCallback(log_dir=str(tmp_path / "vdl"))
    _fit([mc, lw], tmp_path, epochs=1)
    assert os.path.exists(str(tmp_path / "ck" / "epoch_0.pdparams"))
    assert os.path.exists(str(tmp_path / "ck" / "final.pdparams"))
    lines = open(str(tmp_path / "vdl" / "metrics.jsonl")).read().splitlines()
    assert len(lines) == 2
    assert "loss" in lines[0]


def test_lr_scheduler_callback(tmp_path):
    from paddle_tpu.io import TensorDataset
    paddle.seed(2)
    sched = paddle.optimizer.lr.StepDecay(learning_rate=0.1, step_size=1,
                                          gamma=0.5)
    model = paddle.Model(paddle.nn.Linear(4, 1))
    model.prepare(optimizer=paddle.optimizer.SGD(
        learning_rate=sched, parameters=model.parameters()),
        loss=paddle.nn.MSELoss())
    ds = TensorDataset([paddle.randn([8, 4]), paddle.randn([8, 1])])
    model.fit(ds, batch_size=4, epochs=1, verbose=0,
              callbacks=[LRScheduler(by_step=True)])
    assert sched.last_lr < 0.1


def test_jit_save_function_export(tmp_path):
    import paddle_tpu as paddle

    def double_plus(x):
        return x * 2 + 1

    prefix = str(tmp_path / "fn" / "model")
    paddle.jit.save(double_plus, prefix,
                    input_spec=[paddle.jit.InputSpec([2, 3], "float32")])
    loaded = paddle.jit.load(prefix)
    x = np.ones((2, 3), np.float32)
    out = loaded(paddle.to_tensor(x))
    np.testing.assert_allclose(out.numpy(), x * 2 + 1)


def test_mfu_monitor():
    from paddle_tpu.profiler.mfu import (
        MFUMonitor, llama_train_flops, llama_param_count)
    from paddle_tpu.models import llama_tiny
    cfg = llama_tiny()
    n = llama_param_count(cfg)
    assert n > 0
    fl = llama_train_flops(cfg, batch=2, seq_len=32)
    assert fl > 6 * n * 64                      # at least the 6N·tokens term
    mon = MFUMonitor(step_flops=fl, chip="cpu")
    mon.step(tokens=64)
    assert mon.mfu() >= 0 and "MFU" in mon.summary()


def test_config_knobs_are_real(tmp_path):
    """switch_ir_debug dumps the program text; enable_profile collects
    per-run latencies; named IO handles come from the saved InputSpecs
    (the padded-knob cleanup, VERDICT round-2 copy-paste findings)."""
    import os
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn
    from paddle_tpu import inference as paddle_infer

    paddle.seed(0)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    model.eval()
    prefix = str(tmp_path / "m")
    paddle.jit.save(model, prefix,
                    input_spec=[paddle.static.InputSpec([2, 4], "float32",
                                                        "image")])
    cfg = paddle_infer.Config(prefix)
    cfg.switch_ir_debug(True)
    cfg.enable_profile()
    pred = paddle_infer.create_predictor(cfg)
    assert pred.get_input_names() == ["image"]     # spec name survives
    assert os.path.exists(prefix + ".hlo.txt")     # IR dump written
    txt = open(prefix + ".hlo.txt").read()
    assert "module" in txt or "func" in txt
    x = np.ones((2, 4), np.float32)
    for _ in range(3):
        (out,) = pred.run([x])
    assert out.shape == (2, 2)
    prof = pred.get_profile()
    assert prof["runs"] == 3 and prof["total_s"] > 0
    assert prof["p99_s"] >= prof["p50_s"] > 0
