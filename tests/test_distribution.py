"""paddle.distribution tests — log_prob/entropy vs scipy closed forms,
sample-moment checks, KL closed forms vs Monte Carlo, transform
invertibility, and tape-differentiability of log_prob (reference test
pattern: ``test/distribution/test_distribution_*.py``)."""
import numpy as np
import pytest
import scipy.stats as st

import paddle_tpu as paddle
from paddle_tpu.distribution import (
    Bernoulli, Beta, Binomial, Categorical, Cauchy, Dirichlet, Exponential,
    Gamma, Geometric, Gumbel, Independent, Laplace, LogNormal, Multinomial,
    MultivariateNormal, Normal, Poisson, StudentT, TransformedDistribution,
    Uniform,
    AffineTransform, ChainTransform, ExpTransform, SigmoidTransform,
    StickBreakingTransform, TanhTransform,
    kl_divergence, register_kl,
)

RNG = np.random.RandomState(0)


def t(x):
    return paddle.to_tensor(np.asarray(x, np.float32))


def _chk(got, want, rtol=1e-4, atol=1e-5):
    np.testing.assert_allclose(np.asarray(got.numpy()), want,
                               rtol=rtol, atol=atol)


# ---------------------------------------------------------------- log_prob vs scipy

def test_normal_log_prob_entropy():
    d = Normal(t([0.0, 1.0]), t([1.0, 2.0]))
    v = np.array([0.5, -1.0], np.float32)
    _chk(d.log_prob(t(v)), st.norm(
        [0.0, 1.0], [1.0, 2.0]).logpdf(v))
    _chk(d.entropy(), st.norm([0.0, 1.0], [1.0, 2.0]).entropy())
    assert d.batch_shape == (2,)


def test_uniform_log_prob():
    d = Uniform(t(-1.0), t(3.0))
    _chk(d.log_prob(t([0.0])), st.uniform(-1, 4).logpdf([0.0]))
    assert np.isneginf(d.log_prob(t([5.0])).numpy()[0])
    _chk(d.entropy(), st.uniform(-1, 4).entropy())


def test_lognormal_gamma_beta_exponential_logpdf():
    v = np.array([0.3, 1.7], np.float32)
    _chk(LogNormal(t(0.2), t(0.8)).log_prob(t(v)),
         st.lognorm(0.8, scale=np.exp(0.2)).logpdf(v), rtol=1e-4)
    _chk(Gamma(t(2.0), t(3.0)).log_prob(t(v)),
         st.gamma(2.0, scale=1 / 3.0).logpdf(v), rtol=1e-4)
    b = np.array([0.3, 0.7], np.float32)
    _chk(Beta(t(2.0), t(5.0)).log_prob(t(b)),
         st.beta(2.0, 5.0).logpdf(b), rtol=1e-4)
    _chk(Exponential(t(1.5)).log_prob(t(v)),
         st.expon(scale=1 / 1.5).logpdf(v), rtol=1e-4)
    _chk(Laplace(t(0.5), t(1.2)).log_prob(t(v)),
         st.laplace(0.5, 1.2).logpdf(v), rtol=1e-4)
    _chk(Cauchy(t(0.0), t(2.0)).log_prob(t(v)),
         st.cauchy(0.0, 2.0).logpdf(v), rtol=1e-4)
    _chk(Gumbel(t(0.0), t(1.5)).log_prob(t(v)),
         st.gumbel_r(0.0, 1.5).logpdf(v), rtol=1e-4)
    _chk(StudentT(t(4.0), t(0.5), t(2.0)).log_prob(t(v)),
         st.t(4.0, 0.5, 2.0).logpdf(v), rtol=1e-4)


def test_discrete_log_prob():
    k = np.array([0.0, 2.0, 5.0], np.float32)
    _chk(Poisson(t(2.5)).log_prob(t(k)), st.poisson(2.5).logpmf(k),
         rtol=1e-4)
    _chk(Geometric(t(0.3)).log_prob(t(k)),
         st.geom(0.3, loc=-1).logpmf(k), rtol=1e-4)
    _chk(Binomial(10, t(0.4)).log_prob(t(k)),
         st.binom(10, 0.4).logpmf(k), rtol=1e-4)
    _chk(Bernoulli(t(0.3)).log_prob(t([1.0])), np.log([0.3]), rtol=1e-4)


def test_categorical_and_multinomial():
    logits = np.array([[0.5, 1.0, -0.5], [0.1, 0.1, 0.1]], np.float32)
    d = Categorical(t(logits))
    v = np.array([2, 0])
    want = np.log(np.exp(logits) / np.exp(logits).sum(-1, keepdims=True))
    _chk(d.log_prob(paddle.to_tensor(v)), want[np.arange(2), v], rtol=1e-4)
    ent = -(np.exp(want) * want).sum(-1)
    _chk(d.entropy(), ent, rtol=1e-4)
    s = d.sample((7,))
    assert tuple(s.shape) == (7, 2)

    m = Multinomial(8, t([0.2, 0.3, 0.5]))
    val = np.array([2.0, 2.0, 4.0], np.float32)
    _chk(m.log_prob(t(val)),
         st.multinomial(8, [0.2, 0.3, 0.5]).logpmf(val), rtol=1e-4)
    ms = m.sample((3,))
    assert tuple(ms.shape) == (3, 3)
    np.testing.assert_allclose(ms.numpy().sum(-1), 8.0)


def test_dirichlet_mvn():
    c = np.array([2.0, 3.0, 5.0], np.float32)
    d = Dirichlet(t(c))
    v = np.array([0.2, 0.3, 0.5], np.float32)
    _chk(d.log_prob(t(v)), st.dirichlet(c).logpdf(v), rtol=1e-4)
    _chk(d.entropy(), st.dirichlet(c).entropy(), rtol=1e-4)

    mean = np.array([1.0, -1.0], np.float32)
    cov = np.array([[2.0, 0.5], [0.5, 1.0]], np.float32)
    mv = MultivariateNormal(t(mean), covariance_matrix=t(cov))
    x = np.array([0.3, 0.7], np.float32)
    _chk(mv.log_prob(t(x)), st.multivariate_normal(mean, cov).logpdf(x),
         rtol=1e-4)
    _chk(mv.entropy(), st.multivariate_normal(mean, cov).entropy(),
         rtol=1e-4)
    s = mv.rsample((5,))
    assert tuple(s.shape) == (5, 2)


# ---------------------------------------------------------------- sampling moments

@pytest.mark.parametrize("dist,mean,var", [
    (lambda: Normal(t(1.0), t(2.0)), 1.0, 4.0),
    (lambda: Uniform(t(0.0), t(2.0)), 1.0, 1 / 3.0),
    (lambda: Exponential(t(2.0)), 0.5, 0.25),
    (lambda: Gamma(t(3.0), t(2.0)), 1.5, 0.75),
    (lambda: Laplace(t(0.0), t(1.0)), 0.0, 2.0),
    (lambda: Gumbel(t(0.0), t(1.0)), np.euler_gamma, np.pi ** 2 / 6),
    (lambda: Poisson(t(4.0)), 4.0, 4.0),
    (lambda: Geometric(t(0.4)), 1.5, 3.75),
    (lambda: Bernoulli(t(0.3)), 0.3, 0.21),
    (lambda: Binomial(10, t(0.5)), 5.0, 2.5),
], ids=["normal", "uniform", "expon", "gamma", "laplace", "gumbel",
        "poisson", "geom", "bern", "binom"])
def test_sample_moments(dist, mean, var):
    paddle.seed(1234)
    d = dist()
    s = d.sample((20000,)).numpy()
    assert abs(s.mean() - mean) < 4.5 * np.sqrt(var / 20000) + 0.01
    assert abs(s.var() - var) < 0.15 * max(var, 0.1) + 0.02
    # declared moments match closed form
    if not isinstance(d, (Cauchy,)):
        np.testing.assert_allclose(float(d.mean.numpy()), mean, rtol=1e-5,
                                   atol=1e-6)
        np.testing.assert_allclose(float(d.variance.numpy()), var,
                                   rtol=1e-5, atol=1e-6)


def test_seeded_sampling_deterministic():
    paddle.seed(7)
    a = Normal(t(0.0), t(1.0)).sample((5,)).numpy()
    paddle.seed(7)
    b = Normal(t(0.0), t(1.0)).sample((5,)).numpy()
    np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------- KL

def _mc_kl(p, q, n=200000):
    paddle.seed(99)
    x = p.sample((n,))
    return float((p.log_prob(x).numpy() - q.log_prob(x).numpy()).mean())


@pytest.mark.parametrize("mk", [
    lambda: (Normal(t(0.0), t(1.0)), Normal(t(1.0), t(2.0))),
    lambda: (Gamma(t(2.0), t(1.5)), Gamma(t(3.0), t(1.0))),
    lambda: (Beta(t(2.0), t(3.0)), Beta(t(4.0), t(2.0))),
    lambda: (Exponential(t(2.0)), Exponential(t(0.5))),
    lambda: (Laplace(t(0.0), t(1.0)), Laplace(t(1.0), t(2.0))),
    lambda: (Poisson(t(3.0)), Poisson(t(5.0))),
    lambda: (Geometric(t(0.3)), Geometric(t(0.6))),
    lambda: (Bernoulli(t(0.3)), Bernoulli(t(0.7))),
], ids=["normal", "gamma", "beta", "expon", "laplace", "poisson", "geom",
        "bern"])
def test_kl_closed_form_vs_monte_carlo(mk):
    p, q = mk()
    kl = float(kl_divergence(p, q).numpy())
    mc = _mc_kl(p, q)
    assert abs(kl - mc) < max(0.05 * abs(kl), 0.02), (kl, mc)


def test_kl_categorical_dirichlet_mvn_uniform():
    p = Categorical(t([[1.0, 0.0, -1.0]]))
    q = Categorical(t([[0.0, 0.0, 0.0]]))
    kl = kl_divergence(p, q).numpy()
    pp = np.exp([1.0, 0.0, -1.0]) / np.exp([1.0, 0.0, -1.0]).sum()
    want = (pp * (np.log(pp) - np.log(1 / 3))).sum()
    np.testing.assert_allclose(kl[0], want, rtol=1e-4)

    pd = Dirichlet(t([2.0, 3.0]))
    qd = Dirichlet(t([1.0, 1.0]))
    assert float(kl_divergence(pd, qd).numpy()) > 0

    m1 = MultivariateNormal(t([0.0, 0.0]),
                            covariance_matrix=t([[1.0, 0.0], [0.0, 1.0]]))
    m2 = MultivariateNormal(t([1.0, 0.0]),
                            covariance_matrix=t([[2.0, 0.3], [0.3, 1.5]]))
    klm = float(kl_divergence(m1, m2).numpy())
    # closed form vs scipy-computed reference
    cov2 = np.array([[2.0, 0.3], [0.3, 1.5]])
    inv2 = np.linalg.inv(cov2)
    want = 0.5 * (np.log(np.linalg.det(cov2)) - 2
                  + np.trace(inv2) + np.array([1.0, 0]) @ inv2
                  @ np.array([1.0, 0]))
    np.testing.assert_allclose(klm, want, rtol=1e-4)

    u1 = Uniform(t(0.0), t(1.0))
    u2 = Uniform(t(-1.0), t(2.0))
    np.testing.assert_allclose(float(kl_divergence(u1, u2).numpy()),
                               np.log(3.0), rtol=1e-5)
    assert np.isinf(float(kl_divergence(u2, u1).numpy()))


def test_register_kl_custom():
    class MyDist(Normal):
        pass

    @register_kl(MyDist, MyDist)
    def _kl(p, q):
        return t(42.0)

    assert float(kl_divergence(MyDist(t(0.0), t(1.0)),
                               MyDist(t(0.0), t(1.0))).numpy()) == 42.0
    with pytest.raises(NotImplementedError):
        kl_divergence(Cauchy(t(0.0), t(1.0)), Normal(t(0.0), t(1.0)))


# ---------------------------------------------------------------- transforms

def test_transform_roundtrip_and_logdet():
    x = np.linspace(-1.5, 1.5, 7).astype(np.float32)
    for tr, dom in [(AffineTransform(t(1.0), t(2.0)), x),
                    (ExpTransform(), x),
                    (SigmoidTransform(), x),
                    (TanhTransform(), x * 0.6)]:
        y = tr.forward(t(dom))
        back = tr.inverse(y).numpy()
        np.testing.assert_allclose(back, dom, rtol=1e-4, atol=1e-5)
        # log|det| vs numeric derivative
        eps = 1e-3
        num = (tr.forward(t(dom + eps)).numpy()
               - tr.forward(t(dom - eps)).numpy()) / (2 * eps)
        np.testing.assert_allclose(tr.forward_log_det_jacobian(t(dom)).numpy(),
                                   np.log(np.abs(num)), rtol=5e-3, atol=5e-3)


def test_chain_transform():
    ch = ChainTransform([AffineTransform(t(0.5), t(2.0)), ExpTransform()])
    x = np.array([0.0, 1.0], np.float32)
    y = ch.forward(t(x)).numpy()
    np.testing.assert_allclose(y, np.exp(0.5 + 2 * x), rtol=1e-5)
    np.testing.assert_allclose(ch.inverse(t(y)).numpy(), x, rtol=1e-4,
                               atol=1e-5)


def test_stick_breaking_simplex():
    sb = StickBreakingTransform()
    x = np.array([0.3, -0.2, 0.8], np.float32)
    y = sb.forward(t(x)).numpy()
    assert y.shape == (4,)
    np.testing.assert_allclose(y.sum(), 1.0, rtol=1e-5)
    np.testing.assert_allclose(sb.inverse(t(y)).numpy(), x, rtol=1e-3,
                               atol=1e-4)


def test_transformed_distribution_lognormal():
    base = Normal(t(0.2), t(0.7))
    d = TransformedDistribution(base, [ExpTransform()])
    ref = LogNormal(t(0.2), t(0.7))
    v = np.array([0.5, 2.0], np.float32)
    np.testing.assert_allclose(d.log_prob(t(v)).numpy(),
                               ref.log_prob(t(v)).numpy(), rtol=1e-4)
    paddle.seed(3)
    s = d.sample((4,))
    assert tuple(s.shape) == (4,) and (s.numpy() > 0).all()


def test_independent_sums_event_dims():
    base = Normal(t(np.zeros((3, 2), np.float32)),
                  t(np.ones((3, 2), np.float32)))
    ind = Independent(base, 1)
    assert ind.batch_shape == (3,) and ind.event_shape == (2,)
    v = np.zeros((3, 2), np.float32)
    np.testing.assert_allclose(ind.log_prob(t(v)).numpy(),
                               base.log_prob(t(v)).numpy().sum(-1),
                               rtol=1e-5)


# ---------------------------------------------------------------- autograd

def test_log_prob_differentiable_through_tape():
    loc = paddle.to_tensor(np.float32(0.5))
    loc.stop_gradient = False
    scale = paddle.to_tensor(np.float32(1.5))
    scale.stop_gradient = False
    d = Normal(loc, scale)
    lp = d.log_prob(paddle.to_tensor(np.float32(1.0)))
    lp.backward()
    # d/dloc log N(1; loc, s) = (1-loc)/s^2
    np.testing.assert_allclose(np.asarray(loc.grad.numpy()),
                               (1.0 - 0.5) / 1.5 ** 2, rtol=1e-5)
    # rsample pathwise gradient flows to params
    loc2 = paddle.to_tensor(np.float32(0.0))
    loc2.stop_gradient = False
    paddle.seed(5)
    s = Normal(loc2, paddle.to_tensor(np.float32(1.0))).rsample((8,))
    s.sum().backward()
    np.testing.assert_allclose(np.asarray(loc2.grad.numpy()), 8.0,
                               rtol=1e-5)


def test_kl_differentiable():
    s = paddle.to_tensor(np.float32(1.0))
    s.stop_gradient = False
    kl = kl_divergence(Normal(paddle.to_tensor(np.float32(0.0)), s),
                       Normal(paddle.to_tensor(np.float32(0.0)),
                              paddle.to_tensor(np.float32(2.0))))
    kl.backward()
    # d/ds 0.5(s^2/4 - 1 - log(s^2/4)) = s/4 - 1/s
    np.testing.assert_allclose(np.asarray(s.grad.numpy()),
                               1 / 4 - 1.0, rtol=1e-4)


def test_binomial_multinomial_entropy():
    b = Binomial(10, t(0.4))
    np.testing.assert_allclose(float(b.entropy().numpy()),
                               st.binom(10, 0.4).entropy(), rtol=1e-4)
    paddle.seed(0)
    m = Multinomial(8, t([0.2, 0.3, 0.5]))
    ent = float(m.entropy().numpy())
    assert abs(ent - st.multinomial(8, [0.2, 0.3, 0.5]).entropy()) < 0.2


class TestContinuousBernoulli:
    def test_log_prob_normalizes(self):
        """∫p(x)dx == 1 (trapezoid over [0,1]) away from and at λ=1/2."""
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu.distribution import ContinuousBernoulli
        for lam in (0.2, 0.5, 0.9):
            d = ContinuousBernoulli(paddle.to_tensor(float(lam)))
            xs = np.linspace(0, 1, 2001, dtype="float32")
            pdf = np.exp(d.log_prob(paddle.to_tensor(xs)).numpy())
            trapz = getattr(np, "trapezoid", np.trapz)
            assert abs(trapz(pdf, xs) - 1.0) < 1e-3, lam

    def test_moments_match_samples(self):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu.distribution import ContinuousBernoulli
        paddle.seed(11)
        for lam in (0.15, 0.5, 0.8):
            d = ContinuousBernoulli(paddle.to_tensor(float(lam)))
            s = d.sample([20000]).numpy()
            assert abs(s.mean() - float(d.mean.numpy())) < 5e-3, lam
            assert abs(s.var() - float(d.variance.numpy())) < 5e-3, lam
            assert (s >= 0).all() and (s <= 1).all()

    def test_cdf_icdf_roundtrip(self):
        import numpy as np
        import paddle_tpu as paddle
        from paddle_tpu.distribution import ContinuousBernoulli
        d = ContinuousBernoulli(paddle.to_tensor(0.3))
        u = paddle.to_tensor(np.linspace(0.05, 0.95, 7, dtype="float32"))
        x = d.icdf(u)
        np.testing.assert_allclose(d.cdf(x).numpy(), u.numpy(), atol=1e-5)
