"""nn.Layer machinery + layer forward/backward numerics (SURVEY.md §4
API/layer tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn

rng = np.random.RandomState(0)


def test_layer_registration():
    class Net(nn.Layer):
        def __init__(self):
            super().__init__()
            self.fc1 = nn.Linear(4, 8)
            self.fc2 = nn.Linear(8, 2)
            self.register_buffer("counter", paddle.zeros([1]))

        def forward(self, x):
            return self.fc2(self.fc1(x))

    net = Net()
    names = [n for n, _ in net.named_parameters()]
    assert set(names) == {"fc1.weight", "fc1.bias", "fc2.weight", "fc2.bias"}
    assert len(net.parameters()) == 4
    assert len(list(net.named_buffers())) == 1
    assert len(net.sublayers()) == 2
    sd = net.state_dict()
    assert "counter" in sd and "fc1.weight" in sd


def test_state_dict_roundtrip():
    net1 = nn.Linear(3, 3)
    net2 = nn.Linear(3, 3)
    net2.set_state_dict(net1.state_dict())
    np.testing.assert_allclose(net1.weight.numpy(), net2.weight.numpy())


def test_train_eval_mode():
    net = nn.Sequential(nn.Linear(2, 2), nn.Dropout(0.5))
    assert net.training
    net.eval()
    assert not net[1].training
    x = paddle.ones([4, 2])
    y1 = net(x)
    y2 = net(x)
    np.testing.assert_allclose(y1.numpy(), y2.numpy())  # dropout off in eval


def test_linear_matches_numpy():
    fc = nn.Linear(4, 3)
    x = rng.randn(5, 4).astype(np.float32)
    out = fc(paddle.to_tensor(x))
    ref = x @ fc.weight.numpy() + fc.bias.numpy()
    np.testing.assert_allclose(out.numpy(), ref, rtol=1e-5, atol=1e-5)


def test_conv2d_matches_scipy_style():
    conv = nn.Conv2D(2, 3, 3, padding=1)
    x = rng.randn(1, 2, 5, 5).astype(np.float32)
    out = conv(paddle.to_tensor(x))
    assert out.shape == [1, 3, 5, 5]
    # numpy reference conv at one output position
    w = conv.weight.numpy()
    b = conv.bias.numpy()
    xp = np.pad(x, [(0, 0), (0, 0), (1, 1), (1, 1)])
    ref_center = (xp[0, :, 1:4, 1:4] * w[1]).sum() + b[1]
    np.testing.assert_allclose(out.numpy()[0, 1, 1, 1], ref_center, rtol=1e-4,
                               atol=1e-4)


def test_conv_grouped_and_stride():
    conv = nn.Conv2D(4, 4, 3, stride=2, padding=1, groups=2)
    out = conv(paddle.ones([2, 4, 8, 8]))
    assert out.shape == [2, 4, 4, 4]


def test_batchnorm_stats_update():
    bn = nn.BatchNorm2D(3, momentum=0.9)
    x = paddle.to_tensor(rng.randn(4, 3, 2, 2).astype(np.float32) * 2 + 5)
    bn.train()
    out = bn(x)
    # output normalized per-channel
    np.testing.assert_allclose(out.numpy().mean((0, 2, 3)), 0, atol=1e-5)
    assert not np.allclose(bn._mean.numpy(), 0)  # running stats moved
    bn.eval()
    out2 = bn(x)
    assert out2.shape == [4, 3, 2, 2]


def test_layernorm_and_groupnorm():
    ln = nn.LayerNorm(8)
    x = paddle.to_tensor(rng.randn(2, 4, 8).astype(np.float32))
    y = ln(x)
    np.testing.assert_allclose(y.numpy().mean(-1), 0, atol=1e-5)
    np.testing.assert_allclose(y.numpy().std(-1), 1, atol=1e-2)
    gn = nn.GroupNorm(2, 4)
    z = gn(paddle.to_tensor(rng.randn(2, 4, 3, 3).astype(np.float32)))
    assert z.shape == [2, 4, 3, 3]


def test_rmsnorm():
    rn = nn.RMSNorm(8)
    x = paddle.to_tensor(rng.randn(2, 8).astype(np.float32))
    y = rn(x)
    ref = x.numpy() / np.sqrt((x.numpy() ** 2).mean(-1, keepdims=True) + 1e-6)
    np.testing.assert_allclose(y.numpy(), ref, rtol=1e-4, atol=1e-5)


def test_embedding():
    emb = nn.Embedding(10, 4, padding_idx=0)
    idx = paddle.to_tensor([0, 3, 5])
    out = emb(idx)
    assert out.shape == [3, 4]
    np.testing.assert_allclose(out.numpy()[0], 0)


def test_pooling():
    x = paddle.to_tensor(np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4))
    mp = nn.MaxPool2D(2)(x)
    np.testing.assert_allclose(mp.numpy()[0, 0], [[5, 7], [13, 15]])
    ap = nn.AvgPool2D(2)(x)
    np.testing.assert_allclose(ap.numpy()[0, 0], [[2.5, 4.5], [10.5, 12.5]])
    aap = nn.AdaptiveAvgPool2D(1)(x)
    np.testing.assert_allclose(aap.numpy()[0, 0, 0, 0], 7.5)


def test_activations():
    x = paddle.to_tensor([-2.0, 0.0, 2.0])
    np.testing.assert_allclose(nn.ReLU()(x).numpy(), [0, 0, 2])
    np.testing.assert_allclose(nn.functional.gelu(x).numpy(),
                               [-0.0455, 0.0, 1.9545], atol=1e-3)
    np.testing.assert_allclose(nn.Sigmoid()(x).numpy(),
                               1 / (1 + np.exp([2.0, 0, -2.0])), rtol=1e-5)
    np.testing.assert_allclose(nn.functional.softmax(x).numpy().sum(), 1.0,
                               rtol=1e-6)


def test_cross_entropy_matches_numpy():
    logits = rng.randn(4, 5).astype(np.float32)
    labels = np.array([0, 2, 4, 1])
    loss = nn.functional.cross_entropy(paddle.to_tensor(logits),
                                       paddle.to_tensor(labels))
    # numpy ref
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = -np.log(p[np.arange(4), labels]).mean()
    np.testing.assert_allclose(loss.numpy(), ref, rtol=1e-5)


def test_cross_entropy_ignore_index_and_soft():
    logits = rng.randn(4, 5).astype(np.float32)
    labels = np.array([0, -100, 4, -100])
    loss = nn.functional.cross_entropy(paddle.to_tensor(logits),
                                       paddle.to_tensor(labels),
                                       ignore_index=-100)
    e = np.exp(logits - logits.max(-1, keepdims=True))
    p = e / e.sum(-1, keepdims=True)
    ref = -np.log(p[[0, 2], [0, 4]]).mean()
    np.testing.assert_allclose(loss.numpy(), ref, rtol=1e-5)
    soft = np.full((4, 5), 0.2, np.float32)
    l2 = nn.functional.cross_entropy(paddle.to_tensor(logits),
                                     paddle.to_tensor(soft), soft_label=True)
    assert np.isfinite(float(l2))


def test_losses():
    x = paddle.to_tensor([1.0, 2.0])
    y = paddle.to_tensor([1.5, 1.0])
    np.testing.assert_allclose(nn.MSELoss()(x, y).numpy(), (0.25 + 1.0) / 2)
    np.testing.assert_allclose(nn.L1Loss()(x, y).numpy(), 0.75)
    z = paddle.to_tensor([0.3, 0.8])
    l = paddle.to_tensor([0.0, 1.0])
    ref = -(np.log(1 - 0.3) + np.log(0.8)) / 2
    np.testing.assert_allclose(nn.BCELoss()(z, l).numpy(), ref, rtol=1e-5)


def test_mha_and_transformer_encoder():
    mha = nn.MultiHeadAttention(16, 4)
    x = paddle.to_tensor(rng.randn(2, 5, 16).astype(np.float32))
    out = mha(x)
    assert out.shape == [2, 5, 16]
    enc_layer = nn.TransformerEncoderLayer(16, 4, 32, dropout=0.0)
    enc = nn.TransformerEncoder(enc_layer, 2)
    out2 = enc(x)
    assert out2.shape == [2, 5, 16]
    # layers are deep-copied, not shared
    p0 = enc.layers[0].linear1.weight
    p1 = enc.layers[1].linear1.weight
    assert p0 is not p1


def test_sdpa_causal():
    q = paddle.to_tensor(rng.randn(1, 4, 2, 8).astype(np.float32))
    out = nn.functional.scaled_dot_product_attention(q, q, q, is_causal=True)
    assert out.shape == [1, 4, 2, 8]
    # first position attends only to itself -> equals v[0]
    np.testing.assert_allclose(out.numpy()[0, 0], q.numpy()[0, 0], rtol=1e-4,
                               atol=1e-5)


def test_weight_norm_and_clip():
    fc = nn.Linear(3, 3)
    nn.utils.weight_norm(fc, "weight")
    x = paddle.ones([1, 3])
    _ = fc(x)
    assert "weight_g" in dict(fc.named_parameters().__iter__() if False else
                              [(n, p) for n, p in fc.named_parameters()])
    clip = nn.ClipGradByGlobalNorm(1.0)
    p = paddle.Parameter(np.ones(4, np.float32))
    g = paddle.to_tensor(np.full(4, 10.0, np.float32))
    (p2, g2), = clip([(p, g)])
    np.testing.assert_allclose(np.linalg.norm(g2.numpy()), 1.0, rtol=1e-5)


def test_sequential_layerlist():
    seq = nn.Sequential(nn.Linear(2, 3), nn.ReLU(), nn.Linear(3, 1))
    assert len(seq) == 3
    out = seq(paddle.ones([1, 2]))
    assert out.shape == [1, 1]
    ll = nn.LayerList([nn.Linear(2, 2) for _ in range(3)])
    ll.append(nn.Linear(2, 2))
    assert len(ll) == 4
    assert len(nn.Sequential(*ll)(paddle.ones([1, 2])).shape) == 2


def test_forward_hooks():
    fc = nn.Linear(2, 2)
    calls = []
    h = fc.register_forward_post_hook(lambda l, i, o: calls.append(1))
    fc(paddle.ones([1, 2]))
    assert calls == [1]
    h.remove()
    fc(paddle.ones([1, 2]))
    assert calls == [1]
