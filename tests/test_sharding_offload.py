"""Stage-3 ``offload=True`` (VERDICT.md round-3 item 6; reference:
``group_sharded_parallel(..., offload=True)`` — params resident in host
memory between steps, streamed to the device per use).

TPU-native contract under test: offload KEEPS the sharded layout and
moves residence via the sharding's host memory kind; each forward fetches
device copies and the host copy stays authoritative afterwards."""
import numpy as np

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn
from paddle_tpu.distributed.sharding import group_sharded_parallel


def _model_and_opt(seed=41):
    paddle.seed(seed)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
    opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                 parameters=model.parameters())
    return model, opt


def test_offload_params_host_resident_and_trainable():
    dist.mesh.reset_mesh()
    dist.init_mesh({"sharding": 8})
    try:
        model, opt = _model_and_opt()
        model, opt, _ = group_sharded_parallel(model, opt, level="p_g_os",
                                               offload=True)
        # at rest: sharded AND host-resident
        kinds = {p._data.sharding.memory_kind for p in model.parameters()
                 if getattr(p, "_sharding_spec", None) is not None}
        assert kinds == {"pinned_host"}, kinds

        rng = np.random.RandomState(2)
        x = paddle.to_tensor(rng.randn(16, 8).astype("float32"))
        y = paddle.to_tensor(rng.randn(16, 2).astype("float32"))
        losses = []
        for _ in range(8):
            loss = ((model(x) - y) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
            losses.append(float(loss))
        assert losses[-1] < losses[0] * 0.9, losses
        # after training the updated values are back home on the host
        kinds = {p._data.sharding.memory_kind for p in model.parameters()
                 if getattr(p, "_sharding_spec", None) is not None}
        assert kinds == {"pinned_host"}, kinds
    finally:
        dist.mesh.reset_mesh()


def test_stage3_composes_with_existing_mp_sharding():
    """VERDICT round-3 weak item 9: shard_spec_for must compose with a
    tensor-parallel placement already on the weight (vocab-parallel /
    column-parallel), never clobber it or double-book the same dim."""
    import jax
    from jax.sharding import NamedSharding, PartitionSpec as P
    from paddle_tpu.distributed.fleet.meta_parallel.sharding import \
        GroupShardedStage3, shard_spec_for

    dist.mesh.reset_mesh()
    dist.init_mesh({"sharding": 2, "mp": 4})
    try:
        paddle.seed(1)
        model = nn.Sequential(nn.Linear(8, 16))
        w = model.sublayers()[0].weight          # [8, 16]
        # simulate an mp layer: weight column-split over 'mp' already
        w._data = jax.device_put(
            w._data, NamedSharding(dist.mesh.get_mesh(), P(None, "mp")))
        GroupShardedStage3(model)
        spec = w._sharding_spec
        assert spec == ("sharding", "mp"), spec   # composed, not clobbered
        got = tuple(w._data.sharding.spec)
        assert got == ("sharding", "mp"), got
        # unit level: a dim already taken is skipped even if largest
        assert shard_spec_for((16, 4), existing=(None, "mp")) == \
            ("sharding", "mp")
        assert shard_spec_for((2, 3), existing=("mp", None)) is None
    finally:
        dist.mesh.reset_mesh()


def test_offload_matches_non_offload_numerics():
    dist.mesh.reset_mesh()
    dist.init_mesh({"sharding": 8})
    try:
        rng = np.random.RandomState(3)
        x = rng.randn(16, 8).astype("float32")
        y = rng.randn(16, 2).astype("float32")
        results = []
        for offload in (False, True):
            model, opt = _model_and_opt(seed=7)
            model, opt, _ = group_sharded_parallel(model, opt,
                                                   level="p_g_os",
                                                   offload=offload)
            for _ in range(4):
                loss = ((model(paddle.to_tensor(x)) -
                         paddle.to_tensor(y)) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
            results.append(model(paddle.to_tensor(x)).numpy())
        np.testing.assert_allclose(results[0], results[1], rtol=1e-5,
                                   atol=1e-6)
    finally:
        dist.mesh.reset_mesh()
