"""Bucketed + quantized gradient communication layer
(``paddle_tpu.distributed.comm`` — EQuARX-style blockwise-int8
collectives, fusion bucketing, CommStats accounting, policy wiring
through DistributedStrategy / HybridParallelOptimizer / sharding)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn
from paddle_tpu.distributed.comm import (
    GradientBucketer, all_reduce_quantized, dequantize_blockwise,
    dequantize_blockwise_jax, get_comm_stats, quantize_blockwise,
    quantize_blockwise_jax, reset_comm_stats,
)


# ---------------------------------------------------------------------------
# codec
# ---------------------------------------------------------------------------


class TestQuantizationCodec:
    @pytest.mark.parametrize("block_size", [64, 256, 1024])
    def test_roundtrip_error_bound_per_block(self, block_size):
        """|x - dq(q(x))| <= scale/2 = max|block|/254 per block."""
        rng = np.random.default_rng(0)
        x = (rng.normal(size=5000) * np.repeat(
            10.0 ** rng.integers(-3, 3, size=5000 // 100 + 1), 100)[:5000]
        ).astype(np.float32)
        q, scales = quantize_blockwise(x, block_size)
        d = dequantize_blockwise(q, scales, x.size, block_size)
        err = np.abs(d - x)
        bound = np.repeat(scales / 2, block_size)[:x.size]
        assert (err <= bound + 1e-12).all()
        # wire sizes: 1 byte/elem (padded) + 4 bytes/block
        n_blocks = -(-x.size // block_size)
        assert q.nbytes == n_blocks * block_size
        assert scales.nbytes == n_blocks * 4

    def test_zero_and_tiny_blocks_safe(self):
        """All-zero blocks and denormal-tiny blocks (scale underflow)
        must not divide by zero or emit garbage."""
        x = np.zeros(512, np.float32)
        x[300] = 1e-42                      # maxabs/127 underflows fp32
        q, s = quantize_blockwise(x, 256)
        d = dequantize_blockwise(q, s, x.size, 256)
        assert np.isfinite(d).all()
        np.testing.assert_allclose(d[:256], 0.0)

    def test_jax_path_matches_numpy(self):
        """Same codec on both paths — scales agree to 1 ulp (XLA may
        lower the division as a reciprocal multiply), int8 values to at
        most one quantization step at rounding boundaries, and the
        dequantized values satisfy the same per-block error bound."""
        rng = np.random.default_rng(1)
        x = rng.normal(size=1000).astype(np.float32)
        q, s = quantize_blockwise(x, 256)
        qj, sj = quantize_blockwise_jax(x, 256)
        np.testing.assert_allclose(np.asarray(sj), s, rtol=1e-6)
        assert np.abs(np.asarray(qj).astype(np.int32)
                      - q.astype(np.int32)).max() <= 1
        dj = np.asarray(dequantize_blockwise_jax(qj, sj, x.size, 256))
        bound = np.repeat(s / 2, 256)[:x.size] * (1 + 1e-5) + 1e-12
        assert (np.abs(dj - x) <= bound).all()


# ---------------------------------------------------------------------------
# bucketer layout
# ---------------------------------------------------------------------------


def _fake_params(shapes, dtype=np.float32):
    return [paddle.to_tensor(np.zeros(s, dtype)) for s in shapes]


class TestBucketerLayout:
    def test_fuse_zero_is_per_tensor(self):
        b = GradientBucketer(_fake_params([(4, 4), (8,), (2, 2)]),
                             fuse_grad_size_in_MB=0)
        assert b.num_buckets == 3

    def test_fusion_cap_splits(self):
        # 1 MB cap, fp32: 262144 elems/bucket; 3x (256,256)=65536 fit,
        # the 5th forces a new bucket
        b = GradientBucketer(_fake_params([(256, 256)] * 5),
                             fuse_grad_size_in_MB=1)
        assert b.num_buckets == 2
        assert [len(bk.items) for bk in b.buckets] == [4, 1]

    def test_dtype_homogeneous(self):
        params = _fake_params([(8,)]) + _fake_params([(8,)], np.int32) \
            + _fake_params([(8,)])
        b = GradientBucketer(params, fuse_grad_size_in_MB=32)
        assert b.num_buckets == 2
        assert {str(bk.dtype) for bk in b.buckets} == {"float32", "int32"}

    def test_int8_layout_is_block_aligned(self):
        b = GradientBucketer(_fake_params([(10,), (300,), (5,)]),
                             fuse_grad_size_in_MB=32, quantization="int8",
                             block_size=256)
        offs = [it[1] for it in b.buckets[0].items]
        assert offs == [0, 256, 768]    # each param starts a fresh block

    def test_layout_identical_across_ranks(self):
        shapes = [(64, 32), (64,), (32, 16), (16,), (7, 3)]

        def worker():
            b = GradientBucketer(_fake_params(shapes),
                                 fuse_grad_size_in_MB=32,
                                 quantization="int8")
            sigs = []
            dist.all_gather_object(sigs, b.signature())
            return all(s == sigs[0] for s in sigs)

        assert all(dist.spawn(worker, nprocs=4).results)


# ---------------------------------------------------------------------------
# quantized collectives in the simulator
# ---------------------------------------------------------------------------


class TestQuantizedCollectives:
    def test_all_reduce_quantized_sim(self):
        def worker():
            r = dist.get_rank()
            rng = np.random.default_rng(r)
            x = rng.normal(size=600).astype(np.float32)
            t = paddle.to_tensor(x.copy())
            all_reduce_quantized(t, op=dist.ReduceOp.AVG, block_size=64)
            return x, t.numpy()

        res = dist.spawn(worker, nprocs=4).results
        exact = np.mean([x for x, _ in res], axis=0)
        for _, got in res:
            np.testing.assert_allclose(got, exact, atol=0.05)
            np.testing.assert_allclose(got, res[0][1])  # ranks agree

    def test_all_reduce_quantized_world1_device_roundtrip(self):
        """World size 1 outside the simulator: the jitted q/dq round trip
        applies (per-contribution semantics match the multi-rank path)."""
        rng = np.random.default_rng(3)
        x = rng.normal(size=500).astype(np.float32)
        t = paddle.to_tensor(x.copy())
        all_reduce_quantized(t, block_size=256)
        q, s = quantize_blockwise(x, 256)
        np.testing.assert_allclose(t.numpy(),
                                   dequantize_blockwise(q, s, x.size, 256),
                                   rtol=1e-6)

    def test_reduce_scatter_quantized_sim(self):
        from paddle_tpu.distributed.comm import reduce_scatter_quantized

        def worker():
            r = dist.get_rank()
            parts = [np.full((8,), float(r + 10 * i), np.float32)
                     for i in range(2)]
            out = paddle.zeros([8])
            reduce_scatter_quantized(out, [paddle.to_tensor(p) for p in parts],
                                     op=dist.ReduceOp.SUM, block_size=64)
            return out.numpy()

        res = dist.spawn(worker, nprocs=2).results
        np.testing.assert_allclose(res[0], 1.0, atol=0.1)    # 0 + 1
        np.testing.assert_allclose(res[1], 21.0, atol=0.3)   # 10 + 11

    def test_error_feedback_transmits_residual(self):
        """With EF the quantization error of round k is carried into
        round k+1 — the cumulative transmitted sum converges to the
        cumulative true sum (bias-free), unlike the EF-off path which
        can lose the same sub-threshold mass every round."""
        rng = np.random.default_rng(5)
        grads = [rng.normal(size=512).astype(np.float32) * 1e-3
                 for _ in range(20)]
        from paddle_tpu.distributed.comm import allreduce_array
        residual = np.zeros(512, np.float32)
        got_ef, got_raw = np.zeros(512), np.zeros(512)
        for g in grads:
            got_ef += allreduce_array(g, scheme="int8", block_size=512,
                                      residual=residual)
            got_raw += allreduce_array(g, scheme="int8", block_size=512)
        true = np.sum(grads, axis=0)
        # EF's remaining error is the last residual only
        assert np.abs(got_ef - true).max() <= np.abs(residual).max() + 1e-7
        assert np.abs(got_ef - true).max() <= np.abs(got_raw - true).max() + 1e-7

    def test_bf16_scheme(self):
        def worker():
            r = dist.get_rank()
            t = paddle.to_tensor(np.full(64, 1.0 + r, np.float32))
            all_reduce_quantized(t, op=dist.ReduceOp.AVG, scheme="bf16")
            return t.numpy()

        res = dist.spawn(worker, nprocs=2).results
        for v in res:
            np.testing.assert_allclose(v, 1.5, rtol=1e-2)


# ---------------------------------------------------------------------------
# CommStats accounting
# ---------------------------------------------------------------------------


class TestCommStats:
    def test_byte_accounting_exact(self):
        reset_comm_stats()

        def worker():
            t = paddle.to_tensor(np.ones(1024, np.float32))
            all_reduce_quantized(t, block_size=256)

        dist.spawn(worker, nprocs=2)
        st = get_comm_stats().as_dict()
        # per rank: logical = 1024*4; wire = 1024 int8 + 4 scales * 4B
        assert st["by_kind"]["all_reduce_q"]["logical_bytes"] == 2 * 1024 * 4
        assert st["by_kind"]["all_reduce_q"]["wire_bytes"] == 2 * (1024 + 16)
        assert st["calls"] == 2
        assert st["compression_ratio"] > 3.9

    def test_dense_collectives_recorded(self):
        reset_comm_stats()

        def worker():
            t = paddle.to_tensor(np.ones(256, np.float32))
            dist.all_reduce(t)

        dist.spawn(worker, nprocs=2)
        st = get_comm_stats().as_dict()
        assert st["by_kind"]["all_reduce"]["wire_bytes"] == 2 * 256 * 4

    def test_profiler_exposes_comm_stats(self):
        from paddle_tpu import profiler
        reset_comm_stats()
        d = profiler.comm_stats()
        assert d["calls"] == 0 and "compression_ratio" in d


# ---------------------------------------------------------------------------
# end-to-end policy wiring
# ---------------------------------------------------------------------------


NPROCS, STEPS = 4, 20


def _training_data():
    rng = np.random.default_rng(7)
    X = rng.normal(size=(NPROCS * 8 * STEPS, 16)).astype(np.float32)
    Y = (X @ rng.normal(size=(16, 4)).astype(np.float32)).astype(np.float32)
    return X, Y


def _build_model():
    # 8 fp32 parameters -> per-tensor baseline issues 8 collectives/step,
    # the 32 MB bucket exactly one
    model = nn.Sequential(nn.Linear(16, 64), nn.Tanh(), nn.Linear(64, 64),
                          nn.Tanh(), nn.Linear(64, 64), nn.Linear(64, 4))
    wr = np.random.default_rng(0)   # deterministic across simulator threads
    for p in model.parameters():
        v = (wr.normal(size=p.shape) * (0.3 / np.sqrt(max(p.shape[0], 1)))
             if len(p.shape) == 2 else np.zeros(p.shape))
        p.set_value(paddle.to_tensor(v.astype(np.float32)))
    return model


def _train_dp(X, Y, quant, fuse_mb, error_feedback=True):
    """Simulated dp-NPROCS run through HybridParallelOptimizer; returns
    (common eval loss, CommStats dict)."""
    Xe, Ye = X[:64], Y[:64]

    def worker():
        r = dist.get_rank()
        model = _build_model()
        strat = dist.fleet.DistributedStrategy()
        strat.hybrid_configs = {"dp_degree": NPROCS}
        strat.comm_quantization = quant
        strat.fuse_grad_size_in_MB = fuse_mb
        strat.comm_configs = {"error_feedback": error_feedback}
        opt = dist.fleet.HybridParallelOptimizer(
            paddle.optimizer.SGD(learning_rate=0.05,
                                 parameters=model.parameters()),
            strategy=strat)
        loss_fn = nn.MSELoss()
        for s in range(STEPS):
            lo = (s * NPROCS + r) * 8
            loss = loss_fn(model(paddle.to_tensor(X[lo:lo + 8])),
                           paddle.to_tensor(Y[lo:lo + 8]))
            loss.backward()
            opt.step()
            opt.clear_grad()
        ev = loss_fn(model(paddle.to_tensor(Xe)), paddle.to_tensor(Ye))
        return float(ev.numpy())

    reset_comm_stats()
    res = dist.spawn(worker, nprocs=NPROCS).results
    # replicas must stay consistent (grads exchanged, same updates)
    assert np.allclose(res, res[0], rtol=1e-4), res
    return res[0], get_comm_stats().as_dict()


class TestEndToEnd:
    def test_acceptance_dp4_int8_fuse32(self):
        """ISSUE 1 acceptance: comm_quantization='int8' +
        fuse_grad_size_in_MB=32 on simulated dp-4 — wire bytes <= 30% of
        the fp32 baseline, >= 4x fewer collective calls, final loss
        within 2% relative of the fp32 path."""
        X, Y = _training_data()
        loss_fp, st_fp = _train_dp(X, Y, quant=None, fuse_mb=0)
        loss_q, st_q = _train_dp(X, Y, quant="int8", fuse_mb=32)

        assert st_q["wire_bytes"] <= 0.30 * st_fp["wire_bytes"], (
            st_q["wire_bytes"], st_fp["wire_bytes"])
        assert st_fp["calls"] >= 4 * st_q["calls"], (
            st_fp["calls"], st_q["calls"])
        rel = abs(loss_q - loss_fp) / max(abs(loss_fp), 1e-9)
        assert rel <= 0.02, (loss_q, loss_fp, rel)
        assert st_q["quant_max_error"] > 0.0
        # training moved: eval loss is finite and below the untrained start
        assert np.isfinite(loss_q)

    def test_bucketed_fp32_is_exact(self):
        """Bucketing alone (no quantization) must change NOTHING about
        the training math vs the per-tensor baseline — same elementwise
        averaging, just fused."""
        X, Y = _training_data()
        loss_per_tensor, _ = _train_dp(X, Y, quant=None, fuse_mb=0,
                                       error_feedback=False)
        loss_bucketed, st = _train_dp(X, Y, quant=None, fuse_mb=32,
                                      error_feedback=False)
        np.testing.assert_allclose(loss_bucketed, loss_per_tensor, rtol=1e-6)
        assert st["calls"] == NPROCS * STEPS    # one bucket per step

    def test_stage2_reduce_scatter_parity(self):
        """Stage-2 sharded optimizer in per-rank mode: the bucketed
        reduce-scatter + shard all-gather wire pattern must produce the
        same averaged gradient as a dense all-reduce."""
        def worker():
            r = dist.get_rank()
            model = nn.Linear(16, 8)
            wr = np.random.default_rng(0)
            for p in model.parameters():
                p.set_value(paddle.to_tensor(
                    wr.normal(size=p.shape).astype(np.float32) * 0.1))
            from paddle_tpu.distributed.sharding import group_sharded_parallel
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=model.parameters())
            wrapped, opt, _ = group_sharded_parallel(
                model, opt, level="os_g",
                comm_config={"fuse_grad_size_in_MB": 32,
                             "quantization": None, "block_size": 256,
                             "error_feedback": False})
            rng = np.random.default_rng(100 + r)
            x = paddle.to_tensor(rng.normal(size=(4, 16)).astype(np.float32))
            loss = wrapped(x).sum()
            loss.backward()
            grads_before = [p.grad.numpy().copy()
                            for p in model.parameters()]
            opt.step()
            return grads_before, [p.numpy() for p in model.parameters()]

        res = dist.spawn(worker, nprocs=2).results
        # after step, both ranks hold identical params (same avg grad)
        for p0, p1 in zip(res[0][1], res[1][1]):
            np.testing.assert_allclose(p0, p1, rtol=1e-5, atol=1e-6)
        # and the applied update used the AVERAGE of the per-rank grads
        mean_g = [(a + b) / 2 for a, b in zip(res[0][0], res[1][0])]
        assert any(np.abs(g).max() > 0 for g in mean_g)

    def test_dataparallel_routes_through_bucketer(self):
        """DataParallel's backward flush uses the bucketer: grads exchange
        in one fused collective, values equal the per-tensor average."""
        reset_comm_stats()

        def worker():
            r = dist.get_rank()
            model = nn.Sequential(nn.Linear(8, 8), nn.Linear(8, 2))
            wr = np.random.default_rng(0)
            for p in model.parameters():
                p.set_value(paddle.to_tensor(
                    wr.normal(size=p.shape).astype(np.float32) * 0.1))
            dp = dist.DataParallel(model)
            rng = np.random.default_rng(r)
            x = paddle.to_tensor(rng.normal(size=(4, 8)).astype(np.float32))
            loss = dp(x).sum()
            loss.backward()
            return [p.grad.numpy().copy() for p in model.parameters()]

        res = dist.spawn(worker, nprocs=2).results
        for g0, g1 in zip(res[0], res[1]):
            np.testing.assert_allclose(g0, g1, rtol=1e-5, atol=1e-6)
        st = get_comm_stats().as_dict()
        # 4 params fused into ONE bucket -> 1 call per rank
        assert st["by_kind"]["all_reduce"]["calls"] == 2

    def test_strategy_serializes_comm_knobs(self):
        s = dist.fleet.DistributedStrategy()
        s.comm_quantization = "int8"
        s.fuse_grad_size_in_MB = 16
        s.comm_configs = {"error_feedback": True}
        d = s.to_dict()
        s2 = dist.fleet.DistributedStrategy.from_dict(d)
        assert s2.comm_quantization == "int8"
        assert s2.fuse_grad_size_in_MB == 16
        assert s2.comm_configs["error_feedback"] is True
        assert s2.comm_configs["block_size"] == 256
