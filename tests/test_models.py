"""Transformer model-zoo tests (SURVEY.md §2.4: in-repo BERT/ERNIE/GPT/Llama
families). Style follows the reference's model tests: finite losses, grads
flow to every parameter, numeric spot checks vs numpy."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import (
    LlamaForCausalLM, llama_tiny, GPTForCausalLM, gpt_tiny,
    BertForSequenceClassification, BertForPretraining, bert_tiny,
    ErnieForSequenceClassification, ErnieConfig)


def _ids(shape, high=128, seed=0):
    return paddle.to_tensor(
        np.random.default_rng(seed).integers(0, high, shape), dtype="int64")


def test_llama_forward_backward_all_grads():
    paddle.seed(0)
    m = LlamaForCausalLM(llama_tiny())
    loss, logits = m(_ids((2, 16)), labels=_ids((2, 16), seed=1))
    assert logits.shape == [2, 16, 128]
    assert np.isfinite(float(loss.numpy()))
    # random init => loss ~ ln(vocab)
    assert abs(float(loss.numpy()) - np.log(128)) < 0.5
    loss.backward()
    for name, p in m.named_parameters():
        assert p.grad is not None, name
        assert np.isfinite(np.asarray(p.grad.numpy())).all(), name


def test_llama_causality():
    """Changing a future token must not change past logits."""
    paddle.seed(0)
    m = LlamaForCausalLM(llama_tiny())
    m.eval()
    ids = _ids((1, 12))
    ids2_np = ids.numpy().copy()
    ids2_np[0, -1] = (ids2_np[0, -1] + 1) % 128
    with paddle.no_grad():
        a = m(ids).numpy()
        b = m(paddle.to_tensor(ids2_np, dtype="int64")).numpy()
    np.testing.assert_allclose(a[0, :-1], b[0, :-1], rtol=1e-5, atol=1e-5)
    assert np.abs(a[0, -1] - b[0, -1]).max() > 1e-6


def test_llama_gqa_matches_repeated_kv():
    """GQA (kv-heads < heads) must equal MHA with kv heads repeated."""
    import jax.numpy as jnp
    from paddle_tpu.nn import functional as F
    rng = np.random.default_rng(0)
    q = paddle.to_tensor(rng.standard_normal((2, 8, 4, 16)), dtype="float32")
    k = paddle.to_tensor(rng.standard_normal((2, 8, 2, 16)), dtype="float32")
    v = paddle.to_tensor(rng.standard_normal((2, 8, 2, 16)), dtype="float32")
    out = F.scaled_dot_product_attention(q, k, v, is_causal=True,
                                         training=False)
    k_rep = paddle.to_tensor(np.repeat(k.numpy(), 2, axis=2), dtype="float32")
    v_rep = paddle.to_tensor(np.repeat(v.numpy(), 2, axis=2), dtype="float32")
    ref = F.scaled_dot_product_attention(q, k_rep, v_rep, is_causal=True,
                                         training=False)
    np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5, atol=1e-5)


def test_rope_rotation_properties():
    """RoPE: position 0 is identity; rotation preserves norms."""
    from paddle_tpu.ops import fused
    cos, sin = fused.rope_freqs(16, 32)
    x = paddle.to_tensor(
        np.random.default_rng(0).standard_normal((1, 8, 2, 16)),
        dtype="float32")
    q, _, _ = fused.fused_rotary_position_embedding(x, sin=sin, cos=cos)
    qn = q.numpy()
    np.testing.assert_allclose(qn[0, 0], x.numpy()[0, 0], rtol=1e-5,
                               atol=1e-6)  # pos 0 identity
    np.testing.assert_allclose(
        np.linalg.norm(qn, axis=-1), np.linalg.norm(x.numpy(), axis=-1),
        rtol=1e-4)


def test_gpt_tied_lm_head():
    paddle.seed(0)
    m = GPTForCausalLM(gpt_tiny())
    loss, _ = m(_ids((2, 16)), labels=_ids((2, 16), seed=1))
    loss.backward()
    emb = m.gpt.embeddings.word_embeddings.weight
    assert emb.grad is not None
    # tied head: embedding grad gets contributions from both lookup and logits
    assert np.abs(emb.grad.numpy()).sum() > 0


def test_bert_classification_and_mask():
    paddle.seed(0)
    m = BertForSequenceClassification(bert_tiny())
    ids = _ids((2, 12))
    mask_np = np.ones((2, 12), np.int64)
    mask_np[:, 8:] = 0
    labels = paddle.to_tensor(np.array([0, 1]), dtype="int64")
    loss, logits = m(ids, attention_mask=paddle.to_tensor(mask_np),
                     labels=labels)
    assert logits.shape == [2, 2]
    assert np.isfinite(float(loss.numpy()))
    loss.backward()
    # padding tokens masked out: changing a padded token leaves logits intact
    m.eval()
    with paddle.no_grad():
        a = m(ids, attention_mask=paddle.to_tensor(mask_np)).numpy()
        ids2 = ids.numpy().copy()
        ids2[:, 9] = (ids2[:, 9] + 1) % 128
        b = m(paddle.to_tensor(ids2, dtype="int64"),
              attention_mask=paddle.to_tensor(mask_np)).numpy()
    np.testing.assert_allclose(a, b, rtol=1e-5, atol=1e-5)


def test_bert_pretraining_heads():
    paddle.seed(0)
    m = BertForPretraining(bert_tiny())
    mlm_labels = np.array(_ids((2, 12), seed=2).numpy())
    mlm_labels[:, :6] = -100  # ignored positions
    loss, mlm_logits, nsp_logits = m(
        _ids((2, 12)), masked_lm_labels=paddle.to_tensor(mlm_labels),
        next_sentence_labels=paddle.to_tensor(np.array([0, 1])))
    assert mlm_logits.shape == [2, 12, 128]
    assert nsp_logits.shape == [2, 2]
    loss.backward()
    assert np.isfinite(float(loss.numpy()))


def test_ernie_is_bert_shaped():
    cfg = ErnieConfig(vocab_size=128, hidden_size=64, num_hidden_layers=2,
                      num_attention_heads=4, intermediate_size=128,
                      max_position_embeddings=64)
    m = ErnieForSequenceClassification(cfg)
    logits = m(_ids((2, 10)))
    assert logits.shape == [2, 2]


def test_llama_sharding_rules_cover_all_params():
    from paddle_tpu.framework.functional import FunctionalModule
    m = LlamaForCausalLM(llama_tiny())
    fm = FunctionalModule(m)
    specs = fm.param_specs(LlamaForCausalLM.sharding_rules(),
                           fsdp_axis="sharding", fsdp_size=2)
    assert len(specs) == len(fm.params)
    named = dict(m.named_parameters())
    by_name = dict(zip([n for n, p in m.named_parameters() if p is not None],
                       specs))
    # column-parallel q_proj sharded on mp over dim1
    qspec = [s for n, s in by_name.items() if "q_proj" in n][0]
    assert "mp" in tuple(qspec)
