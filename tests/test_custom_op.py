"""Custom-op extension API (VERDICT.md round-1 item 8; reference:
``paddle/phi/api/ext/`` PD_BUILD_OP + ``python/paddle/utils/cpp_extension``,
exercised upstream by ``test/custom_op/``)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.utils import register_op, get_op, cpp_extension
from paddle_tpu.utils.custom_op import REGISTRY


def _leaf(a):
    t = paddle.to_tensor(np.asarray(a, np.float32))
    t.stop_gradient = False
    return t


def test_register_plain_op_autodiff():
    @register_op(name="t_sq3", override=True)
    def sq3(x):
        return x * x * x

    x = _leaf([1.0, 2.0])
    y = sq3(x)
    np.testing.assert_allclose(y.numpy(), [1, 8])
    y.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [3, 12])   # jax autodiff
    assert "t_sq3" in REGISTRY and get_op("t_sq3") is sq3.raw


def test_register_custom_vjp():
    calls = {"bwd": 0}

    def fwd(x):
        return jnp.tanh(x), (x,)

    def vjp(res, cot):
        calls["bwd"] += 1
        (x,) = res
        return (cot * (1 - jnp.tanh(x) ** 2) * 2.0,)   # deliberately 2x

    mytanh = register_op(fwd, name="t_tanh2", vjp=vjp, override=True)
    x = _leaf([0.3])
    y = mytanh(x)
    np.testing.assert_allclose(y.numpy(), np.tanh([0.3]), rtol=1e-6)
    y.backward()
    # custom rule (2x the true grad) proves the vjp was used
    np.testing.assert_allclose(x.grad.numpy(),
                               2 * (1 - np.tanh(0.3) ** 2), rtol=1e-5)
    assert calls["bwd"] == 1


def test_custom_op_under_to_static_and_double_grad():
    def fwd(x):
        return x * x, (x,)

    def vjp(res, cot):
        (x,) = res
        return (cot * 2 * x,)

    sq = register_op(fwd, name="t_sq_vjp", vjp=vjp, override=True)

    @paddle.jit.to_static
    def f(x):
        return sq(x).sum()

    x = _leaf([2.0, 3.0])
    np.testing.assert_allclose(float(f(x).numpy()), 13.0)

    # double grad through the custom vjp (jax.custom_vjp composes)
    x2 = _leaf([2.0])
    y = sq(x2).sum()
    (g1,) = paddle.grad(y, x2, create_graph=True)
    np.testing.assert_allclose(g1.numpy(), [4.0])
    (g2,) = paddle.grad(g1, x2)
    np.testing.assert_allclose(g2.numpy(), [2.0])


def test_register_pallas_kernel_op():
    """A user Pallas kernel as a first-class op (the TPU-native custom
    device kernel; interpret mode on CPU)."""
    from jax.experimental import pallas as pl

    def scale_kernel(x_ref, o_ref):
        o_ref[...] = x_ref[...] * 2.5

    def _call(x):
        return pl.pallas_call(
            scale_kernel,
            out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
            interpret=jax.default_backend() != "tpu",
        )(x)

    # inference-only kernel: fine on non-diff inputs
    pallas_scale = register_op(_call, name="t_pallas_scale", override=True)
    x = paddle.to_tensor(np.arange(8, dtype=np.float32).reshape(2, 4))
    y = pallas_scale(x)
    np.testing.assert_allclose(y.numpy(), np.arange(8).reshape(2, 4) * 2.5)

    # training kernel: pair the pallas fwd with a custom vjp
    pallas_scale_t = register_op(
        lambda x: (_call(x), ()), name="t_pallas_scale_t",
        vjp=lambda res, cot: (cot * 2.5,), override=True)
    xl = _leaf(np.ones((2, 4)))
    out = pallas_scale_t(xl)
    out.sum().backward()
    np.testing.assert_allclose(xl.grad.numpy(), np.full((2, 4), 2.5))


def test_vjp_op_with_static_kwargs():
    def fwd(x, scale=1.0):
        return x * scale, (scale,)

    def vjp(res, cot):
        (scale,) = res
        return (cot * scale,)

    op = register_op(fwd, name="t_scale_kw", vjp=vjp, override=True)
    x = _leaf([2.0])
    y = op(x, scale=3.0)
    np.testing.assert_allclose(y.numpy(), [6.0])
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [3.0])


def test_duplicate_registration_rejected():
    register_op(lambda x: x, name="t_dup", override=True)
    with pytest.raises(ValueError, match="already registered"):
        register_op(lambda x: x, name="t_dup")


def test_fused_swiglu_ported_through_api():
    """The in-tree worked example: fused_swiglu runs through register_op
    with a hand-written VJP matching jax autodiff."""
    from paddle_tpu.ops import fused

    rng = np.random.RandomState(0)
    a, g = rng.randn(4, 8).astype(np.float32), rng.randn(4, 8).astype(np.float32)
    x, gate = _leaf(a), _leaf(g)
    out = fused.fused_swiglu(x, gate)
    silu = a * (1 / (1 + np.exp(-a)))
    np.testing.assert_allclose(out.numpy(), silu * g, rtol=1e-5)
    out.sum().backward()
    # numeric grad check of the hand-written vjp
    eps = 1e-3
    num = (fused._swiglu_fwd(jnp.asarray(a + eps), jnp.asarray(g))[0].sum()
           - fused._swiglu_fwd(jnp.asarray(a - eps), jnp.asarray(g))[0].sum()) / (2 * eps)
    np.testing.assert_allclose(float(x.grad.numpy().sum()), float(num),
                               rtol=1e-2)
    assert "fused_swiglu" in REGISTRY


CPP_SRC = r"""
extern "C" void double_plus_one(const float* in, float* out, long n) {
    for (long i = 0; i < n; ++i) out[i] = 2.0f * in[i] + 1.0f;
}
"""


def test_cpp_extension_host_op():
    """Host tier: C++ source -> g++ shared lib -> ctypes -> pure_callback
    op that stays jit-compatible (reference: cpp_extension.load custom op)."""
    import ctypes

    lib = cpp_extension.load("t_host_ext", [CPP_SRC])
    lib.double_plus_one.argtypes = [
        ctypes.POINTER(ctypes.c_float), ctypes.POINTER(ctypes.c_float),
        ctypes.c_long]

    def host_fn(x):
        x = np.ascontiguousarray(np.asarray(x), np.float32)
        out = np.empty_like(x)
        lib.double_plus_one(
            x.ctypes.data_as(ctypes.POINTER(ctypes.c_float)),
            out.ctypes.data_as(ctypes.POINTER(ctypes.c_float)), x.size)
        return out

    op = register_op(host_fn, name="t_double_plus_one", host_callback=True,
                     out_shape=lambda x: jax.ShapeDtypeStruct(x.shape,
                                                              jnp.float32),
                     override=True)
    x = paddle.to_tensor(np.array([1.0, 2.0, 3.0], np.float32))
    np.testing.assert_allclose(op(x).numpy(), [3, 5, 7])

    # under jit (pure_callback path)
    @paddle.jit.to_static
    def f(x):
        return op(x) + 1.0

    np.testing.assert_allclose(f(x).numpy(), [4, 6, 8])
