"""Fleet substrate (ISSUE 8 satellites): atomic KV counters on both
store tiers, the SlotPagedKVCache page export/import handoff, tenant
token buckets, and the engine start/stop state-provider lifecycle."""
import threading

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.elastic.tcp_kv import (MemKVStore,
                                                         TcpKVStore)
from paddle_tpu.inference.fleet import Rejected, TenantQuotaManager
from paddle_tpu.models import LlamaForCausalLM, llama_tiny


# ---------------------------------------------------------------------------
# atomic incr — MemKVStore (thread tier) and TcpKVStore (native TCPStore)
# ---------------------------------------------------------------------------

def test_mem_kv_incr_concurrent():
    store = MemKVStore()

    def bump():
        for _ in range(250):
            store.incr("fleet/quota/t/used", 2)

    threads = [threading.Thread(target=bump) for _ in range(8)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert store.get("fleet/quota/t/used") == 8 * 250 * 2
    assert store.incr("fleet/quota/t/used", -1000) == 3000
    # counters live in the same key space as put/get
    assert store.get("fleet/quota/t/used") == 3000


def test_tcp_kv_incr_concurrent():
    from paddle_tpu.distributed import native
    if not native.available():
        pytest.skip("native TCPStore unavailable")
    master = TcpKVStore("tcp://127.0.0.1:0")
    port = master._store.port
    try:
        results = []

        def bump():
            # one client per thread — the realistic fleet shape (each
            # router/replica process owns its own connection)
            client = TcpKVStore(f"tcp://127.0.0.1:{port}")
            try:
                for _ in range(100):
                    results.append(client.incr("ctr", 1))
            finally:
                client.close()

        threads = [threading.Thread(target=bump) for _ in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert master.incr("ctr", 0) == 400
        # every increment observed a distinct value (no lost updates)
        assert len(set(results)) == 400
        # get() reads the native ADD representation back as an int
        assert master.get("ctr") == 400
    finally:
        master.close()


# ---------------------------------------------------------------------------
# page export/import (disagg handoff payload)
# ---------------------------------------------------------------------------

@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return LlamaForCausalLM(llama_tiny(num_hidden_layers=2,
                                       max_position_embeddings=256))


def _filled_cache(model, prompt):
    """Run a 1-token generate so the engine fills + commits the prompt's
    full blocks, then hand back the engine (still running)."""
    from paddle_tpu.inference import ContinuousServingEngine
    eng = ContinuousServingEngine(model, max_batch_size=2, max_len=96,
                                  page_size=16)
    eng.start()
    eng.generate(prompt, max_new_tokens=1, timeout=600)
    return eng


def test_export_import_roundtrip(model):
    from paddle_tpu.models.generation import block_hash_chain
    prompt = np.random.RandomState(0).randint(0, 128, (1, 40)) \
        .astype(np.int64)
    chain = block_hash_chain(prompt[0], 16)
    src = _filled_cache(model, prompt)
    try:
        blob = src.run_on_loop(lambda e: e._cache.export_pages(chain))
        assert blob is not None
        assert len(blob["digests"]) == 2            # 40 tokens, 2 full blocks
        assert len(blob["layers"]) == 2             # one K/V pair per layer
        k0, v0 = blob["layers"][0]
        assert k0.shape[1] == 2 and k0.shape[2] == 16
        # source pages survive the export byte-for-byte
        src_k = src.run_on_loop(
            lambda e: np.asarray(next(iter(e._cache._pools.values()))[0]
                                 [:, e._cache._index[blob["digests"][0]]]))
        np.testing.assert_array_equal(src_k, k0[:, 0])
    finally:
        src.stop()

    # import into a COLD cache (no forward run yet): pages land via the
    # pool-creation backlog, and a prompt sharing the prefix maps onto
    # them with zero prefill work
    from paddle_tpu.models.generation import SlotPagedKVCache
    dst = SlotPagedKVCache(2, page_size=16, max_len=96)
    assert dst.import_pages(blob) == 2
    assert dst.pages_imported == 2
    cached, hits, misses = dst.assign(0, prompt[0])
    assert (cached, hits) == (32, 2)
    # re-import is first-writer-wins: nothing double-registers
    assert dst.import_pages(blob) == 0


def test_import_rejects_mismatched_geometry(model):
    from paddle_tpu.models.generation import SlotPagedKVCache, \
        block_hash_chain
    prompt = np.random.RandomState(1).randint(0, 128, (1, 36)) \
        .astype(np.int64)
    src = _filled_cache(model, prompt)
    try:
        chain = block_hash_chain(prompt[0], 16)
        blob = src.run_on_loop(lambda e: e._cache.export_pages(chain))
    finally:
        src.stop()
    dst = SlotPagedKVCache(2, page_size=8, max_len=96)
    with pytest.raises(ValueError):
        dst.import_pages(blob)
    # cache-off receivers refuse politely (nothing to register into)
    dst2 = SlotPagedKVCache(2, page_size=16, max_len=96,
                            enable_prefix_cache=False)
    assert dst2.import_pages(blob) == 0


# ---------------------------------------------------------------------------
# tenant token buckets
# ---------------------------------------------------------------------------

def test_quota_manager_bucket_and_refill():
    store = MemKVStore()
    q = TenantQuotaManager(store, capacity=100, refill_per_s=0.0,
                           overrides={"vip": (0, 0.0),
                                      "tiny": (10, 1000.0)})
    q.admit("a", 60)
    q.admit("a", 40)
    with pytest.raises(Rejected) as exc:
        q.admit("a", 1)
    assert exc.value.reason == "tenant_quota"
    assert q.usage("a") == 100            # rejected charge rolled back
    q.admit("vip", 10 ** 9)               # capacity<=0 => unlimited
    # a refilling bucket recovers: 10-token capacity + 1000 tok/s
    q.admit("tiny", 10)
    import time
    time.sleep(0.05)
    q.admit("tiny", 10)

    # two managers over one store share the fleet-wide counter
    q2 = TenantQuotaManager(store, capacity=100)
    with pytest.raises(Rejected):
        q2.admit("a", 1)


# ---------------------------------------------------------------------------
# engine lifecycle: state provider must not leak across start/stop
# ---------------------------------------------------------------------------

def test_engine_stop_unregisters_state_provider(model):
    """Repeated start/stop — exactly the router's drain/rejoin cycle —
    must never accumulate stale providers in watchdog dumps, and the
    provider must stay live for the engine's whole serving window."""
    from paddle_tpu.inference import ContinuousServingEngine, ServingEngine
    from paddle_tpu.profiler import flight_recorder as flight

    def serving_keys():
        return [k for k in flight._STATE_PROVIDERS
                if k.startswith("serving_")]

    base = len(serving_keys())
    prompt = np.random.RandomState(2).randint(0, 128, (1, 12)) \
        .astype(np.int64)
    eng = ContinuousServingEngine(model, max_batch_size=2, max_len=48)
    for _ in range(3):
        eng.start()
        assert len(serving_keys()) == base + 1
        eng.generate(prompt, max_new_tokens=2, timeout=600)
        state = flight._STATE_PROVIDERS[eng._flight_key]()
        assert state["engine"] == "continuous"
        eng.stop()
        assert len(serving_keys()) == base, serving_keys()
    # the static engine shares the same contract (incl. abort teardown)
    se = ServingEngine(model, max_batch_size=2)
    se.start()
    assert len(serving_keys()) == base + 1
    se.abort()
    assert len(serving_keys()) == base


def test_engine_abort_fails_inflight_fast(model):
    """abort() is replica death: queued AND in-flight requests error out
    instead of draining to completion."""
    from paddle_tpu.inference import ContinuousServingEngine
    import time
    eng = ContinuousServingEngine(model, max_batch_size=2, max_len=96)
    prompt = np.random.RandomState(3).randint(0, 128, (1, 16)) \
        .astype(np.int64)
    errors = []

    def call():
        try:
            eng.generate(prompt, max_new_tokens=64, timeout=600)
        except RuntimeError as e:
            errors.append(e)

    with eng:
        t = threading.Thread(target=call)
        t.start()
        deadline = time.monotonic() + 5
        while eng.decode_steps + eng.prefill_chunks == 0 \
                and time.monotonic() < deadline:
            time.sleep(0.01)
        eng.abort()
        t.join(timeout=30)
    assert errors and "abort" in str(errors[0]).lower()
