"""Compile observatory (ISSUE 18): retrace-cause attribution unit tier,
the PADDLE_COMPILE_OBSERVATORY gate, paddle_compile_* metric rollups,
recompile-storm / family-drift alert rules (+ env grammar), the
``/compile`` exporter route and fleet merge, zero post-warmup misses on
mixed / speculative / q-block serving replays, cold-request TTFT
decomposition through log_query, and the compile_report CLI."""
import json
import os
import sys
import threading
import urllib.request

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousServingEngine
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.profiler import alerts, eventlog, scrape
from paddle_tpu.profiler import compile_observatory as co
from paddle_tpu.profiler import request_trace as rt
from paddle_tpu.profiler.exporter import TelemetryServer
from paddle_tpu.profiler.telemetry import MetricRegistry, get_registry
from paddle_tpu.profiler.timeseries import MetricsHistory

REPO = os.path.join(os.path.dirname(__file__), "..")
sys.path.insert(0, os.path.join(REPO, "tools"))

ENGINE_KW = dict(max_batch_size=2, max_len=48, token_budget=16,
                 prefill_chunk_tokens=16)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return LlamaForCausalLM(llama_tiny(num_hidden_layers=1))


@pytest.fixture(autouse=True)
def _fresh_observatory():
    co.reset()
    co.enable()
    yield
    co.reset()
    eventlog.reset()


def _prompts(sizes, seed=1):
    rng = np.random.RandomState(seed)
    return [rng.randint(0, 128, (1, n)).astype(np.int64) for n in sizes]


def _drive(eng, prompts, new_tokens):
    results = [None] * len(prompts)
    with eng:
        threads = [threading.Thread(
            target=lambda i=i, p=p: results.__setitem__(
                i, np.asarray(eng.generate(p, max_new_tokens=new_tokens,
                                           timeout=300).numpy())))
            for i, p in enumerate(prompts)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    return results


def _tok(n, dtype="int64"):
    return {"tokens": co.tensor_arg((n,), dtype)}


# ---------------------------------------------------------------------------
# unit tier: cause attribution
# ---------------------------------------------------------------------------

def test_cause_new_family_then_hit():
    r = co.observe("unit.a", _tok(8), seconds=0.5)
    assert r["miss"] and r["cause"] == "new family (family undeclared)"
    r = co.observe("unit.a", _tok(8))
    assert not r["miss"] and r["cause"] is None
    snap = co.snapshot()["families"]["unit.a"]
    assert (snap["hits"], snap["misses"]) == (1, 1)
    assert snap["compile_s"] == pytest.approx(0.5)
    assert snap["signatures"] == 1


def test_cause_bucket_miss_names_argument_and_dim():
    """The acceptance-bar cause string: a shape outside the declared
    bucket set must name the exact argument, dimension, offending value
    and the declared set."""
    co.declare_family("unit.buckets", buckets={"tokens": [128, 256]})
    co.observe("unit.buckets", _tok(128))
    r = co.observe("unit.buckets", _tok(136))
    assert r["cause"] == "arg `tokens` dim0 136∉{128,256}: bucket miss"
    # a declared-but-cold bucket is a "new bucket", not a bucket miss
    r = co.observe("unit.buckets", _tok(256))
    assert r["cause"] == "arg `tokens` dim0 136→256: new bucket"


def test_cause_static_dtype_rank_and_removed_args():
    fam = "unit.static"
    co.declare_family(fam)
    base = {"tokens": co.tensor_arg((8,), "int64"),
            "weight_dtype": co.static_arg("int8")}
    co.observe(fam, base)
    r = co.observe(fam, {"tokens": co.tensor_arg((8,), "int64"),
                         "weight_dtype": co.static_arg("bf16")})
    assert r["cause"] == "static arg `weight_dtype` int8→bf16"
    r = co.observe(fam, {"tokens": co.tensor_arg((8,), "int32"),
                         "weight_dtype": co.static_arg("bf16")})
    assert r["cause"] == "arg `tokens` dtype int64→int32"
    r = co.observe(fam, {"tokens": co.tensor_arg((2, 8), "int32"),
                         "weight_dtype": co.static_arg("bf16")})
    assert r["cause"] == "arg `tokens` rank 1→2"
    r = co.observe(fam, {"tokens": co.tensor_arg((2, 8), "int32")})
    assert r["cause"] == "arg `weight_dtype` removed"
    # undeclared dims diff without bucket vocabulary
    co.observe("unit.free", _tok(4))
    r = co.observe("unit.free", _tok(6))
    assert "arg `tokens` dim0 4→6" in r["cause"]


def test_signature_formatting():
    sig = {"tokens": co.tensor_arg((2, 16), "int64"),
           "weight_dtype": co.static_arg("int8")}
    assert (co.format_signature(sorted(sig.items()))
            == "tokens=int64[2x16], weight_dtype='int8'")


# ---------------------------------------------------------------------------
# gate + snapshot + cost table
# ---------------------------------------------------------------------------

def test_env_knob_gates_observation(monkeypatch):
    """PADDLE_COMPILE_OBSERVATORY=0 turns the plane off: the facade
    returns None and records nothing."""
    monkeypatch.setenv("PADDLE_COMPILE_OBSERVATORY", "0")
    co.reset()
    assert not co.is_enabled()
    assert co.observe("unit.off", _tok(8)) is None
    snap = co.snapshot()
    assert snap["enabled"] is False and snap["families"] == {}
    monkeypatch.setenv("PADDLE_COMPILE_OBSERVATORY", "1")
    co.reset()
    assert co.is_enabled()
    assert co.observe("unit.on", _tok(8))["miss"]


def test_snapshot_drift_and_warmup_accounting():
    co.declare_family("unit.declared", buckets={"tokens": [8]},
                      warmup=lambda: "warm")
    co.declare_family("unit.cold")
    co.observe("unit.declared", _tok(8))
    co.observe("unit.rogue", _tok(3))
    snap = co.snapshot()
    assert snap["schema"] == co.SCHEMA
    assert snap["undeclared"] == ["unit.rogue"]
    assert snap["declared_unobserved"] == ["unit.cold"]
    fam = snap["families"]["unit.declared"]
    assert fam["declared"] and fam["warmup"]
    assert not snap["families"]["unit.rogue"]["declared"]
    assert snap["families"]["unit.rogue"]["last_causes"][-1]["cause"] \
        .endswith("(family undeclared)")
    assert co.undeclared_families() == ["unit.rogue"]
    assert co.run_warmup(families=["unit.declared"]) \
        == {"unit.declared": "warm"}


def test_cost_table_compile_section():
    co.observe("unit.cost", _tok(8), seconds=0.25)
    co.observe("unit.cost", _tok(16), seconds=0.75)
    co.observe("unit.cost", _tok(8))                 # hit: no cost
    sect = co.cost_section()
    assert sect["unit.cost"]["compiles"] == 2
    assert sect["unit.cost"]["compile_s"] == pytest.approx(1.0)
    assert sect["unit.cost"]["mean_compile_s"] == pytest.approx(0.5)
    table = rt.cost_table()
    assert table["schema"] == "paddle_cost_table/2"   # additive key only
    assert table["compile"]["unit.cost"]["compiles"] == 2


def test_metrics_rollup_and_all_series():
    """Every observe lands on the per-family series AND the family="all"
    rollup the recompile-storm burn rate consumes."""
    reg = get_registry()
    hits = reg.counter("paddle_compile_hits_total", labels=("family",))
    misses = reg.counter("paddle_compile_misses_total",
                         labels=("family",))
    h0, m0 = hits.value(family="all"), misses.value(family="all")
    co.observe("unit.metrics", _tok(8), seconds=0.1)
    co.observe("unit.metrics", _tok(8))
    co.observe("unit.metrics", _tok(8))
    assert misses.value(family="unit.metrics") == 1.0
    assert hits.value(family="unit.metrics") == 2.0
    assert misses.value(family="all") - m0 == 1.0
    assert hits.value(family="all") - h0 == 2.0
    seconds = reg.get("paddle_compile_seconds")
    assert seconds.labels(family="unit.metrics").count == 1
    gauge = reg.get("paddle_compile_undeclared_families")
    assert gauge.value() >= 1.0          # unit.metrics was never declared
    co.declare_family("unit.metrics")
    co.observe("unit.metrics", _tok(8))
    assert gauge.value() == 0.0


# ---------------------------------------------------------------------------
# alert rules: recompile storm + family drift (+ env grammar)
# ---------------------------------------------------------------------------

def _compile_registry():
    reg = MetricRegistry()
    hits = reg.counter("paddle_compile_hits_total", labels=("family",))
    misses = reg.counter("paddle_compile_misses_total",
                         labels=("family",))
    return reg, hits, misses


def test_shape_churn_fires_recompile_storm_with_cause():
    """Acceptance bar: a shape-churn workload fires the recompile-storm
    page and the attribution names the exact argument and dimension."""
    co.declare_family("serving.ragged", buckets={"tokens": [8, 16]})
    co.observe("serving.ragged", _tok(8))
    reg, hits, misses = _compile_registry()
    h = MetricsHistory(capacity=256, registry=reg)
    rule = alerts.recompile_storm_rule(budget=0.1, fast_window_s=3.0,
                                       slow_window_s=9.0)
    assert rule.severity == "page" and rule.name == "recompile_storm"
    eng = alerts.AlertEngine(history=h, rules=[rule])
    # warm steady state: pure hits, no alert
    for t in range(10):
        hits.inc(family="all")
        h.tick(now=float(t))
        eng.evaluate(now=float(t))
    assert not eng.active
    # shape churn: every tick a fresh padded size outside {8,16}
    fired = []
    for t in range(10, 24):
        ev = co.observe("serving.ragged", _tok(16 + t), seconds=0.01)
        assert ev["miss"]
        misses.inc(family="all")
        h.tick(now=float(t))
        fired += eng.evaluate(now=float(t))
    assert any(tr["rule"] == "recompile_storm" and tr["action"] == "fired"
               for tr in fired), fired
    causes = [c["cause"] for c in
              co.snapshot()["families"]["serving.ragged"]["last_causes"]]
    assert any("`tokens`" in c and "dim0" in c and "bucket miss" in c
               for c in causes), causes


def test_family_drift_rule_fires_and_clears():
    reg = MetricRegistry()
    g = reg.gauge("paddle_compile_undeclared_families")
    h = MetricsHistory(capacity=64, registry=reg)
    rule = alerts.family_drift_rule()
    assert isinstance(rule, alerts.ThresholdRule)
    assert rule.name == "compile_family_drift" and rule.above == 0.0
    eng = alerts.AlertEngine(history=h, rules=[rule])
    g.set(0.0)
    h.tick(now=0.0)
    assert eng.evaluate(now=0.0) == []
    g.set(2.0)
    h.tick(now=1.0)
    trs = eng.evaluate(now=1.0)
    assert trs and trs[0]["action"] == "fired"
    g.set(0.0)
    h.tick(now=2.0)
    trs = eng.evaluate(now=2.0)
    assert trs and trs[0]["action"] == "cleared"


def test_parse_rules_compile_kinds():
    rules = alerts.parse_rules(
        "recompile_storm:budget=0.05,fast=30,slow=120,factor=2;"
        "family_drift:severity=page,for=5")
    storm, drift = rules
    assert isinstance(storm, alerts.BurnRateRule)
    assert storm.good_metric == "paddle_compile_hits_total"
    assert storm.bad_metric == "paddle_compile_misses_total"
    assert storm.slo == "all"
    assert (storm.budget, storm.fast_window_s, storm.slow_window_s,
            storm.factor) == (0.05, 30.0, 120.0, 2.0)
    assert isinstance(drift, alerts.ThresholdRule)
    assert drift.severity == "page" and drift.for_s == 5.0
    # defaults: the storm budget is the documented 2%
    assert alerts.recompile_storm_rule().budget \
        == alerts.DEFAULT_RECOMPILE_BUDGET == 0.02


# ---------------------------------------------------------------------------
# /compile route + fleet scrape/merge
# ---------------------------------------------------------------------------

def test_compile_endpoint_and_fleet_merge():
    co.declare_family("serving.ragged", buckets={"tokens": [8]})
    co.observe("serving.ragged", _tok(8), seconds=0.02)
    co.observe("serving.ragged", _tok(8))
    with TelemetryServer(instance="c0", port=0) as srv:
        with urllib.request.urlopen(
                f"http://{srv.address}/compile", timeout=10) as resp:
            assert resp.status == 200
            snap = json.loads(resp.read())
        assert snap["instance"] == "c0"
        assert snap["schema"] == co.SCHEMA
        fam = snap["families"]["serving.ragged"]
        assert (fam["hits"], fam["misses"]) == (1, 1)
        # scrape-module fetch agrees with the raw GET
        fetched = scrape.fetch_compile(srv.address)
        assert fetched["families"] == snap["families"]
        # FleetScraper static tier folds the instance in
        fs = scrape.FleetScraper(endpoints={"c0": srv.address})
        merged = fs.compile_merged()
        assert merged["instances"] == ["c0"]
        assert merged["families"]["serving.ragged"]["misses"] == 1
        assert merged["totals"]["hits"] == 1


def test_merge_compile_snapshots_attribution():
    """The fleet rollup sums counts but keeps per-instance attribution
    on causes and undeclared families — drift on ONE replica must stay
    visible."""
    a = {"families": {"serving.ragged": {
             "hits": 10, "misses": 1, "compile_s": 0.5, "signatures": 2,
             "last_causes": [{"cause": "new family"}]}},
         "undeclared": [], "totals": {"hits": 10, "misses": 1,
                                      "compile_s": 0.5}}
    b = {"families": {"serving.ragged": {
             "hits": 4, "misses": 3, "compile_s": 1.5, "signatures": 4,
             "last_causes": [{"cause": "arg `tokens` dim0 9∉{8}: "
                                       "bucket miss"}]},
         "spec.rogue": {"hits": 0, "misses": 2, "compile_s": 0.1,
                        "signatures": 2, "last_causes": []}},
         "undeclared": ["spec.rogue"],
         "totals": {"hits": 4, "misses": 5, "compile_s": 1.6}}
    m = scrape.merge_compile_snapshots({"r0": a, "r1": b})
    assert m["instances"] == ["r0", "r1"]
    fam = m["families"]["serving.ragged"]
    assert (fam["hits"], fam["misses"]) == (14, 4)
    assert fam["compile_s"] == pytest.approx(2.0)
    assert fam["instances"] == ["r0", "r1"]
    assert {c["instance"] for c in fam["last_causes"]} == {"r0", "r1"}
    assert m["undeclared"] == {"spec.rogue": ["r1"]}
    assert m["totals"] == {"hits": 14, "misses": 6,
                           "compile_s": pytest.approx(2.1)}


# ---------------------------------------------------------------------------
# engine tier: warmup covers the declared inventory, steady state is
# miss-free
# ---------------------------------------------------------------------------

def _zero_miss_replay(eng, prompts, new_tokens):
    warm = eng.warmup_programs()
    assert warm, "warmup compiled nothing"
    snap = co.snapshot()
    base = snap["totals"]["misses"]
    assert base > 0, "warmup should pay the compiles up front"
    assert snap["undeclared"] == [], snap["undeclared"]
    _drive(eng, prompts, new_tokens)
    snap = co.snapshot()
    causes = {n: [c["cause"] for c in f["last_causes"]]
              for n, f in snap["families"].items() if f["last_causes"]}
    assert snap["totals"]["misses"] == base, causes
    assert snap["totals"]["hits"] > 0
    assert snap["undeclared"] == [], snap["undeclared"]
    # every declared family carries a warmup entry (inventory contract)
    missing = set(co.declared_families()) - set(co.warmup_entries())
    assert not missing, missing
    return snap


def test_mixed_replay_zero_post_warmup_misses(model):
    """Acceptance bar: after warmup_programs() a mixed prefill+decode
    replay re-enters warm programs only — zero observatory misses."""
    eng = ContinuousServingEngine(model, **ENGINE_KW)
    snap = _zero_miss_replay(eng, _prompts((13, 3, 21)), 3)
    assert snap["families"]["serving.ragged"]["hits"] > 0
    # a second warmup run is pure hits too (idempotent warm state)
    co.run_warmup()
    assert co.snapshot()["totals"]["misses"] \
        == snap["totals"]["misses"]


def test_spec_draft_replay_zero_post_warmup_misses(model):
    """Speculative decode with batched drafting stays inside the
    declared pow2 (rows, width) draft family after warmup."""
    eng = ContinuousServingEngine(
        model, max_batch_size=2, max_len=64, token_budget=16,
        prefill_chunk_tokens=16, spec_decode=True, spec_k=3,
        draft_model=model, draft_batch=True)
    snap = _zero_miss_replay(eng, _prompts((19, 9), seed=6), 6)
    assert eng.spec_drafted_tokens > 0
    assert snap["families"]["spec.draft_batch"]["hits"] > 0


def test_qblock_replay_zero_post_warmup_misses(model, monkeypatch):
    """The q-block ragged grid serves the same declared token-bucket
    family: warm replay is miss-free there too."""
    monkeypatch.setenv("PADDLE_TPU_RAGGED_IMPL", "qblock")
    eng = ContinuousServingEngine(model, **ENGINE_KW)
    snap = _zero_miss_replay(eng, _prompts((23, 5), seed=2), 3)
    assert eng.ragged_steps > 0
    assert snap["families"]["serving.ragged"]["hits"] > 0


def test_cold_request_ttft_decomposition(model, tmp_path):
    """Acceptance bar: a COLD request's TTFT decomposes into queue /
    compile / prefill spans, joined by trace id through log_query."""
    import log_query as lq

    rt.enable()
    rt.get_trace_store().clear()
    path = tmp_path / "events.jsonl"
    eventlog.enable(str(path))
    try:
        eng = ContinuousServingEngine(model, max_batch_size=2, max_len=48,
                                      prefill_chunk_tokens=16)
        with eng:                    # deliberately NO warmup: cold start
            eng.generate(_prompts((13,))[0], max_new_tokens=2,
                         timeout=300)
    finally:
        eventlog.disable()
    ids = rt.get_trace_store().trace_ids()
    assert len(ids) == 1
    rows = lq.query([str(path)], trace=ids[0])
    kinds = [r["kind"] for r in rows]
    for need in ("queue_wait", "compile", "prefill_chunk"):
        assert need in kinds, kinds
    # the compile span carries the observatory's attribution
    sp = next(r for r in rows if r["kind"] == "compile")
    assert sp["family"].startswith("serving.")
    assert sp["cause"]
    # the CLI join works on the same file
    assert lq.main([str(path), "--trace", ids[0],
                    "--kind", "queue_wait,compile,prefill_chunk"]) == 0
    # warm spans never emit compile records: warmup removes the tax
    co.reset()
    eventlog.enable(str(tmp_path / "warm.jsonl"))
    try:
        eng2 = ContinuousServingEngine(model, max_batch_size=2,
                                       max_len=48,
                                       prefill_chunk_tokens=16)
        eng2.warmup_programs()
        rt.get_trace_store().clear()
        with eng2:
            eng2.generate(_prompts((13,))[0], max_new_tokens=2,
                          timeout=300)
    finally:
        eventlog.disable()
    tid = rt.get_trace_store().trace_ids()[0]
    warm_rows = lq.query([str(tmp_path / "warm.jsonl")], trace=tid)
    assert "compile" not in [r["kind"] for r in warm_rows]


# ---------------------------------------------------------------------------
# compile_report CLI
# ---------------------------------------------------------------------------

def _write_events(path, rows):
    with open(path, "w") as f:
        for r in rows:
            f.write(json.dumps(r) + "\n")


def _miss(fam, cause, seconds=0.1, src="compile_observatory"):
    return {"ts": 1.0, "kind": "compile", "src": src, "family": fam,
            "cause": cause, "seconds": seconds, "signature": "x"}


def test_compile_report_fold_filters_and_render(tmp_path, capsys):
    import compile_report as cr

    path = tmp_path / "e.jsonl"
    _write_events(path, [
        _miss("serving.ragged", "new family"),
        _miss("serving.ragged", "arg `tokens` dim0 9∉{8,16}: bucket miss",
              seconds=0.4),
        # the request tracer's teed span copy must NOT double-count
        _miss("serving.ragged", "new family", src="trace"),
        {"ts": 1.0, "kind": "delivered", "trace_id": "t"},
    ])
    fams = cr.fold(cr.load_events(str(path)))
    assert fams["serving.ragged"]["compiles"] == 2
    assert fams["serving.ragged"]["compile_s"] == pytest.approx(0.5)
    assert fams["serving.ragged"]["causes"]["new family"] == 1
    assert cr.main([str(path)]) == 0
    out = capsys.readouterr().out
    assert "serving.ragged" in out and "bucket miss" in out
    # usage / unreadable-input errors exit 2
    assert cr.main([str(tmp_path / "missing.jsonl")]) == 2
    bad = tmp_path / "bad.jsonl"
    bad.write_text("{not json\n")
    assert cr.main([str(bad)]) == 2
    assert cr.main([]) == 2


def test_compile_report_diff_exit_codes(tmp_path, capsys):
    import compile_report as cr

    old = tmp_path / "old.jsonl"
    new = tmp_path / "new.jsonl"
    _write_events(old, [_miss("serving.ragged", "new family")])
    _write_events(new, [
        _miss("serving.ragged", "new family"),
        _miss("serving.ragged",
              "arg `tokens` dim0 17∉{8,16}: bucket miss"),
        _miss("serving.ragged",
              "arg `tokens` dim0 33∉{8,16}: bucket miss"),
    ])
    assert cr.main(["--diff", str(old), str(new)]) == 1
    out = capsys.readouterr().out
    assert "REGRESSED" in out and "bucket miss" in out
    # no growth -> clean exit; regressions list the NEW causes
    assert cr.main(["--diff", str(new), str(new)]) == 0
    regs = cr.diff_folds(cr.fold(cr.load_events(str(old))),
                         cr.fold(cr.load_events(str(new))))
    assert regs[0]["family"] == "serving.ragged"
    assert regs[0]["delta"] == 2
    assert any("bucket miss" in c for c in regs[0]["causes"])
    assert cr.main(["--diff", str(old)]) == 2


def test_compile_report_fleet_scrape(tmp_path, capsys):
    import compile_report as cr

    co.declare_family("serving.ragged", buckets={"tokens": [8]})
    co.observe("serving.ragged", _tok(8), seconds=0.01)
    co.observe("unit.rogue", _tok(3))
    with TelemetryServer(instance="f0", port=0) as srv:
        rc = cr.main(["--fleet", f"{srv.address},127.0.0.1:1"])
    assert rc == 0
    out = capsys.readouterr().out
    assert "serving.ragged" in out
    assert "DRIFT" in out and "unit.rogue" in out
    assert "UNREACHABLE: 127.0.0.1:1" in out
    # --fleet composes with neither log paths nor --diff
    assert cr.main(["--fleet", "h:1", "x.jsonl"]) == 2


def test_bench_compare_compile_directions():
    """serving_recompiles_per_1k_ticks / post-warmup misses / warmup
    compile seconds are all lower-better in the bench comparator."""
    import bench_compare as bc

    assert bc.direction_of("serving_recompiles_per_1k_ticks") == "lower"
    assert bc.direction_of("compile_post_warmup_misses") == "lower"
    assert bc.direction_of("serving_warmup_compile_s") == "lower"
    assert bc.direction_of("compile_observatory_overhead_pct") == "lower"
