"""Auto-parallel Engine (reference ``auto_parallel/static/engine.py``:
fit/evaluate/predict/save/load/cost — VERDICT.md round-2 §2.3 'static
Engine remains thin')."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.auto_parallel import Engine
from paddle_tpu.io import TensorDataset


class _MLP(nn.Layer):
    def __init__(self):
        super().__init__()
        self.l1 = nn.Linear(8, 32)
        self.l2 = nn.Linear(32, 1)

    def forward(self, x):
        return self.l2(paddle.tanh(self.l1(x)))


def _data(n=64):
    rng = np.random.RandomState(0)
    X = rng.randn(n, 8).astype(np.float32)
    W = rng.randn(8, 1).astype(np.float32)
    Y = (X @ W).astype(np.float32)
    return TensorDataset([paddle.to_tensor(X), paddle.to_tensor(Y)])


def test_engine_fit_evaluate_predict_roundtrip(tmp_path):
    mesh_mod.init_mesh({"dp": 8})
    try:
        paddle.seed(0)
        model = _MLP()
        eng = Engine(model=model, loss=nn.MSELoss(),
                     optimizer=paddle.optimizer.Adam(
                         learning_rate=0.01, parameters=model.parameters()))
        eng.prepare()
        ds = _data()
        hist = eng.fit(ds, epochs=6, batch_size=16)
        assert hist[-1] < hist[0] * 0.5, (hist[0], hist[-1])
        ev = eng.evaluate(ds, batch_size=16)
        assert ev["loss"] == pytest.approx(hist[-1], rel=1.0)
        assert ev["loss"] < hist[0]
        x = paddle.to_tensor(np.ones((4, 8), np.float32))
        out = eng.predict(x)
        assert tuple(out.shape) == (4, 1)

        # save -> perturb -> load restores the trained state exactly
        path = str(tmp_path / "engine_ckpt.npz")
        eng.save(path)
        before = np.asarray(eng._state["p"][0])
        eng._state["p"] = [a * 0 for a in eng._state["p"]]
        eng.load(path)
        np.testing.assert_array_equal(np.asarray(eng._state["p"][0]), before)
        ev2 = eng.evaluate(ds, batch_size=16, steps=2)
        assert np.isfinite(ev2["loss"])
    finally:
        mesh_mod.reset_mesh()


def test_engine_cost_reports_current_mesh():
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    mesh_mod.init_mesh({"dp": 4, "mp": 2})
    try:
        eng = Engine(model=LlamaForCausalLM(llama_tiny()))
        c = eng.cost(seq_len=128, global_batch=8, chip="v5e")
        assert c["degrees"]["dp"] == 4 and c["degrees"]["mp"] == 2
        assert c["step_time_s"] > 0 and c["mem_per_chip"] > 0
        assert "compute_s" in c
    finally:
        mesh_mod.reset_mesh()
