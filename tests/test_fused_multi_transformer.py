"""FusedMultiTransformer (scan-over-stacked-layers serving block) vs a
straightforward per-layer oracle; prefill+decode cache parity (reference
test pattern: ``test_fused_multi_transformer_op.py``)."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import paddle_tpu as paddle
from paddle_tpu.incubate.nn import FusedMultiTransformer

B, S, D, H, KV, F, L = 2, 6, 32, 4, 2, 64, 3
HD = D // H


def _mk():
    paddle.seed(0)
    return FusedMultiTransformer(
        embed_dim=D, num_heads=H, dim_feedforward=F, num_layers=L,
        num_key_value_heads=KV, activation="gelu")


def _oracle(blk, x):
    """Plain python-loop reimplementation of the same math."""
    def ln(x, s, b):
        mu = x.mean(-1, keepdims=True)
        var = x.var(-1, keepdims=True)
        return (x - mu) / np.sqrt(var + blk.epsilon) * s + b

    x = np.asarray(x, np.float64)
    g = H // KV
    p = {k: np.asarray(v.numpy(), np.float64)
         for k, v in blk.state_dict().items()}
    for i in range(L):
        y = ln(x, p["ln_scale"][i], p["ln_bias"][i])
        qkv = y @ p["qkv_weight"][i] + p["qkv_bias"][i]
        q = qkv[..., :H * HD].reshape(B, -1, H, HD)
        k = qkv[..., H * HD:H * HD + KV * HD].reshape(B, -1, KV, HD)
        v = qkv[..., H * HD + KV * HD:].reshape(B, -1, KV, HD)
        s = q.shape[1]
        o = np.zeros((B, s, H, HD))
        for b in range(B):
            for h in range(H):
                kh = h // g
                logits = (q[b, :, h] @ k[b, :, kh].T) / np.sqrt(HD)
                mask = np.tril(np.ones((s, s), bool))
                logits = np.where(mask, logits, -np.inf)
                w = np.exp(logits - logits.max(-1, keepdims=True))
                w = w / w.sum(-1, keepdims=True)
                o[b, :, h] = w @ v[b, :, kh]
        x = x + o.reshape(B, s, H * HD) @ p["linear_weight"][i] \
            + p["linear_bias"][i]
        y2 = ln(x, p["ffn_ln_scale"][i], p["ffn_ln_bias"][i])
        h1 = y2 @ p["ffn1_weight"][i] + p["ffn1_bias"][i]
        h1 = 0.5 * h1 * (1 + np.vectorize(_erf)(h1 / np.sqrt(2)))
        x = x + h1 @ p["ffn2_weight"][i] + p["ffn2_bias"][i]
    return x


def _erf(v):
    import math
    return math.erf(v)


def test_matches_per_layer_oracle():
    blk = _mk()
    rng = np.random.RandomState(1)
    x = rng.randn(B, S, D).astype(np.float32) * 0.5
    out = blk(paddle.to_tensor(x))
    ref = _oracle(blk, x)
    np.testing.assert_allclose(np.asarray(out.numpy()), ref,
                               rtol=2e-3, atol=2e-3)


def test_prefill_decode_cache_parity():
    """Prefill + N cached decode steps == one uncached full forward."""
    blk = _mk()
    rng = np.random.RandomState(2)
    full = rng.randn(B, S, D).astype(np.float32) * 0.5
    prompt, rest = full[:, :3], full[:, 3:]

    # uncached oracle over the full sequence
    want = np.asarray(blk(paddle.to_tensor(full)).numpy())

    caches = blk.init_cache(B, max_len=16)
    out_p, caches = blk(paddle.to_tensor(prompt), caches=caches)
    np.testing.assert_allclose(np.asarray(out_p.numpy()), want[:, :3],
                               rtol=2e-3, atol=2e-3)
    for t in range(rest.shape[1]):
        tok = rest[:, t:t + 1]
        out_t, caches = blk(paddle.to_tensor(tok), caches=caches,
                            time_step=3 + t)
        np.testing.assert_allclose(np.asarray(out_t.numpy()),
                                   want[:, 3 + t:4 + t],
                                   rtol=2e-3, atol=2e-3)


def test_trains_through_tape():
    blk = _mk()
    rng = np.random.RandomState(3)
    x = paddle.to_tensor(rng.randn(B, S, D).astype(np.float32) * 0.5)
    out = blk(x)
    out.mean().backward()
    g = blk.qkv_weight.grad
    assert g is not None
    assert np.isfinite(np.asarray(g.numpy())).all()
    assert float(np.abs(np.asarray(g.numpy())).sum()) > 0


def test_jits_under_to_static():
    blk = _mk()
    rng = np.random.RandomState(4)
    x = paddle.to_tensor(rng.randn(B, S, D).astype(np.float32) * 0.5)
    eager = np.asarray(blk(x).numpy())
    static = paddle.jit.to_static(blk)
    np.testing.assert_allclose(np.asarray(static(x).numpy()), eager,
                               rtol=1e-5, atol=1e-5)


def test_attn_mask_shapes():
    """4-D [b,1,q,s] and 3-D [b,q,s] masks broadcast correctly per batch
    row (the reference's documented mask shapes)."""
    blk = _mk()
    rng = np.random.RandomState(5)
    x = rng.randn(B, S, D).astype(np.float32) * 0.5
    # block position 0 for row 0 only; row 1 unmasked
    m3 = np.zeros((B, S, S), np.float32)
    m3[0, :, 0] = -1e9
    out3 = np.asarray(blk(paddle.to_tensor(x),
                          attn_mask=paddle.to_tensor(m3)).numpy())
    out_plain = np.asarray(blk(paddle.to_tensor(x)).numpy())
    m4 = m3[:, None]
    out4 = np.asarray(blk(paddle.to_tensor(x),
                          attn_mask=paddle.to_tensor(m4)).numpy())
    np.testing.assert_allclose(out3, out4, rtol=1e-5, atol=1e-6)
    # row 1 must be untouched by row 0's mask
    np.testing.assert_allclose(out3[1], out_plain[1], rtol=1e-5, atol=1e-6)
    # row 0 (beyond pos 0, which attends to itself only) must differ
    assert np.abs(out3[0, 1:] - out_plain[0, 1:]).max() > 1e-4


def test_fused_functional_shims():
    """incubate.nn.functional fused_* API-parity shims compute the same
    math as the composed ops (XLA provides the fusion on TPU)."""
    import paddle_tpu.incubate.nn.functional as IF
    rng = np.random.RandomState(0)
    x = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
    w = paddle.to_tensor(rng.randn(8, 6).astype(np.float32))
    b = paddle.to_tensor(rng.randn(6).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(IF.fused_linear(x, w, b).numpy()),
        x.numpy() @ w.numpy() + b.numpy(), rtol=1e-5)
    np.testing.assert_allclose(
        np.asarray(IF.fused_linear_activation(x, w, b,
                                              activation="relu").numpy()),
        np.maximum(x.numpy() @ w.numpy() + b.numpy(), 0), rtol=1e-5)
    y = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
    np.testing.assert_allclose(
        np.asarray(IF.fused_dropout_add(x, y, p=0.0).numpy()),
        x.numpy() + y.numpy(), rtol=1e-6)
    h = x.numpy() + y.numpy()
    want = ((h - h.mean(-1, keepdims=True))
            / np.sqrt(h.var(-1, keepdims=True) + 1e-5))
    got = IF.fused_bias_dropout_residual_layer_norm(
        x, y, ln_scale=paddle.to_tensor(np.ones(8, np.float32)),
        dropout_rate=0.0)
    np.testing.assert_allclose(np.asarray(got.numpy()), want, rtol=1e-4,
                               atol=1e-5)
    # dropout path differentiates
    xt = paddle.to_tensor(rng.randn(4, 8).astype(np.float32))
    xt.stop_gradient = False
    paddle.seed(3)
    IF.fused_dropout_add(xt, y, p=0.4).sum().backward()
    assert np.isfinite(np.asarray(xt.grad.numpy())).all()


def test_fused_feedforward_and_linear():
    """FusedFeedForward matches the hand-composed FFN chain; FusedLinear
    honors transpose_weight."""
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.nn.functional as F
    from paddle_tpu.incubate.nn import FusedFeedForward, FusedLinear
    from paddle_tpu.incubate.nn.functional import (fused_feedforward,
                                                   fused_linear)

    paddle.seed(3)
    x = paddle.to_tensor(np.random.randn(2, 5, 8).astype("float32"))
    ffn = FusedFeedForward(8, 16, dropout_rate=0.0, act_dropout_rate=0.0)
    ffn.eval()
    out = ffn(x)
    # manual chain (post-LN variant)
    h = F.linear(x, ffn.linear1_weight, ffn.linear1_bias)
    h = F.relu(h)
    h = F.linear(h, ffn.linear2_weight, ffn.linear2_bias)
    from paddle_tpu.nn.functional.norm import layer_norm
    want = layer_norm(x + h, 8, weight=ffn.ln2_scale, bias=ffn.ln2_bias)
    np.testing.assert_allclose(out.numpy(), want.numpy(), atol=1e-5)

    # pre-LN variant changes the result
    ffn2 = FusedFeedForward(8, 16, dropout_rate=0.0, normalize_before=True)
    ffn2.eval()
    assert not np.allclose(ffn2(x).numpy(), out.numpy())

    lin = FusedLinear(8, 4, transpose_weight=True)
    assert list(lin.weight.shape) == [4, 8]
    got = lin(x)
    want = x.numpy() @ lin.weight.numpy().T + lin.bias.numpy()
    np.testing.assert_allclose(got.numpy(), want, atol=1e-5)

    # grads flow through the functional
    x.stop_gradient = False
    loss = fused_feedforward(
        x, ffn.linear1_weight, ffn.linear1_bias, ffn.linear2_weight,
        ffn.linear2_bias, dropout1_rate=0.0, dropout2_rate=0.0,
        ln2_scale=ffn.ln2_scale, ln2_bias=ffn.ln2_bias).sum()
    loss.backward()
    assert x.grad is not None


def test_sparse_softmax():
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.sparse as sparse

    dense = np.array([[1.0, 0.0, 2.0], [0.0, 3.0, 0.0]], "float32")
    coo = sparse.sparse_coo_tensor(np.nonzero(dense),
                                   dense[dense != 0], shape=[2, 3])
    sm = sparse.softmax(coo)
    out = sm.to_dense().numpy()
    # row 0 normalizes over {1, 2} only; zero pattern preserved
    e = np.exp(np.array([1.0, 2.0]) - 2.0)
    np.testing.assert_allclose(out[0, [0, 2]], e / e.sum(), atol=1e-6)
    assert out[0, 1] == 0.0
    np.testing.assert_allclose(out[1], [0.0, 1.0, 0.0], atol=1e-6)
