"""KV-cache generation tests (in-repo PaddleNLP-equivalent decode;
SURVEY.md §2.4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.models import LlamaForCausalLM, llama_tiny, GPTForCausalLM, gpt_tiny
from paddle_tpu.models.generation import KVCache


def _ids(b=2, s=5, vocab=128, seed=0):
    return paddle.to_tensor(
        np.random.default_rng(seed).integers(0, vocab, (b, s)), "int64")


def test_cached_matches_uncached_greedy():
    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny())
    ids = _ids()
    out_cached = model.generate(ids, max_new_tokens=6)

    model.supports_cache = False          # force full-recompute path
    out_full = model.generate(ids, max_new_tokens=6)
    model.supports_cache = True
    np.testing.assert_array_equal(out_cached.numpy(), out_full.numpy())
    assert out_cached.shape == [2, 11]
    # prompt is preserved
    np.testing.assert_array_equal(out_cached.numpy()[:, :5], ids.numpy())


def test_cache_incremental_forward_matches_full():
    """Prefill + 1-token decode logits == full forward logits."""
    paddle.seed(1)
    model = LlamaForCausalLM(llama_tiny())
    model.eval()
    ids = _ids(b=1, s=6, seed=3)
    full_logits = model(ids).numpy()

    cache = KVCache()
    pre = model(paddle.to_tensor(ids.numpy()[:, :5]), cache=cache)
    step = model(paddle.to_tensor(ids.numpy()[:, 5:6]), cache=cache)
    np.testing.assert_allclose(step.numpy()[:, 0], full_logits[:, 5],
                               rtol=1e-4, atol=1e-4)
    assert cache.pos == 6


def test_sampling_and_eos():
    paddle.seed(2)
    model = LlamaForCausalLM(llama_tiny())
    ids = _ids(b=2, s=3, seed=5)
    out = model.generate(ids, max_new_tokens=5, do_sample=True, top_k=10,
                         temperature=0.8)
    assert out.shape == [2, 8]
    v = model.config.vocab_size
    assert out.numpy().min() >= 0 and out.numpy().max() < v

    # eos stops generation (force eos = whatever greedy produces first)
    g = model.generate(ids, max_new_tokens=1)
    eos = int(g.numpy()[0, -1])
    out2 = model.generate(ids, max_new_tokens=8, eos_token_id=eos)
    # batch row 0 hit eos on step 1 → all later tokens are eos
    row = out2.numpy()[0, 3:]
    assert row[0] == eos


def test_gpt_generate_cached_matches_uncached():
    paddle.seed(3)
    model = GPTForCausalLM(gpt_tiny())
    ids = _ids(b=1, s=4, vocab=model.config.vocab_size, seed=7)
    out = model.generate(ids, max_new_tokens=3)
    assert out.shape == [1, 7]
    model.supports_cache = False
    out_full = model.generate(ids, max_new_tokens=3)
    model.supports_cache = True
    np.testing.assert_array_equal(out.numpy(), out_full.numpy())


# ---------------------------------------------------------------------------
# beam search (reference decode_strategy="beam_search")
# ---------------------------------------------------------------------------

def _seq_logprob(model, seq, prompt_len):
    """Sum of per-token log-probs the model assigns to seq's generated
    part (teacher forcing)."""
    import jax.numpy as jnp
    import jax
    logits = model(paddle.to_tensor(seq[None, :-1]))._data.astype("float32")
    lp = jax.nn.log_softmax(logits, axis=-1)[0]
    tgt = jnp.asarray(seq[1:])
    tok = jnp.take_along_axis(lp, tgt[:, None], axis=-1)[:, 0]
    return float(tok[prompt_len - 1:].sum())


def test_beam1_matches_greedy():
    paddle.seed(4)
    model = LlamaForCausalLM(llama_tiny())
    ids = _ids(b=2, s=4, seed=7)
    greedy = model.generate(ids, max_new_tokens=5)
    beam1 = model.generate(ids, max_new_tokens=5, num_beams=1)
    np.testing.assert_array_equal(greedy.numpy(), beam1.numpy())


def test_beam_search_finds_no_worse_sequences():
    paddle.seed(5)
    model = LlamaForCausalLM(llama_tiny())
    model.eval()
    ids = _ids(b=2, s=4, seed=9)
    greedy = model.generate(ids, max_new_tokens=6).numpy()
    beams = model.generate(ids, max_new_tokens=6, num_beams=4).numpy()
    assert beams.shape == greedy.shape
    np.testing.assert_array_equal(beams[:, :4], ids.numpy())
    for r in range(2):
        g = _seq_logprob(model, greedy[r], 4)
        b = _seq_logprob(model, beams[r], 4)
        assert b >= g - 1e-4, (r, b, g)


def test_beam_search_cache_matches_uncached():
    paddle.seed(6)
    model = LlamaForCausalLM(llama_tiny())
    ids = _ids(b=2, s=3, seed=11)
    cached = model.generate(ids, max_new_tokens=5, num_beams=3).numpy()
    model.supports_cache = False
    full = model.generate(ids, max_new_tokens=5, num_beams=3).numpy()
    model.supports_cache = True
    np.testing.assert_array_equal(cached, full)


def test_beam_search_rejects_sampling():
    paddle.seed(7)
    model = LlamaForCausalLM(llama_tiny())
    with pytest.raises(ValueError, match="do_sample"):
        model.generate(_ids(), num_beams=2, do_sample=True)
