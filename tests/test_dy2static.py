"""dy2static control-flow conversion (VERDICT.md round-3 item 4;
reference: ``python/paddle/jit/dy2static/transformers/`` ifelse→cond,
while→while_loop — SURVEY.md §2.2, §3.2).

A ``@to_static`` function with a data-dependent Python ``if``/``while``
must STAY COMPILED: the first graph break triggers the AST converter,
re-tracing the branch through ``lax.cond``/``lax.while_loop`` instead of
latching the whole function to eager. The graph-break counter and the
entry's ``converted``/``fallback`` flags are the observable contract.
"""
import warnings

import jax
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.jit import dy2static
from paddle_tpu.jit.api import StaticFunction


def _entries(sf):
    assert isinstance(sf, StaticFunction)
    return list(sf._cache.values())


# ---------------------------------------------------------------------------
# converter unit level
# ---------------------------------------------------------------------------

def test_convert_ifelse_python_semantics_preserved():
    def f(x, flag):
        if flag:           # python bool — must stay single-arm
            y = x + 1
        else:
            y = x - 1
        return y

    conv = dy2static.convert_function(f)
    x = paddle.to_tensor([1.0, 2.0])
    np.testing.assert_allclose(conv(x, True).numpy(), [2.0, 3.0])
    np.testing.assert_allclose(conv(x, False).numpy(), [0.0, 1.0])


def test_convert_while_python_semantics_preserved():
    def f(n):
        i, acc = 0, 0
        while i < n:       # python ints
            acc += i
            i += 1
        return acc

    conv = dy2static.convert_function(f)
    assert conv(5) == (0 + 1 + 2 + 3 + 4)


def test_convert_no_control_flow_raises():
    def f(x):
        return x + 1

    with pytest.raises(dy2static.ConversionUnsupported):
        dy2static.convert_function(f)


def test_converted_code_exposes_rewrite():
    def f(x):
        if x.sum() > 0:
            y = x
        else:
            y = -x
        return y

    src = dy2static.converted_code(f)
    assert "_jst_if" in src


# ---------------------------------------------------------------------------
# to_static integration: data-dependent branch stays compiled
# ---------------------------------------------------------------------------

def test_data_dependent_if_stays_compiled():
    @paddle.jit.to_static
    def f(x):
        if x.sum() > 0:
            y = x * 2
        else:
            y = x - 1
        return y

    xp = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    xn = paddle.to_tensor(np.array([-1.0, -2.0], np.float32))
    with warnings.catch_warnings():
        warnings.simplefilter("error")     # any graph-break warn = failure
        np.testing.assert_allclose(f(xp).numpy(), [2.0, 4.0])
        np.testing.assert_allclose(f(xn).numpy(), [-2.0, -3.0])
    (entry,) = _entries(f)
    assert entry["converted"] is True
    assert entry["fallback"] is False and entry["breaks"] == 0


def test_data_dependent_if_grads_match_eager():
    def raw(x):
        if x.sum() > 0:
            y = x * x
        else:
            y = x * 3.0
        return y.sum()

    sf = paddle.jit.to_static(raw)
    for sign in (1.0, -1.0):
        x = paddle.to_tensor(np.array([sign, 2 * sign], np.float32),
                             stop_gradient=False)
        out = sf(x)
        out.backward()
        g_static = x.grad.numpy().copy()
        x2 = paddle.to_tensor(np.array([sign, 2 * sign], np.float32),
                              stop_gradient=False)
        raw(x2).backward()
        np.testing.assert_allclose(g_static, x2.grad.numpy(), rtol=1e-6)


def test_data_dependent_while_stays_compiled():
    @paddle.jit.to_static
    def f(x):
        # double until the sum crosses 100 — tensor condition + python
        # counter promoted into the carry
        steps = 0
        while x.sum() < 100.0:
            x = x * 2
            steps = steps + 1
        return x, steps

    x = paddle.to_tensor(np.array([1.0, 2.0], np.float32))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out, steps = f(x)
    # 3.0 * 2^6 = 192 >= 100; 2^5*3 = 96 < 100
    assert int(steps.numpy()) == 6
    np.testing.assert_allclose(out.numpy(), [64.0, 128.0])
    (entry,) = _entries(f)
    assert entry["converted"] is True and entry["fallback"] is False


def test_layer_with_branch_stays_compiled():
    class Gate(nn.Layer):
        def __init__(self):
            super().__init__()
            self.lin = nn.Linear(4, 4)

        def forward(self, x):
            h = self.lin(x)
            if h.mean() > 0:
                out = h * 2
            else:
                out = -h
            return out

    net = paddle.jit.to_static(Gate())
    x = paddle.to_tensor(np.ones((2, 4), np.float32))
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        y = net(x)
    assert y.shape == [2, 4]
    (entry,) = _entries(net.forward)
    assert entry["converted"] is True and entry["fallback"] is False


def test_second_spec_skips_doomed_plain_trace():
    @paddle.jit.to_static
    def f(x):
        if x.sum() > 0:
            y = x * 2
        else:
            y = x - 1
        return y

    f(paddle.to_tensor(np.ones((2,), np.float32)))
    f(paddle.to_tensor(np.ones((3,), np.float32)))     # new input spec
    entries = _entries(f)
    assert len(entries) == 2
    assert all(e["converted"] for e in entries)
    assert all(not e["fallback"] for e in entries)


def test_unconvertible_still_falls_back_eager():
    @paddle.jit.to_static
    def f(x):
        if x.sum() > 0:       # return inside the branch: not converted
            return x * 2
        return x - 1

    x = paddle.to_tensor(np.ones((2,), np.float32))
    with pytest.warns(UserWarning, match="graph break"):
        out = f(x)
    np.testing.assert_allclose(out.numpy(), [2.0, 2.0])


def test_factory_closures_do_not_share_conversion():
    def make(k):
        def f(x):
            if x.sum() > 0:
                y = x * k
            else:
                y = x
            return y
        return f

    c2 = dy2static.convert_function(make(2.0))
    c3 = dy2static.convert_function(make(3.0))
    x = paddle.to_tensor(np.array([1.0], np.float32))
    np.testing.assert_allclose(c2(x).numpy(), [2.0])
    np.testing.assert_allclose(c3(x).numpy(), [3.0])


def test_raise_in_branch_keeps_eager_semantics():
    @paddle.jit.to_static
    def f(x):
        if (x != x).any():        # NaN check guarding a raise
            raise ValueError("nan input")
        y = x * 2
        return y

    x = paddle.to_tensor(np.ones((2,), np.float32))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")   # eager fallback is expected here
        out = f(x)                         # must NOT raise on clean input
    np.testing.assert_allclose(out.numpy(), [2.0, 2.0])
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(ValueError, match="nan"):
            f(paddle.to_tensor(np.array([np.nan, 1.0], np.float32)))


def test_nested_if_inside_tensor_if():
    @paddle.jit.to_static
    def f(x):
        if x.sum() > 0:
            y = x * 2
            if x.max() > 10:      # nested tensor condition
                y = y + 100
            else:
                y = y - 1
        else:
            y = -x
        return y

    with warnings.catch_warnings():
        warnings.simplefilter("error")
        out = f(paddle.to_tensor(np.array([1.0, 2.0], np.float32)))
        np.testing.assert_allclose(out.numpy(), [1.0, 3.0])
        out = f(paddle.to_tensor(np.array([20.0, 2.0], np.float32)))
        np.testing.assert_allclose(out.numpy(), [140.0, 104.0])
        out = f(paddle.to_tensor(np.array([-1.0, -2.0], np.float32)))
        np.testing.assert_allclose(out.numpy(), [1.0, 2.0])
    (entry,) = _entries(f)
    assert entry["converted"] is True and entry["fallback"] is False


def test_in_trace_grad_through_converted_branch():
    """paddle.grad INSIDE the @to_static function must differentiate
    through the converted lax.cond (the tape records one cond node with
    edges to every operand, including names the arms only read)."""
    def g(x):
        if x.sum() > 0:
            y = x * x
        else:
            y = x * 3.0
        gx = paddle.grad([y.sum()], [x], create_graph=False)[0]
        return (y + gx).sum()

    sf = paddle.jit.to_static(g)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        for arr in (np.array([1.0, 2.0], np.float32),
                    np.array([-1.0, -2.0], np.float32),
                    np.ones((3,), np.float32)):       # second spec too
            x = paddle.to_tensor(arr, stop_gradient=False)
            got = float(sf(x).numpy())
            want = float(np.sum(arr * arr + 2 * arr)) if arr.sum() > 0 \
                else float(np.sum(arr * 3.0 + 3.0))
            np.testing.assert_allclose(got, want, rtol=1e-5)
    assert all(e["converted"] and not e["fallback"]
               for e in sf._cache.values())


def test_mismatched_branch_shapes_error_is_clear():
    @paddle.jit.to_static
    def f(x):
        if x.sum() > 0:
            y = x
        else:
            y = x[:1]          # different shape — must raise, not silently
        return y

    x = paddle.to_tensor(np.ones((4,), np.float32))
    with warnings.catch_warnings():
        warnings.simplefilter("ignore")
        with pytest.raises(Exception, match="branch|shape"):
            try:
                f(x)
            except Exception:
                raise
            else:              # eager fallback would mask the mismatch
                raise AssertionError("expected an error")
