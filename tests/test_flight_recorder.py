"""Distributed flight recorder (ISSUE 3): ring buffer, collective seq
tracking, watchdog stall dumps, cross-rank aggregation/desync/straggler
reports, trace merging, and the disabled-path overhead guard."""
import json
import multiprocessing as mp
import os
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import simulator
from paddle_tpu.distributed import collective as coll
from paddle_tpu.distributed.fleet.elastic.tcp_kv import MemKVStore
from paddle_tpu.profiler import flight_recorder as flight


@pytest.fixture(autouse=True)
def _clean_recorder():
    flight.disable()
    flight.reset()
    yield
    flight.disable()
    flight.reset()


# ---------------------------------------------------------------------------
# ring buffer + gating
# ---------------------------------------------------------------------------


def test_disabled_is_noop_and_ring_is_bounded():
    assert not flight.is_enabled()
    assert flight.record_event("x") is None
    assert flight.collective_begin("all_reduce", 64, (0, 1)) is None
    flight.collective_end(None)          # tolerated
    flight.heartbeat()                   # tolerated
    fr = flight.enable(capacity=16)
    try:
        for i in range(40):
            flight.record_event("probe", i=i)
        evs = fr.events(kind="probe")
        assert len(evs) == 16                      # bounded
        assert [e["i"] for e in evs] == list(range(24, 40))  # newest kept
        assert all(e["rank"] == 0 and "t" in e for e in evs)
    finally:
        flight.disable()


def test_collective_seq_tracking_in_4rank_sim():
    flight.enable()

    def worker():
        t = paddle.to_tensor(np.ones(8, np.float32))
        dist.all_reduce(t)
        lst = []
        dist.all_gather(lst, t)
        dist.barrier()
        return True

    assert all(simulator.run(worker, 4))
    by_rank = flight.get_flight_recorder().collective_events(by_rank=True)
    assert sorted(by_rank) == [0, 1, 2, 3]
    for r in range(4):
        evs = by_rank[r]
        assert [e["seq"] for e in evs] == [1, 2, 3]   # monotonic per rank
        assert [e["op"] for e in evs] == ["all_reduce", "all_gather",
                                          "barrier"]
        assert evs[0]["bytes"] == 32
        for e in evs:
            assert e["t_exit"] is not None and e["t_exit"] >= e["t_enter"]
            assert e["group"] == [0, 1, 2, 3]
    # nothing left in flight after a clean run
    assert not flight.get_flight_recorder()._inflight


# ---------------------------------------------------------------------------
# desync + straggler analysis
# ---------------------------------------------------------------------------


def test_skipped_collective_yields_seq_mismatch_naming_rank_and_seq():
    """Rank 2 'skips' the third collective — it meets its peers at the
    transport level (so the run completes) but never through the tracked
    API, the realistic shape of a rank wandering down a different code
    path. The report must name rank 2 and seq 3."""
    flight.enable()

    def worker():
        r = dist.get_rank()
        t = paddle.to_tensor(np.ones(4, np.float32))
        dist.all_reduce(t)
        dist.all_reduce(t)
        g = coll._get_default_group()
        if r == 2:
            coll._exchange("all_reduce", np.ones(4, np.float32), g)
        else:
            dist.all_reduce(t)
        return True

    assert all(simulator.run(worker, 4))
    by_rank = flight.get_flight_recorder().collective_events(by_rank=True)
    rep = flight.desync_report(by_rank, world=range(4))
    assert rep["frontier_seq"] == 3
    assert rep["last_seq"][2] == 2
    assert len(rep["stalled"]) == 1
    s = rep["stalled"][0]
    assert s["rank"] == 2 and s["missing_seq"] == 3
    assert s["op"] == "all_reduce"
    assert s["entered_by"] == [0, 1, 3]


def test_desync_report_flags_op_and_byte_mismatch():
    evs = {
        0: [{"seq": 1, "op": "all_reduce", "bytes": 64, "t_enter": 0.0}],
        1: [{"seq": 1, "op": "all_gather", "bytes": 128, "t_enter": 0.0}],
    }
    rep = flight.desync_report(evs)
    assert rep["stalled"] == []
    assert len(rep["mismatches"]) == 1
    m = rep["mismatches"][0]
    assert m["seq"] == 1
    assert m["detail"][0]["op"] == "all_reduce"
    assert m["detail"][1]["op"] == "all_gather"


def test_straggler_report_names_slowest_rank():
    evs = {r: [{"seq": s, "op": "all_reduce", "bytes": 8,
                "t_enter": s * 1.0 + (0.2 if r == 1 else 0.0)}
               for s in range(1, 6)]
           for r in range(3)}
    rep = flight.straggler_report(evs)
    assert rep["n_seqs"] == 5
    assert rep["slowest_rank"] == 1
    assert rep["per_rank_lag"][1]["mean_s"] == pytest.approx(0.2)
    assert rep["by_op"]["all_reduce"]["slowest_rank"] == 1
    assert rep["skew_percentiles"]["p50"] == pytest.approx(0.2)


# ---------------------------------------------------------------------------
# acceptance: watchdog catches an artificially stalled rank
# ---------------------------------------------------------------------------


def test_watchdog_dumps_stalled_4rank_run(tmp_path):
    """ISSUE 3 acceptance: 4 simulated ranks, rank 3 stalls before the
    last collective. Without manual intervention the watchdog must
    produce per-rank dump files (thread stacks + last-N collective
    events) and a cross-rank report naming rank 3 and the seq it never
    entered. (The disabled-path half of the criterion is
    test_disabled_recorder_adds_no_step_cost.)"""
    dump_dir = str(tmp_path / "dumps")
    flight.enable(watchdog=True, deadline_s=0.5, poll_s=0.05,
                  dump_dir=dump_dir)
    M = 4

    def worker():
        r = dist.get_rank()
        t = paddle.to_tensor(np.ones(8, np.float32))
        for _ in range(M - 1):
            dist.all_reduce(t)
        if r == 3:
            time.sleep(2.0)          # the artificial stall
        dist.all_reduce(t)
        return True

    assert all(simulator.run(worker, 4))
    wd = flight.get_watchdog()
    assert wd is not None and wd.last_dump is not None, \
        "watchdog never fired during the stall"
    flight.disable()

    for r in range(4):
        path = os.path.join(dump_dir, f"flight_rank{r}.json")
        assert os.path.exists(path), f"missing per-rank dump for rank {r}"
        with open(path) as f:
            d = json.load(f)
        assert d["schema"] == flight.DUMP_SCHEMA and d["rank"] == r
        assert d["thread_stacks"], "dump must carry all-thread stacks"
        assert d["collectives"], "dump must carry recent collective events"
        assert "metrics" in d and "state" in d
        assert d["deadline_s"] == 0.5

    with open(os.path.join(dump_dir, "flight_cross_report.json")) as f:
        rep = json.load(f)
    assert rep["schema"] == flight.REPORT_SCHEMA
    stalled = rep["desync"]["stalled"]
    assert [s["rank"] for s in stalled] == [3]
    assert stalled[0]["missing_seq"] == M          # the seq it never entered
    assert stalled[0]["op"] == "all_reduce"
    assert rep["stalled_heartbeat_ranks"]          # heartbeats went stale
    assert "straggler" in rep


def test_watchdog_check_latches_until_heartbeat_resumes(tmp_path):
    fr = flight.enable()
    fr.heartbeat(rank=0)
    wd = flight.Watchdog(fr, deadline_s=0.02, dump_dir=str(tmp_path))
    time.sleep(0.05)
    assert wd.check() == [0]
    first = wd.last_dump
    assert first is not None
    assert wd.check() == [0]
    assert wd.last_dump is first      # latched: one dump per stall episode
    fr.heartbeat(rank=0)
    assert wd.check() == []           # re-armed
    time.sleep(0.05)
    assert wd.check() == [0]
    assert wd.last_dump is not first


def test_watchdog_writes_metrics_text_for_tpu_watch(tmp_path):
    from paddle_tpu.profiler.telemetry import get_registry
    get_registry().counter("flight_probe_total", "probe").inc()
    path = str(tmp_path / "metrics.prom")
    wd = flight.Watchdog(flight.get_flight_recorder(), deadline_s=60,
                         metrics_text_path=path)
    wd.write_metrics_text()
    with open(path) as f:
        text = f.read()
    assert "flight_probe_total" in text and "# TYPE" in text


# ---------------------------------------------------------------------------
# cross-rank aggregation over the elastic KV store
# ---------------------------------------------------------------------------


def test_gather_metrics_rank_labeled_over_kv_store():
    flight.enable()
    store = MemKVStore()

    def worker():
        t = paddle.to_tensor(np.ones(4, np.float32))
        dist.all_reduce(t)
        dist.all_reduce(t)
        flight.publish_snapshot(store)
        return True

    assert all(simulator.run(worker, 4))
    snaps = flight.gather_snapshots(store)
    assert sorted(snaps) == [0, 1, 2, 3]           # rank-labeled snapshots
    for r, s in snaps.items():
        assert s["rank"] == r and s["last_seq"] == 2
        assert [e["seq"] for e in s["collectives"]] == [1, 2]

    g = flight.gather_metrics(store)
    assert g["ranks"] == [0, 1, 2, 3]
    assert g["last_seq"] == {r: 2 for r in range(4)}
    fam = g["merged"]["paddle_comm_collectives_total"]
    assert fam["label_names"][0] == "rank"         # one registry view,
    for r in range(4):                             # rank as leading label
        assert f"{r},all_reduce" in fam["series"]
    assert g["desync"]["stalled"] == []
    assert g["straggler"]["n_seqs"] == 2


def test_gather_metrics_local_fallback_without_store():
    flight.enable()
    flight.record_event("probe")
    g = flight.gather_metrics()
    assert g["ranks"] == [0]
    assert isinstance(g["merged"], dict)


# ---------------------------------------------------------------------------
# chrome trace merging + trace_merge CLI
# ---------------------------------------------------------------------------


def _fake_trace(name):
    return {"traceEvents": [
        {"name": name, "ph": "X", "pid": 777, "tid": 0, "ts": 1.0,
         "dur": 5.0, "args": {}},
        {"name": name + "_b", "ph": "X", "pid": 777, "tid": 1, "ts": 2.0,
         "dur": 1.0, "args": {}},
    ], "displayTimeUnit": "ms"}


def test_merge_chrome_traces_one_pid_per_rank(tmp_path):
    p1 = tmp_path / "rank1.trace.json"
    p1.write_text(json.dumps(_fake_trace("r1")))
    merged = flight.merge_chrome_traces({0: _fake_trace("r0"), 1: str(p1)})
    evs = merged["traceEvents"]
    assert {e["pid"] for e in evs} == {0, 1}       # one pid per rank
    meta = [e for e in evs if e.get("ph") == "M"]
    assert {e["args"]["name"] for e in meta} == {"rank 0", "rank 1"}
    assert {e["pid"] for e in evs if e["name"] == "r1"} == {1}


def _load_trace_merge():
    import importlib.util
    path = os.path.join(os.path.dirname(__file__), "..", "tools",
                        "trace_merge.py")
    spec = importlib.util.spec_from_file_location("trace_merge_test", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_trace_merge_cli_smoke(tmp_path):
    def dump(rank, n):
        return {"schema": flight.DUMP_SCHEMA, "rank": rank, "reason": "test",
                "stalled_ranks": [], "events": [],
                "collectives": [
                    {"t": float(i), "rank": rank, "kind": "collective",
                     "seq": i + 1, "op": "all_reduce", "bytes": 64,
                     "t_enter": float(i), "t_exit": float(i) + 0.001}
                    for i in range(n)]}

    for r, n in ((0, 5), (1, 4), (2, 5)):
        (tmp_path / f"flight_rank{r}.json").write_text(json.dumps(dump(r, n)))
    for r in (0, 1):
        (tmp_path / f"rank{r}.trace.json").write_text(
            json.dumps(_fake_trace(f"r{r}")))

    tm = _load_trace_merge()
    out_trace = str(tmp_path / "merged.json")
    out_report = str(tmp_path / "report.json")
    rc = tm.main(["--trace", out_trace, "--report", out_report,
                  str(tmp_path / "flight_rank*.json"),
                  str(tmp_path / "rank*.trace.json")])
    assert rc == 0

    with open(out_trace) as f:
        merged = json.load(f)
    assert {e["pid"] for e in merged["traceEvents"]} == {0, 1}

    with open(out_report) as f:
        rep = json.load(f)
    assert rep["ranks"] == [0, 1, 2]
    stalled = rep["desync"]["stalled"]
    assert len(stalled) == 1
    assert stalled[0]["rank"] == 1 and stalled[0]["missing_seq"] == 5


# ---------------------------------------------------------------------------
# satellites: O_APPEND jsonl, dataloader tracebacks, serving state,
# heartbeat wiring, overhead guard
# ---------------------------------------------------------------------------


def _jsonl_writer(path, n):
    from paddle_tpu.profiler.telemetry import MetricRegistry
    reg = MetricRegistry()
    c = reg.counter("fr_jsonl_probe_total", "probe")
    for _ in range(n):
        c.inc()
        reg.export_jsonl(path, extra={"pad": "z" * 4096})


def test_export_jsonl_concurrent_ranks_never_interleave(tmp_path):
    if "fork" not in mp.get_all_start_methods():
        pytest.skip("needs fork start method")
    path = str(tmp_path / "telemetry.jsonl")
    ctx = mp.get_context("fork")
    procs = [ctx.Process(target=_jsonl_writer, args=(path, 20))
             for _ in range(4)]
    for p in procs:
        p.start()
    for p in procs:
        p.join(timeout=60)
        assert p.exitcode == 0
    with open(path) as f:
        lines = f.read().splitlines()
    assert len(lines) == 80
    for ln in lines:                      # every line is one whole record
        rec = json.loads(ln)
        assert rec["pad"] == "z" * 4096


class _BoomDataset:
    def __len__(self):
        return 8

    def __getitem__(self, i):
        if i == 3:
            raise ValueError("boom-item-3")
        return np.zeros(2, np.float32)


def test_dataloader_worker_traceback_lands_in_ring():
    from paddle_tpu import io
    flight.enable()
    loader = io.DataLoader(_BoomDataset(), batch_size=2, num_workers=1)
    with pytest.raises(RuntimeError, match="worker failed"):
        for _ in loader:
            pass
    evs = flight.get_flight_recorder().events(
        kind="dataloader_worker_failure")
    assert evs, "worker failure must land in the flight ring"
    assert "boom-item-3" in evs[-1]["traceback"]
    assert "ValueError" in evs[-1]["traceback"]    # full worker traceback


def test_serving_engine_registers_queue_state_for_dumps(tmp_path):
    from paddle_tpu.inference.serving import ServingEngine
    flight.enable()
    eng = ServingEngine(model=object())
    eng.start()
    try:
        keys = [k for k in flight._STATE_PROVIDERS
                if k.startswith("serving_static")]
        assert keys
        d = flight.get_flight_recorder().dump(directory=str(tmp_path))
        with open(d["ranks"][0]) as f:
            data = json.load(f)
        st = data["state"][keys[0]]
        assert st["engine"] == "static" and st["running"] is True
        assert st["queue_depth"] == 0
    finally:
        eng.stop()
    assert not any(k.startswith("serving_static")
                   for k in flight._STATE_PROVIDERS)


def test_telemetry_callback_feeds_heartbeat():
    from paddle_tpu.callbacks import TelemetryCallback
    flight.enable()
    cb = TelemetryCallback(track_ops=False, track_memory=False)
    cb.on_train_begin()
    cb.on_train_batch_begin(0)
    cb.on_train_batch_end(0)
    cb.on_train_end()
    assert 0 in flight.get_flight_recorder()._heartbeats


def test_disabled_recorder_adds_no_step_cost():
    """Overhead guard (and the disabled half of the ISSUE 3 acceptance):
    a bare step loop with the recorder machinery present-but-disabled
    must show no measurable added per-step cost. Reuses bench.py's
    telemetry_overhead_pct machinery with the recorder's disabled-path
    gate calls as the 'instrumented' surface."""
    import bench

    assert not flight.is_enabled()
    x = np.random.default_rng(0).normal(size=200_000).astype(np.float32)

    def step():
        return float(np.tanh(x).sum())

    def gated_step():
        # every disabled-path call the wiring makes per step/collective
        flight.heartbeat()
        ev = flight.collective_begin("all_reduce", x.nbytes, (0, 1, 2, 3))
        flight.collective_end(ev)
        flight.record_event("probe")
        return step()

    pct = min(
        bench._telemetry_overhead_pct(step, lambda r: None, steps=30,
                                      instrumented_step=gated_step)
        for _ in range(5))
    # the gates cost ~1 µs against a ~2 ms step, so a real per-step
    # regression reads as 100%+; the loose bound is noise headroom for a
    # shared single-core host, where even min-of-N sees >10% scheduler
    # jitter, not a tolerance for actual recorder work
    assert pct < 25.0, f"disabled flight recorder costs {pct}% per step"
    assert len(flight.get_flight_recorder()._ring) == 0  # truly recorded nothing
