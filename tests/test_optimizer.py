"""Optimizers: update math vs hand-rolled numpy + end-to-end convergence
(SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn, optimizer


def _param(val):
    return paddle.Parameter(np.asarray(val, np.float32))


def _set_grad(p, g):
    p.grad = paddle.to_tensor(np.asarray(g, np.float32))


def test_sgd_step():
    p = _param([1.0, 2.0])
    opt = optimizer.SGD(learning_rate=0.1, parameters=[p])
    _set_grad(p, [1.0, 1.0])
    opt.step()
    np.testing.assert_allclose(p.numpy(), [0.9, 1.9], rtol=1e-6)


def test_momentum_matches_numpy():
    p = _param([1.0])
    opt = optimizer.Momentum(learning_rate=0.1, momentum=0.9, parameters=[p])
    v = 0.0
    x = 1.0
    for g in [1.0, 0.5, 0.25]:
        _set_grad(p, [g])
        opt.step()
        v = 0.9 * v + g
        x = x - 0.1 * v
    np.testing.assert_allclose(p.numpy(), [x], rtol=1e-6)


def test_adam_matches_numpy():
    p = _param([1.0])
    opt = optimizer.Adam(learning_rate=0.01, parameters=[p])
    m = v = 0.0
    x = 1.0
    for t, g in enumerate([1.0, -0.5, 0.3], 1):
        _set_grad(p, [g])
        opt.step()
        m = 0.9 * m + 0.1 * g
        v = 0.999 * v + 0.001 * g * g
        mh = m / (1 - 0.9 ** t)
        vh = v / (1 - 0.999 ** t)
        x = x - 0.01 * mh / (np.sqrt(vh) + 1e-8)
    np.testing.assert_allclose(p.numpy(), [x], rtol=1e-5)


def test_adamw_decoupled_decay():
    p = _param([1.0])
    opt = optimizer.AdamW(learning_rate=0.1, weight_decay=0.1, parameters=[p])
    _set_grad(p, [0.0])
    opt.step()
    # zero grad -> only decay applies: p *= (1 - lr*wd)
    np.testing.assert_allclose(p.numpy(), [1.0 * (1 - 0.1 * 0.1)], rtol=1e-5)


def test_grad_clip_in_optimizer():
    p = _param(np.ones(4))
    opt = optimizer.SGD(learning_rate=1.0, parameters=[p],
                        grad_clip=nn.ClipGradByGlobalNorm(1.0))
    _set_grad(p, np.full(4, 10.0))
    opt.step()
    # clipped grad has norm 1 -> each entry 0.5
    np.testing.assert_allclose(p.numpy(), 1 - 0.5, rtol=1e-4)


def test_multi_precision_master_weights():
    p = paddle.Parameter(np.ones(3, np.float32))
    p._data = p._data.astype("bfloat16")
    opt = optimizer.AdamW(learning_rate=1e-4, parameters=[p],
                          multi_precision=True)
    _set_grad(p, np.full(3, 1e-3))
    opt.step()
    slots = opt._slots[id(p)]
    assert "master" in slots
    assert str(slots["master"].dtype) == "float32"
    assert str(np.dtype(p.dtype)) == "bfloat16" or "bfloat16" in str(p.dtype)


def test_lr_schedulers():
    lr = optimizer.lr.StepDecay(0.1, step_size=2, gamma=0.5)
    vals = []
    for _ in range(5):
        vals.append(lr())
        lr.step()
    np.testing.assert_allclose(vals, [0.1, 0.1, 0.05, 0.05, 0.025])

    warm = optimizer.lr.LinearWarmup(0.1, warmup_steps=4, start_lr=0.0, end_lr=0.1)
    v0 = warm()
    warm.step()
    warm.step()
    assert v0 == 0.0 and abs(warm() - 0.05) < 1e-6

    cos = optimizer.lr.CosineAnnealingDecay(1.0, T_max=10)
    cos.step(5)
    np.testing.assert_allclose(cos(), 0.5, atol=1e-6)

    noam = optimizer.lr.NoamDecay(d_model=512, warmup_steps=100)
    assert noam() > 0


def test_scheduler_in_optimizer():
    p = _param([1.0])
    sched = optimizer.lr.StepDecay(0.1, step_size=1, gamma=0.1)
    opt = optimizer.SGD(learning_rate=sched, parameters=[p])
    _set_grad(p, [1.0])
    opt.step()
    np.testing.assert_allclose(p.numpy(), [0.9], rtol=1e-6)
    sched.step()
    _set_grad(p, [1.0])
    opt.step()
    np.testing.assert_allclose(p.numpy(), [0.89], rtol=1e-5)


def test_optimizer_state_dict_roundtrip():
    p = _param([1.0, 2.0])
    opt = optimizer.Adam(learning_rate=0.01, parameters=[p])
    _set_grad(p, [1.0, 1.0])
    opt.step()
    state = opt.state_dict()
    p2 = _param([1.0, 2.0])
    p2.name = p.name
    opt2 = optimizer.Adam(learning_rate=0.01, parameters=[p2])
    opt2.set_state_dict(state)
    np.testing.assert_allclose(opt2._slots[id(p2)]["moment1"],
                               opt._slots[id(p)]["moment1"])
    assert opt2._step_t[id(p2)] == 1


def test_regression_convergence():
    paddle.seed(0)
    net = nn.Linear(3, 1)
    opt = optimizer.Adam(learning_rate=0.05, parameters=net.parameters())
    w_true = np.array([[1.0], [-2.0], [0.5]], np.float32)
    rng = np.random.RandomState(0)
    for _ in range(150):
        x = rng.randn(32, 3).astype(np.float32)
        y = x @ w_true
        pred = net(paddle.to_tensor(x))
        loss = nn.functional.mse_loss(pred, paddle.to_tensor(y))
        loss.backward()
        opt.step()
        opt.clear_grad()
    np.testing.assert_allclose(net.weight.numpy(), w_true, atol=0.05)


def test_extra_optimizers_converge():
    """Rprop/ASGD/NAdam/RAdam minimize a quadratic; parity sanity on a
    1-step Adam-family bound (reference optimizer test pattern)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import optimizer as opt

    target = np.array([1.5, -2.0, 0.5], np.float32)
    for cls, kw in [(opt.Rprop, {}), (opt.ASGD, {"batch_num": 2}),
                    (opt.NAdam, {}), (opt.RAdam, {})]:
        paddle.seed(0)
        w = paddle.to_tensor(np.zeros(3, np.float32))
        w.stop_gradient = False
        o = cls(learning_rate=0.1, parameters=[w], **kw)
        for _ in range(200):
            loss = ((w - paddle.to_tensor(target)) ** 2).sum()
            loss.backward()
            o.step()
            o.clear_grad()
        got = np.asarray(w.numpy())
        np.testing.assert_allclose(got, target, atol=0.15,
                                   err_msg=cls.__name__)


def test_lbfgs_rosenbrock():
    """LBFGS with strong-Wolfe line search solves Rosenbrock in a handful
    of closure steps (the classic L-BFGS acceptance test)."""
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import optimizer as opt

    w = paddle.to_tensor(np.array([-1.0, 1.0], np.float32))
    w.stop_gradient = False
    o = opt.LBFGS(learning_rate=1.0, max_iter=25,
                  line_search_fn="strong_wolfe", parameters=[w])

    def closure():
        o.clear_grad()
        x, y = w[0], w[1]
        loss = (1 - x) ** 2 + 100 * (y - x ** 2) ** 2
        loss.backward()
        return loss

    for _ in range(8):
        loss = o.step(closure)
    final = np.asarray(w.numpy())
    np.testing.assert_allclose(final, [1.0, 1.0], atol=1e-2)
    assert float(loss.numpy()) < 1e-4


def test_lbfgs_partial_params_and_wd():
    import paddle_tpu.optimizer as opt
    w1 = paddle.to_tensor(np.array([2.0], np.float32))
    w2 = paddle.to_tensor(np.array([5.0], np.float32))
    w1.stop_gradient = False
    w2.stop_gradient = False
    o = opt.LBFGS(learning_rate=0.5, max_iter=5, parameters=[w1, w2])

    def closure():
        o.clear_grad()
        loss = (w1 ** 2).sum()     # w2 unused -> grad None
        loss.backward()
        return loss

    o.step(closure)                # must not crash on w2.grad is None
    assert abs(float(w2.numpy()[0]) - 5.0) < 1e-6   # untouched
    import pytest as _pytest
    from paddle_tpu.nn import ClipGradByGlobalNorm
    with _pytest.raises(ValueError):
        opt.LBFGS(parameters=[w1], grad_clip=ClipGradByGlobalNorm(1.0))


def test_linear_lr():
    import paddle_tpu as paddle
    sched = paddle.optimizer.lr.LinearLR(0.1, total_steps=4,
                                         start_factor=0.5, end_factor=1.0)
    vals = []
    for _ in range(6):
        vals.append(round(sched(), 6))
        sched.step()
    assert vals[0] == 0.05 and vals[4] == 0.1 and vals[5] == 0.1
    assert vals[1] == 0.0625 and vals[2] == 0.075
