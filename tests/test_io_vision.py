"""DataLoader / sampler / transforms / vision models (SURVEY.md §3.5)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import io
from paddle_tpu.vision import datasets, transforms, models


class _SquareDataset(io.Dataset):
    def __init__(self, n=20):
        self.n = n

    def __getitem__(self, i):
        return np.float32([i]), np.float32([i * i])

    def __len__(self):
        return self.n


def test_dataloader_single_process():
    dl = io.DataLoader(_SquareDataset(), batch_size=4, shuffle=False,
                       drop_last=False)
    batches = list(dl)
    assert len(batches) == 5
    x, y = batches[0]
    assert isinstance(x, paddle.Tensor)
    assert x.shape == [4, 1]
    np.testing.assert_allclose(x.numpy().ravel(), [0, 1, 2, 3])


def test_dataloader_shuffle_and_drop_last():
    dl = io.DataLoader(_SquareDataset(10), batch_size=3, shuffle=True,
                       drop_last=True)
    batches = list(dl)
    assert len(batches) == 3
    seen = np.concatenate([b[0].numpy().ravel() for b in batches])
    assert len(set(seen.tolist())) == 9


def test_dataloader_multiprocess():
    dl = io.DataLoader(_SquareDataset(16), batch_size=4, num_workers=2)
    batches = list(dl)
    assert len(batches) == 4
    allx = np.sort(np.concatenate([b[0].numpy().ravel() for b in batches]))
    np.testing.assert_allclose(allx, np.arange(16))


def test_batch_sampler_and_distributed():
    ds = _SquareDataset(10)
    bs = io.BatchSampler(ds, batch_size=4)
    assert len(bs) == 3
    dbs = io.DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=0)
    idx0 = [i for b in dbs for i in b]
    dbs1 = io.DistributedBatchSampler(ds, batch_size=2, num_replicas=2, rank=1)
    idx1 = [i for b in dbs1 for i in b]
    assert set(idx0) | set(idx1) == set(range(10))
    assert not (set(idx0) & set(idx1))


def test_tensor_dataset_and_subset():
    td = io.TensorDataset([paddle.arange(10, dtype="float32"),
                           paddle.arange(10, dtype="float32") * 2])
    x, y = td[3]
    assert float(x) == 3 and float(y) == 6
    sub = io.Subset(td, [1, 5])
    assert float(sub[1][0]) == 5
    a, b = io.random_split(td, [0.5, 0.5])
    assert len(a) == 5 and len(b) == 5


def test_iterable_dataset():
    class Gen(io.IterableDataset):
        def __iter__(self):
            for i in range(7):
                yield np.float32([i])

    dl = io.DataLoader(Gen(), batch_size=3)
    sizes = [b.shape[0] for b in dl]
    assert sizes == [3, 3, 1]


def test_transforms_pipeline():
    tr = transforms.Compose([
        transforms.Resize(40),
        transforms.RandomCrop(32),
        transforms.RandomHorizontalFlip(0.5),
        transforms.ToTensor(),
        transforms.Normalize([0.5] * 3, [0.5] * 3),
    ])
    img = np.random.randint(0, 255, (32, 32, 3), np.uint8)
    out = tr(img)
    assert out.shape == [3, 32, 32]
    assert -1.1 <= float(out.min()) and float(out.max()) <= 1.1


def test_fakedata_and_lenet_forward():
    ds = datasets.FakeData(size=8, image_shape=(1, 28, 28))
    dl = io.DataLoader(ds, batch_size=4)
    x, y = next(iter(dl))
    net = models.LeNet()
    out = net(x)
    assert out.shape == [4, 10]


def test_resnet18_forward_shapes():
    net = models.resnet18(num_classes=10)
    net.eval()
    x = paddle.randn([2, 3, 32, 32])
    out = net(x)
    assert out.shape == [2, 10]
    n_params = sum(p.size for p in net.parameters())
    assert 11_000_000 < n_params < 12_000_000  # ~11.2M like the reference


def test_resnet50_param_count():
    net = models.resnet50(num_classes=1000)
    n = sum(p.size for p in net.parameters())
    assert 25_000_000 < n < 26_000_000  # 25.5M matches torchvision/paddle


def test_distributed_sampler_deterministic_resume():
    """Checkpoint the sampler mid-epoch, restore, and get exactly the
    unconsumed remainder in the same shuffle order (SURVEY.md §5.4 /
    hard part 3 'sampler state in checkpoints')."""
    import numpy as np
    from paddle_tpu.io import DistributedBatchSampler

    ds = np.arange(37)
    s = DistributedBatchSampler(ds, batch_size=4, num_replicas=2, rank=0,
                                shuffle=True)
    s.set_epoch(3)
    full = [list(b) for b in s]

    s2 = DistributedBatchSampler(ds, batch_size=4, num_replicas=2, rank=0,
                                 shuffle=True)
    s2.set_epoch(3)
    it = iter(s2)
    consumed = [next(it) for _ in range(2)]
    state = s2.state_dict()
    assert state == {"epoch": 3, "consumed_batches": 2}

    s3 = DistributedBatchSampler(ds, batch_size=4, num_replicas=2, rank=0,
                                 shuffle=True)
    s3.set_state_dict(state)
    resumed = [list(b) for b in s3]
    assert consumed + resumed == full
    # next epoch after the resumed one starts fresh
    s3.set_epoch(4)
    assert len([b for b in s3]) == len(full)


def test_dataloader_mid_epoch_checkpoint_prefetch_accurate():
    """Loader-level consumed count = batches handed to the train loop —
    the buffered reader's prefetch depth must not over-report."""
    import numpy as np
    from paddle_tpu.io import DataLoader, DistributedBatchSampler

    class DS:
        def __len__(self):
            return 32

        def __getitem__(self, i):
            return np.float32(i)

    def make():
        return DataLoader(DS(), batch_sampler=DistributedBatchSampler(
            DS(), batch_size=4, num_replicas=1, rank=0),
            prefetch_factor=3)

    dl = make()
    full = [np.asarray(b).tolist() for b in dl]

    dl1 = make()
    it = iter(dl1)
    seen = [np.asarray(next(it)).tolist() for _ in range(3)]
    state = dl1.state_dict()
    assert state["consumed_batches"] == 3, state    # NOT 3+prefetch

    dl2 = make()
    dl2.set_state_dict(state)
    rest = [np.asarray(b).tolist() for b in dl2]
    assert seen + rest == full

    # abandoned iteration must NOT skip on the next fresh epoch
    again = [np.asarray(b).tolist() for b in dl1]
    assert again == full


def test_dataloader_resume_default_and_custom_sampler():
    """The default BatchSampler is resumable since the elastic PR
    (state_dict/set_state_dict with epoch + consumed + seed); a custom
    sampler without set_state_dict still rejects a consumed-batch skip."""
    import numpy as np
    import pytest as _pytest
    from paddle_tpu.io import DataLoader

    class DS:
        def __len__(self):
            return 8

        def __getitem__(self, i):
            return np.float32(i)

    dl = DataLoader(DS(), batch_size=4)
    dl.set_state_dict({"epoch": 0, "consumed_batches": 1})
    vals = [np.asarray(b.numpy()).tolist() for b in dl]
    assert vals == [[4.0, 5.0, 6.0, 7.0]]        # first batch skipped

    # a loader whose batch_sampler lacks set_state_dict entirely
    class Legacy:
        def __iter__(self):
            return iter([[0, 1], [2, 3]])

        def __len__(self):
            return 2

    dl3 = DataLoader(DS(), batch_size=2)
    dl3.batch_sampler = Legacy()
    with _pytest.raises(ValueError, match="set_state_dict"):
        dl3.set_state_dict({"epoch": 0, "consumed_batches": 2})


def test_cached_vision_datasets(tmp_path):
    import numpy as np
    import pytest
    from paddle_tpu.vision.datasets import FlowersArrays, VOC2012

    np.savez(tmp_path / "flowers_train.npz",
             images=np.zeros((4, 8, 8, 3), np.uint8),
             labels=np.arange(4, dtype=np.int64))
    ds = FlowersArrays(data_file=str(tmp_path / "flowers_train.npz"))
    img, lab = ds[1]
    assert img.shape == (8, 8, 3) and lab == 1 and len(ds) == 4

    np.savez(tmp_path / "voc.npz",
             images=np.zeros((2, 8, 8, 3), np.uint8),
             masks=np.ones((2, 8, 8), np.uint8))
    voc = VOC2012(data_file=str(tmp_path / "voc.npz"))
    img, mask = voc[0]
    assert mask.shape == (8, 8)

    with pytest.raises(IOError, match="place the reference archive"):
        VOC2012(data_file=str(tmp_path / "missing.npz"))


def test_round4_transforms():
    """RandomErasing / GaussianBlur / RandomAffine / RandomPerspective."""
    import numpy as np
    from paddle_tpu.vision import transforms as T

    np.random.seed(3)
    img = np.random.randint(0, 255, (32, 48, 3), np.uint8)

    er = T.RandomErasing(prob=1.0, value=0)(img)
    assert er.shape == img.shape and er.dtype == np.uint8
    assert (er != img).any(), "nothing erased at prob=1"

    bl = T.GaussianBlur(kernel_size=5, sigma=1.5)(img)
    assert bl.shape == img.shape and bl.dtype == np.uint8
    # blur must reduce local variance
    assert np.diff(bl.astype(int), axis=0).std() < \
        np.diff(img.astype(int), axis=0).std()

    # identity affine == identity warp
    ident = T.RandomAffine(degrees=(0, 0))(img)
    np.testing.assert_array_equal(ident, img)
    aff = T.RandomAffine(degrees=30, translate=(0.1, 0.1), scale=(0.8, 1.2),
                         shear=10, interpolation="bilinear")(img)
    assert aff.shape == img.shape

    # distortion_scale=0 -> identity homography
    same = T.RandomPerspective(prob=1.0, distortion_scale=0.0)(img)
    np.testing.assert_array_equal(same, img)
    warped = T.RandomPerspective(prob=1.0, distortion_scale=0.5)(img)
    assert warped.shape == img.shape and (warped != img).any()
