"""Fault-injection harness unit tier (ISSUE 6): FaultPlan grammar,
trigger semantics, env wiring, and the simulator kill/delay hooks."""
import time

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed import fault
from paddle_tpu.distributed.fault import (
    Fault, FaultPlan, RankFailure, SimulatedRankKill,
)


@pytest.fixture(autouse=True)
def _clean_plan():
    fault.clear()
    yield
    fault.clear()


class TestParser:
    def test_single_kill_at_step(self):
        plan = FaultPlan.parse("kill:rank=2,step=5")
        (f,) = plan.faults
        assert (f.kind, f.rank, f.step, f.seq) == ("kill", 2, 5, None)
        assert not f.fired

    def test_multi_directive_with_whitespace(self):
        plan = FaultPlan.parse(
            " kill:rank=2,seq=12 ; delay: rank=1, step=3, seconds=0.5 ;")
        assert len(plan.faults) == 2
        k, d = plan.faults
        assert (k.kind, k.rank, k.seq) == ("kill", 2, 12)
        assert (d.kind, d.rank, d.step, d.seconds) == ("delay", 1, 3, 0.5)

    def test_repr_round_trips_the_directive(self):
        plan = FaultPlan.parse("delay:rank=1,seq=8,seconds=0.25")
        assert "delay:rank=1,seq=8" in repr(plan.faults[0])

    @pytest.mark.parametrize("spec,match", [
        ("explode:rank=0,step=1", "unknown fault kind"),
        ("kill:rank=0,when=1", "unknown fault key"),
        ("kill:step=1", "needs rank="),
        ("kill:rank=0", "exactly one trigger"),
        ("kill:rank=0,step=1,seq=2", "exactly one trigger"),
        ("delay:rank=0,step=1", "seconds > 0"),
    ])
    def test_rejects_malformed(self, spec, match):
        with pytest.raises(ValueError, match=match):
            FaultPlan.parse(spec)

    def test_env_plan_parsed_lazily(self, monkeypatch):
        monkeypatch.setenv("PADDLE_FAULT_PLAN", "kill:rank=1,step=7")
        fault.clear()                      # re-arm env parsing
        plan = fault.active_plan()
        assert plan is not None and plan.faults[0].rank == 1
        # parsed once: a changed env is not re-read until clear()
        monkeypatch.setenv("PADDLE_FAULT_PLAN", "kill:rank=3,step=1")
        assert fault.active_plan() is plan


class TestTriggers:
    def test_step_kill_fires_once_and_marks_dead(self):
        fault.install("kill:rank=0,step=2")
        fault.check_step(0)
        fault.check_step(1)                # not yet
        with pytest.raises(SimulatedRankKill) as ei:
            fault.check_step(2)
        assert ei.value.rank == 0
        fault.check_step(2)                # fired=True: never again

    def test_delay_sleeps_without_raising(self):
        fault.install("delay:rank=0,step=1,seconds=0.2")
        t0 = time.monotonic()
        fault.check_step(1)
        assert time.monotonic() - t0 >= 0.15

    def test_kill_and_delay_count_in_telemetry(self):
        c = fault.elastic_telemetry()["events"]
        k0, d0 = c.value(kind="kill"), c.value(kind="delay")
        fault.install("delay:rank=0,step=1,seconds=0.01;kill:rank=0,step=2")
        fault.check_step(1)
        with pytest.raises(SimulatedRankKill):
            fault.check_step(2)
        assert c.value(kind="kill") == k0 + 1
        assert c.value(kind="delay") == d0 + 1

    def test_install_accepts_plan_object_and_none(self):
        plan = FaultPlan([Fault("kill", 0, step=1)])
        assert fault.install(plan) is plan
        assert fault.active_plan() is plan
        fault.install(None)
        assert fault.active_plan() is None


class TestSimulatorWiring:
    def test_seq_kill_surfaces_rank_failure_on_survivor(self):
        """Rank 1 dies before its 2nd collective; rank 0, blocked in the
        rendezvous, gets a structured RankFailure naming rank 1 — not a
        hang, not a bare timeout."""
        fault.install("kill:rank=1,seq=2")

        def worker():
            r = dist.get_rank()
            t = paddle.to_tensor(np.ones(4, np.float32))
            try:
                for _ in range(3):
                    dist.all_reduce(t)
                return "finished"
            except SimulatedRankKill:
                return "killed"
            except RankFailure as e:
                return ("failure", e.rank)

        res = dist.spawn(worker, nprocs=2).results
        assert res[1] == "killed"
        assert res[0] == ("failure", 1)

    def test_collective_counter_is_per_rank(self):
        fault.install("kill:rank=1,seq=3")
        plan = fault.active_plan()

        def worker():
            r = dist.get_rank()
            t = paddle.to_tensor(np.ones(2, np.float32))
            try:
                for _ in range(4):
                    dist.all_reduce(t)
                return "finished"
            except (SimulatedRankKill, RankFailure):
                return "stopped"

        dist.spawn(worker, nprocs=2)
        assert plan.collective_seq(1) == 3      # died entering its 3rd
        assert plan.collective_seq(0) >= 3

    def test_no_plan_is_zero_overhead_hook(self):
        from paddle_tpu.distributed import simulator
        fault.clear()
        assert simulator._FAULT_HOOK[0] is None
        fault.install("kill:rank=0,step=99")
        assert simulator._FAULT_HOOK[0] is not None
        fault.clear()
        assert simulator._FAULT_HOOK[0] is None


class TestFleetDirectives:
    """ISSUE 14: the fault grammar extended to the serving fleet —
    ``kill:replica=R,request=N`` and ``stall:replica=R,seconds=T``
    trigger on the replica's N-th routed request (the ServingRouter
    calls check_fleet_route at every routing decision)."""

    def test_parse_fleet_directives(self):
        plan = FaultPlan.parse(
            "kill:replica=r1,request=4;stall:replica=r0,seconds=0.5")
        k, s = plan.faults
        assert (k.kind, k.replica, k.request) == ("kill", "r1", 4)
        assert k.rank is None and k.step is None and k.seq is None
        assert (s.kind, s.replica, s.request, s.seconds) == (
            "stall", "r0", 1, 0.5)           # request defaults to 1
        assert "kill:replica=r1,request=4" in repr(k)
        assert "stall:replica=r0" in repr(s)

    @pytest.mark.parametrize("spec,match", [
        ("nan:replica=r0,request=1", "unknown fleet fault kind"),
        ("stall:replica=r0", "seconds > 0"),
        ("kill:replica=r0,step=1", "request=N"),
        ("kill:rank=0,request=3", "need replica="),
        ("kill:replica=r0,when=1", "unknown fault key"),
    ])
    def test_rejects_malformed_fleet(self, spec, match):
        with pytest.raises(ValueError, match=match):
            FaultPlan.parse(spec)

    def test_fleet_kinds_catalog(self):
        assert fault.FLEET_FAULT_KINDS == ("kill", "stall")

    def test_route_trigger_counts_per_replica_and_fires_once(self):
        fault.install("kill:replica=r1,request=3")
        # r0's routes never advance r1's counter
        assert fault.check_fleet_route("r0") is None
        assert fault.check_fleet_route("r1") is None
        assert fault.check_fleet_route("r1") is None
        f = fault.check_fleet_route("r1")
        assert f is not None and f.kind == "kill" and f.fired
        assert fault.check_fleet_route("r1") is None     # once only

    def test_fleet_firing_counts_in_telemetry(self):
        c = fault.elastic_telemetry()["events"]
        s0 = c.value(kind="stall")
        fault.install("stall:replica=rX,seconds=0.01")
        assert fault.check_fleet_route("rX") is not None
        assert c.value(kind="stall") == s0 + 1

    def test_no_plan_route_check_is_none(self):
        fault.clear()
        assert fault.check_fleet_route("r0") is None
