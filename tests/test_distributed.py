"""Distributed-core tests (SURVEY.md §4: collective tests per-rank with loss
parity vs a single-process oracle; hybrid mp/pp/sharding parity tests)."""
import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn
import paddle_tpu.nn.functional as F


# ---------------------------------------------------------------------------
# imperative collectives (thread-rank simulator)
# ---------------------------------------------------------------------------


class TestCollectives:
    def test_all_reduce_sum(self):
        def worker():
            r = dist.get_rank()
            t = paddle.to_tensor(np.full((2, 3), float(r + 1), "float32"))
            dist.all_reduce(t)
            return t.numpy()

        res = dist.spawn(worker, nprocs=4).results
        for v in res:
            np.testing.assert_allclose(v, 10.0)

    def test_all_reduce_max_and_group(self):
        def worker():
            r = dist.get_rank()
            g = dist.new_group([0, 2])
            t = paddle.to_tensor(np.array([float(r)], "float32"))
            if r in (0, 2):
                dist.all_reduce(t, op=dist.ReduceOp.MAX, group=g)
            return t.numpy()[0]

        res = dist.spawn(worker, nprocs=4).results
        assert res[0] == 2.0 and res[2] == 2.0
        assert res[1] == 1.0 and res[3] == 3.0

    def test_all_gather(self):
        def worker():
            r = dist.get_rank()
            out = []
            dist.all_gather(out, paddle.to_tensor(np.array([r], "float32")))
            return [t.numpy()[0] for t in out]

        res = dist.spawn(worker, nprocs=3).results
        for v in res:
            assert v == [0.0, 1.0, 2.0]

    def test_reduce_scatter(self):
        def worker():
            r = dist.get_rank()
            parts = [paddle.to_tensor(np.full((2,), float(r + 10 * i), "float32"))
                     for i in range(2)]
            out = paddle.zeros([2])
            dist.reduce_scatter(out, parts)
            return out.numpy()[0]

        res = dist.spawn(worker, nprocs=2).results
        # rank0 gets sum of parts[0] over ranks = 0+1; rank1: 10+11
        assert res[0] == 1.0 and res[1] == 21.0

    def test_alltoall(self):
        def worker():
            r = dist.get_rank()
            ins = [paddle.to_tensor(np.array([r * 10 + i], "float32"))
                   for i in range(2)]
            outs = []
            dist.alltoall(outs, ins)
            return [t.numpy()[0] for t in outs]

        res = dist.spawn(worker, nprocs=2).results
        assert res[0] == [0.0, 10.0]
        assert res[1] == [1.0, 11.0]

    def test_broadcast_scatter(self):
        def worker():
            r = dist.get_rank()
            t = paddle.to_tensor(np.array([float(r)], "float32"))
            dist.broadcast(t, src=1)
            parts = [paddle.to_tensor(np.array([7.0 + i], "float32"))
                     for i in range(2)] if r == 0 else None
            s = paddle.zeros([1])
            dist.scatter(s, parts, src=0)
            return t.numpy()[0], s.numpy()[0]

        res = dist.spawn(worker, nprocs=2).results
        assert [v[0] for v in res] == [1.0, 1.0]
        assert [v[1] for v in res] == [7.0, 8.0]

    def test_send_recv(self):
        def worker():
            r = dist.get_rank()
            if r == 0:
                dist.send(paddle.to_tensor(np.array([42.0], "float32")), dst=1)
                return 0.0
            t = paddle.zeros([1])
            dist.recv(t, src=0)
            return t.numpy()[0]

        res = dist.spawn(worker, nprocs=2).results
        assert res[1] == 42.0

    def test_barrier_and_object_gather(self):
        def worker():
            dist.barrier()
            objs = []
            dist.all_gather_object(objs, {"rank": dist.get_rank()})
            return [o["rank"] for o in objs]

        res = dist.spawn(worker, nprocs=3).results
        for v in res:
            assert v == [0, 1, 2]

    def test_world_size_rank_outside_spawn(self):
        assert dist.get_world_size() == 1
        assert dist.get_rank() == 0
        # world-size-1 collectives are identities
        t = paddle.to_tensor(np.array([3.0], "float32"))
        dist.all_reduce(t)
        np.testing.assert_allclose(t.numpy(), [3.0])


# ---------------------------------------------------------------------------
# mesh-mode tensor parallelism: parity vs unsharded oracle
# ---------------------------------------------------------------------------


@pytest.fixture
def mp2_mesh():
    strat = dist.fleet.DistributedStrategy()
    strat.hybrid_configs = {"dp_degree": 4, "mp_degree": 2}
    dist.fleet.init(is_collective=True, strategy=strat)
    yield
    dist.mesh.reset_mesh()


class TestTensorParallel:
    def test_column_row_linear_parity(self, mp2_mesh):
        from paddle_tpu.distributed.fleet import (ColumnParallelLinear,
                                                  RowParallelLinear)
        paddle.seed(11)
        col = ColumnParallelLinear(8, 16, gather_output=False)
        row = RowParallelLinear(16, 8, input_is_parallel=True)
        dense1 = nn.Linear(8, 16)
        dense2 = nn.Linear(16, 8)
        dense1.weight.set_value(col.weight)
        dense1.bias.set_value(col.bias)
        dense2.weight.set_value(row.weight)
        dense2.bias.set_value(row.bias)

        x = paddle.randn([4, 8])
        x.stop_gradient = False
        x2 = paddle.to_tensor(x.numpy())
        x2.stop_gradient = False

        y_mp = row(F.relu(col(x)))
        y_ref = dense2(F.relu(dense1(x2)))
        np.testing.assert_allclose(y_mp.numpy(), y_ref.numpy(), rtol=1e-5, atol=1e-5)

        y_mp.sum().backward()
        y_ref.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), x2.grad.numpy(), rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(col.weight.grad.numpy(),
                                   dense1.weight.grad.numpy(), rtol=1e-5, atol=1e-5)
        np.testing.assert_allclose(row.weight.grad.numpy(),
                                   dense2.weight.grad.numpy(), rtol=1e-5, atol=1e-5)

    def test_vocab_parallel_embedding_parity(self, mp2_mesh):
        from paddle_tpu.distributed.fleet import VocabParallelEmbedding
        paddle.seed(12)
        vpe = VocabParallelEmbedding(32, 8)
        ref = nn.Embedding(32, 8)
        ref.weight.set_value(vpe.weight)
        ids = paddle.to_tensor(np.array([[1, 5, 31], [0, 2, 7]], "int32"))
        np.testing.assert_allclose(vpe(ids).numpy(), ref(ids).numpy(),
                                   rtol=1e-6, atol=1e-6)

    def test_parallel_cross_entropy_parity(self, mp2_mesh):
        from paddle_tpu.distributed.fleet import ParallelCrossEntropy
        paddle.seed(13)
        logits = paddle.randn([4, 32])
        logits.stop_gradient = False
        labels = paddle.to_tensor(np.array([1, 5, 0, 31], "int32"))
        pce = ParallelCrossEntropy()
        loss = pce(logits, labels)
        ref = F.cross_entropy(paddle.to_tensor(logits.numpy()), labels,
                              reduction="none")
        np.testing.assert_allclose(loss.numpy().ravel(), ref.numpy().ravel(),
                                   rtol=1e-5, atol=1e-5)

    def test_sequence_parallel_ops(self, mp2_mesh):
        from paddle_tpu.distributed.fleet.utils import sequence_parallel_utils as spu
        x = paddle.randn([8, 2, 4])  # [s, b, h]
        x.stop_gradient = False
        y = spu.GatherOp.apply(spu.ScatterOp.apply(x))
        np.testing.assert_allclose(y.numpy(), x.numpy(), rtol=1e-6)
        y.sum().backward()
        np.testing.assert_allclose(x.grad.numpy(), np.ones_like(x.numpy()))

    def test_sequence_parallel_linear_parity(self, mp2_mesh):
        from paddle_tpu.distributed.fleet.utils.sequence_parallel_utils import (
            ColumnSequenceParallelLinear, RowSequenceParallelLinear, ScatterOp,
            GatherOp)
        paddle.seed(14)
        col = ColumnSequenceParallelLinear(8, 16, gather_output=False)
        row = RowSequenceParallelLinear(16, 8, input_is_parallel=True)
        d1, d2 = nn.Linear(8, 16), nn.Linear(16, 8)
        d1.weight.set_value(col.weight)
        d1.bias.set_value(col.bias)
        d2.weight.set_value(row.weight)
        d2.bias.set_value(row.bias)
        x = paddle.randn([8, 2, 8])
        y_sp = GatherOp.apply(row(col(ScatterOp.apply(x))))
        y_ref = d2(d1(x))
        np.testing.assert_allclose(y_sp.numpy(), y_ref.numpy(), rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# DataParallel (mesh mode) parity vs single-device oracle
# ---------------------------------------------------------------------------


class TestDataParallelMesh:
    def test_dp_training_parity(self):
        def build_and_train(wrap_dp):
            dist.mesh.reset_mesh()
            if wrap_dp:
                dist.init_mesh({"dp": 8})
            paddle.seed(21)
            model = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 2))
            if wrap_dp:
                model = dist.DataParallel(model)
            opt = paddle.optimizer.SGD(learning_rate=0.1,
                                       parameters=model.parameters())
            rng = np.random.RandomState(0)
            losses = []
            for _ in range(5):
                x = paddle.to_tensor(rng.randn(16, 4).astype("float32"))
                y = paddle.to_tensor(rng.randn(16, 2).astype("float32"))
                loss = ((model(x) - y) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss))
            dist.mesh.reset_mesh()
            return losses

        ref = build_and_train(False)
        got = build_and_train(True)
        np.testing.assert_allclose(got, ref, rtol=1e-5, atol=1e-6)

    def test_dp_simulated_grad_sync(self):
        def worker():
            paddle.seed(5)
            model = dist.DataParallel(nn.Linear(3, 1, bias_attr=False))
            r = dist.get_rank()
            x = paddle.to_tensor(np.full((2, 3), float(r + 1), "float32"))
            loss = model(x).sum()
            loss.backward()
            return model._layers.weight.grad.numpy().copy()

        res = dist.spawn(worker, nprocs=2).results
        # grads averaged: each rank's local grad is 2*(r+1) per weight elem
        np.testing.assert_allclose(res[0], res[1])
        np.testing.assert_allclose(res[0], np.full((3, 1), 3.0))

    def test_dp_no_sync(self):
        def worker():
            model = dist.DataParallel(nn.Linear(3, 1, bias_attr=False))
            r = dist.get_rank()
            x = paddle.to_tensor(np.full((2, 3), float(r + 1), "float32"))
            with model.no_sync():
                model(x).sum().backward()
            return model._layers.weight.grad.numpy().copy()

        res = dist.spawn(worker, nprocs=2).results
        np.testing.assert_allclose(res[0], np.full((3, 1), 2.0))
        np.testing.assert_allclose(res[1], np.full((3, 1), 4.0))


# ---------------------------------------------------------------------------
# pipeline parallelism: schedule parity vs plain grad accumulation
# ---------------------------------------------------------------------------


class TestPipeline:
    def _build(self):
        from paddle_tpu.distributed.fleet import PipelineLayer, LayerDesc
        paddle.seed(31)
        return PipelineLayer(
            layers=[
                LayerDesc(nn.Linear, 4, 8),
                LayerDesc(nn.ReLU),
                LayerDesc(nn.Linear, 8, 8),
                LayerDesc(nn.ReLU),
                LayerDesc(nn.Linear, 8, 2),
            ],
            num_stages=2,
            loss_fn=nn.MSELoss(),
        )

    def test_stage_partition(self):
        pl = self._build()
        assert pl.segment_parts == [0, 3, 5]
        assert len(pl.get_stage_layers(0)) == 3
        assert len(pl.get_stage_layers(1)) == 2

    def test_train_batch_parity(self):
        strat = dist.fleet.DistributedStrategy()
        strat.hybrid_configs = {"pp_degree": 2, "dp_degree": 4,
                                "pp_configs": {"accumulate_steps": 4}}
        dist.fleet.init(is_collective=True, strategy=strat)
        try:
            pl = self._build()
            model = dist.fleet.distributed_model(pl)
            opt = dist.fleet.distributed_optimizer(
                paddle.optimizer.SGD(learning_rate=0.05,
                                     parameters=pl.parameters()))

            # oracle: same weights, plain full-batch step
            paddle.seed(31)
            ref = self._build()
            ref_opt = paddle.optimizer.SGD(learning_rate=0.05,
                                           parameters=ref.parameters())
            loss_fn = nn.MSELoss()

            rng = np.random.RandomState(1)
            for _ in range(3):
                x = paddle.to_tensor(rng.randn(8, 4).astype("float32"))
                y = paddle.to_tensor(rng.randn(8, 2).astype("float32"))
                pp_loss = model.train_batch([x, y], opt)
                ref_loss = loss_fn(ref(x), y)
                ref_loss.backward()
                ref_opt.step()
                ref_opt.clear_grad()
                # micro-batched mean-of-means == full-batch mean for equal splits
                np.testing.assert_allclose(float(pp_loss), float(ref_loss),
                                           rtol=1e-5, atol=1e-6)
        finally:
            dist.mesh.reset_mesh()

    def test_shared_layer_desc_ties_weights(self):
        from paddle_tpu.distributed.fleet import (PipelineLayer, LayerDesc,
                                                  SharedLayerDesc)
        paddle.seed(32)
        pl = PipelineLayer(
            layers=[
                SharedLayerDesc("embed", nn.Embedding, 16, 8),
                LayerDesc(nn.Linear, 8, 8),
                SharedLayerDesc("embed", nn.Embedding, 16, 8,
                                forward_func=lambda l, x: x @ l.weight.T),
            ],
            num_stages=1, loss_fn=nn.MSELoss())
        embeds = [l for l in pl.run_function if isinstance(l, nn.Embedding)]
        assert len(embeds) == 2
        assert embeds[0].weight is embeds[1].weight


# ---------------------------------------------------------------------------
# group sharded (ZeRO stages)
# ---------------------------------------------------------------------------


class TestGroupSharded:
    def test_stage3_param_sharding_and_training(self):
        from paddle_tpu.distributed.sharding import group_sharded_parallel
        dist.mesh.reset_mesh()
        dist.init_mesh({"sharding": 8})
        try:
            paddle.seed(41)
            model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 2))
            opt = paddle.optimizer.AdamW(learning_rate=0.01,
                                         parameters=model.parameters())
            model, opt, _ = group_sharded_parallel(model, opt, level="p_g_os")
            specs = [p._sharding_spec for p in model.parameters()
                     if p._sharding_spec is not None]
            assert specs, "no parameter got a sharding spec"

            rng = np.random.RandomState(2)
            x = paddle.to_tensor(rng.randn(16, 8).astype("float32"))
            y = paddle.to_tensor(rng.randn(16, 2).astype("float32"))
            losses = []
            for _ in range(8):
                loss = ((model(x) - y) ** 2).mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
                losses.append(float(loss))
            assert losses[-1] < losses[0] * 0.9
        finally:
            dist.mesh.reset_mesh()

    def test_stage1_optimizer_state_sharded(self):
        dist.mesh.reset_mesh()
        dist.init_mesh({"sharding": 8})
        try:
            from paddle_tpu.distributed.sharding import group_sharded_parallel
            paddle.seed(42)
            model = nn.Linear(8, 16)
            opt = paddle.optimizer.Adam(learning_rate=0.01,
                                        parameters=model.parameters())
            model, opt, _ = group_sharded_parallel(model, opt, level="os")
            x = paddle.randn([4, 8])
            ((model(x)) ** 2).mean().backward()
            opt.step()
            opt.clear_grad()
            # slots exist and are sharded over the sharding axis
            slots = opt._inner_opt._slots[id(model.weight)]
            sh = slots["moment1"].sharding
            assert "sharding" in str(sh.spec), sh
        finally:
            dist.mesh.reset_mesh()


# ---------------------------------------------------------------------------
# recompute
# ---------------------------------------------------------------------------


class TestRecompute:
    def test_recompute_parity_under_jit(self):
        from paddle_tpu.distributed.fleet.utils import recompute
        paddle.seed(51)
        inner = nn.Sequential(nn.Linear(4, 32), nn.ReLU(), nn.Linear(32, 4))

        class Net(nn.Layer):
            def __init__(self, use_rc):
                super().__init__()
                self.inner = inner
                self.head = nn.Linear(4, 2)
                self.use_rc = use_rc

            def forward(self, x):
                h = recompute(self.inner, x) if self.use_rc else self.inner(x)
                return self.head(h)

        net_rc = Net(True)
        net_plain = Net(False)
        net_plain.head = net_rc.head

        x = paddle.randn([4, 4])
        ref = net_plain(x)

        st = paddle.jit.to_static(net_rc)
        out = st(x)
        np.testing.assert_allclose(out.numpy(), ref.numpy(), rtol=1e-5, atol=1e-6)

        # grads flow through the recomputed region
        loss = st(x).sum()
        loss.backward()
        assert inner[0].weight.grad is not None


class TestGatherScatterObjects:
    def test_gather_to_dst(self):
        def worker():
            r = dist.get_rank()
            out = []
            dist.gather(paddle.to_tensor(np.array([float(r)], "float32")),
                        out, dst=1)
            return [t.numpy()[0] for t in out]

        res = dist.spawn(worker, nprocs=3).results
        assert res[1] == [0.0, 1.0, 2.0]
        assert res[0] == [] and res[2] == []

    def test_scatter_object_list(self):
        def worker():
            r = dist.get_rank()
            out = []
            payload = [{"rank": i, "x": i * 2} for i in range(3)] \
                if r == 0 else None
            dist.scatter_object_list(out, payload, src=0)
            return out[0]

        res = dist.spawn(worker, nprocs=3).results
        assert [v["x"] for v in res] == [0, 2, 4]
