"""Round-4 detection op surface (reference ``python/paddle/vision/ops.py``:
roi_pool / ps_roi_pool / deform_conv2d / matrix_nms / prior_box /
distribute_fpn_proposals — SURVEY.md §2.2 "vision"). Numerics are pinned
against direct loop oracles on tiny shapes; gradients must flow through
the differentiable ops."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.vision import ops as V


def _t(a):
    return paddle.to_tensor(np.asarray(a, np.float32))


# ---------------------------------------------------------------------------
# roi_pool / ps_roi_pool vs loop oracles
# ---------------------------------------------------------------------------

def _roi_pool_oracle(x, rois, img_idx, oh, ow, scale):
    r = rois.shape[0]
    _, c, h, w = x.shape
    out = np.zeros((r, c, oh, ow), np.float32)
    for ri in range(r):
        x1, y1, x2, y2 = np.round(rois[ri] * scale)
        rw = max(x2 - x1 + 1, 1.0)
        rh = max(y2 - y1 + 1, 1.0)
        for i in range(oh):
            hs = int(np.clip(np.floor(i * rh / oh) + y1, 0, h))
            he = int(np.clip(np.ceil((i + 1) * rh / oh) + y1, 0, h))
            for j in range(ow):
                ws = int(np.clip(np.floor(j * rw / ow) + x1, 0, w))
                we = int(np.clip(np.ceil((j + 1) * rw / ow) + x1, 0, w))
                if he <= hs or we <= ws:
                    continue
                out[ri, :, i, j] = x[img_idx[ri], :, hs:he, ws:we].max(
                    axis=(1, 2))
    return out


def test_roi_pool_matches_oracle_and_grads():
    rng = np.random.default_rng(0)
    x = rng.normal(size=(2, 3, 12, 16)).astype(np.float32)
    rois = np.asarray([[0, 0, 8, 8], [2, 3, 15, 11], [1, 1, 5, 4],
                       [0, 0, 15, 11]], np.float32)
    nums = np.asarray([2, 2], np.int32)
    out = V.roi_pool(_t(x), _t(rois), paddle.to_tensor(nums), (3, 4),
                     spatial_scale=0.5)
    want = _roi_pool_oracle(x, rois, [0, 0, 1, 1], 3, 4, 0.5)
    np.testing.assert_allclose(out.numpy(), want, rtol=1e-5, atol=1e-5)

    xt = paddle.to_tensor(x, stop_gradient=False)
    V.roi_pool(xt, _t(rois), paddle.to_tensor(nums), 2).sum().backward()
    assert np.isfinite(xt.grad.numpy()).all()
    assert np.abs(xt.grad.numpy()).sum() > 0


def _ps_roi_pool_oracle(x, rois, img_idx, oh, ow, scale):
    r = rois.shape[0]
    _, c, h, w = x.shape
    out_c = c // (oh * ow)
    out = np.zeros((r, out_c, oh, ow), np.float32)
    for ri in range(r):
        x1, y1, x2, y2 = np.round(rois[ri] * scale)
        rw = max(x2 - x1 + 1, 1.0)
        rh = max(y2 - y1 + 1, 1.0)
        for i in range(oh):
            hs = int(np.clip(np.floor(i * rh / oh) + y1, 0, h))
            he = int(np.clip(np.ceil((i + 1) * rh / oh) + y1, 0, h))
            for j in range(ow):
                ws = int(np.clip(np.floor(j * rw / ow) + x1, 0, w))
                we = int(np.clip(np.ceil((j + 1) * rw / ow) + x1, 0, w))
                if he <= hs or we <= ws:
                    continue
                for co in range(out_c):
                    ch = co * oh * ow + i * ow + j
                    out[ri, co, i, j] = x[img_idx[ri], ch,
                                          hs:he, ws:we].mean()
    return out


def test_ps_roi_pool_matches_oracle():
    rng = np.random.default_rng(1)
    oh = ow = 2
    x = rng.normal(size=(1, 3 * oh * ow, 10, 10)).astype(np.float32)
    rois = np.asarray([[0, 0, 6, 6], [2, 2, 9, 9]], np.float32)
    nums = np.asarray([2], np.int32)
    out = V.ps_roi_pool(_t(x), _t(rois), paddle.to_tensor(nums), oh, 1.0)
    want = _ps_roi_pool_oracle(x, rois, [0, 0], oh, ow, 1.0)
    np.testing.assert_allclose(out.numpy(), want, rtol=1e-5, atol=1e-5)


# ---------------------------------------------------------------------------
# deform_conv2d: zero offsets == plain conv; grads; v2 mask
# ---------------------------------------------------------------------------

def test_deform_conv_zero_offset_equals_conv():
    import paddle_tpu.nn.functional as F
    rng = np.random.default_rng(2)
    x = rng.normal(size=(2, 4, 9, 9)).astype(np.float32)
    wgt = rng.normal(size=(6, 4, 3, 3)).astype(np.float32) * 0.2
    b = rng.normal(size=(6,)).astype(np.float32)
    off = np.zeros((2, 2 * 9, 7, 7), np.float32)
    got = V.deform_conv2d(_t(x), _t(off), _t(wgt), _t(b))
    want = F.conv2d(_t(x), _t(wgt), _t(b))
    np.testing.assert_allclose(got.numpy(), want.numpy(), rtol=2e-4,
                               atol=2e-4)


def test_deform_conv_offsets_shift_sampling():
    """Integer offset (0, 1) with a 1x1 kernel shifts the input by one
    column (bilinear at integer points is exact)."""
    x = np.arange(16, dtype=np.float32).reshape(1, 1, 4, 4)
    wgt = np.ones((1, 1, 1, 1), np.float32)
    off = np.zeros((1, 2, 4, 4), np.float32)
    off[:, 1] = 1.0                                 # dx = +1
    got = V.deform_conv2d(_t(x), _t(off), _t(wgt)).numpy()[0, 0]
    want = np.zeros((4, 4), np.float32)
    want[:, :3] = x[0, 0][:, 1:]                    # shifted left
    np.testing.assert_allclose(got[:, :3], want[:, :3], atol=1e-6)
    np.testing.assert_allclose(got[:, 3], 0.0, atol=1e-6)  # out of bounds


def test_deform_conv_mask_and_grads():
    rng = np.random.default_rng(3)
    x = paddle.to_tensor(rng.normal(size=(1, 2, 6, 6)).astype(np.float32),
                         stop_gradient=False)
    wgt = paddle.to_tensor(rng.normal(size=(3, 2, 3, 3)).astype(np.float32),
                           stop_gradient=False)
    off = paddle.to_tensor(
        rng.normal(size=(1, 18, 4, 4)).astype(np.float32) * 0.3,
        stop_gradient=False)
    msk = paddle.to_tensor(
        (rng.random((1, 9, 4, 4)) * 0.5 + 0.5).astype(np.float32))
    out = V.deform_conv2d(x, off, wgt, mask=msk)
    assert out.shape == [1, 3, 4, 4]
    out.sum().backward()
    for t in (x, wgt, off):
        assert np.isfinite(t.grad.numpy()).all()
        assert np.abs(t.grad.numpy()).sum() > 0


def test_deform_conv_layer():
    layer = V.DeformConv2D(4, 8, 3, padding=1)
    x = _t(np.random.default_rng(4).normal(size=(2, 4, 8, 8)))
    off = _t(np.zeros((2, 18, 8, 8)))
    out = layer(x, off)
    assert out.shape == [2, 8, 8, 8]
    assert len(list(layer.parameters())) == 2


# ---------------------------------------------------------------------------
# matrix_nms / prior_box / distribute_fpn_proposals
# ---------------------------------------------------------------------------

def test_matrix_nms_suppresses_overlaps():
    boxes = np.asarray([[0, 0, 10, 10], [1, 1, 10.5, 10.5],
                        [20, 20, 30, 30]], np.float32)
    scores = np.asarray([[0.9, 0.85, 0.8]], np.float32)
    out, idx = V.matrix_nms(_t(boxes), _t(scores), score_threshold=0.1)
    o = out.numpy()
    assert o.shape[1] == 6
    assert int(idx.numpy()[0]) == 0 and o[0, 1] == pytest.approx(0.9)
    # the heavily-overlapping second box is decayed below the isolated one
    by_idx = {int(i): s for i, s in zip(idx.numpy(), o[:, 1])}
    assert by_idx[1] < by_idx[2] < by_idx[0]
    # gaussian decay variant also runs and keeps ordering
    out2, _ = V.matrix_nms(_t(boxes), _t(scores), 0.1, use_gaussian=True)
    assert out2.shape[0] == 3


def test_prior_box_shapes_and_normalization():
    feat = _t(np.zeros((1, 8, 4, 4)))
    img = _t(np.zeros((1, 3, 64, 64)))
    boxes, variances = V.prior_box(feat, img, min_sizes=[16.0],
                                   max_sizes=[32.0],
                                   aspect_ratios=[2.0], flip=True,
                                   clip=True)
    # priors: 1 (ar=1,min) + 2 (ar=2, 1/2) + 1 (sqrt(min*max)) = 4
    assert boxes.shape == [4, 4, 4, 4]
    b = boxes.numpy()
    assert (b >= 0).all() and (b <= 1).all()
    assert variances.shape == [4, 4, 4, 4]
    # center of cell (0,0) is at offset*step/img = 0.5*16/64
    cx = (b[0, 0, 0, 0] + b[0, 0, 0, 2]) / 2
    assert cx == pytest.approx(0.125, abs=1e-6)


def test_distribute_fpn_proposals():
    # input order deliberately NOT monotone in level, so the concatenated
    # per-level output is a non-trivial permutation of the input
    rois = np.asarray([[0, 0, 500, 500],      # big -> high level
                       [0, 0, 10, 10],        # small -> low level
                       [0, 0, 112, 112],      # ~sqrt(area)=112 -> middle
                       [0, 0, 11, 11]],       # small -> low level
                      np.float32)
    multi, restore, nums = V.distribute_fpn_proposals(
        _t(rois), min_level=2, max_level=5, refer_level=4, refer_scale=224,
        rois_num=paddle.to_tensor(np.asarray([4], np.int32)))
    sizes = [m.shape[0] for m in multi]
    assert sum(sizes) == 4 and len(multi) == 4
    assert sizes[0] >= 1 and sizes[-1] >= 1       # spread across levels
    # contract: cat(multi)[restore] recovers the ORIGINAL roi order
    cat = np.concatenate([m.numpy() for m in multi if m.shape[0]])
    inv = restore.numpy()[:, 0]
    assert not np.array_equal(inv, np.arange(4))  # permutation is real
    np.testing.assert_allclose(cat[inv], rois, atol=0)
    np.testing.assert_allclose(np.sort(inv), np.arange(4))
    assert [int(n.numpy()[0]) for n in nums] == sizes
