"""ZeRO (sharding stage 2/3) memory accounting (VERDICT.md round-1 item 6;
reference semantics: ``group_sharded_stage3.py`` params-sharded-at-rest +
grad reduce-scatter).

Proves the sharded layouts are real, not just claimed:
- params/opt-state at rest occupy ~1/shd of their global bytes per device,
- grads come OUT of the step already fsdp-sharded (the transpose of the
  ``unshard_for_compute`` all-gather is a reduce-scatter),
- the compiled step's per-device argument bytes shrink accordingly
  (``compiled.memory_analysis()`` when the backend reports it).
"""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.framework.functional import FunctionalModule
from paddle_tpu.models import llama_tiny, LlamaForCausalLM


def _bytes(a):
    return a.size * a.dtype.itemsize


def test_zero3_params_and_grads_sharded_at_rest():
    shd = 4
    mesh = mesh_mod.init_mesh({"dp": 2, "sharding": shd})
    try:
        paddle.seed(0)
        model = LlamaForCausalLM(llama_tiny())
        fm = FunctionalModule(model, training=True)
        specs = fm.param_specs(LlamaForCausalLM.sharding_rules(),
                               fsdp_axis="sharding", fsdp_size=shd)
        shards = [NamedSharding(mesh, s) for s in specs]
        p_arrs = [jax.device_put(a, s)
                  for a, s in zip(fm.param_arrays(), shards)]

        # at rest: every >=2-D param holds 1/shd of its bytes per device
        for a, spec in zip(p_arrs, specs):
            per_dev = a.addressable_shards[0].data.nbytes
            if a.ndim >= 2:
                assert "sharding" in jax.tree.leaves(tuple(spec)), spec
                assert per_dev * shd == _bytes(a), (a.shape, spec)
            else:
                assert per_dev == _bytes(a), (a.shape, spec)

        key = fm.next_key()
        rng = np.random.default_rng(0)
        ids = jax.device_put(
            jnp.asarray(rng.integers(0, 128, (8, 16)), jnp.int32),
            NamedSharding(mesh, P(("dp", "sharding"))))

        def grads_fn(ps, key, ids):
            def loss_fn(ps):
                ps = mesh_mod.unshard_for_compute(ps, specs, "sharding")
                (loss, _), _ = fm(ps, [], key, ids, labels=ids)
                return loss

            return jax.value_and_grad(loss_fn)(ps)

        step = jax.jit(grads_fn,
                       in_shardings=(shards, None,
                                     NamedSharding(mesh, P(("dp", "sharding")))),
                       out_shardings=(NamedSharding(mesh, P()), shards))
        with mesh:
            loss, grads = step(p_arrs, key, ids)
        assert np.isfinite(float(loss))
        # grads land fsdp-sharded (reduce-scatter), matching param layout
        for g, a in zip(grads, p_arrs):
            assert g.sharding == a.sharding, (g.shape, g.sharding, a.sharding)
            if g.ndim >= 2:
                assert g.addressable_shards[0].data.nbytes * shd == _bytes(g)

        # compiled accounting: per-device argument bytes must be well under
        # the global param bytes (i.e. XLA sees sharded storage, not
        # replicas). memory_analysis is backend-dependent; skip if absent.
        compiled = step.lower(p_arrs, key, ids).compile()
        ma = compiled.memory_analysis()
        if ma is not None and getattr(ma, "argument_size_in_bytes", 0):
            global_param_bytes = sum(_bytes(a) for a in p_arrs)
            big = sum(_bytes(a) for a in p_arrs if a.ndim >= 2)
            expect_args = global_param_bytes - big * (1 - 1 / shd)
            assert ma.argument_size_in_bytes < global_param_bytes * 0.7, (
                ma.argument_size_in_bytes, global_param_bytes, expect_args)
    finally:
        mesh_mod.reset_mesh()


def test_stage2_grads_sharded_at_backward_time():
    """ZeRO-2 contract: each grad lands on its 'sharding' layout the
    moment the tape accumulates it (hook), NOT at step() — peak grad
    memory during eager backward is bounded (VERDICT round-1 weak 6)."""
    import numpy as np
    import jax
    import paddle_tpu as paddle
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.distributed.fleet.meta_parallel.sharding import (
        GroupShardedStage2, GroupShardedOptimizerStage2)

    mesh_mod.init_mesh({"sharding": 4, "dp": 2})
    try:
        paddle.seed(0)
        m = paddle.nn.Sequential(paddle.nn.Linear(16, 32), paddle.nn.Tanh(),
                                 paddle.nn.Linear(32, 8))
        opt = GroupShardedOptimizerStage2(
            paddle.optimizer.AdamW(learning_rate=1e-3,
                                   parameters=m.parameters()))
        wrapped = GroupShardedStage2(m, opt)
        x = paddle.to_tensor(
            np.random.RandomState(0).randn(8, 16).astype(np.float32))
        loss = (wrapped(x) ** paddle.to_tensor(2.0)).mean()
        loss.backward()
        n_sharded = 0
        for p in m.parameters():
            if p.grad is not None:
                sh = p.grad._data.sharding
                if hasattr(sh, "spec") and any(
                        e == "sharding"
                        for e in jax.tree.leaves(tuple(sh.spec))):
                    n_sharded += 1
        assert n_sharded >= 2, n_sharded
        opt.step()     # sharded update still works
    finally:
        mesh_mod.reset_mesh()
