"""Ulysses all-to-all sequence-parallel attention (VERDICT.md round-2
item 9 / SURVEY.md §5.7 mechanism 2): parity vs the full-sequence oracle
and vs ring attention, fwd + grad, incl. GQA; Llama end-to-end with
cp_mode='ulysses'."""
import numpy as np
import jax
import jax.numpy as jnp
import pytest
from jax.sharding import NamedSharding, PartitionSpec as P

import paddle_tpu as paddle
from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.fleet.utils import (ring_attention,
                                                ulysses_attention,
                                                UlyssesAttention)
from paddle_tpu.ops.pallas.flash_attention import mha_reference


def _data(b=2, s=64, hq=8, hk=8, d=16, seed=0):
    rng = np.random.default_rng(seed)
    q = jnp.asarray(rng.normal(size=(b, s, hq, d)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(b, s, hk, d)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(b, s, hk, d)), jnp.float32)
    return q, k, v


def _oracle(q, k, v, causal=True):
    out = mha_reference(jnp.swapaxes(q, 1, 2), jnp.swapaxes(k, 1, 2),
                        jnp.swapaxes(v, 1, 2), causal=causal)
    return jnp.swapaxes(out, 1, 2)


@pytest.mark.parametrize("hk", [8, 4])   # MHA and GQA (group 2)
def test_ulysses_matches_oracle_and_ring(hk):
    mesh = mesh_mod.init_mesh({"dp": 2, "sep": 4})
    try:
        q, k, v = _data(hk=hk)
        sh = NamedSharding(mesh, P(None, "sep", None, None))
        qs, ks, vs = (jax.device_put(x, sh) for x in (q, k, v))
        out_u = jax.jit(lambda a, b_, c: ulysses_attention(a, b_, c))(
            qs, ks, vs)
        ref = _oracle(q, k, v)
        np.testing.assert_allclose(np.asarray(out_u), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)
        out_r = jax.jit(lambda a, b_, c: ring_attention(a, b_, c))(qs, ks, vs)
        np.testing.assert_allclose(np.asarray(out_u), np.asarray(out_r),
                                   rtol=2e-4, atol=2e-4)
    finally:
        mesh_mod.reset_mesh()


def test_ulysses_grad_matches_oracle():
    mesh = mesh_mod.init_mesh({"sep": 4, "dp": 2})
    try:
        q, k, v = _data()
        g = jnp.asarray(np.random.default_rng(5).normal(size=q.shape),
                        jnp.float32)

        def loss_u(q_, k_, v_):
            return jnp.sum(ulysses_attention(q_, k_, v_) * g)

        def loss_ref(q_, k_, v_):
            return jnp.sum(_oracle(q_, k_, v_) * g)

        gu = jax.jit(jax.grad(loss_u, argnums=(0, 1, 2)))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gu, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=2e-4, atol=2e-4)
    finally:
        mesh_mod.reset_mesh()


def test_ulysses_head_divisibility_guard():
    mesh_mod.init_mesh({"sep": 4, "dp": 2})
    try:
        q, k, v = _data(hq=6, hk=6)    # 6 % 4 != 0
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, k, v)
    finally:
        mesh_mod.reset_mesh()


def test_ulysses_facade_and_tensor_path():
    mesh_mod.init_mesh({"sep": 4, "dp": 2})
    try:
        q, k, v = _data()
        t = paddle.to_tensor(np.asarray(q))
        tk = paddle.to_tensor(np.asarray(k))
        tv = paddle.to_tensor(np.asarray(v))
        t.stop_gradient = False
        out = UlyssesAttention.apply(t, tk, tv)
        out.sum().backward()
        assert t.grad is not None
        np.testing.assert_allclose(np.asarray(out.numpy()),
                                   np.asarray(_oracle(q, k, v)),
                                   rtol=2e-4, atol=2e-4)
    finally:
        mesh_mod.reset_mesh()


def test_llama_cp_ulysses_matches_plain():
    from paddle_tpu.models import LlamaForCausalLM, llama_tiny
    from paddle_tpu.framework.functional import FunctionalModule

    paddle.seed(0)
    model = LlamaForCausalLM(llama_tiny(max_position_embeddings=128))
    model.eval()
    fm = FunctionalModule(model, training=False)
    p = fm.param_arrays()
    key = fm.next_key()
    ids = jnp.asarray(np.random.default_rng(0).integers(0, 128, (4, 64)),
                      jnp.int32)
    ref = jax.jit(lambda p_, i: fm(p_, [], key, i)[0])(p, ids)

    # llama_tiny has 2 kv heads (GQA) -> sep=2 respects the head limit
    mesh = mesh_mod.init_mesh({"dp": 4, "sep": 2})
    try:
        model.config.context_parallel = True
        model.config.cp_mode = "ulysses"
        ids_sh = jax.device_put(ids, NamedSharding(mesh, P("dp", "sep")))
        out = jax.jit(lambda p_, i: fm(p_, [], key, i)[0])(p, ids_sh)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-4)
    finally:
        model.config.context_parallel = False
        model.config.cp_mode = "ring"
        mesh_mod.reset_mesh()
