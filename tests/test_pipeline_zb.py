"""ZB-H1 pipeline schedule (VERDICT round-4 item 5; reference:
``pipeline_scheduler_pass`` ZBH1 — the zero-bubble family's H1 member:
backward split into B (activation grad, on the inter-stage wire) and W
(weight grad, deferred to fill bubble slots), at 1F1B-equal memory).

``schedule='zb'`` reuses the 1F1B-memory recompute scan but linearizes
each microbatch ONCE and evaluates the two transpose halves in different
ticks: dx immediately (the ppermute chain consumes it), dW one tick
later from the carried residuals — so the dW matmuls sit outside the
recv→B→send dependency chain. Gradients must be exact; compiled temp
memory must stay in the 1F1B class (far below fthenb's O(M) residuals).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from paddle_tpu.distributed import mesh as mesh_mod
from paddle_tpu.distributed.engine import _chunk_key, pipeline_forward
from conftest import requires_spmd_pipeline


def _stage(params, x):
    w1, b1, w2, b2 = params
    h = jax.nn.gelu(x @ w1 + b1)
    return jnp.tanh(h @ w2 + b2) + x


def _stoch_stage(params, x, key):
    w1, b1, w2, b2 = params
    keep = jax.random.bernoulli(key, 0.8, x.shape)
    h = jax.nn.gelu(x @ w1 + b1)
    return (jnp.tanh(h @ w2 + b2) + x) * keep


def _setup(n_chunks=4, n_micro=8, mb=2, d=8, hidden=16, seed=0):
    rng = np.random.default_rng(seed)
    params = (
        jnp.asarray(rng.normal(size=(n_chunks, d, hidden)) * 0.3, jnp.float32),
        jnp.asarray(rng.normal(size=(n_chunks, hidden)) * 0.1, jnp.float32),
        jnp.asarray(rng.normal(size=(n_chunks, hidden, d)) * 0.3, jnp.float32),
        jnp.asarray(rng.normal(size=(n_chunks, d)) * 0.1, jnp.float32),
    )
    micro = jnp.asarray(rng.normal(size=(n_micro, mb, d)), jnp.float32)
    return params, micro


def _sequential(params, micro, base_key=None):
    out = []
    for m in range(micro.shape[0]):
        x = micro[m]
        for c in range(params[0].shape[0]):
            p = tuple(a[c] for a in params)
            if base_key is None:
                x = _stage(p, x)
            else:
                x = _stoch_stage(p, x, _chunk_key(base_key, m, c))
        out.append(x)
    return jnp.stack(out)


@requires_spmd_pipeline
def test_zb_forward_matches_sequential():
    mesh_mod.init_mesh({"pp": 4, "dp": 2})
    try:
        params, micro = _setup()
        out = jax.jit(lambda p, x: pipeline_forward(
            _stage, p, x, schedule="zb"))(params, micro)
        np.testing.assert_allclose(np.asarray(out),
                                   np.asarray(_sequential(params, micro)),
                                   rtol=1e-5, atol=1e-5)
    finally:
        mesh_mod.reset_mesh()


@requires_spmd_pipeline
def test_zb_grads_match_fthenb_and_oracle():
    mesh_mod.init_mesh({"pp": 4, "dp": 2})
    try:
        params, micro = _setup()
        g = jnp.asarray(np.random.default_rng(5).normal(size=micro.shape),
                        jnp.float32)

        def loss(p, x, sched):
            return jnp.sum(pipeline_forward(_stage, p, x,
                                            schedule=sched) * g)

        gz, gxz = jax.jit(jax.grad(lambda p, x: loss(p, x, "zb"),
                                   argnums=(0, 1)))(params, micro)
        g0, gx0 = jax.jit(jax.grad(lambda p, x: loss(p, x, "fthenb"),
                                   argnums=(0, 1)))(params, micro)
        gs, gxs = jax.grad(lambda p, x: jnp.sum(_sequential(p, x) * g),
                           argnums=(0, 1))(params, micro)
        for a, b in zip(jax.tree.leaves(gz), jax.tree.leaves(g0)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
        for a, b in zip(jax.tree.leaves(gz), jax.tree.leaves(gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gxz), np.asarray(gx0),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gxz), np.asarray(gxs),
                                   rtol=1e-4, atol=1e-5)
    finally:
        mesh_mod.reset_mesh()


@requires_spmd_pipeline
def test_zb_dropout_grads_match_sequential():
    """The B tick's linearization and the W tick's deferred transpose
    must replay the SAME per-(micro, chunk) dropout mask."""
    mesh_mod.init_mesh({"pp": 4, "dp": 2})
    try:
        params, micro = _setup(n_micro=6)
        base = jax.random.key(11)
        g = jnp.asarray(np.random.default_rng(7).normal(size=micro.shape),
                        jnp.float32)

        def loss_pipe(p):
            return jnp.sum(pipeline_forward(_stoch_stage, p, micro,
                                            rng_key=base,
                                            schedule="zb") * g)

        def loss_seq(p):
            return jnp.sum(_sequential(p, micro, base) * g)

        gp = jax.jit(jax.grad(loss_pipe))(params)
        gs = jax.grad(loss_seq)(params)
        for a, b in zip(jax.tree.leaves(gp), jax.tree.leaves(gs)):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=1e-4, atol=1e-5)
    finally:
        mesh_mod.reset_mesh()


def test_zb_rejects_vpp():
    mesh_mod.init_mesh({"pp": 4, "dp": 2})
    try:
        params, micro = _setup(n_chunks=8)
        with pytest.raises(ValueError, match="vpp"):
            pipeline_forward(_stage, params, micro, vpp_degree=2,
                             schedule="zb")
    finally:
        mesh_mod.reset_mesh()


@requires_spmd_pipeline
def test_zb_memory_in_1f1b_class():
    """ZBH1's contract vs the schedule family (VERDICT round-4 item 5
    asks for the memory_analysis comparison at M=8, S=4): temp memory
    far below fthenb's O(M) residual sets, and within a small constant
    of 1f1b (the extra carried (residuals, cotangent) slot — H1 keeps
    1F1B-class memory, unlike ZB-V's 2x)."""
    mesh_mod.init_mesh({"pp": 4, "dp": 2})
    try:
        params, micro = _setup(n_chunks=4, n_micro=8, mb=4, d=64, hidden=256)

        def make_loss(sched):
            def loss(p, x):
                return jnp.sum(pipeline_forward(_stage, p, x,
                                                schedule=sched) ** 2)
            return jax.jit(jax.grad(loss))

        sizes = {}
        for sched in ("fthenb", "1f1b", "zb"):
            compiled = make_loss(sched).lower(params, micro).compile()
            ma = compiled.memory_analysis()
            assert ma is not None, "memory_analysis unavailable"
            sizes[sched] = int(ma.temp_size_in_bytes)
        assert sizes["zb"] < 0.6 * sizes["fthenb"], sizes
        assert sizes["zb"] < 2.0 * sizes["1f1b"], sizes
    finally:
        mesh_mod.reset_mesh()
