"""CI gate: every SURVEY.md §2 inventory item resolves to real symbols."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


def test_inventory_complete():
    from check_inventory import check
    failures = check(verbose=False)
    assert not failures, failures
