"""CI gate: every SURVEY.md §2 inventory item resolves to real symbols."""
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "tools"))


def test_inventory_complete():
    from check_inventory import check
    failures = check(verbose=False)
    assert not failures, failures


def test_strategy_fields_documented():
    """Every public DistributedStrategy field is mentioned in
    docs/PERF.md, so future knobs stay documented."""
    from check_inventory import check_strategy_docs
    missing = check_strategy_docs(verbose=False)
    assert not missing, f"undocumented DistributedStrategy fields: {missing}"


def test_env_knobs_documented():
    """Every PADDLE_* env knob referenced in paddle_tpu/ is mentioned in
    a docs/*.md file (same discoverability rule as the strategy fields)."""
    from check_inventory import check_env_docs
    missing = check_env_docs(verbose=False)
    assert not missing, f"undocumented PADDLE_* env knobs: {missing}"


def test_fleet_knobs_covered():
    """Every PADDLE_FLEET_* knob is documented in docs/SERVING.md and
    every router policy string is exercised by a test (and documented)."""
    from check_inventory import check_fleet_knobs
    violations = check_fleet_knobs(verbose=False)
    assert not violations, violations


def test_observability_catalog():
    """Every paddle_request_*/paddle_slo_* metric and PADDLE_SLO_*/
    PADDLE_REQUEST_TRACE* knob referenced in paddle_tpu/ is cataloged in
    docs/OBSERVABILITY.md."""
    from check_inventory import check_observability_catalog
    violations = check_observability_catalog(verbose=False)
    assert not violations, violations


def test_alert_catalog():
    """Every PADDLE_HISTORY_*/PADDLE_ALERT_*/PADDLE_REPLAY_*/
    PADDLE_TELEMETRY_* knob and paddle_history_*/paddle_alert* metric
    is cataloged in docs/OBSERVABILITY.md AND exercised by a test, and
    every replay preset appears in a test."""
    from check_inventory import check_alert_catalog
    violations = check_alert_catalog(verbose=False)
    assert not violations, violations


def test_training_observability_catalog():
    """Every PADDLE_NUMERICS_*/PADDLE_MEMORY_*/PADDLE_STEP_PHASE* knob
    and paddle_numerics_*/paddle_memory_*/paddle_step_phase_* metric is
    cataloged in docs/OBSERVABILITY.md AND exercised by a test."""
    from check_inventory import check_training_observability
    violations = check_training_observability(verbose=False)
    assert not violations, violations


def test_ledger_catalog():
    """Every PADDLE_LEDGER* knob and paddle_ledger_* metric is cataloged
    in docs/OBSERVABILITY.md AND exercised by a test."""
    from check_inventory import check_ledger_catalog
    violations = check_ledger_catalog(verbose=False)
    assert not violations, violations


def test_controller_catalog():
    """Every PADDLE_CONTROLLER_* knob, paddle_controller_* metric,
    controller action string, fleet fault directive and structured
    rejection reason is documented AND exercised by a test."""
    from check_inventory import check_controller_catalog
    violations = check_controller_catalog(verbose=False)
    assert not violations, violations


def test_telemetry_plane_catalog():
    """Every PADDLE_TELEMETRY_*/PADDLE_EVENTLOG* knob,
    paddle_telemetry_*/paddle_eventlog_* metric and exporter HTTP route
    is cataloged in docs/OBSERVABILITY.md AND exercised by a test."""
    from check_inventory import check_telemetry_plane
    violations = check_telemetry_plane(verbose=False)
    assert not violations, violations


def test_serving_program_budget():
    """Compiled-program guard: a mixed prefill+decode load stays inside
    the ragged scheduler's declared token-bucket family (no per-request
    shapes / unbounded recompiles) and exercises both token kinds; the
    speculative pass proves verify spans (q_len = 1+k) stay inside the
    SAME family — spec decode must not explode the program set."""
    from check_inventory import check_serving_programs
    violations = check_serving_programs(verbose=False)
    assert not violations, violations


def test_quantized_config_catalog():
    """Quantized-config guard (ISSUE 16): every device-tier decode-speed
    knob (PADDLE_WEIGHT_DTYPE / PADDLE_TPU_RAGGED_QBLOCK /
    PADDLE_SPEC_DRAFT_BATCH / PADDLE_TPU_RAGGED_IMPL / PADDLE_KV_DTYPE)
    is documented in docs/*.md AND exercised by a test, and the
    fully-int8 serving config (int8 weights + int8 KV pages on the
    q-block ragged grid) is bit-stable across two same-seed runs with a
    matching token digest."""
    from check_inventory import check_quantized_config
    violations = check_quantized_config(verbose=False)
    assert not violations, violations


def test_compile_observatory_catalog():
    """Compile-observatory guard (ISSUE 18): every PADDLE_COMPILE* knob
    and paddle_compile_* metric is cataloged in docs/OBSERVABILITY.md
    AND exercised by a test; a warmed engine's mixed replay observes
    only declared program families, every declared family has a warmup
    entry, and zero post-warmup trace-cache misses occur."""
    from check_inventory import check_compile_observatory
    violations = check_compile_observatory(verbose=False)
    assert not violations, violations


def test_kv_tier_catalog():
    """Tiered-KV guard (ISSUE 19): every PADDLE_KV_HOST_* / PADDLE_SEP_*
    knob is documented in docs/SERVING.md AND exercised by a test, and
    every paddle_kv_* metric (plus the tier-labelled prefix-eviction
    counter) is cataloged in docs/OBSERVABILITY.md AND exercised by a
    test."""
    from check_inventory import check_kv_tier
    violations = check_kv_tier(verbose=False)
    assert not violations, violations


def test_paddle_flops():
    import numpy as np
    import paddle_tpu as paddle
    from paddle_tpu import nn

    net = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU(),
                        nn.Flatten(), nn.Linear(8 * 16, 5))
    total = paddle.flops(net, (2, 3, 4, 4))
    # reference MAC convention with bias: out_numel * (Cin*K + 1)
    conv = 2 * 4 * 4 * 8 * (3 * 9 + 1)
    relu = 2 * 8 * 4 * 4
    lin = 2 * 5 * (128 + 1)
    assert total == conv + relu + lin, (total, conv + relu + lin)
    # bare leaf layer counts too
    leaf = paddle.flops(nn.Linear(10, 20, bias_attr=False), (4, 10))
    assert leaf == 4 * 20 * 10, leaf


def test_compat_namespaces():
    import numpy as np
    import paddle_tpu as paddle

    assert paddle.iinfo("int8").max == 127
    assert abs(paddle.finfo("float16").eps - 0.000977) < 1e-5
    x = paddle.to_tensor(np.zeros((4, 6), np.float32))
    c = paddle.crop(x, shape=[2, -1], offsets=[1, 2])
    assert tuple(c.shape) == (2, 4)
    assert paddle.version.cuda() == "False"
    assert paddle.tensor.matmul is paddle.matmul
    p = paddle.create_parameter([2, 2], is_bias=True)
    assert float(np.abs(np.asarray(p.numpy())).sum()) == 0.0
    v = paddle.view(paddle.to_tensor(np.zeros((2, 6), np.float32)), [3, 4])
    assert tuple(v.shape) == (3, 4)
    tl = np.asarray(paddle.tril_indices(3).numpy())
    want_r, want_c = np.tril_indices(3)
    np.testing.assert_array_equal(tl, np.stack([want_r, want_c]))
    hist = paddle.histogramdd(paddle.to_tensor(
        np.random.rand(20, 2).astype(np.float32)), bins=4)
    assert np.asarray(hist[0].numpy()).sum() == 20
