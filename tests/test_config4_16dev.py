"""Config-4's REAL shape: dp=2 × pp=2 × sharding=2 × mp=2 — all four
axes >1 SIMULTANEOUSLY in one jitted program (reference: the GPT-1.3B
hybrid of Fleet dp+mp+pp + Sharding; SURVEY.md §2.4 config 4, §3.4;
VERDICT round-4 missing #3).

Needs 16 devices, so the 8-device suite mesh can't host it: the check
runs in its own sanitized 16-virtual-device CPU subprocess via
``__graft_entry__.py --config4``, which asserts loss AND grad parity
against the sequential single-device oracle plus that both the ZeRO-3
('sharding', input dim) and Megatron ('mp', output dim) weight shardings
actually took on the stacked block leaves."""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_config4_four_axis_mesh_parity():
    sys.path.insert(0, REPO)
    from __graft_entry__ import _sanitized_cpu_env

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "__graft_entry__.py"),
         "--config4"],
        env=_sanitized_cpu_env(16), cwd=REPO, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, timeout=420)
    assert proc.returncode == 0, proc.stdout[-2000:]
    assert "dryrun config4 OK: mesh=(dp=2, pp=2, sharding=2, mp=2)" \
        in proc.stdout, proc.stdout[-2000:]
