"""Serving fleet (ISSUE 8): prefix-affinity router over N engine
replicas — routing parity vs the single-engine oracle, affinity vs
round-robin cache locality, per-tenant quota rejections, replica
kill/requeue, drain/rejoin, and disaggregated prefill→decode handoff."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.distributed.fleet.elastic.tcp_kv import MemKVStore
from paddle_tpu.inference import (Rejected, ROUTER_POLICIES,
                                  ServingRouter)
from paddle_tpu.models import LlamaForCausalLM, llama_tiny

ENGINE_KW = dict(max_batch_size=4, max_len=160, page_size=16,
                 prefill_chunk_tokens=32)


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return LlamaForCausalLM(llama_tiny(num_hidden_layers=1,
                                       max_position_embeddings=256))


def _oracle(model, p, n):
    return np.asarray(model.generate(paddle.to_tensor(p),
                                     max_new_tokens=n)._data)


def _mixed_workload(n_req=12, sys_len=64, tail=8, seed=0):
    """n_req single-sequence prompts sharing a sys_len-token system
    prompt (page-aligned: sys_len/16 full shared blocks) with unique
    tails, cycled over 3 tenants."""
    rng = np.random.RandomState(seed)
    sys_prompt = rng.randint(0, 128, sys_len)
    prompts = [np.concatenate([sys_prompt, rng.randint(0, 128, tail)])
               .astype(np.int64)[None] for _ in range(n_req)]
    tenants = [f"tenant{i % 3}" for i in range(n_req)]
    return prompts, tenants


def _run_fleet(router, prompts, tenants, max_new, results=None,
               errors=None, first_alone=True):
    """Drive the workload: request 0 first (it fills and commits the
    shared prefix somewhere), the rest concurrently."""
    results = [None] * len(prompts) if results is None else results
    errors = [None] * len(prompts) if errors is None else errors

    def call(i):
        try:
            results[i] = np.asarray(router.generate(
                prompts[i], max_new_tokens=max_new, tenant=tenants[i],
                timeout=600).numpy())
        except Exception as e:          # noqa: BLE001 — asserted by tests
            errors[i] = e

    start = 0
    if first_alone:
        call(0)
        start = 1
    threads = [threading.Thread(target=call, args=(i,))
               for i in range(start, len(prompts))]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    return results, errors


# ---------------------------------------------------------------------------
# acceptance (a)+(b): 3-replica mixed-tenant parity + affinity locality
# ---------------------------------------------------------------------------

def test_fleet_acceptance_parity_and_affinity(model):
    """3 replicas, 12 requests from 3 tenants sharing a system prompt:
    every output is bit-identical to the single-engine oracle, >= 80% of
    the shared-prefix requests land on the replica holding the chain,
    and the fleet-wide cached-token count beats round-robin routing."""
    prompts, tenants = _mixed_workload()
    want = [_oracle(model, p, 3) for p in prompts]

    def run(policy):
        router = ServingRouter(model, num_replicas=3, policy=policy,
                               engine_kwargs=ENGINE_KW, store=MemKVStore(),
                               heartbeat_ttl=60.0)
        with router:
            results, errors = _run_fleet(router, prompts, tenants, 3)
            cached = sum(r.engine._cache.cached_tokens_total
                         for r in router.replicas)
            stats = router.stats()
        assert not [e for e in errors if e], errors
        return results, cached, stats

    got_aff, cached_aff, stats = run("affinity")
    for g, w in zip(got_aff, want):
        np.testing.assert_array_equal(g, w)                       # (a)
    # (b) every follower shares the 4-block chain: >= 80% must be routed
    # to the replica the router believes holds it
    assert stats["affinity_matchable"] >= 11
    hit_rate = stats["affinity_hits"] / stats["affinity_matchable"]
    assert hit_rate >= 0.8, stats
    got_rr, cached_rr, _ = run("round_robin")
    for g, w in zip(got_rr, want):
        np.testing.assert_array_equal(g, w)     # rr parity rides along
    assert cached_aff > cached_rr, (cached_aff, cached_rr)        # (b)


# ---------------------------------------------------------------------------
# acceptance (c): per-tenant quota — structured rejection, others fine
# ---------------------------------------------------------------------------

def test_fleet_tenant_quota_rejections(model):
    prompts, _ = _mixed_workload(n_req=9)
    want = [_oracle(model, p, 3) for p in prompts]
    # each request costs 72 prompt + 3 decode = 75 tokens; "capped" can
    # afford exactly two before its fleet-wide bucket runs dry
    router = ServingRouter(model, num_replicas=3,
                           engine_kwargs=ENGINE_KW, store=MemKVStore(), heartbeat_ttl=60.0,
                           tenant_quotas={"capped": (150, 0.0)})
    tenants = ["capped" if i % 3 == 0 else f"tenant{i % 3}"
               for i in range(9)]
    with router:
        results, errors = _run_fleet(router, prompts, tenants, 3)
        stats = router.stats()
    rejected = [i for i, e in enumerate(errors) if e is not None]
    for i in rejected:
        assert isinstance(errors[i], Rejected), errors[i]
        assert errors[i].reason == "tenant_quota"
        assert tenants[i] == "capped"
    assert len(rejected) == 1, errors          # 3 capped requests, 2 fit
    assert stats["rejected_total"] == 1
    for i in range(9):                         # everyone else completed
        if i not in rejected:
            np.testing.assert_array_equal(results[i], want[i])
    assert router.quota.usage("capped") == 150


def test_fleet_queue_full_backpressure(model):
    p = np.random.RandomState(3).randint(0, 128, (1, 24)).astype(np.int64)
    router = ServingRouter(model, num_replicas=2, policy="balance",
                           engine_kwargs=ENGINE_KW,
                           store=MemKVStore(), max_queue_tokens=1,
                           heartbeat_ttl=60.0)
    with router:
        # occupy both replicas, then admission must refuse immediately
        t = threading.Thread(target=lambda: router.generate(
            p, max_new_tokens=8, timeout=600))
        t2 = threading.Thread(target=lambda: router.generate(
            p, max_new_tokens=8, timeout=600))
        t.start()
        t2.start()
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if all(r.load_tokens >= 1 for r in router.replicas):
                break
            time.sleep(0.01)
        with pytest.raises(Rejected) as exc:
            router.generate(p, max_new_tokens=8, timeout=600)
        assert exc.value.reason == "queue_full"
        t.join()
        t2.join()


# ---------------------------------------------------------------------------
# acceptance (d): replica death mid-decode -> requeue, parity preserved
# ---------------------------------------------------------------------------

def test_fleet_replica_kill_requeues(model):
    prompts, tenants = _mixed_workload(n_req=6, sys_len=32, seed=2)
    want = [_oracle(model, p, 16) for p in prompts]
    # TTL is deliberately generous: kill_replica() models a dead PROCESS,
    # so the fast attempt-failure path requeues without waiting for the
    # sweep (the sweep path gets its own test below)
    router = ServingRouter(model, num_replicas=3, policy="balance",
                           engine_kwargs=ENGINE_KW, store=MemKVStore(),
                           heartbeat_ttl=60.0)
    with router:
        results, errors = [None] * 6, [None] * 6
        threads = [threading.Thread(
            target=lambda i=i: _run_one(router, prompts, tenants, i,
                                        results, errors))
            for i in range(6)]
        for t in threads:
            t.start()
        # wait for real in-flight work, then kill that replica's
        # heartbeat — the health loop must miss the TTL, hard-abort the
        # engine, and the dispatch layer requeues to survivors
        deadline = time.monotonic() + 5
        victim = None
        while time.monotonic() < deadline:
            busy = [r for r in router.replicas if r.inflight]
            if busy:
                victim = max(busy, key=lambda r: len(r.inflight))
                break
            time.sleep(0.01)
        assert victim is not None, "no in-flight work to kill under"
        router.kill_replica(victim.id)
        for t in threads:
            t.join()
        stats = router.stats()
    assert not [e for e in errors if e], errors
    for g, w in zip(results, want):
        np.testing.assert_array_equal(g, w)
    assert not stats["replicas"][victim.id]["alive"]
    assert stats["requeues_total"] >= 1, stats


def test_fleet_missed_ttl_marks_dead_and_rejoins(model):
    """A replica whose heartbeats stop (zombie process) is detected by
    the health loop's TTL sweep, aborted, and can later rejoin."""
    p = np.random.RandomState(7).randint(0, 128, (1, 16)).astype(np.int64)
    want = _oracle(model, p, 2)
    router = ServingRouter(model, num_replicas=2, engine_kwargs=ENGINE_KW,
                           store=MemKVStore(), heartbeat_interval=0.05,
                           heartbeat_ttl=0.3)
    with router:
        router.kill_replica("r1", hard=False)     # heartbeat goes silent
        deadline = time.monotonic() + 10
        while router._replica("r1").alive and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not router._replica("r1").alive
        # relax the TTL before serving: the interpret-mode forward holds
        # the GIL long enough to starve the survivor's own heartbeat
        # thread past a 0.3s deadline (the sweep itself is proven above)
        router.heartbeat_ttl = 60.0
        # survivors keep serving, and the recovered replica rejoins
        np.testing.assert_array_equal(np.asarray(router.generate(
            p, max_new_tokens=2, timeout=600).numpy()), want)
        router.rejoin("r1")
        assert router._replica("r1").alive


def _run_one(router, prompts, tenants, i, results, errors):
    try:
        results[i] = np.asarray(router.generate(
            prompts[i], max_new_tokens=16, tenant=tenants[i],
            timeout=600).numpy())
    except Exception as e:              # noqa: BLE001 — asserted by tests
        errors[i] = e


# ---------------------------------------------------------------------------
# acceptance (e): disaggregated prefill -> decode bit-parity
# ---------------------------------------------------------------------------

def test_fleet_disagg_handoff_parity(model):
    prompts, tenants = _mixed_workload(n_req=4, sys_len=48, seed=4)
    want = [_oracle(model, p, 4) for p in prompts]
    router = ServingRouter(model, num_replicas=2, disagg=True,
                           engine_kwargs=ENGINE_KW, store=MemKVStore(),
                           heartbeat_ttl=60.0)
    assert [r.role for r in router.replicas] == ["prefill", "decode"]
    with router:
        results, errors = _run_fleet(router, prompts, tenants, 4)
        pre, dec = router.replicas
        stats = router.stats()
        # the prefill replica never ran a decode step; the decode
        # replica served the prefix straight from the imported pages
        assert pre.engine.decode_steps == 0
        assert dec.engine._cache.pages_imported > 0
        assert pre.engine._cache.pages_exported > 0
        assert dec.engine._cache.prefix_hits > 0
    assert not [e for e in errors if e], errors
    for g, w in zip(results, want):
        np.testing.assert_array_equal(g, w)
    assert stats["handoff_pages"] > 0


# ---------------------------------------------------------------------------
# drain / rejoin
# ---------------------------------------------------------------------------

def test_fleet_drain_and_rejoin(model):
    p = np.random.RandomState(5).randint(0, 128, (1, 20)).astype(np.int64)
    want = _oracle(model, p, 3)
    router = ServingRouter(model, num_replicas=2, engine_kwargs=ENGINE_KW,
                           store=MemKVStore(), heartbeat_ttl=60.0)
    with router:
        np.testing.assert_array_equal(np.asarray(router.generate(
            p, max_new_tokens=3, timeout=600).numpy()), want)
        router.drain("r0")
        assert not router._replica("r0").alive
        np.testing.assert_array_equal(np.asarray(router.generate(
            p, max_new_tokens=3, timeout=600).numpy()), want)
        router.rejoin("r0")
        assert router._replica("r0").alive
        np.testing.assert_array_equal(np.asarray(router.generate(
            p, max_new_tokens=3, timeout=600).numpy()), want)


# ---------------------------------------------------------------------------
# knobs & policies
# ---------------------------------------------------------------------------

def test_fleet_affinity_knob_zero_is_balance(model, monkeypatch):
    """PADDLE_FLEET_AFFINITY=0 turns affinity scoring into pure
    least-loaded: no route is labeled an affinity decision."""
    monkeypatch.setenv("PADDLE_FLEET_AFFINITY", "0")
    prompts, tenants = _mixed_workload(n_req=4)
    router = ServingRouter(model, num_replicas=2, engine_kwargs=ENGINE_KW,
                           store=MemKVStore(), heartbeat_ttl=60.0)
    assert router.affinity == 0.0
    from paddle_tpu.profiler.telemetry import get_registry
    fam = get_registry().collect().get("paddle_fleet_routed_total", {})
    before = dict(fam.get("series", {}))
    with router:
        _run_fleet(router, prompts, tenants, 2)
    fam = get_registry().collect()["paddle_fleet_routed_total"]
    delta = {k: v - before.get(k, 0) for k, v in fam["series"].items()}
    assert delta.get("balance", 0) == 4, delta
    assert delta.get("affinity", 0) == 0, delta


def test_fleet_env_knobs(model, monkeypatch):
    monkeypatch.setenv("PADDLE_FLEET_DISAGG", "1")
    monkeypatch.setenv("PADDLE_FLEET_TENANT_TOKENS", "512")
    monkeypatch.setenv("PADDLE_FLEET_MAX_QUEUE_TOKENS", "64")
    monkeypatch.setenv("PADDLE_FLEET_HEARTBEAT_TTL_S", "2.5")
    router = ServingRouter(model, num_replicas=2, store=MemKVStore())
    assert router.disagg
    assert router.quota is not None and router.quota.capacity == 512
    assert router.max_queue_tokens == 64
    assert router.heartbeat_ttl == 2.5


def test_router_policy_surface(model):
    assert set(ROUTER_POLICIES) == {"affinity", "balance", "round_robin",
                                    "disagg"}
    with pytest.raises(ValueError):
        ServingRouter(model, num_replicas=2, policy="disagg")
    with pytest.raises(ValueError):
        ServingRouter(model, num_replicas=1, disagg=True)


# ---------------------------------------------------------------------------
# telemetry & state provider
# ---------------------------------------------------------------------------

def test_fleet_telemetry_and_state_provider(model):
    from paddle_tpu.profiler import flight_recorder as flight
    from paddle_tpu.profiler.telemetry import get_registry
    prompts, tenants = _mixed_workload(n_req=4)
    router = ServingRouter(model, num_replicas=2, engine_kwargs=ENGINE_KW,
                           store=MemKVStore(), heartbeat_ttl=60.0,
                           tenant_quotas={"tenant1": (10, 0.0)})
    with router:
        errors = _run_fleet(router, prompts, tenants, 2)[1]
        key = router._flight_key
        assert key in flight._STATE_PROVIDERS
        state = flight._STATE_PROVIDERS[key]()
        assert state["routed_total"] >= 3
        assert set(state["replicas"]) == {"r0", "r1"}
    assert any(isinstance(e, Rejected) for e in errors)   # tenant1 capped
    snap = get_registry().collect()
    for fam in ("paddle_fleet_routed_total", "paddle_fleet_requeues_total",
                "paddle_fleet_rejected_total",
                "paddle_fleet_affinity_hit_rate",
                "paddle_fleet_replica_queue_depth",
                "paddle_fleet_replicas_alive"):
        assert fam in snap, fam
    assert any("tenant_quota" in k
               for k in snap["paddle_fleet_rejected_total"]["series"])
    # the heartbeat landed in the KV store via the flight-recorder path
    states = flight.gather_component_states(router.store, "fleet/replica/")
    assert set(states) == {"fleet/replica/r0", "fleet/replica/r1"}
    assert states["fleet/replica/r0"]["engine"] == "continuous"
    # stop() tears the provider down
    assert key not in flight._STATE_PROVIDERS
