"""int8 end-to-end (VERDICT.md round-3 item 5; reference:
``python/paddle/quantization/`` PTQ observers → static quantization →
int8 inference — SURVEY.md §2.2 "quantization").

The full chain under test: PTQ observer wrapping → calibration over a
DataLoader → ``convert`` (int8 weights + per-channel scales, calibrated
activation scales recorded) → ``paddle.jit.save`` → ``paddle.inference``
Config/Predictor → execution routed through the Pallas weight-only int8
matmul (``ops/pallas/quant_matmul.py``), with accuracy pinned against the
fp32 model (<1% top-1 delta on the CIFAR-shaped ResNet)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu import nn
from paddle_tpu.inference import Config, create_predictor
from paddle_tpu.io import DataLoader
from paddle_tpu.jit import InputSpec
from paddle_tpu.quantization import PTQ, QuantConfig, AbsmaxObserver, \
    QuantedLinear, calibrate
from paddle_tpu.vision.datasets import FakeData
from paddle_tpu.vision.models import resnet18


def _train_briefly(model, loader, steps=8):
    opt = paddle.optimizer.Adam(learning_rate=1e-3,
                                parameters=model.parameters())
    crit = nn.CrossEntropyLoss()
    it = iter(loader)
    for _ in range(steps):
        try:
            xb, yb = next(it)
        except StopIteration:
            it = iter(loader)
            xb, yb = next(it)
        loss = crit(model(xb), yb)
        loss.backward()
        opt.step()
        opt.clear_grad()
    model.eval()


def test_ptq_int8_resnet_end_to_end(tmp_path):
    paddle.seed(7)
    model = resnet18(num_classes=10)
    ds = FakeData(size=128, image_shape=(3, 32, 32))
    loader = DataLoader(ds, batch_size=16, shuffle=True, drop_last=True)
    _train_briefly(model, loader, steps=6)

    # fp32 reference predictions
    xs = np.stack([np.asarray(ds[i][0]) for i in range(64)])
    fp32_logits = model(paddle.to_tensor(xs)).numpy()
    fp32_top1 = fp32_logits.argmax(-1)

    # PTQ: observer wrapping → calibration over the loader → convert
    ptq = PTQ(QuantConfig(activation=AbsmaxObserver(), weight=None))
    ptq.quantize(model)
    seen = calibrate(model, loader, steps=4)
    assert seen == 4
    quanted = [s for s in model.sublayers() if isinstance(s, QuantedLinear)]
    assert quanted and all(q.a_q.scale > 0 for q in quanted), \
        "calibration must populate activation observers"
    ptq.convert(model)
    assert all(q._converted and q.act_scale is not None for q in quanted)

    int8_logits = model(paddle.to_tensor(xs)).numpy()
    agree = float((int8_logits.argmax(-1) == fp32_top1).mean())
    assert agree >= 0.99, f"top-1 delta {1-agree:.3%} exceeds 1%"

    # export → Predictor: the served program must reproduce the converted
    # model (int8 weights baked into the artifact as i8 constants)
    prefix = str(tmp_path / "resnet_int8")
    paddle.jit.save(model, prefix,
                    input_spec=[InputSpec([8, 3, 32, 32], "float32", "x")])
    cfg = Config(prefix)
    cfg.switch_ir_debug(True)
    pred = create_predictor(cfg)
    with open(prefix + ".hlo.txt") as f:
        assert "xi8" in f.read(), "program must embed int8 weight constants"
    (got,) = pred.run([xs[:8]])
    np.testing.assert_allclose(got, int8_logits[:8], rtol=2e-4, atol=2e-4)


def test_int8_linear_routes_through_pallas_kernel(monkeypatch):
    """The converted Linear must execute ops/pallas/quant_matmul.int8_matmul
    (not a silent dequant fallback) and match the dequantized math."""
    from paddle_tpu.ops.pallas import quant_matmul as qm

    calls = []
    real = qm.int8_matmul

    def spy(x, w, s, **kw):
        calls.append(w.dtype)
        return real(x, w, s, **kw)

    monkeypatch.setattr(qm, "int8_matmul", spy)

    paddle.seed(11)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    ptq = PTQ(QuantConfig(activation=AbsmaxObserver(), weight=None))
    ptq.quantize(model)
    xs = np.random.RandomState(0).randn(8, 16).astype(np.float32)
    calibrate(model, [xs], steps=1)
    ptq.convert(model)
    model.eval()

    out = model(paddle.to_tensor(xs)).numpy()
    assert calls and all(str(d) == "int8" for d in calls)

    # manual weight-only reference: x @ (int8 * scale) + b
    h = xs
    for lyr in model.sublayers():
        if isinstance(lyr, QuantedLinear):
            w = lyr._w_int8.astype(np.float32) * lyr._w_scale[None, :]
            h = h @ w + lyr.inner.bias.numpy()
            h = np.maximum(h, 0) if lyr is not quanted_last(model) else h
    np.testing.assert_allclose(out, h, rtol=1e-4, atol=1e-4)


def quanted_last(model):
    qs = [s for s in model.sublayers() if isinstance(s, QuantedLinear)]
    return qs[-1]


def test_quantized_conv_per_channel_scales():
    paddle.seed(3)
    conv_net = nn.Sequential(nn.Conv2D(3, 8, 3, padding=1), nn.ReLU())
    ptq = PTQ(QuantConfig(activation=AbsmaxObserver(), weight=None))
    ptq.quantize(conv_net)
    xs = np.random.RandomState(1).randn(2, 3, 8, 8).astype(np.float32)
    calibrate(conv_net, [xs], steps=1)
    ptq.convert(conv_net)
    conv_net.eval()
    q = conv_net.sublayers()[0]
    assert q._w_int8.dtype == np.int8 and q._w_scale.shape == (8,)
    out = conv_net(paddle.to_tensor(xs)).numpy()
    assert np.isfinite(out).all()
