"""Training observatory (ISSUE 12): per-layer numerics sentinel, step
memory timeline + per-module breakdown, step-phase spans feeding
cost_table v2, the ``nan:`` fault directive, and tools/bench_compare.py.

Acceptance here: dp-4 sim with ``PADDLE_FAULT_PLAN="nan:rank=2,step=5"``
— the sentinel detects the nonfinite grad within step 5, names the
exact parameter in the raised error, the alert fires with a
flight-recorder event, and the watchdog dump's ``numerics`` state
provider carries the per-param stats; with numerics in ``warn`` mode
and the fault plan off, the trajectory is bit-identical to sensing
disabled.
"""
import json
import os
import subprocess
import sys

import numpy as np
import pytest

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu import nn
from paddle_tpu.autograd import tape
from paddle_tpu.distributed import fault, simulator
from paddle_tpu.profiler import (alerts, flight_recorder as flight,
                                 memory, step_phase, tensor_stats,
                                 timeseries)
from paddle_tpu.profiler.tensor_stats import (NonFiniteGradError,
                                              NumericsSentinel)
from paddle_tpu.profiler.telemetry import get_registry

REPO = os.path.join(os.path.dirname(__file__), "..")


@pytest.fixture(autouse=True)
def _clean_observatory():
    yield
    tensor_stats.disable()
    tensor_stats.reset()
    memory.disable()
    memory.reset()
    step_phase.disable()
    step_phase.reset()
    alerts.reset_alert_engine()
    timeseries.reset()
    flight.disable()
    flight.reset()
    fault.clear()


def _mlp(seed=0, din=4, dh=8, dout=2):
    net = nn.Sequential(nn.Linear(din, dh), nn.Tanh(), nn.Linear(dh, dout))
    wr = np.random.default_rng(seed)
    for p in net.parameters():
        p.set_value(paddle.to_tensor(
            (wr.normal(size=p.shape) * 0.1).astype(np.float32)))
    return net


# ---------------------------------------------------------------------------
# numerics sentinel
# ---------------------------------------------------------------------------


class TestNumericsSentinel:
    def test_grad_stats_match_hand_computed_oracle(self):
        """Per-parameter L2 / abs-max attribution on a 2-layer net
        equals the hand-computed numpy values over the same grads."""
        net = _mlp()
        s = tensor_stats.enable(interval=1, mode="warn")
        x = paddle.to_tensor(np.linspace(-1, 1, 12)
                             .reshape(3, 4).astype(np.float32))
        (net(x) ** 2).mean().backward()
        rep = s.report()
        params = [p for p in net.parameters()]
        assert len(rep) == len(params)
        for p in params:
            g = np.asarray(p.grad.numpy(), np.float64)
            st = rep[f"0/{p.name}"]
            assert st["l2"] == pytest.approx(float(np.linalg.norm(g)),
                                             rel=1e-9)
            assert st["absmax"] == pytest.approx(float(np.abs(g).max()),
                                                 rel=1e-9)
            assert st["nonfinite"] == 0
            assert st["numel"] == g.size

    def test_nonfinite_raises_naming_exact_param(self):
        """First nonfinite grad raises a structured error naming the
        parameter, ticks paddle_numerics_nonfinite_total{param} and
        records a flight-recorder 'numerics' event."""
        flight.enable()
        tensor_stats.enable(interval=1, mode="raise")
        net = _mlp(seed=1)
        x = paddle.to_tensor(np.ones((3, 4), np.float32))
        tape.poison_next_leaf_grad()
        with pytest.raises(NonFiniteGradError) as ei:
            (net(x) ** 2).mean().backward()
        err = ei.value
        names = {p.name for p in net.parameters()}
        assert err.param in names
        assert err.nonfinite >= 1
        c = get_registry().counter("paddle_numerics_nonfinite_total",
                                   labels=("param",))
        assert c.value(param=err.param) >= 1
        evs = flight.get_flight_recorder().events(kind="numerics")
        assert any(e["param"] == err.param for e in evs)

    def test_warn_mode_records_and_continues(self):
        s = tensor_stats.enable(interval=1, mode="warn")
        net = _mlp(seed=2)
        x = paddle.to_tensor(np.ones((3, 4), np.float32))
        tape.poison_next_leaf_grad()
        (net(x) ** 2).mean().backward()           # must NOT raise
        off = s.offenders()
        assert off and off[0]["nonfinite"] >= 1
        # the gauge the built-in alert rule watches is set
        g = get_registry().gauge("paddle_numerics_nonfinite_params")
        assert g.value() >= 1

    def test_interval_env_knob_and_sampling(self, monkeypatch):
        """PADDLE_NUMERICS_INTERVAL / PADDLE_NUMERICS_MODE seed the
        sentinel, and interval=2 samples every other backward."""
        monkeypatch.setenv("PADDLE_NUMERICS_INTERVAL", "2")
        monkeypatch.setenv("PADDLE_NUMERICS_MODE", "warn")
        s = NumericsSentinel()
        assert s.interval == 2 and s.mode == "warn"
        monkeypatch.delenv("PADDLE_NUMERICS_INTERVAL")
        monkeypatch.delenv("PADDLE_NUMERICS_MODE")
        s = tensor_stats.enable(interval=2, mode="warn")
        net = _mlp(seed=3)
        n_params = len(list(net.parameters()))
        ctr = get_registry().counter("paddle_numerics_samples_total")
        x = paddle.to_tensor(np.ones((3, 4), np.float32))
        before = ctr.value()
        for _ in range(3):                 # steps 0,1,2 -> sampled 0 and 2
            (net(x) ** 2).mean().backward()
            net.clear_gradients()
        assert ctr.value() - before == 2 * n_params

    def test_activation_absmax_optional(self, monkeypatch):
        monkeypatch.setenv("PADDLE_NUMERICS_ACTIVATIONS", "1")
        assert NumericsSentinel().activations
        monkeypatch.delenv("PADDLE_NUMERICS_ACTIVATIONS")
        s = tensor_stats.enable(interval=1, mode="warn", activations=True)
        net = _mlp(seed=4)
        x = paddle.to_tensor(np.ones((3, 4), np.float32) * 2.0)
        (net(x) ** 2).mean().backward()
        acts = s.activation_report()
        assert acts, "no activation abs-max recorded"
        assert all(v >= 0 for v in acts.values())

    def test_env_enable_knobs_at_import(self):
        """PADDLE_NUMERICS / PADDLE_MEMORY / PADDLE_STEP_PHASE enable
        their layers at import (fresh interpreter)."""
        code = (
            "import jax; jax.config.update('jax_platforms', 'cpu')\n"
            "from paddle_tpu.profiler import tensor_stats, memory, "
            "step_phase\n"
            "assert tensor_stats.is_enabled()\n"
            "assert memory.is_enabled()\n"
            "assert step_phase.is_enabled()\n"
            "print('ENABLED_OK')\n")
        env = dict(os.environ, PADDLE_NUMERICS="1", PADDLE_MEMORY="1",
                   PADDLE_STEP_PHASE="1", JAX_PLATFORMS="cpu")
        proc = subprocess.run([sys.executable, "-c", code], env=env,
                              capture_output=True, text=True, timeout=240)
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "ENABLED_OK" in proc.stdout


# ---------------------------------------------------------------------------
# nan fault directive
# ---------------------------------------------------------------------------


class TestNanFault:
    def test_parse_nan_directive(self):
        plan = fault.FaultPlan.parse("nan:rank=2,step=5")
        (f,) = plan.faults
        assert (f.kind, f.rank, f.step, f.seq) == ("nan", 2, 5, None)
        with pytest.raises(ValueError, match="unknown fault kind"):
            fault.FaultPlan.parse("nanx:rank=0,step=1")
        with pytest.raises(ValueError, match="exactly one trigger"):
            fault.FaultPlan.parse("nan:rank=0")

    def test_nan_poisons_next_backward_once_only(self):
        fault.install("nan:rank=0,step=2")
        ctr = fault.elastic_telemetry()["events"]
        before = ctr.value(kind="nan")
        fault.check_step(0)
        fault.check_step(1)
        net = _mlp(seed=5)
        x = paddle.to_tensor(np.ones((3, 4), np.float32))
        (net(x) ** 2).mean().backward()
        assert all(np.isfinite(p.grad.numpy()).all()
                   for p in net.parameters()), "poison fired early"
        net.clear_gradients()
        fault.check_step(2)                         # arms the poison
        assert ctr.value(kind="nan") == before + 1
        (net(x) ** 2).mean().backward()
        bad = [p.name for p in net.parameters()
               if not np.isfinite(p.grad.numpy()).all()]
        assert len(bad) == 1, f"exactly one poisoned grad expected: {bad}"
        net.clear_gradients()
        fault.check_step(2)                         # fired=True: never again
        (net(x) ** 2).mean().backward()
        assert all(np.isfinite(p.grad.numpy()).all()
                   for p in net.parameters())


# ---------------------------------------------------------------------------
# dp-4 acceptance + parity
# ---------------------------------------------------------------------------


def _dp4_nan_worker(steps=7):
    r = dist.get_rank()
    net = _mlp(seed=0, din=16, dh=16, dout=4)
    strat = dist.fleet.DistributedStrategy()
    strat.hybrid_configs = {"dp_degree": 4}
    dp = dist.parallel.DataParallel(net, strategy=strat)
    opt = paddle.optimizer.SGD(learning_rate=0.05,
                               parameters=net.parameters())
    tensor_stats.attach()                  # per-rank: tape hooks are TLS
    rngX = np.random.default_rng(7)
    X = rngX.normal(size=(4 * 4 * steps, 16)).astype(np.float32)
    names = [p.name for p in net.parameters()]
    try:
        for s in range(steps):
            fault.check_step(s)
            lo = (s * 4 + r) * 4
            x = paddle.to_tensor(X[lo:lo + 4])
            loss = (dp(x) ** 2).mean()
            loss.backward()
            opt.step()
            opt.clear_grad()
        return ("done", None, None, names)
    except NonFiniteGradError as e:
        w = simulator.active_world()
        if w is not None:
            w.mark_dead(r)                 # unblock the survivors
        return ("nonfinite", s, e.param, names)
    except simulator.RankFailure as e:
        return ("peer_failure", s, e.rank, names)
    finally:
        dp.shutdown()
        tensor_stats.detach()


class TestAcceptanceDp4:
    def test_nan_fault_detected_alert_fires_dump_names_layer(
            self, monkeypatch, tmp_path):
        """ISSUE 12 acceptance: dp-4 sim with
        PADDLE_FAULT_PLAN="nan:rank=2,step=5" — rank 2's sentinel
        raises within step 5 naming the exact parameter, survivors
        surface a structured RankFailure naming rank 2, the built-in
        numerics_nonfinite alert fires with a flight-recorder event,
        and the watchdog dump's numerics state provider carries the
        per-param stats."""
        monkeypatch.setenv("PADDLE_FAULT_PLAN", "nan:rank=2,step=5")
        monkeypatch.setenv("PADDLE_COMM_OVERLAP_TIMEOUT_S", "60")
        fault.clear()                       # re-arm lazy env parsing
        flight.enable()
        tensor_stats.enable(interval=1, mode="raise")
        results = dist.spawn(_dp4_nan_worker, nprocs=4).results
        by_rank = {i: r for i, r in enumerate(results)}
        kind, step, param, names = by_rank[2]
        assert kind == "nonfinite", by_rank
        assert step == 5, "detection must land within step 5"
        assert param in names, "error must name the exact parameter"
        for r in (0, 1, 3):
            k, _, failed, _ = by_rank[r]
            assert k in ("peer_failure", "done")
            if k == "peer_failure":
                assert failed == 2
        # the detection landed in the sentinel's state
        st = tensor_stats.get_sentinel().state()
        assert any(p["nonfinite"] for p in st["params"])
        assert any(o["param"] == param and o["rank"] == 2
                   for o in st["offenders"])
        # fault firing + numerics events are on the flight ring
        fr = flight.get_flight_recorder()
        assert any("nan" in e.get("fault", "")
                   for e in fr.events(kind="fault_injected"))
        assert any(e.get("param") == param
                   for e in fr.events(kind="numerics"))
        # alert: one history tick evaluates the built-in threshold rule
        eng = alerts.get_alert_engine()
        assert "numerics_nonfinite" in eng.rules
        timeseries.get_history().tick()
        active = alerts.active_alerts()
        assert "numerics_nonfinite" in active
        assert active["numerics_nonfinite"]["severity"] == "page"
        assert any(e.get("rule") == "numerics_nonfinite"
                   and e.get("action") == "fired"
                   for e in fr.events(kind="alert"))
        # watchdog dump carries the numerics provider with per-param stats
        out = fr.dump(reason="test", directory=str(tmp_path))
        with open(next(iter(out["ranks"].values()))) as f:
            dumped = json.load(f)
        numerics = dumped["state"]["numerics"]
        assert any(p["param"] == param and p["nonfinite"]
                   for p in numerics["params"])
        assert dumped["state"]["alerts"]["active"].get("numerics_nonfinite")

    def test_warn_mode_sentinel_is_bit_identical_to_disabled(self):
        """With numerics in warn mode and the fault plan off, the dp-4
        loss trajectory AND final params are bit-identical to sensing
        disabled (the sentinel is read-only over finalized grads)."""

        def run(sense):
            if sense:
                tensor_stats.enable(interval=1, mode="warn")
            else:
                tensor_stats.disable()
                tensor_stats.reset()

            def worker():
                r = dist.get_rank()
                net = _mlp(seed=0, din=16, dh=16, dout=4)
                strat = dist.fleet.DistributedStrategy()
                strat.hybrid_configs = {"dp_degree": 4}
                dp = dist.parallel.DataParallel(net, strategy=strat)
                opt = paddle.optimizer.SGD(learning_rate=0.05,
                                           parameters=net.parameters())
                if sense:
                    tensor_stats.attach()
                rngX = np.random.default_rng(7)
                X = rngX.normal(size=(48, 16)).astype(np.float32)
                losses = []
                try:
                    for s in range(3):
                        lo = (s * 4 + r) * 4
                        loss = (dp(paddle.to_tensor(X[lo:lo + 4])) ** 2) \
                            .mean()
                        loss.backward()
                        losses.append(np.asarray(loss.numpy()).copy())
                        opt.step()
                        opt.clear_grad()
                    return (losses,
                            [np.asarray(p.numpy()).copy()
                             for p in net.parameters()])
                finally:
                    dp.shutdown()
                    if sense:
                        tensor_stats.detach()

            return dist.spawn(worker, nprocs=4).results

        sensed = run(True)
        plain = run(False)
        for (l_a, p_a), (l_b, p_b) in zip(sensed, plain):
            for a, b in zip(l_a, l_b):
                np.testing.assert_array_equal(a, b)
            for a, b in zip(p_a, p_b):
                np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# memory timeline + module breakdown
# ---------------------------------------------------------------------------


class TestMemoryTimeline:
    def test_phase_samples_and_peak_attribution(self, monkeypatch):
        monkeypatch.setenv("PADDLE_MEMORY_CAPACITY", "32")
        tl = memory.MemoryTimeline()
        assert tl.capacity == 32
        monkeypatch.delenv("PADDLE_MEMORY_CAPACITY")
        tl = memory.enable(capacity=64)
        tl.step_begin(0)
        memory.phase_sample("forward", nbytes=100)
        memory.phase_sample("backward", nbytes=300)
        memory.phase_sample("optimizer", nbytes=200)
        tl.step_begin(1)
        memory.phase_sample("forward", nbytes=150)
        memory.phase_sample("backward", nbytes=900)
        rep = tl.peak_report()
        assert rep["peak_bytes"] == 900
        assert rep["peak_step"] == 1
        assert rep["peak_phase"] == "backward"
        assert rep["per_phase_max"]["forward"] == 150
        assert rep["samples"] == 5
        # telemetry gauges carry the last sample + step peak
        r = get_registry()
        live = r.gauge("paddle_memory_live_bytes", labels=("phase",))
        assert live.value(phase="backward") == 900
        assert r.gauge("paddle_memory_step_peak_bytes").value() == 900
        assert r.counter("paddle_memory_samples_total").value() >= 5

    def test_ring_is_bounded(self):
        tl = memory.enable(capacity=64)     # floor is 16
        for i in range(200):
            tl.sample("x", nbytes=i)
        assert len(tl.samples()) == 64

    def test_chrome_counter_track_merges(self):
        tl = memory.enable(capacity=64)
        tl.step_begin(0)
        tl.sample("forward", nbytes=128)
        tl.sample("backward", nbytes=256)
        merged = flight.merge_chrome_traces({0: tl.to_chrome()})
        counters = [e for e in merged["traceEvents"]
                    if e.get("ph") == "C"
                    and e["name"] == "paddle_memory_live_bytes"]
        assert len(counters) == 2
        assert counters[0]["pid"] == 0
        assert counters[1]["args"]["value"] == 256
        assert counters[1]["args"]["phase"] == "backward"

    def test_module_breakdown_oracle_dtype_aware(self):
        """Per-module param/grad/opt/comm bytes equal hand-computed
        values, including a bf16 parameter at 2 bytes/element."""
        import jax.numpy as jnp
        from paddle_tpu.distributed.comm import GradientBucketer

        net = _mlp(seed=6)
        params = list(net.parameters())
        # make one param bf16 to prove dtype-awareness
        params[0]._data = params[0]._data.astype(jnp.bfloat16)
        opt = paddle.optimizer.Adam(parameters=params)
        x = paddle.to_tensor(np.ones((3, 4), np.float32))
        (net(x) ** 2).mean().backward()
        opt.step()                          # populates Adam slots
        bucketer = GradientBucketer(params, fuse_grad_size_in_MB=32)
        bd = memory.module_breakdown(net, optimizer=opt,
                                     bucketer=bucketer)
        named = dict(net.named_parameters())
        exp: dict = {}
        for name, p in named.items():
            mod = name.split(".")[0]
            e = exp.setdefault(mod, {"param": 0, "grad": 0, "opt": 0})
            nbytes = int(np.prod(p.shape)) * np.dtype(
                str(p._data.dtype)).itemsize
            e["param"] += nbytes
            e["grad"] += int(np.prod(p.shape)) * np.dtype(
                str(p.grad._data.dtype)).itemsize
            slots = opt._slots[id(p)]
            e["opt"] += sum(
                int(np.prod(a.shape)) * np.dtype(str(a.dtype)).itemsize
                for a in slots.values())
        for mod, e in exp.items():
            got = bd["modules"][mod]
            assert got["param_bytes"] == e["param"], mod
            assert got["grad_bytes"] == e["grad"], mod
            assert got["opt_bytes"] == e["opt"], mod
            assert got["comm_bytes"] > 0
        assert bd["totals"]["param_bytes"] == sum(
            e["param"] for e in exp.values())
        # dtype-aware: the bf16 weight produced a bf16 grad at 2
        # bytes/element (the Adam update itself promotes the stored
        # param back to fp32 — the breakdown reads LIVE dtypes)
        g0 = named["0.weight"].grad
        assert np.dtype(str(g0._data.dtype)).itemsize == 2
        assert bd["modules"]["0"]["grad_bytes"] < \
            bd["modules"]["0"]["param_bytes"]


# ---------------------------------------------------------------------------
# step phases + cost_table v2
# ---------------------------------------------------------------------------


class TestStepPhases:
    def test_hapi_fit_records_phases_and_memory(self):
        """One fit() with TelemetryCallback populates
        paddle_step_phase_seconds{forward|backward|optimizer} and the
        memory timeline samples at every phase boundary."""
        from paddle_tpu.callbacks import TelemetryCallback
        from paddle_tpu.hapi import Model
        import paddle_tpu.io as io

        memory.enable(capacity=256)
        step_phase.reset()
        net = _mlp(seed=7)

        class DS(io.Dataset):
            def __len__(self):
                return 8

            def __getitem__(self, i):
                return (np.full(4, i, np.float32),
                        np.zeros(2, np.float32))

        m = Model(net)
        m.prepare(optimizer=paddle.optimizer.SGD(
            0.01, parameters=net.parameters()), loss=nn.MSELoss())
        m.fit(DS(), batch_size=4, epochs=1, verbose=0,
              callbacks=[TelemetryCallback(track_ops=False)])
        assert not step_phase.is_enabled(), \
            "TelemetryCallback must disable phases after the fit"
        bd = step_phase.breakdown()
        for ph in ("forward", "backward", "optimizer"):
            assert bd["phases"][ph]["seconds"] > 0, ph
            assert bd["phases"][ph]["count"] >= 2, ph
        assert bd["steps"] == 2
        assert abs(sum(p["fraction"]
                       for p in bd["phases"].values()) - 1.0) < 1e-9
        fam = get_registry().collect()["paddle_step_phase_seconds"]
        assert {"forward", "backward", "optimizer"} <= set(fam["series"])
        phases_seen = {s[2] for s in memory.get_timeline().samples()}
        assert {"forward", "backward", "optimizer", "step"} <= phases_seen

    def test_hybrid_parallel_cost_table_v2(self):
        """ISSUE 12 acceptance: cost_table() reports per-phase step
        seconds (incl. comm_wait from the overlapped dp exchange) and
        per-module param/grad/optimizer-state bytes for a
        hybrid-parallel (dp-4) config."""
        step_phase.reset()
        step_phase.enable()
        memory.enable(capacity=256)

        def worker():
            r = dist.get_rank()
            net = _mlp(seed=0, din=16, dh=16, dout=4)
            strat = dist.fleet.DistributedStrategy()
            strat.hybrid_configs = {"dp_degree": 4}
            inner = paddle.optimizer.Adam(
                learning_rate=0.01, parameters=net.parameters())
            opt = dist.fleet.HybridParallelOptimizer(inner,
                                                     strategy=strat)
            rngX = np.random.default_rng(7)
            X = rngX.normal(size=(48, 16)).astype(np.float32)
            for s in range(2):
                lo = (s * 4 + r) * 4
                with step_phase.span("forward"):
                    loss = (net(paddle.to_tensor(X[lo:lo + 4])) ** 2) \
                        .mean()
                loss.backward()
                opt.step()
                opt.clear_grad()
            if r == 0:
                from paddle_tpu.distributed.comm import GradientBucketer
                memory.register_model_breakdown(
                    net, optimizer=inner,
                    bucketer=GradientBucketer.from_strategy(
                        list(net.parameters()), strat))
            return True

        assert all(dist.spawn(worker, nprocs=4).results)
        table = paddle.profiler.cost_table()
        assert table["schema"] == "paddle_cost_table/2"
        phases = table["phases"]["phases"]
        for ph in ("forward", "backward", "comm_wait", "optimizer"):
            assert phases[ph]["seconds"] > 0, ph
        mods = table["memory"]["modules"]
        assert mods, "per-module memory table missing"
        for ent in mods.values():
            assert ent["param_bytes"] > 0
            assert ent["grad_bytes"] > 0
            assert ent["opt_bytes"] > 0       # Adam moments
        assert table["memory"]["timeline"]["samples"] > 0
        # the same histogram rides in the programs section too
        assert any(k.startswith("paddle_step_phase_seconds")
                   for k in table["programs"])

    def test_disabled_observatory_adds_no_step_cost(self):
        """Overhead guard: the full disabled-path call surface
        (tensor_stats gate, memory phase_sample, step_phase
        record/clock) adds no measurable per-step cost — reuses
        bench.py's telemetry_overhead_pct machinery like the flight
        recorder's guard."""
        import bench

        assert not tensor_stats.is_enabled()
        assert not memory.is_enabled()
        assert not step_phase.is_enabled()
        x = np.random.default_rng(0).normal(size=200_000) \
            .astype(np.float32)

        def step():
            return float(np.tanh(x).sum())

        def gated_step():
            tensor_stats.is_enabled()
            memory.phase_sample("forward")
            memory.step_begin(0)
            step_phase.clock()
            step_phase.record_phase("forward", 0.0)
            step_phase.step_begin(0)
            step_phase.step_end()
            return step()

        pct = min(
            bench._telemetry_overhead_pct(step, lambda r: None, steps=30,
                                          instrumented_step=gated_step)
            for _ in range(3))
        assert pct < 10.0, f"disabled observatory costs {pct}% per step"
        assert memory.get_timeline().samples() == []   # truly recorded 0
        assert step_phase.breakdown()["total_seconds"] == 0.0


# ---------------------------------------------------------------------------
# tools/bench_compare.py
# ---------------------------------------------------------------------------


sys.path.insert(0, os.path.join(REPO, "tools"))


def _bench_records(tmp_path, regress=False):
    old = {
        "metric": "llama_1b_train_tokens_per_sec", "value": 1000.0,
        "unit": "tokens/sec", "vs_baseline": None, "mfu_pct": 31.0,
        "train_peak_bytes": 1_000_000, "numerics_overhead_pct": 2.0,
        "train_phase_breakdown": {"forward": 0.3, "backward": 0.5,
                                  "comm_wait": 0.05, "optimizer": 0.15},
        "config": {"batch": 4},
    }
    new = json.loads(json.dumps(old))
    if regress:
        new["value"] = 650.0                  # tokens/s down 35%
        new["train_peak_bytes"] = 1_600_000   # peak up 60%
    a, b = tmp_path / "old.json", tmp_path / "new.json"
    a.write_text(json.dumps(old))
    b.write_text(json.dumps(new))
    return str(a), str(b)


class TestBenchCompare:
    def test_direction_inference(self):
        import bench_compare as bc
        assert bc.direction_of("llama_1b_train_tokens_per_sec") == "higher"
        assert bc.direction_of("train_peak_bytes") == "lower"
        assert bc.direction_of("p95_ttft_ms") == "lower"
        assert bc.direction_of("numerics_overhead_pct") == "lower"
        assert bc.direction_of("fleet_time_to_recover_s") == "lower"
        assert bc.direction_of("serving_prefix_ttft_speedup") == "higher"
        # ISSUE 14: controller chaos-pair metrics — recovery ratio is
        # off/on (higher = controller helps more); the action count is
        # workload-shaped churn, informational only
        assert bc.direction_of("fleet_controller_recover_ratio") == "higher"
        assert bc.direction_of("fleet_controller_actions") == "ignore"
        assert bc.direction_of("train_phase_breakdown.forward") is None

    def test_compare_flags_regressions_only(self, tmp_path):
        import bench_compare as bc
        a, b = _bench_records(tmp_path, regress=True)
        rows = bc.compare(bc.load_record(a), bc.load_record(b))
        by = {r["metric"]: r for r in rows}
        assert by["llama_1b_train_tokens_per_sec"]["status"] == "REGRESSED"
        assert by["train_peak_bytes"]["status"] == "REGRESSED"
        assert by["mfu_pct"]["status"] == "ok"
        assert by["train_phase_breakdown.forward"]["status"] == "info"
        # override can silence a metric
        rows = bc.compare(bc.load_record(a), bc.load_record(b),
                          overrides={"train_peak_bytes": ("ignore", None)})
        by = {r["metric"]: r for r in rows}
        assert by["train_peak_bytes"]["status"] == "info"

    def test_cli_no_jax_import_exit_codes(self, tmp_path):
        """The comparator runs with jax AND numpy poisoned out of the
        interpreter (laptop-vs-fleet-records discipline): exit 0 on
        parity, 1 on a synthetic regression, 2 on bad input; --html
        writes the table."""
        a, b = _bench_records(tmp_path, regress=True)
        html = str(tmp_path / "diff.html")
        tool = os.path.join(REPO, "tools", "bench_compare.py")

        def run(argv):
            code = (
                "import sys\n"
                "sys.modules['jax'] = None\n"
                "sys.modules['numpy'] = None\n"
                f"sys.argv = {argv!r}\n"
                "import runpy\n"
                "try:\n"
                f"    runpy.run_path({tool!r}, run_name='__main__')\n"
                "except SystemExit as e:\n"
                "    raise SystemExit(e.code or 0)\n")
            return subprocess.run([sys.executable, "-c", code],
                                  capture_output=True, text=True,
                                  timeout=60)

        proc = run(["bench_compare.py", a, b, "--html", html])
        assert proc.returncode == 1, proc.stderr
        assert "REGRESSED" in proc.stdout
        assert "llama_1b_train_tokens_per_sec" in proc.stdout
        with open(html) as f:
            assert "REGRESSED" in f.read()
        same = run(["bench_compare.py", a, a])
        assert same.returncode == 0, same.stderr
        bad = run(["bench_compare.py", a, str(tmp_path / "missing.json")])
        assert bad.returncode == 2
