"""Op numeric parity vs numpy (the OpTest check_output analogue — SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle

rng = np.random.RandomState(42)


def t(a):
    return paddle.to_tensor(np.asarray(a, np.float32))


@pytest.mark.parametrize("op,npop", [
    ("exp", np.exp), ("log1p", np.log1p), ("sqrt", np.sqrt),
    ("tanh", np.tanh), ("floor", np.floor), ("ceil", np.ceil),
    ("abs", np.abs), ("square", np.square),
])
def test_unary(op, npop):
    x = np.abs(rng.randn(3, 4).astype(np.float32)) + 0.1
    out = getattr(paddle, op)(t(x))
    np.testing.assert_allclose(out.numpy(), npop(x), rtol=1e-5, atol=1e-6)


@pytest.mark.parametrize("op,npop", [
    ("add", np.add), ("subtract", np.subtract), ("multiply", np.multiply),
    ("divide", np.divide), ("maximum", np.maximum), ("minimum", np.minimum),
])
def test_binary(op, npop):
    x = rng.randn(3, 4).astype(np.float32)
    y = rng.randn(3, 4).astype(np.float32) + 2.0
    out = getattr(paddle, op)(t(x), t(y))
    np.testing.assert_allclose(out.numpy(), npop(x, y), rtol=1e-5, atol=1e-6)


def test_reductions():
    x = rng.randn(3, 4, 5).astype(np.float32)
    np.testing.assert_allclose(paddle.sum(t(x), axis=1).numpy(),
                               x.sum(1), rtol=1e-5)
    np.testing.assert_allclose(paddle.mean(t(x), axis=[0, 2]).numpy(),
                               x.mean((0, 2)), rtol=1e-5)
    np.testing.assert_allclose(paddle.max(t(x), axis=-1, keepdim=True).numpy(),
                               x.max(-1, keepdims=True), rtol=1e-5)
    np.testing.assert_allclose(paddle.std(t(x)).numpy(), x.std(ddof=1), rtol=1e-4)
    np.testing.assert_allclose(paddle.logsumexp(t(x), axis=0).numpy(),
                               np.log(np.exp(x).sum(0)), rtol=1e-4)
    np.testing.assert_allclose(paddle.cumsum(t(x), axis=1).numpy(),
                               np.cumsum(x, 1), rtol=1e-4)


def test_matmul_shapes():
    a = rng.randn(2, 3, 4).astype(np.float32)
    b = rng.randn(2, 4, 5).astype(np.float32)
    np.testing.assert_allclose(paddle.matmul(t(a), t(b)).numpy(), a @ b, rtol=1e-4)
    np.testing.assert_allclose(
        paddle.matmul(t(a), t(b.swapaxes(1, 2)), transpose_y=True).numpy(),
        a @ b, rtol=1e-4)
    np.testing.assert_allclose(
        paddle.einsum("bij,bjk->bik", t(a), t(b)).numpy(), a @ b, rtol=1e-4)


def test_manipulation():
    x = np.arange(24, dtype=np.float32).reshape(2, 3, 4)
    assert paddle.reshape(t(x), [6, 4]).shape == [6, 4]
    assert paddle.flatten(t(x), 1).shape == [2, 12]
    assert paddle.transpose(t(x), [2, 0, 1]).shape == [4, 2, 3]
    assert paddle.unsqueeze(t(x), [0, -1]).shape == [1, 2, 3, 4, 1]
    assert paddle.squeeze(paddle.ones([1, 3, 1])).shape == [3]
    np.testing.assert_allclose(paddle.flip(t(x), [1]).numpy(), x[:, ::-1])
    np.testing.assert_allclose(paddle.roll(t(x), 1, 0).numpy(), np.roll(x, 1, 0))
    np.testing.assert_allclose(paddle.tile(t([1.0, 2.0]), [2, 2]).numpy(),
                               np.tile([1, 2], (2, 2)))
    np.testing.assert_allclose(
        paddle.expand(t(np.ones((1, 3))), [4, 3]).numpy(), np.ones((4, 3)))
    np.testing.assert_allclose(
        paddle.pad(t(np.ones((2, 2))), [1, 1, 1, 1]).numpy(),
        np.pad(np.ones((2, 2)), 1))


def test_gather_scatter():
    x = np.arange(10, dtype=np.float32)
    idx = np.array([2, 5, 7])
    np.testing.assert_allclose(paddle.gather(t(x), paddle.to_tensor(idx)).numpy(),
                               x[idx])
    out = paddle.scatter(t(np.zeros(5)), paddle.to_tensor([1, 3]),
                         t([10.0, 20.0]))
    np.testing.assert_allclose(out.numpy(), [0, 10, 0, 20, 0])
    x2 = np.arange(12, dtype=np.float32).reshape(3, 4)
    i2 = np.array([[0, 1], [2, 0], [1, 3]])
    np.testing.assert_allclose(
        paddle.take_along_axis(t(x2), paddle.to_tensor(i2), 1).numpy(),
        np.take_along_axis(x2, i2, 1))


def test_where_sort_search():
    x = rng.randn(4, 5).astype(np.float32)
    cond = x > 0
    np.testing.assert_allclose(
        paddle.where(paddle.to_tensor(cond), t(x), t(-x)).numpy(),
        np.where(cond, x, -x))
    np.testing.assert_allclose(paddle.sort(t(x), axis=1).numpy(), np.sort(x, 1))
    np.testing.assert_array_equal(paddle.argsort(t(x), axis=1).numpy(),
                                  np.argsort(x, 1))
    np.testing.assert_array_equal(paddle.argmax(t(x), axis=1).numpy(),
                                  np.argmax(x, 1))
    v, i = paddle.topk(t([1.0, 5.0, 3.0]), 2)
    np.testing.assert_allclose(v.numpy(), [5, 3])
    np.testing.assert_array_equal(i.numpy(), [1, 2])


def test_logic_ops():
    a = t([1.0, 2.0, 3.0])
    b = t([2.0, 2.0, 2.0])
    np.testing.assert_array_equal((a == b).numpy(), [False, True, False])
    np.testing.assert_array_equal(
        paddle.logical_and(a > 1, b > 1).numpy(), [False, True, True])
    assert bool(paddle.allclose(a, a))
    assert not bool(paddle.equal_all(a, b))


def test_linalg():
    x = rng.randn(4, 4).astype(np.float32)
    spd = x @ x.T + 4 * np.eye(4, dtype=np.float32)
    np.testing.assert_allclose(paddle.linalg.inv(t(spd)).numpy(),
                               np.linalg.inv(spd), rtol=1e-3, atol=1e-4)
    np.testing.assert_allclose(paddle.linalg.norm(t(x)).numpy(),
                               np.linalg.norm(x), rtol=1e-5)
    np.testing.assert_allclose(paddle.linalg.det(t(spd)).numpy(),
                               np.linalg.det(spd), rtol=1e-3)
    c = paddle.linalg.cholesky(t(spd))
    np.testing.assert_allclose((c @ c.T).numpy(), spd, rtol=1e-3, atol=1e-3)


def test_one_hot_and_embedding_ops():
    oh = paddle.one_hot(paddle.to_tensor([0, 2]), 3)
    np.testing.assert_allclose(oh.numpy(), [[1, 0, 0], [0, 0, 1]])
