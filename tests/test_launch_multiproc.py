"""REAL multi-process distributed path (VERDICT.md round-1 item 7;
reference: the ``TestDistBase`` shell-out pattern of
``test/legacy_test/test_dist_base.py`` — spawn trainers via the launch CLI,
compare losses against a single-process oracle).

Two local processes rendezvous through ``jax.distributed.initialize``
(driven by the PADDLE_* env the launcher sets), each drives 2 virtual CPU
devices, and one jitted SPMD step trains over the global 4-device dp mesh —
collectives ride Gloo across the processes."""
import os
import socket
import subprocess
import sys
import textwrap

import numpy as np
import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

WORKER = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = ("--xla_force_host_platform_device_count="
                               + os.environ.get("LOCAL_DEVICES", "2"))
    import jax
    jax.config.update("jax_platforms", "cpu")
    jax.config.update("jax_default_matmul_precision", "highest")
    import numpy as np
    import paddle_tpu as paddle
    import paddle_tpu.distributed as dist
    from paddle_tpu.distributed import mesh as mesh_mod
    from paddle_tpu.framework.functional import FunctionalModule
    from jax.sharding import NamedSharding, PartitionSpec as P
    import jax.numpy as jnp

    dist.init_parallel_env()
    world = jax.process_count()
    n_dev = len(jax.devices())
    assert n_dev == 4, f"expected 4 global devices, got {n_dev}"
    mesh = mesh_mod.init_mesh({"dp": n_dev})

    paddle.seed(11)
    model = paddle.nn.Sequential(paddle.nn.Linear(8, 16), paddle.nn.Tanh(),
                                 paddle.nn.Linear(16, 1))
    fm = FunctionalModule(model, training=True)
    p_arrs = fm.param_arrays()
    rng = np.random.RandomState(5)
    X = rng.randn(16, 8).astype(np.float32)
    W = rng.randn(8, 1).astype(np.float32)
    Y = (X @ W).astype(np.float32)

    data_sh = NamedSharding(mesh, P("dp"))
    repl = NamedSharding(mesh, P())
    gx = jax.make_array_from_callback(X.shape, data_sh, lambda i: X[i])
    gy = jax.make_array_from_callback(Y.shape, data_sh, lambda i: Y[i])
    key = fm.next_key()

    @jax.jit
    def step(p_arrs, x, y):
        def loss_fn(ps):
            out, _ = fm(ps, [], key, x)
            return ((out - y) ** 2).mean()
        loss, g = jax.value_and_grad(loss_fn)(p_arrs)
        return loss, [p - 0.1 * gg for p, gg in zip(p_arrs, g)]

    losses = []
    for _ in range(5):
        loss, p_arrs = step(p_arrs, gx, gy)
        losses.append(float(jax.device_get(
            jax.jit(lambda l: l, out_shardings=repl)(loss))))
    if jax.process_index() == 0:
        print("LOSSES:", ",".join(f"{l:.6f}" for l in losses), flush=True)

    # eager collective over the device tier (one jitted reduction across
    # processes instead of a host allgather)
    if world > 1:
        from paddle_tpu.framework.core import Tensor
        me = jax.process_index()
        t = Tensor(jnp.full((4,), float(me + 1)))
        dist.all_reduce(t)
        expect = sum(range(1, world + 1))
        assert np.allclose(np.asarray(t._data), expect), np.asarray(t._data)
        if me == 0:
            print("ALLREDUCE_OK", flush=True)

        # reduce_scatter device tier: rank r gets sum_p(p-th input of
        # each process); inputs are (proc+1)*(slot+1) -> slice r sums to
        # (slot r+1) * sum(proc+1)
        outs = Tensor(jnp.zeros((2,), jnp.float32))
        ins = [Tensor(jnp.full((2,), float((me + 1) * (s + 1)), jnp.float32))
               for s in range(world)]
        dist.reduce_scatter(outs, ins)
        want_rs = (me + 1) * sum(p + 1 for p in range(world))
        assert np.allclose(np.asarray(outs._data), want_rs), \
            (np.asarray(outs._data), want_rs)

        # alltoall device tier: slot s of my inputs goes to rank s
        a2a_out = []
        a2a_in = [Tensor(jnp.full((2,), float(me * 10 + s), jnp.float32))
                  for s in range(world)]
        dist.alltoall(a2a_out, a2a_in)
        got = [float(np.asarray(t_._data)[0]) for t_ in a2a_out]
        assert got == [p * 10 + me for p in range(world)], got

        # real cross-process send/recv through the TCPStore p2p channel
        if me == 0:
            msg = Tensor(jnp.arange(6, dtype=jnp.float32).reshape(2, 3))
            dist.send(msg, dst=1)
            back = Tensor(jnp.zeros((2, 3), jnp.float32))
            dist.recv(back, src=1)
            assert np.allclose(np.asarray(back._data),
                               np.arange(6).reshape(2, 3) * 2), \
                np.asarray(back._data)
            print("P2P_OK", flush=True)
        else:
            got_t = Tensor(jnp.zeros((2, 3), jnp.float32))
            dist.recv(got_t, src=0)
            reply = Tensor(jnp.asarray(np.asarray(got_t._data) * 2))
            dist.send(reply, dst=0)
        if me == 0:
            print("RS_A2A_OK", flush=True)
    print("WORKER_DONE rank", jax.process_index(), flush=True)
""")


def _sanitized_env(extra):
    env = dict(os.environ)
    parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep)
             if p and "axon_site" not in p]
    env["PYTHONPATH"] = os.pathsep.join([REPO] + parts)
    env.pop("PALLAS_AXON_POOL_IPS", None)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    env.update(extra)
    return env


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _parse_losses(text):
    for line in text.splitlines():
        if line.startswith("LOSSES:"):
            return [float(v) for v in line.split(":", 1)[1].split(",")]
    raise AssertionError(f"no LOSSES line in output:\n{text[-2000:]}")


def test_launch_two_process_dp_parity(tmp_path):
    worker = tmp_path / "worker.py"
    worker.write_text(WORKER)

    # ---- oracle: one process, 4 local devices, same global mesh
    out = subprocess.run(
        [sys.executable, str(worker)],
        env=_sanitized_env({"LOCAL_DEVICES": "4"}),
        capture_output=True, text=True, timeout=420, cwd=str(tmp_path))
    assert out.returncode == 0, out.stderr[-2000:]
    oracle = _parse_losses(out.stdout)
    assert oracle[-1] < oracle[0], oracle

    # ---- 2 processes x 2 local devices through the launch CLI
    port = _free_port()
    logdir = tmp_path / "logs"
    procs = []
    for rank in range(2):
        procs.append(subprocess.Popen(
            [sys.executable, "-m", "paddle_tpu.distributed.launch",
             "--nnodes", "2", "--rank", str(rank),
             "--master", f"127.0.0.1:{port}",
             "--log_dir", str(logdir), str(worker)],
            env=_sanitized_env({"LOCAL_DEVICES": "2"}),
            cwd=str(tmp_path)))
    for p in procs:
        try:
            assert p.wait(timeout=420) == 0
        except subprocess.TimeoutExpired:
            for q in procs:
                q.kill()
            logs = "\n".join(f.read_text()[-1500:]
                             for f in sorted(logdir.glob("workerlog.*")))
            pytest.fail(f"multi-process launch timed out; logs:\n{logs}")

    log0 = (logdir / "workerlog.0").read_text()
    dist_losses = _parse_losses(log0)
    np.testing.assert_allclose(dist_losses, oracle, rtol=1e-5, atol=1e-6)
    assert "ALLREDUCE_OK" in log0
    assert "RS_A2A_OK" in log0
    assert "P2P_OK" in log0
    assert "WORKER_DONE rank 0" in log0
    assert "WORKER_DONE rank 1" in (logdir / "workerlog.1").read_text()
