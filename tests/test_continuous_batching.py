"""Continuous-batching serving engine (VERDICT.md round-2 item 8):
per-step admit/evict over the slot-paged KV cache — greedy parity vs
``model.generate``, mid-flight slot reuse, and mixed-length throughput
beating the static same-shape window batcher."""
import threading
import time

import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousServingEngine, ServingEngine
from paddle_tpu.models import LlamaForCausalLM, llama_tiny


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    return LlamaForCausalLM(llama_tiny(num_hidden_layers=2))


def _oracle(model, p, n):
    return np.asarray(model.generate(paddle.to_tensor(p),
                                     max_new_tokens=n)._data)


def test_greedy_parity_mixed_lengths(model):
    """Requests with DIFFERENT prompt lengths and budgets decode together
    yet match the per-request sequential oracle exactly (greedy)."""
    rng = np.random.RandomState(1)
    specs = [(4, 6), (7, 4), (10, 5), (5, 3)]      # (prompt_len, max_new)
    prompts = [rng.randint(0, 128, (1, s)).astype(np.int64)
               for s, _ in specs]
    oracle = [_oracle(model, p, n) for p, (_, n) in zip(prompts, specs)]

    eng = ContinuousServingEngine(model, max_batch_size=4, max_len=64)
    with eng:
        results = [None] * len(specs)

        def call(i):
            results[i] = np.asarray(eng.generate(
                prompts[i], max_new_tokens=specs[i][1], timeout=300).numpy())

        threads = [threading.Thread(target=call, args=(i,))
                   for i in range(len(specs))]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    for got, want in zip(results, oracle):
        np.testing.assert_array_equal(got, want)
    # the whole mixed workload shared decode steps: fewer than the sum
    # of per-request budgets proves co-batching happened
    assert eng.decode_steps < sum(n for _, n in specs), eng.decode_steps
    assert eng.prefills == len(specs)


def test_multi_row_request_and_slot_reuse(model):
    """A 2-row request splits into per-row slots; more requests than
    slots forces eviction + reuse mid-flight."""
    rng = np.random.RandomState(2)
    p2 = rng.randint(0, 128, (2, 5)).astype(np.int64)
    singles = [rng.randint(0, 128, (1, 5)).astype(np.int64)
               for _ in range(3)]
    want2 = _oracle(model, p2, 4)
    want_s = [_oracle(model, p, 4) for p in singles]

    eng = ContinuousServingEngine(model, max_batch_size=2, max_len=64)
    with eng:
        results = {}

        def call(name, ids):
            results[name] = np.asarray(eng.generate(
                ids, max_new_tokens=4, timeout=300).numpy())

        threads = [threading.Thread(target=call, args=("p2", p2))]
        threads += [threading.Thread(target=call, args=(f"s{i}", p))
                    for i, p in enumerate(singles)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
    np.testing.assert_array_equal(results["p2"], want2)
    for i, want in enumerate(want_s):
        np.testing.assert_array_equal(results[f"s{i}"], want)
    assert eng.prefills == 5          # 2 rows + 3 singles through 2 slots


def test_eos_frees_slot_early(model):
    """A request whose eos fires immediately stops decoding and its
    output is trimmed to the eos, not padded to max_new_tokens."""
    rng = np.random.RandomState(3)
    p = rng.randint(0, 128, (1, 6)).astype(np.int64)
    # discover the first greedy token, then use it as "eos"
    first = int(_oracle(model, p, 1)[0, -1])
    eng = ContinuousServingEngine(model, max_batch_size=2, max_len=64)
    with eng:
        out = np.asarray(eng.generate(p, max_new_tokens=8, timeout=300,
                                      eos_token_id=first).numpy())
    assert out.shape[1] == p.shape[1] + 1
    assert out[0, -1] == first
    assert eng.decode_steps == 0      # finished at prefill, zero decodes


def test_request_validation_and_budget_edges(model):
    rng = np.random.RandomState(5)
    p = rng.randint(0, 128, (1, 6)).astype(np.int64)
    eng = ContinuousServingEngine(model, max_batch_size=2, max_len=32)
    with eng:
        # zero budget: prompt returned unchanged, nothing scheduled
        out = eng.generate(p, max_new_tokens=0, timeout=60)
        np.testing.assert_array_equal(np.asarray(out.numpy()), p)
        # max_length honored (GenerationMixin contract)
        out = eng.generate(p, max_length=9, timeout=120)
        assert np.asarray(out.numpy()).shape == (1, 9)
        # an oversized request fails ITSELF up front, not its batch-mates
        with pytest.raises(ValueError, match="max_len"):
            eng.generate(p, max_new_tokens=30, timeout=60)
        # engine still serves afterwards
        out = eng.generate(p, max_new_tokens=2, timeout=120)
        assert np.asarray(out.numpy()).shape == (1, 8)
    assert eng.prefills == 2


def test_continuous_beats_static_window_on_mixed_lengths(model):
    """The round-2 verdict's bar: mixed-length decode throughput must
    beat static window batching (which can only group same-shape
    requests, so distinct prompt lengths serialize)."""
    rng = np.random.RandomState(4)
    specs = [(4, 8), (6, 8), (9, 8), (12, 8)]
    prompts = [rng.randint(0, 128, (1, s)).astype(np.int64)
               for s, _ in specs]

    def run(engine_cls, **kw):
        eng = engine_cls(model, max_batch_size=4, **kw)
        with eng:
            t0 = time.perf_counter()
            threads = [threading.Thread(
                target=lambda i=i: eng.generate(prompts[i],
                                                max_new_tokens=specs[i][1],
                                                timeout=600))
                for i in range(len(specs))]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
            return time.perf_counter() - t0

    t_cont = run(ContinuousServingEngine, max_len=64)
    t_static = run(ServingEngine, batch_window_s=0.2)
    # static pays 4 separate decode sequences (one per unique prompt
    # length); continuous shares every step. Generous margin for CI noise.
    assert t_cont < t_static, (t_cont, t_static)
