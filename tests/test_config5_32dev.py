"""Config-5-shaped FIVE-axis mesh: dp=2 x pp=2 x sharding=2 x sep=2 x
mp=2 all >1 simultaneously in one jitted program (SURVEY.md §2.4
config 5, §3.4; VERDICT round-4 weak #7 — sep together with the rest).
Needs 32 virtual devices, so it runs in its own sanitized CPU
subprocess (tests/_config5_child.py) with loss+grad parity vs the
sequential oracle."""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_config5_five_axis_mesh_parity():
    sys.path.insert(0, REPO)
    from __graft_entry__ import _sanitized_cpu_env

    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tests", "_config5_child.py")],
        env=_sanitized_cpu_env(32), cwd=REPO, text=True,
        stdout=subprocess.PIPE, stderr=subprocess.STDOUT, timeout=560)
    assert proc.returncode == 0, proc.stdout[-2000:]
    assert "config5 OK: mesh=(dp=2, pp=2, sharding=2, sep=2, mp=2)" \
        in proc.stdout.replace("dryrun ", ""), proc.stdout[-2000:]
