"""Auto-parallel API tests (reference: test/auto_parallel/ — structure-level
checks without needing a real cluster; SURVEY.md §4)."""
import numpy as np
import jax
import jax.numpy as jnp
from jax.sharding import PartitionSpec as P

import paddle_tpu as paddle
import paddle_tpu.distributed as dist
from paddle_tpu.distributed.auto_parallel import (
    ProcessMesh, Shard, Replicate, Partial, shard_tensor, dtensor_from_fn,
    reshard, shard_optimizer,
)


def _mesh2d():
    return ProcessMesh(np.arange(8).reshape(2, 4), dim_names=["x", "y"])


def test_process_mesh_basics():
    m = _mesh2d()
    assert m.shape == [2, 4]
    assert m.ndim == 2
    assert m.get_dim_size("y") == 4
    assert m.process_ids == list(range(8))
    jm = m.jax_mesh()
    assert jm.axis_names == ("x", "y")


def test_placements():
    assert Shard(0) == Shard(0) and Shard(0) != Shard(1)
    assert Replicate().is_replicated()
    assert Partial().is_partial()
    assert Shard(1).is_shard(1) and not Shard(1).is_shard(0)


def test_shard_tensor_layouts():
    m = _mesh2d()
    t = paddle.randn([8, 16])
    st = shard_tensor(t, m, [Shard(0), Shard(1)])
    assert st._data.sharding.spec == P("x", "y")
    assert st.placements == [Shard(0), Shard(1)]
    assert st.process_mesh is m
    np.testing.assert_allclose(np.asarray(st._data), t.numpy())

    st2 = shard_tensor(t, m, [Replicate(), Shard(0)])
    assert st2._data.sharding.spec == P("y", None)

    # both mesh dims shard the same tensor dim
    st3 = shard_tensor(t, m, [Shard(0), Shard(0)])
    assert st3._data.sharding.spec == P(("x", "y"), None)


def test_reshard_changes_layout():
    m = _mesh2d()
    t = shard_tensor(paddle.randn([8, 8]), m, [Shard(0), Replicate()])
    r = reshard(t, m, [Replicate(), Shard(1)])
    assert r._data.sharding.spec == P(None, "y")
    np.testing.assert_allclose(np.asarray(r._data), np.asarray(t._data))


def test_dtensor_from_fn():
    m = _mesh2d()
    t = dtensor_from_fn(paddle.zeros, m, [Shard(0)], [4, 4])
    assert t.shape == [4, 4]
    assert t._data.sharding.spec in (P("x"), P("x", None))


def test_sharded_training_matches_replicated():
    """dp-style: input sharded on mesh 'x'; params replicated; loss parity."""
    m = ProcessMesh(np.arange(8), dim_names=["x"])
    paddle.seed(7)
    model = paddle.nn.Linear(8, 4)
    opt = paddle.optimizer.SGD(learning_rate=0.1,
                               parameters=model.parameters())
    x_np = np.random.default_rng(0).normal(size=(16, 8)).astype(np.float32)

    # replicated oracle
    ref_model = paddle.nn.Linear(8, 4)
    ref_model.set_state_dict({k: v for k, v in model.state_dict().items()})
    ref_opt = paddle.optimizer.SGD(learning_rate=0.1,
                                   parameters=ref_model.parameters())
    for _ in range(3):
        loss = (ref_model(paddle.to_tensor(x_np)) ** 2).mean()
        loss.backward()
        ref_opt.step()
        ref_opt.clear_grad()

    xs = shard_tensor(paddle.to_tensor(x_np), m, [Shard(0)])
    opt = shard_optimizer(opt)
    for _ in range(3):
        loss = (model(xs) ** 2).mean()
        loss.backward()
        opt.step()
        opt.clear_grad()

    np.testing.assert_allclose(model.weight.numpy(), ref_model.weight.numpy(),
                               rtol=1e-5, atol=1e-5)
