"""Native C++ TCPStore (reference ``tcp_store.cc`` rendezvous) — KV ops,
blocking wait, atomic add, cross-process barrier."""
import multiprocessing as mp
import threading
import time

import pytest

from paddle_tpu.distributed.native import TCPStore, available

pytestmark = pytest.mark.skipif(not available(),
                                reason="g++ toolchain unavailable")


def test_set_get_delete_keys():
    master = TCPStore(is_master=True, world_size=1)
    try:
        master.set("alpha", b"1")
        master.set("beta/x", "two")
        assert master.get("alpha") == b"1"
        assert master.get("beta/x") == b"two"
        assert sorted(master.keys("beta")) == ["beta/x"]
        master.delete_key("alpha")
        with pytest.raises(KeyError):
            master.get("alpha", wait=False)
    finally:
        master.close()


def test_add_is_atomic_across_clients():
    master = TCPStore(is_master=True, world_size=1)
    port = master.port
    try:
        clients = [TCPStore(port=port, world_size=1) for _ in range(4)]
        errs = []

        def bump(c):
            try:
                for _ in range(50):
                    c.add("ctr", 1)
            except Exception as e:
                errs.append(e)
        ts = [threading.Thread(target=bump, args=(c,)) for c in clients]
        [t.start() for t in ts]
        [t.join() for t in ts]
        assert not errs
        assert master.add("ctr", 0) == 200
        for c in clients:
            c.close()
    finally:
        master.close()


def test_wait_blocks_until_set():
    master = TCPStore(is_master=True, world_size=1)
    try:
        client = TCPStore(port=master.port, world_size=1)
        t0 = time.monotonic()

        def late_set():
            time.sleep(0.3)
            master.set("late", b"v")
        th = threading.Thread(target=late_set)
        th.start()
        assert client.get("late", timeout=5) == b"v"
        assert time.monotonic() - t0 >= 0.25
        th.join()
        with pytest.raises(TimeoutError):
            client.wait("never", timeout=0.2)
        client.close()
    finally:
        master.close()


def _rank_proc(port, rank, world, q):
    try:
        store = TCPStore(port=port, is_master=False, world_size=world,
                         timeout=30)
        store.set(f"rank/{rank}", str(rank))
        store.barrier("join")
        # after the barrier every rank's key must be visible
        got = sorted(int(store.get(f"rank/{r}", timeout=5))
                     for r in range(world))
        q.put((rank, got))
        store.close()
    except Exception as e:   # pragma: no cover
        q.put((rank, repr(e)))


def test_multiprocess_rendezvous_barrier():
    world = 3
    master = TCPStore(is_master=True, world_size=world)
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [ctx.Process(target=_rank_proc,
                         args=(master.port, r, world, q))
             for r in range(world)]
    try:
        [p.start() for p in procs]
        results = [q.get(timeout=60) for _ in range(world)]
        for rank, got in results:
            assert isinstance(got, list), f"rank {rank} failed: {got}"
            assert got == list(range(world))
    finally:
        [p.join(timeout=10) for p in procs]
        [p.terminate() for p in procs if p.is_alive()]
        master.close()


def test_elastic_manager_over_tcp_store():
    """The elastic membership layer runs over the tcp:// (C++ TCPStore)
    backend exactly as over file:// — etcd-role parity."""
    from paddle_tpu.distributed.fleet.elastic import ElasticManager
    from paddle_tpu.distributed.native import TCPStore as _TS

    seed = _TS(is_master=True)          # hold the port as the server
    try:
        spec = f"tcp://127.0.0.1:{seed.port}"
        a = ElasticManager(server=spec, job_id="jt", np="1:4",
                           host="10.0.0.1:8000", ttl=0.5,
                           heartbeat_interval=0.1)
        b = ElasticManager(server=spec, job_id="jt", np="1:4",
                           host="10.0.0.2:8000", ttl=0.5,
                           heartbeat_interval=0.1)
        a.register()
        assert a.hosts() == ["10.0.0.1:8000"]
        b.register()
        changed, cur = a.world_changed()
        assert changed and len(cur) == 2
        env = a.accept_world()
        assert env["PADDLE_TRAINERS_NUM"] == "2"
        a.stop(); b.stop()
    finally:
        seed.close()


def test_barrier_is_reusable():
    master = TCPStore(is_master=True, world_size=2)
    client = TCPStore(port=master.port, world_size=2)
    try:
        for _ in range(3):      # three rounds over the same name
            errs = []

            def go(s):
                try:
                    s.barrier("phase", timeout=10)
                except Exception as e:
                    errs.append(e)
            ts = [threading.Thread(target=go, args=(s,))
                  for s in (master, client)]
            [t.start() for t in ts]
            [t.join() for t in ts]
            assert not errs, errs
    finally:
        client.close()
        master.close()


def test_negative_counters_ok():
    master = TCPStore(is_master=True, world_size=1)
    try:
        assert master.add("neg", -1) == -1
        assert master.add("neg", -1) == -2
        assert master.add("neg", 5) == 3
    finally:
        master.close()


def test_server_stop_with_live_blocked_client():
    """close() with a client blocked in wait() must not crash/UAF; the
    blocked wait returns an error promptly."""
    master = TCPStore(is_master=True, world_size=1)
    client = TCPStore(port=master.port, world_size=1)
    out = {}

    def waiter():
        try:
            client.wait("nothing", timeout=30)
            out["r"] = "found"
        except Exception as e:
            out["r"] = type(e).__name__
    th = threading.Thread(target=waiter)
    th.start()
    time.sleep(0.2)
    master.close()               # server gone while wait in flight
    th.join(timeout=10)
    assert not th.is_alive()
    assert out["r"] in ("TimeoutError", "RuntimeError")
    client.close()
