"""Serving fast path (ISSUE 4): prefix-cache KV reuse over the shared
refcounted page pool, chunked decode-interleaved prefill, and the
non-blocking admission scheduler — greedy-oracle parity, page
refcount/copy-on-write lifecycle, decode liveness, and timeout
cancellation."""
import threading
import time

import numpy as np
import jax.numpy as jnp
import pytest

import paddle_tpu as paddle
from paddle_tpu.inference import ContinuousServingEngine
from paddle_tpu.models import LlamaForCausalLM, llama_tiny
from paddle_tpu.models.generation import SlotPagedKVCache, block_hash_chain


@pytest.fixture(scope="module")
def model():
    paddle.seed(0)
    # rope table large enough for the 128-token shared-system-prompt runs
    return LlamaForCausalLM(llama_tiny(num_hidden_layers=2,
                                       max_position_embeddings=256))


def _oracle(model, p, n):
    return np.asarray(model.generate(paddle.to_tensor(p),
                                     max_new_tokens=n)._data)


# ---------------------------------------------------------------------------
# acceptance: shared system prompt -> prefix reuse, bit-identical outputs
# ---------------------------------------------------------------------------

def test_shared_system_prompt_reuse_and_parity(model):
    """8 requests sharing a 128-token system prompt: after the first
    prefills and registers the shared blocks, the other 7 prefill only
    their unique 8-token tails — telemetry shows hits and >= 7 x (shared
    blocks x page_size) cached tokens, while greedy outputs stay
    bit-identical to the prefix-cache-off path and the dense oracle."""
    rng = np.random.RandomState(0)
    sys_prompt = rng.randint(0, 128, 128)
    prompts = [np.concatenate([sys_prompt, rng.randint(0, 128, 8)])
               .astype(np.int64)[None] for _ in range(8)]

    def run(prefix_cache):
        eng = ContinuousServingEngine(
            model, max_batch_size=4, max_len=160, page_size=16,
            enable_prefix_cache=prefix_cache, prefill_chunk_tokens=32)
        results = [None] * 8
        with eng:
            # request 0 fills (and, when enabled, registers) the prefix
            results[0] = np.asarray(eng.generate(
                prompts[0], max_new_tokens=4, timeout=300).numpy())

            def call(i):
                results[i] = np.asarray(eng.generate(
                    prompts[i], max_new_tokens=4, timeout=300).numpy())

            threads = [threading.Thread(target=call, args=(i,))
                       for i in range(1, 8)]
            for t in threads:
                t.start()
            for t in threads:
                t.join()
        return results, eng

    got_on, eng_on = run(True)
    got_off, eng_off = run(False)
    for a, b in zip(got_on, got_off):
        np.testing.assert_array_equal(a, b)
    # spot-check against the dense concat-cache oracle too
    for i in (0, 3):
        np.testing.assert_array_equal(got_on[i],
                                      _oracle(model, prompts[i], 4))
    cache = eng_on._cache
    assert cache.prefix_hits > 0
    # 7 followers x 8 shared full blocks x 16 tokens/page
    assert cache.cached_tokens_total >= 7 * 8 * 16
    assert eng_off._cache.prefix_hits == 0
    assert eng_off._cache.cached_tokens_total == 0


def test_chunked_prefill_matches_dense_oracle(model):
    """A prompt much longer than the chunk budget prefills in several
    fixed-bucket chunks yet decodes bit-identically to the dense path."""
    rng = np.random.RandomState(1)
    p = rng.randint(0, 128, (1, 50)).astype(np.int64)
    want = _oracle(model, p, 5)
    eng = ContinuousServingEngine(model, max_batch_size=2, max_len=64,
                                  prefill_chunk_tokens=16)
    with eng:
        got = np.asarray(eng.generate(p, max_new_tokens=5,
                                      timeout=300).numpy())
    np.testing.assert_array_equal(got, want)
    assert eng.prefill_chunks >= 4          # ceil(50/16) chunks
    assert eng.prefills == 1                # still one admission


def test_env_flag_disables_prefix_cache(model, monkeypatch):
    monkeypatch.setenv("PADDLE_SERVING_PREFIX_CACHE", "0")
    eng = ContinuousServingEngine(model)
    assert eng.enable_prefix_cache is False
    monkeypatch.setenv("PADDLE_SERVING_PREFIX_CACHE", "1")
    assert ContinuousServingEngine(model).enable_prefix_cache is True


# ---------------------------------------------------------------------------
# cache-level lifecycle: refcounts, copy-on-write, eviction
# ---------------------------------------------------------------------------

def _write_tokens(cache, slot, layer, tokens):
    """Push synthetic K/V for ``tokens`` through the prefill path (the
    content is the token value broadcast, so page content is checkable)."""
    s = len(tokens)
    t = np.asarray(tokens, np.float32)
    k = np.broadcast_to(t[None, :, None, None], (1, s, 1, 4)).copy()
    q = np.zeros((1, s, 1, 4), np.float32)
    cache.begin_prefill(slot, s)
    cache.attend(layer, jnp.asarray(q), jnp.asarray(k), jnp.asarray(k))
    cache.advance(s)


def test_refcount_and_cow_lifecycle():
    layer = object()
    cache = SlotPagedKVCache(2, page_size=4, max_len=32,
                             enable_prefix_cache=True)
    prompt = np.arange(12)
    chain = block_hash_chain(prompt, 4)

    cached, hits, misses = cache.assign(0, prompt)
    assert (cached, hits, misses) == (0, 0, 3)
    _write_tokens(cache, 0, layer, prompt)
    assert cache.commit_prefix(0) == 3
    pages0 = cache._tables[0, :3].copy()
    assert (cache._ref[pages0] == 2).all()          # slot 0 + index

    # identical prompt on slot 1: full-block reuse capped so >= 1 token
    # still prefills (the model must emit last-token logits)
    cached, hits, misses = cache.assign(1, prompt)
    assert (cached, hits) == (8, 2)
    assert (cache._tables[1, :2] == pages0[:2]).all()
    assert (cache._ref[pages0[:2]] == 3).all()

    cache.free(0)
    assert (cache._ref >= 0).all()
    assert (cache._ref[pages0[:2]] == 2).all()      # index + slot 1
    cache.free(0)                                   # double free: no-op
    assert (cache._ref >= 0).all()

    # copy-on-write: force a mid-block write into slot 1's SHARED block 1
    cache.lens[1] = 6
    _write_tokens(cache, 1, layer, np.arange(100, 102))
    assert cache.cow_copies == 1
    assert cache._tables[1, 1] != pages0[1]
    assert cache._index[chain[1]] == pages0[1]      # index entry intact
    # the index's copy kept its original content, the COW page diverged
    kp, _ = cache._pools[id(layer)]
    assert float(kp[0, pages0[1], 2, 0]) == 6.0     # original token value
    assert float(kp[0, cache._tables[1, 1], 2, 0]) == 100.0

    cache.free(1)
    assert (cache._ref >= 0).all()
    # only the 3 registered pages remain charged to the pool
    assert cache.used_page_count == 3
    assert (cache._ref[pages0] == 1).all()


def test_pool_eviction_reclaims_index_pages():
    """When the free list empties, LRU prefix-index entries with no live
    users are evicted instead of failing allocation."""
    layer = object()
    # 1 slot x 4 pages/seq + scratch = 4 allocatable pages
    cache = SlotPagedKVCache(1, page_size=4, max_len=16,
                             enable_prefix_cache=True)
    for i in range(4):
        prompt = np.arange(8) + 1000 * i            # 2 full blocks each
        cache.assign(0, prompt)
        _write_tokens(cache, 0, layer, prompt)
        cache.commit_prefix(0)
        cache.free(0)
        assert (cache._ref >= 0).all()
    # 4 rounds x 2 registered blocks through a 4-page pool forced
    # evictions; the pool never overflowed and stays fully utilized
    assert cache.used_page_count <= 4
    assert len(cache._index) <= 4
    # a fresh identical prompt still round-trips
    cached, hits, _ = cache.assign(0, np.arange(8) + 3000)
    assert cached == hits * 4


def test_refcount_underflow_raises():
    cache = SlotPagedKVCache(1, page_size=4, max_len=16)
    page = cache._alloc_page()
    cache._decref(page)
    with pytest.raises(RuntimeError, match="underflow"):
        cache._decref(page)


# ---------------------------------------------------------------------------
# scheduler: decode liveness between chunks, timeout cancellation
# ---------------------------------------------------------------------------

def test_decode_liveness_between_prefill_chunks(model):
    """Chunked prefill must not head-of-line-block decoding: while a long
    prompt prefills chunk by chunk, the already-admitted request keeps
    earning decode steps between consecutive chunks."""
    rng = np.random.RandomState(2)
    short = rng.randint(0, 128, (1, 4)).astype(np.int64)
    long_p = rng.randint(0, 128, (1, 40)).astype(np.int64)
    eng = ContinuousServingEngine(model, max_batch_size=2, max_len=80,
                                  prefill_chunk_tokens=8,
                                  enable_prefix_cache=False)
    with eng:
        t = threading.Thread(target=lambda: eng.generate(
            short, max_new_tokens=40, timeout=300))
        t.start()
        deadline = time.time() + 60
        while eng.decode_steps < 1 and time.time() < deadline:
            time.sleep(0.005)
        assert eng.decode_steps >= 1, "short request never started decoding"
        eng.generate(long_p, max_new_tokens=2, timeout=300)
        t.join()
    events = list(eng.events)
    # the long prompt ran on the second slot in >= 5 chunks (40/8)
    chunk_slots = {e[1] for e in events if e[0] == "chunk"}
    assert len(chunk_slots) == 2
    long_slot = max(chunk_slots)        # short admitted first -> slot 0
    idx = [i for i, e in enumerate(events)
           if e[0] == "chunk" and e[1] == long_slot]
    assert len(idx) >= 5
    for a, b in zip(idx, idx[1:]):
        between = [e for e in events[a + 1:b]
                   if e[0] == "decode" and e[1] >= 1]
        assert between, f"no decode step between chunks {a} and {b}"


def test_timeout_cancellation_frees_slot_and_stops_decoding(model):
    """A timed-out request must not keep burning decode steps to
    max_new_tokens: the scheduler frees its slot/pages at the next step
    boundary and the engine keeps serving."""
    rng = np.random.RandomState(3)
    p = rng.randint(0, 128, (1, 4)).astype(np.int64)
    eng = ContinuousServingEngine(model, max_batch_size=2, max_len=128)
    with eng:
        with pytest.raises(TimeoutError):
            eng.generate(p, max_new_tokens=120, timeout=0.05)
        deadline = time.time() + 60
        while eng.cancelled_rows < 1 and time.time() < deadline:
            time.sleep(0.01)
        assert eng.cancelled_rows >= 1
        # slot and pages were released, nowhere near the 120-token budget
        deadline = time.time() + 60
        while eng._cache.used_page_count > 0 and time.time() < deadline:
            time.sleep(0.01)
        assert eng._cache.used_page_count == 0
        assert eng.decode_steps < 120
        # engine still serves afterwards
        out = eng.generate(p, max_new_tokens=2, timeout=120)
        assert np.asarray(out.numpy()).shape == (1, 6)


def test_cancelled_pending_rows_skipped_at_admission(model):
    """A request that times out while still queued never occupies a slot."""
    rng = np.random.RandomState(4)
    p = rng.randint(0, 128, (1, 4)).astype(np.int64)
    eng = ContinuousServingEngine(model, max_batch_size=1, max_len=128)
    with eng:
        blocker = threading.Thread(target=lambda: eng.generate(
            p, max_new_tokens=60, timeout=300))
        blocker.start()
        deadline = time.time() + 60
        while eng.prefills < 1 and time.time() < deadline:
            time.sleep(0.005)
        prefills_before = eng.prefills
        with pytest.raises(TimeoutError):
            # the single slot is busy for many steps; this one queues and
            # times out before admission
            eng.generate(p, max_new_tokens=2, timeout=0.05)
        blocker.join()
        # give the scheduler a beat to sweep the cancelled pending row
        deadline = time.time() + 60
        while eng.cancelled_rows < 1 and time.time() < deadline:
            time.sleep(0.01)
    assert eng.cancelled_rows >= 1
    assert eng.prefills == prefills_before   # never admitted


# ---------------------------------------------------------------------------
# telemetry wiring
# ---------------------------------------------------------------------------

def test_prefix_and_chunk_telemetry(model):
    from paddle_tpu.profiler import metrics
    rng = np.random.RandomState(5)
    shared = rng.randint(0, 128, 32)
    p1 = np.concatenate([shared, rng.randint(0, 128, 4)]).astype(
        np.int64)[None]
    p2 = np.concatenate([shared, rng.randint(0, 128, 6)]).astype(
        np.int64)[None]
    eng = ContinuousServingEngine(model, max_batch_size=2, max_len=64,
                                  page_size=16, prefill_chunk_tokens=16,
                                  enable_prefix_cache=True)
    with eng:
        eng.generate(p1, max_new_tokens=2, timeout=300)
        eng.generate(p2, max_new_tokens=2, timeout=300)
    assert eng._cache.prefix_hits >= 2      # 32-token shared = 2 blocks
    snap = metrics()
    assert snap["paddle_serving_prefix_hits"]["series"][""] >= 2
    assert snap["paddle_serving_prefix_cached_tokens"]["series"][""] >= 32
    # the ragged scheduler observes batch-level budget utilization; the
    # legacy path observes per-chunk utilization
    if eng.enable_ragged:
        util = snap["paddle_serving_token_budget_utilization"]["series"][""]
        assert util["count"] >= eng.ragged_steps > 0
    else:
        util = snap["paddle_serving_chunk_utilization"]["series"][""]
        assert util["count"] >= eng.prefill_chunks > 0
    assert "paddle_serving_page_pool_occupancy" in snap
    assert "paddle_serving_prefix_misses" in snap
