"""Autograd tape semantics vs analytic/numeric references (the OpTest
check_grad analogue — SURVEY.md §4)."""
import numpy as np
import pytest

import paddle_tpu as paddle
from paddle_tpu.autograd import PyLayer


def _leaf(val):
    t = paddle.to_tensor(val)
    t.stop_gradient = False
    return t


def test_simple_backward():
    x = _leaf([1.0, 2.0, 3.0])
    y = (x * x).sum()
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2, 4, 6])


def test_chain_and_accumulation():
    x = _leaf([2.0])
    y = x * 3.0
    z1 = y * y      # dz1/dx = 18x = 36
    z2 = y + 1.0    # dz2/dx = 3
    (z1 + z2).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [39.0])


def test_stop_gradient_blocks():
    x = _leaf([1.0])
    w = paddle.to_tensor([5.0])  # stop_gradient=True
    y = x * w
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])
    assert w.grad is None


def test_backward_twice_raises():
    x = _leaf([1.0])
    y = x * x
    y.backward(retain_graph=True)
    y.backward()
    with pytest.raises(RuntimeError):
        y.backward()


def test_grad_accumulates_across_backwards():
    x = _leaf([1.0])
    (x * 2).backward()
    (x * 3).backward()
    np.testing.assert_allclose(x.grad.numpy(), [5.0])


def test_matmul_grad_matches_numeric():
    rng = np.random.RandomState(0)
    a_np = rng.randn(3, 4).astype(np.float32)
    b_np = rng.randn(4, 2).astype(np.float32)
    a, b = _leaf(a_np), _leaf(b_np)
    loss = paddle.matmul(a, b).sum()
    loss.backward()
    # analytic: dL/da = ones @ b.T
    np.testing.assert_allclose(a.grad.numpy(),
                               np.ones((3, 2)) @ b_np.T, rtol=1e-5)
    np.testing.assert_allclose(b.grad.numpy(),
                               a_np.T @ np.ones((3, 2)), rtol=1e-5)


def test_paddle_grad_api():
    x = _leaf([3.0])
    y = _leaf([4.0])
    z = x * x * y
    gx, gy = paddle.grad(z, [x, y])
    np.testing.assert_allclose(gx.numpy(), [24.0])
    np.testing.assert_allclose(gy.numpy(), [9.0])
    assert x.grad is None  # paddle.grad must not touch .grad


def test_grad_unused_input():
    x = _leaf([1.0])
    y = _leaf([1.0])
    z = x * 2
    with pytest.raises(ValueError):
        paddle.grad(z, [x, y], allow_unused=False)
    gs = paddle.grad(z, [x, y], allow_unused=True)
    assert gs[1] is None


def test_no_grad_context():
    x = _leaf([1.0])
    with paddle.no_grad():
        y = x * x
    assert y._grad_node is None

    @paddle.no_grad()
    def f(t):
        return t * 2

    assert f(x)._grad_node is None


def test_hooks():
    x = _leaf([1.0])
    seen = []

    def hook(g):
        seen.append(g.numpy().copy())
        return g * 2

    x.register_hook(hook)
    (x * 3).backward()
    assert len(seen) == 1
    np.testing.assert_allclose(seen[0], [3.0])
    np.testing.assert_allclose(x.grad.numpy(), [6.0])


def test_retain_grads_intermediate():
    x = _leaf([2.0])
    y = x * 3
    y.retain_grads()
    z = y * y
    z.backward()
    np.testing.assert_allclose(y.grad.numpy(), [12.0])


def test_indexing_grad():
    x = _leaf(np.arange(6, dtype=np.float32).reshape(2, 3))
    y = x[0].sum() + 2 * x[1, 2]
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [[1, 1, 1], [0, 0, 2]])


def test_setitem_grad():
    x = _leaf(np.zeros(3, np.float32))
    v = _leaf([5.0])
    x[1] = v[0] * 2
    x.sum().backward()
    np.testing.assert_allclose(v.grad.numpy(), [2.0])


def test_concat_split_grad():
    a = _leaf([1.0, 2.0])
    b = _leaf([3.0])
    c = paddle.concat([a, b])
    (c * paddle.to_tensor([1.0, 2.0, 3.0])).sum().backward()
    np.testing.assert_allclose(a.grad.numpy(), [1, 2])
    np.testing.assert_allclose(b.grad.numpy(), [3])
    x = _leaf(np.arange(6, dtype=np.float32))
    parts = paddle.split(x, 3)
    parts[1].sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [0, 0, 1, 1, 0, 0])


def test_topk_mixed_output_grad():
    x = _leaf([1.0, 9.0, 3.0, 7.0])
    vals, idx = paddle.topk(x, 2)
    vals.sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), [0, 1, 0, 1])
    assert idx.stop_gradient


def test_pylayer():
    class Double(PyLayer):
        @staticmethod
        def forward(ctx, x):
            ctx.save_for_backward(x)
            return x * 2

        @staticmethod
        def backward(ctx, g):
            return g * 2

    x = _leaf([3.0])
    y = Double.apply(x)
    np.testing.assert_allclose(y.numpy(), [6.0])
    y.backward()
    np.testing.assert_allclose(x.grad.numpy(), [2.0])


def test_inplace_autograd():
    x = _leaf([1.0, 2.0])
    w = _leaf([3.0, 4.0])
    y = x * w
    y.add_(x)          # y = x*w + x, in-place on y
    y.sum().backward()
    np.testing.assert_allclose(w.grad.numpy(), [1.0, 2.0])
    np.testing.assert_allclose(x.grad.numpy(), [4.0, 5.0])


def test_broadcast_grad():
    x = _leaf(np.ones((3, 1), np.float32))
    y = _leaf(np.ones((1, 4), np.float32))
    (x * y).sum().backward()
    np.testing.assert_allclose(x.grad.numpy(), np.full((3, 1), 4.0))
    np.testing.assert_allclose(y.grad.numpy(), np.full((1, 4), 3.0))
